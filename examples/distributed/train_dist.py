"""Distributed data-parallel training example (ref
`example/distributed_training/cifar10_dist.py`, SURVEY.md §2.8).

Each worker trains on its OWN shard of a synthetic CIFAR-like dataset;
gradients are summed across workers by the `dist_sync` KVStore (DCN
allreduce), keeping replicas identical — the reference's
parameter-server recipe re-expressed as SPMD.

Run (N workers on one machine — the CI pattern):
  python tools/launch.py -n 3 --launcher local \
      python examples/distributed/train_dist.py --epochs 2
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser(description="dist data-parallel trainer")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32,
                   help="PER-WORKER batch size")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--samples-per-worker", type=int, default=512)
    return p


def train(args):
    import jax
    import jax.numpy as jnp
    import numpy as onp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, loss as loss_mod, nn
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    print(f"worker {rank}/{nw} up", flush=True)

    # per-worker shard: disjoint seeds -> disjoint data
    rng = onp.random.RandomState(100 + rank)
    tpl = onp.random.RandomState(7).randn(10, 3 * 16 * 16).astype("float32")
    Y = rng.randint(0, 10, args.samples_per_worker)
    X = tpl[Y] + 0.3 * rng.randn(args.samples_per_worker, 3 * 16 * 16).astype("float32")

    mx.random.seed(0)  # identical init everywhere
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize()
    net.hybridize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": args.lr}, kvstore=kv)
    loss_fn = loss_mod.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    global_batch = args.batch_size * nw
    for epoch in range(args.epochs):
        metric.reset()
        for i in range(0, len(X), args.batch_size):
            x = NDArray(jnp.asarray(X[i:i + args.batch_size]))
            y = NDArray(jnp.asarray(Y[i:i + args.batch_size].astype("float32")))
            with autograd.record():
                out = net(x)
                L = loss_fn(out, y)
            L.backward()
            trainer.step(global_batch)  # grads summed across workers
            metric.update([y], [out])
        print(f"worker {rank}: epoch {epoch} acc={metric.get()[1]:.3f}",
              flush=True)

    # replicas must agree bit-for-bit after synchronized training
    from jax.experimental import multihost_utils

    w = net.collect_params()
    first = list(w.values())[0].data()._data
    if nw > 1:
        allw = multihost_utils.process_allgather(first)
        for r in range(nw):
            onp.testing.assert_allclose(onp.asarray(allw[r]),
                                        onp.asarray(first), rtol=1e-6,
                                        err_msg=f"replica divergence at rank {r}")
        print(f"worker {rank}: replicas consistent OK", flush=True)
    return metric.get()[1]


if __name__ == "__main__":
    train(build_parser().parse_args())
