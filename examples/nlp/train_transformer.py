"""Transformer NMT training (the WMT baseline config's recipe).

TPU-native rendition of the GluonNLP-era Transformer training script
(SURVEY.md §2.8 "Gluon examples", BASELINE.md "Transformer-big WMT14"):
encoder-decoder `models.transformer.Transformer` with label-smoothed
cross-entropy, inverse-sqrt warmup LR, Adam, teacher forcing, and
greedy-decode evaluation.

Real WMT bitext cannot be downloaded here (no network egress), so the
script trains on a deterministic synthetic translation task — "copy
with +1 token shift" — which exercises the identical training stack
(encoder attention, causal decoder, cross attention, label smoothing,
tokens/s accounting) and is verifiable: a working model reaches ~100%
greedy-decode token accuracy.  Pass `--data-src/--data-tgt` with token
id files (one sentence per line) to train on a real corpus.

Run: python examples/nlp/train_transformer.py --steps 60
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser(description="Transformer NMT trainer")
    p.add_argument("--model", type=str, default="base",
                   choices=["base", "big", "tiny"])
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--lr", type=float, default=3e-3,
                   help="PEAK learning rate of the inverse-sqrt schedule")
    p.add_argument("--warmup", type=int, default=20)
    p.add_argument("--smoothing", type=float, default=0.1)
    p.add_argument("--data-src", type=str, default=None)
    p.add_argument("--data-tgt", type=str, default=None)
    p.add_argument("--eval-every", type=int, default=20)
    p.add_argument("--dtype", type=str, default="float32",
                   choices=["float32", "bfloat16"],
                   help="bfloat16 = MXU-rate matmuls + fp32 master weights")
    p.add_argument("--seed", type=int, default=1, help="data RNG seed")
    p.add_argument("--report-mfu", action="store_true",
                   help="print an MFU line (bench.py FLOPs convention)")
    return p


def synthetic_batch(rng, batch, seq, vocab):
    """src random; tgt = src shifted by +1 mod vocab (BOS=0 prepended).

    `rng` is a numpy RandomState — batches are built host-side because
    per-step eager device ops each cost a dispatch round-trip on a
    remote-attached chip."""
    import numpy as onp

    src = rng.randint(2, vocab, (batch, seq)).astype("int32")
    tgt_full = (src % (vocab - 2)) + 2  # stay off BOS/EOS ids
    bos = onp.zeros((batch, 1), "int32")
    tgt_in = onp.concatenate([bos, tgt_full[:, :-1]], axis=1)
    return src, tgt_in, tgt_full


def greedy_token_acc(net, src, tgt_labels, vocab):
    """Teacher-forced greedy accuracy (fast proxy for BLEU trend)."""
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    B, T = tgt_labels.shape
    bos = jnp.zeros((B, 1), jnp.int32)
    tgt_in = jnp.concatenate([bos, tgt_labels[:, :-1]], axis=1)
    logits = net(NDArray(src), NDArray(tgt_in))
    # argmax ON DEVICE: fetching (B, T, V) logits over the relay's ~MB/s
    # device->host link costs minutes at V=32k — a (B, T) array is free.
    # NDArray.argmax returns float32 (mxnet convention); round-trip to
    # int so the equality check is dtype-honest
    pred = logits.argmax(axis=-1).asnumpy().astype("int64")
    import numpy as onp

    return float((pred == onp.asarray(tgt_labels)).mean())


def train(args):
    import jax

    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, lr_scheduler
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.models import transformer as tfm
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)
    dims = {"base": dict(units=512, hidden_size=2048, num_layers=6, num_heads=8),
            "big": dict(units=1024, hidden_size=4096, num_layers=6, num_heads=16),
            "tiny": dict(units=64, hidden_size=128, num_layers=2, num_heads=4)}
    net = tfm.Transformer(src_vocab=args.vocab, tgt_vocab=args.vocab,
                          dropout=0.0, **dims[args.model])
    import numpy as onp

    rng = onp.random.RandomState(args.seed)
    net.initialize()
    if args.dtype == "bfloat16":
        # shape materialization with a THROWAWAY rng: the data stream
        # stays identical across dtypes
        s0, t0_, _ = synthetic_batch(onp.random.RandomState(0),
                                     args.batch_size, args.seq_len,
                                     args.vocab)
        net(NDArray(jnp.asarray(s0)), NDArray(jnp.asarray(t0_)))
        net.cast("bfloat16")
    net.hybridize()
    loss_fn = tfm.LabelSmoothedCELoss(smoothing=args.smoothing)

    # Noam schedule hits its maximum at step == warmup; scale base_lr so
    # that maximum equals --lr (the reference recipe's base_lr*units^-0.5
    # convention assumes warmup in the thousands)
    sched = lr_scheduler.InvSqrtScheduler(
        warmup_steps=args.warmup, base_lr=args.lr * args.warmup ** 0.5)
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": sched.base_lr, "beta1": 0.9,
                       "beta2": 0.98, "lr_scheduler": sched,
                       "multi_precision": args.dtype == "bfloat16"},
                      keep_grads=False)  # grads live only inside the step

    tokens_done = 0
    t0 = None  # started AFTER the first step so compile time is excluded
    acc = 0.0
    best_tps = 0.0
    for step in range(1, args.steps + 1):
        src, tgt_in, tgt_lbl = synthetic_batch(rng, args.batch_size,
                                               args.seq_len, args.vocab)
        with autograd.record():
            logits = net(NDArray(src), NDArray(tgt_in))
            L = loss_fn(logits, NDArray(tgt_lbl))
        L.backward()
        trainer.step(1)
        if t0 is None:
            float(L.asnumpy())  # drain warmup/compile before timing
            t0 = time.time()
        else:
            tokens_done += args.batch_size * args.seq_len
        if step % args.eval_every == 0 or step == args.steps:
            loss_val = float(L.asnumpy())   # drains the async queue
            tps = tokens_done / max(time.time() - t0, 1e-9)
            best_tps = max(best_tps, tps)
            acc = greedy_token_acc(net, src, tgt_lbl, args.vocab)
            print(f"step {step}: loss={loss_val:.4f} "
                  f"greedy_acc={acc:.3f} {tps:.0f} tok/s (post-compile)")
            t0 = time.time()
            tokens_done = 0
    if args.report_mfu:
        # bench.py's convention: 6·N FLOPs/token over the matmul params
        # (embedding tables are gathers — excluded) + the attention
        # score/value terms.  Each step processes B target tokens whose
        # program also runs the encoder over B·T source tokens, so the
        # per-reported-token cost doubles, and the decoder carries self
        # PLUS cross attention.
        from incubator_mxnet_tpu.callback import device_peak_flops
        import jax

        d = dims[args.model]
        D_, L_ = d["units"], d["num_layers"]
        n_params = sum(p.data().size
                       for p in net.collect_params().values()
                       if p.grad_req != "null")
        n_embed = sum(p.data().size
                      for name, p in
                      net._collect_params_with_prefix().items()
                      if "embed" in name or "pos" in name)
        # per step: 6·B·T FLOPs through the encoder params + 6·B·T
        # through the decoder params = 6·(N−N_embed) per REPORTED token
        # (tokens_done counts B·T/step); attention adds enc-self +
        # dec-self + cross = 3L score/value terms
        T_ = args.seq_len
        flops_per_tok = (6 * (n_params - n_embed)
                         + 12 * T_ * D_ * 3 * L_)
        mfu = best_tps * flops_per_tok / device_peak_flops(jax.devices()[0])
        print(f"MFU {100 * mfu:.2f}% at {best_tps:.0f} tok/s "
              f"(T={T_}, {n_params / 1e6:.0f}M params, "
              f"final loss {loss_val:.4f}, greedy_acc {acc:.3f})")
    return acc


if __name__ == "__main__":
    train(build_parser().parse_args())
