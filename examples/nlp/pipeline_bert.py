"""BERT language model trained through GluonPipeline (1F1B pipeline
parallelism) — the public Gluon doorway to PP.

Mirrors the reference's pipelined-transformer training examples
(ref concept: SURVEY.md §2.4 PP row): stage blocks are plain Gluon
BERTLayers, the embedding trains outside the pipe through its input
cotangent, the LM head trains as loss_params — all wired by
`parallel.GluonPipeline`, updated by the unchanged `gluon.Trainer`.

Run (CPU mesh): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                JAX_PLATFORMS=cpu python examples/nlp/pipeline_bert.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--pipe", type=int, default=2)
    p.add_argument("--units", type=int, default=32)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--microbatch", type=int, default=4)
    p.add_argument("--num-microbatches", type=int, default=4)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=1e-2)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.models import bert
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.parallel import GluonPipeline, create_mesh

    n, D, V, T = args.pipe, args.units, args.vocab, args.seq_len
    mb, M = args.microbatch, args.num_microbatches
    B = mb * M
    mesh = create_mesh(jax.devices()[:n], pipe=n)
    mx.random.seed(0)

    stages = []
    for _ in range(n):
        layer = bert.BERTLayer(units=D, hidden_size=2 * D, num_heads=2,
                               dropout=0.0, use_flash=False)
        layer.initialize()
        layer(NDArray(jnp.ones((mb, T, D), jnp.float32)))
        stages.append(layer)
    emb = gluon.nn.Embedding(V, D)
    emb.initialize()
    emb(NDArray(jnp.zeros((mb, T), jnp.int32)))
    head = gluon.nn.Dense(V, flatten=False)
    head.initialize()
    head(NDArray(jnp.ones((mb, T, D), jnp.float32)))

    def ce_loss(logits, t):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, t[..., None], -1))

    pipe = GluonPipeline(stages, mesh, ce_loss, num_microbatches=M,
                         embedding=emb, head=head)
    trainer = gluon.Trainer(pipe.collect_params(), "adam",
                            {"learning_rate": args.lr})

    # copy task: predict the input token (memorizable by the head alone,
    # but gradients must flow through every stage to converge fast)
    k = jax.random.PRNGKey(1)
    tokens = NDArray(jax.random.randint(k, (B, T), 0, V))
    first = last = None
    for step in range(args.steps):
        loss = float(pipe.train_step(tokens, tokens).asnumpy())
        trainer.step(B)
        if step == 0:
            first = loss
        last = loss
        if step % 5 == 0:
            print(f"step {step:3d} loss {loss:.4f}", flush=True)
    print(f"first {first:.4f} -> last {last:.4f}")
    return first, last


if __name__ == "__main__":
    main()
