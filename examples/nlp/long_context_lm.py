"""Long-context causal LM with ring-attention sequence parallelism.

The marquee TPU capability (SURVEY.md §5.7 — ABSENT in the reference,
built first-class here): a decoder-only transformer whose sequence
dimension is sharded over the `seq` mesh axis.  Each device holds
T/seq tokens; KV blocks rotate around the ICI ring
(`parallel.ring.ring_attention`, double-buffered `lax.ppermute` with
online-softmax accumulation), so NO device ever materializes the full
(T, T) score matrix or the full sequence — context length scales
linearly with the ring size.

The whole train step (fwd + bwd + SGD) runs under one `shard_map` over
a {data × seq} mesh: grads are `psum`-ed over both axes, the loss over
the global batch.  Runs on the 8-virtual-CPU mesh in CI (tiny dims)
and unchanged on a real slice.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
       python examples/nlp/long_context_lm.py --seq-len 2048 --steps 30
"""
from __future__ import annotations

import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser(description="Ring-attention long-context LM")
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=128)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--lr", type=float, default=3e-3, help="Adam lr")
    p.add_argument("--data-parallel", type=int, default=2)
    p.add_argument("--seq-parallel", type=int, default=4)
    p.add_argument("--log-interval", type=int, default=10)
    return p


def init_params(key, args):
    import jax
    import jax.numpy as jnp

    V, D, H, F, L = (args.vocab, args.d_model, args.n_heads, args.d_ff,
                     args.n_layers)
    Dh = D // H
    ks = jax.random.split(key, 6)
    layer = lambda k, shape, scale: \
        jax.random.normal(k, (L,) + shape, jnp.float32) * scale
    return {
        "embed": jax.random.normal(ks[0], (V, D), jnp.float32) * 0.02,
        "pos": jax.random.normal(ks[1], (args.seq_len, D), jnp.float32) * 0.02,
        "wqkv": layer(ks[2], (D, H, 3 * Dh), D ** -0.5),
        "wo": layer(ks[3], (H, Dh, D), D ** -0.5),
        "w1": layer(ks[4], (D, F), D ** -0.5),
        "w2": layer(ks[5], (F, D), F ** -0.5),
        "ln1": jnp.ones((L, D)), "ln2": jnp.ones((L, D)),
        "lnf": jnp.ones((D,)),
    }


def make_train_step(mesh, args):
    """One shard_map program: local fwd → ring attention → bwd → psum."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from incubator_mxnet_tpu.parallel.ring import ring_attention

    H = args.n_heads
    Dh = args.d_model // H
    L = args.n_layers

    def ln(x, g):
        m = x.mean(-1, keepdims=True)
        v = ((x - m) ** 2).mean(-1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(v + 1e-5) * g

    def local_loss(params, x, y):
        # x, y: (B_local, T_local); positions are GLOBAL: offset by the
        # seq-shard index so every ring rank embeds its own slice
        Bl, Tl = x.shape
        off = lax.axis_index("seq") * Tl
        h = jnp.take(params["embed"], x, axis=0) \
            + lax.dynamic_slice_in_dim(params["pos"], off, Tl, axis=0)[None]
        for i in range(L):
            a = ln(h, params["ln1"][i])
            qkv = jnp.einsum("btd,dhx->bhtx", a, params["wqkv"][i])
            q, k, v = jnp.split(qkv, 3, axis=-1)  # (B, H, T_local, Dh)
            o = ring_attention(q, k, v, axis_name="seq", causal=True,
                               scale=1.0 / math.sqrt(Dh))
            h = h + jnp.einsum("bhtx,hxd->btd", o, params["wo"][i])
            a = ln(h, params["ln2"][i])
            h = h + jax.nn.gelu(a @ params["w1"][i]) @ params["w2"][i]
        h = ln(h, params["lnf"])
        logits = h @ params["embed"].T  # tied unembedding
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()
        return nll

    def step(params, m, v, t, x, y):
        loss, grads = jax.value_and_grad(local_loss)(params, x, y)
        # params replicated over (data, seq): average grads over both
        grads = jax.tree_util.tree_map(
            lambda g: lax.pmean(g, ("data", "seq")), grads)
        loss = lax.pmean(loss, ("data", "seq"))
        b1, b2, eps = 0.9, 0.999, 1e-8
        m = jax.tree_util.tree_map(lambda a, g: b1 * a + (1 - b1) * g, m, grads)
        v = jax.tree_util.tree_map(lambda a, g: b2 * a + (1 - b2) * g * g,
                                   v, grads)
        corr = jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        new_params = jax.tree_util.tree_map(
            lambda p, mi, vi: p - args.lr * corr * mi / (jnp.sqrt(vi) + eps),
            params, m, v)
        return new_params, m, v, loss

    pspec = P()               # replicated params/optimizer state
    xspec = P("data", "seq")  # batch over data, sequence over the ring
    fn = shard_map(step, mesh=mesh,
                   in_specs=(pspec, pspec, pspec, P(), xspec, xspec),
                   out_specs=(pspec, pspec, pspec, P()), check_vma=False)
    return jax.jit(fn, donate_argnums=(0, 1, 2))


def synthetic_batch(key, args, vocab):
    """Induction task: each sample repeats a random pattern with period
    STRIDE > T/seq_parallel, so predicting token t requires attending
    to t−STRIDE — across ring-shard boundaries."""
    import jax
    import jax.numpy as jnp

    B, T = args.batch_size, args.seq_len
    stride = max(T // args.seq_parallel, 2)  # longer than one seq shard
    pattern = jax.random.randint(key, (B, stride), 0, vocab, dtype=jnp.int32)
    reps = (T + stride - 1) // stride
    x = jnp.tile(pattern, (1, reps))[:, :T]
    y = jnp.concatenate([x[:, 1:], x[:, :1]], axis=1)  # next-token
    return x, y


def main(argv=None):
    args = build_parser().parse_args(argv)

    import jax

    from incubator_mxnet_tpu import parallel

    n_needed = args.data_parallel * args.seq_parallel
    if len(jax.devices()) < n_needed:
        raise SystemExit(f"need {n_needed} devices (run with "
                         "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    mesh = parallel.create_mesh(data=args.data_parallel,
                                seq=args.seq_parallel)
    assert args.seq_len % args.seq_parallel == 0
    assert args.batch_size % args.data_parallel == 0

    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    params = init_params(key, args)
    m = jax.tree_util.tree_map(jnp.zeros_like, params)
    v = jax.tree_util.tree_map(jnp.zeros_like, params)
    step = make_train_step(mesh, args)

    losses = []
    t0 = time.time()
    for i in range(args.steps):
        key, kb = jax.random.split(key)
        x, y = synthetic_batch(kb, args, args.vocab)
        params, m, v, loss = step(params, m, v, jnp.float32(i + 1), x, y)
        if i % args.log_interval == 0 or i == args.steps - 1:
            l = float(loss)
            losses.append(l)
            tok_s = args.batch_size * args.seq_len * (i + 1) / (time.time() - t0)
            print(f"step {i:4d} loss {l:.4f}  ({tok_s:,.0f} tok/s, "
                  f"T={args.seq_len} over ring of {args.seq_parallel})")
    return losses


if __name__ == "__main__":
    main()
