"""Long-context causal LM with ring-attention sequence parallelism —
THROUGH THE GLUON FRONTEND (r4: the SP doorway).

The marquee TPU capability (SURVEY.md §5.7 — ABSENT in the reference,
built first-class here): a decoder-only `models.TransformerLM` whose
sequence dimension is sharded over the `seq` mesh axis.  Each device
holds T/seq tokens; KV blocks rotate around the ICI ring
(`parallel.ring.ring_attention`, double-buffered `lax.ppermute` with
online-softmax accumulation), so NO device ever materializes the full
(T, T) score matrix or the full sequence — context length scales
linearly with the ring size.

r3 drove this with a hand-written shard_map program; r4 needs three
Gluon lines: `shard_params(net, mesh)` flips every causal attention to
the ring (`MultiHeadAttention.set_seq_parallel`), inputs are placed
P(data, seq), and the UNCHANGED Trainer loop trains the model.

Runs on the 8-virtual-CPU mesh in CI (tiny dims) and unchanged on a
real slice.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
       python examples/nlp/long_context_lm.py --seq-len 2048 --steps 30
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser(description="Ring-attention long-context LM")
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=128)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--lr", type=float, default=3e-3, help="Adam lr")
    p.add_argument("--data-parallel", type=int, default=2)
    p.add_argument("--seq-parallel", type=int, default=4)
    p.add_argument("--log-interval", type=int, default=10)
    return p


def synthetic_batch(key, args, vocab):
    """Periodic induction task: token t is predictable only by attending
    to t−STRIDE — across ring-shard boundaries."""
    import jax
    import jax.numpy as jnp

    B, T = args.batch_size, args.seq_len
    stride = max(T // args.seq_parallel, 2)  # longer than one seq shard
    pattern = jax.random.randint(key, (B, stride), 0, vocab, dtype=jnp.int32)
    reps = (T + stride - 1) // stride
    x = jnp.tile(pattern, (1, reps))[:, :T]
    y = jnp.concatenate([x[:, 1:], x[:, :1]], axis=1)  # next-token
    return x, y


def main(argv=None):
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, parallel
    from incubator_mxnet_tpu.models.transformer import TransformerLM
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.parallel.sharding import shard_params

    n_needed = args.data_parallel * args.seq_parallel
    if len(jax.devices()) < n_needed:
        raise SystemExit(f"need {n_needed} devices (run with "
                         "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    mesh = parallel.create_mesh(data=args.data_parallel,
                                seq=args.seq_parallel)
    assert args.seq_len % args.seq_parallel == 0
    assert args.batch_size % args.data_parallel == 0

    mx.random.seed(0)
    net = TransformerLM(vocab=args.vocab, units=args.d_model,
                        hidden_size=args.d_ff, num_layers=args.n_layers,
                        num_heads=args.n_heads, max_len=args.seq_len,
                        dropout=0.0)
    net.initialize()
    net(NDArray(jnp.zeros((args.batch_size, args.seq_len), jnp.int32)))
    # THE Gluon doorway: seq>1 mesh → every causal attention goes ring
    report = shard_params(net, mesh, warn=False)
    assert report.seq_parallel == args.n_layers, report.seq_parallel
    net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    in_sh = NamedSharding(mesh, P("data", "seq"))

    key = jax.random.PRNGKey(0)
    losses = []
    t0 = time.time()
    for i in range(args.steps):
        key, kb = jax.random.split(key)
        x, y = synthetic_batch(kb, args, args.vocab)
        x = NDArray(jax.device_put(x, in_sh))
        y = NDArray(jax.device_put(y, in_sh))
        with autograd.record():
            L = loss_fn(net(x), y)
        L.backward()
        trainer.step(args.batch_size)
        if i % args.log_interval == 0 or i == args.steps - 1:
            l = float(L.asnumpy().mean())
            losses.append(l)
            tok_s = args.batch_size * args.seq_len * (i + 1) / (time.time() - t0)
            print(f"step {i:4d} loss {l:.4f}  ({tok_s:,.0f} tok/s, "
                  f"T={args.seq_len} over ring of {args.seq_parallel})")
    return losses


if __name__ == "__main__":
    main()
