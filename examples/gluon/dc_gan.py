"""Gluon DCGAN.

TPU-native rendition of the reference `example/gluon/dc_gan/dcgan.py`
[UNVERIFIED] (SURVEY.md §2.8): DCGAN generator (Conv2DTranspose +
BatchNorm + ReLU stack from a latent vector) and discriminator (Conv2D
+ LeakyReLU + BatchNorm) trained adversarially with the sigmoid
binary-cross-entropy loss and Adam(beta1=0.5), alternating D and G
updates through the canonical `autograd.record()` → `backward()` →
`trainer.step()` loop.

Data: a deterministic synthetic 32×32 image distribution (class
templates + noise) stands in for CIFAR/LSUN — no network egress here.
The CI gate checks both losses stay finite and the discriminator can't
saturate to zero loss (the adversarial balance).

Run: python examples/gluon/dc_gan.py --epochs 1 --max-batches 20
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser(description="Gluon DCGAN")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--latent", type=int, default=100)
    p.add_argument("--ngf", type=int, default=32, help="generator base width")
    p.add_argument("--ndf", type=int, default=32, help="discriminator base width")
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--num-samples", type=int, default=640)
    p.add_argument("--max-batches", type=int, default=0,
                   help="stop each epoch after N batches (0 = full epoch)")
    p.add_argument("--log-interval", type=int, default=10)
    p.add_argument("--save-prefix", type=str, default=None)
    return p


def build_nets(args):
    from incubator_mxnet_tpu.gluon import nn

    # generator: z (latent,1,1) -> (3,32,32), tanh output
    netG = nn.HybridSequential()
    netG.add(
        nn.Conv2DTranspose(args.ngf * 4, 4, strides=1, padding=0, use_bias=False),
        nn.BatchNorm(), nn.Activation("relu"),          # 4x4
        nn.Conv2DTranspose(args.ngf * 2, 4, strides=2, padding=1, use_bias=False),
        nn.BatchNorm(), nn.Activation("relu"),          # 8x8
        nn.Conv2DTranspose(args.ngf, 4, strides=2, padding=1, use_bias=False),
        nn.BatchNorm(), nn.Activation("relu"),          # 16x16
        nn.Conv2DTranspose(3, 4, strides=2, padding=1, use_bias=False),
        nn.Activation("tanh"),                          # 32x32
    )
    # discriminator: (3,32,32) -> 1 logit
    netD = nn.HybridSequential()
    netD.add(
        nn.Conv2D(args.ndf, 4, strides=2, padding=1, use_bias=False),
        nn.LeakyReLU(0.2),                              # 16x16
        nn.Conv2D(args.ndf * 2, 4, strides=2, padding=1, use_bias=False),
        nn.BatchNorm(), nn.LeakyReLU(0.2),              # 8x8
        nn.Conv2D(args.ndf * 4, 4, strides=2, padding=1, use_bias=False),
        nn.BatchNorm(), nn.LeakyReLU(0.2),              # 4x4
        nn.Conv2D(1, 4, strides=1, padding=0, use_bias=False),
        nn.Flatten(),
    )
    return netG, netD


def real_batches(args):
    """Deterministic synthetic image distribution in [-1, 1], NCHW."""
    import numpy as onp

    from incubator_mxnet_tpu.gluon.data import DataLoader
    from incubator_mxnet_tpu.gluon.data.vision import SyntheticImageDataset

    ds = SyntheticImageDataset(num_samples=args.num_samples, num_classes=4,
                               shape=(3, 32, 32), noise=0.2, seed=3,
                               template_seed=11)
    # dataset yields HWC; normalize to [-1,1] CHW to match tanh output
    def tf(x, y):
        import jax.numpy as jnp

        from incubator_mxnet_tpu.ndarray.ndarray import NDArray, raw

        a = raw(x).transpose(2, 0, 1)
        a = jnp.tanh(a)  # squash template+noise into (-1, 1)
        return NDArray(a), y

    ds._transform = tf
    return DataLoader(ds, batch_size=args.batch_size, shuffle=True,
                      last_batch="discard")


def main(argv=None):
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, loss as gloss
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)
    netG, netD = build_nets(args)
    netG.initialize(mx.init.Normal(0.02))
    netD.initialize(mx.init.Normal(0.02))

    # materialize deferred shapes, then hybridize
    z0 = NDArray(jnp.zeros((args.batch_size, args.latent, 1, 1), jnp.float32))
    netD(netG(z0))
    netG.hybridize()
    netD.hybridize()

    loss_fn = gloss.SigmoidBinaryCrossEntropyLoss()
    trainerG = Trainer(netG.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": args.beta1})
    trainerD = Trainer(netD.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": args.beta1})

    ones = NDArray(jnp.ones((args.batch_size, 1), jnp.float32))
    zeros = NDArray(jnp.zeros((args.batch_size, 1), jnp.float32))

    key = jax.random.PRNGKey(1)
    hist = []
    for epoch in range(args.epochs):
        t0, seen = time.time(), 0
        for bi, (real, _) in enumerate(real_batches(args)):
            if args.max_batches and bi >= args.max_batches:
                break
            key, kz1, kz2 = jax.random.split(key, 3)
            z = NDArray(jax.random.normal(kz1, (args.batch_size, args.latent, 1, 1)))

            # --- update D: maximize log D(x) + log(1 - D(G(z))) ---
            fake = netG(z).detach()
            with autograd.record():
                out_real = netD(real)
                out_fake = netD(fake)
                lossD = (loss_fn(out_real, ones) + loss_fn(out_fake, zeros)).mean()
            lossD.backward()
            trainerD.step(1)

            # --- update G: maximize log D(G(z)) ---
            z = NDArray(jax.random.normal(kz2, (args.batch_size, args.latent, 1, 1)))
            with autograd.record():
                lossG = loss_fn(netD(netG(z)), ones).mean()
            lossG.backward()
            trainerG.step(1)

            seen += args.batch_size
            if bi % args.log_interval == 0:
                d, g = float(lossD.asnumpy()), float(lossG.asnumpy())
                hist.append((d, g))
                print(f"epoch {epoch} batch {bi} lossD {d:.3f} lossG {g:.3f} "
                      f"({seen / (time.time() - t0):.0f} img/s)")
    if args.save_prefix:
        netG.save_parameters(args.save_prefix + "-G.params")
        netD.save_parameters(args.save_prefix + "-D.params")
    return hist


if __name__ == "__main__":
    main()
