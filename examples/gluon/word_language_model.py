"""Gluon word-level language model (LSTM, BPTT).

TPU-native rendition of the reference
`example/gluon/word_language_model/train.py` [UNVERIFIED]
(SURVEY.md §2.8): Embedding → multi-layer LSTM → (optionally tied)
Dense decoder, trained with truncated BPTT — hidden state carried
across windows and detached — gradient clipping by global norm, SGD
with validation-driven LR annealing, perplexity reporting.

Data: a PTB-layout text file via `--data`; otherwise a deterministic
synthetic Markov corpus stands in (no network egress here), which a
2-layer LSTM compresses well below the uniform-perplexity baseline —
that drop is the CI gate.

Run: python examples/gluon/word_language_model.py --epochs 2
"""
from __future__ import annotations

import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser(description="Gluon word language model")
    p.add_argument("--data", type=str, default=None,
                   help="path to a tokenized text file; synthetic if absent")
    p.add_argument("--vocab", type=int, default=200,
                   help="synthetic corpus vocabulary size")
    p.add_argument("--corpus-tokens", type=int, default=40000,
                   help="synthetic corpus length")
    p.add_argument("--emsize", type=int, default=128)
    p.add_argument("--nhid", type=int, default=128)
    p.add_argument("--nlayers", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=20)
    p.add_argument("--bptt", type=int, default=35)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=1.0)
    p.add_argument("--clip", type=float, default=0.25)
    p.add_argument("--dropout", type=float, default=0.2)
    p.add_argument("--tied", action="store_true",
                   help="tie embedding and decoder weights")
    p.add_argument("--log-interval", type=int, default=50)
    return p


class RNNModel:
    """Container holding the LM blocks (built in main to defer imports)."""


def build_model(args, vocab_size):
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn, rnn
    from incubator_mxnet_tpu.gluon.block import HybridBlock

    tied = args.tied
    if tied and args.emsize != args.nhid:
        raise ValueError("--tied requires emsize == nhid")

    class LM(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.embed = nn.Embedding(vocab_size, args.emsize)
            self.drop = nn.Dropout(args.dropout)
            self.lstm = rnn.LSTM(args.nhid, num_layers=args.nlayers,
                                 layout="TNC", dropout=args.dropout)
            if tied:
                # weight tying = ONE shared Parameter: project with the
                # embedding matrix itself (ref --tied), own bias only
                self.decoder_bias = self.params.get(
                    "decoder_bias", shape=(vocab_size,), init="zeros")
            else:
                self.decoder = nn.Dense(vocab_size, flatten=False)

        def forward(self, x, states):
            # x: (bptt, batch) int tokens
            emb = self.drop(self.embed(x))
            out, new_states = self.lstm(emb, states)
            out = self.drop(out)
            if tied:
                logits = nd.FullyConnected(
                    out, self.embed.weight.data(), self.decoder_bias.data(),
                    num_hidden=vocab_size, flatten=False, no_bias=False)
            else:
                logits = self.decoder(out)
            return logits, new_states

    return LM()


def synthetic_corpus(vocab, n_tokens, seed=7):
    """Markov bigram chain: each token strongly prefers 4 successors."""
    import numpy as onp

    rng = onp.random.RandomState(seed)
    successors = rng.randint(0, vocab, size=(vocab, 4))
    toks = onp.empty(n_tokens, dtype="int32")
    toks[0] = 0
    choices = rng.randint(0, 4, size=n_tokens)          # which successor
    noise = rng.rand(n_tokens) < 0.05                   # 5% random jumps
    jumps = rng.randint(0, vocab, size=n_tokens)
    for i in range(1, n_tokens):
        toks[i] = jumps[i] if noise[i] else successors[toks[i - 1], choices[i]]
    return toks


def load_corpus(args):
    import numpy as onp

    if args.data and os.path.exists(args.data):
        with open(args.data) as f:
            words = f.read().replace("\n", " <eos> ").split()
        vocab = {w: i for i, w in enumerate(sorted(set(words)))}
        toks = onp.asarray([vocab[w] for w in words], dtype="int32")
        return toks, len(vocab)
    return synthetic_corpus(args.vocab, args.corpus_tokens), args.vocab


def batchify(toks, batch_size):
    import numpy as onp

    nbatch = len(toks) // batch_size
    return onp.asarray(toks[: nbatch * batch_size]).reshape(batch_size, nbatch).T


def detach_states(states):
    return [s.detach() for s in states]


def evaluate(model, loss_fn, data, args, mx):
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    total, count = 0.0, 0
    states = model.lstm.begin_state(args.batch_size)
    for i in range(0, data.shape[0] - 1, args.bptt):
        seq_len = min(args.bptt, data.shape[0] - 1 - i)
        if seq_len < args.bptt:
            break  # static shapes: skip the ragged tail window
        x = NDArray(jnp.asarray(data[i:i + seq_len]))
        y = NDArray(jnp.asarray(data[i + 1:i + 1 + seq_len]))
        logits, states = model(x, states)
        l = loss_fn(logits, y)
        total += float(l.mean().asnumpy()) * seq_len
        count += seq_len
    return total / max(count, 1)


def main(argv=None):
    args = build_parser().parse_args(argv)

    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, loss as gloss, utils as gutils
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(42)
    toks, vocab_size = load_corpus(args)
    split = int(len(toks) * 0.9)
    train_data = batchify(toks[:split], args.batch_size)
    val_data = batchify(toks[split:], args.batch_size)

    model = build_model(args, vocab_size)
    model.initialize(mx.init.Uniform(0.1))
    model.hybridize()

    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    trainer = Trainer(model.collect_params(), "sgd",
                      {"learning_rate": args.lr, "momentum": 0.0})

    uniform_ppl = vocab_size
    best_val = float("inf")
    for epoch in range(args.epochs):
        states = model.lstm.begin_state(args.batch_size)
        total, count, t0 = 0.0, 0, time.time()
        for bi, i in enumerate(range(0, train_data.shape[0] - 1, args.bptt)):
            seq_len = min(args.bptt, train_data.shape[0] - 1 - i)
            if seq_len < args.bptt:
                break
            x = NDArray(jnp.asarray(train_data[i:i + seq_len]))
            y = NDArray(jnp.asarray(train_data[i + 1:i + 1 + seq_len]))
            states = detach_states(states)
            with autograd.record():
                logits, states = model(x, states)
                l = loss_fn(logits, y).mean()
            l.backward()
            grads = [p.grad() for p in model.collect_params().values()
                     if p.grad_req != "null"]
            gutils.clip_global_norm(grads, args.clip * args.batch_size)
            trainer.step(1)
            total += float(l.asnumpy()) * seq_len
            count += seq_len
            if bi % args.log_interval == 0 and bi > 0:
                cur = total / count
                print(f"epoch {epoch} batch {bi} loss {cur:.3f} "
                      f"ppl {math.exp(min(cur, 20)):.1f} "
                      f"({count * args.batch_size / (time.time() - t0):.0f} tok/s)")
        val_loss = evaluate(model, loss_fn, val_data, args, mx)
        val_ppl = math.exp(min(val_loss, 20))
        print(f"epoch {epoch}: val loss {val_loss:.3f} val ppl {val_ppl:.1f} "
              f"(uniform ppl {uniform_ppl})")
        if val_loss < best_val:
            best_val = val_loss
        else:
            trainer.set_learning_rate(trainer.learning_rate / 4.0)
            print(f"annealed lr to {trainer.learning_rate}")
    return math.exp(min(best_val, 20)), uniform_ppl


if __name__ == "__main__":
    final_ppl, uniform = main()
    print(f"final val ppl {final_ppl:.1f} vs uniform {uniform}")
