"""Gluon MNIST — the first demo gate (BASELINE config #1).

TPU-native rendition of the reference `example/gluon/mnist/mnist.py`
[UNVERIFIED] (SURVEY.md §2.8, §7 P2): LeNet trained with the canonical
Gluon loop — `autograd.record()` → `loss.backward()` →
`trainer.step()` — hybridized, checkpointed, ≥98% val accuracy.

Data: real MNIST when `--data-dir` points at the ubyte files
(`mx.gluon.data.vision.MNIST` layout); otherwise a deterministic
synthetic image dataset stands in so the gate runs in any sandbox
(this environment has no network egress).

Run: python examples/gluon/mnist.py --epochs 3
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser(description="Gluon MNIST LeNet")
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--data-dir", type=str, default=None,
                   help="dir with MNIST ubyte files; synthetic data if absent")
    p.add_argument("--no-hybridize", action="store_true")
    p.add_argument("--save-prefix", type=str, default=None,
                   help="checkpoint prefix (writes .params + trainer states)")
    p.add_argument("--train-samples", type=int, default=4000,
                   help="synthetic train set size")
    return p


def get_data(args):
    from incubator_mxnet_tpu.gluon.data import DataLoader
    from incubator_mxnet_tpu.gluon.data.vision import (MNIST,
                                                       SyntheticImageDataset,
                                                       transforms)

    tf = lambda x, y: (transforms.ToTensor()(x), y)  # HWC uint8 -> CHW float
    if args.data_dir and os.path.exists(args.data_dir):
        train_ds = MNIST(root=args.data_dir, train=True, transform=tf)
        val_ds = MNIST(root=args.data_dir, train=False, transform=tf)
    else:
        train_ds = SyntheticImageDataset(num_samples=args.train_samples,
                                         num_classes=10, seed=1,
                                         template_seed=7, transform=tf)
        val_ds = SyntheticImageDataset(num_samples=1000, num_classes=10,
                                       seed=2, template_seed=7, transform=tf)
    return (DataLoader(train_ds, batch_size=args.batch_size, shuffle=True),
            DataLoader(val_ds, batch_size=args.batch_size))


def evaluate(net, val_dl, metric):
    metric.reset()
    for x, y in val_dl:
        metric.update([y], [net(x)])
    return metric.get()[1]


def train(args):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, metric as metric_mod
    from incubator_mxnet_tpu.gluon import Trainer, loss as loss_mod
    from incubator_mxnet_tpu.gluon.model_zoo.vision import LeNet

    train_dl, val_dl = get_data(args)
    mx.random.seed(0)
    net = LeNet()
    net.initialize()
    if not args.no_hybridize:
        net.hybridize()
    loss_fn = loss_mod.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": args.lr, "momentum": args.momentum})
    acc = metric_mod.Accuracy()

    val_acc = 0.0
    for epoch in range(args.epochs):
        tic = time.time()
        n = 0
        for x, y in train_dl:
            with autograd.record():
                out = net(x)
                L = loss_fn(out, y)
            L.backward()
            trainer.step(x.shape[0])
            n += x.shape[0]
        val_acc = evaluate(net, val_dl, acc)
        print(f"Epoch {epoch}: val_acc={val_acc:.4f} "
              f"({n / (time.time() - tic):.0f} samples/s)")

    if args.save_prefix:
        net.save_parameters(args.save_prefix + ".params")
        trainer.save_states(args.save_prefix + ".states")
        print(f"saved checkpoint to {args.save_prefix}.params/.states")
    return val_acc


def main(argv=None):
    args = build_parser().parse_args(argv)
    val_acc = train(args)
    gate = 0.98
    status = "PASS" if val_acc >= gate else "FAIL"
    print(f"MNIST gate: val_acc={val_acc:.4f} (target >= {gate}) {status}")
    return val_acc


if __name__ == "__main__":
    main()
