"""ArcFace training recipe — model-parallel sharded margin-softmax.

TPU-native rendition of the InsightFace/ArcFace large-softmax hybrid
parallel recipe (SURVEY.md §2.4 "Large-softmax hybrid parallel",
BASELINE config #5): a CNN embedding backbone (DP over the `data`
axis) feeding a classifier weight SHARDED over the `model` axis, with
the global softmax assembled via `psum`/`pmax` collectives
(`models.arcface.arcface_loss_sharded`) — classifier memory scales
1/model_parallel, the marquee property of the recipe.

Identities/data are synthetic (no dataset egress in this sandbox):
each identity is a fixed random template plus noise, which a working
embedding+margin pipeline must separate to ~100% train accuracy.

Run (8 virtual CPU devices, 4-way data x 2-way model):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/face/train_arcface.py --data-parallel 4 --model-parallel 2
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser(description="ArcFace sharded-softmax trainer")
    p.add_argument("--num-identities", type=int, default=64)
    p.add_argument("--emb-dim", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--steps", type=int, default=80)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--scale", type=float, default=16.0)
    p.add_argument("--margin", type=float, default=0.2)
    p.add_argument("--data-parallel", type=int, default=1)
    p.add_argument("--model-parallel", type=int, default=1)
    return p


def train(args):
    import jax
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel as par
    from incubator_mxnet_tpu.models import arcface

    mesh = None
    if args.data_parallel * args.model_parallel > 1:
        mesh = par.create_mesh(data=args.data_parallel,
                               model=args.model_parallel)
        print(f"mesh: {dict(mesh.shape)}")

    # synthetic identities: fixed template per class + per-sample noise
    kt, kd = jax.random.split(jax.random.PRNGKey(0))
    feat_dim = 128
    templates = jax.random.normal(kt, (args.num_identities, feat_dim))

    # embedding backbone: 2-layer MLP (stands in for the ResNet trunk;
    # swap in model_zoo.vision.get_model for a real face dataset)
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    params = {
        "w1": jax.random.normal(k1, (feat_dim, 128)) * 0.05,
        "w2": jax.random.normal(k2, (128, args.emb_dim)) * 0.05,
        "cls": jax.random.normal(jax.random.PRNGKey(2),
                                 (args.num_identities, args.emb_dim)) * 0.01,
    }
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        params["cls"] = jax.device_put(
            params["cls"], NamedSharding(mesh, P("model", None)))

    scale, margin = args.scale, args.margin

    def embed(p, x):
        h = jnp.tanh(x @ p["w1"])
        return h @ p["w2"]

    def loss_fn(p, x, y):
        emb = embed(p, x)
        if mesh is not None:
            return arcface.arcface_loss_sharded(emb, p["cls"], y, mesh,
                                                scale, margin)
        logits = arcface.arcface_logits(emb, p["cls"], y, scale, margin)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    lr = args.lr

    @jax.jit
    def step(p, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        p = jax.tree_util.tree_map(lambda w, gw: w - lr * gw, p, g)
        return p, l

    @jax.jit
    def accuracy(p, x, y):
        emb = embed(p, x)
        embn = emb / jnp.linalg.norm(emb, axis=1, keepdims=True)
        wn = p["cls"] / jnp.linalg.norm(p["cls"], axis=1, keepdims=True)
        return jnp.mean((embn @ wn.T).argmax(axis=1) == y)

    key = jax.random.PRNGKey(3)
    t0 = time.time()
    acc = 0.0
    for it in range(1, args.steps + 1):
        key, ky, kn = jax.random.split(key, 3)
        y = jax.random.randint(ky, (args.batch_size,), 0,
                               args.num_identities, dtype=jnp.int32)
        x = templates[y] + 0.3 * jax.random.normal(kn, (args.batch_size, feat_dim))
        params, L = step(params, x, y)
        if it % 20 == 0 or it == args.steps:
            acc = float(accuracy(params, x, y))
            print(f"step {it}: loss={float(L):.4f} train_acc={acc:.3f} "
                  f"({it * args.batch_size / (time.time() - t0):.0f} samples/s)")
    return acc


if __name__ == "__main__":
    train(build_parser().parse_args())
