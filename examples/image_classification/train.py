"""Image-classification training over the native RecordIO pipeline.

TPU-native rendition of the reference
`example/image-classification/train_imagenet.py` + `common/fit.py`
[UNVERIFIED] (SURVEY.md §2.8): any model-zoo network (default
ResNet-50 v1) fed by the C++ threaded RecordIO decode/augment pipeline
(`mx.io.ImageRecordIter`), Speedometer logging, epoch checkpoints, and
an images/sec report — the metric of record for this config
(BASELINE.md ResNet-50 img/s).

Without `--data-train` a synthetic RecordIO file is packed on the fly
(JPEG-encoded class templates) so the full path — .rec container → C++
decode → augment → device — is exercised in any sandbox.

Run: python examples/image_classification/train.py --network resnet50_v1 \
        --image-shape 3,224,224 --batch-size 64 --num-epochs 1
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser(description="image-classification trainer")
    p.add_argument("--network", type=str, default="resnet50_v1")
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--image-shape", type=str, default="3,64,64")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-epochs", type=int, default=1)
    p.add_argument("--max-batches", type=int, default=0,
                   help="cap batches/epoch (0 = full epoch)")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=1e-4)
    p.add_argument("--data-train", type=str, default=None,
                   help=".rec file; synthetic data packed if absent")
    p.add_argument("--synthetic-samples", type=int, default=256)
    p.add_argument("--disp-batches", type=int, default=20,
                   help="Speedometer frequency")
    p.add_argument("--model-prefix", type=str, default=None)
    p.add_argument("--dtype", type=str, default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--remat", action="store_true",
                   help="rematerializing backward (trade FLOPs for HBM; "
                        "hybridize(remat_backward=True))")
    p.add_argument("--chain-steps", type=int, default=1,
                   help="buffer K steps into ONE dispatched program "
                        "(Trainer(chain_steps=K)); amortizes per-step "
                        "dispatch overhead — metric updates are deferred "
                        "to flush boundaries so they don't force early "
                        "flushes")
    return p


def make_synthetic_rec(path, num_samples, num_classes, hw):
    """Pack JPEG class templates into a .rec (exercises the real codec)."""
    import numpy as onp

    from incubator_mxnet_tpu import recordio

    rng = onp.random.RandomState(7)
    templates = rng.randint(0, 255, (num_classes, hw, hw, 3), dtype=onp.uint8)
    rec = recordio.MXRecordIO(path, "w")
    order = onp.random.RandomState(1).randint(0, num_classes, num_samples)
    for i, cls in enumerate(order):
        noise = rng.randint(-20, 20, templates[cls].shape).astype(onp.int16)
        img = onp.clip(templates[cls].astype(onp.int16) + noise, 0, 255).astype(onp.uint8)
        hdr = recordio.IRHeader(0, float(cls), i, 0)
        rec.write(recordio.pack_img(hdr, img, quality=90))
    rec.close()
    return path


def train(args):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, callback, metric as metric_mod
    from incubator_mxnet_tpu.gluon import Trainer, loss as loss_mod
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    shape = tuple(int(x) for x in args.image_shape.split(","))
    rec_path = args.data_train
    if not rec_path:
        rec_path = os.path.join("/tmp", f"synthetic_{shape[1]}.rec")
        if not os.path.exists(rec_path):
            make_synthetic_rec(rec_path, args.synthetic_samples,
                               args.num_classes, shape[1])
        print(f"using synthetic RecordIO at {rec_path}")

    train_iter = mx.io.ImageRecordIter(
        path_imgrec=rec_path, data_shape=shape, batch_size=args.batch_size,
        shuffle=True, rand_mirror=True,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.1, std_b=57.4,
        device_normalize=True)  # uint8 over the link; affine fuses on device

    mx.random.seed(0)
    body = vision.get_model(args.network, classes=args.num_classes)
    body.initialize()
    # materialize deferred shapes before optional bf16 cast
    body(NDArray(mx.nd.zeros((args.batch_size,) + shape)._data))
    if args.dtype == "bfloat16":
        body.cast("bfloat16")
    # uint8 over the link; normalize+cast fuse into the compiled step
    net = train_iter.wrap_net(body, dtype=args.dtype)
    net.hybridize(remat_backward=args.remat)
    loss_fn = loss_mod.SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": args.lr, "momentum": args.momentum,
                       "wd": args.wd,
                       "multi_precision": args.dtype == "bfloat16"},
                      keep_grads=False,  # grads consumed in the fused step
                      chain_steps=args.chain_steps)
    acc = metric_mod.Accuracy()

    total_samples = 0
    deferred = []  # (label, logits) awaiting a chain flush
    t_start = time.time()
    for epoch in range(args.num_epochs):
        speed = callback.Speedometer(args.batch_size, args.disp_batches)
        train_iter.reset()
        acc.reset()
        for nbatch, batch in enumerate(train_iter):
            if args.max_batches and nbatch >= args.max_batches:
                break
            x = batch.data[0]  # raw uint8: normalization is inside net
            y = batch.label[0]
            with autograd.record():
                out = net(x)
                L = loss_fn(out, y)
            L.backward()
            trainer.step(args.batch_size)
            if args.chain_steps > 1:
                # reading `out` would force an early chain flush — defer
                # metric updates to the flush boundary (values then fill
                # from the already-dispatched chained program)
                deferred.append((y, out))
                if len(deferred) >= args.chain_steps:
                    for yy, oo in deferred:
                        acc.update([yy], [oo])
                    deferred.clear()
            else:
                acc.update([y], [out])
            total_samples += args.batch_size
            speed(callback.BatchEndParam(epoch=epoch, nbatch=nbatch,
                                         eval_metric=acc, locals=locals()))
        trainer.flush()
        for yy, oo in deferred:
            acc.update([yy], [oo])
        deferred.clear()
        print(f"Epoch {epoch}: train_acc={acc.get()[1]:.4f}")
        if args.model_prefix:
            # save from the inner model: keys stay loadable into a bare
            # vision.get_model() network (no wrapper prefix)
            body.save_parameters(f"{args.model_prefix}-{epoch:04d}.params")

    dt = time.time() - t_start
    img_s = total_samples / dt
    print(f"TOTAL {total_samples} images in {dt:.1f}s = {img_s:.1f} img/s")
    return img_s, acc.get()[1]


if __name__ == "__main__":
    train(build_parser().parse_args())
