"""Inference throughput scorer (ref `benchmark_score.py`).

TPU-native rendition of the reference
`example/image-classification/benchmark_score.py` [UNVERIFIED]
(SURVEY.md §2.8, §6 "Measurement conventions"): forward-only img/s for
any model-zoo network across batch sizes, synthetic device-resident
input (measures the model, not the input pipeline).

Run: python examples/image_classification/benchmark_score.py \
        --network resnet50_v1 --batch-sizes 1,8,32
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_parser():
    p = argparse.ArgumentParser(description="inference img/s scorer")
    p.add_argument("--network", type=str, default="resnet50_v1")
    p.add_argument("--image-shape", type=str, default="3,224,224")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--batch-sizes", type=str, default="1,8,32")
    p.add_argument("--num-batches", type=int, default=20)
    p.add_argument("--dtype", type=str, default="float32",
                   choices=["float32", "bfloat16", "int8"])
    p.add_argument("--calib-mode", type=str, default="minmax",
                   choices=["minmax", "entropy"])
    return p


def score(args):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    shape = tuple(int(x) for x in args.image_shape.split(","))
    mx.random.seed(0)
    net = vision.get_model(args.network, classes=args.num_classes)
    net.initialize()
    net(NDArray(mx.nd.zeros((1,) + shape)._data))
    if args.dtype == "bfloat16":
        net.cast("bfloat16")
    if args.dtype == "int8":
        # PTQ: conv+dense swapped for int8 MXU kernels (ref
        # quantized ResNet flow, src/operator/quantization/); the rest
        # of the net (BN/pool/relu) runs bf16 so the epilogues don't
        # give back the int8 win
        from incubator_mxnet_tpu.contrib.quantization import quantize_net

        import jax

        net.cast("bfloat16")
        calib = [NDArray(jax.random.normal(jax.random.PRNGKey(i),
                                           (8,) + shape).astype("bfloat16"))
                 for i in range(2)]
        quantize_net(net, calib, calib_mode=args.calib_mode)
    net.hybridize()  # one compiled program either way (int8 kernels trace)

    results = []
    for bs in (int(b) for b in args.batch_sizes.split(",")):
        x = mx.nd.zeros((bs,) + shape)
        if args.dtype in ("bfloat16", "int8"):
            x = x.astype("bfloat16")  # int8 nets run bf16 between convs
        out = net(x)  # compile
        float(out.asnumpy().ravel()[0])
        tic = time.time()
        for _ in range(args.num_batches):
            out = net(x)
        float(out.asnumpy().ravel()[0])  # sync
        img_s = bs * args.num_batches / (time.time() - tic)
        results.append((bs, img_s))
        print(f"batchsize={bs:4d}  {img_s:10.1f} img/s  ({args.network}, {args.dtype})")
    return results


if __name__ == "__main__":
    score(build_parser().parse_args())
