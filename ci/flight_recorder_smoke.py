"""CI gate: the crash/preemption flight recorder really ships a bundle.

Parent/child protocol:

1. the CHILD (`--child`) runs a 3-step Gluon train with
   ``MXTPU_FLIGHT_DIR`` set (which both enables telemetry and installs
   the recorder), prints READY, and parks;
2. the PARENT SIGTERMs it — the preemption signal TPU pools deliver —
   and asserts:
   * the child exits with the conventional 128+SIGTERM code (the
     handler re-delivers after dumping, so preemption tooling still
     sees a killed process);
   * ``flight.jsonl`` exists, parses, leads with a ``flight_meta``
     line whose reason is ``signal:SIGTERM``;
   * the FINAL record is the in-flight step (step 3) and carries its
     span tree (``trainer/step``) and a metric snapshot
     (``trainer_step_seconds`` count == 3);
   * ``flight_trace.json`` is a well-formed chrome trace of the window.

Run via ci/lint.sh; standalone:
    JAX_PLATFORMS=cpu python ci/flight_recorder_smoke.py
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def child():
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, nn
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)
    net = nn.Dense(4, in_units=3)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    x = NDArray(jnp.ones((2, 3)))
    for _ in range(3):
        with autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        tr.step(2)
    print("READY", flush=True)
    while True:  # park: the parent's SIGTERM is the exit path
        time.sleep(0.1)


def main():
    flight_dir = tempfile.mkdtemp(prefix="mxtpu_flight_")
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_FLIGHT_DIR=flight_dir)
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__),
                             "--child"],
                            env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 180
        line = ""
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "READY" in line:
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f"child died before READY: {line}{proc.stdout.read()}")
        else:
            raise AssertionError("child never reached READY")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()

    assert rc == -signal.SIGTERM or rc == 128 + signal.SIGTERM, \
        f"child exit code {rc}, wanted SIGTERM death (-15 or 143)"

    jsonl = os.path.join(flight_dir, "flight.jsonl")
    trace = os.path.join(flight_dir, "flight_trace.json")
    assert os.path.exists(jsonl), f"no flight.jsonl in {flight_dir}"
    assert os.path.exists(trace), f"no flight_trace.json in {flight_dir}"

    with open(jsonl) as f:
        lines = [json.loads(l) for l in f if l.strip()]
    assert lines and "flight_meta" in lines[0], f"no flight_meta: {lines[:1]}"
    meta = lines[0]["flight_meta"]
    assert meta["reason"] == "signal:SIGTERM", meta
    assert meta["step"] == 3 and meta["records"] == len(lines) - 1, meta

    records = lines[1:]
    assert records, "flight bundle carries no step records"
    last = records[-1]
    assert last["step"] == 3, f"final record is step {last['step']}, not 3"
    span_names = {s["name"] for s in last["spans"]}
    assert "trainer/step" in span_names, \
        f"final step's span tree missing trainer/step: {span_names}"
    hist = last["metrics"].get("trainer_step_seconds")
    assert hist and hist["count"] == 3, \
        f"final metric snapshot wrong: trainer_step_seconds={hist}"
    assert last["deltas"], "final record carries no counter deltas"

    with open(trace) as f:
        tr = json.load(f)
    assert tr.get("traceEvents"), "flight_trace.json has no events"
    assert any(e.get("name") == "trainer/step" for e in tr["traceEvents"])

    print(f"flight recorder smoke: OK ({len(records)} records, "
          f"reason {meta['reason']}, exit {rc})")


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        main()
