#!/usr/bin/env bash
# ASAN build + test of the C++ host components (SURVEY.md §5.2: the
# reference runs sanitizer builds in CI, not in product code — same
# here: RecordIO codec + image pipeline compile under
# -fsanitize=address,undefined and the native IO test suite runs
# against the instrumented libraries).
set -euo pipefail
cd "$(dirname "$0")/.."

BUILD=incubator_mxnet_tpu/native/_build_asan
mkdir -p "$BUILD"

CXXFLAGS="-O1 -g -std=c++17 -shared -fPIC -pthread -fsanitize=address,undefined -fno-omit-frame-pointer"
echo "ASAN-compiling native/recordio.cc"
g++ $CXXFLAGS -o "$BUILD/librecordio.so" incubator_mxnet_tpu/native/recordio.cc
echo "ASAN-compiling native/image_pipeline.cc"
g++ $CXXFLAGS -o "$BUILD/libimage_pipeline.so" \
    incubator_mxnet_tpu/native/image_pipeline.cc -ljpeg

# point the loader at the instrumented libs and run the native IO tests.
# leak detection off: the long-lived python process holds allocator pools.
export MXTPU_NATIVE_BUILD_DIR="$PWD/$BUILD"
export MXTPU_NATIVE_NO_REBUILD=1
export ASAN_OPTIONS=detect_leaks=0
export LD_PRELOAD="$(g++ -print-file-name=libasan.so)"
JAX_PLATFORMS=cpu python -m pytest tests/test_native_io.py -q
echo "ASAN native suite: OK"
