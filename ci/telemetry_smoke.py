"""CI smoke: a 3-step tiny train with MXTPU_TELEMETRY_DUMP=1 must
produce a parseable Prometheus dump containing the acceptance series
(trainer_step_seconds buckets, kvstore_push_bytes_total,
retraces_total), a valid JSONL, and a merged chrome trace with Trainer
spans nested under the step span.

Run as `python ci/telemetry_smoke.py` (ci/lint.sh invokes it).
"""
import json
import os
import sys
import tempfile

# runnable as `python ci/telemetry_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# env must be set BEFORE the package import: _configure_from_env reads
# it at import time (this is exactly the user-facing flow under test)
_DIR = tempfile.mkdtemp(prefix="mxtpu_tel_smoke_")
os.environ["MXTPU_TELEMETRY_DUMP"] = "1"
os.environ["MXTPU_TELEMETRY_DIR"] = _DIR
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax.numpy as jnp  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import autograd, telemetry  # noqa: E402
from incubator_mxnet_tpu.gluon import Trainer, nn  # noqa: E402
from incubator_mxnet_tpu.ndarray.ndarray import NDArray  # noqa: E402


def main() -> int:
    assert telemetry.enabled(), "MXTPU_TELEMETRY_DUMP=1 did not enable"

    mx.random.seed(0)
    net = nn.Dense(4)
    net.initialize()
    # fuse_step=False drives the kvstore push/pull path
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                      fuse_step=False)
    x = NDArray(jnp.ones((2, 3)))
    for _ in range(3):
        with autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        trainer.step(2)

    paths = telemetry.dump()  # the atexit dump would fire too; be explicit

    # -- Prometheus text: required series present and well-formed ------- #
    prom = open(paths["prom"]).read()
    for needle in ("trainer_step_seconds_bucket{le=",
                   'trainer_step_seconds_bucket{le="+Inf"}',
                   "trainer_step_seconds_count 3",
                   "kvstore_push_bytes_total",
                   "retraces_total"):
        if needle not in prom:
            print(f"FAIL: {needle!r} missing from {paths['prom']}")
            return 1
    for line in prom.splitlines():
        if not line or line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        float(value)  # every sample line must end in a number
        assert name_part, line

    # -- JSONL: every line parses --------------------------------------- #
    n = 0
    with open(paths["jsonl"]) as f:
        for raw in f:
            rec = json.loads(raw)
            assert "name" in rec and "type" in rec, rec
            n += 1
    assert n > 0, "empty JSONL"

    # -- chrome trace: Trainer spans nested under trainer/step ---------- #
    trace = json.load(open(paths["trace"]))
    evs = trace["traceEvents"]
    assert any(e["name"] == "trainer/step" for e in evs), "no step span"
    nested = [e for e in evs
              if e.get("args", {}).get("parent") == "trainer/step"]
    assert nested, "no span nested under trainer/step"

    print(f"telemetry smoke: OK ({n} jsonl metrics, {len(evs)} trace "
          f"events, dir {_DIR})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
