"""CI gate: compiled-program contracts over the repo's flagship programs.

Compiles the programs whose compiled-artifact properties the repo
stakes perf claims on, extracts hlolint fact summaries from the SAME
AOT compile that feeds the roofline (telemetry.perf text capture — no
extra compilation beyond what trainer/generation already do), and
evaluates the committed `.hlolint_contracts.json`:

* ``trainer_full_step``               — monolithic data-parallel step
* ``trainer_full_step_zero_bucketed`` — ZeRO explicit tier, bucketed
  overlapped gradient sync (one reduce-scatter per bucket)
* ``decode_float`` / ``decode_int8``  — generation's bf16 and
  int8-weight greedy decode programs
* ``checkpoint_snapshot``             — the async checkpointer's
  on-device copy (must stay pure per-shard copies: no collectives,
  no host transfers)
* ``serving_prefill_chunk_float`` / ``serving_step_float`` and their
  ``_int8`` twins — the continuous-batching engine's paged-KV
  programs (donation must hold so eviction never doubles the pool;
  the int8 path must not materialize bf16 weight copies).  Prefill is
  the ISSUE 20 fixed-width chunk program — ONE per engine, no pow2
  bucket ladder
* ``serving_*_float_kv8`` — the int8-KV-pool family (``kv_dtype=
  "int8"``): the pool must actually carry s8 pages and keep donation
* ``serving_*_float_pallas`` — the forced paged-attention-kernel
  family: the decode step must NOT materialize the fp32
  ``(B, H, max_seq_len)`` attention-probs buffer the dense-gather
  path streams (that buffer is the whole point of the kernel)
* ``serving_draft_step_float`` / ``serving_spec_verify_float`` /
  ``serving_draft_prefill_chunk_float`` — the speculative-decoding
  family (``speculate_k > 0``): draft k-token proposer, batched target
  verifier, and the draft-pool chunk prefill.  Donation must hold on BOTH
  pool sets and everything stays on-device / collective-free /
  f64-free — speculation is a throughput lever, not a numerics change

Contract context (``ctx``) carries the run's ground truth: the mesh
size ``D``, the bucket count ``n_buckets``, the global gradient bytes
``grad_bytes``, and the quantized weight shapes — so contracts can say
``collective_count('reduce-scatter') == ctx['n_buckets']`` instead of
hard-coding numbers that drift with the smoke model.

The gate fails on any contract violation AND on any captured program
with no contract (tpulint-style: new programs must either get a
contract or be listed under ``accepted``).  Bootstrap or refresh with

    JAX_PLATFORMS=cpu python ci/hlolint_gate.py --write-contracts

then review + tighten the pinned bounds before committing.

Run via ci/lint.sh; standalone:  JAX_PLATFORMS=cpu python ci/hlolint_gate.py
"""
import argparse
import json
import os
import sys
import tempfile

# runnable as `python ci/hlolint_gate.py` from the repo root
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

# env must be set BEFORE the package import: the virtual device count is
# read at backend init, telemetry config at package import
_FLAGS = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if not f.startswith("--xla_force_host_platform_device_count")]
os.environ["XLA_FLAGS"] = " ".join(
    _FLAGS + ["--xla_force_host_platform_device_count=8"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("MXTPU_TELEMETRY_DUMP", None)
os.environ["MXTPU_TELEMETRY_DIR"] = tempfile.mkdtemp(prefix="mxtpu_hlolint_")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import autograd, gluon, telemetry  # noqa: E402
from incubator_mxnet_tpu.gluon import nn  # noqa: E402
from incubator_mxnet_tpu.models import generation as G  # noqa: E402
from incubator_mxnet_tpu.models.transformer import TransformerLM  # noqa: E402
from incubator_mxnet_tpu.ndarray.ndarray import NDArray  # noqa: E402
from incubator_mxnet_tpu.parallel import create_mesh  # noqa: E402
from tools import hlolint  # noqa: E402

CONTRACTS_PATH = os.path.join(_ROOT, ".hlolint_contracts.json")

# decode smoke model (small: the contract is about program structure,
# not quality)
V, C, DFF, L, H, MAXLEN = 31, 16, 32, 1, 2, 16
B, P, N = 1, 4, 6


class MLPWithLoss(gluon.nn.HybridBlock):
    def __init__(self):
        super().__init__()
        self.d1 = nn.Dense(64, activation="relu", in_units=32)
        self.d2 = nn.Dense(64, activation="relu", in_units=64)
        self.d3 = nn.Dense(8, in_units=64)
        self.loss = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(self, x, y):
        return self.loss(self.d3(self.d2(self.d1(x))), y).mean()


def _train_program(zero, checkpoint_dir=None):
    """One 2-step train; telemetry.perf captures the step program's HLO
    under its perf name.  With ``checkpoint_dir``, a synchronous
    checkpoint save afterwards additionally captures the
    ``checkpoint_snapshot`` on-device copy program.  Returns
    (n_buckets, grad_bytes)."""
    np.random.seed(0)
    mx.random.seed(0)
    mesh = create_mesh(data=len(jax.devices()))
    net = MLPWithLoss()
    net.initialize(force_reinit=True)
    net.hybridize()
    kw = dict(zero_stage=1, zero_overlap=True, zero_bucket_mb=0.01) \
        if zero else dict(zero_stage=0)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2}, mesh=mesh, **kw)
    with mesh:
        for s in range(2):
            rs = np.random.RandomState(s)
            x = rs.randn(16, 32).astype(np.float32)
            y = rs.randint(0, 8, (16,)).astype(np.int32)
            with autograd.record():
                loss = net(mx.nd.array(x), mx.nd.array(y))
            loss.backward()
            trainer.step(16)
    if checkpoint_dir is not None:
        from incubator_mxnet_tpu.utils.checkpoint import CheckpointManager

        with CheckpointManager(checkpoint_dir, async_save=False) as mgr:
            mgr.save(2, net=net, trainer=trainer)
    bks = (trainer._fullstep_ctx or {}).get("zero_buckets")
    grad_bytes = sum(
        int(np.prod(p.data().shape)) * 4
        for p in net.collect_params().values() if p.grad_req != "null")
    return (len(bks) if bks else None), grad_bytes


def _decode_programs():
    """Compile decode_float and decode_int8; returns the quantized
    weight shapes."""
    mx.random.seed(0)
    net = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=MAXLEN, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))
    net.cast("bfloat16")
    prompt = np.zeros((B, P), dtype="int32")
    net.generate(prompt, N)                   # decode_float
    net.quantize_for_decode(act_quant="none")
    net.generate(prompt, N)                   # decode_int8
    qc = net._decode_quant
    return sorted(tuple(qc.packed(d)["w8"].shape)
                  for d in qc._targets.values())


def _serving_programs():
    """Compile the continuous-batching engine's program families
    (float / int8-KV / forced-pallas / int8-weight, x prefill/step) by
    running one request through each engine flavour on a fresh tiny
    net.  Returns the decode-step attention-probs shape
    ``(max_batch, H, max_seq_len)`` — the fp32 buffer the paged kernel
    must NOT materialize."""
    from incubator_mxnet_tpu.serving import ServingEngine

    mx.random.seed(0)
    net = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=MAXLEN, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))
    net.cast("bfloat16")
    prompt = np.zeros((P,), dtype="int32")
    # prefill_chunk=5: the weight census counts f32/bf16 buffers SHAPED
    # like an s8 weight, and a chunk width of 16/32/48 would make the
    # chunk program's (chunk, C)-family activations alias the smoke
    # model's weight shapes — 5 aliases nothing
    kws = dict(max_batch=1, block_size=4, poll_interval=0.001,
               prefill_chunk=5)
    with ServingEngine(net, **kws) as eng:
        eng.submit(prompt, N).result(timeout=60)   # serving_*_float
    with ServingEngine(net, kv_dtype="int8", **kws) as eng:
        eng.submit(prompt, N).result(timeout=60)   # serving_*_float_kv8
    with ServingEngine(net, attn_impl="pallas", **kws) as eng:
        eng.submit(prompt, N).result(timeout=60)   # serving_*_float_pallas
    mx.random.seed(99)
    draft = TransformerLM(vocab=V, units=8, hidden_size=16, num_layers=1,
                          num_heads=1, max_len=MAXLEN, dropout=0.0)
    draft.initialize()
    draft(NDArray(jnp.ones((1, 4), jnp.int32)))
    with ServingEngine(net, speculate_k=2, draft_net=draft, **kws) as eng:
        # serving_draft_prefill_chunk_float + serving_draft_step_float
        # + serving_spec_verify_float
        eng.submit(prompt, N).result(timeout=60)
    net.quantize_for_decode(act_quant="none")
    with ServingEngine(net, **kws) as eng:
        eng.submit(prompt, N).result(timeout=60)   # serving_*_int8
    return (1, H, MAXLEN)


def collect_facts():
    """Compile the sixteen programs and return (facts_by_program, ctx)."""
    telemetry.enable()
    telemetry.perf.set_hlo_text_capture(True)
    _, _ = _train_program(zero=False)
    n_buckets, grad_bytes = _train_program(
        zero=True,
        checkpoint_dir=tempfile.mkdtemp(prefix="mxtpu_hlolint_ckpt_"))
    assert n_buckets and n_buckets >= 2, \
        f"bucket cap did not split the grads: {n_buckets}"
    weight_shapes = _decode_programs()
    probs_shape = _serving_programs()

    D = len(jax.devices())
    texts = telemetry.perf.hlo_texts()
    want = ("trainer_full_step", "trainer_full_step_zero_bucketed",
            "decode_float", "decode_int8", "checkpoint_snapshot",
            "serving_prefill_chunk_float", "serving_step_float",
            "serving_prefill_chunk_float_kv8", "serving_step_float_kv8",
            "serving_prefill_chunk_float_pallas",
            "serving_step_float_pallas",
            "serving_draft_prefill_chunk_float",
            "serving_draft_step_float",
            "serving_spec_verify_float",
            "serving_prefill_chunk_int8", "serving_step_int8")
    missing = [p for p in want if p not in texts]
    assert not missing, \
        f"programs not captured (telemetry text capture broken?): " \
        f"{missing}; have {sorted(texts)}"

    facts = {}
    for name in want:
        t = texts[name]
        module = hlolint.parse_hlo(t["hlo"])
        smod = hlolint.parse_stablehlo(t["stablehlo"]) \
            if "stablehlo" in t else None
        kw = {}
        if name.startswith("trainer"):
            kw = dict(axis_order=["data"], axis_sizes={"data": D})
        if name.endswith("int8"):
            kw = dict(weight_shapes=weight_shapes)
        if name in ("serving_step_float", "serving_step_float_pallas"):
            # "weight" census repurposed as a probs census: any f32
            # buffer shaped (B, H, max_seq_len) is the dense-gather
            # score/softmax materialization the kernel path eliminates
            kw = dict(weight_shapes=[probs_shape],
                      weight_float_dtypes=("f32",))
        facts[name] = hlolint.fact_summary(module, stablehlo=smod, **kw)
    ctx = {"D": D, "n_buckets": n_buckets, "grad_bytes": grad_bytes,
           "weight_shapes": [list(w) for w in weight_shapes],
           "probs_shape": list(probs_shape)}
    return facts, ctx


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--write-contracts", action="store_true",
                    help="bootstrap/refresh the contract file from the "
                         "current programs instead of gating")
    ap.add_argument("--facts-out",
                    help="also dump the fact summaries (JSON) here")
    args = ap.parse_args(argv)

    facts, ctx = collect_facts()
    if args.facts_out:
        with open(args.facts_out, "w", encoding="utf-8") as fh:
            json.dump({"facts": facts, "ctx": ctx}, fh, indent=2,
                      sort_keys=True)

    if args.write_contracts:
        doc = hlolint.bootstrap_contracts(facts, ctx=ctx)
        with open(CONTRACTS_PATH, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"hlolint gate: wrote bootstrap contracts for "
              f"{len(doc['programs'])} program(s) to {CONTRACTS_PATH} — "
              "review and tighten before committing")
        return 0

    contracts = hlolint.load_contracts(CONTRACTS_PATH)
    violations, uncontracted = hlolint.evaluate(contracts, facts, ctx=ctx)
    for v in violations:
        print(v.render())
    for name in uncontracted:
        print(f"{name}: HLO000 ({hlolint.RULES['HLO000']}) — add a "
              "contract under 'programs' or list it under 'accepted' "
              f"in {os.path.basename(CONTRACTS_PATH)}")
    n_checks = sum(len(p.get("checks", ()))
                   for p in contracts.get("programs", {}).values())
    if violations or uncontracted:
        print(f"hlolint gate: FAIL — {len(violations)} violation(s), "
              f"{len(uncontracted)} un-contracted program(s)")
        return 1
    print(f"hlolint gate: OK ({len(facts)} programs, {n_checks} "
          f"contract checks, ctx={ctx})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
