"""CI gate: the serving engine survives overload without stalling or
recompiling (ISSUE 12 acceptance criteria).

One short open-loop Poisson run against a tiny LM, arrival rate forced
above capacity (an injected slow decode step caps throughput), then
asserts the overload contract:

1. **Zero recompiles after warmup** — a budget-0 `RetraceGuard` over
   the serving program names spans the whole loaded run: admission,
   eviction and shedding may only change argument VALUES, never
   program shapes.
2. **Sheds rather than stalls** — the bounded queue sheds at least one
   request (``serving_shed_total`` > 0) and every submit() returns
   promptly (open-loop: the generator never blocks on the engine).
3. **Admitted requests meet the TTFT budget** — p50 TTFT of admitted
   requests stays under a pinned CPU-smoke bound.
4. **Graceful drain + close** — all admitted work completes, the
   scheduler thread joins, blocks all return to the pool.
5. **Metrics present** — the serving counters/histograms documented in
   docs/observability.md actually populated.
6. **Ops plane live** (ISSUE 13) — /metrics scraped over HTTP DURING
   the overloaded run returns scrape-conformant Prometheus text with
   the right content type, including ``serving_slo_fraction``;
   /healthz answers; after the run /requestz shows a complete span
   timeline for at least one shed AND one evicted request; every
   terminal request has a complete trace; close() joins the HTTP
   acceptor thread along with the scheduler.
7. **Lock discipline, observed** (ISSUE 16) — the runtime lock witness
   records every held-while-acquiring edge across both overloaded runs
   (scheduler threads, HTTP acceptor, signal-era telemetry locks) and
   asserts the observed graph is acyclic AND a subset of tpulint's
   static lock-order graph, exporting ``lock_witness_edges_total`` /
   ``lock_contention_seconds`` gauges.
8. **int8-KV engine holds the same line** (ISSUE 15) — a second
   overloaded run against a ``kv_dtype="int8"`` engine: greedy tokens
   match the float-KV engine >= 95%, zero recompiles after warmup
   under its own budget-0 guard (``serving_step_kv8`` /
   ``serving_prefill_chunk_kv8``), and every block returns to the pool.
9. **Stall attribution explains the slow steps** (ISSUE 17) — the
   fault hook injects one 10x slow decode step every
   ``HICCUP_EVERY``; ``/profilez`` and ``/stallz`` are hit DURING the
   overloaded run (valid chrome-trace JSON with request + scheduler +
   program lanes under the conformance validator the tests use); after
   drain, every recent step's cause ledger sums to its wall time
   within 5% (zero invariant violations), at least one injected step
   was flagged as a hiccup with ``device_step`` dominating its ledger,
   the witness gauges appeared in the MID-RUN /metrics scrape, and an
   enabled-vs-disabled A/B pins the profiler's tpot p50 overhead <3%.

10. **Speculative decoding holds the same line** (ISSUE 19) — a third
    overloaded run against a ``speculate_k=3`` engine self-drafting
    with the target's int8 twin: greedy tokens BIT-IDENTICAL to the
    float engine, zero recompiles after warmup under a budget-0 guard
    spanning the whole speculative family (``serving_draft_step`` /
    ``serving_spec_verify`` / ``serving_draft_prefill_chunk`` plus the
    base names), acceptance rate > 0, every KV block (target AND draft
    pools share one allocation) returns on drain, and /requestz +
    /stallz answer DURING the loaded run.

11. **Prefix cache + chunked prefill hold the line** (ISSUE 20) — a
    shared-prefix overload run against a ``prefill_chunk=8`` engine
    with a per-chunk injected sleep: a cache-hit re-arrival's TTFT
    beats the cold TTFT (only the uncached tail chunks run), its
    greedy tokens are BIT-IDENTICAL to the cold request's, zero
    recompiles after warmup (ONE chunk program — no pow2 bucket
    ladder to compile), and every block AND refcount is drained at
    close even though shared blocks were bound by multiple requests.

Budget: well under 45 s on the CPU smoke host.
Run via ci/lint.sh; standalone:  JAX_PLATFORMS=cpu python ci/serving_smoke.py
"""
import json
import os
import sys
import time
import urllib.request

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("MXTPU_TELEMETRY_DUMP", None)

# Lock witness (always on for the smoke): installed BEFORE the package
# import so module-level locks (telemetry registries, flight recorder)
# are created through the patched factories.  Loaded by file path and
# pre-registered in sys.modules — a normal import would run the package
# __init__ first, creating those locks un-witnessed.
import importlib.util  # noqa: E402

_spec = importlib.util.spec_from_file_location(
    "incubator_mxnet_tpu.lock_witness",
    os.path.join(_ROOT, "incubator_mxnet_tpu", "lock_witness.py"))
lock_witness = importlib.util.module_from_spec(_spec)
sys.modules["incubator_mxnet_tpu.lock_witness"] = lock_witness
_spec.loader.exec_module(lock_witness)
lock_witness.install(force=True)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import telemetry  # noqa: E402
from incubator_mxnet_tpu.models.transformer import TransformerLM  # noqa: E402
from incubator_mxnet_tpu.ndarray.ndarray import NDArray  # noqa: E402
from incubator_mxnet_tpu.retrace_guard import RetraceGuard  # noqa: E402
from incubator_mxnet_tpu.serving import ServingEngine  # noqa: E402

# pinned smoke bounds (generous for a shared CPU host; the contract is
# "bounded", not "fast")
TTFT_P50_BUDGET_S = 2.0
N_REQUESTS = 24
ARRIVAL_RATE_HZ = 60.0        # >> capacity with the slow step below
SLOW_STEP_S = 0.02
HICCUP_EVERY = 25             # every Nth decode step is 10x slower --
HICCUP_STEP_S = 0.2           # guaranteed hiccups for the stall ledger
PROFILER_OVERHEAD_FRAC = 0.03  # enabled-vs-disabled tpot p50 gate
MAX_QUEUE = 3
SEED = 0
TERMINAL_EVENTS = ("done", "shed", "evicted", "cancelled", "failed")


def _fetch(base: str, path: str):
    """(status, content_type, body) for one GET against the ops plane."""
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), \
            resp.read().decode("utf-8")


def _check_prom_conformance(body: str) -> None:
    """Scrape conformance: prefer the real parser when the host has
    prometheus_client; always check the histogram grammar by hand
    (cumulative le buckets ending at +Inf, _sum/_count present)."""
    try:
        from prometheus_client.parser import text_string_to_metric_families
        assert list(text_string_to_metric_families(body))
    except ImportError:
        pass
    hists = {}
    for line in body.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        if "_bucket{" in line and 'le="' in line:
            series = line.split('le="', 1)[0]   # name + labels before le
            le = line.split('le="', 1)[1].split('"', 1)[0]
            cum = float(line.rsplit(" ", 1)[1])
            hists.setdefault(series, []).append((le, cum))
    assert hists, "no histograms in the scrape"
    for series, buckets in hists.items():
        name = series.split("_bucket", 1)[0]
        assert buckets[-1][0] == "+Inf", f"{series}: no +Inf bucket"
        counts = [c for _, c in buckets]
        assert counts == sorted(counts), f"{series}: buckets not cumulative"
        assert f"{name}_sum" in body and f"{name}_count" in body, series


def main() -> int:
    t_start = time.perf_counter()
    mx.random.seed(SEED)
    telemetry.enable()
    net = TransformerLM(vocab=61, units=16, hidden_size=32, num_layers=1,
                        num_heads=2, max_len=64, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))

    telemetry.requestlog.clear()
    eng = ServingEngine(net, max_batch=2, block_size=8, max_queue=MAX_QUEUE,
                        poll_interval=0.001, http_port=0)
    assert eng.http_port, "ops endpoint did not come up on port 0"
    base = f"http://127.0.0.1:{eng.http_port}"

    # -- warmup: compile the step + prefill-chunk programs ------------- #
    # (ONE chunk program serves every prompt length — ISSUE 20; the two
    # lengths double as a chunk-boundary probe)
    for p in ((3, 7, 11), (2, 9, 4, 1, 5, 8, 6, 3, 2)):
        eng.submit(np.array(p, np.int32), 4).result(timeout=60)
    assert eng.drain(timeout=30)

    # -- loaded run: Poisson arrivals above capacity, zero-compile ----- #
    # every decode step sleeps SLOW_STEP_S (caps throughput -> forced
    # overload); every HICCUP_EVERY-th sleeps 10x that, so the stall
    # ledger must flag hiccups with device_step dominating (ISSUE 17)
    n_steps_hooked = {"n": 0}

    def loaded_hook(ph):
        if ph != "step":
            return
        n_steps_hooked["n"] += 1
        time.sleep(HICCUP_STEP_S
                   if n_steps_hooked["n"] % HICCUP_EVERY == 0
                   else SLOW_STEP_S)

    eng.set_fault_hook(loaded_hook)
    rng = np.random.RandomState(SEED)
    gaps = rng.exponential(1.0 / ARRIVAL_RATE_HZ, size=N_REQUESTS)
    prompts = [rng.randint(0, 61, size=rng.choice([3, 5, 9]))
               .astype(np.int32) for _ in range(N_REQUESTS)]
    reqs = []
    with RetraceGuard(budget=0,
                      watch={"serving_step",
                             "serving_prefill_chunk"}) as guard:
        # one request whose deadline expires mid-decode: admitted first
        # (empty queue), then evicted — /requestz must explain it
        doomed = eng.submit(prompts[0], 48, deadline=0.5)
        reqs.append(doomed)
        for gap, prompt in zip(gaps, prompts):
            time.sleep(gap)
            reqs.append(eng.submit(prompt, 6))    # open loop: never blocks
        # scrape the ops plane WHILE the engine is loaded
        code, ctype, metrics_body = _fetch(base, "/metrics")
        assert code == 200 and ctype.startswith("text/plain; version=0.0.4"), \
            (code, ctype)
        hcode, _, hbody = _fetch(base, "/healthz")
        assert hcode == 200, (hcode, hbody)   # degraded is still 200
        assert json.loads(hbody)["status"] in ("healthy", "degraded")
        # profiler plane, also DURING the overload: /stallz parses and
        # shows this engine; /profilez captures 0.3s of loaded serving
        # into a merged trace the shared validator accepts
        scode, _, sbody = _fetch(base, "/stallz")
        assert scode == 200 and eng._name in json.loads(sbody)["engines"]
        pcode, pctype, pbody = _fetch(base, "/profilez?seconds=0.3")
        assert pcode == 200 and pctype.startswith("application/json")
        assert eng.drain(timeout=60), "engine failed to drain under load"
        guard.check()     # zero serving-program compiles after warmup
    assert "serving_slo_fraction" in metrics_body, "SLO gauge not scraped"
    assert "serving_slo_burn_rate" in metrics_body
    # the witness gauges must be scrapeable MID-RUN (the engine rides a
    # periodic snapshot every 8 decode steps), not only after the
    # end-of-run assert_clean below
    assert "lock_witness_edges_total" in metrics_body, \
        "lock witness gauges absent from the mid-load scrape"
    assert "lock_contention_seconds" in metrics_body
    _check_prom_conformance(metrics_body)

    # -- stall attribution contract (ISSUE 17) -------------------------- #
    from incubator_mxnet_tpu.telemetry.profiler import validate_chrome_trace
    problems = validate_chrome_trace(pbody)
    assert problems == [], f"/profilez trace fails conformance: {problems[:5]}"
    lanes = {e.get("cat") for e in json.loads(pbody)["traceEvents"]
             if e.get("ph") != "M"}
    for lane in ("request", "scheduler", "program"):
        assert lane in lanes, f"/profilez missing {lane} lane: {lanes}"
    prof = eng.profiler
    assert prof.invariant_violations == 0, \
        f"{prof.invariant_violations} step ledger(s) broke sum-to-wall"
    recent = prof.recent_steps()
    assert recent, "no step ledgers recorded under load"
    for rec in recent:
        total = sum(rec["causes"].values())
        assert abs(total - rec["wall_s"]) <= 0.05 * rec["wall_s"] + 1e-6, \
            f"step {rec['step']}: causes sum {total} != wall {rec['wall_s']}"
    assert n_steps_hooked["n"] >= HICCUP_EVERY, \
        f"run too short to inject a hiccup: {n_steps_hooked['n']} steps"
    assert prof.hiccups_total >= 1, \
        f"no hiccup flagged over {prof.steps} steps ({n_steps_hooked})"
    hics = prof.recent_stalls()
    assert any(h["dominant"] == "device_step" for h in hics), \
        f"injected stalls not attributed to device_step: {hics}"
    for h in hics:
        assert abs(sum(h["causes"].values()) - h["wall_s"]) \
            <= 0.05 * h["wall_s"] + 1e-6, h

    # -- overload contract --------------------------------------------- #
    stats = eng.stats()
    shed = sum(stats["shed"].values())
    evicted = sum(stats["evicted"].values())
    done = [r for r in reqs if r.status == "done"]
    assert shed >= 1, f"no sheds at {ARRIVAL_RATE_HZ} Hz offered: {stats}"
    assert done, f"nothing admitted: {stats}"
    assert doomed.status == "evicted", \
        f"deadline request not evicted: {doomed.status}"
    assert len(done) + shed + evicted == len(reqs), stats
    assert stats["blocks_free"] == stats["blocks_total"], stats
    ttfts = sorted(r.t_first - r.t_submit for r in done)
    p50 = ttfts[len(ttfts) // 2]
    assert p50 < TTFT_P50_BUDGET_S, \
        f"TTFT p50 {p50:.3f}s over the {TTFT_P50_BUDGET_S}s budget"

    # -- metrics present ----------------------------------------------- #
    reg = telemetry.get_registry()
    for name, labels in (("serving_admitted_total", None),
                         ("serving_queue_depth", None),
                         ("serving_batch_occupancy", None),
                         ("serving_kv_blocks_in_use", None),
                         ("serving_ttft_seconds", {"path": "float"}),
                         ("serving_tpot_seconds", {"path": "float"}),
                         ("serving_step_stall_seconds",
                          {"cause": "device_step"}),
                         ("serving_step_stall_seconds",
                          {"cause": "host_other"})):
        assert reg.get(name, labels) is not None, f"metric missing: {name}"
    assert reg.get("serving_shed_total",
                   {"reason": "queue_full"}).value >= 1
    assert reg.get("serving_step_hiccups_total",
                   {"engine": eng._name}).value >= 1

    # -- request traces: every terminal request is fully explained ----- #
    for r in reqs:
        evs = [e["name"] for e in r.trace.snapshot()]
        assert evs[0] == "submit" and evs[-1] in TERMINAL_EVENTS, \
            f"incomplete trace for rid={r.rid}: {evs}"
    rcode, _, rbody = _fetch(base, "/requestz")
    assert rcode == 200
    requestz = json.loads(rbody)
    by_status = {}
    for t in requestz["recent"]:
        by_status.setdefault(t["status"], []).append(t)
    for status in ("shed", "evicted"):
        assert by_status.get(status), \
            f"/requestz shows no {status} trace: {sorted(by_status)}"
        names = [e["name"] for e in by_status[status][0]["events"]]
        assert names[0] == "submit" and names[-1] == status, names
    # the evicted one was admitted first — its timeline proves it ran
    ev_names = [e["name"] for e in by_status["evicted"][0]["events"]]
    assert "admitted" in ev_names and "prefill" in ev_names, ev_names

    # -- profiler overhead A/B: enabled tpot p50 within 3% of disabled - #
    # constant (hiccup-free) step cost so the two runs are comparable
    eng.set_fault_hook(lambda ph: time.sleep(SLOW_STEP_S)
                       if ph == "step" else None)

    def _tpot_p50() -> float:
        rs = []
        for _ in range(6):        # closed loop: never overflows the queue
            r = eng.submit(np.array((3, 7, 11), np.int32), 8)
            r.result(timeout=60)
            rs.append(r)
        assert eng.drain(timeout=30)
        tps = sorted(r.tpot for r in rs if r.tpot is not None)
        assert tps, "A/B run produced no tpot samples"
        return tps[len(tps) // 2]

    prof.set_enabled(False)
    off_p50 = _tpot_p50()
    prof.set_enabled(True)
    on_p50 = _tpot_p50()
    # 2 ms absolute slack absorbs shared-CI scheduling jitter on a
    # ~20 ms step; the relative term is the actual contract
    assert on_p50 < off_p50 * (1 + PROFILER_OVERHEAD_FRAC) + 2e-3, \
        f"profiler overhead: tpot p50 {on_p50:.4f}s on vs {off_p50:.4f}s off"

    # -- int8-KV engine: greedy parity + same overload contract -------- #
    eng.set_fault_hook(None)
    eval_prompts = [np.array((3, 7, 11), np.int32),
                    np.array((2, 9, 4, 1, 5, 8, 6, 3, 2), np.int32)]
    ref_toks = [eng.submit(p, 8).result(timeout=60) for p in eval_prompts]
    assert eng.drain(timeout=30)

    q8 = ServingEngine(net, max_batch=2, block_size=8, max_queue=MAX_QUEUE,
                       kv_dtype="int8", poll_interval=0.001)
    assert q8.kv_dtype == "int8"
    assert q8.kv_bytes_per_token < eng.kv_bytes_per_token, \
        (q8.kv_bytes_per_token, eng.kv_bytes_per_token)
    # warmup doubles as the parity probe: both prompt buckets compile
    q8_toks = [q8.submit(p, 8).result(timeout=60) for p in eval_prompts]
    assert q8.drain(timeout=30)
    par_tot = sum(len(t) for t in ref_toks)
    par_hit = sum(a == b for ta, tb in zip(ref_toks, q8_toks)
                  for a, b in zip(ta, tb))
    assert par_hit / par_tot >= 0.95, \
        f"int8-KV greedy parity {par_hit}/{par_tot} vs float engine"

    q8.set_fault_hook(lambda ph: time.sleep(SLOW_STEP_S)
                      if ph == "step" else None)
    q8_reqs = []
    with RetraceGuard(budget=0,
                      watch={"serving_step_kv8",
                             "serving_prefill_chunk_kv8"}) as q8_guard:
        for gap, prompt in zip(gaps, prompts):
            time.sleep(gap)
            q8_reqs.append(q8.submit(prompt, 6))
        assert q8.drain(timeout=60), \
            "int8-KV engine failed to drain under load"
        q8_guard.check()   # zero kv8-program compiles after warmup
    q8_stats = q8.stats()
    q8_done = [r for r in q8_reqs if r.status == "done"]
    assert q8_done, f"int8-KV run admitted nothing: {q8_stats}"
    assert q8_stats["blocks_free"] == q8_stats["blocks_total"], q8_stats
    q8.close()

    # -- speculative engine: amortized weight stream, same line -------- #
    # the int8 twin from quantize_for_decode IS the draft (draft_net
    # omitted); the target stays float, so greedy output must be
    # bit-identical to the float engine — speculation is a throughput
    # lever, never an output change
    net.quantize_for_decode(act_quant="none")
    sp = ServingEngine(net, max_batch=2, block_size=8, max_queue=MAX_QUEUE,
                       poll_interval=0.001, speculate_k=3, quantized=False,
                       http_port=0)
    assert sp.http_port, "speculative engine ops endpoint did not come up"
    sp_base = f"http://127.0.0.1:{sp.http_port}"
    # warmup doubles as the parity probe: both prompt buckets compile
    sp_toks = [sp.submit(p, 8).result(timeout=60) for p in eval_prompts]
    assert sp.drain(timeout=30)
    assert sp_toks == ref_toks, \
        f"speculative greedy not bit-identical:\n{sp_toks}\n{ref_toks}"
    # slow the VERIFY step only: the one amortized target weight stream
    sp.set_fault_hook(lambda ph: time.sleep(SLOW_STEP_S)
                      if ph == "step" else None)
    sp_reqs = []
    with RetraceGuard(budget=0,
                      watch={"serving_step", "serving_prefill_chunk",
                             "serving_draft_step",
                             "serving_draft_prefill_chunk",
                             "serving_spec_verify"}) as sp_guard:
        for gap, prompt in zip(gaps, prompts):
            time.sleep(gap)
            sp_reqs.append(sp.submit(prompt, 6))
        # ops plane DURING the speculative overload
        scode, _, sbody = _fetch(sp_base, "/stallz")
        assert scode == 200 and sp._name in json.loads(sbody)["engines"]
        rcode, _, _ = _fetch(sp_base, "/requestz")
        assert rcode == 200
        assert sp.drain(timeout=60), \
            "speculative engine failed to drain under load"
        sp_guard.check()   # zero speculative-family compiles after warmup
    sp_stats = sp.stats()
    sp_spec = sp_stats["speculate"]
    assert sp_spec["accepted"] > 0 and sp_spec["accept_rate"] > 0.0, sp_spec
    assert sp_stats["blocks_free"] == sp_stats["blocks_total"], sp_stats
    sp_done = [r for r in sp_reqs if r.status == "done"]
    assert sp_done, f"speculative run admitted nothing: {sp_stats}"
    sp.close()

    # -- prefix cache + chunked prefill (ISSUE 20) --------------------- #
    # Fresh engine, small chunk, and an injected sleep per prefill
    # CHUNK — so prefill cost is proportional to the UNCACHED tail and
    # a cache hit must beat the cold TTFT by construction, not luck.
    pc = ServingEngine(net, max_batch=2, block_size=8,
                       max_queue=MAX_QUEUE, quantized=False,
                       prefill_chunk=8, poll_interval=0.001)
    rng_pc = np.random.RandomState(7)
    warm_prompt = rng_pc.randint(0, 61, size=48).astype(np.int32)
    prefix = rng_pc.randint(0, 61, size=40).astype(np.int32)
    tails = [rng_pc.randint(0, 61, size=8).astype(np.int32)
             for _ in range(8)]
    shared = [np.concatenate([prefix, t]) for t in tails]
    # warmup compiles the chunk + step programs on an UNRELATED prefix
    # (it must not pre-populate the cache for the cold measurement)
    pc.submit(warm_prompt, 4).result(timeout=60)
    assert pc.drain(timeout=30)
    pc.set_fault_hook(lambda ph: time.sleep(0.03)
                      if ph == "prefill" else None)
    with RetraceGuard(budget=0,
                      watch={"serving_step",
                             "serving_prefill_chunk"}) as pc_guard:
        cold = pc.submit(shared[0], 8)           # 6 chunks, cache miss
        cold_toks = cold.result(timeout=60)
        hit = pc.submit(shared[0], 8)            # 40/48 tokens cached
        hit_toks = hit.result(timeout=60)
        # overload burst: every arrival shares the now-resident prefix
        pc_reqs = [pc.submit(p, 6) for p in shared[1:]]
        assert pc.drain(timeout=60), \
            "prefix-cache engine failed to drain under load"
        pc_guard.check()   # zero compiles: one chunk program, no ladder
    assert hit_toks == cold_toks, \
        f"cache-hit greedy not bit-identical:\n{hit_toks}\n{cold_toks}"
    assert hit.ttft < cold.ttft * 0.7, \
        f"cache hit did not beat cold TTFT: {hit.ttft:.3f}s vs " \
        f"{cold.ttft:.3f}s"
    pc_stats = pc.stats()
    pcache = pc_stats["prefix_cache"]
    assert pcache["hits"] >= 2 and pcache["cached_tokens"] >= 80, pcache
    assert pc_stats["blocks_free"] == pc_stats["blocks_total"], pc_stats
    assert pc._pool.num_allocated == 0, "refcounts not drained"
    assert reg.get("serving_prefix_cache_hits_total").value >= 2
    assert reg.get("serving_prefix_cache_misses_total").value >= 1
    pc_done = [r for r in pc_reqs if r.status == "done"]
    assert pc_done, f"shared-prefix burst admitted nothing: {pc_stats}"
    pc.close()
    assert pc._pool.num_allocated == 0, "refcounts leaked across close"

    # -- graceful shutdown --------------------------------------------- #
    thread = eng._thread
    http_thread = eng.http._thread
    eng.close()
    assert not thread.is_alive(), "scheduler thread not joined"
    assert not http_thread.is_alive(), "HTTP acceptor thread not joined"
    assert eng.http.closed

    # -- lock witness: observed order acyclic and within the static map  #
    lock_witness.snapshot()
    assert reg.get("lock_witness_edges_total") is not None, \
        "witness gauges not exported"
    wstats = lock_witness.assert_clean()
    assert wstats["tracked_locks"] > 0, "witness tracked no package locks"

    telemetry.disable()
    dt = time.perf_counter() - t_start
    print(f"serving smoke: OK — {len(done)}/{len(reqs)} served, "
          f"{shed} shed, {evicted} evicted, TTFT p50 {p50 * 1e3:.1f} ms, "
          f"{stats['steps']} steps, 0 recompiles after warmup, "
          f"/metrics+/healthz+/requestz scraped live, int8-KV parity "
          f"{par_hit}/{par_tot} at {q8.kv_bytes_per_token} B/token "
          f"(float {eng.kv_bytes_per_token}), {len(q8_done)}/{len(q8_reqs)} "
          f"served kv8, spec k={sp_spec['k']} accept "
          f"{sp_spec['accept_rate']:.2f} ({len(sp_done)}/{len(sp_reqs)} "
          f"served, 0 recompiles), lock witness {wstats['edges']} edge(s) over "
          f"{wstats['tracked_locks']} locks acyclic+static-covered, "
          f"{prof.hiccups_total} hiccup(s) attributed "
          f"(tpot p50 {on_p50 * 1e3:.1f} ms on / {off_p50 * 1e3:.1f} ms "
          f"off profiler), /profilez+/stallz live, "
          f"{dt:.1f}s total on {jax.devices()[0].platform}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
