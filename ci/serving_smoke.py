"""CI gate: the serving engine survives overload without stalling or
recompiling (ISSUE 12 acceptance criteria).

One short open-loop Poisson run against a tiny LM, arrival rate forced
above capacity (an injected slow decode step caps throughput), then
asserts the overload contract:

1. **Zero recompiles after warmup** — a budget-0 `RetraceGuard` over
   the serving program names spans the whole loaded run: admission,
   eviction and shedding may only change argument VALUES, never
   program shapes.
2. **Sheds rather than stalls** — the bounded queue sheds at least one
   request (``serving_shed_total`` > 0) and every submit() returns
   promptly (open-loop: the generator never blocks on the engine).
3. **Admitted requests meet the TTFT budget** — p50 TTFT of admitted
   requests stays under a pinned CPU-smoke bound.
4. **Graceful drain + close** — all admitted work completes, the
   scheduler thread joins, blocks all return to the pool.
5. **Metrics present** — the serving counters/histograms documented in
   docs/observability.md actually populated.

Budget: well under 30 s on the CPU smoke host.
Run via ci/lint.sh; standalone:  JAX_PLATFORMS=cpu python ci/serving_smoke.py
"""
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("MXTPU_TELEMETRY_DUMP", None)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import telemetry  # noqa: E402
from incubator_mxnet_tpu.models.transformer import TransformerLM  # noqa: E402
from incubator_mxnet_tpu.ndarray.ndarray import NDArray  # noqa: E402
from incubator_mxnet_tpu.retrace_guard import RetraceGuard  # noqa: E402
from incubator_mxnet_tpu.serving import ServingEngine  # noqa: E402

# pinned smoke bounds (generous for a shared CPU host; the contract is
# "bounded", not "fast")
TTFT_P50_BUDGET_S = 2.0
N_REQUESTS = 24
ARRIVAL_RATE_HZ = 60.0        # >> capacity with the slow step below
SLOW_STEP_S = 0.02
MAX_QUEUE = 3
SEED = 0


def main() -> int:
    t_start = time.perf_counter()
    mx.random.seed(SEED)
    telemetry.enable()
    net = TransformerLM(vocab=61, units=16, hidden_size=32, num_layers=1,
                        num_heads=2, max_len=64, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))

    eng = ServingEngine(net, max_batch=2, block_size=8, max_queue=MAX_QUEUE,
                        poll_interval=0.001)

    # -- warmup: compile the step program and both prompt buckets ------ #
    for p in ((3, 7, 11), (2, 9, 4, 1, 5, 8, 6, 3, 2)):   # buckets 8, 16
        eng.submit(np.array(p, np.int32), 4).result(timeout=60)
    assert eng.drain(timeout=30)

    # -- loaded run: Poisson arrivals above capacity, zero-compile ----- #
    eng.set_fault_hook(lambda ph: time.sleep(SLOW_STEP_S)
                       if ph == "step" else None)
    rng = np.random.RandomState(SEED)
    gaps = rng.exponential(1.0 / ARRIVAL_RATE_HZ, size=N_REQUESTS)
    prompts = [rng.randint(0, 61, size=rng.choice([3, 5, 9]))
               .astype(np.int32) for _ in range(N_REQUESTS)]
    reqs = []
    with RetraceGuard(budget=0,
                      watch={"serving_step", "serving_prefill"}) as guard:
        for gap, prompt in zip(gaps, prompts):
            time.sleep(gap)
            reqs.append(eng.submit(prompt, 6))    # open loop: never blocks
        assert eng.drain(timeout=60), "engine failed to drain under load"
        guard.check()     # zero serving-program compiles after warmup

    # -- overload contract --------------------------------------------- #
    stats = eng.stats()
    shed = sum(stats["shed"].values())
    done = [r for r in reqs if r.status == "done"]
    assert shed >= 1, f"no sheds at {ARRIVAL_RATE_HZ} Hz offered: {stats}"
    assert done, f"nothing admitted: {stats}"
    assert len(done) + shed == len(reqs), stats
    assert stats["blocks_free"] == stats["blocks_total"], stats
    ttfts = sorted(r.t_first - r.t_submit for r in done)
    p50 = ttfts[len(ttfts) // 2]
    assert p50 < TTFT_P50_BUDGET_S, \
        f"TTFT p50 {p50:.3f}s over the {TTFT_P50_BUDGET_S}s budget"

    # -- metrics present ----------------------------------------------- #
    reg = telemetry.get_registry()
    for name, labels in (("serving_admitted_total", None),
                         ("serving_queue_depth", None),
                         ("serving_batch_occupancy", None),
                         ("serving_kv_blocks_in_use", None),
                         ("serving_ttft_seconds", {"path": "float"}),
                         ("serving_tpot_seconds", {"path": "float"})):
        assert reg.get(name, labels) is not None, f"metric missing: {name}"
    assert reg.get("serving_shed_total",
                   {"reason": "queue_full"}).value >= 1

    # -- graceful shutdown --------------------------------------------- #
    thread = eng._thread
    eng.close()
    assert not thread.is_alive(), "scheduler thread not joined"

    telemetry.disable()
    dt = time.perf_counter() - t_start
    print(f"serving smoke: OK — {len(done)}/{len(reqs)} served, "
          f"{shed} shed, TTFT p50 {p50 * 1e3:.1f} ms, "
          f"{stats['steps']} steps, 0 recompiles after warmup, "
          f"{dt:.1f}s total on {jax.devices()[0].platform}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
