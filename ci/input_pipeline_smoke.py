"""CI smoke for the async device-feed input pipeline (ISSUE 3).

Gates, in the spirit of ci/telemetry_smoke.py:

1. sync-vs-prefetched equivalence — `DataLoader(prefetch_to_device=)`
   and `PrefetchingIter(prefetch_to_device=True)` batches are
   byte-identical to their synchronous counterparts;
2. sharded staging — under a mesh, prefetched batches arrive with the
   batch dim NamedSharded on the data axis;
3. a short prefetched train loop runs end-to-end through
   `Trainer.step`;
4. the pipeline metrics — `data_wait_seconds`, `prefetch_queue_depth`,
   `h2d_bytes_total` — appear in the Prometheus export.

Run as `python ci/input_pipeline_smoke.py` (ci/lint.sh invokes it).
"""
import os
import sys
import tempfile

# runnable as `python ci/input_pipeline_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# env before the package import: telemetry reads it at import time, and
# the virtual devices must exist before jax initializes
_DIR = tempfile.mkdtemp(prefix="mxtpu_input_smoke_")
os.environ["MXTPU_TELEMETRY_DUMP"] = "1"
os.environ["MXTPU_TELEMETRY_DIR"] = _DIR
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as onp  # noqa: E402
from jax.sharding import NamedSharding  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import autograd, telemetry  # noqa: E402
from incubator_mxnet_tpu.gluon import Trainer, nn  # noqa: E402
from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader  # noqa: E402
from incubator_mxnet_tpu.parallel import create_mesh, use_mesh  # noqa: E402


def _bytes(batch):
    return [a.asnumpy().tobytes() for a in batch]


def main() -> int:
    assert telemetry.enabled(), "MXTPU_TELEMETRY_DUMP=1 did not enable"

    X = onp.random.RandomState(0).randn(24, 6).astype("float32")
    Y = onp.arange(24, dtype="float32")
    ds = ArrayDataset(X, Y)

    # -- 1a. DataLoader: sync vs device-prefetched, byte-identical ------ #
    sync = [_bytes(b) for b in DataLoader(ds, batch_size=4)]
    pref = [_bytes(b) for b in
            DataLoader(ds, batch_size=4, num_workers=2,
                       prefetch_to_device=2, mesh=False)]
    if sync != pref:
        print("FAIL: prefetched DataLoader batches differ from sync")
        return 1

    # -- 1b. PrefetchingIter: sync vs device-prefetched ----------------- #
    plain = [(b.data[0].asnumpy().tobytes(), b.label[0].asnumpy().tobytes())
             for b in mx.io.NDArrayIter(X, Y, batch_size=4)]
    pit = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, Y, batch_size=4),
                                prefetch_to_device=True)
    moved = [(b.data[0].asnumpy().tobytes(), b.label[0].asnumpy().tobytes())
             for b in pit]
    pit.close()
    if plain != moved:
        print("FAIL: PrefetchingIter(prefetch_to_device) batches differ")
        return 1

    # -- 2. sharded staging under a mesh -------------------------------- #
    mesh = create_mesh(data=2)
    with use_mesh(mesh):
        batch = next(iter(DataLoader(ds, batch_size=4, prefetch_to_device=2)))
    sh = batch[0]._data.sharding
    if not (isinstance(sh, NamedSharding) and sh.spec and sh.spec[0] == "data"):
        print(f"FAIL: prefetched batch not data-sharded (sharding={sh})")
        return 1

    # -- 3. prefetched Trainer consumption loop ------------------------- #
    mx.random.seed(0)
    net = nn.Dense(4)
    net.initialize()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    for data, label in DataLoader(ds, batch_size=4, prefetch_to_device=True,
                                  mesh=False):
        with autograd.record():
            y = net(data)
            loss = (y * y).sum()
        loss.backward()
        trainer.step(4)
    trainer.flush()

    paths = telemetry.dump()

    # -- 4. pipeline metrics in the Prometheus export ------------------- #
    prom = open(paths["prom"]).read()
    for needle in ("data_wait_seconds_bucket{le=",
                   "data_wait_seconds_count",
                   "prefetch_queue_depth",
                   "h2d_bytes_total"):
        if needle not in prom:
            print(f"FAIL: {needle!r} missing from {paths['prom']}")
            return 1

    h2d = telemetry.counter("h2d_bytes_total").value
    if not h2d > 0:
        print("FAIL: h2d_bytes_total never incremented")
        return 1

    print(f"input pipeline smoke: OK ({int(h2d)} h2d bytes, dir {_DIR})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
