"""CI smoke: backward-overlapped bucketed gradient sync (ISSUE 5).

A 3-step train on a data=8 virtual-CPU mesh through the ZeRO explicit
tier, once with `zero_overlap=True` (bucket cap forced tiny so the
grads split into several buckets) and once with `zero_overlap=False`
(the monolithic per-param exchange).  Asserts:

  * the bucketed build engaged (>= 2 buckets, no sticky fallback),
  * parameters MATCH the monolithic path (the interleaved pack layout
    feeds the identical per-param shard update, so this is exact),
  * telemetry's `overlap_fraction{source="plan"}` gauge is > 0, and the
    compiled schedule hides every bucket behind independent compute
    (`schedule_overlap_stats` overlap_fraction > 0).

Run as `JAX_PLATFORMS=cpu python ci/overlap_smoke.py` (ci/lint.sh
invokes it).
"""
import os
import sys
import tempfile

# runnable as `python ci/overlap_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# env must be set BEFORE the package import: the virtual device count is
# read at backend init, telemetry config at package import
_FLAGS = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if not f.startswith("--xla_force_host_platform_device_count")]
os.environ["XLA_FLAGS"] = " ".join(
    _FLAGS + ["--xla_force_host_platform_device_count=8"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MXTPU_TELEMETRY_DUMP"] = "1"
# the atexit dump must not land in the invoking checkout
os.environ["MXTPU_TELEMETRY_DIR"] = tempfile.mkdtemp(prefix="mxtpu_ov_smoke_")

import jax  # noqa: E402
import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import autograd, gluon, telemetry  # noqa: E402
from incubator_mxnet_tpu.gluon import nn  # noqa: E402
from incubator_mxnet_tpu.parallel import create_mesh, overlap  # noqa: E402


class MLPWithLoss(gluon.nn.HybridBlock):
    def __init__(self):
        super().__init__()
        self.d1 = nn.Dense(64, activation="relu", in_units=32)
        self.d2 = nn.Dense(64, activation="relu", in_units=64)
        self.d3 = nn.Dense(8, in_units=64)
        self.loss = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(self, x, y):
        return self.loss(self.d3(self.d2(self.d1(x))), y).mean()


def run(zero_overlap):
    np.random.seed(0)
    mx.random.seed(0)
    mesh = create_mesh(data=len(jax.devices()))
    net = MLPWithLoss()
    net.initialize(force_reinit=True)
    net.hybridize()
    # 0.01 MB cap: this MLP's ~20 KB of fp32 grads split into >= 2 buckets
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2}, mesh=mesh,
                            zero_stage=1, zero_overlap=zero_overlap,
                            zero_bucket_mb=0.01)
    trainer._capture_hlo = True
    losses = []
    with mesh:
        for s in range(3):
            rs = np.random.RandomState(s)
            x = rs.randn(16, 32).astype(np.float32)
            y = rs.randint(0, 8, (16,)).astype(np.int32)
            with autograd.record():
                loss = net(mx.nd.array(x), mx.nd.array(y))
            loss.backward()
            trainer.step(16)
            losses.append(float(loss.asnumpy()))
    params = {k: v.data().asnumpy() for k, v in net.collect_params().items()}
    return losses, params, trainer


def main() -> int:
    l_off, p_off, _ = run(zero_overlap=False)
    l_on, p_on, tr = run(zero_overlap=True)

    assert tr._zero_sig() == ("explicit", "data", 8), \
        f"explicit ZeRO tier did not engage: {tr._zero_sig()}"
    assert not tr._zero_overlap_broken, "bucketed build fell back"
    bks = tr._fullstep_ctx.get("zero_buckets")
    assert bks and len(bks) >= 2, f"bucket cap did not split grads: {bks}"

    # parity: same losses, same params as the monolithic exchange.
    # gluon name counters differ between the two instantiations, so
    # compare in sorted order, not by name.
    np.testing.assert_allclose(l_on, l_off, rtol=2e-4, atol=2e-5)
    for (ka, va), (kb, vb) in zip(sorted(p_off.items()), sorted(p_on.items())):
        np.testing.assert_allclose(va, vb, rtol=2e-3, atol=1e-4,
                                   err_msg=f"{ka} vs {kb}")

    # the trainer published the planned overlap fraction
    prom = telemetry.exporters.prometheus_text(telemetry.get_registry())
    frac = None
    for line in prom.splitlines():
        if line.startswith("overlap_fraction{") and 'source="plan"' in line:
            frac = float(line.rpartition(" ")[2])
    assert frac is not None and frac > 0, \
        f"overlap_fraction{{source=plan}} not published (> 0): {frac}\n" \
        + prom[:500]

    # and the compiled schedule actually interleaves the collectives
    st = overlap.schedule_overlap_stats(tr.last_step_hlo)
    assert st["n_collectives"] == len(bks), st
    assert st["overlap_fraction"] > 0, st

    print(f"overlap smoke: OK (buckets={len(bks)}, "
          f"plan_overlap_fraction={frac:.2f}, "
          f"schedule_overlap_fraction={st['overlap_fraction']:.2f}, "
          f"losses={l_on})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
