"""CI gate: the int8 decode path really streams int8 weights.

Quantizes a tiny bf16 TransformerLM for decode and asserts, on CPU:

1. greedy decode token parity >= 95% vs the bf16 program (the ISSUE 7
   quality floor, at smoke scale) for BOTH dequant strategies;
2. the compiled quantized programs take s8 weight parameters (hlolint
   dtype census over optimized HLO and lowered StableHLO);
3. the optimized HLO contains NO bf16 buffer of any quantized weight
   shape (hlolint ``float_weight_materializations``) — the whole point
   of the pass is to break the bf16 weight-streaming floor, so a
   materialized bf16[O,I] would mean the dequant was hoisted out of
   the matmul epilogue.  (XLA:CPU legalizes the mixed dot through an
   f32 weight convert and the int dot through s32 — backend artifacts
   with no TPU analogue — so the gate is on bf16, the dtype the float
   path would stream.)
4. for the dynamic-activation program, the lowered StableHLO contains
   NO float tensor of any quantized weight shape at all (hlolint
   ``stablehlo_census``) — dequant acts on the (batch, out)
   activation, never on the weight matrix.  (The mixed dot is excluded
   here by construction: jax spells it as a convert feeding the dot,
   which fuses in-register on TPU.)

All compiled-artifact checks go through the shared tools/hlolint
parser — this file holds no HLO string matching of its own.

Run via ci/lint.sh; standalone:  JAX_PLATFORMS=cpu python ci/quantized_decode_smoke.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.models import generation as G
from incubator_mxnet_tpu.models.transformer import TransformerLM
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from tools import hlolint

V, C, DFF, L, H, MAXLEN = 97, 32, 96, 2, 4, 64
B, P, N = 2, 5, 16


def _lower_quantized(net):
    """The quantized program parsed through hlolint — (StableHloModule,
    HloModule, quantized-weight shapes)."""
    qc = net._decode_quant
    fn = next(f for s, f in net._gen_programs.items()
              if s[-2] == qc.cache_key())
    params = G._gather_params(net, P + N, qc)
    prompt = jnp.zeros((B, P), jnp.int32)
    lowered = fn.lower(params, prompt, jax.random.PRNGKey(0))
    shapes = {tuple(qc.packed(d)["w8"].shape) for d in qc._targets.values()}
    assert shapes, "quant pass registered no target denses"
    smod = hlolint.parse_stablehlo(lowered.as_text())
    hmod = hlolint.parse_hlo(lowered.compile().as_text())
    return smod, hmod, shapes


def main():
    mx.random.seed(0)
    net = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=MAXLEN, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))
    net.cast("bfloat16")

    prompt = onp.array(jax.random.randint(jax.random.PRNGKey(3), (B, P), 0, V),
                       dtype="int32")
    base = onp.asarray(net.generate(prompt, N))

    for aq in ("none", "dynamic"):
        net.quantize_for_decode(act_quant=aq)
        q = onp.asarray(net.generate(prompt, N))
        parity = float((q[:, P:] == base[:, P:]).mean())
        assert parity >= 0.95, \
            f"[{aq}] greedy parity {parity:.2%} < 95% vs bf16"

        smod, hmod, w_shapes = _lower_quantized(net)
        assert smod.dtypes().get("s8", 0) > 0, \
            f"[{aq}] no int8 tensors in the lowered program: {smod.dtypes()}"
        census = hlolint.dtype_census(hmod)
        assert census["dtypes"].get("s8", {}).get("count", 0) > 0, \
            f"[{aq}] no s8 buffers in the optimized HLO: " \
            f"{sorted(census['dtypes'])}"
        mats = hlolint.float_weight_materializations(
            hmod, w_shapes, float_dtypes=("bf16",))
        assert not mats, \
            f"[{aq}] optimized HLO materializes a bf16 copy of a " \
            f"quantized weight — dequant was hoisted out of the " \
            f"epilogue: {mats}"
        if aq == "dynamic":
            sc = hlolint.facts.stablehlo_census(
                smod, weight_shapes=w_shapes,
                float_dtypes=("f32", "bf16", "f16"))
            assert not sc["float_weight_tensors"], \
                f"[{aq}] lowered program builds a float weight " \
                f"({sc['float_weight_tensors']}); dequant must stay " \
                f"on the activation side"
        print(f"quantized decode smoke [{aq}]: parity {parity:.0%}, "
              f"{len(w_shapes)} weight shapes gated")
        net.dequantize_decode()

    # LRU eviction accounting (ISSUE 8 satellite): squeezing the program
    # cache below its population must tick the eviction counter and the
    # size gauge must settle at the cap
    from incubator_mxnet_tpu import telemetry

    was_on = telemetry.enabled()
    telemetry.enable()
    try:
        evict = telemetry.counter("gen_program_cache_evictions_total")
        before = evict.value
        net._gen_program_cache_cap = 1
        net.generate(prompt, 2)
        net.generate(prompt, 3)
        assert evict.value > before, \
            "gen_program_cache_evictions_total did not advance under a " \
            f"cap-1 cache (before={before}, after={evict.value})"
        assert len(net._gen_programs) == 1, \
            f"cap-1 cache holds {len(net._gen_programs)} programs"
        size = telemetry.get_registry().get("gen_program_cache_size")
        assert size is not None and size.value == 1, \
            f"gen_program_cache_size gauge reads {size and size.value}, not 1"
        print(f"quantized decode smoke [lru]: "
              f"{int(evict.value - before)} evictions counted")
    finally:
        del net._gen_program_cache_cap
        if not was_on:
            telemetry.disable()
    print("quantized decode smoke: OK")


if __name__ == "__main__":
    main()
