#!/usr/bin/env bash
# Static gates: tpulint (JAX/TPU tracing-hazard analyzer, tools/tpulint/)
# over the whole package in --strict mode (every suppression must carry a
# reason), plus a bytecode compile of package + tools as a syntax gate.
# Exits non-zero on any finding. See docs/static_analysis.md.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "tpulint: analyzing incubator_mxnet_tpu/"
python -m tools.tpulint incubator_mxnet_tpu/ --strict

# the telemetry package carries the no-host-sync contract (its spans
# and metric updates run inside trace-reachable hot paths) — lint it
# explicitly so a path-scoped invocation can never silently skip it
echo "tpulint: analyzing incubator_mxnet_tpu/telemetry/"
python -m tools.tpulint incubator_mxnet_tpu/telemetry/ --strict

echo "compileall: incubator_mxnet_tpu/ tools/ tests/ ci/"
python -m compileall -q incubator_mxnet_tpu/ tools/ tests/ ci/

echo "telemetry smoke: 3-step train with MXTPU_TELEMETRY_DUMP=1"
JAX_PLATFORMS=cpu python ci/telemetry_smoke.py

echo "input pipeline smoke: sync-vs-prefetched equivalence + metrics"
JAX_PLATFORMS=cpu python ci/input_pipeline_smoke.py

echo "overlap smoke: bucketed-vs-monolithic ZeRO parity + overlap_fraction"
JAX_PLATFORMS=cpu python ci/overlap_smoke.py

echo "quantized decode smoke: int8 weight streaming + greedy parity"
JAX_PLATFORMS=cpu python ci/quantized_decode_smoke.py

echo "flight recorder smoke: SIGTERM mid-train ships a parseable bundle"
JAX_PLATFORMS=cpu python ci/flight_recorder_smoke.py

echo "lint gates: OK"
