#!/usr/bin/env bash
# Static gates: tpulint (JAX/TPU tracing/sharding/thread-safety analyzer,
# tools/tpulint/) project-wide in --strict mode (every suppression must
# carry a reason) against the committed findings baseline — the gate
# fails ONLY on NEW findings, so pre-existing accepted ones never block
# an unrelated change.  Refresh the baseline with
#   python -m tools.tpulint incubator_mxnet_tpu tools ci --strict --write-baseline
# Plus a bytecode compile of package + tools as a syntax gate, and
# hlolint (tools/hlolint/): compiled-program contracts over the HLO of
# the flagship programs, gated against .hlolint_contracts.json — refresh
# with   JAX_PLATFORMS=cpu python ci/hlolint_gate.py --write-contracts
# See docs/static_analysis.md.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "tpulint: analyzing incubator_mxnet_tpu/ tools/ ci/ (baseline gate)"
python -m tools.tpulint incubator_mxnet_tpu tools ci \
    --strict --baseline .tpulint_baseline.json --stats

echo "tpulint: rule modules must ship covering fixtures"
for mod in tools/tpulint/*_rules.py; do
    for code in $(grep -o 'TPU[0-9]\{3\}' "$mod" | sort -u); do
        fix="tests/fixtures/tpulint/$(echo "$code" | tr '[:upper:]' '[:lower:]')_case.py"
        if [[ ! -f "$fix" ]]; then
            echo "FAIL: $mod implements $code but $fix is missing" >&2
            exit 1
        fi
        if ! grep -q "$(basename "$fix")" tests/test_tpulint.py; then
            echo "FAIL: $fix exists but tests/test_tpulint.py never loads it" >&2
            exit 1
        fi
    done
done

echo "tpulint: lock-order graph dump (--format dot)"
lock_dot=$(python -m tools.tpulint incubator_mxnet_tpu --format dot)
grep -q '^digraph lock_order' <<<"$lock_dot"
echo "$lock_dot"

echo "compileall: incubator_mxnet_tpu/ tools/ tests/ ci/"
python -m compileall -q incubator_mxnet_tpu/ tools/ tests/ ci/

echo "hlolint: compiled-program contracts (.hlolint_contracts.json)"
JAX_PLATFORMS=cpu python ci/hlolint_gate.py

echo "telemetry smoke: 3-step train with MXTPU_TELEMETRY_DUMP=1"
JAX_PLATFORMS=cpu python ci/telemetry_smoke.py

echo "input pipeline smoke: sync-vs-prefetched equivalence + metrics"
JAX_PLATFORMS=cpu python ci/input_pipeline_smoke.py

echo "overlap smoke: bucketed-vs-monolithic ZeRO parity + overlap_fraction"
JAX_PLATFORMS=cpu python ci/overlap_smoke.py

echo "quantized decode smoke: int8 weight streaming + greedy parity"
JAX_PLATFORMS=cpu python ci/quantized_decode_smoke.py

echo "flight recorder smoke: SIGTERM mid-train ships a parseable bundle"
JAX_PLATFORMS=cpu python ci/flight_recorder_smoke.py

echo "resume smoke: kill-and-resume on a halved mesh, async stall < 10% sync"
JAX_PLATFORMS=cpu python ci/resume_smoke.py

echo "serving smoke: overloaded Poisson run — sheds, drains, 0 recompiles"
JAX_PLATFORMS=cpu python ci/serving_smoke.py

echo "baseline sync: BASELINE.md matches the committed BENCH round(s)"
python tools/gen_baseline.py --check

echo "lint gates: OK"
