#!/usr/bin/env bash
# Static gates: tpulint (JAX/TPU tracing-hazard analyzer, tools/tpulint/)
# over the whole package in --strict mode (every suppression must carry a
# reason), plus a bytecode compile of package + tools as a syntax gate.
# Exits non-zero on any finding. See docs/static_analysis.md.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "tpulint: analyzing incubator_mxnet_tpu/"
python -m tools.tpulint incubator_mxnet_tpu/ --strict

echo "compileall: incubator_mxnet_tpu/ tools/ tests/"
python -m compileall -q incubator_mxnet_tpu/ tools/ tests/

echo "lint gates: OK"
