"""CI gate: kill-and-resume on a RESIZED mesh really works (ISSUE 11).

The end-to-end preemption story, exercised with a real subprocess and a
real SIGTERM (same idiom as ci/flight_recorder_smoke.py):

1. REFERENCE: a child trains N steps uninterrupted on a data=8 mesh
   (ZeRO-1 explicit tier), async-checkpointing every step, and records
   its loss curve + final params.
2. KILL: a second child trains the same schedule but parks after step K
   (once the async worker has committed at least step K-2) with
   ``MXTPU_FLIGHT_DIR`` set; the parent SIGTERMs it and asserts the
   SIGTERM death code, a parseable flight bundle with reason
   ``signal:SIGTERM``, and a committed (manifest-complete) checkpoint
   no older than K-2 — WITHOUT importing jax in the parent: manifest +
   meta files are plain JSON.
3. RESUME: a third child reuses the kill run's checkpoint dir on a
   data=4 mesh — half the data axis, as after losing half the pod.
   Restore must fall back past any write the SIGTERM truncated,
   re-shard the ZeRO-1 state onto D=4 (``Zero1State.meta.D == 4``),
   and train to N.  The parent then pins:
   * loss-curve continuity: the resumed per-step losses match the
     uninterrupted reference on every overlapping step (rtol 2e-3 —
     the dryrun's zero-vs-replicated parity bound is 2e-4, and the
     resize adds one more reduction-order change);
   * final params match the reference within the same tolerance;
   * the ASYNC save stalls the step loop < 10% of a measured
     synchronous save of the same state (median stall from
     ``checkpoint_step_stall_seconds`` vs median of 3 sync saves).

Run via ci/lint.sh (and the multichip dryrun); standalone:
    JAX_PLATFORMS=cpu python ci/resume_smoke.py
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

STEPS = 10        # reference/uninterrupted length
PARK_AFTER = 5    # kill run parks (and is SIGTERMed) after this step
BATCH = 16        # divisible by both mesh sizes (8 and 4)
D_IN, D_HID = 512, 2048


# -- child ---------------------------------------------------------------- #
def _build():
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.gluon.block import HybridBlock
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    class MLPWithLoss(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.fc1 = nn.Dense(D_HID, in_units=D_IN, activation="tanh")
            self.fc2 = nn.Dense(D_IN, in_units=D_HID)

        def forward(self, x, y):
            return ((self.fc2(self.fc1(x)) - y) ** 2).mean()

    mx.random.seed(0)
    model = MLPWithLoss()
    model.initialize()
    model(NDArray(jnp.ones((BATCH, D_IN))), NDArray(jnp.ones((BATCH, D_IN))))
    model.hybridize()
    return model


def _batch(step):
    import jax
    import jax.numpy as jnp

    kx, ky = jax.random.split(jax.random.PRNGKey(step))
    return (jax.random.normal(kx, (BATCH, D_IN), jnp.float32),
            jax.random.normal(ky, (BATCH, D_IN), jnp.float32))


def child(args):
    import jax
    import numpy as onp

    import incubator_mxnet_tpu.parallel as par
    from incubator_mxnet_tpu import autograd, telemetry
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.gluon import zero as zero_mod
    from incubator_mxnet_tpu.gluon.utils import shard_batch
    from incubator_mxnet_tpu.utils.checkpoint import CheckpointManager

    telemetry.enable()
    mesh = par.create_mesh(data=args.mesh)
    model = _build()
    trainer = Trainer(model.collect_params(), "sgd",
                      {"learning_rate": 0.01, "momentum": 0.9}, mesh=mesh)
    # queue depth covers the whole run: the gate measures the protocol's
    # intrinsic stall (snapshot dispatch + enqueue), not back-pressure
    mgr = CheckpointManager(args.ckpt_dir, keep=3, async_save=True,
                            queue_depth=STEPS + 2)
    start = 0
    if mgr.latest_step() is not None:
        import warnings

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            info = mgr.restore(net=model, trainer=trainer)
        start = info["step"]
        for w in caught:
            print(f"RESTORE-WARN {w.message}", flush=True)
        print(f"RESUMED {start}", flush=True)

    losses = {}
    for step in range(start + 1, args.steps + 1):
        x, y = _batch(step)
        with autograd.record():
            loss = model(shard_batch(x, mesh), shard_batch(y, mesh))
        loss.backward()
        trainer.step(1)
        mgr.save(step, net=model, trainer=trainer)
        losses[step] = float(loss.asnumpy())
        print(f"STEP {step} {losses[step]:.6f}", flush=True)
        if args.park_after and step >= args.park_after:
            # park only once the worker has committed step-K-2 — the
            # parent's SIGTERM may still truncate the later writes
            # (restore's fallback path covers those)
            deadline = time.time() + 120
            while time.time() < deadline:
                latest = mgr.latest_step()
                if latest is not None and latest >= step - 2:
                    break
                time.sleep(0.05)
            print("PARKED", flush=True)
            while True:
                time.sleep(0.1)

    mgr.close()
    stall_p50 = telemetry.histogram(
        "checkpoint_step_stall_seconds").percentile(0.5)
    # measured synchronous baseline: same full state, inline fetch+write
    sync_times = []
    for i in range(3):
        sdir = tempfile.mkdtemp(prefix="mxtpu_sync_ckpt_")
        smgr = CheckpointManager(sdir, async_save=False)
        t0 = time.perf_counter()
        smgr.save(10_000 + i, net=model, trainer=trainer)
        sync_times.append(time.perf_counter() - t0)
        import shutil

        shutil.rmtree(sdir, ignore_errors=True)
    zero_D = 0
    for st in trainer._states.values():
        if isinstance(st, zero_mod.Zero1State):
            zero_D = st.meta.D
            break
    params = onp.concatenate(
        [onp.asarray(jax.device_get(p.data()._data)).ravel()
         for _n, p in sorted(model._collect_params_with_prefix().items())])
    onp.savez(args.out,
              steps=onp.asarray(sorted(losses)),
              losses=onp.asarray([losses[s] for s in sorted(losses)]),
              params=params,
              stall_p50=stall_p50,
              sync_save_seconds=sorted(sync_times)[1],
              resumed_from=start,
              zero_D=zero_D)
    print(f"DONE start={start} zero_D={zero_D} stall_p50={stall_p50:.4f}s "
          f"sync={sorted(sync_times)[1]:.4f}s", flush=True)


# -- parent --------------------------------------------------------------- #
def _child_env(flight_dir=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8")
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            env.pop(k)
    if flight_dir is not None:
        env["MXTPU_FLIGHT_DIR"] = flight_dir
    return env


def _run_child(extra, env, timeout=600):
    cmd = [sys.executable, os.path.abspath(__file__), "--child"] + extra
    proc = subprocess.run(cmd, env=env, timeout=timeout,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise AssertionError(
            f"child {extra} failed rc={proc.returncode}:\n"
            f"{proc.stdout[-4000:]}\n{proc.stderr[-4000:]}")
    return proc


def _complete_steps(ckpt_dir):
    """Committed steps by manifest+meta inspection — pure JSON, no jax
    import in the parent process."""
    steps = []
    for name in sorted(os.listdir(ckpt_dir)):
        d = os.path.join(ckpt_dir, name)
        if not name.startswith("ckpt-") or ".tmp" in name:
            continue
        try:
            with open(os.path.join(d, "meta.json")) as f:
                meta = json.load(f)
            with open(os.path.join(d, "manifest-proc0.json")) as f:
                man = json.load(f)
        except (OSError, ValueError):
            continue
        if all(os.path.getsize(os.path.join(d, fn)) == rec["bytes"]
               for fn, rec in man["files"].items()
               if os.path.exists(os.path.join(d, fn))) \
                and all(os.path.exists(os.path.join(d, fn))
                        for fn in man["files"]):
            steps.append(meta["step"])
    return sorted(steps)


def main():
    import numpy as onp

    root = tempfile.mkdtemp(prefix="mxtpu_resume_smoke_")
    flight_dir = os.path.join(root, "flight")
    ckpt_ref = os.path.join(root, "ck_ref")
    ckpt_elastic = os.path.join(root, "ck_elastic")
    ref_out = os.path.join(root, "ref.npz")
    res_out = os.path.join(root, "res.npz")

    # 1. uninterrupted reference on data=8
    _run_child(["--mesh", "8", "--steps", str(STEPS),
                "--ckpt-dir", ckpt_ref, "--out", ref_out], _child_env())

    # 2. kill run: park after step K, SIGTERM from here
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--mesh", "8", "--steps", str(STEPS),
         "--park-after", str(PARK_AFTER),
         "--ckpt-dir", ckpt_elastic, "--out", os.path.join(root, "x.npz")],
        env=_child_env(flight_dir), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 300
        line = ""
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "PARKED" in line:
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f"kill-run child died early: {line}{proc.stdout.read()}")
        else:
            raise AssertionError("kill-run child never parked")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == -signal.SIGTERM or rc == 128 + signal.SIGTERM, \
        f"kill-run exit code {rc}, wanted SIGTERM death (-15 or 143)"

    # flight bundle shipped
    jsonl = os.path.join(flight_dir, "flight.jsonl")
    assert os.path.exists(jsonl), f"no flight.jsonl in {flight_dir}"
    with open(jsonl) as f:
        lines = [json.loads(l) for l in f if l.strip()]
    assert lines and lines[0]["flight_meta"]["reason"] == "signal:SIGTERM", \
        f"flight bundle wrong: {lines[:1]}"

    # a committed checkpoint no older than K-2 survived the SIGTERM
    committed = _complete_steps(ckpt_elastic)
    assert committed and committed[-1] >= PARK_AFTER - 2, \
        f"latest committed step {committed} < {PARK_AFTER - 2}"

    # 3. resume on HALF the data axis
    res = _run_child(["--mesh", "4", "--steps", str(STEPS),
                      "--ckpt-dir", ckpt_elastic, "--out", res_out],
                     _child_env())
    assert "RESUMED" in res.stdout, res.stdout[-2000:]

    ref = onp.load(ref_out)
    got = onp.load(res_out)
    assert int(got["zero_D"]) == 4, \
        f"resumed state not re-sharded to D=4: {got['zero_D']}"
    assert int(got["resumed_from"]) >= PARK_AFTER - 2

    # loss-curve continuity on every overlapping step
    ref_by_step = dict(zip(ref["steps"].tolist(), ref["losses"].tolist()))
    got_by_step = dict(zip(got["steps"].tolist(), got["losses"].tolist()))
    assert got_by_step, "resume run trained no steps"
    for s, v in got_by_step.items():
        onp.testing.assert_allclose(
            v, ref_by_step[s], rtol=2e-3,
            err_msg=f"loss diverged at step {s} after resized resume")
    onp.testing.assert_allclose(got["params"], ref["params"],
                                rtol=2e-3, atol=1e-4,
                                err_msg="final params diverged")

    # async protocol stalls the step loop < 10% of a synchronous save
    stall, sync = float(got["stall_p50"]), float(got["sync_save_seconds"])
    assert stall < 0.10 * sync, \
        (f"async save stall p50 {stall * 1e3:.1f}ms is not < 10% of the "
         f"synchronous write {sync * 1e3:.1f}ms")

    print(f"resume smoke: OK (killed after step {PARK_AFTER}, committed "
          f"{committed[-1]}, resumed from {int(got['resumed_from'])} on "
          f"data=4, {len(got_by_step)} continuity steps, stall p50 "
          f"{stall * 1e3:.2f}ms vs sync {sync * 1e3:.1f}ms)")


if __name__ == "__main__":
    if "--child" in sys.argv:
        p = argparse.ArgumentParser()
        p.add_argument("--child", action="store_true")
        p.add_argument("--mesh", type=int, required=True)
        p.add_argument("--steps", type=int, required=True)
        p.add_argument("--park-after", type=int, default=0)
        p.add_argument("--ckpt-dir", required=True)
        p.add_argument("--out", required=True)
        child(p.parse_args())
    else:
        main()
