"""Roofline/MFU attribution report over the repo's compiled programs.

Builds, at smoke scale, the four headline program families and prints
one table row per program from `telemetry.perf.roofline_table()`:

* ``trainer_full_step``               — the monolithic one-program step;
* ``trainer_full_step_zero_bucketed`` — the ZeRO-1 explicit tier with
  backward-overlapped bucketed gradient sync (data-axis mesh);
* ``decode_float`` / ``decode_int8``  — `lm_generate`'s bf16 and
  int8 weight-quantized decode programs (the int8 row must move FEWER
  HBM bytes — the whole point of the quantized path).

Columns: compile-time flops / HBM bytes / arithmetic intensity /
bound-by (roofline ridge classification), and the achieved MFU / HBM
GB/s / roofline fraction from a short measured phase (value-fetched
walls — a report tool may sync; hot-path instrumentation never does).

Decode caveat (stated in telemetry.perf too): XLA's cost analysis
models a scan body as executing once, so decode rows compare to each
other exactly (the int8-vs-float byte ratio) but not to trainer rows.

Usage:  python tools/roofline_report.py [--json] [--devices N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _parse_args(argv):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="emit the rows as one JSON array instead of a table")
    ap.add_argument("--devices", type=int, default=2,
                    help="virtual host devices for the ZeRO data mesh "
                         "(default 2)")
    return ap.parse_args(argv)


def _force_devices(n: int) -> None:
    flag = f"--xla_force_host_platform_device_count={n}"
    if "jax" in sys.modules:  # pragma: no cover — direct script use only
        raise SystemExit("roofline_report must set XLA flags before jax "
                         "imports; run it as a standalone script")
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " "
                               + flag).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _train_programs(n_devices: int):
    """Build + time the monolithic and ZeRO-bucketed trainer steps."""
    import time

    import jax
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, telemetry
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.gluon.block import HybridBlock
    from incubator_mxnet_tpu.gluon.utils import shard_batch
    from incubator_mxnet_tpu.models import bert
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.parallel import create_mesh
    from incubator_mxnet_tpu.parallel.sharding import shard_params

    V, D, DFF, L, H, B, T = 64, 32, 64, 2, 4, 2 * n_devices, 16

    class WithLoss(HybridBlock):
        def __init__(self, net_, **kw):
            super().__init__(**kw)
            self.net = net_

        def forward(self, tokens, labels):
            mlm_logits, _nsp = self.net(tokens)
            logp = mx.nd.log_softmax(mlm_logits.astype("float32"))
            return -(mx.nd.pick(logp, labels).mean())

    def run(mesh, **tr_kw):
        mx.random.seed(0)
        net = bert.BERTForPretraining(vocab_size=V, units=D, hidden_size=DFF,
                                      num_layers=L, num_heads=H, dropout=0.0)
        net.initialize()
        net(NDArray(jnp.ones((B, T), jnp.int32)))
        if mesh is not None:
            shard_params(net, mesh, warn=False)
        model = WithLoss(net)
        model.hybridize()
        trainer = Trainer(model.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          mesh=mesh, **tr_kw)
        key = jax.random.PRNGKey(7)
        tok = jax.random.randint(key, (B, T), 0, V, dtype=jnp.int32)
        lab = jax.random.randint(jax.random.fold_in(key, 1), (B, T), 0, V,
                                 dtype=jnp.int32)
        if mesh is not None:
            tokens, labels = shard_batch(tok, mesh), shard_batch(lab, mesh)
        else:
            tokens, labels = NDArray(tok), NDArray(lab)
        loss = None
        for i in range(3):
            t0 = time.perf_counter()
            with autograd.record():
                loss = model(tokens, labels)
            loss.backward()
            trainer.step(1)
            trainer.flush()
            float(loss.asnumpy())  # value fetch: end-to-end wall
            if i:  # skip the compile step
                telemetry.perf.note_timing(trainer._perf_program,
                                           time.perf_counter() - t0)
        return trainer._perf_program

    names = [run(mesh=None, zero_stage=0)]
    mesh = create_mesh(jax.devices()[:n_devices], data=n_devices)
    # tiny bucket cap → the backward-overlapped BUCKETED tier engages
    names.append(run(mesh=mesh, zero_stage=1, zero_overlap=True,
                     zero_bucket_mb=0.05))
    return names


def _decode_programs():
    """Build + time the float and int8 weight-quantized decode programs."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as onp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import telemetry
    from incubator_mxnet_tpu.models.transformer import TransformerLM
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    V, C, DFF, L, H, MAXLEN = 97, 32, 96, 2, 4, 64
    B, P, N = 2, 5, 16

    mx.random.seed(0)
    net = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=MAXLEN, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))
    net.cast("bfloat16")
    prompt = onp.array(
        jax.random.randint(jax.random.PRNGKey(3), (B, P), 0, V),
        dtype="int32")

    names = []
    for quant in (False, True):
        if quant:
            net.quantize_for_decode(act_quant="none")
        for i in range(2):
            t0 = time.perf_counter()
            out = net.generate(prompt, N)
            out.block_until_ready()
            if i:  # second call: compiled program, end-to-end wall
                name = f"decode_{'int8' if quant else 'float'}"
                telemetry.perf.note_timing(name, time.perf_counter() - t0)
        names.append(f"decode_{'int8' if quant else 'float'}")
    return names


_COLS = [("program", 34, "s"), ("flops", 12, "g"), ("hbm_bytes", 12, "g"),
         ("intensity", 10, "v"), ("bound_by", 8, "s"), ("mfu", 10, "v"),
         ("hbm_gbps", 10, "v"), ("roofline_fraction", 10, "v")]


def _fmt_cell(v, kind):
    if v is None:
        return "-"
    if kind == "s":
        return str(v)
    if kind == "g":
        return f"{v:.4g}"
    return f"{v:.4f}"


def _print_table(rows):
    head = "  ".join(f"{name:<{w}}" for name, w, _ in _COLS)
    print(head)
    print("-" * len(head))
    for r in rows:
        print("  ".join(f"{_fmt_cell(r.get(name), kind):<{w}}"
                        for name, w, kind in _COLS))


def main(argv=None):
    args = _parse_args(argv)
    _force_devices(max(2, args.devices))

    from incubator_mxnet_tpu import telemetry

    telemetry.enable()
    want = _train_programs(max(2, args.devices)) + _decode_programs()

    rows = telemetry.perf.roofline_table()
    have = {r["program"] for r in rows}
    missing = [n for n in want if n not in have]
    assert not missing, f"programs not captured: {missing} (have {have})"

    by = {r["program"]: r for r in rows}
    f_b = by["decode_float"]["hbm_bytes"]
    i_b = by["decode_int8"]["hbm_bytes"]
    assert i_b < f_b, \
        f"int8 decode moves {i_b} HBM bytes, not fewer than float {f_b}"

    if args.json:
        print(json.dumps(rows))
    else:
        _print_table(rows)
        print(f"\nint8 decode HBM bytes / float: {i_b / f_b:.3f}x "
              f"({len(rows)} programs captured)")
    return rows


if __name__ == "__main__":
    main()
