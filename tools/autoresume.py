#!/usr/bin/env python
"""Elastic checkpoint-restart supervisor (SURVEY.md §5.3: "must exceed
reference" — MXNet's ps-lite generally hangs or dies on worker failure).

Supervises a training command; on non-zero exit OR a stalled heartbeat
it kills and relaunches the command, which is expected to resume from
its latest checkpoint (`utils.checkpoint.CheckpointManager.restore`).
Restart count is bounded; steady progress (heartbeat mtime advancing)
resets the budget.

Hardened contract (docs/robustness.md):

* **Exponential backoff** between restarts (``--backoff``, doubling up
  to ``--backoff-max``) so a crash-looping job doesn't hammer shared
  infrastructure (checkpoint filesystem, coordinator) at poll speed.
* **Graceful kill escalation**: a hung child gets SIGTERM first — its
  flight recorder (telemetry.flight_recorder) dumps the last-N-steps
  bundle and the checkpoint worker flushes — then SIGKILL after
  ``--grace`` seconds if it still won't die.
* **Exit-code propagation**: the supervisor's own exit status is the
  child's FINAL exit code (128+signum for a signal death, shell
  convention), so outer schedulers see why the job ultimately stopped.
* **Signal forwarding**: SIGTERM/SIGINT at the supervisor (pod
  preemption hits the process group leader first) forwards to the
  child with the same grace escalation, then exits with the child's
  code — the supervisor never orphans a training process.

Heartbeat contract: the training script touches `--heartbeat-file`
every step (one os.utime / write).  If the file goes stale for longer
than `--heartbeat-timeout` seconds the job is declared hung (the
barrier-timeout failure mode of distributed training) and restarted.

Usage:
  python tools/autoresume.py --max-restarts 3 \
      [--heartbeat-file /tmp/hb --heartbeat-timeout 300] \
      -- python train.py --ckpt-dir /ckpts ...
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time

__all__ = ["supervise", "main", "build_parser"]


def build_parser():
    p = argparse.ArgumentParser(description="checkpoint-restart supervisor")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--heartbeat-file", type=str, default=None)
    p.add_argument("--heartbeat-timeout", type=float, default=300.0)
    p.add_argument("--poll-interval", type=float, default=1.0)
    p.add_argument("--backoff", type=float, default=1.0,
                   help="initial sleep before a restart (doubles each "
                        "consecutive restart, resets on progress)")
    p.add_argument("--backoff-max", type=float, default=60.0)
    p.add_argument("--grace", type=float, default=10.0,
                   help="seconds between SIGTERM and SIGKILL when the "
                        "supervisor has to kill the child")
    p.add_argument("command", nargs=argparse.REMAINDER)
    return p


def _heartbeat_age(path):
    try:
        return time.time() - os.path.getmtime(path)
    except OSError:
        return None  # not yet written


def _terminate(proc, grace: float) -> int:
    """SIGTERM → wait up to ``grace`` → SIGKILL.  The TERM-first window
    lets the child's flight recorder dump its bundle and the checkpoint
    worker finish an in-flight commit; KILL is the backstop for a child
    wedged past signal delivery (stuck collective, D2H hang).  Returns
    the child's exit code."""
    if proc.poll() is not None:
        return proc.returncode
    try:
        proc.send_signal(signal.SIGTERM)
    except OSError:
        pass
    try:
        return proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        pass
    try:
        proc.send_signal(signal.SIGKILL)
    except OSError:
        pass
    return proc.wait()


def _exit_code(rc: int) -> int:
    """Child exit status → supervisor exit status: negative (signal
    death) becomes the shell's 128+signum so outer schedulers can tell
    SIGKILL(137)/SIGTERM(143) from ordinary failures."""
    return 128 - rc if rc < 0 else rc


def supervise(command, max_restarts=3, heartbeat_file=None,
              heartbeat_timeout=300.0, poll_interval=1.0,
              backoff=1.0, backoff_max=60.0, grace=10.0) -> int:
    restarts = 0
    delay = backoff
    stop = {"sig": None}

    def _forward(signum, _frame):
        stop["sig"] = signum

    # forward preemption signals to the child (main thread only —
    # supervise() is also called from test threads, where signal
    # handlers are unavailable)
    installed = {}
    if threading.current_thread() is threading.main_thread():
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                installed[signum] = signal.signal(signum, _forward)
            except (ValueError, OSError):
                pass
    try:
        while True:
            start = time.time()
            if heartbeat_file is not None:
                # reset staleness: the relaunched process needs init time
                # before its first beat — a stale mtime from the previous
                # incarnation must not kill it instantly
                try:
                    os.utime(heartbeat_file, None)
                except OSError:
                    pass
            proc = subprocess.Popen(command)
            hung = False
            while True:
                rc = proc.poll()
                if rc is not None:
                    break
                if stop["sig"] is not None:
                    print(f"autoresume: received signal {stop['sig']} — "
                          f"forwarding to job and exiting",
                          file=sys.stderr, flush=True)
                    return _exit_code(_terminate(proc, grace))
                if heartbeat_file is not None:
                    age = _heartbeat_age(heartbeat_file)
                    if age is not None and age > heartbeat_timeout:
                        print(f"autoresume: heartbeat stale {age:.0f}s > "
                              f"{heartbeat_timeout:.0f}s — killing job",
                              file=sys.stderr, flush=True)
                        rc, hung = _terminate(proc, grace), True
                        if rc == 0:
                            rc = 1  # a hung-then-killed job never "passed"
                        break
                time.sleep(poll_interval)
            if rc == 0:
                return 0
            # sustained progress earns the budget back — BEFORE the
            # exhaustion check, so a long-healthy job gets a fresh
            # budget and the backoff clock restarts from its base
            if time.time() - start > 10 * heartbeat_timeout:
                restarts = 0
                delay = backoff
            restarts += 1
            reason = "hang" if hung else f"rc={rc}"
            if restarts > max_restarts:
                print(f"autoresume: {reason}; restart budget exhausted "
                      f"({max_restarts})", file=sys.stderr, flush=True)
                return _exit_code(rc) or 1
            print(f"autoresume: {reason}; restarting in {delay:.1f}s "
                  f"({restarts}/{max_restarts})",
                  file=sys.stderr, flush=True)
            time.sleep(delay)
            delay = min(delay * 2, backoff_max)
    finally:
        for signum, prev in installed.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):
                pass


def main(argv=None):
    args = build_parser().parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("autoresume: no command given", file=sys.stderr)
        return 2
    return supervise(command, args.max_restarts, args.heartbeat_file,
                     args.heartbeat_timeout, args.poll_interval,
                     backoff=args.backoff, backoff_max=args.backoff_max,
                     grace=args.grace)


if __name__ == "__main__":
    sys.exit(main())
