#!/usr/bin/env python
"""Elastic checkpoint-restart supervisor (SURVEY.md §5.3: "must exceed
reference" — MXNet's ps-lite generally hangs or dies on worker failure).

Supervises a training command; on non-zero exit OR a stalled heartbeat
it kills and relaunches the command, which is expected to resume from
its latest checkpoint (`utils.checkpoint.CheckpointManager.restore`).
Restart count is bounded; steady progress (heartbeat mtime advancing)
resets the budget.

Heartbeat contract: the training script touches `--heartbeat-file`
every step (one os.utime / write).  If the file goes stale for longer
than `--heartbeat-timeout` seconds the job is declared hung (the
barrier-timeout failure mode of distributed training) and restarted.

Usage:
  python tools/autoresume.py --max-restarts 3 \
      [--heartbeat-file /tmp/hb --heartbeat-timeout 300] \
      -- python train.py --ckpt-dir /ckpts ...
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def build_parser():
    p = argparse.ArgumentParser(description="checkpoint-restart supervisor")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--heartbeat-file", type=str, default=None)
    p.add_argument("--heartbeat-timeout", type=float, default=300.0)
    p.add_argument("--poll-interval", type=float, default=1.0)
    p.add_argument("command", nargs=argparse.REMAINDER)
    return p


def _heartbeat_age(path):
    try:
        return time.time() - os.path.getmtime(path)
    except OSError:
        return None  # not yet written


def supervise(command, max_restarts=3, heartbeat_file=None,
              heartbeat_timeout=300.0, poll_interval=1.0) -> int:
    restarts = 0
    while True:
        start = time.time()
        if heartbeat_file is not None:
            # reset staleness: the relaunched process needs init time
            # before its first beat — a stale mtime from the previous
            # incarnation must not kill it instantly
            try:
                os.utime(heartbeat_file, None)
            except OSError:
                pass
        proc = subprocess.Popen(command)
        hung = False
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            if heartbeat_file is not None:
                age = _heartbeat_age(heartbeat_file)
                if age is not None and age > heartbeat_timeout:
                    print(f"autoresume: heartbeat stale {age:.0f}s > "
                          f"{heartbeat_timeout:.0f}s — killing job",
                          file=sys.stderr, flush=True)
                    proc.send_signal(signal.SIGKILL)
                    proc.wait()
                    rc, hung = -9, True
                    break
            time.sleep(poll_interval)
        if rc == 0:
            return 0
        # sustained progress earns the budget back — BEFORE the
        # exhaustion check, so a long-healthy job gets a fresh budget
        if time.time() - start > 10 * heartbeat_timeout:
            restarts = 0
        restarts += 1
        reason = "hang" if hung else f"rc={rc}"
        if restarts > max_restarts:
            print(f"autoresume: {reason}; restart budget exhausted "
                  f"({max_restarts})", file=sys.stderr, flush=True)
            return rc if rc else 1
        print(f"autoresume: {reason}; restarting ({restarts}/{max_restarts})",
              file=sys.stderr, flush=True)


def main(argv=None):
    args = build_parser().parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("autoresume: no command given", file=sys.stderr)
        return 2
    return supervise(command, args.max_restarts, args.heartbeat_file,
                     args.heartbeat_timeout, args.poll_interval)


if __name__ == "__main__":
    sys.exit(main())
