"""Shared structured parser for compiled HLO text and lowered StableHLO.

Compiled/optimized HLO (``compiled.as_text()``) is a line-oriented
format::

    HloModule jit_f, is_scheduled=true, input_output_alias={ {2}: (2, {}, may-alias) }, ...

    %region_0.10 (Arg_0.11: f32[], Arg_1.12: f32[]) -> f32[] {
      ...
      ROOT %add.13 = f32[] add(f32[] %Arg_0.11, f32[] %Arg_1.12), metadata={...}
    }

    ENTRY %main_spmd (param: f32[64], ...) -> (f32[8], ...) {
      %reduce-scatter.2 = f32[8]{0} reduce-scatter(f32[64]{0} %param),
          channel_id=1, replica_groups={{0,...,7}}, use_global_device_ids=true,
          dimensions={0}, to_apply=%region_0.10, metadata={...}
      ...
    }

The parser handles both the ``%name``-prefixed and the bare-name
spellings, tuple result types, the three printed ``replica_groups``
forms (explicit ``{{..},{..}}``, iota-v2 ``[G,S]<=[dims]T(perm)``, and
the empty all-device ``{}``), ``control-predecessors``, and the module
header attributes (``is_scheduled``, ``input_output_alias``,
``num_partitions``/``replica_count``).

Lowered StableHLO (``lowered.as_text()``) is MLIR; :func:`parse_stablehlo`
extracts what the fact extractors need — the entry func's argument
attributes (``jax.buffer_donor`` / ``tf.aliasing_output`` donation
markers), per-op names, and every ``tensor<...>`` type token with its
shape and dtype — without pretending to be a full MLIR parser.

In a *scheduled* module (``is_scheduled=true``) entry-instruction order
IS the schedule; `parallel/overlap.py` builds its overlap measurement
directly on this IR.
"""
from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Shape", "HloInstruction", "HloComputation", "HloModule",
           "StableHloModule", "parse_hlo", "parse_stablehlo",
           "DTYPE_BYTES", "COLLECTIVE_OPS"]

# bytes per element of every dtype XLA prints; sub-byte types (s4/u4)
# round up to 1 — hlolint over- rather than under-counts them
DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "s32": 4, "u32": 4, "s64": 8, "u64": 8,
    "f8e3m4": 1, "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e4m3fnuz": 1, "f8e5m2": 1, "f8e5m2fnuz": 1,
    "bf16": 2, "f16": 2, "f32": 4, "f64": 8, "c64": 8, "c128": 16,
}

# collective opcodes (sync spelling; async adds -start/-done)
COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "ragged-all-to-all",
})


class Shape:
    """One array shape: element dtype + dims.  ``dtype='token'`` and
    other non-array types byte out at 0."""

    __slots__ = ("dtype", "dims")

    def __init__(self, dtype: str, dims: Tuple[int, ...]):
        self.dtype = dtype
        self.dims = tuple(dims)

    @property
    def nbytes(self) -> int:
        item = DTYPE_BYTES.get(self.dtype)
        if item is None:
            return 0
        n = 1
        for d in self.dims:
            n *= d
        return n * item

    def __repr__(self):
        return f"{self.dtype}[{','.join(map(str, self.dims))}]"

    def __eq__(self, other):
        return (isinstance(other, Shape) and self.dtype == other.dtype
                and self.dims == other.dims)

    def __hash__(self):
        return hash((self.dtype, self.dims))


_SHAPE_TOKEN_RE = re.compile(r"([a-z][a-z0-9]*)\[([\d,]*)\]")


def parse_shapes(type_str: str) -> List[Shape]:
    """Every dtype[dims] token in an HLO type string (tuple-aware —
    a tuple type simply yields one Shape per element)."""
    out = []
    for m in _SHAPE_TOKEN_RE.finditer(type_str):
        dt, dims = m.group(1), m.group(2)
        if dt == "token":
            out.append(Shape("token", ()))
            continue
        if dt not in DTYPE_BYTES:
            continue
        out.append(Shape(dt, tuple(int(d) for d in dims.split(",") if d)))
    return out


class HloInstruction:
    """One HLO instruction: result name, opcode, result shapes (tuple
    types give several), operand names, and the parsed attributes the
    fact extractors read."""

    __slots__ = ("name", "opcode", "shapes", "operands", "attrs",
                 "is_root", "index", "raw")

    def __init__(self, name, opcode, shapes, operands, attrs, is_root,
                 index, raw):
        self.name = name
        self.opcode = opcode
        self.shapes: List[Shape] = shapes
        self.operands: Tuple[str, ...] = tuple(operands)
        self.attrs: Dict[str, object] = attrs
        self.is_root = is_root
        self.index = index
        self.raw = raw

    @property
    def result_bytes(self) -> int:
        return sum(s.nbytes for s in self.shapes)

    @property
    def called_computations(self) -> List[str]:
        out = []
        for k in ("to_apply", "calls", "condition", "body",
                  "branch_computations"):
            v = self.attrs.get(k)
            if isinstance(v, str):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                out.extend(v)
        return out

    def replica_group_members(self, num_devices: Optional[int] = None
                              ) -> Optional[List[List[int]]]:
        """The collective's replica groups as explicit member lists.
        ``{}`` (all devices) resolves when `num_devices` is given, else
        returns ``[[]]`` meaning "one group of everything"."""
        rg = self.attrs.get("replica_groups")
        if rg is None:
            return None
        if rg == "empty":
            if num_devices:
                return [list(range(num_devices))]
            return [[]]
        if isinstance(rg, dict):        # iota v2 form
            G, S = rg["shape"]
            dims, perm = rg["dims"], rg.get("perm")
            n = 1
            for d in dims:
                n *= d
            flat = list(range(n))
            # reshape to dims, optionally transpose, reshape to (G, S)
            # — plain-python strides, no numpy dependency
            strides = [0] * len(dims)
            s = 1
            for i in reversed(range(len(dims))):
                strides[i] = s
                s *= dims[i]
            order = perm if perm else list(range(len(dims)))
            out_dims = [dims[i] for i in order]
            out_strides = [strides[i] for i in order]

            def unflatten(idx):
                coord = []
                for d in reversed(out_dims):
                    coord.append(idx % d)
                    idx //= d
                coord.reverse()
                return sum(c * st for c, st in zip(coord, out_strides))

            flat = [unflatten(i) for i in range(n)]
            return [flat[g * S:(g + 1) * S] for g in range(G)]
        return [list(g) for g in rg]

    def __repr__(self):
        return f"<{self.opcode} %{self.name} {self.shapes}>"


class HloComputation:
    __slots__ = ("name", "instructions", "is_entry", "is_fusion", "by_name")

    def __init__(self, name: str, is_entry: bool):
        self.name = name
        self.is_entry = is_entry
        self.is_fusion = "fused_computation" in name
        self.instructions: List[HloInstruction] = []
        self.by_name: Dict[str, HloInstruction] = {}

    @property
    def root(self) -> Optional[HloInstruction]:
        for ins in self.instructions:
            if ins.is_root:
                return ins
        return self.instructions[-1] if self.instructions else None

    def parameters(self) -> List[HloInstruction]:
        return [i for i in self.instructions if i.opcode == "parameter"]


class HloModule:
    """Parsed compiled-HLO module: header attributes + computations."""

    __slots__ = ("name", "is_scheduled", "num_partitions", "replica_count",
                 "input_output_alias", "computations", "entry")

    def __init__(self):
        self.name = ""
        self.is_scheduled = False
        self.num_partitions = 1
        self.replica_count = 1
        # [(output_tuple_index, param_number, param_tuple_index, kind)]
        self.input_output_alias: List[Tuple[Tuple[int, ...], int,
                                            Tuple[int, ...], str]] = []
        self.computations: Dict[str, HloComputation] = {}
        self.entry: Optional[HloComputation] = None

    def all_instructions(self) -> Iterable[HloInstruction]:
        for comp in self.computations.values():
            for ins in comp.instructions:
                yield ins

    def computation(self, name: str) -> Optional[HloComputation]:
        return self.computations.get(name.lstrip("%"))

    def async_pairs(self) -> List[Tuple[HloInstruction, HloInstruction]]:
        """(start, done) pairs for split async ops, matched by the done
        instruction consuming the start's result (never by name suffix)."""
        pairs = []
        for comp in self.computations.values():
            starts = {i.name: i for i in comp.instructions
                      if i.opcode.endswith("-start")}
            for ins in comp.instructions:
                if not ins.opcode.endswith("-done"):
                    continue
                for op in ins.operands:
                    st = starts.get(op)
                    if st is not None:
                        pairs.append((st, ins))
                        break
        return pairs

    def collectives(self, include_inner: bool = True
                    ) -> List[HloInstruction]:
        """Collective instructions (one per op: async ``-done`` halves
        are excluded, the ``-start`` carries shape and attrs).  With
        ``include_inner`` collectives inside called computations (while
        bodies, fusions) count too."""
        comps = self.computations.values() if include_inner else \
            ([self.entry] if self.entry else [])
        out = []
        for comp in comps:
            for ins in comp.instructions:
                base = ins.opcode
                for suf in ("-start", "-done"):
                    if base.endswith(suf):
                        base = base[:-len(suf)]
                if base in COLLECTIVE_OPS and not ins.opcode.endswith("-done"):
                    out.append(ins)
        return out


# ------------------------------------------------------------------ #
# compiled-HLO text parsing
# ------------------------------------------------------------------ #
# parameter lists may nest parens (tuple-typed args like
# `(arg_tuple.1: (s32[], bf16[2,4,4]))`), so the arg group is greedy
_COMP_HEAD_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->.*\{\s*$")
_INSTR_HEAD_RE = re.compile(
    r"^\s*(ROOT\s+)?%?([\w.\-]+)\s*=\s*")
_NAME_RE = re.compile(r"%([\w.\-]+)")
_ALIAS_RE = re.compile(
    r"\{\s*([\d,\s]*)\}:\s*\(\s*(\d+)\s*,\s*\{([\d,\s]*)\}\s*"
    r"(?:,\s*([\w\-]+))?\s*\)")
_RG_IOTA_RE = re.compile(
    r"\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")


def _parse_header(line: str, mod: HloModule) -> None:
    mod.name = line.split(",", 1)[0].split()[1] if " " in line else ""
    if "is_scheduled=true" in line:
        mod.is_scheduled = True
    m = re.search(r"num_partitions=(\d+)", line)
    if m:
        mod.num_partitions = int(m.group(1))
    m = re.search(r"replica_count=(\d+)", line)
    if m:
        mod.replica_count = int(m.group(1))
    start = line.find("input_output_alias={")
    if start >= 0:
        # the alias list nests braces ({out_idx}: (p, {p_idx}, kind)) —
        # take the balanced {...} body, not up-to-first-}
        i = start + len("input_output_alias=")
        depth = 0
        end = i
        for end in range(i, len(line)):
            if line[end] == "{":
                depth += 1
            elif line[end] == "}":
                depth -= 1
                if depth == 0:
                    break
        body = line[i + 1:end]
        for am in _ALIAS_RE.finditer(body):
            out_idx = tuple(int(t) for t in am.group(1).split(",") if t.strip())
            param = int(am.group(2))
            p_idx = tuple(int(t) for t in am.group(3).split(",") if t.strip())
            kind = am.group(4) or "may-alias"
            mod.input_output_alias.append((out_idx, param, p_idx, kind))


def _split_operand_attrs(rest: str) -> Tuple[str, str]:
    """Split `opcode(<operands>), attr=..., ...` text after the opening
    paren into (operand text, attr text) by matching parens/braces —
    operand types carry `{1,0}` layouts, tuple operands nest parens."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i], rest[i + 1:]
    return rest, ""


def _parse_attrs(attr_text: str) -> Dict[str, object]:
    attrs: Dict[str, object] = {}
    m = re.search(r"channel_id=(\d+)", attr_text)
    if m:
        attrs["channel_id"] = int(m.group(1))
    if "use_global_device_ids=true" in attr_text:
        attrs["use_global_device_ids"] = True
    m = re.search(r"custom_call_target=\"([^\"]*)\"", attr_text)
    if m:
        attrs["custom_call_target"] = m.group(1)
    m = re.search(r"dimensions=\{([\d,\s]*)\}", attr_text)
    if m:
        attrs["dimensions"] = tuple(
            int(t) for t in m.group(1).split(",") if t.strip())
    for key in ("to_apply", "condition", "body", "calls"):
        m = re.search(key + r"=%?([\w.\-]+)", attr_text)
        if m:
            attrs[key] = m.group(1)
    m = re.search(r"control-predecessors=\{([^}]*)\}", attr_text)
    if m:
        attrs["control_predecessors"] = tuple(
            t.strip().lstrip("%") for t in m.group(1).split(",") if t.strip())
    m = re.search(r"source_target_pairs=\{\{(.*?)\}\}", attr_text)
    if m:
        attrs["source_target_pairs"] = [
            tuple(int(t) for t in pair.split(","))
            for pair in m.group(1).split("},{")]
    # replica_groups: three printed forms
    m = re.search(r"replica_groups=\{\{(.*?)\}\}", attr_text)
    if m:
        attrs["replica_groups"] = [
            [int(t) for t in grp.split(",")]
            for grp in m.group(1).split("},{")]
    else:
        m = re.search(r"replica_groups=" + _RG_IOTA_RE.pattern, attr_text)
        if m:
            attrs["replica_groups"] = {
                "shape": (int(m.group(1)), int(m.group(2))),
                "dims": [int(t) for t in m.group(3).split(",")],
                "perm": [int(t) for t in m.group(4).split(",")]
                if m.group(4) else None,
            }
        elif re.search(r"replica_groups=\{\}", attr_text):
            attrs["replica_groups"] = "empty"
    return attrs


def _operand_names(op_text: str) -> List[str]:
    """Operand result-names from the operand text.  `%`-prefixed names
    when present; else bare identifiers left after stripping shape
    tokens (newer jax prints `add(f32[] Arg_0.11, f32[] Arg_1.12)` or
    `add(Arg_0.11, Arg_1.12)`)."""
    names = _NAME_RE.findall(op_text)
    if names or not op_text.strip():
        return names
    stripped = _SHAPE_TOKEN_RE.sub(" ", op_text)
    stripped = re.sub(r"\{[\d,\s]*\}", " ", stripped)   # layouts
    out = []
    for tok in stripped.replace("(", " ").replace(")", " ").split(","):
        tok = tok.strip()
        if tok and re.fullmatch(r"[\w.\-]+", tok):
            out.append(tok)
    return out


def parse_hlo(text: str) -> HloModule:
    """Parse compiled/optimized HLO text into an :class:`HloModule`."""
    mod = HloModule()
    comp: Optional[HloComputation] = None
    idx = 0
    for line in text.splitlines():
        if line.startswith("HloModule"):
            _parse_header(line, mod)
            continue
        stripped = line.strip()
        if comp is None:
            m = _COMP_HEAD_RE.match(stripped)
            if m and "=" not in stripped.split("(")[0]:
                comp = HloComputation(m.group(2), is_entry=bool(m.group(1)))
                mod.computations[comp.name] = comp
                if comp.is_entry:
                    mod.entry = comp
                idx = 0
            continue
        if stripped.startswith("}"):
            comp = None
            continue
        m = _INSTR_HEAD_RE.match(line)
        if m is None:
            continue
        is_root, name = bool(m.group(1)), m.group(2)
        rest = line[m.end():]
        # result type = text before the opcode; opcode = identifier
        # immediately before the operand '('
        om = re.search(r"([a-z][\w\-]*)\(", rest)
        if om is None:
            continue
        type_str, opcode = rest[:om.start()], om.group(1)
        op_text, attr_text = _split_operand_attrs(rest[om.end():])
        operands = [n for n in _operand_names(op_text) if n != name]
        attrs = _parse_attrs(attr_text)
        operands += [n for n in attrs.get("control_predecessors", ())
                     if n != name]
        comp.instructions.append(HloInstruction(
            name=name, opcode=opcode, shapes=parse_shapes(type_str),
            operands=operands, attrs=attrs, is_root=is_root, index=idx,
            raw=stripped))
        comp.by_name[name] = comp.instructions[-1]
        idx += 1
    return mod


# ------------------------------------------------------------------ #
# StableHLO (MLIR) text parsing
# ------------------------------------------------------------------ #
# dims are `\d+x` repeats; the element type never contains a bare `x`
# (i8, ui32, bf16, f8E4M3FN, ...), so anchor the dtype after the last
# `<digits>x` run — a plain `[a-z]+` dtype group would swallow the `x`
# separators themselves.
_TENSOR_RE = re.compile(r"tensor<((?:\d+x)*)([a-zA-Z][a-zA-Z0-9]*)>")
_MLIR_ARG_RE = re.compile(r"%arg(\d+):\s*tensor<[^>]*>\s*(\{[^}]*\})?")
_MLIR_OP_RE = re.compile(
    r"^\s*(?:%[\w#:]+\s*=\s*)?(?:\"?)([\w.]+)(?:\"?)[\s(]")

_MLIR_DTYPES = {
    "i1": "pred", "i2": "s2", "i4": "s4", "i8": "s8", "i16": "s16",
    "i32": "s32", "i64": "s64", "ui8": "u8", "ui16": "u16", "ui32": "u32",
    "ui64": "u64", "bf16": "bf16", "f16": "f16", "f32": "f32",
    "f64": "f64", "f8E4M3FN": "f8e4m3fn", "f8E5M2": "f8e5m2",
}


class StableHloModule:
    """Lightweight view of a lowered StableHLO module: entry argument
    donation attributes, op-name census, and every tensor type token."""

    __slots__ = ("name", "arg_attrs", "ops", "types")

    def __init__(self):
        self.name = ""
        # per entry argument: the raw attr dict text ('' when none)
        self.arg_attrs: List[str] = []
        self.ops: Dict[str, int] = {}
        self.types: Dict[Shape, int] = {}

    @property
    def donated_args(self) -> List[int]:
        """Argument indices jax marked for donation — either the
        ``jax.buffer_donor`` marker or an explicit
        ``tf.aliasing_output`` assignment."""
        return [i for i, a in enumerate(self.arg_attrs)
                if "jax.buffer_donor" in a or "tf.aliasing_output" in a]

    @property
    def aliased_args(self) -> List[int]:
        return [i for i, a in enumerate(self.arg_attrs)
                if "tf.aliasing_output" in a]

    def dtypes(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for sh, n in self.types.items():
            out[sh.dtype] = out.get(sh.dtype, 0) + n
        return out

    def shapes_with_dims(self, dims: Tuple[int, ...]) -> List[Shape]:
        return [sh for sh in self.types if sh.dims == tuple(dims)]


def _mlir_shape(dims_str: str, dtype_str: str) -> Optional[Shape]:
    dt = _MLIR_DTYPES.get(dtype_str)
    if dt is None:
        return None
    dims = tuple(int(d) for d in dims_str.split("x") if d) \
        if dims_str else ()
    return Shape(dt, dims)


def parse_stablehlo(text: str) -> StableHloModule:
    """Parse lowered StableHLO (MLIR) text into a
    :class:`StableHloModule` — arg donation attrs from the first public
    func signature, op-name counts, and a census of every ``tensor<>``
    type token (operand and result positions both — exactly what the
    no-float-weight gate needs)."""
    smod = StableHloModule()
    m = re.search(r"module\s+@([\w.\-]+)", text)
    if m:
        smod.name = m.group(1)
    in_sig = False
    sig = ""
    for line in text.splitlines():
        # the type census covers EVERY line, signature included — the
        # entry arg types are where the weight tensors live
        for tm in _TENSOR_RE.finditer(line):
            sh = _mlir_shape(tm.group(1), tm.group(2))
            if sh is not None:
                smod.types[sh] = smod.types.get(sh, 0) + 1
        if "func.func" in line and "@main" in line:
            in_sig = True
        if in_sig:
            sig += line
            if "{" in line.split("->")[-1] or line.rstrip().endswith("{"):
                in_sig = False
                args = sig.split("->")[0]
                for am in _MLIR_ARG_RE.finditer(args):
                    i = int(am.group(1))
                    while len(smod.arg_attrs) <= i:
                        smod.arg_attrs.append("")
                    smod.arg_attrs[i] = am.group(2) or ""
            continue
        om = _MLIR_OP_RE.match(line)
        if om:
            op = om.group(1)
            if op not in ("func.func", "module"):
                smod.ops[op] = smod.ops.get(op, 0) + 1
    return smod
