"""hlolint — compiled-program contract checker over HLO / StableHLO.

tpulint (tools/tpulint/) guards the Python side; the properties that
actually decide TPU performance and correctness — which collectives a
program issues, whether int8 weights stay int8, whether donated buffers
alias, whether comms are async — live in the *compiled* artifact.
hlolint is the static analyzer for that artifact:

* :mod:`.parser` — ONE shared parser turning compiled/optimized HLO
  text and lowered StableHLO (MLIR) into a structured module IR:
  computations, instructions with opcode/shape/dtype/operands,
  collective attributes (replica_groups, channel_id,
  use_global_device_ids, source_target_pairs), async start/done
  pairing, fusion bodies, and input/output aliasing from donation.  It
  replaces the three ad-hoc regex/grep inspectors the repo grew
  (``__graft_entry__`` dryrun collective counts, ``parallel/overlap.py``
  schedule parsing, ``ci/quantized_decode_smoke.py`` substring asserts).
* :mod:`.facts` — fact extractors over the IR: per-program collective
  inventory (count + bytes by op and mesh axis, via replica-group
  factorization against the active mesh), dtype census, host-transfer
  ops, donation coverage, while/fusion stats, float-weight
  materialization checks.
* :mod:`.contracts` — declarative per-program contracts
  (``.hlolint_contracts.json``, rules HLO001–HLO006) evaluated against
  the facts; ``ci/hlolint_gate.py`` compiles the repo's flagship
  programs and gates them in ci/lint.sh.

CLI: ``python -m tools.hlolint facts FILE.hlo`` for ad-hoc inspection,
``python -m tools.hlolint check --contracts ... --facts ...`` for the
gate.  See docs/static_analysis.md ("compiled-program contracts").
"""
from .parser import (HloComputation, HloInstruction, HloModule, Shape,
                     StableHloModule, parse_hlo, parse_stablehlo)
from . import facts
from .facts import (collective_inventory, donation, dtype_census,
                    fact_summary, float_weight_materializations,
                    host_transfers, reduction_accumulators,
                    stablehlo_census, while_fusion_stats)
from .contracts import (RULES, ContractViolation, bootstrap_contracts,
                        evaluate, load_contracts)

__all__ = [
    "HloModule", "HloComputation", "HloInstruction", "Shape",
    "StableHloModule", "parse_hlo", "parse_stablehlo",
    "collective_inventory", "dtype_census", "donation", "host_transfers",
    "while_fusion_stats", "float_weight_materializations",
    "reduction_accumulators", "stablehlo_census", "fact_summary",
    "RULES", "ContractViolation", "load_contracts", "evaluate",
    "bootstrap_contracts",
]
