"""Declarative per-program contracts over hlolint fact summaries.

A contract file (``.hlolint_contracts.json`` at the repo root) pins
what a compiled program is ALLOWED to look like:

.. code-block:: json

    {
      "version": 1,
      "programs": {
        "trainer_full_step_zero_bucketed": {
          "checks": [
            {"rule": "HLO003",
             "expr": "collective_count('reduce-scatter') == ctx['n_buckets']",
             "note": "one reduce-scatter per gradient bucket"},
            {"rule": "HLO004", "expr": "donation_coverage >= 0.9"}
          ]
        }
      },
      "accepted": ["some_legacy_program"]
    }

``expr`` is a python expression evaluated (restricted: no builtins
beyond a safe whitelist, no attribute access on modules) against the
program's fact summary (see :func:`namespace_for`): the raw ``facts``
dict plus flat convenience names (``collective_count(op)``,
``donation_coverage``, ``has_f64``, ``param_bytes``, ...), the gate's
``ctx`` dict (mesh size, bucket count, grad bytes, ...), and
``programs`` — every captured summary by name, for cross-program bounds
like ``param_bytes < 0.75 * programs['decode_float']['entry']['param_bytes']``.

The gate is tpulint-style two-sided: a contracted program FAILS on any
violated check; a program with facts but NO contract is a NEW
un-contracted regression unless listed under ``accepted`` (default
rules HLO001/HLO005 still apply to accepted programs).  Bootstrap a
contract skeleton from live facts with
``ci/hlolint_gate.py --write-contracts``.

Rule catalog (docs/static_analysis.md has the long form):

========  ==========================================================
HLO001    f64 (or c128) appears anywhere in the program
HLO002    float materialization of a quantized/bf16 weight shape
HLO003    collective budget: count/bytes per op vs contract bound
HLO004    donation coverage below bound (donated input not aliased)
HLO005    host-transfer op in a steady-state program
HLO006    reduction accumulating in a sub-f32 float (bf16/f16/f8)
HLO000    un-contracted program (meta-rule for the baseline gate)
========  ==========================================================
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

RULES: Dict[str, str] = {
    "HLO000": "program captured by the gate but has no contract",
    "HLO001": "f64/c128 dtype present in compiled program",
    "HLO002": "float materialization of a quantized weight",
    "HLO003": "collective budget violated",
    "HLO004": "donation coverage below bound",
    "HLO005": "host-transfer op in steady-state program",
    "HLO006": "sub-f32 reduction accumulator",
}

#: checks applied to EVERY captured program, contracted or accepted.
DEFAULT_CHECKS: List[Dict[str, str]] = [
    {"rule": "HLO001", "expr": "not has_f64",
     "note": "f64 doubles bytes and runs at deci-rate on TPU"},
    {"rule": "HLO005", "expr": "host_transfer_count == 0",
     "note": "host round-trips stall the device every step"},
]

_SAFE_BUILTINS = {"abs": abs, "min": min, "max": max, "len": len,
                  "sum": sum, "any": any, "all": all, "round": round,
                  "sorted": sorted, "set": set, "float": float,
                  "int": int, "bool": bool, "True": True,
                  "False": False, "None": None}


@dataclass
class ContractViolation:
    program: str
    rule: str
    expr: str
    note: str = ""
    observed: str = ""

    def render(self) -> str:
        head = f"{self.program}: {self.rule} ({RULES.get(self.rule, '?')})"
        lines = [head, f"    check : {self.expr}"]
        if self.note:
            lines.append(f"    note  : {self.note}")
        if self.observed:
            lines.append(f"    facts : {self.observed}")
        return "\n".join(lines)


def load_contracts(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "programs" not in doc:
        raise ValueError(f"{path}: not a hlolint contract file "
                         "(missing 'programs')")
    return doc


def namespace_for(facts: Dict[str, Any],
                  ctx: Optional[Dict[str, Any]] = None,
                  programs: Optional[Dict[str, Dict]] = None
                  ) -> Dict[str, Any]:
    """The evaluation namespace one program's checks see."""
    coll = facts.get("collectives", {})
    per_op = coll.get("per_op", {})

    def collective_count(op: str) -> int:
        return int(per_op.get(op, {}).get("count", 0))

    def collective_bytes(op: str) -> int:
        return int(per_op.get(op, {}).get("bytes", 0))

    don = facts.get("donation", {})
    weights = facts.get("weights", {})
    ns: Dict[str, Any] = dict(_SAFE_BUILTINS)
    ns.update({
        "facts": facts,
        "ctx": dict(ctx or {}),
        "programs": dict(programs or {}),
        "collectives": coll,
        "collective_count": collective_count,
        "collective_bytes": collective_bytes,
        "total_collective_bytes": int(coll.get("total_bytes", 0)),
        "n_async_collectives": int(coll.get("n_async", 0)),
        "dtypes": facts.get("dtypes", {}).get("dtypes", {}),
        "has_f64": bool(facts.get("dtypes", {}).get("has_f64", False)),
        "sub_f32_accumulators":
            len(facts.get("sub_f32_accumulators", [])),
        "host_transfer_count":
            int(facts.get("host_transfers", {}).get("count", 0)),
        "donation_coverage": don.get("coverage"),
        "donated_inputs": don.get("donated"),
        "aliased_inputs": don.get("aliased"),
        "float_weight_materializations":
            len(weights.get("float_materializations", [])),
        "stablehlo_float_weight_tensors":
            len(facts.get("stablehlo", {}).get("float_weight_tensors", [])),
        "param_bytes": int(facts.get("entry", {}).get("param_bytes", 0)),
        "output_bytes": int(facts.get("entry", {}).get("output_bytes", 0)),
        "n_while": int(facts.get("stats", {}).get("while", 0)),
        "n_fusion": int(facts.get("stats", {}).get("fusion", 0)),
        "num_partitions": int(facts.get("num_partitions", 1)),
    })
    return ns


def _observed(ns: Dict[str, Any], expr: str) -> str:
    """Names from the namespace that appear in the failing expr, with
    their current values — the per-rule diagnostic payload."""
    shown = []
    for name in ("collectives", "donation_coverage", "donated_inputs",
                 "aliased_inputs", "has_f64", "host_transfer_count",
                 "sub_f32_accumulators", "float_weight_materializations",
                 "stablehlo_float_weight_tensors", "param_bytes",
                 "output_bytes", "total_collective_bytes",
                 "n_async_collectives", "n_while", "n_fusion",
                 "num_partitions"):
        if name in expr:
            shown.append(f"{name}={ns.get(name)!r}")
    if "ctx[" in expr or "ctx." in expr:
        shown.append(f"ctx={ns.get('ctx')!r}")
    if "collective_count(" in expr or "collective_bytes(" in expr:
        shown.append(f"per_op={ns.get('collectives', {}).get('per_op')!r}")
    return ", ".join(shown)


def _run_checks(program: str, checks: List[Dict[str, Any]],
                ns: Dict[str, Any]) -> List[ContractViolation]:
    out = []
    for chk in checks:
        expr = chk.get("expr", "")
        rule = chk.get("rule", "HLO003")
        note = chk.get("note", "")
        try:
            ok = bool(eval(expr, {"__builtins__": {}}, ns))  # noqa: S307
        except Exception as exc:  # bad expr IS a violation, not a pass
            out.append(ContractViolation(
                program=program, rule=rule, expr=expr, note=note,
                observed=f"check raised {type(exc).__name__}: {exc}"))
            continue
        if not ok:
            out.append(ContractViolation(
                program=program, rule=rule, expr=expr, note=note,
                observed=_observed(ns, expr)))
    return out


def evaluate(contracts: Dict[str, Any],
             facts_by_program: Dict[str, Dict[str, Any]],
             ctx: Optional[Dict[str, Any]] = None
             ) -> Tuple[List[ContractViolation], List[str]]:
    """Check every captured program against the contract file.

    Returns ``(violations, uncontracted)``: violations from contracted
    programs' checks plus the DEFAULT_CHECKS everyone gets, and the
    names of captured programs with neither a contract nor an
    ``accepted`` entry (the HLO000 baseline half of the gate).
    """
    prog_contracts = contracts.get("programs", {})
    accepted = set(contracts.get("accepted", []))
    violations: List[ContractViolation] = []
    uncontracted: List[str] = []
    for name in sorted(facts_by_program):
        facts = facts_by_program[name]
        ns = namespace_for(facts, ctx=ctx, programs=facts_by_program)
        violations.extend(_run_checks(name, DEFAULT_CHECKS, ns))
        entry = prog_contracts.get(name)
        if entry is None:
            if name not in accepted:
                uncontracted.append(name)
            continue
        violations.extend(_run_checks(name, entry.get("checks", []), ns))
    return violations, uncontracted


def bootstrap_contracts(facts_by_program: Dict[str, Dict[str, Any]],
                        ctx: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, Any]:
    """Generate a contract skeleton from live facts: pins each
    program's current collective counts, donation coverage (when
    known), and weight-materialization cleanliness.  Review and tighten
    before committing — a bootstrap records what IS, a contract should
    say what MUST BE."""
    programs: Dict[str, Any] = {}
    for name in sorted(facts_by_program):
        facts = facts_by_program[name]
        checks: List[Dict[str, str]] = []
        per_op = facts.get("collectives", {}).get("per_op", {})
        for op in sorted(per_op):
            checks.append({
                "rule": "HLO003",
                "expr": f"collective_count({op!r}) == {per_op[op]['count']}",
                "note": "bootstrap: pinned observed count"})
        cov = facts.get("donation", {}).get("coverage")
        if cov is not None:
            bound = 0.9 if cov >= 0.9 else round(cov - 0.05, 2)
            checks.append({"rule": "HLO004",
                           "expr": f"donation_coverage >= {bound}",
                           "note": "bootstrap: donated inputs must alias"})
        if "weights" in facts:
            checks.append({"rule": "HLO002",
                           "expr": "float_weight_materializations == 0",
                           "note": "quantized weights stay quantized"})
        checks.append({"rule": "HLO006",
                       "expr": "sub_f32_accumulators == "
                               f"{len(facts.get('sub_f32_accumulators', []))}",
                       "note": "bootstrap: no NEW sub-f32 accumulators"})
        programs[name] = {"checks": checks}
    return {"version": 1, "programs": programs, "accepted": []}
