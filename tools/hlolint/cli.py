"""hlolint command line.

``python -m tools.hlolint facts step.hlo [--stablehlo step.mlir] ...``
    Parse one program's artifacts and print its fact summary as JSON —
    the ad-hoc inspection path ("what collectives does this program
    actually issue?").

``python -m tools.hlolint check --contracts .hlolint_contracts.json \\
      --facts facts.json [--ctx ctx.json]``
    Evaluate pre-extracted fact summaries (a JSON dict program →
    summary, e.g. dumped by ci/hlolint_gate.py) against a contract
    file.  Exit 1 on any violation or un-contracted program.

The CI gate itself lives in ci/hlolint_gate.py because it must COMPILE
the repo's flagship programs first; this module stays compile-free.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import contracts as _contracts
from . import facts as _facts
from .parser import parse_hlo, parse_stablehlo


def _read(path: str) -> str:
    with open(path, "r", encoding="utf-8") as fh:
        return fh.read()


def _cmd_facts(args: argparse.Namespace) -> int:
    module = parse_hlo(_read(args.hlo))
    smod = parse_stablehlo(_read(args.stablehlo)) if args.stablehlo else None
    axis_order = axis_sizes = None
    if args.mesh:
        # --mesh data=4,model=2 (order as written)
        axis_sizes = {}
        for part in args.mesh.split(","):
            k, _, v = part.partition("=")
            axis_sizes[k.strip()] = int(v)
        axis_order = list(axis_sizes)
    weight_shapes = []
    if args.weight_shapes:
        weight_shapes = [tuple(int(d) for d in w.split("x"))
                         for w in args.weight_shapes.split(",")]
    summary = _facts.fact_summary(module, stablehlo=smod,
                                  axis_order=axis_order,
                                  axis_sizes=axis_sizes,
                                  weight_shapes=weight_shapes)
    json.dump(summary, sys.stdout, indent=2, sort_keys=True)
    sys.stdout.write("\n")
    return 0


def _cmd_check(args: argparse.Namespace) -> int:
    contracts = _contracts.load_contracts(args.contracts)
    with open(args.facts, "r", encoding="utf-8") as fh:
        facts_by_program = json.load(fh)
    ctx = {}
    if args.ctx:
        with open(args.ctx, "r", encoding="utf-8") as fh:
            ctx = json.load(fh)
    violations, uncontracted = _contracts.evaluate(
        contracts, facts_by_program, ctx=ctx)
    for v in violations:
        print(v.render())
    for name in uncontracted:
        print(f"{name}: HLO000 ({_contracts.RULES['HLO000']}) — add a "
              "contract under 'programs' or list it under 'accepted'")
    n = len(violations) + len(uncontracted)
    print(f"hlolint: {len(facts_by_program)} program(s), "
          f"{len(violations)} violation(s), "
          f"{len(uncontracted)} un-contracted")
    return 1 if n else 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hlolint",
        description="compiled-program contract checker over HLO/StableHLO")
    sub = ap.add_subparsers(dest="cmd", required=True)

    fp = sub.add_parser("facts", help="print one program's fact summary")
    fp.add_argument("hlo", help="compiled/optimized HLO text file")
    fp.add_argument("--stablehlo", help="lowered StableHLO (MLIR) file")
    fp.add_argument("--mesh", help="mesh axes, e.g. data=4,model=2")
    fp.add_argument("--weight-shapes",
                    help="quantized weight shapes, e.g. 96x32,32x96")
    fp.set_defaults(func=_cmd_facts)

    cp = sub.add_parser("check", help="evaluate contracts against facts")
    cp.add_argument("--contracts", required=True)
    cp.add_argument("--facts", required=True,
                    help="JSON dict: program name -> fact summary")
    cp.add_argument("--ctx", help="JSON dict of contract context values")
    cp.set_defaults(func=_cmd_check)

    args = ap.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
