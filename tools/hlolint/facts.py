"""Fact extractors over the hlolint module IR.

Every extractor is a pure function of the parsed :class:`~.parser.HloModule`
(plus, where stated, the lowered StableHLO view) returning JSON-able
dicts — the currency the contract checker (:mod:`.contracts`), the CI
gate (ci/hlolint_gate.py), bench.py's ``detail.hlo_facts``, and the
dryrun gates all trade in:

* :func:`collective_inventory` — count + result bytes per collective
  op, and per mesh axis via replica-group factorization against the
  active mesh (the structured descendant of ``__graft_entry__``'s
  ``_collective_axis_stats``);
* :func:`dtype_census` — result-buffer counts/bytes per dtype, the f64
  flag;
* :func:`reduction_accumulators` — reductions whose accumulator is a
  sub-f32 float (bf16/f16/f8) — silent precision loss on TPU;
* :func:`host_transfers` — infeed/outfeed/send/recv and host-callback
  custom-calls (steady-state programs should have none);
* :func:`donation` — donated-argument count (StableHLO markers) vs
  inputs the compiled module actually aliases to outputs;
* :func:`while_fusion_stats` — control-flow/fusion shape of the program;
* :func:`float_weight_materializations` — float buffers shaped like a
  declared quantized weight (the int8-decode "no bf16 copy" gate);
* :func:`fact_summary` — all of the above in one dict.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .parser import (COLLECTIVE_OPS, HloInstruction, HloModule,
                     StableHloModule)

__all__ = ["collective_inventory", "dtype_census",
           "reduction_accumulators", "host_transfers", "donation",
           "while_fusion_stats", "float_weight_materializations",
           "stablehlo_census", "fact_summary"]

_SUB_F32_FLOATS = frozenset({"bf16", "f16", "f8e4m3fn", "f8e5m2",
                             "f8e4m3", "f8e3m4", "f8e4m3b11fnuz",
                             "f8e4m3fnuz", "f8e5m2fnuz"})
_REDUCE_OPS = frozenset({"reduce", "reduce-window", "all-reduce",
                         "reduce-scatter", "all-reduce-start",
                         "reduce-scatter-start"})
_HOST_OPS = frozenset({"infeed", "outfeed", "send", "recv",
                       "send-done", "recv-done"})
# SPMD plumbing custom-calls that are NOT host transfers
_BENIGN_CUSTOM_CALLS = ("Sharding", "SPMDFullToShardShape",
                        "SPMDShardToFullShape", "AllocateBuffer")


def _base_opcode(op: str) -> str:
    for suf in ("-start", "-done"):
        if op.endswith(suf):
            return op[:-len(suf)]
    return op


# ------------------------------------------------------------------ #
# collectives
# ------------------------------------------------------------------ #
def _axes_of(ins: HloInstruction, axis_order: Sequence[str],
             axis_sizes: Dict[str, int], num_devices: int) -> List[str]:
    """Mesh axes one collective spans: factorize its replica-group
    membership (or permute neighbor strides) against per-axis device
    strides — axis ``a`` participates iff stepping by ``stride[a]``
    stays inside the group."""
    strides = {}
    s = 1
    for a in reversed(list(axis_order)):
        strides[a] = s
        s *= axis_sizes[a]
    live = [a for a in axis_order if axis_sizes[a] > 1]
    pairs = ins.attrs.get("source_target_pairs")
    if pairs:
        # a permute's axis: the one whose stride equals the smallest
        # |target - source| (wrap-around pairs jump stride*(size-1))
        steps = [abs(b - a_) for a_, b in pairs if b != a_]
        if not steps:
            return []
        step = min(steps)
        return [a for a in live if strides[a] == step]
    groups = ins.replica_group_members(num_devices)
    if not groups:
        return []
    g = set(groups[0])
    if not g:               # unresolved all-device group
        return live
    lo = min(g)
    return [a for a in live if lo + strides[a] in g]


def collective_inventory(module: HloModule,
                         axis_order: Optional[Sequence[str]] = None,
                         axis_sizes: Optional[Dict[str, int]] = None
                         ) -> Dict:
    """Per-program collective inventory.

    Returns ``{"per_op": {op: {count, bytes}}, "per_axis":
    {"op[axisA+axisB]": {count, bytes}}, "total_bytes", "n_async"}``.
    Bytes are the collective's RESULT bytes (the async ``-start`` form
    counts once; its ``-done`` half is skipped).  ``per_axis`` needs the
    active mesh (`axis_order` + `axis_sizes`); without it only
    ``per_op`` is attributed.
    """
    ndev = max(module.num_partitions, module.replica_count)
    per_op: Dict[str, Dict[str, int]] = {}
    per_axis: Dict[str, Dict[str, int]] = {}
    n_async = 0
    total = 0
    for ins in module.collectives():
        op = _base_opcode(ins.opcode)
        if ins.opcode.endswith("-start"):
            n_async += 1
            # the start op's result is (operand, result[, scratch]) on
            # some backends: take the LAST array shape as the payload
            arrays = [sh for sh in ins.shapes if sh.dtype != "token"]
            b = arrays[-1].nbytes if arrays else 0
        else:
            b = ins.result_bytes
        ent = per_op.setdefault(op, {"count": 0, "bytes": 0})
        ent["count"] += 1
        ent["bytes"] += b
        total += b
        if axis_order is not None and axis_sizes is not None:
            axes = _axes_of(ins, axis_order, axis_sizes, ndev)
            key = f"{op}[{'+'.join(axes) if axes else '?'}]"
            ent = per_axis.setdefault(key, {"count": 0, "bytes": 0})
            ent["count"] += 1
            ent["bytes"] += b
    out = {"per_op": per_op, "total_bytes": total, "n_async": n_async}
    if axis_order is not None and axis_sizes is not None:
        out["per_axis"] = per_axis
    return out


# ------------------------------------------------------------------ #
# dtypes
# ------------------------------------------------------------------ #
def dtype_census(module: HloModule) -> Dict:
    """Result-buffer census per dtype over every computation:
    ``{"dtypes": {dt: {count, bytes}}, "has_f64": bool}``."""
    dts: Dict[str, Dict[str, int]] = {}
    for ins in module.all_instructions():
        for sh in ins.shapes:
            if sh.dtype == "token":
                continue
            ent = dts.setdefault(sh.dtype, {"count": 0, "bytes": 0})
            ent["count"] += 1
            ent["bytes"] += sh.nbytes
    return {"dtypes": dts, "has_f64": "f64" in dts or "c128" in dts}


def reduction_accumulators(module: HloModule) -> List[Dict]:
    """Reductions accumulating in a sub-f32 float: each reduce-family
    instruction whose result element type is bf16/f16/f8.  (f32 and
    integer accumulators are fine; a bf16 accumulator silently loses
    mantissa on every partial sum.)"""
    out = []
    for comp in module.computations.values():
        for ins in comp.instructions:
            if ins.opcode not in _REDUCE_OPS:
                continue
            for sh in ins.shapes:
                if sh.dtype in _SUB_F32_FLOATS:
                    out.append({"instruction": ins.name,
                                "opcode": ins.opcode,
                                "computation": comp.name,
                                "dtype": sh.dtype})
                    break
    return out


# ------------------------------------------------------------------ #
# host transfers
# ------------------------------------------------------------------ #
def host_transfers(module: HloModule) -> Dict:
    """Ops that move data to/from the host: infeed/outfeed/send/recv
    plus custom-calls whose target smells like a host callback.  A
    steady-state training or decode program should have none."""
    ops = []
    for comp in module.computations.values():
        for ins in comp.instructions:
            if ins.opcode in _HOST_OPS:
                ops.append({"instruction": ins.name, "opcode": ins.opcode,
                            "computation": comp.name})
            elif ins.opcode == "custom-call":
                tgt = str(ins.attrs.get("custom_call_target", ""))
                if tgt in _BENIGN_CUSTOM_CALLS:
                    continue
                if "callback" in tgt.lower() or "host" in tgt.lower():
                    ops.append({"instruction": ins.name,
                                "opcode": f"custom-call:{tgt}",
                                "computation": comp.name})
    return {"count": len(ops), "ops": ops}


# ------------------------------------------------------------------ #
# donation
# ------------------------------------------------------------------ #
def donation(module: HloModule,
             stablehlo: Optional[StableHloModule] = None) -> Dict:
    """Donation coverage: of the inputs jax was ASKED to donate (the
    ``jax.buffer_donor``/``tf.aliasing_output`` markers in the lowered
    StableHLO), how many the compiled module actually aliases to an
    output (``input_output_alias`` header).  A donated-but-unaliased
    input is a silent extra copy of that buffer every step.

    Without the StableHLO view the donated count is unknown and
    ``coverage`` is None (the aliased count still reports).
    """
    aliased_params = sorted({p for (_o, p, _pi, _k)
                             in module.input_output_alias})
    out = {"aliased": len(aliased_params),
           "aliased_params": aliased_params,
           "donated": None, "coverage": None}
    if stablehlo is not None:
        donors = stablehlo.donated_args
        out["donated"] = len(donors)
        if donors:
            covered = sum(1 for d in donors if d in aliased_params)
            out["coverage"] = covered / len(donors)
        elif not aliased_params:
            out["coverage"] = None      # nothing donated, nothing owed
    return out


# ------------------------------------------------------------------ #
# control flow / fusion shape
# ------------------------------------------------------------------ #
def while_fusion_stats(module: HloModule) -> Dict:
    n_while = n_fusion = n_instr = 0
    max_fusion = 0
    for comp in module.computations.values():
        n_instr += len(comp.instructions)
        if comp.is_fusion:
            max_fusion = max(max_fusion, len(comp.instructions))
        for ins in comp.instructions:
            if ins.opcode == "while":
                n_while += 1
            elif ins.opcode == "fusion":
                n_fusion += 1
    return {"while": n_while, "fusion": n_fusion,
            "computations": len(module.computations),
            "instructions": n_instr,
            "max_fusion_instructions": max_fusion}


# ------------------------------------------------------------------ #
# weight materialization (the int8-decode gate)
# ------------------------------------------------------------------ #
def float_weight_materializations(
        module: HloModule,
        weight_shapes: Iterable[Tuple[int, ...]],
        float_dtypes: Sequence[str] = ("bf16",)) -> List[Dict]:
    """Instructions producing a float buffer shaped like a declared
    quantized weight — either orientation of each (O, I) shape.  Any
    hit means the dequant was hoisted out of the matmul epilogue and
    the program streams a float copy of a weight it was supposed to
    keep int8."""
    want = set()
    for dims in weight_shapes:
        dims = tuple(dims)
        want.add(dims)
        want.add(tuple(reversed(dims)))
    hits = []
    fd = set(float_dtypes)
    for comp in module.computations.values():
        for ins in comp.instructions:
            for sh in ins.shapes:
                if sh.dtype in fd and sh.dims in want:
                    hits.append({"instruction": ins.name,
                                 "opcode": ins.opcode,
                                 "computation": comp.name,
                                 "dtype": sh.dtype,
                                 "shape": list(sh.dims)})
                    break
    return hits


def stablehlo_census(smod: StableHloModule,
                     weight_shapes: Iterable[Tuple[int, ...]] = (),
                     float_dtypes: Sequence[str] = ("f32", "bf16", "f16")
                     ) -> Dict:
    """StableHLO-side census: per-dtype tensor-token counts plus any
    float tensor shaped like a declared weight (the dynamic-activation
    decode gate: dequant must act on the activation, never the
    weight)."""
    want = set()
    for dims in weight_shapes:
        dims = tuple(dims)
        want.add(dims)
        want.add(tuple(reversed(dims)))
    fd = set(float_dtypes)
    float_weights = sorted(
        {repr(sh) for sh in smod.types
         if sh.dtype in fd and sh.dims in want})
    return {"dtypes": smod.dtypes(),
            "float_weight_tensors": float_weights}


# ------------------------------------------------------------------ #
# the one-call summary
# ------------------------------------------------------------------ #
def fact_summary(module: HloModule,
                 stablehlo: Optional[StableHloModule] = None,
                 axis_order: Optional[Sequence[str]] = None,
                 axis_sizes: Optional[Dict[str, int]] = None,
                 weight_shapes: Iterable[Tuple[int, ...]] = (),
                 weight_float_dtypes: Sequence[str] = ("bf16",)) -> Dict:
    """Everything hlolint knows about one program, as one JSON-able
    dict — the object contracts evaluate against and bench.py records
    under ``detail.hlo_facts``."""
    entry = module.entry
    entry_params = entry.parameters() if entry else []
    root = entry.root if entry else None
    weight_shapes = [tuple(w) for w in weight_shapes]
    out = {
        "module": module.name,
        "is_scheduled": module.is_scheduled,
        "num_partitions": module.num_partitions,
        "collectives": collective_inventory(module, axis_order, axis_sizes),
        "dtypes": dtype_census(module),
        "sub_f32_accumulators": reduction_accumulators(module),
        "host_transfers": host_transfers(module),
        "donation": donation(module, stablehlo),
        "stats": while_fusion_stats(module),
        "entry": {
            "n_params": len(entry_params),
            "param_bytes": sum(i.result_bytes for i in entry_params),
            "output_bytes": root.result_bytes if root else 0,
        },
    }
    if weight_shapes:
        out["weights"] = {
            "shapes": [list(w) for w in weight_shapes],
            "float_materializations": float_weight_materializations(
                module, weight_shapes, weight_float_dtypes),
        }
    if stablehlo is not None:
        out["stablehlo"] = stablehlo_census(smod=stablehlo,
                                            weight_shapes=weight_shapes)
    return out
