#!/usr/bin/env python
"""parse_log — scrape speed/accuracy from training logs (ref
`tools/parse_log.py`, SURVEY.md §2.8).  Understands the Speedometer
line format this framework's `callback.Speedometer` prints:

  Epoch[3] Batch [200]\tSpeed: 1234.56 samples/sec\taccuracy=0.987

Run: python tools/parse_log.py train.log [--format json|md]
"""
from __future__ import annotations

import argparse
import json
import re
import sys

_LINE = re.compile(
    r"Epoch\[(\d+)\]\s+Batch\s*\[(\d+)\].*?Speed:\s*([\d.]+)\s*samples/sec"
    r"(.*)$")
_METRIC = re.compile(r"([\w-]+)=([\d.eE+-]+)")
_VAL = re.compile(r"Epoch\[(\d+)\]\s+(Train|Validation)-([\w-]+)=([\d.eE+-]+)")


def parse(lines):
    rows = []
    epochs = {}
    for line in lines:
        m = _LINE.search(line)
        if m:
            metrics = {k: float(v) for k, v in _METRIC.findall(m.group(4))}
            rows.append({"epoch": int(m.group(1)), "batch": int(m.group(2)),
                         "speed": float(m.group(3)), **metrics})
            continue
        v = _VAL.search(line)
        if v:
            ep = int(v.group(1))
            key = f"{v.group(2).lower()}-{v.group(3)}"
            epochs.setdefault(ep, {"epoch": ep})[key] = float(v.group(4))
    summary = []
    for ep in sorted({r["epoch"] for r in rows} | set(epochs)):
        ep_rows = [r for r in rows if r["epoch"] == ep]
        entry = dict(epochs.get(ep, {"epoch": ep}))
        if ep_rows:
            entry["mean_speed"] = sum(r["speed"] for r in ep_rows) / len(ep_rows)
        summary.append(entry)
    return {"batches": rows, "epochs": summary}


def main(argv=None):
    p = argparse.ArgumentParser(description="training log parser")
    p.add_argument("logfile")
    p.add_argument("--format", choices=["json", "md"], default="json")
    args = p.parse_args(argv)
    with open(args.logfile) as f:
        res = parse(f)
    if args.format == "json":
        print(json.dumps(res["epochs"], indent=2))
    else:
        keys = sorted({k for e in res["epochs"] for k in e})
        print("| " + " | ".join(keys) + " |")
        print("|" + "---|" * len(keys))
        for e in res["epochs"]:
            print("| " + " | ".join(str(e.get(k, "")) for k in keys) + " |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
