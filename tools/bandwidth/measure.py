#!/usr/bin/env python
"""Collective bandwidth measurement (ref `tools/bandwidth/measure.py`,
SURVEY.md §2.8): times allreduce (psum) across the device mesh over a
sweep of tensor sizes and reports achieved GB/s — ICI on a real slice,
host rings on the virtual CPU mesh.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/bandwidth/measure.py --sizes 1,8,64 --devices 8
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def measure(sizes_mb, n_devices=None, runs=5):
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    import incubator_mxnet_tpu.parallel as par

    n = n_devices or len(jax.devices())
    mesh = par.create_mesh(data=n)

    results = []
    for mb in sizes_mb:
        n_elem = int(mb * 1024 * 1024 / 4)
        n_elem = max(n, n_elem - n_elem % n)
        x = jnp.ones((n_elem,), jnp.float32)

        fn = jax.jit(shard_map(lambda xs: jax.lax.psum(xs, "data"),
                               mesh=mesh, in_specs=P("data"),
                               out_specs=P("data")))
        r = fn(x)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(runs):
            r = fn(x)
        float(jnp.sum(r))  # value fetch: real sync
        dt = (time.perf_counter() - t0) / runs
        # per-device shard is x.size/n; ring allreduce moves 2*(n-1)/n
        # of THAT buffer per device
        gbytes = (x.size / n) * 4 * 2 * (n - 1) / n / 1e9
        results.append({"size_mb": mb, "time_ms": round(dt * 1e3, 3),
                        "GBps": round(gbytes / dt, 3)})
        print(results[-1])
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description="allreduce bandwidth sweep")
    p.add_argument("--sizes", type=str, default="1,4,16,64",
                   help="comma-separated MB sizes")
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--runs", type=int, default=5)
    args = p.parse_args(argv)
    measure([float(s) for s in args.sizes.split(",")], args.devices, args.runs)
    return 0


if __name__ == "__main__":
    sys.exit(main())
