"""C++ image-pipeline thread-scaling measurement (r4 VERDICT item 9).

Writes a synthetic JPEG .rec, then measures decode+augment throughput of
`native/image_pipeline.cc` (via io.ImageRecordIter) at preprocess
threads = 1, 2, 4, 8.

On a multi-core TPU host the aggregate should scale ~linearly until the
cores run out; on THIS sandbox's single CPU core, linear scaling is
physically impossible — what the run proves instead is that adding
workers does not COLLAPSE aggregate throughput (no lock contention /
queue serialization in the pipeline), which is the software property
the scaling claim rests on.

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        PYTHONPATH=. python tools/bandwidth/pipeline_scaling.py
"""
import argparse
import json
import os
import time

import numpy as onp


def make_rec(path: str, n: int, hw: int = 224, quality: int = 90) -> None:
    from incubator_mxnet_tpu import recordio as rio

    rng = onp.random.RandomState(0)
    # a handful of distinct source images re-packed n times keeps rec
    # generation fast while every record still JPEG-decodes fully
    srcs = [rng.randint(0, 255, (hw, hw, 3), dtype=onp.uint8)
            for _ in range(8)]
    payloads = [rio.pack_img(rio.IRHeader(0, float(i % 10), i, 0),
                             srcs[i % len(srcs)], quality=quality)
                for i in range(len(srcs))]
    w = rio.MXRecordIO(path, "w")
    for i in range(n):
        w.write(payloads[i % len(payloads)])
    w.close()


def measure(rec: str, threads: int, batch: int = 64,
            warm_batches: int = 2, timed_batches: int = 12) -> float:
    from incubator_mxnet_tpu import io as mxio

    it = mxio.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 224, 224), batch_size=batch,
        preprocess_threads=threads, shuffle=False, device=False)
    n = 0
    for _ in range(warm_batches):
        next(it)
    t0 = time.perf_counter()
    for _ in range(timed_batches):
        b = next(it)
        n += batch
    # touch the data so lazy work can't escape the timer
    onp.asarray(b.data[0].asnumpy()).ravel()[0]
    dt = time.perf_counter() - t0
    return n / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--records", type=int, default=2048)
    args = ap.parse_args()
    rec = "/tmp/pipeline_scaling.rec"
    if not os.path.exists(rec):
        make_rec(rec, args.records)
    rows = []
    for threads in (1, 2, 4, 8):
        ips = measure(rec, threads)
        rows.append({"threads": threads, "images_per_s": round(ips, 1)})
        print(f"threads={threads}: {ips:,.1f} img/s")
    ncores = os.cpu_count()
    result = {"host_cores": ncores, "rows": rows}
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
