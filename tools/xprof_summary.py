"""Summarize a device trace: per-op and per-category tables.

Usage:
    python tools/xprof_summary.py TRACE_DIR [--top N] [--module SUBSTR]

TRACE_DIR is a directory written by `mx.profiler.start()` /
`jax.profiler.trace` (the one containing plugins/profile/...), or a
single .xplane.pb file.  With --module, restricts to ops inside the
LAST execution of the first XLA module whose name contains SUBSTR
(e.g. --module jit_train_step isolates one steady-state step).

This is the per-operator view the reference's `profiler.dumps`
aggregate table gave (src/profiler/profiler.cc): under XLA a train
step is ONE fused program, so op attribution must come from the
device trace — decoded by utils/xplane.py, no tensorboard required.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from incubator_mxnet_tpu.utils import xplane


def _device_events(path, substr=None, device_substr="TPU"):
    """Raw XLA-Op events from every device plane; with `substr`,
    restricted to the last execution window of the matching XLA module
    (the steady-state-step view)."""
    if os.path.isdir(path):
        paths = xplane.latest_run_files(path)  # device_op_table's rule
    else:
        paths = [path]
    planes = [p for f in paths for p in xplane.parse_xspace(f)
              if device_substr in p.name]
    if not planes:
        raise RuntimeError("no device plane in trace")
    events = []
    for plane in planes:
        lines = {l.name: l for l in plane.lines}
        opsl = lines.get("XLA Ops")
        if not opsl:
            continue
        if substr is None:
            events += opsl.events
            continue
        mods = lines.get("XLA Modules")
        if not mods:
            continue
        cand = [e for e in mods.events if substr in e.name]
        if not cand:
            continue
        last = max(cand, key=lambda e: e.offset_ps)
        w0, w1 = last.offset_ps, last.offset_ps + last.duration_ps
        events += [ev for ev in opsl.events if w0 <= ev.offset_ps < w1]
    return events


def module_window_rows(path, substr, device_substr="TPU"):
    """Rows restricted to the last execution window of the matching
    XLA module — the steady-state-step view."""
    # collect every plane's window events first, aggregate ONCE — so a
    # multi-host run dir yields one merged row per op, same as
    # device_op_table, not one fractional row per host file
    return xplane.aggregate_events(
        _device_events(path, substr, device_substr))  # sorted by -total_us


# ---------------------------------------------------------------------------
# exposed-vs-hidden collective time (the trace-measured counterpart of
# parallel/overlap.py's schedule_overlap_stats)
# ---------------------------------------------------------------------------

_COLLECTIVE_BASES = ("all-reduce", "reduce-scatter", "all-gather",
                     "all-to-all", "collective-permute")
# what counts as useful work a collective can hide behind; mirrors
# overlap.py's _COMPUTE_KINDS so schedule- and trace-measured fractions
# agree on the denominator's meaning
_COMPUTE_BASES = ("fusion", "dot", "convolution", "custom-call")


def _base(name):
    """`%all-reduce-start.3` -> (`all-reduce`, `start`, `.3`)."""
    n = name.lstrip("%").split("(")[0]
    head, _, suffix = n.partition(".")
    for tag in ("start", "done"):
        if head.endswith("-" + tag):
            return head[: -len(tag) - 1], tag, suffix
    return head, None, suffix


def collective_overlap_from_events(events):
    """Exposed-vs-hidden communication time from trace events.

    Async collectives appear as `<op>-start.N` / `<op>-done.N` pairs;
    the wire transfer spans [start.begin, done.end].  Pairs are matched
    by suffix when both sides carry one, else by time order within the
    op kind (start i with the i-th done beginning after it).  Sync
    collectives occupy their own interval.  A picosecond of collective
    time is *hidden* iff some compute op (fusion/dot/convolution/
    custom-call) is executing at that instant; the rest is *exposed* —
    time the step genuinely stalls on the network.

    Returns {n_collectives, comm_seconds, exposed_seconds,
    hidden_seconds, overlap_fraction, per_collective: [{name, seconds,
    hidden_seconds}]}.  Pure over (name, offset_ps, duration_ps) — no
    trace file or jax dependency, so it is unit-testable with synthetic
    events.
    """
    starts, dones, comm, compute = {}, {}, [], []
    for ev in events:
        base, tag, suffix = _base(ev.name)
        t0, t1 = ev.offset_ps, ev.offset_ps + ev.duration_ps
        if base in _COLLECTIVE_BASES:
            if tag == "start":
                starts.setdefault(base, []).append((t0, t1, suffix, ev.name))
            elif tag == "done":
                dones.setdefault(base, []).append((t0, t1, suffix, ev.name))
            else:
                comm.append((ev.name, t0, t1))
        elif base in _COMPUTE_BASES:
            compute.append((t0, t1))
    for base, ss in starts.items():
        dd = sorted(dones.get(base, []))
        by_suffix = {d[2]: d for d in dd if d[2]}
        used = set()
        for s in sorted(ss):
            d = by_suffix.get(s[2]) if s[2] else None
            if d is None or id(d) in used:
                # fall back: earliest unused done beginning at/after the
                # start (the runtime never retires a transfer early)
                d = next((c for c in dd
                          if id(c) not in used and c[0] >= s[0]), None)
            if d is None:
                comm.append((s[3], s[0], s[1]))  # unmatched start: sync-like
                continue
            used.add(id(d))
            comm.append((s[3], s[0], d[1]))
    # merge compute into disjoint intervals once; each comm interval is
    # then measured against the union (concurrent collectives are each
    # attributed in full — the question is per-collective exposure)
    merged = []
    for t0, t1 in sorted(compute):
        if merged and t0 <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], t1)
        else:
            merged.append([t0, t1])

    def _hidden_ps(t0, t1):
        h = 0
        for c0, c1 in merged:
            if c0 >= t1:
                break
            h += max(0, min(t1, c1) - max(t0, c0))
        return h

    per, tot, hid = [], 0, 0
    for name, t0, t1 in sorted(comm, key=lambda c: c[1]):
        h = _hidden_ps(t0, t1)
        per.append({"name": name, "seconds": (t1 - t0) / 1e12,
                    "hidden_seconds": h / 1e12})
        tot += t1 - t0
        hid += h
    return {
        "n_collectives": len(per),
        "comm_seconds": tot / 1e12,
        "exposed_seconds": (tot - hid) / 1e12,
        "hidden_seconds": hid / 1e12,
        "overlap_fraction": (hid / tot) if tot else 0.0,
        "per_collective": per,
    }


def print_overlap_report(stats, record=False):
    print(f"== collective overlap ({stats['n_collectives']} collectives, "
          f"{stats['comm_seconds']*1e3:.3f} ms comm) ==")
    print(f"  exposed {stats['exposed_seconds']*1e3:9.3f} ms   "
          f"hidden {stats['hidden_seconds']*1e3:9.3f} ms   "
          f"overlap_fraction {stats['overlap_fraction']:.2f}")
    for p in stats["per_collective"][:20]:
        frac = p["hidden_seconds"] / p["seconds"] if p["seconds"] else 0.0
        print(f"  {p['seconds']*1e3:9.3f} ms  {frac*100:5.1f}% hidden"
              f"  {p['name']}")
    if record:
        from incubator_mxnet_tpu import telemetry

        telemetry.record_collective_overlap(
            stats["exposed_seconds"], stats["hidden_seconds"],
            source="trace")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--module", default=None,
                    help="restrict to the last run of this XLA module")
    ap.add_argument("--overlap", action="store_true",
                    help="attribute exposed-vs-hidden collective time "
                         "(async start/done pair matching)")
    args = ap.parse_args()

    if args.module:
        rows = module_window_rows(args.trace, args.module)
    else:
        rows = xplane.device_op_table(args.trace)

    if args.overlap:
        print_overlap_report(collective_overlap_from_events(
            _device_events(args.trace, args.module)))

    total = sum(r["total_us"] for r in rows)
    print(f"== categories (total {total/1e3:.2f} ms device time) ==")
    for c in xplane.category_summary(rows)[:15]:
        flops = sum(r["flops"] for r in rows if r["category"] == c["category"])
        d = c["total_us"] / 1e6
        tf = flops / d / 1e12 if d else 0.0
        print(f"  {c['total_us']/1e3:9.3f} ms  {c['total_us']/total*100:5.1f}%"
              f"  x{c['occurrences']:6d}  {tf:6.1f} TF/s  {c['category']}")
    print(f"== top {args.top} ops ==")
    print(xplane.dump_table(rows, top=args.top))


if __name__ == "__main__":
    main()
