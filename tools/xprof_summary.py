"""Summarize a device trace: per-op and per-category tables.

Usage:
    python tools/xprof_summary.py TRACE_DIR [--top N] [--module SUBSTR]

TRACE_DIR is a directory written by `mx.profiler.start()` /
`jax.profiler.trace` (the one containing plugins/profile/...), or a
single .xplane.pb file.  With --module, restricts to ops inside the
LAST execution of the first XLA module whose name contains SUBSTR
(e.g. --module jit_train_step isolates one steady-state step).

This is the per-operator view the reference's `profiler.dumps`
aggregate table gave (src/profiler/profiler.cc): under XLA a train
step is ONE fused program, so op attribution must come from the
device trace — decoded by utils/xplane.py, no tensorboard required.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from incubator_mxnet_tpu.utils import xplane


def module_window_rows(path, substr, device_substr="TPU"):
    """Rows restricted to the last execution window of the matching
    XLA module — the steady-state-step view."""
    if os.path.isdir(path):
        paths = xplane.latest_run_files(path)  # device_op_table's rule
    else:
        paths = [path]
    planes = [p for f in paths for p in xplane.parse_xspace(f)
              if device_substr in p.name]
    if not planes:
        raise RuntimeError("no device plane in trace")
    # collect every plane's window events first, aggregate ONCE — so a
    # multi-host run dir yields one merged row per op, same as
    # device_op_table, not one fractional row per host file
    window_events = []
    for plane in planes:
        lines = {l.name: l for l in plane.lines}
        mods = lines.get("XLA Modules")
        opsl = lines.get("XLA Ops")
        if not mods or not opsl:
            continue
        cand = [e for e in mods.events if substr in e.name]
        if not cand:
            continue
        last = max(cand, key=lambda e: e.offset_ps)
        w0, w1 = last.offset_ps, last.offset_ps + last.duration_ps
        window_events += [ev for ev in opsl.events
                          if w0 <= ev.offset_ps < w1]
    return xplane.aggregate_events(window_events)  # sorted by -total_us


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--module", default=None,
                    help="restrict to the last run of this XLA module")
    args = ap.parse_args()

    if args.module:
        rows = module_window_rows(args.trace, args.module)
    else:
        rows = xplane.device_op_table(args.trace)

    total = sum(r["total_us"] for r in rows)
    print(f"== categories (total {total/1e3:.2f} ms device time) ==")
    for c in xplane.category_summary(rows)[:15]:
        flops = sum(r["flops"] for r in rows if r["category"] == c["category"])
        d = c["total_us"] / 1e6
        tf = flops / d / 1e12 if d else 0.0
        print(f"  {c['total_us']/1e3:9.3f} ms  {c['total_us']/total*100:5.1f}%"
              f"  x{c['occurrences']:6d}  {tf:6.1f} TF/s  {c['category']}")
    print(f"== top {args.top} ops ==")
    print(xplane.dump_table(rows, top=args.top))


if __name__ == "__main__":
    main()
