#!/usr/bin/env python
"""Distributed job launcher — `tools/launch.py` parity.

TPU-native rendition of the reference `tools/launch.py` + dmlc tracker
(SURVEY.md §2.8, §3.5): instead of spawning scheduler + parameter
servers + workers over ssh/mpi/yarn, SPMD training needs exactly N
identical worker processes rendezvousing at a coordinator
(`jax.distributed.initialize`).

Launch modes (`--launcher`):
  local  — spawn N worker processes on THIS machine (the reference's
           `--launcher local` CI pattern: "an N-worker cluster on one
           machine", how the dist kvstore tests run without a cluster).
           Workers are pinned to the CPU backend so they don't fight
           over an accelerator.
  env    — emit the environment for externally-orchestrated workers
           (GKE/GCE/slurm): print per-worker env assignments and exit.

Worker-side contract (read by `parallel.collectives` /
`kvstore.create('dist_sync')`):
  MXTPU_COORDINATOR   host:port of process 0
  MXTPU_NUM_PROCESSES N
  MXTPU_PROCESS_ID    0..N-1
(the dmlc DMLC_PS_ROOT_URI / DMLC_NUM_WORKER / DMLC_WORKER_ID
equivalents; those names are also exported for script compat.)

Usage:
  python tools/launch.py -n 3 --launcher local python train.py --kv-store dist_sync
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def build_parser():
    p = argparse.ArgumentParser(
        description="launch a distributed training job",
        usage="launch.py [-h] -n NUM_WORKERS [--launcher {local,env}] command ...")
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("--launcher", type=str, default="local",
                   choices=["local", "env"])
    p.add_argument("--coordinator-port", type=int, default=0,
                   help="port for process 0 (0 = pick a free port)")
    p.add_argument("--coordinator-host", type=str, default=None,
                   help="routable host of process 0 (env mode; default: "
                        "this machine's hostname)")
    p.add_argument("--env-keys", type=str, default="",
                   help="comma-separated extra env vars to forward")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="worker command, e.g. python train.py ...")
    return p


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker_env(rank: int, n: int, coordinator: str, base=None) -> dict:
    env = dict(base if base is not None else os.environ)
    env["MXTPU_COORDINATOR"] = coordinator
    env["MXTPU_NUM_PROCESSES"] = str(n)
    env["MXTPU_PROCESS_ID"] = str(rank)
    # dmlc-compatible names for scripts that read the reference's vars
    env["DMLC_PS_ROOT_URI"] = coordinator.split(":")[0]
    env["DMLC_PS_ROOT_PORT"] = coordinator.split(":")[1]
    env["DMLC_NUM_WORKER"] = str(n)
    env["DMLC_WORKER_ID"] = str(rank)
    env["DMLC_ROLE"] = "worker"
    return env


def launch_local(n: int, command, coordinator_port: int = 0) -> int:
    port = coordinator_port or _free_port()
    coordinator = f"127.0.0.1:{port}"
    procs = []
    for rank in range(n):
        env = worker_env(rank, n, coordinator)
        # local mode = CI pattern: CPU backend, keep off the accelerator
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("JAX_PLATFORM_NAME", None)
        for k in list(env):
            if k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
                env.pop(k)
        procs.append(subprocess.Popen(command, env=env))

    rc = 0
    try:
        for p in procs:
            p.wait()
            rc = rc or p.returncode
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        rc = 1
    return rc


def main(argv=None):
    args = build_parser().parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("launch.py: no worker command given", file=sys.stderr)
        return 2
    if args.launcher == "env":
        port = args.coordinator_port or _free_port()
        # externally-orchestrated workers live on OTHER machines: the
        # coordinator address must be routable, not loopback
        host = args.coordinator_host or socket.getfqdn()
        coordinator = f"{host}:{port}"
        for rank in range(args.num_workers):
            env = worker_env(rank, args.num_workers, coordinator, base={})
            assigns = " ".join(f"{k}={v}" for k, v in sorted(env.items()))
            print(f"# worker {rank}\n{assigns} {' '.join(command)}")
        return 0
    return launch_local(args.num_workers, command, args.coordinator_port)


if __name__ == "__main__":
    sys.exit(main())
