#!/usr/bin/env python
"""im2rec — image dataset → RecordIO packer (ref `tools/im2rec.py`,
SURVEY.md §2.8).

Two modes, reference parity (args: PREFIX-or-LST first, ROOT second):
  list mode:  --list --recursive prefix root → prefix.lst (idx\tlabel\tpath)
  pack mode:  prefix.lst root → prefix.rec (+ prefix.idx)

Run: python tools/im2rec.py --list --recursive train imgs/
     python tools/im2rec.py train.lst imgs/ --quality 95 --resize 256
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def make_list(root, prefix, recursive=True):
    classes = {}
    entries = []
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        if not recursive and dirpath != root:
            continue
        label_name = os.path.relpath(dirpath, root)
        for fn in sorted(filenames):
            if os.path.splitext(fn)[1].lower() in _EXTS:
                if label_name not in classes:
                    classes[label_name] = len(classes)
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                entries.append((len(entries), classes[label_name], rel))
    with open(prefix + ".lst", "w") as f:
        for idx, label, rel in entries:
            f.write(f"{idx}\t{label}\t{rel}\n")
    print(f"wrote {prefix}.lst ({len(entries)} items, {len(classes)} classes)")
    return entries


def pack(lst_path, root, quality=95, resize=0, color=1):
    from PIL import Image
    import numpy as onp

    from incubator_mxnet_tpu import recordio

    prefix = lst_path[:-4] if lst_path.endswith(".lst") else lst_path
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    with open(lst_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx, label, rel = int(parts[0]), float(parts[1]), parts[-1]
            img = Image.open(os.path.join(root, rel))
            img = img.convert("RGB" if color else "L")
            if resize:
                w, h = img.size
                scale = resize / min(w, h)
                img = img.resize((max(1, int(w * scale)),
                                  max(1, int(h * scale))))
            hdr = recordio.IRHeader(0, label, idx, 0)
            rec.write_idx(idx, recordio.pack_img(hdr, onp.asarray(img),
                                                 quality=quality))
            n += 1
    rec.close()
    print(f"packed {n} images → {prefix}.rec / {prefix}.idx")
    return n


def main(argv=None):
    p = argparse.ArgumentParser(description="image → RecordIO converter")
    p.add_argument("prefix_or_lst")
    p.add_argument("root")
    p.add_argument("--list", action="store_true", dest="make_list")
    p.add_argument("--recursive", action="store_true")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--color", type=int, default=1)
    args = p.parse_args(argv)
    if args.make_list:
        # reference arg order: im2rec.py --list prefix root
        make_list(args.root, args.prefix_or_lst, args.recursive)
        return 0
    pack(args.prefix_or_lst, args.root, args.quality, args.resize, args.color)
    return 0


if __name__ == "__main__":
    sys.exit(main())
