"""tpulint — static analysis for JAX/TPU tracing hazards.

The reference MXNet project kept its correctness tooling in CI
(sanitizer builds over `src/engine`); tpulint is the Python/JAX
equivalent for this repo: an AST-based analyzer that makes silent
TPU-throughput hazards build-breaking.

Rules (see docs/static_analysis.md for the full catalogue):

  TPU001  host-numpy call in trace-reachable code
  TPU002  implicit host sync in trace-reachable / per-step code
  TPU003  PRNG key reuse without an intervening split
  TPU004  Python control flow on tracer-derived values under trace
  TPU005  side effect under jit (print / closure mutation / global write)
  TPU006  mutable default argument in a Block subclass signature
  ...
  TPU013  lock-order cycle across threads (deadlock)
  TPU014  Condition.wait() outside a while-predicate loop (lost wakeup)
  TPU015  blocking call (device dispatch / I/O / un-timed queue or
          join) while holding a hot lock
  TPU016  blocking lock acquisition in signal-handler context

TPU013-TPU016 run as one project-wide pass over a per-object
lock-acquisition graph (lock_rules.build_lock_graph); the runtime
counterpart `incubator_mxnet_tpu.lock_witness` cross-checks observed
acquisition order against that graph under MXTPU_LOCK_WITNESS=1.

Trace-reachability is computed by a conservative call-graph walk seeded
at jit entry points (`hybrid_forward`/`forward` of Block subclasses,
functions passed to `jax.jit`/`pjit`/`shard_map`/`pallas_call` — also
transitively, through helpers that jit their own function arguments,
e.g. `_program_jits`).  Host-only code (dataloaders, recordio, tools)
is deliberately out of scope for the trace rules.

Suppression: ``# tpulint: disable=TPU001,TPU004 -- reason`` on the
offending line (or ``disable-next=`` on the line above, or
``disable-file=`` anywhere in the file).  ``--strict`` requires every
suppression to carry a ``-- reason``.

Usage: ``python -m tools.tpulint incubator_mxnet_tpu/ --strict``
"""
from .analyzer import Project, Finding
from .cli import main, run

__all__ = ["Project", "Finding", "main", "run"]
