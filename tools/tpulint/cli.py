"""tpulint command line: ``python -m tools.tpulint <paths> [--strict]``.

Exit codes: 0 clean (or all findings baselined), 1 findings, 2 usage /
analysis errors.

CI shape (ci/lint.sh)::

    python -m tools.tpulint incubator_mxnet_tpu tools ci \
        --strict --baseline .tpulint_baseline.json

which fails only on findings NOT in the committed baseline — the
ratchet: new hazards block, pre-existing accepted ones don't.  Seed or
refresh the baseline with ``--write-baseline``.

Repeat invocations hit a findings cache under ``.tpulint_cache/``
keyed on every analyzed file's (path, mtime, size) and the linter's
own sources; ``--no-cache`` forces a fresh analysis, ``--stats``
reports files/elapsed/cache status on stderr.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from . import baseline as bl
from .analyzer import Project
from .rules import ALL_RULES, run_rules
from .suppressions import apply_suppressions


def run(paths: List[str], select: Optional[List[str]] = None,
        ignore: Optional[List[str]] = None, strict: bool = False):
    """Analyze `paths`; returns (project, findings-after-suppression)."""
    project = Project(paths)
    active = set(select) if select else set(ALL_RULES)
    if ignore:
        active -= set(ignore)
    findings = run_rules(project, active)
    sources = {m.path: m.source for m in project.modules.values()}
    findings = apply_suppressions(findings, sources, strict=strict)
    return project, findings


def _emit(pairs, fmt: str):
    if fmt == "json":
        # one finding per line (JSON-lines): trivially grep/jq-able,
        # diff-stable, and streamable — no enclosing array
        for f, fp in pairs:
            rec = {"rule": f.code, "path": f.path,
                   "line": f.line, "col": f.col,
                   "function": f.function, "message": f.message,
                   "fingerprint": fp}
            if f.extra:
                # structured rule payload, e.g. TPU013's lock-order
                # cycle and per-edge acquisition stacks
                rec.update(f.extra)
            print(json.dumps(rec, sort_keys=True))
    else:
        for f, _fp in pairs:
            print(f.format())


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint",
        description="Static analyzer for JAX/TPU tracing, sharding, "
                    "thread-safety and lock-order hazards (TPU001-TPU016; "
                    "see docs/static_analysis.md)")
    ap.add_argument("paths", nargs="+", help="files or directories to lint")
    ap.add_argument("--strict", action="store_true",
                    help="require a `-- reason` on every suppression")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule codes to run (default: all)")
    ap.add_argument("--ignore", default=None,
                    help="comma-separated rule codes to skip")
    ap.add_argument("--format", choices=("text", "json", "dot"),
                    default="text",
                    help="json = one finding per line with rule/path/line/"
                         "fingerprint; dot = Graphviz dump of the static "
                         "lock-order graph (no findings)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="accepted-findings file: report and fail only on "
                         "findings NOT fingerprinted in it")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to the --baseline file "
                         "(default .tpulint_baseline.json) and exit 0")
    ap.add_argument("--no-cache", action="store_true",
                    help="skip the .tpulint_cache/ findings memo")
    ap.add_argument("--stats", action="store_true",
                    help="report analyzed files / elapsed / cache status")
    ap.add_argument("--show-reachable", action="store_true",
                    help="dump the trace-reachable function set and exit")
    args = ap.parse_args(argv)

    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    for code in (select or []) + (ignore or []):
        if code not in ALL_RULES:
            print(f"tpulint: unknown rule code {code!r}", file=sys.stderr)
            return 2

    if args.format == "dot":
        # debugging view, not a lint: build the lock graph fresh
        # (cache stores findings, not graphs) and dump it
        from . import lock_rules
        project = Project(args.paths)
        if project.errors:
            for e in project.errors:
                print(f"tpulint: parse error: {e}", file=sys.stderr)
            return 2
        print(lock_rules.to_dot(lock_rules.build_lock_graph(project)),
              end="")
        return 0

    t0 = time.monotonic()
    files = Project._collect_files(args.paths)
    key = bl.cache_key(files, select, ignore, args.strict)
    cached = None
    if not args.no_cache and not args.show_reachable:
        cached = bl.cache_load(bl.CACHE_DIR, key)

    if cached is not None:
        pairs = bl.payload_to_findings(cached)
        n_mod = cached.get("n_modules", 0)
        n_reach = cached.get("n_reachable", 0)
    else:
        project, findings = run(args.paths, select, ignore, args.strict)
        if project.errors:
            for e in project.errors:
                print(f"tpulint: parse error: {e}", file=sys.stderr)
            return 2
        if args.show_reachable:
            for fn in sorted(project.trace_reachable_functions(),
                             key=lambda f: f.full_name):
                print(f"{fn.full_name}  [{fn.trace_reason}]")
            return 0
        sources = {m.path: m.source for m in project.modules.values()}
        pairs = bl.fingerprint_findings(findings, sources)
        n_mod = len(project.modules)
        n_reach = len(project.trace_reachable_functions())
        if not args.no_cache:
            bl.cache_store(bl.CACHE_DIR, key, bl.findings_to_payload(
                pairs, n_mod, n_reach, len(files)))

    elapsed = time.monotonic() - t0

    if args.write_baseline:
        out = args.baseline or ".tpulint_baseline.json"
        n = bl.write_baseline(out, [f for f, _ in pairs])
        print(f"tpulint: wrote {n} finding(s) to {out}", file=sys.stderr)
        return 0

    new_pairs = pairs
    n_baselined = n_renamed = 0
    if args.baseline is not None:
        try:
            entries = bl.load_baseline_entries(args.baseline)
        except FileNotFoundError:
            print(f"tpulint: baseline {args.baseline} not found — seed it "
                  f"with --write-baseline", file=sys.stderr)
            return 2
        except (ValueError, KeyError) as e:
            print(f"tpulint: bad baseline: {e}", file=sys.stderr)
            return 2
        new_pairs, n_exact, n_renamed = bl.filter_new_with_renames(
            pairs, entries)
        n_baselined = n_exact + n_renamed

    _emit(new_pairs, args.format)
    if args.format == "text":
        tail = (f"tpulint: {len(new_pairs)} finding(s) in {n_mod} module(s) "
                f"({n_reach} trace-reachable functions)")
        if n_baselined:
            tail += f"; {n_baselined} baselined finding(s) suppressed"
            if n_renamed:
                tail += f" ({n_renamed} matched cross-path)"
        print(tail, file=sys.stderr)
    if args.stats:
        src = "hit" if cached is not None else "miss"
        print(f"tpulint: analyzed {len(files)} file(s) in {elapsed:.2f}s "
              f"(cache {src})", file=sys.stderr)
    return 1 if new_pairs else 0
