"""tpulint command line: ``python -m tools.tpulint <paths> [--strict]``.

Exit codes: 0 clean, 1 findings, 2 usage / analysis errors.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .analyzer import Project
from .rules import ALL_RULES, run_rules
from .suppressions import apply_suppressions


def run(paths: List[str], select: Optional[List[str]] = None,
        ignore: Optional[List[str]] = None, strict: bool = False):
    """Analyze `paths`; returns (project, findings-after-suppression)."""
    project = Project(paths)
    active = set(select) if select else set(ALL_RULES)
    if ignore:
        active -= set(ignore)
    findings = run_rules(project, active)
    sources = {m.path: m.source for m in project.modules.values()}
    findings = apply_suppressions(findings, sources, strict=strict)
    return project, findings


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint",
        description="Static analyzer for JAX/TPU tracing hazards "
                    "(TPU001-TPU006; see docs/static_analysis.md)")
    ap.add_argument("paths", nargs="+", help="files or directories to lint")
    ap.add_argument("--strict", action="store_true",
                    help="require a `-- reason` on every suppression")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule codes to run (default: all)")
    ap.add_argument("--ignore", default=None,
                    help="comma-separated rule codes to skip")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--show-reachable", action="store_true",
                    help="dump the trace-reachable function set and exit")
    args = ap.parse_args(argv)

    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    for code in (select or []) + (ignore or []):
        if code not in ALL_RULES:
            print(f"tpulint: unknown rule code {code!r}", file=sys.stderr)
            return 2

    project, findings = run(args.paths, select, ignore, args.strict)

    if project.errors:
        for e in project.errors:
            print(f"tpulint: parse error: {e}", file=sys.stderr)
        return 2

    if args.show_reachable:
        for fn in sorted(project.trace_reachable_functions(),
                         key=lambda f: f.full_name):
            print(f"{fn.full_name}  [{fn.trace_reason}]")
        return 0

    if args.format == "json":
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        n_mod = len(project.modules)
        n_reach = len(project.trace_reachable_functions())
        tail = (f"tpulint: {len(findings)} finding(s) in {n_mod} module(s) "
                f"({n_reach} trace-reachable functions)")
        print(tail, file=sys.stderr)
    return 1 if findings else 0
