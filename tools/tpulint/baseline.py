"""Findings baseline + fingerprinting + analyzer result cache.

The CI gate (ci/lint.sh) must fail on NEW findings without forcing a
contributor to fix every pre-existing one in the same change.  The
mechanism is the ratchet pyflakes/ruff users know as a *baseline*:

  python -m tools.tpulint <paths> --write-baseline   # seed, commit it
  python -m tools.tpulint <paths> --baseline .tpulint_baseline.json
                                                     # fail only on new

A finding's **fingerprint** is a sha1 over (rule, path, enclosing
function, the stripped text of the flagged source line, occurrence
index) — deliberately NOT the line number, so baselined findings
survive unrelated edits that shift code up or down.  The occurrence
index disambiguates identical lines flagged more than once in the
same function (index is per (rule, path, function, line-text) group,
in (line, col) order).

Renames get a second chance: a finding whose fingerprint misses (the
path is hashed) is matched against the baseline entries the exact pass
did not consume on (rule, function, line text) alone — multiset
semantics, each entry usable once — so moving a file does not
resurrect every accepted finding in it (:func:`filter_new_with_renames`).

The same module hosts the **result cache**: a full project analysis
parses every file and runs a half-dozen interprocedural fixpoints, so
repeat CI invocations memoize the *findings* (not ASTs — measured:
unpickling 122 ASTs is slower than re-parsing them) under
``.tpulint_cache/``, keyed on every analyzed file's (path, mtime,
size) plus the lint tool's own sources and the rule selection.  Any
edit anywhere — target tree or linter — misses the cache.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .analyzer import Finding

BASELINE_VERSION = 1
CACHE_DIR = ".tpulint_cache"


# -- fingerprints --------------------------------------------------------- #
def _line_text(sources: Dict[str, str], path: str, line: int) -> str:
    src = sources.get(path)
    if src is None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
        except OSError:
            src = ""
        sources[path] = src
    lines = src.splitlines()
    return lines[line - 1].strip() if 0 < line <= len(lines) else ""


def fingerprint_findings(findings: Sequence[Finding],
                         sources: Optional[Dict[str, str]] = None
                         ) -> List[Tuple[Finding, str]]:
    """[(finding, fingerprint)] in the input order.

    Stable under line-number shifts: the hash covers rule, path,
    function, stripped line text, and an occurrence index — never the
    line number itself.
    """
    sources = dict(sources) if sources else {}
    groups: Dict[Tuple[str, str, str, str], List[Finding]] = {}
    texts: Dict[int, str] = {}
    for f in findings:
        text = _line_text(sources, f.path, f.line)
        texts[id(f)] = text
        groups.setdefault((f.code, f.path, f.function, text), []).append(f)
    index: Dict[int, int] = {}
    for members in groups.values():
        for i, f in enumerate(sorted(members,
                                     key=lambda f: (f.line, f.col))):
            index[id(f)] = i
    out: List[Tuple[Finding, str]] = []
    for f in findings:
        h = hashlib.sha1("\x00".join(
            (f.code, f.path, f.function, texts[id(f)],
             str(index[id(f)]))).encode("utf-8")).hexdigest()
        out.append((f, h))
    return out


# -- baseline file -------------------------------------------------------- #
def write_baseline(path: str, findings: Sequence[Finding],
                   sources: Optional[Dict[str, str]] = None) -> int:
    """Serialize `findings` as the accepted baseline; returns count."""
    sources = dict(sources) if sources else {}
    entries = []
    for f, fp in fingerprint_findings(findings, sources):
        entries.append({
            "rule": f.code,
            "path": f.path,
            "function": f.function,
            "line": f.line,          # informational only — not hashed
            "line_text": _line_text(sources, f.path, f.line),
            "fingerprint": fp,
        })
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": BASELINE_VERSION, "findings": entries},
                  fh, indent=1, sort_keys=True)
        fh.write("\n")
    return len(entries)


def load_baseline_entries(path: str) -> List[dict]:
    """Full baseline entries (rule/function/line_text/fingerprint) —
    the cross-path rename-tolerance pass needs more than the
    fingerprint set.  Raises on a bad or version-skewed file."""
    with open(path, "r", encoding="utf-8") as fh:
        blob = json.load(fh)
    if blob.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: baseline version {blob.get('version')!r} "
            f"(this tpulint writes {BASELINE_VERSION}) — regenerate with "
            f"--write-baseline")
    return list(blob.get("findings", []))


def load_baseline(path: str) -> Set[str]:
    """Fingerprint set from a baseline file (raises on bad file)."""
    return {e["fingerprint"] for e in load_baseline_entries(path)}


def filter_new(pairs: Iterable[Tuple[Finding, str]],
               baseline: Set[str]) -> List[Tuple[Finding, str]]:
    """Drop findings whose fingerprint the baseline already accepts."""
    return [(f, fp) for f, fp in pairs if fp not in baseline]


def _cross_path_function(path: str, function: str) -> str:
    """The rename-invariant part of a finding's function name.

    ``Finding.function`` is module-qualified (``pkg.mod.Class.method``)
    and the module name derives from the file path, so a rename changes
    it along with the path.  Strip everything up to and including the
    path's stem component, leaving the qualname — picking the LAST
    stem occurrence that still leaves a non-empty tail, so a package
    directory sharing the stem's name doesn't confuse the split."""
    stem = os.path.splitext(os.path.basename(path))[0]
    parts = function.split(".")
    for i in range(len(parts) - 2, -1, -1):
        if parts[i] == stem:
            return ".".join(parts[i + 1:])
    return function


def filter_new_with_renames(pairs: Iterable[Tuple[Finding, str]],
                            entries: Sequence[dict],
                            sources: Optional[Dict[str, str]] = None
                            ) -> Tuple[List[Tuple[Finding, str]], int, int]:
    """Two-pass baseline filter: exact fingerprints, then a cross-path
    (rule, function, line-text) match so a file RENAME or move doesn't
    resurrect every baselined finding inside it.

    Pass 1 drops findings whose fingerprint the baseline holds (same
    semantics as :func:`filter_new`).  Pass 2 matches the leftovers
    against the baseline entries pass 1 did NOT consume, on (rule,
    enclosing function, stripped line text) with the path ignored —
    each entry consumable once, so a genuinely new DUPLICATE of a
    baselined finding still fails the gate.

    Returns ``(new_pairs, n_exact, n_renamed)``.
    """
    pairs = list(pairs)
    sources = dict(sources) if sources else {}
    accepted = {e["fingerprint"] for e in entries}
    matched_fps: Set[str] = set()
    survivors: List[Tuple[Finding, str]] = []
    for f, fp in pairs:
        if fp in accepted:
            matched_fps.add(fp)
        else:
            survivors.append((f, fp))
    n_exact = len(pairs) - len(survivors)
    pool: Dict[Tuple[str, str, str], int] = {}
    for e in entries:
        if e["fingerprint"] in matched_fps:
            continue
        k = (e["rule"],
             _cross_path_function(e.get("path", ""), e.get("function", "")),
             e.get("line_text", ""))
        pool[k] = pool.get(k, 0) + 1
    out: List[Tuple[Finding, str]] = []
    n_renamed = 0
    for f, fp in survivors:
        k = (f.code, _cross_path_function(f.path, f.function),
             _line_text(sources, f.path, f.line))
        if pool.get(k, 0) > 0:
            pool[k] -= 1
            n_renamed += 1
        else:
            out.append((f, fp))
    return out, n_exact, n_renamed


# -- result cache --------------------------------------------------------- #
def _tool_files() -> List[str]:
    """The linter's own sources — part of every cache key, so editing a
    rule invalidates all cached results."""
    here = os.path.dirname(os.path.abspath(__file__))
    return [os.path.join(here, f) for f in sorted(os.listdir(here))
            if f.endswith(".py")]


def cache_key(files: Sequence[str], select: Optional[Sequence[str]],
              ignore: Optional[Sequence[str]], strict: bool) -> Optional[str]:
    """sha1 over (path, mtime, size) of every analyzed file AND the
    tool itself, plus the rule selection; None when any stat fails."""
    h = hashlib.sha1()
    h.update(f"v{BASELINE_VERSION}|{sorted(select or [])}|"
             f"{sorted(ignore or [])}|{strict}".encode("utf-8"))
    for path in list(files) + _tool_files():
        try:
            st = os.stat(path)
        except OSError:
            return None
        h.update(f"{path}|{st.st_mtime_ns}|{st.st_size}\n".encode("utf-8"))
    return h.hexdigest()


def cache_load(cache_dir: str, key: Optional[str]) -> Optional[dict]:
    if key is None:
        return None
    try:
        with open(os.path.join(cache_dir, f"{key}.json"), "r",
                  encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def cache_store(cache_dir: str, key: Optional[str], payload: dict) -> None:
    if key is None:
        return
    try:
        os.makedirs(cache_dir, exist_ok=True)
        tmp = os.path.join(cache_dir, f".{key}.tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
        os.replace(tmp, os.path.join(cache_dir, f"{key}.json"))
    except OSError:
        pass            # cache is best-effort — never fail the lint


def findings_to_payload(pairs: Sequence[Tuple[Finding, str]],
                        n_modules: int, n_reachable: int,
                        n_files: int) -> dict:
    return {
        "n_modules": n_modules,
        "n_reachable": n_reachable,
        "n_files": n_files,
        "findings": [
            dict({"code": f.code, "message": f.message, "path": f.path,
                  "line": f.line, "col": f.col, "function": f.function,
                  "fingerprint": fp},
                 **({"extra": f.extra} if f.extra else {}))
            for f, fp in pairs
        ],
    }


def payload_to_findings(payload: dict) -> List[Tuple[Finding, str]]:
    return [
        (Finding(e["code"], e["message"], e["path"], e["line"], e["col"],
                 e.get("function", ""), e.get("extra")), e["fingerprint"])
        for e in payload.get("findings", [])
    ]
