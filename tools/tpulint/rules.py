"""tpulint rules TPU001–TPU006.

Each rule is a function ``(project, fn_info) -> [Finding]`` over one
analyzed function.  Scope discipline:

* TPU001/TPU002/TPU004/TPU005 need trace context — they only fire in
  ``trace_reachable`` functions (TPU002 additionally in
  ``perstep_reachable`` ones, explicit-sync patterns only);
* TPU003 (key reuse) and TPU006 (mutable defaults) are correctness
  bugs anywhere — they run unconditionally.

The shared taint engine marks values derived from the function's array
parameters; static metadata (``x.shape``/``x.ndim``/``x.dtype``/
``len(x)``/``is None``) is explicitly *untainted* so shape-polymorphic
Python (ubiquitous in Gluon forwards) stays quiet.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .analyzer import Finding, FunctionInfo, Project, dotted_name

# parameters that are flags/contexts by convention, never arrays.
# zero_stage is a Trainer config flag: branching on it swaps the fused
# step program (one legitimate recompile), never a per-step retrace.
NEVER_TAINTED_PARAMS = {"self", "cls", "F", "training", "mode", "ctx",
                        "context", "deterministic", "axis", "name", "prefix",
                        "zero_stage"}

# attribute reads that are static under trace (aval metadata)
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                "itemsize", "nbytes", "weak_type"}

# calls whose result is host-static even on tracer args
STATIC_FUNCS = {"len", "isinstance", "type", "hasattr", "id", "callable",
                "getattr", "repr"}

# method calls that launder a tracer into a host value — TPU002's job,
# not TPU004's (flagging the branch too would double-report)
SYNC_METHODS = {"item", "asnumpy", "tolist", "wait_to_read",
                "block_until_ready"}

SYNC_FUNCS = {"jax.device_get", "jax.block_until_ready"}

MUTATOR_METHODS = {"append", "extend", "insert", "remove", "pop", "clear",
                   "update", "setdefault", "popitem", "add", "discard",
                   "appendleft", "extendleft"}

# jax.random producers (return keys) vs everything else (consume keys)
KEY_PRODUCERS = {"PRNGKey", "key", "split", "fold_in", "wrap_key_data",
                 "clone", "key_data"}


def _fn_params(node: ast.FunctionDef):
    return node.args.posonlyargs + node.args.args + node.args.kwonlyargs


# annotations that prove a parameter is a host value, not an array
_HOST_ANNOTATIONS = {"int", "bool", "str", "float", "bytes", "Callable",
                     "Mesh", "Path"}


def _host_annotation(ann) -> bool:
    if ann is None:
        return False
    name = None
    if isinstance(ann, ast.Name):
        name = ann.id
    elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value
    if name is None:
        return False
    # `cfg: HybridConfig`-style hyperparameter bundles are host objects
    return name in _HOST_ANNOTATIONS or name.endswith(("Config", "Settings"))


def _is_numpy(project: Project, fn: FunctionInfo, d: str) -> Optional[str]:
    """Resolved dotted path if `d` is a host-numpy reference, else None."""
    resolved = project.resolve(fn.module, d)
    if resolved == "numpy" or resolved.startswith("numpy."):
        return resolved
    return None


# ---------------------------------------------------------------------------
# taint engine (shared by TPU002 / TPU004)
# ---------------------------------------------------------------------------


class Taint:
    """Per-function forward taint over array-valued names.

    Seeds: positional parameters without defaults (conventional flag
    names excluded).  ``*args`` is a *container* — the tuple itself is
    host-static (its length is fixed at trace time) but its elements
    are tainted.
    """

    def __init__(self, project: Project, fn: FunctionInfo):
        self.project = project
        self.fn = fn
        args = fn.node.args
        pos = args.posonlyargs + args.args
        n_defaults = len(args.defaults)
        seeded = pos[: len(pos) - n_defaults] if n_defaults else pos
        self.tainted: Set[str] = {
            a.arg for a in seeded
            if a.arg not in NEVER_TAINTED_PARAMS
            and not _host_annotation(a.annotation)}
        if fn.cls is not None and pos and pos[0].arg in self.tainted:
            self.tainted.discard(pos[0].arg)
        # static_argnums/static_argnames at the jit boundary are host
        # values by contract
        self.tainted -= fn.static_params
        self.containers: Set[str] = set()
        if args.vararg is not None:
            self.containers.add(args.vararg.arg)
        # `args`/`kwargs` as PLAIN params are tuple/dict containers by
        # convention: host-static themselves, tainted elements
        for a in pos:
            if a.arg in ("args", "kwargs"):
                self.tainted.discard(a.arg)
                self.containers.add(a.arg)

    # -- expression taint -------------------------------------------------- #
    def expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Name) \
                    and node.value.id in self.containers:
                return True
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            return self.call(node)
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` are host-static identity checks
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            # comparisons against string constants are host dispatch
            # (`s.op == "_group"`) — tracers never compare to strings
            if all(isinstance(c, ast.Constant) and isinstance(c.value, str)
                   for c in node.comparators):
                return False
            return self.expr(node.left) or any(self.expr(c)
                                               for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return self.expr(node.test) or self.expr(node.body) \
                or self.expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            # a comprehension over tainted data yields tainted elements
            return self.expr(node.elt) or any(self.expr(g.iter)
                                              for g in node.generators)
        if isinstance(node, ast.DictComp):
            return self.expr(node.key) or self.expr(node.value) \
                or any(self.expr(g.iter) for g in node.generators)
        return False

    def call(self, node: ast.Call) -> bool:
        d = dotted_name(node.func)
        if d is not None:
            resolved = self.project.resolve(self.fn.module, d)
            if resolved in STATIC_FUNCS or d in STATIC_FUNCS:
                return False
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in SYNC_METHODS:
                return False        # host value — TPU002 territory
            if node.func.attr in STATIC_ATTRS:
                return False
            if self.expr(node.func.value):
                return True         # method on a tainted value
        return any(self.expr(a) for a in node.args) \
            or any(self.expr(kw.value) for kw in node.keywords)

    # -- statement walk ----------------------------------------------------- #
    def assign(self, target: ast.AST, value_tainted: bool):
        if isinstance(target, ast.Name):
            if value_tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.assign(e, value_tainted)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, value_tainted)
        # attribute/subscript writes don't create new taintable names

    def process_stmt(self, stmt: ast.stmt):
        """Propagate taint through one statement (no recursion into
        compound bodies — the rule drivers own the traversal order)."""
        if isinstance(stmt, ast.Assign):
            t = self.expr(stmt.value)
            for tgt in stmt.targets:
                self.assign(tgt, t)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self.assign(stmt.target, self.expr(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                if self.expr(stmt.value) or stmt.target.id in self.tainted:
                    self.tainted.add(stmt.target.id)
        elif isinstance(stmt, ast.For):
            it = stmt.iter
            tainted_iter = self.expr(it) or (
                isinstance(it, ast.Name) and it.id in self.containers)
            # `for i, x in enumerate(xs)`: the counter is a host int
            # even when xs holds tracers — only the element is tainted
            if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                    and it.func.id == "enumerate" \
                    and isinstance(stmt.target, ast.Tuple) \
                    and len(stmt.target.elts) == 2:
                self.assign(stmt.target.elts[0], False)
                self.assign(stmt.target.elts[1], tainted_iter)
            elif tainted_iter:
                self.assign(stmt.target, True)


def _walk_stmts(body: List[ast.stmt]):
    """Statements in execution-ish order, descending into compound
    statements but NOT into nested function/class defs."""
    for stmt in body:
        yield stmt
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if sub and not isinstance(stmt, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.ClassDef)):
                yield from _walk_stmts(sub)
        for h in getattr(stmt, "handlers", []) or []:
            yield from _walk_stmts(h.body)


def _own_exprs(stmt: ast.stmt):
    """Expression nodes evaluated directly by `stmt` — excludes nested
    statements (they are visited on their own by `_walk_stmts`) and
    nested function/class defs."""

    def rec(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.excepthandler,
                                  ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            yield child
            yield from rec(child)

    yield from rec(stmt)


# ---------------------------------------------------------------------------
# TPU001 — host numpy under trace
# ---------------------------------------------------------------------------


def check_tpu001(project: Project, fn: FunctionInfo,
                 claimed: Set[int]) -> List[Finding]:
    if not fn.trace_reachable:
        return []
    out = []
    for node in project.iter_own_nodes(fn):
        if not isinstance(node, ast.Call) or id(node) in claimed:
            continue
        d = dotted_name(node.func)
        if d is None:
            continue
        resolved = _is_numpy(project, fn, d)
        if resolved is not None:
            out.append(Finding(
                "TPU001",
                f"host-numpy call `{d}` (→ {resolved}) in trace-reachable "
                f"code — constant-folds at trace time or breaks on tracers; "
                f"use jax.numpy",
                fn.module.path, node.lineno, node.col_offset, fn.full_name))
    return out


# ---------------------------------------------------------------------------
# TPU002 — implicit host sync
# ---------------------------------------------------------------------------


def check_tpu002(project: Project, fn: FunctionInfo,
                 claimed: Set[int]) -> List[Finding]:
    in_trace = fn.trace_reachable
    in_step = fn.perstep_reachable
    if not (in_trace or in_step):
        return []
    out: List[Finding] = []
    where = "trace-reachable" if in_trace else "per-step"
    taint = Taint(project, fn)

    def add(node, what):
        out.append(Finding(
            "TPU002",
            f"implicit host sync `{what}` in {where} code — forces the "
            f"device queue to drain (tens of ms on TPU); keep values on "
            f"device or move the sync off the step path",
            fn.module.path, node.lineno, node.col_offset, fn.full_name))

    for stmt in _walk_stmts(fn.node.body):
        for node in _own_exprs(stmt):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            # .item() / .asnumpy() / .tolist() / .wait_to_read()
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in SYNC_METHODS:
                add(node, f".{node.func.attr}()")
                claimed.add(id(node))
                continue
            if d is not None:
                resolved = project.resolve(fn.module, d)
                if resolved in SYNC_FUNCS:
                    add(node, d)
                    claimed.add(id(node))
                    continue
                if in_trace and resolved in ("numpy.asarray", "numpy.array"):
                    add(node, d)
                    claimed.add(id(node))
                    continue
            # float(x)/int(x)/bool(x) on array-derived values
            if isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int", "bool") \
                    and len(node.args) == 1 and taint.expr(node.args[0]):
                add(node, f"{node.func.id}(...)")
                claimed.add(id(node))
        taint.process_stmt(stmt)
    return out


# ---------------------------------------------------------------------------
# TPU003 — PRNG key reuse
# ---------------------------------------------------------------------------


class _KeyState:
    __slots__ = ("uses",)

    def __init__(self):
        self.uses: Dict[str, List[int]] = {}   # key var -> consume line numbers


def check_tpu003(project: Project, fn: FunctionInfo) -> List[Finding]:
    """Linear abstract interpretation: loop bodies run twice so a key
    consumed once-per-iteration still counts as reused."""
    out: List[Finding] = []
    reported: Set[int] = set()

    def is_random_call(node: ast.Call) -> Optional[str]:
        d = dotted_name(node.func)
        if d is None:
            return None
        resolved = project.resolve(fn.module, d)
        if resolved.startswith("jax.random."):
            return resolved.rpartition(".")[2]
        return None

    def scan(body: List[ast.stmt], uses: Dict[str, int]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            # expression-level: find consumes and producers in eval order
            for node in _own_exprs(stmt):
                if not isinstance(node, ast.Call):
                    continue
                tail = is_random_call(node)
                if tail is None or tail in KEY_PRODUCERS:
                    continue
                if node.args and isinstance(node.args[0], ast.Name):
                    k = node.args[0].id
                    if k not in uses:
                        continue
                    uses[k] += 1
                    if uses[k] > 1 and node.lineno not in reported:
                        reported.add(node.lineno)
                        out.append(Finding(
                            "TPU003",
                            f"PRNG key `{k}` consumed more than once without "
                            f"an intervening jax.random.split — identical "
                            f"random draws; split the key per use",
                            fn.module.path, node.lineno, node.col_offset,
                            fn.full_name))
            # assignments from producers (re)arm tracking
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                value = stmt.value
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                produced = False
                if isinstance(value, ast.Call):
                    tail = is_random_call(value)
                    produced = tail in KEY_PRODUCERS if tail else False
                if isinstance(value, ast.Subscript) \
                        and isinstance(value.value, ast.Call):
                    tail = is_random_call(value.value)
                    produced = produced or (tail in KEY_PRODUCERS
                                            if tail else False)
                for tgt in targets:
                    names = []
                    if isinstance(tgt, ast.Name):
                        names = [tgt.id]
                    elif isinstance(tgt, (ast.Tuple, ast.List)):
                        names = [e.id for e in tgt.elts
                                 if isinstance(e, ast.Name)]
                    for n in names:
                        if produced:
                            uses[n] = 0
                        else:
                            uses.pop(n, None)
            # control flow
            if isinstance(stmt, (ast.For, ast.While)):
                for _ in range(2):          # two symbolic iterations
                    scan(stmt.body, uses)
                scan(stmt.orelse, uses)
            elif isinstance(stmt, ast.If):
                left = dict(uses)
                scan(stmt.body, left)
                right = dict(uses)
                scan(stmt.orelse, right)
                for k in set(left) | set(right):
                    uses[k] = max(left.get(k, 0), right.get(k, 0))
            elif isinstance(stmt, ast.Try):
                scan(stmt.body, uses)
                for h in stmt.handlers:
                    scan(h.body, uses)
                scan(stmt.finalbody, uses)
            elif isinstance(stmt, ast.With):
                scan(stmt.body, uses)

    scan(fn.node.body, {})
    return out


# ---------------------------------------------------------------------------
# TPU004 — Python control flow on tracers
# ---------------------------------------------------------------------------


def check_tpu004(project: Project, fn: FunctionInfo) -> List[Finding]:
    if not fn.trace_reachable:
        return []
    out: List[Finding] = []
    taint = Taint(project, fn)

    def flag(node, kind):
        out.append(Finding(
            "TPU004",
            f"Python `{kind}` on a tracer-derived value in trace-reachable "
            f"code — raises TracerBoolConversionError under jit (or bakes "
            f"in one branch); use jax.lax.cond/select or jnp.where",
            fn.module.path, node.lineno, node.col_offset, fn.full_name))

    for stmt in _walk_stmts(fn.node.body):
        if isinstance(stmt, ast.If) and taint.expr(stmt.test):
            flag(stmt, "if")
        elif isinstance(stmt, ast.While) and taint.expr(stmt.test):
            flag(stmt, "while")
        elif isinstance(stmt, ast.Assert) and taint.expr(stmt.test):
            flag(stmt, "assert")
        taint.process_stmt(stmt)
    return out


# ---------------------------------------------------------------------------
# TPU005 — side effects under jit
# ---------------------------------------------------------------------------


def _local_names(fn: FunctionInfo) -> Set[str]:
    """Names assigned in fn's own body (params excluded on purpose:
    mutating an argument container under jit is still a side effect)."""
    out: Set[str] = set()
    for node in ast.walk(fn.node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn.node:
            out.add(node.name)
            continue
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(node.target, ast.Name):
                out.add(node.target.id)
        elif isinstance(node, ast.For):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    for sub in ast.walk(item.optional_vars):
                        if isinstance(sub, ast.Name):
                            out.add(sub.id)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                for sub in ast.walk(gen.target):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
    return out


def check_tpu005(project: Project, fn: FunctionInfo) -> List[Finding]:
    if not fn.trace_reachable:
        return []
    out: List[Finding] = []
    local = _local_names(fn)

    def flag(node, msg):
        out.append(Finding("TPU005", msg, fn.module.path, node.lineno,
                           node.col_offset, fn.full_name))

    for node in project.iter_own_nodes(fn):
        if isinstance(node, ast.Global):
            flag(node, "`global` write under jit — the rebind happens at "
                       "trace time, not per call; thread state through "
                       "function arguments instead")
        elif isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d == "print":
                flag(node, "`print` under jit runs at trace time only "
                           "(once per compilation); use jax.debug.print "
                           "for per-call output")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in MUTATOR_METHODS
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id not in local):
                n = node.func.value.id
                flag(node, f"mutation of non-local `{n}.{node.func.attr}()` "
                           f"under jit — appending/assigning tracers into "
                           f"host containers leaks tracers out of the trace")
    return out


# ---------------------------------------------------------------------------
# TPU006 — mutable defaults on Block signatures
# ---------------------------------------------------------------------------


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        d = dotted_name(node.func)
        return d in ("list", "dict", "set", "bytearray",
                     "collections.OrderedDict", "OrderedDict",
                     "collections.defaultdict", "defaultdict")
    return False


def check_tpu006(project: Project, fn: FunctionInfo) -> List[Finding]:
    if fn.cls is None or not fn.cls.is_block:
        return []
    out = []
    args = fn.node.args
    for default in list(args.defaults) + [d for d in args.kw_defaults
                                          if d is not None]:
        if _is_mutable_default(default):
            out.append(Finding(
                "TPU006",
                f"mutable default argument in Block subclass method "
                f"`{fn.qualname}` — shared across every instance (and "
                f"every retrace); default to None and create inside",
                fn.module.path, default.lineno, default.col_offset,
                fn.full_name))
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

ALL_RULES = ("TPU001", "TPU002", "TPU003", "TPU004", "TPU005", "TPU006",
             "TPU007", "TPU008", "TPU009", "TPU010", "TPU011", "TPU012",
             "TPU013", "TPU014", "TPU015", "TPU016")


def run_rules(project: Project, select: Optional[Set[str]] = None) -> List[Finding]:
    # deferred: mesh_rules/race_rules import taint helpers from here
    from . import cache_rules, lock_rules, mesh_rules, race_rules

    findings: List[Finding] = []
    active = set(select) if select else set(ALL_RULES)
    for fn in project.iter_functions():
        claimed: Set[int] = set()
        if "TPU002" in active:
            findings.extend(check_tpu002(project, fn, claimed))
        if "TPU001" in active:
            findings.extend(check_tpu001(project, fn, claimed))
        if "TPU003" in active:
            findings.extend(check_tpu003(project, fn))
        if "TPU004" in active:
            findings.extend(check_tpu004(project, fn))
        if "TPU005" in active:
            findings.extend(check_tpu005(project, fn))
        if "TPU006" in active:
            findings.extend(check_tpu006(project, fn))
        if "TPU007" in active:
            findings.extend(mesh_rules.check_tpu007(project, fn))
        if "TPU008" in active:
            findings.extend(mesh_rules.check_tpu008(project, fn))
        if "TPU009" in active:
            findings.extend(mesh_rules.check_tpu009(project, fn))
    # module/class-scoped rules: a cache's (or attribute's) accesses
    # are spread across functions, so these run once per module
    for mod in project.modules.values():
        if "TPU010" in active:
            findings.extend(cache_rules.check_tpu010_module(project, mod))
        for cls in mod.classes.values():
            if "TPU011" in active:
                findings.extend(
                    race_rules.check_tpu011_class(project, mod, cls))
            if "TPU012" in active:
                findings.extend(
                    race_rules.check_tpu012_class(project, mod, cls))
    # project-wide concurrency pass (TPU013-TPU016): one shared
    # lock-graph build, not per-function/per-module dispatch
    findings.extend(lock_rules.check_lock_rules(project, active))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings
