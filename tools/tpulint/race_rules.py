"""Thread-safety rules TPU011–TPU012.

Both consume the analyzer's *thread reachability* pass (functions
running on a ``threading.Thread`` target, transitively through the
call graph):

* TPU011 — an instance attribute written from a thread-reachable
  method and read (or written) from a non-thread method with no common
  lock held on both paths.  Lock tracking is a simple two-part pass:
  locks held lexically (``with self._lock:`` around the site) plus
  *entry locks* — the intersection, over every analyzed call site of a
  method, of the locks its callers hold when calling it (two fixpoint
  iterations, enough for the helper-under-lock idiom).
* TPU012 — a class that starts a background thread whose
  close/stop/``__del__`` path never joins it or signals it to exit
  (Event ``set()``, queue ``put(None)`` sentinel) — or that has no
  close path at all.  Either way pending work is silently dropped at
  interpreter exit and the thread can never be flushed.

Attributes only ever holding intrinsically thread-safe objects
(queues, locks, events, deques, the threads themselves) are exempt
from TPU011 — sharing the *object* is the point; it synchronizes
internally.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .analyzer import (THREAD_FACTORIES, ClassInfo, Finding, FunctionInfo,
                       ModuleInfo, Project, dotted_name)

# constructions whose instances synchronize internally — sharing the
# attribute across threads is safe by design
_THREADSAFE_CTORS = THREAD_FACTORIES | {
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "collections.deque", "deque",
    "threading.Lock", "threading.RLock", "threading.Event",
    "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier", "threading.local",
}

_CLOSE_NAMES = {"close", "stop", "shutdown", "terminate", "finalize",
                "teardown", "__del__", "__exit__"}
_CLOSE_PREFIXES = ("close", "stop", "shutdown", "teardown",
                   "_close", "_stop", "_shutdown", "_teardown")


def _is_close_method(name: str) -> bool:
    return name in _CLOSE_NAMES or name.startswith(_CLOSE_PREFIXES)


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _class_methods(mod: ModuleInfo, cls: ClassInfo) -> List[FunctionInfo]:
    return [f for f in mod.functions.values() if f.cls is cls]


# ---------------------------------------------------------------------------
# lock-held tracking (TPU011)
# ---------------------------------------------------------------------------


def _lock_token(expr: ast.AST) -> Optional[str]:
    """Identity of a lock in a `with` item — its dotted source text
    (`self._lock`, `_mod_lock`, `self._cv`); None for non-name ctxs."""
    return dotted_name(expr)


class _SiteCollector:
    """One walk per method: every `self.X` read/write site annotated
    with the set of locks lexically held there, plus lock sets at
    outgoing call sites (for the entry-lock fixpoint)."""

    def __init__(self, fn: FunctionInfo):
        self.fn = fn
        self.writes: List[Tuple[str, ast.AST, frozenset]] = []
        self.reads: List[Tuple[str, ast.AST, frozenset]] = []
        self.call_locks: Dict[int, frozenset] = {}   # id(Call) -> locks
        self._walk(fn.node.body, frozenset())

    def _walk(self, body: List[ast.stmt], held: frozenset):
        cur = set(held)
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                tokens = {t for t in (_lock_token(i.context_expr)
                                      for i in stmt.items) if t}
                self._exprs(stmt, frozenset(cur))
                self._walk(stmt.body, frozenset(cur | tokens))
                continue
            self._exprs(stmt, frozenset(cur))
            # linear `.acquire()` / `.release()` tracking — the
            # explicit-region idiom (`if not lock.acquire(timeout=..):
            # return` ... `try: ... finally: lock.release()`) holds the
            # lock between the two calls just like a `with` block
            self._acquires(stmt, cur)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    self._walk(sub, frozenset(cur))
            for h in getattr(stmt, "handlers", []) or []:
                self._walk(h.body, frozenset(cur))

    def _acquires(self, stmt: ast.stmt, cur: set):
        def rec(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.stmt, ast.excepthandler,
                                      ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                if isinstance(child, ast.Call) \
                        and isinstance(child.func, ast.Attribute):
                    token = _lock_token(child.func.value)
                    if token is not None:
                        if child.func.attr == "acquire":
                            cur.add(token)
                        elif child.func.attr == "release":
                            cur.discard(token)
                rec(child)

        rec(stmt)

    def _exprs(self, stmt: ast.stmt, held: frozenset):
        def rec(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.stmt, ast.excepthandler,
                                      ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                a = _self_attr(child)
                if a is not None:
                    if isinstance(child.ctx, ast.Load):
                        self.reads.append((a, child, held))
                    else:
                        self.writes.append((a, child, held))
                if isinstance(child, ast.Call):
                    self.call_locks[id(child)] = held
                rec(child)

        rec(stmt)


def _entry_locks(project: Project,
                 collectors: Dict[int, _SiteCollector]) -> Dict[int, frozenset]:
    """Fixpoint (2 rounds): locks provably held on EVERY analyzed call
    path into each method.  A method with no analyzed call sites gets
    an empty set (it is a public entry — assume unlocked)."""
    entry: Dict[int, frozenset] = {fid: frozenset() for fid in collectors}
    for _ in range(2):
        nxt: Dict[int, frozenset] = {}
        for fid, col in collectors.items():
            acc: Optional[frozenset] = None
            for caller, call in project.call_sites(col.fn):
                ccol = collectors.get(id(caller))
                at_site = ccol.call_locks.get(id(call), frozenset()) \
                    if ccol is not None else frozenset()
                here = at_site | entry.get(id(caller), frozenset())
                acc = here if acc is None else (acc & here)
            nxt[fid] = acc if acc is not None else frozenset()
        entry = nxt
    return entry


def _threadsafe_attrs(project: Project, mod: ModuleInfo,
                      methods: List[FunctionInfo]) -> Set[str]:
    safe: Set[str] = set()
    unsafe: Set[str] = set()
    for m in methods:
        for node in project.iter_own_nodes(m):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    or node.value is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                a = _self_attr(tgt)
                if a is None:
                    continue
                v = node.value
                d = dotted_name(v.func) if isinstance(v, ast.Call) else None
                resolved = project.resolve(mod, d) if d else None
                if resolved in _THREADSAFE_CTORS \
                        or isinstance(v, ast.Constant) and v.value is None:
                    safe.add(a)       # None placeholder / safe object
                else:
                    unsafe.add(a)
    return safe - unsafe


def check_tpu011_class(project: Project, mod: ModuleInfo,
                       cls: ClassInfo) -> List[Finding]:
    methods = _class_methods(mod, cls)
    if not any(m.thread_reachable for m in methods):
        return []
    collectors = {id(m): _SiteCollector(m) for m in methods}
    entry = _entry_locks(project, collectors)
    exempt = _threadsafe_attrs(project, mod, methods)
    out: List[Finding] = []
    reported: Set[str] = set()
    for m in methods:
        if not m.thread_reachable:
            continue
        for attr, node, held in collectors[id(m)].writes:
            if attr in exempt or attr in reported:
                continue
            wlocks = held | entry[id(m)]
            for other in methods:
                if other.thread_reachable or other.name == "__init__":
                    continue
                ocol = collectors[id(other)]
                for oattr, onode, oheld in ocol.reads + ocol.writes:
                    if oattr != attr:
                        continue
                    olocks = oheld | entry[id(other)]
                    if wlocks & olocks:
                        continue
                    reported.add(attr)
                    out.append(Finding(
                        "TPU011",
                        f"`self.{attr}` is written from thread-side "
                        f"`{m.qualname}` and accessed from "
                        f"`{other.qualname}` (line {onode.lineno}) with no "
                        f"common lock — torn/stale reads across threads; "
                        f"guard both sides with one lock or use a "
                        f"queue/Event",
                        mod.path, node.lineno, node.col_offset,
                        m.full_name))
                    break
                if attr in reported:
                    break
    return out


# ---------------------------------------------------------------------------
# TPU012 — started thread without a joining/signalling close path
# ---------------------------------------------------------------------------


def _thread_ctor_in(project: Project, mod: ModuleInfo,
                    value: ast.AST) -> bool:
    """Is `value` a Thread construction, or a list/tuple holding one
    (incl. via a comprehension)?"""
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            d = dotted_name(sub.func)
            if d is not None and project.resolve(mod, d) in THREAD_FACTORIES:
                return True
    return False


def _event_queue_attrs(project: Project, mod: ModuleInfo,
                       methods: List[FunctionInfo]) -> Tuple[Set[str], Set[str]]:
    events: Set[str] = set()
    queues: Set[str] = set()
    for m in methods:
        for node in project.iter_own_nodes(m):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    or node.value is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            d = dotted_name(node.value.func) \
                if isinstance(node.value, ast.Call) else None
            resolved = project.resolve(mod, d) if d else None
            for tgt in targets:
                a = _self_attr(tgt)
                if a is None:
                    continue
                if resolved == "threading.Event":
                    events.add(a)
                elif resolved in ("queue.Queue", "queue.LifoQueue",
                                  "queue.PriorityQueue", "queue.SimpleQueue"):
                    queues.add(a)
    return events, queues


def _close_reachable(project: Project, cls: ClassInfo,
                     methods: List[FunctionInfo]) -> List[FunctionInfo]:
    seeds = [m for m in methods if _is_close_method(m.name)]
    seen = {id(m) for m in seeds}
    work = list(seeds)
    while work:
        m = work.pop()
        for callee in project.callees(m):
            if callee.cls is cls and id(callee) not in seen:
                seen.add(id(callee))
                work.append(callee)
                seeds.append(callee)
    return seeds


def check_tpu012_class(project: Project, mod: ModuleInfo,
                       cls: ClassInfo) -> List[Finding]:
    methods = _class_methods(mod, cls)
    # thread attrs: self.X = Thread(...) / [Thread(...), ...]
    thread_attrs: Dict[str, ast.AST] = {}
    started: Set[str] = set()
    for m in methods:
        loop_alias: Dict[str, str] = {}
        for node in project.iter_own_nodes(m):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    and node.value is not None:
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    a = _self_attr(tgt)
                    if a is not None and a not in thread_attrs \
                            and _thread_ctor_in(project, mod, node.value):
                        thread_attrs[a] = tgt
            elif isinstance(node, ast.For):
                a = _self_attr(node.iter)
                if a is not None and isinstance(node.target, ast.Name):
                    loop_alias[node.target.id] = a
        for node in project.iter_own_nodes(m):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "start":
                recv = node.func.value
                a = _self_attr(recv)
                if a is None and isinstance(recv, ast.Name):
                    a = loop_alias.get(recv.id)
                if a is not None:
                    started.add(a)
    live = {a: tgt for a, tgt in thread_attrs.items() if a in started}
    if not live:
        return []

    close_set = _close_reachable(project, cls, methods)
    events, queues = _event_queue_attrs(project, mod, methods)

    if not close_set:
        a, tgt = next(iter(live.items()))
        return [Finding(
            "TPU012",
            f"`{cls.name}` starts background thread `self.{a}` but has no "
            f"close/stop/__del__ path at all — the thread can never be "
            f"joined or told to exit, and queued work is dropped at "
            f"interpreter exit; add a close() that signals and joins it",
            mod.path, tgt.lineno, tgt.col_offset,
            f"{mod.name}.{cls.name}")]

    # evidence inside the close-reachable set: a join of the thread
    # attr (or of a loop var over it), an Event.set(), or a queue
    # sentinel put(None)
    joined: Set[str] = set()
    signalled = False
    for m in close_set:
        loop_alias = {}
        for node in project.iter_own_nodes(m):
            if isinstance(node, ast.For):
                a = _self_attr(node.iter)
                if a is not None and isinstance(node.target, ast.Name):
                    loop_alias[node.target.id] = a
        for node in project.iter_own_nodes(m):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            recv = node.func.value
            a = _self_attr(recv)
            if a is None and isinstance(recv, ast.Name):
                a = loop_alias.get(recv.id)
            if node.func.attr == "join" and a in live:
                joined.add(a)
            elif node.func.attr == "set" and a in events:
                signalled = True
            elif node.func.attr in ("put", "put_nowait") and a in queues \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value is None:
                signalled = True

    out: List[Finding] = []
    for a, tgt in live.items():
        if a in joined or signalled:
            continue
        rep = min((m for m in close_set if _is_close_method(m.name)),
                  key=lambda m: m.node.lineno)
        out.append(Finding(
            "TPU012",
            f"`{cls.name}.{rep.name}()` never joins or signals started "
            f"thread `self.{a}` — close returns while the worker still "
            f"runs (in-flight work races teardown); set a stop "
            f"Event/sentinel and join it",
            mod.path, rep.node.lineno, rep.node.col_offset,
            rep.full_name))
    return out
