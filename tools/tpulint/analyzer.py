"""Project indexing + trace-reachability for tpulint.

The analyzer is a whole-project pass, not a per-file one: rule scopes
depend on *reachability* ("can a jit trace reach this function?"),
which needs imports, the class hierarchy and a call graph across every
analyzed module.

Pipeline:

1. index every ``*.py`` file into a :class:`ModuleInfo` (import alias
   table, classes, functions — including nested defs);
2. resolve the class hierarchy to find ``Block``/``HybridBlock``
   subclasses (their ``forward``/``hybrid_forward`` run under
   ``jax.jit`` once hybridized — the CachedOp equivalence);
3. fixpoint over *jit wrappers*: ``jax.jit``/``pjit``/``shard_map``/
   ``pallas_call``/``lax.scan`` etc. seed the set; any analyzed
   function that passes one of its own parameters to a known wrapper
   becomes a wrapper itself (this is how ``_program_jits(raw_fn)``
   marks every ``raw_fn`` closure as a jit entry point);
4. BFS over call edges from the seeds → ``trace_reachable`` set, and a
   second BFS from per-step seeds (``Trainer.step``/``Optimizer.update``)
   → ``perstep_reachable`` set;
5. two more interprocedural passes reuse the same call graph:
   *shard-axis contexts* (which mesh axis names are bound by every
   ``shard_map``/``pmap``/``vmap(axis_name=)`` context a function is
   reachable from — TPU007's ground truth) and *thread reachability*
   (functions running on a ``threading.Thread`` target, transitively —
   TPU011/TPU012's ground truth).

The call graph is exposed as :meth:`Project.callees` /
:meth:`Project.callers` / :meth:`Project.call_sites` so rules can walk
it interprocedurally (e.g. resolving an ``axis_name`` parameter to the
string constants its analyzed callers actually pass).

Resolution is deliberately conservative in BOTH directions: bare names
only resolve within the module (or explicit imports), ``self.m()``
resolves through the declared ancestry — so host-only code (io,
recordio, tools) never gets dragged into trace scope, and trace scope
never silently loses a hop that a simple name lookup can prove.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# data model
# ---------------------------------------------------------------------------


@dataclass
class Finding:
    code: str
    message: str
    path: str
    line: int
    col: int
    function: str = ""
    # structured payload for machine consumers (``--format json``):
    # e.g. TPU013 carries {"cycle": [...], "edges": [...]}.  NOT part
    # of key()/fingerprints — a cycle rendered from a different edge
    # sample is still the same finding.
    extra: Optional[dict] = None

    def key(self) -> Tuple[str, int, int, str]:
        return (self.path, self.line, self.col, self.code)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.code} {self.message}"


@dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo"
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)   # dotted, resolved where possible
    methods: Dict[str, "FunctionInfo"] = field(default_factory=dict)
    is_block: bool = False       # descends from Block (TPU006 scope)
    is_hybrid: bool = False      # descends from HybridBlock (forward is traced)

    @property
    def full_name(self) -> str:
        return f"{self.module.name}.{self.name}"


@dataclass
class FunctionInfo:
    module: "ModuleInfo"
    qualname: str                      # "Class.method" / "outer.inner" / "func"
    name: str
    node: ast.FunctionDef
    cls: Optional[ClassInfo] = None
    trace_reachable: bool = False
    perstep_reachable: bool = False
    is_jit_wrapper: bool = False
    trace_reason: str = ""             # why it entered trace scope (diagnostics)
    # resolved name of the wrapper that seeded this function (e.g.
    # "jax.jit", "jax.lax.scan", or a project-local wrapper's full
    # name).  TPU008 keys off this: only real COMPILE boundaries
    # (jit/pjit/pallas_call) make closure capture a bug — control-flow
    # primitives (scan/cond) and shard_map bodies share the outer
    # trace, where capturing outer tracers is normal JAX.
    seed_wrapper: Optional[str] = None
    # -- shard-axis context (TPU007) ------------------------------------
    # axis names bound by every shard_map/pmap/vmap context this
    # function is reachable from (None until some context reaches it)
    shard_axes: Optional[Set[str]] = None
    # True when at least one reaching context's axes could not be
    # extracted statically — rules must not flag then
    shard_axes_unknown: bool = False
    shard_reason: str = ""
    # -- thread context (TPU011/TPU012) ---------------------------------
    thread_entry: bool = False         # literally a Thread(target=...)
    thread_reachable: bool = False     # entry or called from one
    # params declared static at the jit boundary (static_argnums/
    # static_argnames) — host values by contract, excluded from taint
    static_params: Set[str] = field(default_factory=set)
    # statics this function forwards to jit when IT is a wrapper
    wrapper_statics: Optional[Tuple[Tuple[int, ...], Tuple[str, ...]]] = None
    # argnums donated when this function RETURNS a donating jit
    # (`return jax.jit(g, donate_argnums=(0,))`) — TPU009 tracks the
    # returned callable through local bindings at call sites
    returns_donating: Optional[Tuple[int, ...]] = None

    @property
    def full_name(self) -> str:
        return f"{self.module.name}.{self.qualname}"


@dataclass
class ModuleInfo:
    name: str                          # dotted module name
    path: str
    tree: ast.Module
    source: str
    aliases: Dict[str, str] = field(default_factory=dict)
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)


# jit entry wrappers: calling one of these with a function argument
# makes that function's body run under trace.
JIT_WRAPPERS = {
    "jax.jit", "jax.pjit",
    "jax.experimental.pjit.pjit",
    "jax.experimental.shard_map.shard_map",
    "jax.experimental.pallas.pallas_call",
    "jax.eval_shape", "jax.make_jaxpr",
    "jax.vjp", "jax.jvp", "jax.grad", "jax.value_and_grad",
    "jax.vmap", "jax.pmap",
    "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.cond",
    "jax.lax.switch", "jax.lax.fori_loop", "jax.lax.map",
    "jax.lax.associative_scan", "jax.lax.custom_root",
}

# wrappers that additionally BIND mesh axis names for the wrapped
# function (collectives inside may name them).  `shard_map` is matched
# by resolved-name tail as well so project-local compat shims
# (parallel/compat.py) count — that is the cross-module propagation
# per-file linting could never see.
SHARD_WRAPPER_TAILS = {"shard_map", "pmap", "smap"}
AXIS_BINDING_WRAPPERS = {
    "jax.experimental.shard_map.shard_map", "jax.shard_map",
    "jax.pmap", "jax.vmap",
}

# collective ops that CONSUME an axis name (TPU007); tail names of
# jax.lax.* — matched on the resolved dotted path.
COLLECTIVE_FUNCS = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.psum_scatter", "jax.lax.all_gather", "jax.lax.all_to_all",
    "jax.lax.axis_index", "jax.lax.axis_size", "jax.lax.ppermute",
    "jax.lax.pshuffle", "jax.lax.pswapaxes",
}

THREAD_FACTORIES = {"threading.Thread", "threading.Timer"}

# methods whose bodies run once per training step (host code, but on
# the step critical path — explicit syncs there serialize the device
# queue).  Scoped to optimizer/trainer-like classes, see _perstep_seed.
PERSTEP_METHOD_NAMES = {"step", "update", "update_multi_precision"}
PERSTEP_CLASS_HINTS = ("Trainer", "Optimizer", "Updater", "KVStore", "LRScheduler")
# free functions documented as per-iteration utilities
PERSTEP_FUNCTION_NAMES = {"clip_global_norm", "allreduce_grads"}

BLOCK_ROOT_NAMES = {"Block", "HybridBlock", "SymbolBlock"}
# only these roots put `forward` under jit (plain eager Blocks —
# dataloader transforms etc. — are host-only by design)
HYBRID_ROOT_NAMES = {"HybridBlock", "SymbolBlock"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------


class _Indexer(ast.NodeVisitor):
    """One pass per module: aliases, classes, functions (incl. nested)."""

    def __init__(self, mod: ModuleInfo, pkg_parts: List[str]):
        self.mod = mod
        self.pkg_parts = pkg_parts      # package path of the module, for relative imports
        self.scope: List[str] = []      # qualname parts
        self.cls_stack: List[Optional[ClassInfo]] = []

    # -- imports --------------------------------------------------------- #
    def visit_Import(self, node: ast.Import):
        for a in node.names:
            self.mod.aliases[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0])
            if a.asname:
                self.mod.aliases[a.asname] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.level:
            base_parts = self.pkg_parts[: len(self.pkg_parts) - (node.level - 1)]
            base = ".".join(base_parts + ([node.module] if node.module else []))
        else:
            base = node.module or ""
        for a in node.names:
            if a.name == "*":
                continue
            target = f"{base}.{a.name}" if base else a.name
            self.mod.aliases[a.asname or a.name] = target

    # -- defs ------------------------------------------------------------- #
    def visit_ClassDef(self, node: ast.ClassDef):
        info = ClassInfo(name=node.name, module=self.mod, node=node)
        for b in node.bases:
            d = dotted_name(b)
            if d:
                info.bases.append(d)
        self.mod.classes[node.name] = info
        self.scope.append(node.name)
        self.cls_stack.append(info)
        self.generic_visit(node)
        self.cls_stack.pop()
        self.scope.pop()

    def _visit_func(self, node):
        qual = ".".join(self.scope + [node.name])
        cls = self.cls_stack[-1] if self.cls_stack else None
        info = FunctionInfo(module=self.mod, qualname=qual, name=node.name,
                            node=node, cls=cls)
        self.mod.functions[qual] = info
        if cls is not None and len(self.scope) and self.scope[-1] == cls.name:
            cls.methods[node.name] = info
        self.scope.append(node.name)
        self.cls_stack.append(None)     # nested defs are not methods
        self.generic_visit(node)
        self.cls_stack.pop()
        self.scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


# ---------------------------------------------------------------------------
# project
# ---------------------------------------------------------------------------


class Project:
    """The analyzed file set plus all derived graphs."""

    def __init__(self, paths: List[str]):
        self.modules: Dict[str, ModuleInfo] = {}
        self.errors: List[str] = []
        for f in self._collect_files(paths):
            self._index_file(f)
        self._resolve_block_classes()
        self._compute_jit_wrappers()
        self._build_call_graph()
        self._compute_reachability()
        self._compute_shard_axes()
        self._compute_thread_reachable()
        self._compute_donations()
        self._compute_registrations()

    # -- file discovery --------------------------------------------------- #
    @staticmethod
    def _collect_files(paths: List[str]) -> List[str]:
        out: List[str] = []
        for p in paths:
            if os.path.isdir(p):
                for root, dirs, files in os.walk(p):
                    dirs[:] = sorted(d for d in dirs
                                     if d not in ("__pycache__", ".git"))
                    for fn in sorted(files):
                        if fn.endswith(".py"):
                            out.append(os.path.join(root, fn))
            elif p.endswith(".py"):
                out.append(p)
        return out

    @staticmethod
    def _module_name(path: str) -> Tuple[str, List[str]]:
        """Dotted module name from the filesystem (walk up __init__.py)."""
        ap = os.path.abspath(path)
        parts = [os.path.splitext(os.path.basename(ap))[0]]
        d = os.path.dirname(ap)
        while os.path.exists(os.path.join(d, "__init__.py")):
            parts.append(os.path.basename(d))
            d = os.path.dirname(d)
        parts.reverse()
        if parts[-1] == "__init__":
            parts.pop()
        name = ".".join(parts)
        pkg_parts = parts if path.endswith("__init__.py") else parts[:-1]
        return name, pkg_parts

    def _index_file(self, path: str):
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except (SyntaxError, UnicodeDecodeError) as e:
            self.errors.append(f"{path}: {e}")
            return
        name, pkg_parts = self._module_name(path)
        mod = ModuleInfo(name=name, path=path, tree=tree, source=src)
        _Indexer(mod, pkg_parts).visit(tree)
        self.modules[name] = mod

    # -- resolution helpers ----------------------------------------------- #
    def resolve(self, mod: ModuleInfo, dotted: str) -> str:
        """Expand the leading alias of a dotted path via the module's
        import table ('onp.asarray' → 'numpy.asarray')."""
        head, _, rest = dotted.partition(".")
        target = mod.aliases.get(head)
        if target is None:
            return dotted
        return f"{target}.{rest}" if rest else target

    def lookup_function(self, full: str) -> Optional[FunctionInfo]:
        """FunctionInfo for a fully resolved dotted path, if analyzed."""
        modname, _, qual = full.rpartition(".")
        while modname:
            m = self.modules.get(modname)
            if m is not None:
                return m.functions.get(qual)
            modname, _, head = modname.rpartition(".")
            qual = f"{head}.{qual}"
        return None

    def lookup_class(self, full: str) -> Optional[ClassInfo]:
        modname, _, cname = full.rpartition(".")
        m = self.modules.get(modname)
        if m is not None:
            return m.classes.get(cname)
        # re-exported through a package __init__? follow one alias hop.
        if m is None and modname:
            pkg = self.modules.get(modname) or self.modules.get(modname + ".__init__")
            if pkg is not None:
                tgt = pkg.aliases.get(cname)
                if tgt and tgt != full:
                    return self.lookup_class(tgt)
        return None

    def _class_ancestry(self, cls: ClassInfo, seen=None) -> List[ClassInfo]:
        if seen is None:
            seen = set()
        out = []
        for b in cls.bases:
            resolved = self.resolve(cls.module, b)
            cand = self.lookup_class(resolved) or cls.module.classes.get(b)
            if cand is not None and id(cand) not in seen:
                seen.add(id(cand))
                out.append(cand)
                out.extend(self._class_ancestry(cand, seen))
        return out

    # -- block subclasses -------------------------------------------------- #
    def _resolve_block_classes(self):
        changed = True
        while changed:
            changed = False
            for mod in self.modules.values():
                for cls in mod.classes.values():
                    for b in cls.bases:
                        resolved = self.resolve(mod, b)
                        tail = resolved.rpartition(".")[2]
                        base_cls = self.lookup_class(resolved) or mod.classes.get(b)
                        if not cls.is_block and (
                                tail in BLOCK_ROOT_NAMES
                                or (base_cls is not None and base_cls.is_block)):
                            cls.is_block = True
                            changed = True
                        if not cls.is_hybrid and (
                                tail in HYBRID_ROOT_NAMES
                                or (base_cls is not None and base_cls.is_hybrid)):
                            cls.is_hybrid = True
                            changed = True

    # -- jit wrapper fixpoint ---------------------------------------------- #
    def _iter_calls(self, fn: FunctionInfo):
        """Call nodes in fn's own body (nested defs excluded — they have
        their own FunctionInfo; lambdas stay with the parent)."""
        skip: Set[int] = set()
        for child in ast.walk(fn.node):
            if child is fn.node:
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(child):
                    skip.add(id(sub))
        for child in ast.walk(fn.node):
            if isinstance(child, ast.Call) and id(child) not in skip:
                yield child

    def iter_own_nodes(self, fn: FunctionInfo):
        """All AST nodes belonging to fn's own body (nested defs excluded)."""
        skip: Set[int] = set()
        for child in ast.walk(fn.node):
            if child is fn.node:
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(child):
                    skip.add(id(sub))
        for child in ast.walk(fn.node):
            if id(child) not in skip:
                yield child

    def is_jit_wrapper_call(self, mod: ModuleInfo, call: ast.Call) -> bool:
        d = dotted_name(call.func)
        if d is None:
            return False
        resolved = self.resolve(mod, d)
        if resolved in JIT_WRAPPERS:
            return True
        target = self.lookup_function(resolved)
        return target is not None and target.is_jit_wrapper

    @staticmethod
    def _call_arg_names(call: ast.Call) -> List[str]:
        names = [a.id for a in call.args if isinstance(a, ast.Name)]
        # *args forwarding counts: `_shard_map(*args, **kwargs)` passes
        # the vararg tuple through — without this, a compat shim like
        # parallel/compat.shard_map breaks wrapper propagation and every
        # shard_map body behind it silently drops out of trace scope
        names += [a.value.id for a in call.args
                  if isinstance(a, ast.Starred) and isinstance(a.value, ast.Name)]
        names += [kw.value.id for kw in call.keywords
                  if isinstance(kw.value, ast.Name)]
        return names

    @staticmethod
    def _extract_statics(call: ast.Call) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
        """(static_argnums, static_argnames) constants from a jit call."""

        def consts(node, typ):
            if isinstance(node, ast.Constant) and isinstance(node.value, typ):
                return (node.value,)
            if isinstance(node, (ast.Tuple, ast.List)):
                return tuple(e.value for e in node.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, typ))
            return ()

        nums: Tuple[int, ...] = ()
        names: Tuple[str, ...] = ()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                nums = consts(kw.value, int)
            elif kw.arg == "static_argnames":
                names = consts(kw.value, str)
        return nums, names

    @staticmethod
    def _apply_statics(fn: "FunctionInfo",
                       nums: Tuple[int, ...], names: Tuple[str, ...]):
        pos = fn.node.args.posonlyargs + fn.node.args.args
        for i in nums:
            if 0 <= i < len(pos):
                fn.static_params.add(pos[i].arg)
        all_names = {a.arg for a in pos + fn.node.args.kwonlyargs}
        fn.static_params.update(set(names) & all_names)

    def _local_fn_aliases(self, fn: FunctionInfo) -> Dict[str, str]:
        """Local `x = some_fn` / `x = functools.partial(some_fn, ...)`
        bindings — so `pl.pallas_call(kernel, ...)` seeds the kernel def
        even when it went through a local variable or a partial."""
        out: Dict[str, str] = {}
        for node in self.iter_own_nodes(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            v = node.value
            tname = None
            if isinstance(v, ast.Name):
                tname = v.id
            elif isinstance(v, ast.Call):
                d = dotted_name(v.func)
                if d is not None and self.resolve(fn.module, d) in (
                        "functools.partial", "partial") and v.args:
                    tname = dotted_name(v.args[0])
            if tname is not None:
                out[node.targets[0].id] = tname
        return out

    def _candidate_fn_args(self, fn: FunctionInfo, call: ast.Call) -> List[str]:
        """Names plausibly naming a function among a call's arguments —
        bare names plus the inner target of inline functools.partial."""
        names = self._call_arg_names(call)
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(a, ast.Call):
                d = dotted_name(a.func)
                if d is not None and self.resolve(fn.module, d) in (
                        "functools.partial", "partial") and a.args:
                    inner = dotted_name(a.args[0])
                    if inner is not None:
                        names.append(inner)
        return names

    def _compute_jit_wrappers(self):
        """f is a jit wrapper iff it passes one of its own parameters to
        a known wrapper — transitive (`_program_jits(raw_fn)` chains)."""
        changed = True
        while changed:
            changed = False
            for mod in self.modules.values():
                for fn in mod.functions.values():
                    if fn.is_jit_wrapper:
                        continue
                    params = {a.arg for a in (fn.node.args.posonlyargs
                                              + fn.node.args.args
                                              + fn.node.args.kwonlyargs)}
                    for va in (fn.node.args.vararg, fn.node.args.kwarg):
                        if va is not None:
                            params.add(va.arg)
                    for call in self._iter_calls(fn):
                        if not self.is_jit_wrapper_call(mod, call):
                            continue
                        if any(n in params for n in self._call_arg_names(call)):
                            fn.is_jit_wrapper = True
                            fn.wrapper_statics = self._extract_statics(call)
                            changed = True
                            break

    # -- seeds + reachability ---------------------------------------------- #
    def _decorator_seeds(self, fn: FunctionInfo) -> bool:
        for dec in fn.node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            d = dotted_name(target)
            if d and self.resolve(fn.module, d) in JIT_WRAPPERS:
                if isinstance(dec, ast.Call):
                    self._apply_statics(fn, *self._extract_statics(dec))
                fn.seed_wrapper = self.resolve(fn.module, d)
                return True
            # @partial(jax.jit, ...) / @functools.partial(jax.jit, ...)
            if isinstance(dec, ast.Call) and d is not None:
                r = self.resolve(fn.module, d)
                if r in ("functools.partial", "partial") and dec.args:
                    inner = dotted_name(dec.args[0])
                    if inner and self.resolve(fn.module, inner) in JIT_WRAPPERS:
                        self._apply_statics(fn, *self._extract_statics(dec))
                        fn.seed_wrapper = self.resolve(fn.module, inner)
                        return True
        return False

    def _seed_functions(self) -> List[FunctionInfo]:
        seeds: List[FunctionInfo] = []
        for mod in self.modules.values():
            for fn in mod.functions.values():
                if fn.cls is not None and (
                        (fn.cls.is_hybrid and fn.name == "forward")
                        or (fn.cls.is_block and fn.name == "hybrid_forward")):
                    fn.trace_reason = "Block forward (runs under jit when hybridized)"
                    seeds.append(fn)
                elif self._decorator_seeds(fn):
                    fn.trace_reason = "jit-decorated"
                    seeds.append(fn)
        # functions passed (by name, local alias, or inline partial) to a
        # jit wrapper call anywhere
        for mod in self.modules.values():
            for caller in mod.functions.values():
                local_aliases = None
                for call in self._iter_calls(caller):
                    if not self.is_jit_wrapper_call(mod, call):
                        continue
                    d = dotted_name(call.func)
                    resolved_w = self.resolve(mod, d) if d else None
                    if resolved_w in JIT_WRAPPERS:
                        statics = self._extract_statics(call)
                    else:
                        wfn = self.lookup_function(resolved_w) if resolved_w else None
                        statics = (wfn.wrapper_statics or ((), ())) if wfn else ((), ())
                    if local_aliases is None:
                        local_aliases = self._local_fn_aliases(caller)
                    for n in self._candidate_fn_args(caller, call):
                        n = local_aliases.get(n, n)
                        target = (mod.functions.get(f"{caller.qualname}.{n}")
                                  or mod.functions.get(n))
                        if target is None:
                            resolved = self.resolve(mod, n)
                            target = self.lookup_function(resolved)
                        if target is not None and not target.trace_reason:
                            target.trace_reason = (
                                f"passed to jit wrapper in {caller.qualname}")
                            target.seed_wrapper = resolved_w
                            self._apply_statics(target, *statics)
                            seeds.append(target)
        return seeds

    def _perstep_seeds(self) -> List[FunctionInfo]:
        seeds = []
        for mod in self.modules.values():
            for fn in mod.functions.values():
                if fn.cls is None:
                    if fn.name in PERSTEP_FUNCTION_NAMES:
                        seeds.append(fn)
                    continue
                if fn.name not in PERSTEP_METHOD_NAMES:
                    continue
                names = [fn.cls.name] + [c.name for c in self._class_ancestry(fn.cls)]
                if any(h in n for n in names for h in PERSTEP_CLASS_HINTS):
                    seeds.append(fn)
        return seeds

    def _resolve_call_target(self, fn: FunctionInfo,
                             d: str) -> Optional[FunctionInfo]:
        """FunctionInfo a dotted callee name resolves to from inside
        `fn` (nested def / module def / import / self.method)."""
        mod = fn.module
        if "." not in d:
            # bare name: nested def, module-level def, or import
            target = (mod.functions.get(f"{fn.qualname}.{d}")
                      or mod.functions.get(d))
            if target is None:
                resolved = self.resolve(mod, d)
                if resolved != d:
                    target = self.lookup_function(resolved)
            return target
        head, _, rest = d.partition(".")
        if head == "self" and fn.cls is not None and "." not in rest:
            target = fn.cls.methods.get(rest)
            if target is None:
                for anc in self._class_ancestry(fn.cls):
                    target = anc.methods.get(rest)
                    if target is not None:
                        break
            return target
        return self.lookup_function(self.resolve(mod, d))

    def _build_call_graph(self):
        """One resolution pass over every call: forward edges (callees),
        reverse edges (callers) and the concrete call sites.  Every
        later pass (reachability, shard axes, threads, TPU007's
        axis-parameter resolution, TPU011's lock propagation) walks
        these maps instead of re-resolving."""
        self._callee_map: Dict[int, List[FunctionInfo]] = {}
        self._caller_map: Dict[int, List[FunctionInfo]] = {}
        self._site_map: Dict[int, List[Tuple[FunctionInfo, ast.Call]]] = {}
        for mod in self.modules.values():
            for fn in mod.functions.values():
                out = self._callee_map.setdefault(id(fn), [])
                for call in self._iter_calls(fn):
                    d = dotted_name(call.func)
                    if d is None:
                        continue
                    target = self._resolve_call_target(fn, d)
                    if target is None:
                        continue
                    if target not in out:
                        out.append(target)
                    callers = self._caller_map.setdefault(id(target), [])
                    if fn not in callers:
                        callers.append(fn)
                    self._site_map.setdefault(id(target), []).append((fn, call))

    def callees(self, fn: FunctionInfo) -> List[FunctionInfo]:
        return self._callee_map.get(id(fn), [])

    def callers(self, fn: FunctionInfo) -> List[FunctionInfo]:
        return self._caller_map.get(id(fn), [])

    def call_sites(self, fn: FunctionInfo) -> List[Tuple["FunctionInfo", ast.Call]]:
        """(caller, call-node) pairs for every resolved call of `fn`."""
        return self._site_map.get(id(fn), [])

    def _callees(self, fn: FunctionInfo) -> List[FunctionInfo]:
        return self._callee_map.get(id(fn), [])

    def _compute_reachability(self):
        seeds = self._seed_functions()
        work = list(seeds)
        for fn in work:
            fn.trace_reachable = True
        while work:
            fn = work.pop()
            for callee in self._callees(fn):
                if not callee.trace_reachable:
                    callee.trace_reachable = True
                    callee.trace_reason = callee.trace_reason or (
                        f"called from {fn.full_name}")
                    work.append(callee)
        work = self._perstep_seeds()
        for fn in work:
            fn.perstep_reachable = True
        while work:
            fn = work.pop()
            for callee in self._callees(fn):
                if not callee.perstep_reachable and not callee.trace_reachable:
                    callee.perstep_reachable = True
                    work.append(callee)

    # -- shard-axis contexts (TPU007) --------------------------------------- #
    def is_shard_binding_call(self, mod: ModuleInfo, call: ast.Call) -> Optional[str]:
        """'shard' / 'pmap' / 'vmap' when this call binds mesh axis
        names for its function argument, else None.  Matched on the
        resolved tail so project-local shard_map compat shims count."""
        d = dotted_name(call.func)
        if d is None:
            return None
        resolved = self.resolve(mod, d)
        tail = resolved.rpartition(".")[2]
        if resolved in ("jax.pmap", "jax.vmap"):
            return "pmap" if resolved == "jax.pmap" else "vmap"
        if resolved in AXIS_BINDING_WRAPPERS or tail in SHARD_WRAPPER_TAILS:
            return "shard"
        return None

    def _shard_call_axes(self, caller: FunctionInfo, call: ast.Call,
                         kind: str) -> Set[str]:
        """Axis-name string constants a shard-wrapper call site binds.

        For shard_map every string constant in the call is collected
        (P(...) specs, axis_names=, partial-bound axis kwargs), plus —
        through one level of local single-assignment resolution — the
        strings behind spec/mesh variables (`in_specs = (P("data"),)`,
        `mesh = Mesh(devs, ("data", "model"))`).  Over-collection only
        widens the bound set (false-negative direction); an EMPTY
        result marks the context unextractable and disables TPU007
        along everything it reaches.

        The mesh argument is the gate: a mesh binds EVERY axis of the
        device grid, not just the ones the in/out specs name, so when
        the mesh expression doesn't resolve to a visible
        ``Mesh(..., ("a", "b"))`` construction (it usually arrives as a
        function parameter), the bound set is unknowable and the whole
        context poisons to unknown."""
        if kind in ("pmap", "vmap"):
            out: Set[str] = set()
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) \
                                and isinstance(sub.value, str):
                            out.add(sub.value)
            return out
        local_assigns: Dict[str, ast.AST] = {}
        for node in self.iter_own_nodes(caller):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                local_assigns[node.targets[0].id] = node.value

        out = set()

        def collect(node: ast.AST, depth: int):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    out.add(sub.value)
                elif isinstance(sub, ast.Name) and depth < 2:
                    v = local_assigns.get(sub.id)
                    if v is not None and v is not node:
                        collect(v, depth + 1)

        # locate the mesh expression: kwarg, or shard_map's 2nd
        # positional; resolve one local-assign hop
        mesh_expr: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg == "mesh":
                mesh_expr = kw.value
        if mesh_expr is None and len(call.args) > 1:
            mesh_expr = call.args[1]
        if isinstance(mesh_expr, ast.Name):
            mesh_expr = local_assigns.get(mesh_expr.id, mesh_expr)
        mesh_visible = mesh_expr is not None and any(
            isinstance(sub, ast.Call)
            and (dotted_name(sub.func) or "").rpartition(".")[2]
            in ("Mesh", "AbstractMesh", "make_mesh")
            for sub in ast.walk(mesh_expr))
        if not mesh_visible:
            return set()       # unknowable axis set → poison to unknown

        collect(call, 0)
        return out

    def _compute_shard_axes(self):
        """Seed the functions passed to axis-binding wrappers with the
        axes their call sites bind, then propagate through the call
        graph (union at joins — an axis bound by ANY reaching context
        is never flagged, the conservative direction for TPU007)."""
        work: List[FunctionInfo] = []

        def merge(fn: FunctionInfo, axes: Set[str], unknown: bool,
                  reason: str) -> None:
            changed = False
            if fn.shard_axes is None:
                fn.shard_axes = set(axes)
                fn.shard_reason = reason
                changed = True
            elif not axes <= fn.shard_axes:
                fn.shard_axes |= axes
                changed = True
            if unknown and not fn.shard_axes_unknown:
                fn.shard_axes_unknown = True
                changed = True
            if changed:
                work.append(fn)

        for mod in self.modules.values():
            for caller in mod.functions.values():
                local_aliases = None
                for call in self._iter_calls(caller):
                    kind = self.is_shard_binding_call(mod, call)
                    if kind is None:
                        continue
                    axes = self._shard_call_axes(caller, call, kind)
                    if local_aliases is None:
                        local_aliases = self._local_fn_aliases(caller)
                    for n in self._candidate_fn_args(caller, call):
                        n = local_aliases.get(n, n)
                        target = self._resolve_call_target(caller, n)
                        if target is not None:
                            merge(target, axes, not axes,
                                  f"wrapped by {kind} in {caller.qualname}")
        while work:
            fn = work.pop()
            for callee in self.callees(fn):
                merge(callee, fn.shard_axes or set(),
                      fn.shard_axes_unknown,
                      callee.shard_reason or f"called from {fn.full_name}")

    # -- thread reachability (TPU011/TPU012) -------------------------------- #
    def thread_target_of(self, fn: FunctionInfo,
                         call: ast.Call) -> Optional[FunctionInfo]:
        """The analyzed function a `threading.Thread(target=...)` call
        names, if this call is a thread construction."""
        d = dotted_name(call.func)
        if d is None:
            return None
        resolved = self.resolve(fn.module, d)
        if resolved not in THREAD_FACTORIES:
            return None
        for kw in call.keywords:
            if kw.arg == "target":
                t = dotted_name(kw.value)
                if t is None:
                    return None
                return self._resolve_call_target(fn, t)
        return None

    def _compute_thread_reachable(self):
        work: List[FunctionInfo] = []
        for mod in self.modules.values():
            for fn in mod.functions.values():
                for call in self._iter_calls(fn):
                    target = self.thread_target_of(fn, call)
                    if target is not None and not target.thread_entry:
                        target.thread_entry = True
                        work.append(target)
        for fn in work:
            fn.thread_reachable = True
        while work:
            fn = work.pop()
            for callee in self.callees(fn):
                if not callee.thread_reachable:
                    callee.thread_reachable = True
                    work.append(callee)

    # -- donation records (TPU009) ------------------------------------------ #
    def donating_jit_nums(self, mod: ModuleInfo,
                          node: ast.AST) -> Optional[Tuple[int, ...]]:
        """Constant donate_argnums of a `jax.jit(...)` expression, or
        None when `node` is not a donating jit / the nums aren't
        literal (dynamic donation lists are skipped, conservatively)."""
        if not isinstance(node, ast.Call):
            return None
        d = dotted_name(node.func)
        if d is None or self.resolve(mod, d) not in JIT_WRAPPERS:
            return None
        for kw in node.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                if kw.arg == "donate_argnames":
                    return None      # name-keyed donation: positions unknown
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)) and all(
                        isinstance(e, ast.Constant)
                        and isinstance(e.value, int) for e in v.elts):
                    return tuple(e.value for e in v.elts)
                return None
        return None

    def _compute_donations(self):
        """Record donation carriers TPU009 tracks interprocedurally:
        functions whose return value is a donating jit, and class
        attributes holding one (`self._fn = jax.jit(..., donate_argnums=)`
        in one method, called from another)."""
        self.donating_attrs: Dict[Tuple[int, str], Tuple[int, ...]] = {}
        for mod in self.modules.values():
            for fn in mod.functions.values():
                for node in self.iter_own_nodes(fn):
                    if isinstance(node, ast.Return) and node.value is not None:
                        vals = node.value.elts if isinstance(
                            node.value, ast.Tuple) else [node.value]
                        for i, v in enumerate(vals):
                            nums = self.donating_jit_nums(mod, v)
                            if nums is not None and i == 0:
                                fn.returns_donating = nums
                    elif isinstance(node, ast.Assign) and fn.cls is not None:
                        nums = self.donating_jit_nums(mod, node.value)
                        if nums is None:
                            continue
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Attribute) \
                                    and isinstance(tgt.value, ast.Name) \
                                    and tgt.value.id == "self":
                                self.donating_attrs[
                                    (id(fn.cls), tgt.attr)] = nums

    # -- handler registrations (TPU013/TPU015/TPU016) ------------------------ #
    def _compute_registrations(self):
        """Registration-based call facts the lock pass consumes:

        * ``signal_handlers`` — functions installed via
          ``signal.signal(sig, handler)``;
        * ``section_callbacks`` — functions registered through a
          ``register_section(name, fn)``-style hook (the flight
          recorder's dump contributors);
        * ``section_dispatchers`` — functions in the module DEFINING
          ``register_section`` that read its registry dict and call an
          element (``for name, fn in _sections.items(): fn()``) — the
          statically-invisible indirect call the lock pass turns into
          dispatcher→callback edges.

        Kept OUT of the main call graph on purpose: registration edges
        are lock-pass facts, and splicing them into ``callees()`` would
        silently widen trace/thread reachability for every other rule.
        """
        self.signal_handlers: List[FunctionInfo] = []
        self.section_callbacks: List[FunctionInfo] = []
        self.section_dispatchers: List[FunctionInfo] = []

        def resolve_fn_arg(fn: FunctionInfo, node: ast.AST
                           ) -> Optional[FunctionInfo]:
            d = dotted_name(node)
            if d is None:
                return None
            return self._resolve_call_target(fn, d)

        # the registry dict `register_section` stores into, per module
        registry_names: Dict[str, str] = {}
        for mod in self.modules.values():
            reg = mod.functions.get("register_section")
            if reg is None:
                continue
            for node in self.iter_own_nodes(reg):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.targets[0], ast.Subscript) \
                        and isinstance(node.targets[0].value, ast.Name):
                    registry_names[mod.name] = node.targets[0].value.id

        for mod in self.modules.values():
            for fn in mod.functions.values():
                for call in self._iter_calls(fn):
                    d = dotted_name(call.func)
                    if d is None:
                        continue
                    resolved = self.resolve(mod, d)
                    tail = resolved.rpartition(".")[2]
                    if resolved == "signal.signal" and len(call.args) >= 2:
                        target = resolve_fn_arg(fn, call.args[1])
                        if target is not None \
                                and target not in self.signal_handlers:
                            self.signal_handlers.append(target)
                    elif tail == "register_section" and len(call.args) >= 2:
                        target = resolve_fn_arg(fn, call.args[1])
                        if target is not None \
                                and target not in self.section_callbacks:
                            self.section_callbacks.append(target)
        for modname, regname in registry_names.items():
            mod = self.modules[modname]
            for fn in mod.functions.values():
                if fn.name == "register_section":
                    continue
                reads_registry = any(
                    isinstance(n, ast.Name) and n.id == regname
                    for n in self.iter_own_nodes(fn))
                calls_bare = any(
                    isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id not in mod.aliases
                    and mod.functions.get(n.func.id) is None
                    for n in self.iter_own_nodes(fn))
                if reads_registry and calls_bare \
                        and fn not in self.section_dispatchers:
                    self.section_dispatchers.append(fn)

    # -- public ------------------------------------------------------------ #
    def iter_functions(self):
        for mod in self.modules.values():
            for fn in mod.functions.values():
                yield fn

    def trace_reachable_functions(self) -> List[FunctionInfo]:
        return [f for f in self.iter_functions() if f.trace_reachable]
