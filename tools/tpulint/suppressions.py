"""Suppression directives for tpulint.

Syntax (all forms take a comma-separated code list, or no list to
suppress every rule; the ``-- reason`` tail is free text, REQUIRED
under ``--strict``):

    x = onp.dot(a, b)   # tpulint: disable=TPU001 -- host fallback, tiny
    # tpulint: disable-next=TPU002,TPU004 -- deliberate sync point
    y = float(loss)
    # tpulint: disable-file=TPU005 -- this module is a debug shim

Directive parsing is line-based on the raw source (AST nodes drop
comments), so a directive also covers findings whose node *starts* on
the directive's line — multi-line statements suppress at the line the
finding points at.
"""
from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

from .analyzer import Finding

_DIRECTIVE = re.compile(
    r"#\s*tpulint:\s*(?P<kind>disable(?:-next|-file)?)"
    r"(?:\s*=\s*(?P<codes>[A-Z0-9, ]+))?"
    r"(?P<reason>\s*--\s*\S.*)?")

ALL = "ALL"


class Suppressions:
    """Per-file directive table + bookkeeping for `--strict` checks."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.line_codes: Dict[int, Set[str]] = {}
        self.file_codes: Set[str] = set()
        self.missing_reason: List[Tuple[int, str]] = []
        self.used: Set[int] = set()
        for i, line in enumerate(source.splitlines(), start=1):
            m = _DIRECTIVE.search(line)
            if m is None:
                continue
            codes = {c.strip() for c in (m.group("codes") or ALL).split(",")
                     if c.strip()}
            kind = m.group("kind")
            if m.group("reason") is None:
                self.missing_reason.append((i, kind))
            if kind == "disable":
                self.line_codes.setdefault(i, set()).update(codes)
            elif kind == "disable-next":
                self.line_codes.setdefault(i + 1, set()).update(codes)
            elif kind == "disable-file":
                self.file_codes.update(codes)

    def suppresses(self, finding: Finding) -> bool:
        if ALL in self.file_codes or finding.code in self.file_codes:
            return True
        codes = self.line_codes.get(finding.line)
        if codes is not None and (ALL in codes or finding.code in codes):
            self.used.add(finding.line)
            return True
        return False

    def strict_findings(self) -> List[Finding]:
        """TPU000 diagnostics: suppressions without a reason."""
        return [
            Finding("TPU000",
                    f"`# tpulint: {kind}` without a `-- reason` "
                    f"(required in --strict mode)",
                    self.path, line, 0)
            for line, kind in self.missing_reason
        ]


def apply_suppressions(findings: List[Finding],
                       sources: Dict[str, str],
                       strict: bool = False) -> List[Finding]:
    tables = {path: Suppressions(path, src) for path, src in sources.items()}
    kept = [f for f in findings
            if f.path not in tables or not tables[f.path].suppresses(f)]
    if strict:
        for t in tables.values():
            kept.extend(t.strict_findings())
        kept.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return kept
