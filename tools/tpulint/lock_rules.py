"""Lock-order / deadlock rules TPU013–TPU016 (tpulint v3).

The serving plane is multi-threaded (engine scheduler, HTTP acceptor,
checkpoint worker, prefetchers, signal-time flight-recorder dumps) and
the bug class that actually wedges a fleet is invisible to per-site
rules: a lock-order inversion between two threads, a blocking device /
queue / join call made while holding the scheduler lock, or a signal
handler blocking on a lock the interrupted thread already holds.

This pass builds a **per-object lock-acquisition graph**:

1. *lock identities* — every ``threading.Lock``/``RLock``/``Condition``
   construction is a node, keyed ``module.Class.attr`` (instance
   attribute, canonicalized to the ancestor class that assigns it) or
   ``module.var`` (module-level).  ``Condition(existing_lock)`` is an
   **alias** of the underlying lock's node — ``self._work =
   Condition(self._lock)`` and the engine lock are one object;
2. *acquisition sites* — ``with lock:`` blocks, explicit
   ``lock.acquire()`` (classified blocking vs try: a ``blocking=False``
   or finite ``timeout=`` acquire cannot deadlock and never creates an
   edge), and ``Condition.wait()`` re-acquisition;
3. *held-while-acquiring edges* — propagated interprocedurally over
   the analyzer call graph **plus** a lock-pass-local typed resolution
   layer (constructor-assigned attribute types, annotated parameters,
   return annotations — so ``telemetry.gauge(...).set()`` under the
   engine lock resolves through ``Registry.gauge -> Gauge`` to the
   metric lock) **plus** registration facts (``signal.signal`` handlers
   and flight-recorder ``register_section`` callbacks, whose calls are
   statically invisible ``fn()`` dispatches).

Rules over the graph:

* **TPU013** — lock-order cycle: a strongly connected component in the
  edge graph means two threads can acquire the same pair of locks in
  opposite order; the finding carries the cycle and both acquisition
  stacks (``extra={"cycle": ..., "edges": ...}``, also emitted by
  ``--format json``);
* **TPU014** — ``Condition.wait()`` outside a ``while`` predicate loop
  (a bare ``if``-recheck or none at all → lost wakeup on spurious
  notify / multi-waiter races);
* **TPU015** — blocking call under a *hot* lock: device dispatch or
  host sync, un-timed ``queue.put/get/join``, ``Thread.join`` or
  ``time.sleep`` reachable while holding a lock that more than one
  thread context (scheduler/main/signal) also takes;
* **TPU016** — signal-handler lock safety: functions reachable from a
  ``signal.signal`` handler or a flight-recorder section callback
  (within the handler's own module — cross-module library locks are
  the callee's audit) may only use try-lock acquisition
  (``acquire(timeout=...)`` / ``acquire(False)``), never a blocking
  ``with lock:`` — the interrupted thread may already hold it, and a
  signal handler that blocks on it self-deadlocks the process.

The runtime counterpart (``incubator_mxnet_tpu/lock_witness.py``)
records *actual* per-thread acquisition order and cross-checks every
observed edge against :func:`build_lock_graph`'s static edges — the
analyzer is validated against reality, not only fixtures.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .analyzer import (Finding, FunctionInfo, ModuleInfo, Project,
                       dotted_name)

LOCK_RULES = ("TPU013", "TPU014", "TPU015", "TPU016")

LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
}

# callables that park the calling thread unboundedly (TPU015)
BLOCKING_FUNCS = {
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "urllib.request.urlopen", "socket.create_connection",
}

# device dispatch / host-sync entry points: these drain or feed the
# device queue — tens of ms under a lock every submitter contends on.
# `numpy.asarray` is included because materializing a device array
# through it is the package's standard sync idiom.
DEVICE_FUNCS = {"jax.device_get", "jax.block_until_ready",
                "jax.device_put", "numpy.asarray"}
DEVICE_TAILS = {"_timed_decode", "block_until_ready"}

QUEUE_CTORS = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue"}
THREAD_CTORS = {"threading.Thread", "threading.Timer"}


# ---------------------------------------------------------------------------
# typed resolution (lock-pass local — deliberately NOT part of the main
# call graph: widening callees() would silently grow trace/thread
# reachability for every other rule)
# ---------------------------------------------------------------------------


class _TypeEnv:
    """Light nominal types: constructor-assigned attributes
    (``self._slo = SloTracker(...)``), module globals
    (``_default_registry = Registry()``), annotated parameters
    (``engine: "ServingEngine"``) and return annotations
    (``def gauge(...) -> Gauge``)."""

    def __init__(self, project: Project):
        self.p = project
        # (class full_name, attr) -> type string (class full name or
        # stdlib ctor like "queue.Queue")
        self.attr_types: Dict[Tuple[str, str], str] = {}
        self.global_types: Dict[Tuple[str, str], str] = {}
        self._locals: Dict[int, Dict[str, str]] = {}
        self._build()

    # -- building --------------------------------------------------------- #
    def _class_named(self, mod: ModuleInfo, name: str):
        """lookup_class through import aliases AND module-local bare
        names (``Request`` inside engine.py — resolve() only maps
        aliases, so same-module classes need the module prefix)."""
        cls = self.p.lookup_class(self.p.resolve(mod, name))
        if cls is None and "." not in name:
            cls = self.p.lookup_class(f"{mod.name}.{name}")
        return cls

    def _ann_type(self, mod: ModuleInfo, ann) -> Optional[str]:
        name = None
        if isinstance(ann, ast.Name):
            name = ann.id
        elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value
        elif isinstance(ann, ast.Attribute):
            name = dotted_name(ann)
        elif isinstance(ann, ast.Subscript):        # Optional["X"] etc.
            return self._ann_type(mod, ann.slice)
        elif isinstance(ann, ast.Tuple):
            # Dict[K, V] slice: prefer the value type — container
            # types deliberately degrade to their ELEMENT type here
            # (iteration/subscript then pass it through)
            for elt in reversed(ann.elts):
                t = self._ann_type(mod, elt)
                if t:
                    return t
            return None
        if not name:
            return None
        cls = self._class_named(mod, name)
        return cls.full_name if cls is not None else None

    def _return_type(self, fi: FunctionInfo) -> Optional[str]:
        return self._ann_type(fi.module, fi.node.returns)

    def _ctor_type(self, fn_or_mod, mod: ModuleInfo,
                   value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        d = dotted_name(value.func)
        if d is None:
            return None
        resolved = self.p.resolve(mod, d)
        cls = self._class_named(mod, d)
        if cls is not None:
            return cls.full_name
        if resolved in QUEUE_CTORS or resolved in THREAD_CTORS \
                or resolved in LOCK_CTORS:
            return resolved
        fi = self.p.lookup_function(resolved)
        if fi is not None:
            return self._return_type(fi)
        return None

    def _build(self) -> None:
        for mod in self.p.modules.values():
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    t = self._ctor_type(None, mod, stmt.value)
                    if t:
                        self.global_types[(mod.name, stmt.targets[0].id)] = t
            for fn in mod.functions.values():
                if fn.cls is None:
                    continue
                ann_params = {
                    a.arg: self._ann_type(mod, a.annotation)
                    for a in (fn.node.args.posonlyargs + fn.node.args.args
                              + fn.node.args.kwonlyargs)
                    if a.annotation is not None}
                for node in self.p.iter_own_nodes(fn):
                    if not isinstance(node, (ast.Assign, ast.AnnAssign)) \
                            or node.value is None:
                        continue
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    t = self._ctor_type(fn, mod, node.value)
                    if t is None and isinstance(node.value, ast.Name):
                        t = ann_params.get(node.value.id)
                    if t is None and isinstance(node, ast.AnnAssign):
                        t = self._ann_type(mod, node.annotation)
                    if t is None:
                        continue
                    for tgt in targets:
                        if isinstance(tgt, ast.Attribute) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "self":
                            self.attr_types.setdefault(
                                (fn.cls.full_name, tgt.attr), t)

    # -- queries ---------------------------------------------------------- #
    def class_attr(self, cls_full: str, attr: str) -> Optional[str]:
        t = self.attr_types.get((cls_full, attr))
        if t:
            return t
        cls = self.p.lookup_class(cls_full)
        if cls is None:
            return None
        for anc in self.p._class_ancestry(cls):
            t = self.attr_types.get((anc.full_name, attr))
            if t:
                return t
        return None

    def method(self, cls_full: str, name: str) -> Optional[FunctionInfo]:
        cls = self.p.lookup_class(cls_full)
        if cls is None:
            return None
        m = cls.methods.get(name)
        if m is not None:
            return m
        for anc in self.p._class_ancestry(cls):
            m = anc.methods.get(name)
            if m is not None:
                return m
        return None

    def fn_locals(self, fn: FunctionInfo) -> Dict[str, str]:
        env = self._locals.get(id(fn))
        if env is not None:
            return env
        env = {}
        self._locals[id(fn)] = env      # registered first: cycle-safe
        mod = fn.module
        for a in (fn.node.args.posonlyargs + fn.node.args.args
                  + fn.node.args.kwonlyargs):
            t = self._ann_type(mod, a.annotation) if a.annotation else None
            if t:
                env[a.arg] = t
        for node in self.p.iter_own_nodes(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = self.infer(fn, node.value)
                if t:
                    env[node.targets[0].id] = t
            elif isinstance(node, ast.For):
                # container attr types degrade to their element type,
                # so `for m in self._metrics.values():` (and bare
                # iteration / `.items()` value slots) pass through
                it = node.iter
                if isinstance(it, ast.Call) \
                        and isinstance(it.func, ast.Attribute) \
                        and it.func.attr in ("values", "items"):
                    it = it.func.value
                t = self.infer(fn, it)
                if not t:
                    continue
                tgt = node.target
                if isinstance(tgt, ast.Tuple) and tgt.elts:
                    tgt = tgt.elts[-1]      # items(): the value slot
                if isinstance(tgt, ast.Name):
                    env.setdefault(tgt.id, t)
        return env

    def infer(self, fn: FunctionInfo, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fn.cls is not None:
                return fn.cls.full_name
            t = self.fn_locals(fn).get(expr.id)
            if t:
                return t
            return self.global_types.get((fn.module.name, expr.id))
        if isinstance(expr, ast.Attribute):
            base = self.infer(fn, expr.value)
            if base is not None and "." in base:
                t = self.class_attr(base, expr.attr)
                if t:
                    return t
            d = dotted_name(expr)
            if d is not None:
                resolved = self.p.resolve(fn.module, d)
                modname, _, var = resolved.rpartition(".")
                if modname in self.p.modules:
                    return self.global_types.get((modname, var))
            return None
        if isinstance(expr, ast.Subscript):
            # element-type degradation: `self._slots[lane]` keeps the
            # container attr's (element) type
            return self.infer(fn, expr.value)
        if isinstance(expr, ast.Call):
            d = dotted_name(expr.func)
            if d is not None:
                resolved = self.p.resolve(fn.module, d)
                cls = self._class_named(fn.module, d)
                if cls is not None:
                    return cls.full_name
                if resolved in QUEUE_CTORS or resolved in THREAD_CTORS:
                    return resolved
                fi = self.p._resolve_call_target(fn, d) \
                    or self.p.lookup_function(resolved)
                if fi is not None:
                    rt = self._return_type(fi)
                    if rt:
                        return rt
            if isinstance(expr.func, ast.Attribute):
                base = self.infer(fn, expr.func.value)
                if base:
                    m = self.method(base, expr.func.attr)
                    if m is not None:
                        return self._return_type(m)
            return None
        return None


# ---------------------------------------------------------------------------
# the lock graph
# ---------------------------------------------------------------------------


class LockGraph:
    """Static lock facts: identities, aliases, held-while-acquiring
    edges, per-token acquisition contexts and hot-lock set."""

    def __init__(self):
        self.defs: Dict[str, dict] = {}     # token -> kind/path/line
        self.alias: Dict[str, str] = {}     # condition token -> lock token
        self.edges: Dict[Tuple[str, str], dict] = {}
        self.contexts: Dict[str, Set[str]] = {}
        self.hot: Set[str] = set()

    def canon(self, token: str) -> str:
        seen = set()
        while token in self.alias and token not in seen:
            seen.add(token)
            token = self.alias[token]
        return token

    def sites(self) -> Dict[str, Tuple[str, int]]:
        """Canonical token -> (path, line) of the lock construction —
        the witness's join key (it attributes observed locks by
        creation frame)."""
        out: Dict[str, Tuple[str, int]] = {}
        for token, d in self.defs.items():
            if self.canon(token) == token:
                out[token] = (d["path"], d["line"])
        return out

    def edge_list(self) -> List[dict]:
        return [dict(sample, src=s, dst=t)
                for (s, t), sample in sorted(self.edges.items())]

    def add_edge(self, src: str, dst: str, sample: dict) -> None:
        if src == dst:
            return
        self.edges.setdefault((src, dst), sample)


def to_dot(graph: LockGraph) -> str:
    """Graphviz dump of the lock-order graph (``--format dot``)."""

    def short(token: str) -> str:
        parts = token.split(".")
        return ".".join(parts[-3:]) if len(parts) > 3 else token

    lines = ["digraph lock_order {", "  rankdir=LR;",
             '  node [shape=box, fontsize=10];']
    tokens = sorted({t for e in graph.edges for t in e}
                    | set(graph.sites()))
    for t in tokens:
        attrs = [f'label="{short(t)}"']
        if t in graph.hot:
            attrs.append('style=filled, fillcolor="#ffd9b3"')
        ctx = graph.contexts.get(t)
        if ctx:
            attrs.append(f'tooltip="{",".join(sorted(ctx))}"')
        lines.append(f'  "{t}" [{", ".join(attrs)}];')
    for (s, t), sample in sorted(graph.edges.items()):
        label = f"{sample.get('path', '?')}:{sample.get('line', 0)}"
        lines.append(f'  "{s}" -> "{t}" [label="{label}", fontsize=8];')
    lines.append("}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# per-function acquisition walker
# ---------------------------------------------------------------------------


class _FnLockInfo:
    __slots__ = ("acqs", "waits", "blocks", "held_at_call")

    def __init__(self):
        # (token, node, blocking, held-frozenset) — token canonical
        self.acqs: List[Tuple[str, ast.AST, bool, frozenset]] = []
        # (token, node, in_loop, held) — Condition.wait sites
        self.waits: List[Tuple[str, ast.AST, bool, frozenset]] = []
        # (node, reason, held) — directly blocking operations
        self.blocks: List[Tuple[ast.AST, str, frozenset]] = []
        self.held_at_call: Dict[int, frozenset] = {}


def _timeout_of(call: ast.Call):
    """The acquire/put/get timeout expression, None when absent."""
    for kw in call.keywords:
        if kw.arg == "timeout":
            return kw.value
    if len(call.args) >= 2:         # acquire(blocking, timeout) / put(x, block, t)
        return call.args[-1]
    return None


def _is_try_acquire(call: ast.Call) -> bool:
    """``acquire(False)`` / ``acquire(blocking=False)`` / finite
    ``acquire(timeout=...)`` — bounded, cannot deadlock."""
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and not kw.value.value:
            return True
        if kw.arg == "timeout":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(
                    v.value, (int, float)) and v.value < 0:
                return False        # timeout=-1 blocks forever
            return True
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and not a0.value:
            return True
        if len(call.args) >= 2:
            return True             # acquire(blocking, timeout)
    return False


class _LockPass:
    """The whole interprocedural pass; built once per project."""

    def __init__(self, project: Project):
        self.p = project
        self.types = _TypeEnv(project)
        self.graph = LockGraph()
        self.info: Dict[int, _FnLockInfo] = {}
        self._local_exprs: Dict[int, Dict[str, ast.AST]] = {}
        self._collect_defs()
        for fn in project.iter_functions():
            self.info[id(fn)] = self._walk_fn(fn)
        self._build_callees()
        self._compute_entry_held()
        self._compute_closures()
        self._compute_signal_scope()
        self._emit_edges_and_contexts()

    # -- lock definitions -------------------------------------------------- #
    def _lock_ctor(self, mod: ModuleInfo, value: ast.AST
                   ) -> Optional[Tuple[str, ast.Call]]:
        if not isinstance(value, ast.Call):
            return None
        d = dotted_name(value.func)
        if d is None:
            return None
        kind = LOCK_CTORS.get(self.p.resolve(mod, d))
        return (kind, value) if kind else None

    def _collect_defs(self) -> None:
        pending: List[Tuple[str, Optional[FunctionInfo], ModuleInfo,
                            ast.AST]] = []
        for mod in self.p.modules.values():
            for stmt in mod.tree.body:
                if not (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)):
                    continue
                got = self._lock_ctor(mod, stmt.value)
                if got is None:
                    continue
                kind, call = got
                token = f"{mod.name}.{stmt.targets[0].id}"
                self.graph.defs.setdefault(token, {
                    "kind": kind, "path": mod.path, "line": stmt.lineno})
                if kind == "condition" and call.args:
                    pending.append((token, None, mod, call.args[0]))
            for fn in mod.functions.values():
                if fn.cls is None:
                    continue
                for node in self.p.iter_own_nodes(fn):
                    if not isinstance(node, (ast.Assign, ast.AnnAssign)) \
                            or node.value is None:
                        continue
                    got = self._lock_ctor(mod, node.value)
                    if got is None:
                        continue
                    kind, call = got
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for tgt in targets:
                        if isinstance(tgt, ast.Attribute) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "self":
                            token = f"{fn.cls.full_name}.{tgt.attr}"
                            self.graph.defs.setdefault(token, {
                                "kind": kind, "path": mod.path,
                                "line": node.lineno})
                            if kind == "condition" and call.args:
                                pending.append((token, fn, mod, call.args[0]))
        for token, fn, mod, arg in pending:
            target = self._token_of(fn, mod, arg, canon=False)
            if target is not None and target != token:
                self.graph.alias[token] = target

    # -- token resolution -------------------------------------------------- #
    def _class_lock(self, cls_full: str, attr: str) -> Optional[str]:
        token = f"{cls_full}.{attr}"
        if token in self.graph.defs:
            return token
        cls = self.p.lookup_class(cls_full)
        if cls is None:
            return None
        for anc in self.p._class_ancestry(cls):
            token = f"{anc.full_name}.{attr}"
            if token in self.graph.defs:
                return token
        return None

    def _local_expr_map(self, fn: FunctionInfo) -> Dict[str, ast.AST]:
        got = self._local_exprs.get(id(fn))
        if got is not None:
            return got
        out: Dict[str, ast.AST] = {}
        for node in self.p.iter_own_nodes(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                out[node.targets[0].id] = node.value
        self._local_exprs[id(fn)] = out
        return out

    def _token_of(self, fn: Optional[FunctionInfo], mod: ModuleInfo,
                  expr: ast.AST, depth: int = 0,
                  canon: bool = True) -> Optional[str]:
        """Canonical lock token an expression refers to, or None."""
        if depth > 2:
            return None
        token: Optional[str] = None
        if isinstance(expr, ast.Name):
            cand = f"{mod.name}.{expr.id}"
            if cand in self.graph.defs:
                token = cand
            elif fn is not None:
                v = self._local_expr_map(fn).get(expr.id)
                if v is not None and v is not expr:
                    token = self._token_of(fn, mod, v, depth + 1, canon=False)
        elif isinstance(expr, ast.Attribute):
            base = expr.value
            base_t: Optional[str] = None
            if isinstance(base, ast.Name) and base.id == "self" \
                    and fn is not None and fn.cls is not None:
                base_t = fn.cls.full_name
            elif fn is not None:
                base_t = self.types.infer(fn, base)
            if base_t:
                token = self._class_lock(base_t, expr.attr)
            if token is None:
                d = dotted_name(expr)
                if d is not None:
                    resolved = self.p.resolve(mod, d)
                    modname, _, var = resolved.rpartition(".")
                    if modname in self.p.modules \
                            and f"{modname}.{var}" in self.graph.defs:
                        token = f"{modname}.{var}"
        if token is None:
            return None
        return self.graph.canon(token) if canon else token

    def _token_kind(self, fn: FunctionInfo, expr: ast.AST) -> Optional[str]:
        raw = self._token_of(fn, fn.module, expr, canon=False)
        if raw is None:
            return None
        return self.graph.defs.get(raw, {}).get("kind")

    # -- acquisition walker ------------------------------------------------ #
    def _walk_fn(self, fn: FunctionInfo) -> _FnLockInfo:
        info = _FnLockInfo()
        mod = fn.module

        def classify_blocking(call: ast.Call) -> Optional[str]:
            d = dotted_name(call.func)
            if d is not None:
                resolved = self.p.resolve(mod, d)
                tail = resolved.rpartition(".")[2]
                if resolved in BLOCKING_FUNCS:
                    return f"`{d}`"
                if resolved in DEVICE_FUNCS or tail in DEVICE_TAILS:
                    return f"device dispatch/sync `{d}`"
            if isinstance(call.func, ast.Attribute):
                attr = call.func.attr
                if attr == "block_until_ready":
                    return f"device sync `.{attr}()`"
                if attr in ("put", "get", "join"):
                    recv_t = self.types.infer(fn, call.func.value)
                    if recv_t in QUEUE_CTORS and _timeout_of(call) is None:
                        return f"un-timed `queue.{attr}()`"
                    if recv_t in THREAD_CTORS and attr == "join" \
                            and not call.args and _timeout_of(call) is None:
                        return "`Thread.join()` without a timeout"
            return None

        def scan_stmt_calls(stmt: ast.stmt, cur: Set[str],
                            in_loop: bool) -> None:
            """Calls evaluated directly by `stmt` (nested statements are
            visited by their own scan)."""

            def rec(node):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.stmt, ast.excepthandler,
                                          ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        continue
                    if isinstance(child, ast.Call):
                        handle_call(child, cur, in_loop)
                    rec(child)

            rec(stmt)

        def handle_call(call: ast.Call, cur: Set[str],
                        in_loop: bool) -> None:
            info.held_at_call[id(call)] = frozenset(cur)
            if isinstance(call.func, ast.Attribute):
                attr = call.func.attr
                recv = call.func.value
                if attr == "acquire":
                    token = self._token_of(fn, mod, recv)
                    if token is not None:
                        blocking = not _is_try_acquire(call)
                        info.acqs.append((token, call, blocking,
                                          frozenset(cur - {token})))
                        cur.add(token)
                    return
                if attr == "release":
                    token = self._token_of(fn, mod, recv)
                    if token is not None:
                        cur.discard(token)
                    return
                if attr == "wait":
                    if self._token_kind(fn, recv) == "condition":
                        token = self._token_of(fn, mod, recv)
                        info.waits.append((token, call, in_loop,
                                           frozenset(cur - {token})))
                    return
            reason = classify_blocking(call)
            if reason is not None:
                info.blocks.append((call, reason, frozenset(cur)))

        def walk(body: List[ast.stmt], held: Set[str],
                 in_loop: bool) -> None:
            cur = set(held)
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                scan_stmt_calls(stmt, cur, in_loop)
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    tokens = set()
                    for item in stmt.items:
                        token = self._token_of(fn, mod, item.context_expr)
                        if token is not None:
                            info.acqs.append((
                                token, item.context_expr, True,
                                frozenset((cur | tokens) - {token})))
                            tokens.add(token)
                    walk(stmt.body, cur | tokens, in_loop)
                    continue
                inner = in_loop or isinstance(stmt, (ast.While, ast.For))
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        walk(sub, cur,
                             inner if attr != "orelse"
                             or isinstance(stmt, (ast.While, ast.For))
                             else in_loop)
                for h in getattr(stmt, "handlers", []) or []:
                    walk(h.body, cur, in_loop)

        walk(fn.node.body, set(), False)
        return info

    # -- lock-pass call graph ---------------------------------------------- #
    def _build_callees(self) -> None:
        self.callees: Dict[int, List[Tuple[ast.Call, FunctionInfo]]] = {}
        dispatchers = {id(f) for f in getattr(self.p, "section_dispatchers",
                                              [])}
        callbacks = list(getattr(self.p, "section_callbacks", []))
        for fn in self.p.iter_functions():
            out: List[Tuple[ast.Call, FunctionInfo]] = []
            for call in self.p._iter_calls(fn):
                d = dotted_name(call.func)
                target = self.p._resolve_call_target(fn, d) \
                    if d is not None else None
                if target is None and isinstance(call.func, ast.Attribute):
                    base_t = self.types.infer(fn, call.func.value)
                    if base_t:
                        target = self.types.method(base_t, call.func.attr)
                if target is not None:
                    out.append((call, target))
                elif id(fn) in dispatchers and d is None is not call.func \
                        and isinstance(call.func, ast.Name):
                    pass
                elif id(fn) in dispatchers and isinstance(call.func,
                                                          ast.Name):
                    for cb in callbacks:
                        out.append((call, cb))
            # dispatcher bare-name calls (`for name, fn in _sections: fn()`)
            if id(fn) in dispatchers:
                resolved_ids = {id(c) for c, _ in out}
                for call in self.p._iter_calls(fn):
                    if id(call) in resolved_ids:
                        continue
                    if isinstance(call.func, ast.Name) \
                            and fn.module.functions.get(call.func.id) is None \
                            and call.func.id not in fn.module.aliases:
                        for cb in callbacks:
                            out.append((call, cb))
            self.callees[id(fn)] = out

    # -- interprocedural fixpoints ----------------------------------------- #
    def _compute_entry_held(self) -> None:
        self.entry_held: Dict[int, frozenset] = {
            id(fn): frozenset() for fn in self.p.iter_functions()}
        for _ in range(10):
            changed = False
            for fn in self.p.iter_functions():
                base = self.entry_held[id(fn)]
                for call, target in self.callees.get(id(fn), []):
                    held = self.info[id(fn)].held_at_call.get(
                        id(call), frozenset()) | base
                    tid = id(target)
                    if tid in self.entry_held \
                            and not held <= self.entry_held[tid]:
                        self.entry_held[tid] = self.entry_held[tid] | held
                        changed = True
            if not changed:
                break

    def _compute_closures(self) -> None:
        """token -> (path, line, chain) each function may BLOCKINGLY
        acquire, transitively; plus a may-block reason closure."""
        self.acq_closure: Dict[int, Dict[str, Tuple[str, int, str]]] = {}
        self.block_closure: Dict[int, Optional[Tuple[str, str, int]]] = {}
        for fn in self.p.iter_functions():
            acc: Dict[str, Tuple[str, int, str]] = {}
            info = self.info[id(fn)]
            for token, node, blocking, _held in info.acqs:
                if blocking and token not in acc:
                    acc[token] = (fn.module.path, node.lineno, fn.qualname)
            for token, node, _in_loop, _held in info.waits:
                if token is not None and token not in acc:
                    acc[token] = (fn.module.path, node.lineno,
                                  f"{fn.qualname} (wait re-acquire)")
            self.acq_closure[id(fn)] = acc
            blk = None
            if info.blocks:
                node, reason, _held = info.blocks[0]
                blk = (reason, fn.module.path, node.lineno)
            self.block_closure[id(fn)] = blk
        for _ in range(20):
            changed = False
            for fn in self.p.iter_functions():
                acc = self.acq_closure[id(fn)]
                blk = self.block_closure[id(fn)]
                for _call, target in self.callees.get(id(fn), []):
                    for token, (path, line, chain) in \
                            self.acq_closure.get(id(target), {}).items():
                        if token not in acc:
                            acc[token] = (path, line,
                                          f"{fn.qualname} -> {chain}")
                            changed = True
                    if blk is None:
                        tb = self.block_closure.get(id(target))
                        if tb is not None:
                            reason, path, line = tb
                            blk = (f"{reason} via `{target.qualname}`",
                                   path, line)
                            self.block_closure[id(fn)] = blk
                            changed = True
            if not changed:
                break

    def _compute_signal_scope(self) -> None:
        """Functions running in signal-handler context.  Two sets: the
        full closure (hot-lock contexts) and a module-scoped one
        (TPU016 flags only the handler's own module — cross-module
        library locks are the callee's audit, provided they are brief).
        """
        handlers = list(getattr(self.p, "signal_handlers", []))
        callbacks = list(getattr(self.p, "section_callbacks", []))
        roots = handlers + callbacks
        self.signal_reachable: Set[int] = set()
        self.signal_scope: Dict[int, str] = {}      # id -> root qualname
        for root in roots:
            work = [root]
            seen = {id(root)}
            self.signal_reachable.add(id(root))
            self.signal_scope.setdefault(id(root), root.qualname)
            while work:
                f = work.pop()
                for _call, target in self.callees.get(id(f), []):
                    if id(target) in seen:
                        continue
                    seen.add(id(target))
                    self.signal_reachable.add(id(target))
                    if target.module is root.module:
                        self.signal_scope.setdefault(id(target),
                                                     root.qualname)
                    work.append(target)

    # -- edges + contexts --------------------------------------------------- #
    def _context_of(self, fn: FunctionInfo) -> Set[str]:
        ctx = {"thread"} if fn.thread_reachable else {"main"}
        if id(fn) in self.signal_reachable:
            ctx.add("signal")
        return ctx

    def _emit_edges_and_contexts(self) -> None:
        g = self.graph
        for fn in self.p.iter_functions():
            info = self.info[id(fn)]
            eh = self.entry_held[id(fn)]
            for token, node, blocking, held in info.acqs:
                if not blocking:
                    continue
                for h in (held | eh) - {token}:
                    g.add_edge(h, token, {
                        "path": fn.module.path, "line": node.lineno,
                        "function": fn.full_name,
                        "via": f"{fn.qualname} acquires `{token}` while "
                               f"holding `{h}`"})
            for token, node, _in_loop, held in info.waits:
                if token is None:
                    continue
                for h in (held | eh) - {token}:
                    g.add_edge(h, token, {
                        "path": fn.module.path, "line": node.lineno,
                        "function": fn.full_name,
                        "via": f"{fn.qualname} Condition.wait re-acquires "
                               f"`{token}` while holding `{h}`"})
            for call, target in self.callees.get(id(fn), []):
                held = info.held_at_call.get(id(call), frozenset()) | eh
                if not held:
                    continue
                for token, (path, line, chain) in \
                        self.acq_closure.get(id(target), {}).items():
                    if token in held:
                        continue
                    for h in held:
                        g.add_edge(h, token, {
                            "path": fn.module.path, "line": call.lineno,
                            "function": fn.full_name,
                            "via": f"{fn.qualname} -> {chain} "
                                   f"({path}:{line})"})
        for fn in self.p.iter_functions():
            ctx = self._context_of(fn)
            for token in self.acq_closure.get(id(fn), {}):
                g.contexts.setdefault(token, set()).update(ctx)
        g.hot = {t for t, ctx in g.contexts.items()
                 if len(ctx) >= 2 or "signal" in ctx}


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

_PASS_CACHE: Dict[int, _LockPass] = {}


def _lock_pass(project: Project) -> _LockPass:
    lp = _PASS_CACHE.get(id(project))
    if lp is None:
        lp = _LockPass(project)
        _PASS_CACHE.clear()
        _PASS_CACHE[id(project)] = lp
    return lp


def build_lock_graph(project: Project) -> LockGraph:
    """The static lock graph (also the witness's cross-check source)."""
    return _lock_pass(project).graph


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _find_cycles(edges: Dict[Tuple[str, str], dict]) -> List[List[str]]:
    """One representative cycle per strongly connected component with
    more than one node (self-loops are reentrancy, not inversions)."""
    adj: Dict[str, List[str]] = {}
    for s, t in edges:
        if s != t:
            adj.setdefault(s, []).append(t)
            adj.setdefault(t, [])
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(adj.get(v, [])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj.get(w, []))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    cycles: List[List[str]] = []
    for comp in sccs:
        comp_set = set(comp)
        start = comp[0]
        # walk inside the SCC until we revisit a node — that's a cycle
        path, seen = [start], {start: 0}
        node = start
        while True:
            nxt = next(w for w in adj[node] if w in comp_set)
            if nxt in seen:
                cycles.append(path[seen[nxt]:])
                break
            seen[nxt] = len(path)
            path.append(nxt)
            node = nxt
    return cycles


def check_tpu013(lp: _LockPass) -> List[Finding]:
    out: List[Finding] = []
    for cycle in _find_cycles(lp.graph.edges):
        pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
        samples = [lp.graph.edges[p] for p in pairs if p in lp.graph.edges]
        if not samples:
            continue
        anchor = min(samples, key=lambda s: (s["path"], s["line"]))
        stacks = "; ".join(
            f"{s['src' if 'src' in s else 'path']}" if False else
            f"[{a} -> {b}] {s['via']} at {s['path']}:{s['line']}"
            for (a, b), s in zip(pairs, samples))
        out.append(Finding(
            "TPU013",
            f"lock-order cycle {' -> '.join(cycle + [cycle[0]])} — two "
            f"threads can acquire these locks in opposite order and "
            f"deadlock; acquisition stacks: {stacks}. Impose one global "
            f"order (or drop to a try-lock on one side)",
            anchor["path"], anchor["line"], 0, anchor["function"],
            extra={"cycle": cycle,
                   "edges": [dict(s, src=a, dst=b)
                             for (a, b), s in zip(pairs, samples)]}))
    return out


def check_tpu014(lp: _LockPass) -> List[Finding]:
    out: List[Finding] = []
    for fn in lp.p.iter_functions():
        for token, node, in_loop, _held in lp.info[id(fn)].waits:
            if in_loop:
                continue
            out.append(Finding(
                "TPU014",
                f"`Condition.wait()` outside a `while` predicate loop — "
                f"spurious wakeups and multi-waiter notify races deliver "
                f"the wakeup without the condition holding (lost-wakeup); "
                f"re-check the predicate in a `while` around the wait",
                fn.module.path, node.lineno, node.col_offset, fn.full_name))
    return out


def check_tpu015(lp: _LockPass) -> List[Finding]:
    out: List[Finding] = []
    hot = lp.graph.hot
    for fn in lp.p.iter_functions():
        info = lp.info[id(fn)]
        eh = lp.entry_held[id(fn)]
        reported: Set[int] = set()
        for node, reason, held in info.blocks:
            hot_held = (held | eh) & hot
            if not hot_held or id(node) in reported:
                continue
            reported.add(id(node))
            tok = sorted(hot_held)[0]
            ctx = ",".join(sorted(lp.graph.contexts.get(tok, ())))
            out.append(Finding(
                "TPU015",
                f"blocking call {reason} while holding hot lock `{tok}` "
                f"(acquired from contexts: {ctx}) — every thread "
                f"contending for the lock stalls behind it; move the "
                f"blocking work outside the lock or bound it with a "
                f"timeout",
                fn.module.path, node.lineno, node.col_offset, fn.full_name))
        for call, target in lp.callees.get(id(fn), []):
            if id(call) in reported:
                continue
            hot_held = (info.held_at_call.get(id(call), frozenset()) | eh) \
                & hot
            if not hot_held:
                continue
            blk = lp.block_closure.get(id(target))
            if blk is None:
                continue
            reason, path, line = blk
            reported.add(id(call))
            tok = sorted(hot_held)[0]
            ctx = ",".join(sorted(lp.graph.contexts.get(tok, ())))
            out.append(Finding(
                "TPU015",
                f"call to `{target.qualname}` may block ({reason}, "
                f"{path}:{line}) while holding hot lock `{tok}` "
                f"(contexts: {ctx}) — move the blocking work outside "
                f"the lock or bound it with a timeout",
                fn.module.path, call.lineno, call.col_offset, fn.full_name))
    return out


def check_tpu016(lp: _LockPass) -> List[Finding]:
    out: List[Finding] = []
    for fn in lp.p.iter_functions():
        root = lp.signal_scope.get(id(fn))
        if root is None:
            continue
        for token, node, blocking, _held in lp.info[id(fn)].acqs:
            if not blocking:
                continue        # try-lock: the sanctioned idiom
            out.append(Finding(
                "TPU016",
                f"blocking acquisition of `{token}` in signal-handler "
                f"context (reachable from `{root}`) — the interrupted "
                f"thread may already hold this lock, deadlocking the "
                f"process inside the handler; use "
                f"`acquire(timeout=...)` and bail out on failure",
                fn.module.path, node.lineno, node.col_offset, fn.full_name))
    return out


def check_lock_rules(project: Project,
                     active: Set[str]) -> List[Finding]:
    """Project-wide driver for TPU013–TPU016 (one shared pass)."""
    if not active & set(LOCK_RULES):
        return []
    lp = _lock_pass(project)
    out: List[Finding] = []
    if "TPU013" in active:
        out.extend(check_tpu013(lp))
    if "TPU014" in active:
        out.extend(check_tpu014(lp))
    if "TPU015" in active:
        out.extend(check_tpu015(lp))
    if "TPU016" in active:
        out.extend(check_tpu016(lp))
    return out
