"""Sharding/collective rules TPU007–TPU009 (interprocedural).

These rules ride the analyzer's cross-module passes:

* TPU007 consumes per-function *shard-axis contexts* — the union of
  mesh axis names bound by every ``shard_map``/``pmap``/``vmap``
  context a function is reachable from, propagated through the call
  graph — and flags collectives naming an axis no reaching context
  binds.  An ``axis_name`` *parameter* is resolved through the reverse
  call graph to the string constants analyzed callers actually pass.
* TPU008 flags a jit-boundary closure capturing an array value from
  its enclosing function: the array is baked into the compiled program
  as a constant (weights become immutable copies, doubling HBM) or, if
  the outer function is itself under trace, the inner jit captures an
  outer tracer and retraces per call.
* TPU009 tracks donated buffers (``donate_argnums``): referencing a
  buffer after the call it was donated to reads a deleted device
  array.  Donating callables are tracked through local bindings,
  through functions that *return* a donating jit, and through class
  attributes holding one.
"""
from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Optional, Set, Tuple

from .analyzer import (COLLECTIVE_FUNCS, Finding, FunctionInfo, Project,
                       dotted_name)

# collectives whose FIRST positional argument is the axis name
_AXIS_ARG0 = {"axis_index", "axis_size"}

# aval metadata reads stay legal on a donated (deleted) buffer
_DONATION_SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                        "itemsize", "nbytes", "weak_type", "is_deleted"}


# ---------------------------------------------------------------------------
# TPU007 — collective over an axis no reaching shard context binds
# ---------------------------------------------------------------------------


def _axis_param_index(fn: FunctionInfo, name: str) -> Optional[int]:
    pos = fn.node.args.posonlyargs + fn.node.args.args
    for i, a in enumerate(pos):
        if a.arg == name:
            return i
    return None


def _param_default(fn: FunctionInfo, name: str) -> Optional[str]:
    args = fn.node.args
    pos = args.posonlyargs + args.args
    n_def = len(args.defaults)
    for a, d in zip(pos[len(pos) - n_def:], args.defaults):
        if a.arg == name and isinstance(d, ast.Constant) \
                and isinstance(d.value, str):
            return d.value
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if a.arg == name and isinstance(d, ast.Constant) \
                and isinstance(d.value, str):
            return d.value
    return None


def _caller_axis_values(project: Project, fn: FunctionInfo,
                        param: str) -> Tuple[Set[str], bool]:
    """String constants analyzed callers pass for `param`, plus the
    param's own default.  (values, all_known): all_known is False when
    some call site passes a non-literal (then TPU007 must stay quiet —
    the value may be an axis the context does bind)."""
    values: Set[str] = set()
    all_known = True
    idx = _axis_param_index(fn, param)
    default = _param_default(fn, param)
    if default is not None:
        values.add(default)
    for _caller, call in project.call_sites(fn):
        got = None
        for kw in call.keywords:
            if kw.arg == param:
                got = kw.value
        if got is None and idx is not None and idx < len(call.args):
            got = call.args[idx]
        if got is None:
            continue           # omitted → default (already counted)
        if isinstance(got, ast.Constant) and isinstance(got.value, str):
            values.add(got.value)
        else:
            all_known = False
    return values, all_known


def _axis_exprs(call: ast.Call, tail: str) -> List[ast.AST]:
    out = [kw.value for kw in call.keywords if kw.arg == "axis_name"]
    i = 0 if tail in _AXIS_ARG0 else 1
    if not out and len(call.args) > i:
        out.append(call.args[i])
    return out


def _literal_axes(expr: ast.AST) -> Optional[Set[str]]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return {expr.value}
    if isinstance(expr, (ast.Tuple, ast.List)) and expr.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in expr.elts):
        return {e.value for e in expr.elts}
    return None


def check_tpu007(project: Project, fn: FunctionInfo) -> List[Finding]:
    if not fn.trace_reachable:
        return []
    # no shard context reaches this function, or a context we couldn't
    # extract axes from does: both mean no ground truth to check against
    if fn.shard_axes is None or fn.shard_axes_unknown:
        return []
    bound = fn.shard_axes
    out: List[Finding] = []
    local_strs: Dict[str, str] = {}
    for node in project.iter_own_nodes(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            local_strs[node.targets[0].id] = node.value.value

    def flag(node, axes: Set[str]):
        shown = ", ".join(sorted(axes))
        have = ", ".join(sorted(bound)) or "(none)"
        out.append(Finding(
            "TPU007",
            f"collective over axis `{shown}` but no enclosing "
            f"shard_map/pmap context reachable from here binds it "
            f"(bound axes: {have}) — fails with an unbound-axis error at "
            f"trace time, or silently reduces over the wrong mesh axis",
            fn.module.path, node.lineno, node.col_offset, fn.full_name))

    params = {a.arg for a in (fn.node.args.posonlyargs + fn.node.args.args
                              + fn.node.args.kwonlyargs)}
    for node in project.iter_own_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func)
        if d is None:
            continue
        resolved = project.resolve(fn.module, d)
        if resolved not in COLLECTIVE_FUNCS:
            continue
        tail = resolved.rpartition(".")[2]
        for expr in _axis_exprs(node, tail):
            axes = _literal_axes(expr)
            if axes is None and isinstance(expr, ast.Name):
                if expr.id in params:
                    vals, known = _caller_axis_values(project, fn, expr.id)
                    if not known or not vals:
                        continue
                    axes = vals
                elif expr.id in local_strs:
                    axes = {local_strs[expr.id]}
            if axes is None:
                continue
            missing = axes - bound
            if missing:
                flag(node, missing)
    return out


# ---------------------------------------------------------------------------
# TPU008 — jit boundary closing over an array / outer tracer
# ---------------------------------------------------------------------------


_ARRAY_PRODUCER_PREFIXES = ("jax.numpy.", "jax.random.", "jax.nn.",
                            "jax.lax.", "jax.scipy.")
_ARRAY_PRODUCER_FUNCS = {"jax.device_put", "jax.device_put_replicated",
                         "jax.device_put_sharded", "jax.block_until_ready"}


# wrappers that start a NEW compiled program.  Control-flow primitives
# (lax.scan/cond/...), shard_map, vmap/grad etc. inline their function
# argument into the SAME trace — closing over outer tracers there is
# normal JAX, not a bug.  eval_shape/make_jaxpr never compile at all.
_COMPILE_BOUNDARIES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
                       "jax.experimental.pallas.pallas_call"}


def _is_jit_entry(fn: FunctionInfo) -> bool:
    return fn.seed_wrapper in _COMPILE_BOUNDARIES


def _free_names(fn: FunctionInfo) -> Set[str]:
    """Names `fn` reads but never binds — closure candidates."""
    bound: Set[str] = set()
    loads: Set[str] = set()
    node = fn.node
    arglike = [node.args]
    for sub in ast.walk(node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(sub.name)
            arglike.append(sub.args)
        elif isinstance(sub, ast.Lambda):
            arglike.append(sub.args)
        elif isinstance(sub, ast.ClassDef):
            bound.add(sub.name)
        elif isinstance(sub, ast.Name):
            if isinstance(sub.ctx, ast.Load):
                loads.add(sub.id)
            else:
                bound.add(sub.id)
        elif isinstance(sub, (ast.Global, ast.Nonlocal)):
            bound.update(sub.names)
        elif isinstance(sub, (ast.Import, ast.ImportFrom)):
            for a in sub.names:
                bound.add((a.asname or a.name).split(".")[0])
        elif isinstance(sub, ast.ExceptHandler) and sub.name:
            bound.add(sub.name)
    for args in arglike:
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            bound.add(a.arg)
        for va in (args.vararg, args.kwarg):
            if va is not None:
                bound.add(va.arg)
    return loads - bound - set(dir(builtins))


def _parent_of(project: Project, fn: FunctionInfo) -> Optional[FunctionInfo]:
    qual, _, _ = fn.qualname.rpartition(".")
    return fn.module.functions.get(qual) if qual else None


def check_tpu008(project: Project, fn: FunctionInfo) -> List[Finding]:
    if not _is_jit_entry(fn):
        return []
    parent = _parent_of(project, fn)
    if parent is None:
        return []
    from .rules import Taint, _walk_stmts

    class _ArrayTaint(Taint):
        """Parent-scope taint extended with array *producers*: a local
        assigned from jnp/jax.random/device_put is an array even though
        it doesn't derive from a parameter."""

        def call(self, node: ast.Call) -> bool:
            d = dotted_name(node.func)
            if d is not None:
                resolved = self.project.resolve(self.fn.module, d)
                if resolved in _ARRAY_PRODUCER_FUNCS or \
                        resolved.startswith(_ARRAY_PRODUCER_PREFIXES):
                    return True
            return super().call(node)

    taint = _ArrayTaint(project, parent)
    if not parent.trace_reachable:
        # host-side builder: its parameters are host objects (nets,
        # pending steps, configs) — param-derived taint would call every
        # attribute an array.  Only values with direct array-producer
        # evidence (jnp.*/jax.random.*/device_put assignments) count.
        taint.tainted.clear()
        taint.containers.clear()
    # closures late-bind: the state that matters is the parent's final
    # one, after every statement ran
    for stmt in _walk_stmts(parent.node.body):
        taint.process_stmt(stmt)
    captured = sorted(_free_names(fn) & taint.tainted)
    out: List[Finding] = []
    for name in captured:
        if parent.trace_reachable:
            msg = (f"jit boundary `{fn.name}` closes over `{name}`, a "
                   f"tracer of the enclosing traced function "
                   f"`{parent.qualname}` — leaks the outer trace into the "
                   f"inner program and retraces on every outer trace; pass "
                   f"it as an argument")
        else:
            msg = (f"jit boundary `{fn.name}` closes over array `{name}` "
                   f"from `{parent.qualname}` — the array is constant-folded "
                   f"into the compiled program (a frozen copy on every "
                   f"device, retrace per rebuild); pass it as an argument")
        out.append(Finding("TPU008", msg, fn.module.path, fn.node.lineno,
                           fn.node.col_offset, fn.full_name))
    return out


# ---------------------------------------------------------------------------
# TPU009 — donated buffer referenced after the donating call
# ---------------------------------------------------------------------------


def _donating_positions(project: Project, fn: FunctionInfo,
                        call: ast.Call,
                        donators: Dict[str, Tuple[int, ...]]
                        ) -> Optional[Tuple[int, ...]]:
    """donate_argnums for this call if it invokes a donating jit:
    a tracked local, `self.attr` recorded by the analyzer, a function
    returning a donating jit called directly, or an immediately-invoked
    `jax.jit(g, donate_argnums=...)(...)`."""
    func = call.func
    if isinstance(func, ast.Name) and func.id in donators:
        return donators[func.id]
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name) \
            and func.value.id == "self" and fn.cls is not None:
        return project.donating_attrs.get((id(fn.cls), func.attr))
    if isinstance(func, ast.Call):
        # immediately-invoked `jax.jit(g, donate_argnums=...)(x)`
        return project.donating_jit_nums(fn.module, func)
    return None


def check_tpu009(project: Project, fn: FunctionInfo) -> List[Finding]:
    out: List[Finding] = []
    reported: Set[Tuple[str, int]] = set()
    # locals bound to donating callables, seeded per scan
    init_donators: Dict[str, Tuple[int, ...]] = {}
    for node in project.iter_own_nodes(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            nums = project.donating_jit_nums(fn.module, node.value)
            if nums is None and isinstance(node.value, ast.Call):
                d = dotted_name(node.value.func)
                if d is not None:
                    called = project._resolve_call_target(fn, d)
                    if called is not None:
                        nums = called.returns_donating
            if nums is not None:
                init_donators[tgt] = nums

    def flag(node, name, line):
        if (name, node.lineno) in reported:
            return
        reported.add((name, node.lineno))
        out.append(Finding(
            "TPU009",
            f"`{name}` was donated to the jitted call on line {line} "
            f"(donate_argnums) and is referenced afterwards — the donated "
            f"device buffer is deleted by XLA; use the call's result or "
            f"drop the donation",
            fn.module.path, node.lineno, node.col_offset, fn.full_name))

    def scan_expr(node, donated: Dict[str, int]):
        """Flag reads of donated names; aval metadata reads excluded."""
        if isinstance(node, ast.Attribute) \
                and node.attr in _DONATION_SAFE_ATTRS:
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in donated:
            flag(node, node.id, donated[node.id])
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.excepthandler,
                                  ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            scan_expr(child, donated)

    def process_calls(stmt, donated, donators):
        from .rules import _own_exprs

        for node in _own_exprs(stmt):
            if not isinstance(node, ast.Call):
                continue
            nums = _donating_positions(project, fn, node, donators)
            if not nums:
                continue
            for p in nums:
                if p < len(node.args) and isinstance(node.args[p], ast.Name):
                    donated[node.args[p].id] = node.lineno

    def process_binds(stmt, donated, donators):
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.For):
            targets = [stmt.target]
        for tgt in targets:
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name):
                    donated.pop(sub.id, None)
                    if not (isinstance(stmt, ast.Assign)
                            and sub.id in init_donators):
                        donators.pop(sub.id, None)

    def scan(body, donated: Dict[str, int],
             donators: Dict[str, Tuple[int, ...]]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            # reads happen at evaluation time, before this statement's
            # donation or rebinding takes effect
            for node in ast.iter_child_nodes(stmt):
                if not isinstance(node, (ast.stmt, ast.excepthandler)):
                    scan_expr(node, donated)
            process_calls(stmt, donated, donators)
            process_binds(stmt, donated, donators)
            if isinstance(stmt, (ast.For, ast.While)):
                for _ in range(2):      # catch next-iteration reuse
                    scan(stmt.body, donated, donators)
                scan(stmt.orelse, donated, donators)
            elif isinstance(stmt, ast.If):
                left_d, left_f = dict(donated), dict(donators)
                scan(stmt.body, left_d, left_f)
                right_d, right_f = dict(donated), dict(donators)
                scan(stmt.orelse, right_d, right_f)
                donated.clear()
                donated.update(right_d)
                for k, v in left_d.items():   # donated on either branch
                    donated.setdefault(k, v)
                donators.clear()
                donators.update({k: v for k, v in left_f.items()
                                 if k in right_f})
            elif isinstance(stmt, ast.Try):
                scan(stmt.body, donated, donators)
                for h in stmt.handlers:
                    scan(h.body, donated, donators)
                scan(stmt.orelse, donated, donators)
                scan(stmt.finalbody, donated, donators)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                scan(stmt.body, donated, donators)

    scan(fn.node.body, {}, dict(init_donators))
    return out
