"""Caching rule TPU010: unbounded compile/program caches.

The failure mode this encodes is the ADVICE-#3 class PR 7 fixed by
hand in ``models/generation.py``: a dict keyed on shapes/configs that
memoizes compiled programs (or their aval specs) grows by one entry
per distinct key and never evicts — every new sequence-length bucket,
batch size, or composition leaks a program *and its device
executable* forever.  The rule detects the memo pattern (guarded read
+ keyed store) on an instance attribute or module global, requires
the store to be *trace-adjacent* (the storing function is
trace/per-step reachable, or itself builds jit programs), and stays
quiet on any eviction evidence: ``pop``/``popitem``/``clear``/
``del``/``move_to_end``, a ``len(cache)`` cap check, or the cache
escaping into a helper call (e.g. ``_lru_put(net, cache, ...)``).

A fresh re-assignment (``self._cache = {}``) outside ``__init__`` is
deliberately NOT eviction evidence: that is *invalidation* — it
resets on structural change but still grows without bound across
distinct keys between resets.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .analyzer import (ClassInfo, Finding, FunctionInfo, ModuleInfo, Project,
                       dotted_name)

_DICTISH_CTORS = {"dict", "collections.OrderedDict", "OrderedDict",
                  "collections.defaultdict", "defaultdict"}
_EVICT_METHODS = {"pop", "popitem", "clear", "move_to_end"}
_STORE_METHODS = {"setdefault", "append"}


def _is_cache_ctor(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List)):
        return True
    if isinstance(node, ast.Call) and not node.args and not node.keywords:
        d = dotted_name(node.func)
        return d in _DICTISH_CTORS or d == "list"
    return False


@dataclass
class _Cache:
    """One candidate cache: a `self.X` attr of a class, or a module
    global, with everything observed about it across the module."""
    label: str                     # "Class._attr" / "module._GLOBAL"
    init_line: int
    store_sites: List[Tuple[FunctionInfo, ast.AST]] = field(
        default_factory=list)
    guarded_read: bool = False
    evicted: bool = False


def _ref_matches(node: ast.AST, attr: Optional[str],
                 gname: Optional[str]) -> bool:
    """Is `node` a reference to the tracked cache (`self.attr` or the
    module global `gname`)?"""
    if attr is not None:
        return (isinstance(node, ast.Attribute) and node.attr == attr
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")
    return isinstance(node, ast.Name) and node.id == gname


def _rewrite_keys(nodes, attr: Optional[str], gname: Optional[str]) -> Set[str]:
    """Loop variables iterating the cache itself (`for k in cache`,
    `for k, v in list(cache.items())`): a store keyed by one rewrites
    an EXISTING entry in place — it can't grow the cache."""
    out: Set[str] = set()
    for node in nodes:
        if not isinstance(node, ast.For):
            continue
        it = node.iter
        # unwrap list(...)/tuple(...)/sorted(...)
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id in ("list", "tuple", "sorted") and it.args:
            it = it.args[0]
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("items", "keys"):
            it = it.func.value
        if not _ref_matches(it, attr, gname):
            continue
        tgt = node.target
        if isinstance(tgt, ast.Name):
            out.add(tgt.id)
        elif isinstance(tgt, ast.Tuple) and tgt.elts \
                and isinstance(tgt.elts[0], ast.Name):
            out.add(tgt.elts[0].id)   # `for k, v in cache.items()`
    return out


def _scan_usage(project: Project, cache: _Cache, fn: Optional[FunctionInfo],
                nodes, attr: Optional[str], gname: Optional[str]):
    nodes = list(nodes)
    rewrite = _rewrite_keys(nodes, attr, gname)
    for node in nodes:
        # keyed store: cache[k] = v
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) \
                        and _ref_matches(tgt.value, attr, gname) \
                        and not (isinstance(tgt.slice, ast.Name)
                                 and tgt.slice.id in rewrite):
                    cache.store_sites.append((fn, tgt))
        # del cache[k] — eviction
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) \
                        and _ref_matches(tgt.value, attr, gname):
                    cache.evicted = True
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and _ref_matches(f.value, attr,
                                                             gname):
                if f.attr in _EVICT_METHODS:
                    cache.evicted = True
                elif f.attr in _STORE_METHODS:
                    cache.store_sites.append((fn, node))
                    if f.attr == "setdefault":
                        cache.guarded_read = True
                elif f.attr == "get":
                    cache.guarded_read = True
            # len(cache) in a cap check / cache escaping into a helper
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if _ref_matches(a, attr, gname):
                    d = dotted_name(f)
                    if d == "len":
                        continue     # classified by the Compare case
                    cache.evicted = True
        elif isinstance(node, ast.Compare):
            for side in [node.left] + list(node.comparators):
                if _ref_matches(side, attr, gname) and any(
                        isinstance(op, (ast.In, ast.NotIn))
                        for op in node.ops):
                    cache.guarded_read = True
                if isinstance(side, ast.Call) \
                        and dotted_name(side.func) == "len" and side.args \
                        and _ref_matches(side.args[0], attr, gname):
                    cache.evicted = True    # explicit size-cap check


def _trace_adjacent(project: Project, fn: Optional[FunctionInfo]) -> bool:
    if fn is None:
        return False
    if fn.trace_reachable or fn.perstep_reachable or fn.is_jit_wrapper:
        return True
    # the store lives next to program construction (jit/eval_shape/…)
    return any(project.is_jit_wrapper_call(fn.module, call)
               for call in project.iter_own_nodes(fn)
               if isinstance(call, ast.Call))


def _class_caches(project: Project, mod: ModuleInfo,
                  cls: ClassInfo) -> List[_Cache]:
    cands: Dict[str, _Cache] = {}
    methods = [f for f in mod.functions.values() if f.cls is cls]
    for m in methods:
        for node in project.iter_own_nodes(m):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    and node.value is not None and _is_cache_ctor(node.value):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self" \
                            and tgt.attr not in cands:
                        cands[tgt.attr] = _Cache(
                            f"{cls.name}.{tgt.attr}", node.lineno)
    for attr, cache in cands.items():
        for m in methods:
            _scan_usage(project, cache, m, project.iter_own_nodes(m),
                        attr, None)
    return list(cands.values())


def _module_caches(project: Project, mod: ModuleInfo) -> List[_Cache]:
    cands: Dict[str, _Cache] = {}
    for stmt in mod.tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)) \
                and stmt.value is not None and _is_cache_ctor(stmt.value):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name) and tgt.id not in cands:
                    cands[tgt.id] = _Cache(f"{mod.name}.{tgt.id}",
                                           stmt.lineno)
    for gname, cache in cands.items():
        for fn in mod.functions.values():
            _scan_usage(project, cache, fn, project.iter_own_nodes(fn),
                        None, gname)
    return list(cands.values())


def check_tpu010_module(project: Project, mod: ModuleInfo) -> List[Finding]:
    """TPU010 is a per-module rule (a cache's stores, reads and
    eviction are spread across functions), unlike the per-function
    TPU001–009 — the driver calls it once per module."""
    out: List[Finding] = []
    caches: List[_Cache] = []
    for cls in mod.classes.values():
        caches.extend(_class_caches(project, mod, cls))
    caches.extend(_module_caches(project, mod))
    for cache in caches:
        if cache.evicted or not cache.guarded_read or not cache.store_sites:
            continue
        adjacent = [s for s in cache.store_sites
                    if _trace_adjacent(project, s[0])]
        if not adjacent:
            continue
        fn, node = adjacent[0]
        out.append(Finding(
            "TPU010",
            f"unbounded cache `{cache.label}`: memoized keyed store with "
            f"no eviction or size cap in trace-adjacent code — one entry "
            f"(often a compiled program or aval spec) leaks per distinct "
            f"key; cap it LRU-style like models/generation._lru_put",
            mod.path, node.lineno, node.col_offset,
            fn.full_name if fn is not None else mod.name))
    out.sort(key=lambda f: (f.line, f.col))
    return out
