"""Flagship benchmark: BERT-large MLM pretraining step throughput → MFU.

Mirrors the reference's headline BERT-large phase-1 (seq 128) training
benchmark (BASELINE.md; GluonNLP `scripts/bert` era) as a fully fused
jitted train step: bf16 compute, fp32 master weights, flash-attention
Pallas kernel, momentum SGD, buffer donation.  North star
(BASELINE.json): ≥40% MFU — `vs_baseline` = measured_MFU / 0.40.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import sys
import time


# bf16 peak FLOP/s per chip by device kind substring
_PEAKS = [
    ("v6e", 918e12), ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12), ("v5 lite", 197e12), ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return 1e12  # unknown accelerator / CPU: nominal 1 TFLOP/s


def main():
    on_cpu = "cpu" in sys.argv
    if on_cpu:
        import os

        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=1")
    import jax

    if on_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.block import functionalize
    from incubator_mxnet_tpu.models import bert

    dev = jax.devices()[0]
    is_tpu = dev.platform == "tpu" or "tpu" in getattr(dev, "device_kind", "").lower() \
        or dev.platform == "axon"
    if is_tpu:
        # BERT-large, phase-1 shapes
        V, D, Dff, L, H, B, T = 30522, 1024, 4096, 24, 16, 32, 128
        steps, warmup = 10, 2
    else:  # CPU smoke configuration — keeps the harness runnable anywhere
        V, D, Dff, L, H, B, T = 1000, 128, 512, 2, 4, 4, 64
        steps, warmup = 3, 1

    mx.random.seed(0)
    net = bert.BERTForPretraining(vocab_size=V, units=D, hidden_size=Dff,
                                  num_layers=L, num_heads=H, dropout=0.0)
    net.initialize()
    x = jnp.ones((B, T), jnp.int32)
    apply_fn, train_raws, aux_raws = functionalize(net, mx.nd.NDArray(x))

    n_params = sum(p.size for p in train_raws)

    def loss_fn(params_bf16, tokens, labels, rng):
        (mlm_logits, nsp_logits), _ = apply_fn(params_bf16, aux_raws, rng, tokens)
        logp = jax.nn.log_softmax(mlm_logits.astype(jnp.float32), axis=-1)
        mlm = -jnp.take_along_axis(logp, labels[..., None], axis=-1).mean()
        nsp = -jax.nn.log_softmax(nsp_logits.astype(jnp.float32), axis=-1)[:, 0].mean()
        return mlm + nsp

    lr, mom = 1e-3, 0.9

    def train_step(params32, velocity, tokens, labels, rng):
        params_bf16 = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), params32)
        loss, grads = jax.value_and_grad(loss_fn)(params_bf16, tokens, labels, rng)
        new_vel = jax.tree_util.tree_map(
            lambda v, g: mom * v + g.astype(jnp.float32), velocity, grads)
        new_params = jax.tree_util.tree_map(
            lambda p, v: p - lr * v, params32, new_vel)
        return new_params, new_vel, loss

    params32 = tuple(p.astype(jnp.float32) for p in train_raws)
    velocity = tuple(jnp.zeros_like(p) for p in params32)
    key = jax.random.PRNGKey(0)
    kx, ky = jax.random.split(key)
    tokens = jax.random.randint(kx, (B, T), 0, V, dtype=jnp.int32)
    labels = jax.random.randint(ky, (B, T), 0, V, dtype=jnp.int32)

    # donate params/velocity for in-place updates
    train_step_donated = jax.jit(train_step, donate_argnums=(0, 1))

    for _ in range(warmup):
        params32, velocity, loss = train_step_donated(
            params32, velocity, tokens, labels, key)
    float(loss)  # value fetch — block_until_ready is unreliable over the relay

    t0 = time.perf_counter()
    for _ in range(steps):
        params32, velocity, loss = train_step_donated(
            params32, velocity, tokens, labels, key)
    final_loss = float(loss)  # steps are serialized by the params dependency
    dt = time.perf_counter() - t0

    tokens_per_s = B * T * steps / dt
    # train FLOPs/token ≈ 6·N_matmul + attention 12·L·T·D; embedding
    # lookups are gathers, not matmuls — exclude their tables
    n_embed = V * D + 512 * D + 2 * D
    flops_per_token = 6 * (n_params - n_embed) + 12 * L * T * D
    mfu = tokens_per_s * flops_per_token / _peak_flops(dev)
    print(json.dumps({
        "metric": "bert_large_pretrain_mfu" if is_tpu else "bert_smoke_pretrain_mfu",
        "value": round(mfu * 100, 2),
        "unit": "%MFU",
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": {
            "tokens_per_s": round(tokens_per_s, 1),
            "device": getattr(dev, "device_kind", str(dev)),
            "n_params": int(n_params),
            "batch": B, "seq": T, "steps_timed": steps,
            "final_loss": round(final_loss, 4),
        },
    }))


if __name__ == "__main__":
    main()
