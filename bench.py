"""Flagship benchmark: BERT-large MLM pretraining step throughput → MFU.

Mirrors the reference's headline BERT-large phase-1 (seq 128) training
benchmark (BASELINE.md; GluonNLP `scripts/bert` era) — driven ENTIRELY
through the framework's public Gluon path (VERDICT r1 #2):

    with autograd.record():
        loss = model(tokens, labels)     # hybridized net+loss, one jit
    loss.backward()                      # cached residual-sharing bwd jit
    trainer.step(1)                      # fused multi-tensor update jit

bf16 params with fp32 master weights (multi_precision), momentum SGD,
buffer donation in the fused Trainer step.  North star (BASELINE.json):
≥40% MFU — `vs_baseline` = measured_MFU / 0.40.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import sys
import time


def _peak_flops(device) -> float:
    from incubator_mxnet_tpu.callback import device_peak_flops

    return device_peak_flops(device)


def main():
    on_cpu = "cpu" in sys.argv
    if on_cpu:
        import os

        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=1")
    import jax

    if on_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.gluon.block import HybridBlock
    from incubator_mxnet_tpu.models import bert
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    dev = jax.devices()[0]
    is_tpu = dev.platform == "tpu" or "tpu" in getattr(dev, "device_kind", "").lower() \
        or dev.platform == "axon"
    if is_tpu:
        # BERT-large, phase-1 shapes
        V, D, Dff, L, H, B, T = 30522, 1024, 4096, 24, 16, 32, 128
        steps, warmup = 30, 3  # ±2 MFU run-to-run drift on the shared
        # tunneled chip — 30 timed steps averages it down
    else:  # CPU smoke configuration — keeps the harness runnable anywhere
        V, D, Dff, L, H, B, T = 1000, 128, 512, 2, 4, 4, 64
        steps, warmup = 3, 1

    class PretrainWithLoss(HybridBlock):
        """net + MLM/NSP cross-entropy so the whole step traces into one jit."""

        def __init__(self, net_, **kw):
            super().__init__(**kw)
            self.net = net_

        def forward(self, tokens, labels):
            mlm_logits, nsp_logits = self.net(tokens)
            logp = mx.nd.log_softmax(mlm_logits.astype("float32"))
            mlm = -(mx.nd.pick(logp, labels).mean())
            nsp_logp = mx.nd.log_softmax(nsp_logits.astype("float32"))
            nsp = -(nsp_logp[:, 0].mean())
            return mlm + nsp

    mx.random.seed(0)
    net = bert.BERTForPretraining(vocab_size=V, units=D, hidden_size=Dff,
                                  num_layers=L, num_heads=H, dropout=0.0)
    net.initialize()
    # materialize deferred shapes, then cast params to bf16 compute
    net(NDArray(jnp.ones((B, T), jnp.int32)))
    net.cast("bfloat16")

    model = PretrainWithLoss(net)
    model.hybridize()

    n_params = sum(p.data().size for p in net.collect_params().values()
                   if p.grad_req != "null")

    # keep_grads=False: grads are consumed inside the one fused step
    # program, never written back to HBM (the documented perf knob —
    # the analogue of the reference's hybridize(static_alloc=True))
    trainer = Trainer(model.collect_params(), "sgd",
                      {"learning_rate": 1e-3, "momentum": 0.9,
                       "multi_precision": True},
                      keep_grads=False)

    key = jax.random.PRNGKey(0)
    kx, ky = jax.random.split(key)
    tokens = NDArray(jax.random.randint(kx, (B, T), 0, V, dtype=jnp.int32))
    labels = NDArray(jax.random.randint(ky, (B, T), 0, V, dtype=jnp.int32))

    def train_step():
        with autograd.record():
            loss = model(tokens, labels)
        loss.backward()
        trainer.step(1)
        return loss

    for _ in range(warmup):
        loss = train_step()
    float(loss.asnumpy())  # value fetch — block_until_ready is unreliable over the relay

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = train_step()
    final_loss = float(loss.asnumpy())  # steps serialized by the params dependency
    dt = time.perf_counter() - t0

    tokens_per_s = B * T * steps / dt
    # train FLOPs/token ≈ 6·N_matmul + attention 12·L·T·D; embedding
    # lookups are gathers, not matmuls — exclude their tables
    n_embed = V * D + 512 * D + 2 * D
    flops_per_token = 6 * (n_params - n_embed) + 12 * L * T * D
    mfu = tokens_per_s * flops_per_token / _peak_flops(dev)

    print(json.dumps({
        "metric": "bert_large_pretrain_mfu" if is_tpu else "bert_smoke_pretrain_mfu",
        "value": round(mfu * 100, 2),
        "unit": "%MFU",
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": {
            "tokens_per_s": round(tokens_per_s, 1),
            "device": getattr(dev, "device_kind", str(dev)),
            "path": "gluon: autograd.record + backward + Trainer.step(fused)",
            "n_params": int(n_params),
            "batch": B, "seq": T, "steps_timed": steps,
            "final_loss": round(final_loss, 4),
        },
    }))


if __name__ == "__main__":
    main()
