"""BERT-large flagship-step ablation (real TPU, product Gluon path).

Finds where the non-ideal ~40% of the flagship step goes, with the same
methodology as resnet_ablate.py: the EXACT bench.py configuration and
code path (hybridized net+loss -> backward -> fused Trainer step), one
component toggled per variant, 30 timed steps fetched once.

    python benchmark/bert_ablate.py full nodrop noxent nohead noln ...

Variants:
  full     bench.py flagship: dropout=0.1, fp32 xent over V=30522
  nodrop   dropout=0.0 (bench.py's secondary number)
  bf16xent log_softmax in bf16 (no fp32 upcast of the (B,T,V) logits)
  noxent   loss = mlm_logits.mean() — keeps the V-decoder matmul,
           removes log_softmax/pick (isolates the xent cost)
  nohead   loss = seq.mean() — removes decoder matmul AND xent
           (isolates the whole MLM-head cost)
  noln     every LayerNorm replaced by identity
  relu     gelu -> relu in FFN + MLM head
  noattn   attention scores/softmax removed (QKV+out projections kept:
           out = out_proj(v)) — isolates the attention-core cost
  nomom    plain SGD, no momentum, no fp32 masters
  frozemb  embedding tables grad_req="null" — isolates the
           scatter-add embedding backward (a classic TPU slow path)
  attntr   pre-r4 TRANSPOSED attention formulation (explicit (B,H,T,D)
           copies + fp32 reference einsums) — A/B partner for the
           shipped transpose-free attention_bthd path
  xlaxent  pre-r4 fp32 log_softmax+pick loss (materializes the (B,T,V)
           fp32 log-prob tensor) — A/B partner for the fused kernel
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp

STEPS = int(os.environ.get("ABLATE_STEPS", "30"))
WARMUP = 3
# BERT-large phase-1 flagship shapes (bench.py); ABLATE_SMALL=1 smoke-tests
if os.environ.get("ABLATE_SMALL"):
    V, D, DFF, L, H, B, T = 1000, 64, 128, 2, 2, 4, 16
else:
    V, D, DFF, L, H, B, T = 30522, 1024, 4096, 24, 16, 32, 128


def build_and_measure(variant: str, trace_dir: str = None):
    """trace_dir: wrap ONLY the timed steps in jax.profiler.trace —
    tracing the compile too overflows the 2 GB XSpace protobuf cap."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, nn
    from incubator_mxnet_tpu.gluon.block import HybridBlock
    from incubator_mxnet_tpu.models import bert
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    dropout = 0.0 if variant == "nodrop" else 0.1

    if variant == "noln":
        class _IdLN(nn.LayerNorm):
            def forward(self, x):
                return x
        ln_cls, restore_ln = nn.LayerNorm, True
        nn.LayerNorm = _IdLN
    else:
        restore_ln = False

    if variant == "relu":
        import incubator_mxnet_tpu.ndarray.nn_ops as nn_ops
        real_gelu = nn_ops.gelu
        nn_ops.gelu = lambda x, approximate=True: nn_ops.Activation(x, "relu")
        mx.nd.gelu = nn_ops.gelu

    if variant == "noattn":
        from incubator_mxnet_tpu.models.bert import MultiHeadAttention

        def _no_scores_forward(self, x, mask=None):
            from incubator_mxnet_tpu.ndarray.ndarray import apply_op, wrap
            x = wrap(x)
            qkv = self.qkv(x)
            v = apply_op(lambda a: a[..., 2 * self._units:], qkv)
            return self.proj(v)

        real_fwd = MultiHeadAttention.forward
        MultiHeadAttention.forward = _no_scores_forward

    if variant == "attntr":
        # the pre-r4 TRANSPOSED formulation (explicit (B,H,T,D) copies
        # + fp32 reference einsums) — the A/B partner for the shipped
        # transpose-free attention_bthd path
        from incubator_mxnet_tpu.models.bert import MultiHeadAttention
        from incubator_mxnet_tpu.ops.flash_attention import attention_reference

        def _transposed_forward(self, x, mask=None):
            if mask is not None:
                raise NotImplementedError("attntr variant: no mask path")
            from incubator_mxnet_tpu.ndarray.ndarray import apply_op, wrap
            x = wrap(x)
            Bx, Tx, Cx = x.shape
            Hn = self._num_heads
            Dh = Cx // Hn
            qkv = self.qkv(x)

            def attend(qkv_raw):
                q, k, v = jnp.split(qkv_raw, 3, axis=-1)
                q = q.reshape(Bx, Tx, Hn, Dh).transpose(0, 2, 1, 3)
                k = k.reshape(Bx, Tx, Hn, Dh).transpose(0, 2, 1, 3)
                v = v.reshape(Bx, Tx, Hn, Dh).transpose(0, 2, 1, 3)
                o = attention_reference(q, k, v)
                return o.transpose(0, 2, 1, 3).reshape(Bx, Tx, Cx)

            return self.proj(apply_op(attend, qkv))

        real_fwd = MultiHeadAttention.forward
        MultiHeadAttention.forward = _transposed_forward

    try:
        mx.random.seed(0)
        net = bert.BERTForPretraining(vocab_size=V, units=D, hidden_size=DFF,
                                      num_layers=L, num_heads=H, dropout=dropout)
        net.initialize()
        net(NDArray(jnp.ones((B, T), jnp.int32)))
        net.cast("bfloat16")
        if variant == "frozemb":
            for name, p in net.collect_params().items():
                if "embed" in name and "weight" in name:
                    p.grad_req = "null"

        class StepLoss(HybridBlock):
            def __init__(self, net_, **kw):
                super().__init__(**kw)
                self.net = net_
                from incubator_mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
                self.mlm_loss = SoftmaxCrossEntropyLoss()

            def forward(self, tokens, labels):
                mlm_logits, nsp_logits = self.net(tokens)
                if variant == "noxent":
                    return mlm_logits.mean() + nsp_logits.mean()
                if variant == "bf16xent":
                    logp = mx.nd.log_softmax(mlm_logits)
                    mlm = -(mx.nd.pick(logp, labels).mean())
                    nsp_logp = mx.nd.log_softmax(nsp_logits)
                    return mlm + (-(nsp_logp[:, 0].mean()))
                if variant == "xlaxent":
                    # pre-r4 path: fp32 log_softmax + pick (materializes
                    # the (B,T,V) fp32 log-prob tensor)
                    logp = mx.nd.log_softmax(mlm_logits.astype("float32"))
                    mlm = -(mx.nd.pick(logp, labels).mean())
                    nsp_logp = mx.nd.log_softmax(nsp_logits.astype("float32"))
                    return mlm + (-(nsp_logp[:, 0].mean()))
                # bench.py flagship path: gluon loss -> fused Pallas
                # xent kernel on TPU (ops/xent_kernel.py)
                mlm = self.mlm_loss(mlm_logits, labels).mean()
                nsp_logp = mx.nd.log_softmax(nsp_logits.astype("float32"))
                return mlm + (-(nsp_logp[:, 0].mean()))

        class EncoderOnlyLoss(HybridBlock):
            def __init__(self, net_, **kw):
                super().__init__(**kw)
                self.net = net_

            def forward(self, tokens, labels):
                seq, pooled = self.net.bert(tokens)
                return seq.mean() + pooled.mean()

        model = (EncoderOnlyLoss if variant == "nohead" else StepLoss)(net)
        model.hybridize()

        opt_args = {"learning_rate": 1e-3}
        if variant != "nomom":
            opt_args.update(momentum=0.9, multi_precision=True)
        trainer = Trainer(model.collect_params(), "sgd", opt_args,
                          keep_grads=False)

        key = jax.random.PRNGKey(0)
        kx, ky = jax.random.split(key)
        tokens = NDArray(jax.random.randint(kx, (B, T), 0, V, dtype=jnp.int32))
        labels = NDArray(jax.random.randint(ky, (B, T), 0, V, dtype=jnp.int32))

        def train_step():
            with autograd.record():
                loss = model(tokens, labels)
            loss.backward()
            trainer.step(1)
            return loss

        for _ in range(WARMUP):
            loss = train_step()
        float(loss.asnumpy())
        import contextlib
        ctx = (jax.profiler.trace(trace_dir) if trace_dir
               else contextlib.nullcontext())
        with ctx:
            t0 = time.perf_counter()
            for _ in range(STEPS):
                loss = train_step()
            float(loss.asnumpy())
            dt = time.perf_counter() - t0
        ms = dt / STEPS * 1e3
        toks = B * T * STEPS / dt
        return ms, toks
    finally:
        if restore_ln:
            nn.LayerNorm = ln_cls
        if variant == "relu":
            nn_ops.gelu = real_gelu
            mx.nd.gelu = real_gelu
        if variant in ("noattn", "attntr"):
            MultiHeadAttention.forward = real_fwd


def main():
    variants = sys.argv[1:] or ["full", "nodrop", "noxent", "nohead", "noln",
                                "relu", "noattn", "nomom", "attntr",
                                "xlaxent", "bf16xent"]
    print(f"device={jax.devices()[0].device_kind} B={B} T={T} L={L} D={D} "
          f"steps={STEPS}")
    base = None
    for v in variants:
        ms, toks = build_and_measure(v)
        delta = "" if base is None else f"  delta={ms - base:+.2f} ms"
        if v == "full":
            base = ms
        print(f"{v:>9}: {ms:7.2f} ms/step  {toks:9.0f} tok/s{delta}",
              flush=True)


if __name__ == "__main__":
    main()
