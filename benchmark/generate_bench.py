"""KV-cache decode throughput — single chip, one compiled program.

    python benchmark/generate_bench.py [B] [P] [N] [--no-quant] [--act-quant=auto|none|dynamic]

TransformerLM at the longctx-bench size (12L/1024D/V=32k); reports
prefill+decode wall time and decoded tokens/s for the bf16 path AND
the int8 weight-quantized path (`quantize_for_decode` — per-channel
int8 weights streamed through the decode matmuls, dequant in the
epilogue), plus the per-step weight bytes each path streams
(`decode_weight_bytes` telemetry).  Small-batch decode is
weight-streaming-bound, so the quantized column is the headline: the
ISSUE 7 target is B=1 step time <= 0.6x bf16.

The inference-side counterpart of `benchmark/longctx_bench.py`'s
training rows.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp

V, D, DFF, L, H = 32000, 1024, 4096, 12, 16


def _time_generate(net, prompt, N, reps, **kw):
    import numpy as onp

    out = net.generate(prompt, N, **kw)  # compile
    onp.asarray(out)  # value fetch — block_until_ready is unreliable
    t0 = time.perf_counter()  # over this sandbox's relay
    for i in range(reps):
        out = net.generate(prompt, N, seed=i, **kw)
        onp.asarray(out[:, -1])
    return (time.perf_counter() - t0) / reps


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    B = int(args[0]) if len(args) > 0 else 8
    P = int(args[1]) if len(args) > 1 else 128
    N = int(args[2]) if len(args) > 2 else 128
    with_quant = "--no-quant" not in sys.argv
    aq = next((a.split("=", 1)[1] for a in sys.argv[1:]
               if a.startswith("--act-quant=")), "auto")

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import telemetry
    from incubator_mxnet_tpu.models.transformer import TransformerLM
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)
    net = TransformerLM(vocab=V, units=D, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=P + N, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((B, 16), jnp.int32)))
    net.cast("bfloat16")

    prompt = jax.random.randint(jax.random.PRNGKey(0), (B, P), 0, V,
                                dtype=jnp.int32)
    reps = 3
    telemetry.enable()
    reg = telemetry.get_registry()

    dt = _time_generate(net, prompt, N, reps)
    w_f = reg.get("decode_weight_bytes", {"path": "float"}).value
    print(f"TransformerLM {L}L/{D}D V={V} bf16, B={B} P={P} N={N}: "
          f"{dt*1e3:.1f} ms/gen = {B*N/dt:.0f} decoded tok/s "
          f"({dt/N*1e3:.2f} ms/token-step, batch {B}; "
          f"streams {w_f/1e6:.0f} MB weights/step)")
    if not with_quant:
        return

    net.quantize_for_decode(act_quant=aq)
    qdt = _time_generate(net, prompt, N, reps)
    w_q = reg.get("decode_weight_bytes", {"path": "int8"}).value
    qc = net._decode_quant
    print(f"TransformerLM {L}L/{D}D V={V} int8-weight "
          f"(act_quant={qc.act_quant}), B={B} P={P} N={N}: "
          f"{qdt*1e3:.1f} ms/gen = {B*N/qdt:.0f} decoded tok/s "
          f"({qdt/N*1e3:.2f} ms/token-step, batch {B}; "
          f"streams {w_q/1e6:.0f} MB weights/step)")
    print(f"quantized/bf16 step-time ratio: {qdt/dt:.2f}x "
          f"(target <= 0.60x at B=1); weight bytes {w_q/w_f:.2f}x")


if __name__ == "__main__":
    main()
