"""KV-cache decode throughput — single chip, one compiled program.

    python benchmark/generate_bench.py [B] [P] [N]

TransformerLM at the longctx-bench size (12L/1024D/V=32k); reports
prefill+decode wall time and decoded tokens/s (the inference-side
counterpart of `benchmark/longctx_bench.py`'s training rows).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp

V, D, DFF, L, H = 32000, 1024, 4096, 12, 16


def main():
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    N = int(sys.argv[3]) if len(sys.argv) > 3 else 128

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models.transformer import TransformerLM
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)
    net = TransformerLM(vocab=V, units=D, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=P + N, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((B, 16), jnp.int32)))
    net.cast("bfloat16")

    prompt = jax.random.randint(jax.random.PRNGKey(0), (B, P), 0, V,
                                dtype=jnp.int32)
    import numpy as onp

    out = net.generate(prompt, N)  # compile
    onp.asarray(out)  # value fetch — block_until_ready is unreliable
    reps = 3          # over this sandbox's relay
    t0 = time.perf_counter()
    for i in range(reps):
        out = net.generate(prompt, N, seed=i)
        onp.asarray(out[:, -1])
    dt = (time.perf_counter() - t0) / reps
    print(f"TransformerLM {L}L/{D}D V={V} bf16, B={B} P={P} N={N}: "
          f"{dt*1e3:.1f} ms/gen = {B*N/dt:.0f} decoded tok/s "
          f"({dt/N*1e3:.2f} ms/token-step, batch {B})")


if __name__ == "__main__":
    main()
