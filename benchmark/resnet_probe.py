"""ResNet-50 train-step perf probe (VERDICT r3 item: ≥40% of the bf16
compute ceiling).  Measures the canonical Gluon path and pure-JAX
variants to localize where the step time goes: framework overhead vs
XLA conv scheduling vs layout.

Run ON THE TPU: python benchmark/resnet_probe.py [variants...]
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp


def fetch(x):
    return float(jnp.sum(jax.tree_util.tree_leaves(x)[0].ravel()[:1]))


def time_steps(step, args, n=20, warm=3):
    for _ in range(warm):
        out = step(*args)
    fetch(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = step(*args)
    fetch(out)
    return (time.perf_counter() - t0) / n


def gluon_variant(B, dtype="bfloat16"):
    """The measured-of-record Gluon loop (train.py config)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)
    net = resnet50_v1(classes=1000)
    net.initialize()
    x0 = NDArray(jnp.ones((B, 3, 224, 224), jnp.float32))
    net(x0)
    if dtype == "bfloat16":
        net.cast("bfloat16")
    net.hybridize()
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9,
                  "multi_precision": True}, keep_grads=False)
    x = NDArray(jnp.ones((B, 3, 224, 224),
                         jnp.bfloat16 if dtype == "bfloat16" else jnp.float32))
    y = NDArray(jnp.zeros((B,), jnp.int32))

    def step(x, y):
        with autograd.record():
            L = loss_fn(net(x), y).mean()
        L.backward()
        tr.step(1)
        return L

    dt = time_steps(lambda *a: step(*a).asnumpy(), (x, y))
    return B / dt


def purejax_variant(B, layout="NCHW", dtype=jnp.bfloat16, bn_dtype="same"):
    """Hand-rolled ResNet-50 train step — the XLA ceiling probe.

    layout: logical activation layout fed to conv_general_dilated.
    """
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.block import functionalize
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)
    net = resnet50_v1(classes=1000)
    net.initialize()
    x0 = NDArray(jnp.ones((B, 3, 224, 224), jnp.float32))
    net(x0)
    net.cast("bfloat16")
    apply_fn, train_raws, aux_raws = functionalize(net)
    rng = jax.random.PRNGKey(0)
    y = jnp.zeros((B,), jnp.int32)
    x = jnp.ones((B, 3, 224, 224), dtype)

    masters = tuple(w.astype(jnp.float32) for w in train_raws)
    moms = tuple(jnp.zeros_like(m) for m in masters)

    def loss_of(tr, aux, xx):
        out, new_aux = apply_fn(tr, aux, rng, xx, training=True)
        logits = out.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1)), new_aux

    @jax.jit
    def step(masters, moms, aux, xx):
        tr = tuple(m.astype(jnp.bfloat16) for m in masters)
        (L, new_aux), grads = jax.value_and_grad(
            lambda t: loss_of(t, aux, xx), has_aux=True)(tr)
        new_moms = tuple(0.9 * v + g.astype(jnp.float32)
                         for v, g in zip(moms, grads))
        new_masters = tuple(m - 0.1 * v for m, v in zip(masters, new_moms))
        return new_masters, new_moms, new_aux, L

    def run(masters, moms, aux):
        return step(masters, moms, aux, x)

    state = [masters, moms, aux_raws]

    def stepper():
        m, v, a, L = step(state[0], state[1], state[2], x)
        state[0], state[1], state[2] = m, v, a
        return L

    for _ in range(3):
        L = stepper()
    fetch(L)
    t0 = time.perf_counter()
    for _ in range(20):
        L = stepper()
    fetch(L)
    dt = (time.perf_counter() - t0) / 20
    return B / dt


def main():
    which = sys.argv[1:] or ["gluon", "purejax"]
    B = 128
    for w in which:
        if w == "gluon":
            print(f"gluon bf16 BS{B}: {gluon_variant(B):.0f} img/s", flush=True)
        elif w == "purejax":
            print(f"purejax bf16 BS{B}: {purejax_variant(B):.0f} img/s", flush=True)


if __name__ == "__main__":
    main()
