"""ResNet-50 train-step perf probe (VERDICT r3 item: ≥40% of the bf16
compute ceiling).  Measures the canonical Gluon path and a pure-JAX
hand-rolled step to localize where the step time goes: framework
overhead vs XLA conv scheduling.

Run ON THE TPU: python benchmark/resnet_probe.py [gluon|purejax ...]

NOTE: the tunneled v5e is shared; when another tenant fragments HBM
(contiguous allocations ≳4 GB fail while total free is ~15 GB), the
BS128 step OOMs — retry when the chip is quiet (BASELINE.md note).
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp


def time_steps(step_once, fetch, n=20, warm=3):
    """Fetch a value ONLY at the timing boundaries (a per-step host
    fetch costs an RTT on the relay and serializes the queue)."""
    for _ in range(warm):
        out = step_once()
    fetch(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = step_once()
    fetch(out)
    return (time.perf_counter() - t0) / n


def _build_net(B):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)
    net = resnet50_v1(classes=1000)
    net.initialize()
    # resolve deferred shapes with a TINY batch: the eager forward
    # materializes every intermediate activation
    net(NDArray(jnp.ones((4, 3, 224, 224), jnp.float32)))
    net.cast("bfloat16")
    return net


def gluon_variant(B):
    """The measured-of-record Gluon loop (train.py config)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    net = _build_net(B)
    net.hybridize()
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9,
                  "multi_precision": True}, keep_grads=False)
    x = NDArray(jnp.ones((B, 3, 224, 224), jnp.bfloat16))
    y = NDArray(jnp.zeros((B,), jnp.int32))

    def step_once():
        with autograd.record():
            # canonical loop: backward on the per-sample loss (NO .mean()
            # — an eager op on the lazy outputs breaks the one-program
            # chain and forces the residual-materializing staged path,
            # which at BS128 OOMs the chip)
            L = loss_fn(net(x), y)
        L.backward()
        tr.step(B)
        return L

    return B / time_steps(step_once,
                          lambda L: float(L.asnumpy().ravel()[0]))


def purejax_variant(B):
    """Hand-rolled ResNet-50 train step — the XLA ceiling probe."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.block import functionalize

    net = _build_net(B)
    apply_fn, train_raws, aux_raws = functionalize(net)
    rng = jax.random.PRNGKey(0)
    y = jnp.zeros((B,), jnp.int32)
    x = jnp.ones((B, 3, 224, 224), jnp.bfloat16)

    masters = tuple(w.astype(jnp.float32) for w in train_raws)
    moms = tuple(jnp.zeros_like(m) for m in masters)

    @jax.jit
    def step(masters, moms, aux, xx):
        tr = tuple(m.astype(jnp.bfloat16) for m in masters)

        def loss_of(t):
            out, new_aux = apply_fn(t, aux, rng, xx, training=True)
            logp = jax.nn.log_softmax(out.astype(jnp.float32))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1)), new_aux

        (L, new_aux), grads = jax.value_and_grad(loss_of, has_aux=True)(tr)
        new_moms = tuple(0.9 * v + g.astype(jnp.float32)
                         for v, g in zip(moms, grads))
        new_masters = tuple(m - 0.1 * v for m, v in zip(masters, new_moms))
        return new_masters, new_moms, new_aux, L

    state = [masters, moms, aux_raws]

    def step_once():
        m, v, a, L = step(state[0], state[1], state[2], x)
        state[0], state[1], state[2] = m, v, a
        return L

    return B / time_steps(step_once, lambda L: float(jnp.asarray(L)))


def scan_variant(B, K=8, reps=4):
    """K train steps CHAINED inside ONE jit (lax.scan over the full
    train state): pure on-chip step time, no per-dispatch relay cost —
    the difference vs `purejax` isolates the relay overhead per step."""
    from jax import lax

    from incubator_mxnet_tpu.gluon.block import functionalize

    net = _build_net(B)
    apply_fn, train_raws, aux_raws = functionalize(net)
    rng = jax.random.PRNGKey(0)
    y = jnp.zeros((B,), jnp.int32)
    x = jnp.ones((B, 3, 224, 224), jnp.bfloat16)

    masters = tuple(w.astype(jnp.float32) for w in train_raws)
    moms = tuple(jnp.zeros_like(m) for m in masters)

    @jax.jit
    def multi(masters, moms, aux, xx):
        def body(carry, _):
            m, v, a = carry
            tr = tuple(w.astype(jnp.bfloat16) for w in m)

            def loss_of(t):
                out, new_aux = apply_fn(t, a, rng, xx, training=True)
                logp = jax.nn.log_softmax(out.astype(jnp.float32))
                return (-jnp.mean(jnp.take_along_axis(logp, y[:, None], 1)),
                        new_aux)

            (L, new_aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(tr)
            nv = tuple(0.9 * vv + g.astype(jnp.float32)
                       for vv, g in zip(v, grads))
            nm = tuple(mm - 0.1 * vv for mm, vv in zip(m, nv))
            return (nm, nv, new_aux), L

        (m, v, a), Ls = lax.scan(body, (masters, moms, aux), None, length=K)
        return m, v, a, Ls[-1]

    out = multi(masters, moms, aux_raws, x)
    float(jnp.asarray(out[-1]))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = multi(masters, moms, aux_raws, x)
    float(jnp.asarray(out[-1]))
    dt = (time.perf_counter() - t0) / (reps * K)
    return B / dt


def gluon_chain_variant(B, K=8):
    """The PRODUCT path with multi-step chaining: the same public
    record→backward→step loop, Trainer(chain_steps=K) — K steps per
    dispatched program (r4 VERDICT item 1)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    net = _build_net(B)
    net.hybridize()
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9,
                  "multi_precision": True}, keep_grads=False,
                 chain_steps=K)
    x = NDArray(jnp.ones((B, 3, 224, 224), jnp.bfloat16))
    y = NDArray(jnp.zeros((B,), jnp.int32))

    def step_once():
        with autograd.record():
            L = loss_fn(net(x), y)
        L.backward()
        tr.step(B)
        return L

    # time whole chains: n must be a multiple of K so the fetch at the
    # timing boundary lands right after a flush
    return B / time_steps(step_once,
                          lambda L: float(L.asnumpy().ravel()[0]),
                          n=3 * K, warm=2 * K + 1)


def main():
    which = sys.argv[1:] or ["gluon", "purejax"]
    B = int(os.environ.get("RESNET_PROBE_BS", "128"))
    for w in which:
        fn = {"gluon": gluon_variant, "purejax": purejax_variant,
              "scan": scan_variant,
              "gluon_chain": gluon_chain_variant}[w]
        print(f"{w} bf16 BS{B}: {fn(B):.0f} img/s", flush=True)


if __name__ == "__main__":
    main()
