"""Open-loop serving load harness — continuous batching under overload.

Drives the paged-KV ServingEngine (incubator_mxnet_tpu/serving/) with
Poisson arrivals at a configurable offered load, optionally injecting
faults (a slowed decode step, mid-flight client cancellations), and
reports the latency/goodput envelope:

    python benchmark/serving_bench.py [--rate HZ] [--requests N]
        [--max-batch B] [--max-queue Q] [--prompt-len P] [--new-tokens T]
        [--slow-step-ms MS] [--cancel-frac F] [--kv-dtype model|int8]
        [--speculate K] [--draft int8|tiny]
        [--shared-prefix-frac F] [--prefill-chunk N]
        [--long-prompt-every K]
        [--sweep-prompt-lens P1,P2,...] [--seed S] [--out FILE]
        [--profile] [--profile-out TRACE.json]

Open loop: arrival gaps are pre-sampled exponentials and submit() never
blocks on the engine — requests the bounded queue cannot hold are shed,
exactly as a real frontend would see.  Per-request timestamps come from
the engine itself (Request.t_submit / t_first / t_done), so TTFT
includes queueing delay and TPOT is pure decode cadence.

Emits ONE BENCH-style JSON row (the repo convention, see bench.py /
BENCH_r06.json): {"metric", "value", "unit", "detail"} where value is
GOODPUT UNDER SLO — decoded tok/s of requests that completed AND met
both latency targets (``--ttft-slo-ms``, ``--tpot-slo-ms``; shed,
evicted and SLO-violating work all count as zero, the number a
capacity planner actually provisions against) — and detail carries raw
goodput, offered load, shed fraction and TTFT/TPOT p50/p95/p99.

``--kv-dtype int8`` runs the same harness against an int8-KV-pool
engine (ISSUE 15): pages quantize at write time, the attention
dequantizes in-kernel, and ``detail.kv_bytes_per_token`` records the
capacity win.  ``--sweep-prompt-lens 24,96,192`` appends compact
secondary rows under ``detail.prompt_sweep`` — the longer-prompt
regime where dense-gather attention traffic grows with ``max_seq_len``
while the paged kernel's page walk stays length-bounded.

``--speculate K`` (ISSUE 19) turns on draft/verify speculative
decoding: a cheap draft proposes K tokens per lane per scheduler
iteration and the target verifies all of them in ONE batched forward —
one target weight stream amortized over up to K+1 tokens per lane.
``--draft int8`` (default) self-drafts with the target's own
int8-quantized twin (high acceptance, no second model);
``--draft tiny`` uses a fresh small TransformerLM (cheaper draft,
lower acceptance).  Greedy output is bit-identical to the
non-speculative engine either way; ``detail.speculate`` reports the
measured acceptance rate and tokens-per-lane-step.

``--shared-prefix-frac F`` (ISSUE 20) makes every short prompt share
its first ``int(F * prompt_len)`` tokens — the system-prompt traffic
shape the copy-on-write prefix cache serves without re-prefilling:
after the first admission registers the shared blocks, later requests
bind them and chunk-prefill only their private tail.
``detail.prefix_cache`` carries the engine's hit/miss/cached-token
counters, and a cold CONTROL pass at the same config (prefix sharing
off) lands under ``detail.prefix_cache_control`` with the measured
TTFT p50 reduction.  ``--prefill-chunk N`` sets the engine's fixed chunk width
(default: the engine's own default).  ``--long-prompt-every K`` runs a
SECOND measured pass where every K-th request carries a cold 2x-length
prompt — the head-of-line-blocking regime chunked prefill exists for —
and reports tpot p99 over the SHORT requests (the victims of a
monolithic prefill) next to the steady-state p99 under
``detail.long_prompt_arrival``.

``--profile`` (ISSUE 17) enables telemetry for the measured run and
carries the stall-attribution table + recent hiccup records under
``detail.profile``, so a BENCH row explains WHERE the step time went
alongside how much goodput it bought; ``--profile-out FILE`` also
writes the merged chrome-trace JSON (request/scheduler/program lanes)
for chrome://tracing / Perfetto.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import jax.numpy as jnp
import numpy as np

# bench model: big enough that a decode step does real work, small
# enough to warm up in seconds on any host
V, C, DFF, L, H = 1024, 128, 512, 2, 4


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=float, default=20.0,
                    help="offered load, requests/s (Poisson)")
    ap.add_argument("--requests", type=int, default=80)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=24)
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--slow-step-ms", type=float, default=0.0,
                    help="fault injection: model a slow device costing "
                         "this long per batched decode step, and "
                         "proportionally per prefill chunk "
                         "(MS * chunk_width / max_batch — same "
                         "per-token device cost)")
    ap.add_argument("--cancel-frac", type=float, default=0.0,
                    help="fault injection: cancel this fraction of "
                         "requests ~one step after submission")
    ap.add_argument("--ttft-slo-ms", type=float, default=2000.0,
                    help="TTFT target a request must meet to count "
                         "toward goodput-under-SLO")
    ap.add_argument("--tpot-slo-ms", type=float, default=500.0,
                    help="TPOT target a request must meet to count "
                         "toward goodput-under-SLO")
    ap.add_argument("--kv-dtype", choices=("model", "int8"),
                    default="model",
                    help="KV pool dtype: 'int8' quantizes pages at "
                         "write time (fp32 per-vector scales ride "
                         "alongside, dequant happens in the attention)")
    ap.add_argument("--speculate", type=int, default=0, metavar="K",
                    help="speculative decoding: draft K tokens per lane "
                         "per step, verify them in one batched target "
                         "forward (0 = off)")
    ap.add_argument("--draft", choices=("int8", "tiny"), default="int8",
                    help="draft model for --speculate: 'int8' "
                         "self-drafts with the target's quantized twin, "
                         "'tiny' uses a fresh small TransformerLM")
    ap.add_argument("--shared-prefix-frac", type=float, default=0.0,
                    metavar="F",
                    help="short prompts share their first int(F * "
                         "prompt_len) tokens; the prefix cache serves "
                         "the shared blocks after the first admission")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    metavar="N",
                    help="fixed prefill chunk width (tokens per chunk "
                         "program call); default: engine default")
    ap.add_argument("--long-prompt-every", type=int, default=0,
                    metavar="K",
                    help="also run a long-prompt-arrival pass: every "
                         "K-th request carries a cold 2x-length prompt; "
                         "reports short-request tpot p99 under "
                         "detail.long_prompt_arrival (0 = off)")
    ap.add_argument("--sweep-prompt-lens",
                    help="comma-separated extra prompt lengths; each "
                         "runs the same open loop and lands a compact "
                         "row under detail.prompt_sweep")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", help="also write the JSON row here")
    ap.add_argument("--profile", action="store_true",
                    help="enable telemetry for the measured run and "
                         "carry the stall-attribution table + recent "
                         "hiccups under detail.profile")
    ap.add_argument("--profile-out",
                    help="with --profile: write the merged chrome-trace "
                         "JSON (request/scheduler/program lanes) here")
    args = ap.parse_args()
    if args.profile_out and not args.profile:
        ap.error("--profile-out requires --profile")

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models.transformer import TransformerLM
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    sweep_lens = [int(s) for s in args.sweep_prompt_lens.split(",")] \
        if args.sweep_prompt_lens else []

    if args.profile:
        # the stall ledger runs regardless; telemetry must be ON for
        # its histograms, trace lanes and program timings to record
        from incubator_mxnet_tpu import telemetry

        telemetry.enable()

    mx.random.seed(args.seed)
    max_prompt = max([args.prompt_len] + sweep_lens
                     + ([2 * args.prompt_len] if args.long_prompt_every
                        else []))
    net = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                        num_heads=H,
                        max_len=max_prompt + args.new_tokens + 40,
                        dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))
    net.cast("bfloat16")

    args.spec_kw = {}
    if args.speculate > 0:
        args.spec_kw["speculate_k"] = args.speculate
        if args.draft == "int8":
            # the target's int8 twin IS the draft; the serving target
            # itself stays bf16 (quantized=False)
            net.quantize_for_decode(act_quant="none")
            args.spec_kw["quantized"] = False
        else:
            mx.random.seed(args.seed + 1)
            draft = TransformerLM(vocab=V, units=C // 2,
                                  hidden_size=DFF // 2, num_layers=1,
                                  num_heads=H // 2,
                                  max_len=max_prompt + args.new_tokens + 40,
                                  dropout=0.0)
            draft.initialize()
            draft(NDArray(jnp.ones((1, 4), jnp.int32)))
            draft.cast("bfloat16")
            args.spec_kw["draft_net"] = draft

    run = _run_once(args, net, args.prompt_len)
    row = _render_row(args, run)
    if sweep_lens:
        row["detail"]["prompt_sweep"] = [
            _sweep_summary(args, net, plen) for plen in sweep_lens]
    if args.shared_prefix_frac > 0:
        # cold control at the SAME config: the measured win of serving
        # the shared prefix from cache instead of re-prefilling it
        ctrl = argparse.Namespace(**vars(args))
        ctrl.shared_prefix_frac = 0.0
        creqs, _, _, _ = _run_once(ctrl, net, args.prompt_len)
        cold = _ttft_p50_ms(creqs)
        warm = row["detail"]["ttft_ms"]["p50"]
        row["detail"]["prefix_cache_control"] = {
            "ttft_p50_ms_cold": cold,
            "ttft_p50_ms_shared": warm,
            "ttft_p50_reduction": (None if not warm or not cold
                                   else round(cold / warm, 2))}
    if args.long_prompt_every:
        row["detail"]["long_prompt_arrival"] = _long_prompt_summary(
            args, net)
    line = json.dumps(row)
    print(line)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
    if args.profile_out:
        with open(args.profile_out, "w", encoding="utf-8") as fh:
            json.dump(run[3]["trace"], fh)


def _run_once(args, net, prompt_len, long_every=0, long_msl=False):
    """One open-loop measured run; returns the raw observations."""
    from incubator_mxnet_tpu.serving import ServingEngine

    long_len = 2 * prompt_len
    msl = (long_len if long_every or long_msl else prompt_len) \
        + args.new_tokens + 8
    eng = ServingEngine(net, max_batch=args.max_batch, block_size=16,
                        max_seq_len=msl, max_queue=args.max_queue,
                        kv_dtype="int8" if args.kv_dtype == "int8" else None,
                        prefill_chunk=args.prefill_chunk,
                        slo_ttft=args.ttft_slo_ms / 1e3,
                        slo_tpot=args.tpot_slo_ms / 1e3,
                        **getattr(args, "spec_kw", {}))

    rng = np.random.RandomState(args.seed)
    share = int(round(args.shared_prefix_frac * prompt_len))
    shared = rng.randint(0, V, size=share).astype(np.int32)
    # long prompts are COLD (no shared prefix): the head-of-line
    # stressor is a full-length chunked prefill, not a cache hit
    long_idx = {i for i in range(args.requests)
                if long_every and i and i % long_every == 0}
    prompts = []
    for i in range(args.requests):
        if i in long_idx:
            prompts.append(rng.randint(0, V, size=long_len)
                           .astype(np.int32))
        else:
            tail = rng.randint(0, V, size=prompt_len - share) \
                      .astype(np.int32)
            prompts.append(np.concatenate([shared, tail]))
    gaps = rng.exponential(1.0 / args.rate, size=args.requests)
    cancel = rng.random_sample(args.requests) < args.cancel_frac

    # warmup: compile the chunk + step programs OUTSIDE the timed run
    # (with a shared prefix this also registers it — the steady state a
    # prefix-cache deployment actually serves from)
    eng.submit(prompts[0], args.new_tokens).result(timeout=120)
    assert eng.drain(timeout=60)
    if args.slow_step_ms > 0:
        # consistent synthetic device: a decode step carries up to
        # max_batch tokens for slow_step_ms, so an N-token prefill
        # chunk on the same device costs slow_step_ms * N / max_batch
        step_s = args.slow_step_ms / 1e3
        chunk_s = step_s * (eng.stats()["prefill_chunk"]["chunk"]
                            / args.max_batch)
        eng.set_fault_hook(
            lambda ph: time.sleep(step_s if ph == "step" else chunk_s)
            if ph in ("step", "prefill") else None)

    reqs = []
    t0 = time.monotonic()
    for i in range(args.requests):
        time.sleep(gaps[i])
        r = eng.submit(prompts[i], args.new_tokens, seed=i)
        reqs.append(r)
        if cancel[i]:
            r.cancel()
    assert eng.drain(timeout=600), "engine failed to drain"
    wall = time.monotonic() - t0
    stats = eng.stats()
    info = {"kv_bytes_per_token": eng.kv_bytes_per_token,
            "attn_impl": eng.attn_impl,
            "prefix_cache": stats["prefix_cache"],
            "prefill_chunk": stats["prefill_chunk"]["chunk"],
            "long_idx": long_idx}
    if args.speculate > 0:
        spec = stats["speculate"]
        info["speculate"] = {
            "k": spec["k"],
            "draft": spec["draft"],
            "accept_rate": round(spec["accept_rate"], 4),
            # per lane-iteration: 1 committed token + k*accept_rate
            # accepted drafts (the amortization factor over one target
            # weight stream)
            "tokens_per_lane_step": round(
                1.0 + spec["k"] * spec["accept_rate"], 2),
            "proposed": spec["proposed"],
            "accepted": spec["accepted"],
        }
    if args.profile:
        prof = eng.profiler
        info["profile"] = {
            "stall_attribution": eng.stall_table(),
            "hiccups": prof.recent_stalls(8),
            "hiccups_total": prof.hiccups_total,
            "invariant_violations": prof.invariant_violations,
        }
        if args.profile_out:
            # capture BEFORE close(): the engine's scheduler lane
            # unregisters from the merged timeline at close
            info["trace"] = eng.capture_profile(0)
    eng.close()
    return reqs, stats, wall, info


def _sweep_summary(args, net, prompt_len):
    """Compact secondary row for one sweep length."""
    reqs, stats, wall, info = _run_once(args, net, prompt_len)
    done = [r for r in reqs if r.status == "done"]
    slo_ok = [r for r in done
              if (r.ttft is None or r.ttft <= args.ttft_slo_ms / 1e3)
              and (r.tpot is None or r.tpot <= args.tpot_slo_ms / 1e3)]
    tpot = sorted((r.t_done - r.t_first) / (len(r.tokens) - 1)
                  for r in done if len(r.tokens) > 1)
    p50 = _pct(tpot, 50)
    return {"prompt_len": prompt_len,
            "goodput_under_slo": round(
                sum(len(r.tokens) for r in slo_ok) / wall, 1),
            "served_under_slo": len(slo_ok),
            "tpot_p50_ms": None if p50 is None else round(p50 * 1e3, 2),
            "wall_s": round(wall, 2)}


def _ttft_p50_ms(reqs):
    tt = sorted(r.t_first - r.t_submit for r in reqs
                if r.status == "done" and r.t_first is not None)
    p = _pct(tt, 50)
    return None if p is None else round(p * 1e3, 2)


def _short_tpot_p99_ms(reqs, long_idx):
    """p99 over INDIVIDUAL inter-token gaps of the short requests (one
    sample per decoded token, not per-request means): a monolithic
    prefill's stall cannot hide inside a request's average."""
    gaps = []
    for i, r in enumerate(reqs):
        if i in long_idx or r.status != "done":
            continue
        gaps.extend(b - a for a, b in zip(r.t_tokens, r.t_tokens[1:]))
    gaps.sort()
    p99 = _pct(gaps, 99)
    return None if p99 is None else round(p99 * 1e3, 2)


def _long_prompt_summary(args, net):
    """Two passes on the IDENTICAL engine config (same max_seq_len, so
    same pool and program shapes): a steady all-short baseline, then
    one where a cold 2x-length prompt arrives every K-th request.  tpot
    p99 is computed over the SHORT requests only — the victims a
    monolithic prefill would stall for the whole long prompt; with
    chunked prefill their decode cadence should barely move."""
    sreqs, _, _, _ = _run_once(args, net, args.prompt_len, long_msl=True)
    steady = _short_tpot_p99_ms(sreqs, set())
    reqs, stats, wall, info = _run_once(args, net, args.prompt_len,
                                        long_every=args.long_prompt_every)
    longs = info["long_idx"]
    p99_ms = _short_tpot_p99_ms(reqs, longs)
    return {"every": args.long_prompt_every,
            "long_prompt_len": 2 * args.prompt_len,
            "long_served": sum(1 for i, r in enumerate(reqs)
                               if i in longs and r.status == "done"),
            "short_served": sum(1 for i, r in enumerate(reqs)
                                if i not in longs and r.status == "done"),
            "short_tpot_p99_ms": p99_ms,
            "steady_tpot_p99_ms": steady,
            "ratio_vs_steady": (None if not p99_ms or not steady
                                else round(p99_ms / steady, 2)),
            "wall_s": round(wall, 2)}


def _render_row(args, run):
    reqs, stats, wall, info = run
    done = [r for r in reqs if r.status == "done"]
    shed = sum(stats["shed"].values())
    evicted = sum(stats["evicted"].values())
    cancelled = sum(1 for r in reqs if r.status == "cancelled")
    ttft = sorted(r.t_first - r.t_submit for r in done
                  if r.t_first is not None)
    tpot = sorted((r.t_done - r.t_first) / (len(r.tokens) - 1)
                  for r in done if len(r.tokens) > 1)
    good_tokens = sum(len(r.tokens) for r in done)
    goodput = good_tokens / wall
    # goodput UNDER SLO: only requests meeting both latency targets
    # (the engine derives r.ttft / r.tpot at finish time)
    slo_ok = [r for r in done
              if (r.ttft is None or r.ttft <= args.ttft_slo_ms / 1e3)
              and (r.tpot is None or r.tpot <= args.tpot_slo_ms / 1e3)]
    slo_tokens = sum(len(r.tokens) for r in slo_ok)

    row = {
        "metric": "serving_goodput_under_slo",
        "value": round(slo_tokens / wall, 1),
        "unit": "tok/s",
        "detail": {
            "offered_load_hz": args.rate,
            "requests": args.requests,
            "served": len(done),
            "served_under_slo": len(slo_ok),
            "goodput_raw": round(goodput, 1),
            "ttft_slo_ms": args.ttft_slo_ms,
            "tpot_slo_ms": args.tpot_slo_ms,
            "shed": shed,
            "shed_fraction": round(shed / args.requests, 4),
            "evicted": evicted,
            "cancelled": cancelled,
            "ttft_ms": {"p50": _pct(ttft, 50), "p95": _pct(ttft, 95),
                        "p99": _pct(ttft, 99)},
            "tpot_ms": {"p50": _pct(tpot, 50), "p95": _pct(tpot, 95),
                        "p99": _pct(tpot, 99)},
            "decode_steps": stats["steps"],
            "wall_s": round(wall, 2),
            "max_batch": args.max_batch,
            "max_queue": args.max_queue,
            "prompt_len": args.prompt_len,
            "new_tokens": args.new_tokens,
            "slow_step_ms": args.slow_step_ms,
            "cancel_frac": args.cancel_frac,
            "shared_prefix_frac": args.shared_prefix_frac,
            "prefill_chunk": info["prefill_chunk"],
            "prefix_cache": info["prefix_cache"],
            "kv_dtype": args.kv_dtype,
            "attn_impl": info["attn_impl"],
            "kv_bytes_per_token": info["kv_bytes_per_token"],
            "model": f"TransformerLM {L}L/{C}D V={V} bf16",
            "device": jax.devices()[0].device_kind,
        },
    }
    for d in (row["detail"]["ttft_ms"], row["detail"]["tpot_ms"]):
        for k, v in d.items():
            d[k] = None if v is None else round(v * 1e3, 2)
    if "speculate" in info:
        row["detail"]["speculate"] = info["speculate"]
    if "profile" in info:
        row["detail"]["profile"] = info["profile"]
    return row


if __name__ == "__main__":
    main()
