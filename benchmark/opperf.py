#!/usr/bin/env python
"""Per-op fwd/bwd timing harness (ref `benchmark/opperf/`, SURVEY.md
§2.8): times every benchmarked op's forward and forward+backward over
representative shapes, emitting JSON (and optionally markdown).

Run: python benchmark/opperf.py [--ops tanh,dot] [--json out.json]
     [--shape-scale small|large] [--warmup 2] [--runs 10]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _registry(scale="small"):
    """op name -> (fn over NDArrays, input-maker)."""
    import jax
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.ndarray import linalg, nn_ops, ops
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    big = scale == "large"
    V = (1024, 1024) if big else (128, 128)
    C = (8, 64, 56, 56) if big else (2, 8, 16, 16)
    key = jax.random.PRNGKey(0)

    def rnd(shape, k=0):
        return NDArray(jax.random.normal(jax.random.fold_in(key, k), shape))

    reg = {}

    def add(name, fn, maker):
        reg[name] = (fn, maker)

    for name in ("tanh", "sigmoid", "exp", "log", "sqrt", "relu", "erf",
                 "square", "abs"):
        fn = getattr(ops, name)
        dom = (0.1, 2.0) if name in ("log", "sqrt") else None

        def mk(name=name, dom=dom):
            x = rnd(V)
            if dom:
                x = NDArray(jnp.abs(x._data) + dom[0])
            return (x,)

        add(name, fn, mk)
    for name in ("add", "multiply", "maximum", "power"):
        def mk2(name=name):
            return (NDArray(jnp.abs(rnd(V, 1)._data) + 0.1), rnd(V, 2))

        add(name, getattr(ops, name), mk2)
    add("dot", ops.dot, lambda: (rnd(V, 3), rnd(V, 4)))
    add("sum", lambda x: ops.sum(x), lambda: (rnd(V, 5),))
    add("softmax", nn_ops.softmax, lambda: (rnd(V, 6),))
    add("log_softmax", nn_ops.log_softmax, lambda: (rnd(V, 7),))
    add("LayerNorm",
        lambda x, g, b: nn_ops.LayerNorm(x, g, b),
        lambda: (rnd(V, 8), NDArray(jnp.ones(V[1])), NDArray(jnp.zeros(V[1]))))
    add("FullyConnected",
        lambda x, w: nn_ops.FullyConnected(x, w, num_hidden=V[1], no_bias=True),
        lambda: (rnd(V, 9), rnd(V, 10)))
    add("Convolution",
        lambda x, w: nn_ops.Convolution(x, w, kernel=(3, 3), pad=(1, 1),
                                        num_filter=C[1], no_bias=True),
        lambda: (rnd(C, 11), rnd((C[1], C[1], 3, 3), 12)))
    add("Pooling",
        lambda x: nn_ops.Pooling(x, kernel=(2, 2), stride=(2, 2),
                                 pool_type="max"),
        lambda: (rnd(C, 13),))
    add("transpose", ops.transpose, lambda: (rnd(V, 14),))
    add("concat", lambda a, b: ops.concat(a, b, dim=1),
        lambda: (rnd(V, 15), rnd(V, 16)))
    add("take", lambda x, i: ops.take(x, i),
        lambda: (rnd(V, 17),
                 NDArray(jnp.arange(0, V[0], 2, dtype=jnp.int32))))
    add("gemm2", linalg.gemm2, lambda: (rnd(V, 18), rnd(V, 19)))
    add("flash_attention",
        lambda q, k, v: __import__(
            "incubator_mxnet_tpu.ops.flash_attention",
            fromlist=["flash_attention"]).flash_attention(q, k, v),
        lambda: tuple(rnd((2, 4, 64, 32), 20 + i) for i in range(3)))
    return reg


def _time_op(fn, args, warmup, runs, backward=False):
    import jax

    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    def fwd():
        out = fn(*args)
        return out[0] if isinstance(out, tuple) else out

    def fwd_bwd():
        for a in args:
            if isinstance(a, NDArray) and str(a.dtype).startswith("float"):
                a.attach_grad()
        with autograd.record():
            out = fn(*args)
            o = out[0] if isinstance(out, tuple) else out
            s = o.sum()
        s.backward()
        return s

    run = fwd_bwd if backward else fwd
    for _ in range(max(1, warmup)):  # at least one compile pass
        r = run()
    float(r.asnumpy().ravel()[0])
    t0 = time.perf_counter()
    for _ in range(runs):
        r = run()
    float(r.asnumpy().ravel()[0])
    return (time.perf_counter() - t0) / runs * 1e3


def main(argv=None):
    p = argparse.ArgumentParser(description="op performance harness")
    p.add_argument("--ops", type=str, default="",
                   help="comma-separated subset (default: all)")
    p.add_argument("--json", type=str, default=None)
    p.add_argument("--markdown", type=str, default=None)
    p.add_argument("--shape-scale", type=str, default="small",
                   choices=["small", "large"])
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--runs", type=int, default=10)
    p.add_argument("--no-backward", action="store_true")
    args = p.parse_args(argv)

    reg = _registry(args.shape_scale)
    names = [s for s in args.ops.split(",") if s] or sorted(reg)
    results = []
    for name in names:
        if name not in reg:
            print(f"opperf: unknown op {name!r}", file=sys.stderr)
            continue
        fn, maker = reg[name]
        row = {"op": name,
               "fwd_ms": round(_time_op(fn, maker(), args.warmup, args.runs), 4)}
        if not args.no_backward:
            try:
                row["fwd_bwd_ms"] = round(
                    _time_op(fn, maker(), args.warmup, args.runs,
                             backward=True), 4)
            except Exception as e:
                row["fwd_bwd_ms"] = None
                row["bwd_error"] = str(e)[:80]
        results.append(row)
        print(json.dumps(row))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write("| op | fwd (ms) | fwd+bwd (ms) |\n|---|---|---|\n")
            for r in results:
                f.write(f"| {r['op']} | {r['fwd_ms']} | "
                        f"{r.get('fwd_bwd_ms', '-')} |\n")
    return results


if __name__ == "__main__":
    main()
