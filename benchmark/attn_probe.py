"""Attention-core formulation probe at flagship shapes (B32 H16 T128 D64).

The bert_ablate.py noattn variant shows the attention core (scores +
softmax + PV, NOT the QKV/out projections) costs ~8 ms of the 70 ms
flagship step.  The product XLA path (`attention_reference`) upcasts
q/k/v to fp32 — fp32 einsums run the MXU at a fraction of the bf16
rate — and the model materializes (B,T,H,D)->(B,H,T,D) transposes.
This probe measures candidate formulations fwd+bwd, K iterations
chained in one jit (conv_probe methodology), with max|Δ| vs the fp32
oracle so wins can be adopted with eyes open:

  ref       product path today: transpose to (B,H,T,D), fp32 einsums
  bf16acc   (B,H,T,D) layout, bf16 einsum inputs + f32 accumulation
            (preferred_element_type) — exact for bf16-exact inputs
  bf16p     bf16acc + P cast to bf16 for the PV einsum (flash-kernel
            convention; rounds P at ~2^-9)
  notrans   bf16p formulated directly on (B,T,H,D) — no transposes
  pallas    the Pallas flash kernel forced on (below its crossover)
"""
import functools
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
from jax import lax

B, H, T, D = 32, 16, 128, 64
C = H * D
K = 96  # chained iterations per timed program (amortizes the ~50 ms
        # relay fetch below 0.6 ms/iter; the `null` row measures it)
REPS = 5
SCALE = 1.0 / math.sqrt(D)


def ref_core(qkv):
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * SCALE
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(qkv.dtype)
    return o.transpose(0, 2, 1, 3).reshape(B, T, C)


def bf16acc_core(qkv):
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * SCALE
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(qkv.dtype)
    return o.transpose(0, 2, 1, 3).reshape(B, T, C)


def bf16p_core(qkv):
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * SCALE
    p = jax.nn.softmax(s, axis=-1).astype(qkv.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v,
                   preferred_element_type=jnp.float32).astype(qkv.dtype)
    return o.transpose(0, 2, 1, 3).reshape(B, T, C)


def notrans_core(qkv):
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, D)
    k = k.reshape(B, T, H, D)
    v = v.reshape(B, T, H, D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * SCALE
    p = jax.nn.softmax(s, axis=-1).astype(qkv.dtype)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                   preferred_element_type=jnp.float32).astype(qkv.dtype)
    return o.reshape(B, T, C)


def pallas_core(qkv):
    import incubator_mxnet_tpu.ops.flash_attention  # noqa: F401 — module
    fa = sys.modules["incubator_mxnet_tpu.ops.flash_attention"]
    fa._PALLAS_FWD_MIN_SCORES = 0
    fa._PALLAS_BWD_MIN_SCORES = 0

    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    o = fa.flash_attention(q, k, v, causal=False, block_q=128, block_k=128)
    return o.transpose(0, 2, 1, 3).reshape(B, T, C)


def null_core(qkv):
    # dispatch/fetch overhead baseline: same signature, trivial compute
    return qkv[..., :C] * 1.0000001


CORES = {"null": null_core, "ref": ref_core, "bf16acc": bf16acc_core,
         "bf16p": bf16p_core, "notrans": notrans_core, "pallas": pallas_core}


def measure(name):
    core = CORES[name]

    def one(qkv, dy):
        # loss = <attend(qkv), dy> gives grad wrt qkv == full bwd pass
        out, vjp = jax.vjp(core, qkv)
        (dqkv,) = vjp(dy)
        return out, dqkv

    @jax.jit
    def chained(qkv, dy):
        def body(carry, _):
            q = carry
            out, dq = one(q, dy)
            # feed outputs forward so nothing is dead-code eliminated
            nq = jnp.concatenate([out, out, out], -1) * 1e-6 + q + dq * 1e-6
            return nq, ()

        final, _ = lax.scan(body, qkv, None, length=K)
        # scalar result: the relay's block_until_ready is unreliable, a
        # value fetch is the only true sync (bench.py methodology)
        return final.astype(jnp.float32).sum()

    key = jax.random.PRNGKey(0)
    qkv = jax.random.normal(key, (B, T, 3 * C), jnp.bfloat16)
    dy = jax.random.normal(jax.random.PRNGKey(1), (B, T, C), jnp.bfloat16)

    float(chained(qkv, dy))  # compile + warm
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(chained(qkv, dy))
        best = min(best, time.perf_counter() - t0)
    ms = best / K * 1e3

    # numerics vs the fp32 oracle (fwd only, single call)
    o = jax.jit(core)(qkv)
    o_ref = jax.jit(ref_core)(qkv)
    err = float(jnp.max(jnp.abs(o.astype(jnp.float32) - o_ref.astype(jnp.float32))))
    return ms, err


def main():
    names = sys.argv[1:] or list(CORES)
    print(f"B={B} H={H} T={T} D={D}  K={K} chained, per-layer fwd+bwd ms")
    overhead = 0.0
    base = None
    for n in names:
        ms, err = measure(n)
        if n == "null":
            overhead = ms
            print(f"{n:>8}: {ms:6.3f} ms/iter dispatch+fetch overhead",
                  flush=True)
            continue
        net = ms - overhead
        if base is None:
            base = net
        print(f"{n:>8}: {net:6.3f} ms/layer  x24={net*24:6.2f} ms  "
              f"maxerr={err:.2e}  vs ref {net/base*100:5.1f}%", flush=True)


if __name__ == "__main__":
    main()
