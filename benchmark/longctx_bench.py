"""Long-context training benchmark — single chip, flash-attention path.

SURVEY.md §5.7 makes long context a first-class capability; this
measures it END-TO-END through the public Gluon loop (same path as
bench.py): a decoder-only TransformerLM at T=32768 — 64x the
reference's fused-attention ceiling (T<=512, BASELINE.md) — trains on
ONE v5e chip because the Pallas flash kernels keep attention memory
O(T) and the streamed xent kernel never materializes the (B*T, 32k)
fp32 log-prob tensor.

    python benchmark/longctx_bench.py [T ...]   (default 2048 8192 32768)

Prints tok/s and MFU per config (attention FLOPs 12*L*T*D dominate at
long T, so MFU here exercises the flash kernels, not the matmuls).

The forward dispatches between a whole-KV-VMEM-resident kernel (below
~1 MB per K/V tensor — fastest) and a streamed-KV grid kernel beyond
it, so a single chip trains T=32k+; sequence sharding (ring attention,
docs/long_context.md §2) scales past a chip's HBM.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp

V, D, DFF, L, H = 32000, 1024, 4096, 12, 16
STEPS, WARMUP = 10, 2


def measure(T: int, B: int, dropout: float = 0.1):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.callback import device_peak_flops
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.gluon.block import HybridBlock
    from incubator_mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    from incubator_mxnet_tpu.models.transformer import TransformerLM
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)
    net = TransformerLM(vocab=V, units=D, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=T, dropout=dropout)
    net.initialize()
    # materialize deferred shapes with a SHORT sequence: the params are
    # still f32 here, and an f32 flash kernel at T=8192 exceeds VMEM
    net(NDArray(jnp.ones((B, 128), jnp.int32)))
    net.cast("bfloat16")

    class LMWithLoss(HybridBlock):
        def __init__(self, net_, **kw):
            super().__init__(**kw)
            self.net = net_
            self.loss = SoftmaxCrossEntropyLoss()

        def forward(self, tokens, labels):
            return self.loss(self.net(tokens), labels).mean()

    model = LMWithLoss(net)
    # beyond T=32k the saved-activation set (12 layers of (1, T, 4096)
    # bf16 FFN hiddens alone = T/32k * 6 GB) exceeds one chip's HBM:
    # rematerialize the forward inside the backward (docs/long_context.md
    # §3) — FLOPs for memory, the standard long-context trade
    model.hybridize(remat_backward=T > 32768)
    trainer = Trainer(model.collect_params(), "sgd",
                      {"learning_rate": 1e-3, "momentum": 0.9,
                       "multi_precision": True}, keep_grads=False)
    kx, ky = jax.random.split(jax.random.PRNGKey(0))
    tokens = NDArray(jax.random.randint(kx, (B, T), 0, V, dtype=jnp.int32))
    labels = NDArray(jax.random.randint(ky, (B, T), 0, V, dtype=jnp.int32))

    def step():
        with autograd.record():
            loss = model(tokens, labels)
        loss.backward()
        trainer.step(1)
        return loss

    for _ in range(WARMUP):
        loss = step()
    float(loss.asnumpy())
    t0 = time.perf_counter()
    for _ in range(STEPS):
        loss = step()
    final = float(loss.asnumpy())
    dt = time.perf_counter() - t0

    toks = B * T * STEPS / dt
    n_params = sum(p.data().size for p in net.collect_params().values()
                   if p.grad_req != "null")
    n_embed = V * D  # the output head is a real matmul, counted
    flops_per_token = 6 * (n_params - n_embed) + 12 * L * T * D
    mfu = toks * flops_per_token / device_peak_flops(jax.devices()[0])
    return toks, mfu, final, flops_per_token


def main():
    Ts = [int(a) for a in sys.argv[1:]] or [2048, 8192, 32768]
    print(f"TransformerLM V={V} D={D} L={L} H={H}, bf16 + fp32 masters, "
          f"dropout=0.1, public Gluon loop")
    for T in Ts:
        B = max(1, 16384 // T)
        toks, mfu, loss, fpt = measure(T, B)
        print(f"T={T:6d} B={B}: {toks:8.0f} tok/s  {mfu*100:5.2f}% MFU  "
              f"(attn share of FLOPs {12*L*T*D/fpt*100:.0f}%, "
              f"final_loss {loss:.3f})", flush=True)


if __name__ == "__main__":
    main()
