"""ResNet-50 train-step ablation (real TPU, scan-chained timing).

The r4 conv probe showed XLA's conv emitter runs at 90-128 TF/s fwd+bwd
on every ResNet-50 layer shape when measured without dispatch/compile
artifacts — so the ~31%-MFU train step is NOT conv-emitter-bound and
the r3 profile's conclusion was a timing artifact.  This script finds
where the step time actually goes by toggling components of a
hand-rolled ResNet-50:

    python benchmark/resnet_ablate.py full nobn norelu nomom nhwc ...

Variants: full (NCHW, BN, relu, momentum+fp32 masters)
          nhwc      same but NHWC layout end-to-end
          nobn      BatchNorm replaced by per-channel scale/shift (no
                    batch stats — isolates the reduction cost)
          norelu    no activations
          nomom     plain SGD, no momentum, no fp32 masters
          convonly  convs + residual adds only
          bnprod    r3 product BN formulation (bf16 stats)
          bn2stage / nhwc2stage  two-stage f32-acc stats
          bndot     BN stats as MXU dots (measured: much WORSE)
          s2d / s2dbndot  space-to-depth stem (nn_ops._stem_conv_s2d)
All variants: BS128 bf16, 8 steps chained in one jit via lax.scan.
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
from jax import lax

B = int(os.environ.get("ABLATE_BS", "128"))
K = 8
REPS = 3

# ResNet-50: stages (blocks, mid_channels, out_channels, stride)
STAGES = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
          (3, 512, 2048, 2)]


def conv(x, w, stride, pad, nhwc):
    dn = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=[(pad, pad)] * 2,
        dimension_numbers=dn)


BN_MODE = "f32"  # f32 | prod | 2stage | dot — set per variant


def bn(x, gamma, beta, nhwc, use_bn):
    caxes = (0, 1, 2) if nhwc else (0, 2, 3)
    shape = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
    if not use_bn:
        return x * gamma.reshape(shape).astype(x.dtype) \
            + beta.reshape(shape).astype(x.dtype)
    if BN_MODE == "2stage":
        if nhwc:
            xr = x.reshape(-1, x.shape[-1])
            s = jnp.sum(xr, 0, dtype=jnp.float32)
            q = jnp.sum(xr * xr, 0, dtype=jnp.float32)
        else:
            xr = x.reshape(x.shape[0], x.shape[1], -1)
            s = jnp.sum(jnp.sum(xr, 2, dtype=jnp.float32), 0)
            q = jnp.sum(jnp.sum(xr * xr, 2, dtype=jnp.float32), 0)
        cnt = x.size // gamma.size
        mean = s / cnt
        var = jnp.maximum(q / cnt - jnp.square(mean), 0.0)
        inv = jax.lax.rsqrt(var + 1e-5) * gamma
        shift = beta - mean * inv
        return x * inv.astype(x.dtype).reshape(shape) \
            + shift.astype(x.dtype).reshape(shape)
    if BN_MODE == "dot":
        # stats as MXU dots: row-sums consume the conv's layout (probe
        # for the layout-copy overhead seen in the compiled HLO)
        if nhwc:
            xr = x.reshape(-1, x.shape[-1])
            ones = jnp.ones((xr.shape[0],), x.dtype)
            s = jnp.einsum("rc,r->c", xr, ones,
                           preferred_element_type=jnp.float32)
            q = jnp.einsum("rc,rc,r->c", xr, xr, ones,
                           preferred_element_type=jnp.float32)
        else:
            xr = x.reshape(x.shape[0], x.shape[1], -1)
            ones = jnp.ones((xr.shape[2],), x.dtype)
            s = jnp.sum(jnp.einsum("ncs,s->nc", xr, ones,
                                   preferred_element_type=jnp.float32), 0)
            q = jnp.sum(jnp.einsum("ncs,ncs,s->nc", xr, xr, ones,
                                   preferred_element_type=jnp.float32), 0)
        cnt = x.size // gamma.size
        mean = s / cnt
        var = jnp.maximum(q / cnt - jnp.square(mean), 0.0)
        inv = jax.lax.rsqrt(var + 1e-5) * gamma
        shift = beta - mean * inv
        return x * inv.astype(x.dtype).reshape(shape) \
            + shift.astype(x.dtype).reshape(shape)
    if BN_MODE == "prod":  # r3 product formulation (bf16 stats)
        mean = jnp.mean(x, caxes)
        var = jnp.mean(jnp.square(x), caxes) - jnp.square(mean)
        inv = jax.lax.rsqrt(var.astype(jnp.float32) + 1e-5).astype(x.dtype)
        return (x - mean.reshape(shape)) \
            * (gamma.astype(x.dtype) * inv).reshape(shape) \
            + beta.astype(x.dtype).reshape(shape)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, caxes)
    var = jnp.mean(jnp.square(xf), caxes) - jnp.square(mean)
    inv = jax.lax.rsqrt(var + 1e-5) * gamma
    out = xf * inv.reshape(shape) + (beta - mean * inv).reshape(shape)
    return out.astype(x.dtype)


def init_params(nhwc, key):
    """(convs, gammas, betas) for the full net."""
    ks = iter(jax.random.split(key, 200))

    def cw(kh, kw, ci, co):
        w = jax.random.normal(next(ks), (co, ci, kh, kw), jnp.bfloat16) * 0.05
        return jnp.transpose(w, (2, 3, 1, 0)) if nhwc else w

    convs, gammas, betas = [], [], []

    def add_bn(c):
        gammas.append(jnp.ones((c,), jnp.float32))
        betas.append(jnp.zeros((c,), jnp.float32))

    convs.append(cw(7, 7, 3, 64)); add_bn(64)
    cin = 64
    for (blocks, mid, cout, stride) in STAGES:
        for b in range(blocks):
            s = stride if b == 0 else 1
            convs.append(cw(1, 1, cin, mid)); add_bn(mid)
            convs.append(cw(3, 3, mid, mid)); add_bn(mid)
            convs.append(cw(1, 1, mid, cout)); add_bn(cout)
            if b == 0:
                convs.append(cw(1, 1, cin, cout)); add_bn(cout)  # downsample
            cin = cout
    return convs, gammas, betas


USE_S2D = False  # space-to-depth stem (MLPerf trick): 7x7 s2 -> 4x4 s1

# the PRODUCT transform — one source of the (ky, r) -> dy mapping
from incubator_mxnet_tpu.ndarray.nn_ops import _stem_conv_s2d as stem_s2d  # noqa: E402


def forward(convs, gammas, betas, x, nhwc, use_bn, use_relu):
    it = iter(range(len(convs)))

    def cbr(x, stride, pad, relu=True):
        i = next(it)
        if i == 0 and USE_S2D and not nhwc:
            y = stem_s2d(x, convs[i])
        else:
            y = conv(x, convs[i], stride, pad, nhwc)
        y = bn(y, gammas[i], betas[i], nhwc, use_bn)
        if use_relu and relu:
            y = jax.nn.relu(y)
        return y

    y = cbr(x, 2, 3)
    # 3x3 s2 maxpool
    if nhwc:
        y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), [(0, 0), (1, 1), (1, 1), (0, 0)])
    else:
        y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 1, 3, 3),
                              (1, 1, 2, 2), [(0, 0), (0, 0), (1, 1), (1, 1)])
    for (blocks, mid, cout, stride) in STAGES:
        for b in range(blocks):
            s = stride if b == 0 else 1
            r = cbr(y, s, 0)          # 1x1 (stride, mxnet v1 style)
            r = cbr(r, 1, 1)          # 3x3
            r = cbr(r, 1, 0, relu=False)  # 1x1 expand
            sc = cbr(y, s, 0, relu=False) if b == 0 else y  # downsample
            y = r + sc
            if use_relu:
                y = jax.nn.relu(y)
    y = jnp.mean(y.astype(jnp.float32), (1, 2) if nhwc else (2, 3))
    return y  # (B, 2048) pooled features; head below


def build_step(nhwc, use_bn, use_relu, momentum, head_w):
    def loss_of(convs, gammas, betas, x, y_lab):
        feats = forward(convs, gammas, betas, x, nhwc, use_bn, use_relu)
        logits = feats @ head_w
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y_lab[:, None], 1))

    def step(carry, _):
        params, moms, x, y_lab = carry
        (convs_m, gammas, betas) = params
        convs = tuple(w.astype(jnp.bfloat16) for w in convs_m)
        L, grads = jax.value_and_grad(
            lambda c: loss_of(c, gammas, betas, x, y_lab))(convs)
        if momentum:
            nmoms = tuple(0.9 * v + g.astype(jnp.float32)
                          for v, g in zip(moms, grads))
            nconvs = tuple(m - 0.1 * v for m, v in zip(convs_m, nmoms))
        else:
            nmoms = moms
            nconvs = tuple(m - 0.1 * g.astype(m.dtype)
                           for m, g in zip(convs_m, grads))
        return ((nconvs, gammas, betas), nmoms, x, y_lab), L

    return step


def run_variant(name):
    global BN_MODE, USE_S2D
    nhwc = name in ("nhwc", "nhwc2stage")
    use_bn = name not in ("nobn", "convonly")
    use_relu = name not in ("norelu", "convonly")
    momentum = name not in ("nomom",)
    USE_S2D = "s2d" in name
    BN_MODE = "2stage" if "2stage" in name else (
        "prod" if name == "bnprod" else
        "dot" if "bndot" in name else "f32")
    key = jax.random.PRNGKey(0)
    convs, gammas, betas = init_params(nhwc, key)
    convs_m = tuple(w.astype(jnp.float32) for w in convs)
    moms = tuple(jnp.zeros_like(m) for m in convs_m)
    head_w = jax.random.normal(key, (2048, 1000), jnp.float32) * 0.01
    shape = (B, 224, 224, 3) if nhwc else (B, 3, 224, 224)
    x = jnp.ones(shape, jnp.bfloat16)
    y_lab = jnp.zeros((B,), jnp.int32)

    step = build_step(nhwc, use_bn, use_relu, momentum, head_w)

    @jax.jit
    def multi(convs_m, moms, x, y_lab):
        carry = ((convs_m, gammas, betas), moms, x, y_lab)
        carry, Ls = lax.scan(step, carry, None, length=K)
        return carry[0][0][0][0], Ls[-1]

    out = multi(convs_m, moms, x, y_lab)
    float(jnp.asarray(out[-1]))  # compile+warm
    import contextlib
    trace_dir = os.environ.get("RESNET_TRACE_DIR")
    ctx = (jax.profiler.trace(trace_dir) if trace_dir
           else contextlib.nullcontext())  # timed region only: tracing
    # the compile overflows the 2 GB XSpace protobuf cap
    with ctx:
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = multi(convs_m, moms, x, y_lab)
        float(jnp.asarray(out[-1]))
        dt = (time.perf_counter() - t0) / (REPS * K)
    print(f"  {name:9s} {B/dt:7.0f} img/s   ({dt*1e3:.1f} ms/step)",
          flush=True)


def main():
    which = sys.argv[1:] or ["full", "nhwc", "nobn", "norelu", "nomom",
                             "convonly"]
    print(f"devices: {jax.devices()}  BS{B} bf16 scan K={K}")
    for w in which:
        run_variant(w)


if __name__ == "__main__":
    main()
