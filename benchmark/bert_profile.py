"""Per-HLO-op device profile of the flagship BERT train step.

The harness behind the r5 mask-split dropout decision
(docs/performance.md): run the EXACT bench.py configuration through the
public Gluon path, trace 8 steady-state steps with `mx.profiler`, and
print the per-op table + category rollup.  Compare dropout on/off:

    python benchmark/bert_profile.py 0.1
    python benchmark/bert_profile.py 0.0

The dropout A/B is read from the CATEGORY deltas (the per-op rows are
dominated by async copy-starts whose durations include dependency
waits, not transfer time — only `copy-done` entries are real stalls).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp

V, D, DFF, L, H, B, T = 30522, 1024, 4096, 24, 16, 32, 128


def main():
    dropout = float(sys.argv[1]) if len(sys.argv) > 1 else 0.1

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.gluon.block import HybridBlock
    from incubator_mxnet_tpu.models import bert
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    class PretrainWithLoss(HybridBlock):
        def __init__(self, net_, **kw):
            super().__init__(**kw)
            self.net = net_
            self.mlm_loss = mx.gluon.loss.SoftmaxCrossEntropyLoss()

        def forward(self, tokens, labels):
            mlm_logits, nsp_logits = self.net(tokens)
            mlm = self.mlm_loss(mlm_logits, labels).mean()
            nsp_logp = mx.nd.log_softmax(nsp_logits.astype("float32"))
            return mlm - nsp_logp[:, 0].mean()

    mx.random.seed(0)
    net = bert.BERTForPretraining(vocab_size=V, units=D, hidden_size=DFF,
                                  num_layers=L, num_heads=H, dropout=dropout)
    net.initialize()
    net(NDArray(jnp.ones((B, T), jnp.int32)))
    net.cast("bfloat16")
    model = PretrainWithLoss(net)
    model.hybridize()
    trainer = Trainer(model.collect_params(), "sgd",
                      {"learning_rate": 1e-3, "momentum": 0.9,
                       "multi_precision": True}, keep_grads=False)
    kx, ky = jax.random.split(jax.random.PRNGKey(0))
    tokens = NDArray(jax.random.randint(kx, (B, T), 0, V, dtype=jnp.int32))
    labels = NDArray(jax.random.randint(ky, (B, T), 0, V, dtype=jnp.int32))

    def step():
        with autograd.record():
            loss = model(tokens, labels)
        loss.backward()
        trainer.step(1)
        return loss

    for _ in range(5):
        loss = step()
    float(loss.asnumpy())

    mx.profiler.start()
    for _ in range(8):
        loss = step()
    float(loss.asnumpy())
    mx.profiler.stop()
    print(f"=== dropout={dropout} per-op table (8 steps) ===")
    print(mx.profiler.device_op_table(top=25))
    print("=== category rollup ===")
    for row in mx.profiler.device_op_summary():
        print(f"  {row['category']:<28} {row['total_us']/8000:8.2f} ms/step "
              f"x{row['occurrences'] // 8}")


if __name__ == "__main__":
    main()
