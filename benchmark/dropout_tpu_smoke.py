"""Live-TPU smoke for the fused dropout kernel path.

The pytest suite pins jax to CPU (conftest), where fused_dropout takes
the block-keyed threefry reference — so the Mosaic kernel itself (seed
arity, tile legality across geometries, fwd/bwd identity on hardware)
must be validated here, on the real chip.  Run from the repo root:

    python benchmark/dropout_tpu_smoke.py

Exercises every geometry class _pick_br can produce: large aligned
(R>=64*br), mid (8 blocks), single-block fallback (odd R), ragged last
dim (col padding), 3D activations, and bf16.

KNOWN GAP: the relay exposes ONE chip, so the PARTITIONED kernel
lowering (axis_index-derived tile offsets feeding prng_seed under a
real multi-device mesh) cannot be executed here — the 8-device CPU
mesh tests cover the partitioning structure via the threefry branch,
and this script covers the Mosaic kernel single-device.  If a
multi-chip TPU ever becomes available, add a sharded case here first.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as onp

from incubator_mxnet_tpu.ops import dropout_kernel as dk

SEED = jnp.array([7], jnp.int32)

SHAPES = [
    ((4096, 1024), jnp.float32),
    ((64, 256), jnp.float32),
    ((256, 512), jnp.float32),
    ((8, 256), jnp.float32),
    ((5, 77), jnp.float32),      # ragged: col pad + single row block
    ((16, 128), jnp.bfloat16),
    ((32, 512, 1024), jnp.bfloat16),   # (B, T, D) flagship activation
    ((384,), jnp.float32),       # 1D
]


def main():
    assert dk._kernel_backend(), (
        f"not a TPU backend: {jax.default_backend()} — run under the relay")
    rate = 0.3
    for shape, dt in SHAPES:
        # strictly positive so (y != 0) recovers the mask exactly (an x
        # that rounds to 0 in bf16 would fake a dropped element)
        x = (jnp.abs(jax.random.normal(
            jax.random.PRNGKey(1), shape, jnp.float32)) + 1.0).astype(dt)
        y = jax.jit(lambda x: dk.fused_dropout(x, SEED, rate))(x)
        g = jax.jit(jax.grad(
            lambda x: dk.fused_dropout(x, SEED, rate)
            .astype(jnp.float32).sum()))(x.astype(jnp.float32))
        yv = onp.asarray(y.astype(jnp.float32))
        gv = onp.asarray(g)
        keep = (yv != 0).mean()
        assert abs(keep - (1 - rate)) < 0.05, (shape, keep)
        # fwd/bwd identity needs SAME dtype runs (geometry depends on
        # itemsize); re-run fwd in f32 for the comparison
        yf = onp.asarray(jax.jit(
            lambda x: dk.fused_dropout(x, SEED, rate))(
                x.astype(jnp.float32)))
        onp.testing.assert_array_equal(yf != 0, gv != 0)
        # determinism
        y2 = onp.asarray(jax.jit(
            lambda x: dk.fused_dropout(x, SEED, rate))(x)
            .astype(jnp.float32))
        onp.testing.assert_array_equal(yv, y2)
        # execution blocking must NOT change the bits: the mask is a
        # function of the (br, bc) MASK grid only — force kr=kc=1 and
        # compare bitwise
        budget = dk._EXEC_BUDGET_BYTES
        try:
            dk._EXEC_BUDGET_BYTES = 1  # forces kr=kc=1
            y1 = onp.asarray(jax.jit(
                lambda x: dk.fused_dropout(x, SEED, rate))(x)
                .astype(jnp.float32))
        finally:
            dk._EXEC_BUDGET_BYTES = budget
        onp.testing.assert_array_equal(yv, y1)
        print(f"  OK {str(shape):18s} {jnp.dtype(dt).name:9s} keep={keep:.3f}")
    bandwidth()
    print("TPU DROPOUT SMOKE PASS")


def bandwidth():
    """Effective GB/s at the flagship site shape under the r5
    mask-split traffic model (mask write+read at 1 B/elem + apply's
    x read / y write).  History: the r4 apply-in-kernel op measured
    ~200 GB/s before execution blocking and >1100 GB/s after, on a
    2*itemsize model — not directly comparable to this number."""
    import time

    from jax import lax

    x = jnp.abs(jax.random.normal(
        jax.random.PRNGKey(2), (4096, 1024), jnp.float32)).astype(jnp.bfloat16) + 1
    K = 100

    @jax.jit
    def chained(x):
        def body(c, _):
            # pure chain — no extra elementwise pass pollutes the number
            # (kept elements grow 1.111x/iter; 1.111^100 ~ 3.8e4, fine)
            return dk.fused_dropout(c, SEED, 0.1), ()

        out, _ = lax.scan(body, x, None, length=K)
        return out.astype(jnp.float32).sum()

    @jax.jit
    def null(x):
        return (x * jnp.asarray(1.0000001, x.dtype)).astype(jnp.float32).sum()

    def best(f):
        float(f(x))  # compile + warm
        b = float("inf")
        for _ in range(7):
            t0 = time.perf_counter()
            float(f(x))
            b = min(b, time.perf_counter() - t0)
        return b

    per_call = (best(chained) - best(null)) / K
    # r5 mask-split traffic per call: mask write + mask read (1 B/elem
    # each) + the XLA apply's x read and y write.  (Pre-r5
    # apply-in-kernel was 2*itemsize; the old ~200 GB/s r4 gate number
    # is not directly comparable.)
    traffic = x.size * (2 + 2 * x.dtype.itemsize)
    print(f"  flagship-site fused_dropout (mask+apply): "
          f"{per_call*1e6:.1f} us/call, "
          f"{traffic/per_call/1e9:.0f} GB/s effective")


if __name__ == "__main__":
    main()
