"""Live-TPU smoke for the fused dropout kernel path.

The pytest suite pins jax to CPU (conftest), where fused_dropout takes
the block-keyed threefry reference — so the Mosaic kernel itself (seed
arity, tile legality across geometries, fwd/bwd identity on hardware)
must be validated here, on the real chip.  Run from the repo root:

    python benchmark/dropout_tpu_smoke.py

Exercises every geometry class _pick_br can produce: large aligned
(R>=64*br), mid (8 blocks), single-block fallback (odd R), ragged last
dim (col padding), 3D activations, and bf16.

KNOWN GAP: the relay exposes ONE chip, so the PARTITIONED kernel
lowering (axis_index-derived tile offsets feeding prng_seed under a
real multi-device mesh) cannot be executed here — the 8-device CPU
mesh tests cover the partitioning structure via the threefry branch,
and this script covers the Mosaic kernel single-device.  If a
multi-chip TPU ever becomes available, add a sharded case here first.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as onp

from incubator_mxnet_tpu.ops import dropout_kernel as dk

SEED = jnp.array([7], jnp.int32)

SHAPES = [
    ((4096, 1024), jnp.float32),
    ((64, 256), jnp.float32),
    ((256, 512), jnp.float32),
    ((8, 256), jnp.float32),
    ((5, 77), jnp.float32),      # ragged: col pad + single row block
    ((16, 128), jnp.bfloat16),
    ((32, 512, 1024), jnp.bfloat16),   # (B, T, D) flagship activation
    ((384,), jnp.float32),       # 1D
]


def main():
    assert dk._kernel_backend(), (
        f"not a TPU backend: {jax.default_backend()} — run under the relay")
    rate = 0.3
    for shape, dt in SHAPES:
        # strictly positive so (y != 0) recovers the mask exactly (an x
        # that rounds to 0 in bf16 would fake a dropped element)
        x = (jnp.abs(jax.random.normal(
            jax.random.PRNGKey(1), shape, jnp.float32)) + 1.0).astype(dt)
        y = jax.jit(lambda x: dk.fused_dropout(x, SEED, rate))(x)
        g = jax.jit(jax.grad(
            lambda x: dk.fused_dropout(x, SEED, rate)
            .astype(jnp.float32).sum()))(x.astype(jnp.float32))
        yv = onp.asarray(y.astype(jnp.float32))
        gv = onp.asarray(g)
        keep = (yv != 0).mean()
        assert abs(keep - (1 - rate)) < 0.05, (shape, keep)
        # fwd/bwd identity needs SAME dtype runs (geometry depends on
        # itemsize); re-run fwd in f32 for the comparison
        yf = onp.asarray(jax.jit(
            lambda x: dk.fused_dropout(x, SEED, rate))(
                x.astype(jnp.float32)))
        onp.testing.assert_array_equal(yf != 0, gv != 0)
        # determinism
        y2 = onp.asarray(jax.jit(
            lambda x: dk.fused_dropout(x, SEED, rate))(x)
            .astype(jnp.float32))
        onp.testing.assert_array_equal(yv, y2)
        print(f"  OK {str(shape):18s} {jnp.dtype(dt).name:9s} keep={keep:.3f}")
    print("TPU DROPOUT SMOKE PASS")


if __name__ == "__main__":
    main()
