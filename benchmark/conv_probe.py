"""Per-shape conv strategy probe (ResNet-50 shapes, real TPU).

Compares, for each profiled ResNet-50 layer shape, the achieved TF/s of:
  conv_nchw   lax.conv_general_dilated, NCHW (current Convolution path)
  conv_nhwc   lax.conv_general_dilated, NHWC
  tap_nhwc    sum over k*k taps of (N*Ho*Wo, C) @ (C, O) matmuls on a
              padded NHWC input (implicit im2col — no patch matrix ever
              materializes; XLA differentiates each tap matmul into
              matmuls, so fwd AND bwd ride the MXU matmul emitter)
  im2col_nhwc concat the taps into (N,Ho,Wo,k*k*C) then ONE matmul

Methodology: the relay adds ~5-15 ms fixed overhead per dispatched
program, so K iterations are CHAINED inside one jit via lax.scan
(output feeds back as input where shapes allow; otherwise the weight is
perturbed by sum(y)*1e-30 to defeat CSE) and the whole program is timed
once warm.  FLOPs = 2*N*Ho*Wo*O*C*k*k (fwd), 3x for fwd+bwd.
"""
import functools
import time

import jax
import jax.numpy as jnp
from jax import lax

K_FWD = 64   # chained iterations per fwd program
K_GRAD = 16  # grad chains keep K small: each iteration's residuals
             # live until its backward runs (~50 MB x K at C64 H56)

SHAPES = [
    # (name, N, C, H, O, k, stride)  square-channel shapes chain y->x
    ("3x3_C64_H56", 128, 64, 56, 64, 3, 1),
    ("3x3_C128_H28", 128, 128, 28, 128, 3, 1),
    ("3x3_C256_H14", 128, 256, 14, 256, 3, 1),
    ("3x3_C512_H7", 128, 512, 7, 512, 3, 1),
    ("1x1_C64_O256_H56", 128, 64, 56, 256, 1, 1),
    ("1x1_C1024_O256_H14", 128, 1024, 14, 256, 1, 1),
    ("7x7_C3_H224_s2", 128, 3, 224, 64, 7, 2),
]


def conv_xla(x, w, stride, pad, spec):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=[(pad, pad)] * 2,
        dimension_numbers=spec)


def tap_conv_nhwc(x, w, stride, pad):
    """x (N,H,W,C); w (k,k,C,O). Implicit-im2col tap matmuls."""
    k = w.shape[0]
    if pad:
        x = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    H = x.shape[1]
    Ho = (H - k) // stride + 1
    acc = None
    for dy in range(k):
        for dx in range(k):
            xs = x[:, dy:dy + stride * (Ho - 1) + 1:stride,
                   dx:dx + stride * (Ho - 1) + 1:stride, :]
            t = jnp.dot(xs, w[dy, dx])
            acc = t if acc is None else acc + t
    return acc


def im2col_conv_nhwc(x, w, stride, pad):
    k = w.shape[0]
    if pad:
        x = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    H = x.shape[1]
    Ho = (H - k) // stride + 1
    cols = [x[:, dy:dy + stride * (Ho - 1) + 1:stride,
              dx:dx + stride * (Ho - 1) + 1:stride, :]
            for dy in range(k) for dx in range(k)]
    patches = jnp.concatenate(cols, axis=-1)
    return jnp.dot(patches, w.reshape(-1, w.shape[-1]))


def chain_fwd(f, same_shape, k):
    """k conv calls in ONE program."""
    if same_shape:
        def run(x, w):
            def body(c, _):
                return f(c, w), ()
            y, _ = lax.scan(body, x, None, length=k)
            return y
    else:
        def run(x, w):
            def body(w, _):
                y = f(x, w)
                # defeat CSE/DCE: fold a negligible function of y into w
                return w + (jnp.sum(y) * 1e-30).astype(w.dtype), ()
            w, _ = lax.scan(body, w, None, length=k)
            return w
    return run


def chain_grad(f, same_shape, k):
    def loss(x, w):
        if same_shape:
            def body(c, _):
                return f(c, w), ()
            y, _ = lax.scan(body, x, None, length=k)
            return jnp.sum(y.astype(jnp.float32))
        else:
            def body(c, _):
                y = f(x, w + c)
                return (jnp.sum(y) * 1e-30).astype(w.dtype), ()
            c, _ = lax.scan(body, jnp.zeros((), w.dtype), None, length=k)
            return jnp.sum(c.astype(jnp.float32))
    return jax.grad(loss, argnums=(0, 1))


def scalarized(fn):
    """Reduce the chain output to ONE scalar INSIDE the jit, so timing
    needs exactly one cheap host fetch (a fresh jnp.sum on the host
    side would compile a new program inside the timed region)."""
    def g(*args):
        out = fn(*args)
        return functools.reduce(
            jnp.add, [jnp.sum(l.astype(jnp.float32))
                      for l in jax.tree_util.tree_leaves(out)])
    return jax.jit(g)


def timeone(jfn, args, k, reps):
    """reps dispatches of a k-iteration chained program, ONE fetch at
    the end: the 40-80ms relay fetch amortizes over reps*k iterations
    (aim >= several hundred ms of real work so shared-chip noise stays
    below ~10%)."""
    float(jfn(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        y = jfn(*args)
    float(y)
    return (time.perf_counter() - t0) / (reps * k)


def main():
    key = jax.random.PRNGKey(0)
    print(f"devices: {jax.devices()}")
    for name, N, C, H, O, k, s in SHAPES:
        pad = (k - 1) // 2
        Ho = (H + 2 * pad - k) // s + 1
        flops_fwd = 2 * N * Ho * Ho * O * C * k * k
        same = (C == O and s == 1)
        x_nchw = jax.random.normal(key, (N, C, H, H), jnp.bfloat16) * 0.1
        x_nhwc = jnp.transpose(x_nchw, (0, 2, 3, 1))
        w_oikk = jax.random.normal(key, (O, C, k, k), jnp.bfloat16) * 0.05
        w_kkco = jnp.transpose(w_oikk, (2, 3, 1, 0))

        cands = {
            "conv_nchw": (lambda x, w: conv_xla(
                x, w, s, pad, ("NCHW", "OIHW", "NCHW")), x_nchw, w_oikk),
            "conv_nhwc": (lambda x, w: conv_xla(
                x, w, s, pad, ("NHWC", "HWIO", "NHWC")), x_nhwc, w_kkco),
            "tap_nhwc": (lambda x, w: tap_conv_nhwc(x, w, s, pad),
                         x_nhwc, w_kkco),
            "im2col_nhwc": (lambda x, w: im2col_conv_nhwc(x, w, s, pad),
                            x_nhwc, w_kkco),
        }
        print(f"\n== {name} (fwd {flops_fwd/1e9:.1f} GFLOP, "
              f"chain={'y->x' if same else 'w-perturb'}) ==", flush=True)
        for cname, (f, xx, ww) in cands.items():
            try:
                t = timeone(scalarized(chain_fwd(f, same, K_FWD)), (xx, ww), K_FWD, 12)
                tg = timeone(scalarized(chain_grad(f, same, K_GRAD)), (xx, ww), K_GRAD, 24)
                print(f"  {cname:12s} fwd {flops_fwd/t/1e12:7.1f} TF/s"
                      f"   fwd+bwd {3*flops_fwd/tg/1e12:7.1f} TF/s",
                      flush=True)
            except Exception as e:
                print(f"  {cname:12s} FAILED: {type(e).__name__}: {e}",
                      flush=True)


if __name__ == "__main__":
    main()
