"""Per-kernel flash-attention device profile at long T (r4 VERDICT #2).

Times each Pallas kernel (fwd resident/streamed, bwd dK/dV, bwd dQ) in
isolation with the chained-scan methodology (K invocations inside one
jit, one value fetch) and reports achieved TF/s against the causal
attention FLOPs each kernel actually performs:

    fwd:    2·B·H·T²·D  (QKᵀ + PV, ×½ causal)
    dK/dV:  4·B·H·T²·D  (S, dP, dV, dK dots, ×½ causal)
    dQ:     3·B·H·T²·D  (S, dP, dS·K dots, ×½ causal)

Run ON THE TPU, one T per process (HBM fragmentation accumulates):

    python benchmark/flash_profile.py 8192
    python benchmark/flash_profile.py 16384 32768
"""
import functools
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
from jax import lax

H, D = 16, 64
REPS, K = 3, 32


def _time_chained(fn, args, flops, program=None):
    """K invocations chained in one jit; fetch once.  Returns (ms, tfs).

    The body DEPENDS on the scan carry (q is perturbed by a zero that
    XLA cannot prove zero-valued at trace time), so the kernel cannot
    be hoisted out of the loop; K=32 amortizes the ~50–90 ms relay
    d2h fetch to ~2 ms which the null variant subtracts.

    With telemetry enabled and a `program` name, the chained program's
    cost/memory analysis and best measured wall land in the
    telemetry.perf roofline attribution (tools/roofline_report.py's
    table format; one scan-body execution per the XLA cost model)."""

    @jax.jit
    def multi(*a):
        def body(c, _):
            perturbed = (a[0] + c.astype(a[0].dtype),) + tuple(a[1:])
            out = fn(*perturbed)[0]
            return out[0, 0, 0, 0].astype(jnp.float32) * 0.0, ()

        c, _ys = lax.scan(body, jnp.float32(0.0), None, length=K)
        return c

    @jax.jit
    def null(*a):  # same fetch + loop skeleton, no kernel
        def body(c, _):
            return c * 1.0000001, ()

        c, _ys = lax.scan(body, jnp.float32(0.0), None, length=K)
        return c + a[0][0, 0, 0, 0].astype(jnp.float32) * 0

    float(multi(*args))
    float(null(*args))
    t_null = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(null(*args))
        t_null = min(t_null, time.perf_counter() - t0)
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(multi(*args))
        best = min(best, (time.perf_counter() - t0 - t_null) / K)
    best = max(best, 1e-6)  # fetch jitter must never yield <=0
    if program is not None:
        from incubator_mxnet_tpu import telemetry

        if telemetry.enabled():
            telemetry.perf.capture(program, multi, *args)
            telemetry.perf.note_timing(program, best)
    return best * 1e3, flops / best / 1e12


def main():
    import importlib

    fa = importlib.import_module("incubator_mxnet_tpu.ops.flash_attention")

    Ts = [int(a) for a in sys.argv[1:]] or [8192]
    for T in Ts:
        B = max(1, 2 * 8192 // T)
        scale = 1.0 / math.sqrt(D)
        key = jax.random.PRNGKey(0)
        q, k, v, do = (jax.random.normal(jax.random.fold_in(key, i),
                                         (B, H, T, D), jnp.bfloat16)
                       for i in range(4))
        causal_flops = B * H * T * T * D  # 2·T²·D·BH × ½ causal

        bq = fa._auto_block(T, None)
        resident = T * D * 2 <= fa._KV_RESIDENT_MAX_BYTES
        fwd = functools.partial(fa._flash_core, causal=True, scale=scale,
                                block_q=bq, block_k=bq, interpret=False)
        ms, tfs = _time_chained(lambda a, b, c: fwd(a, b, c),
                                (q, k, v), 2 * causal_flops,
                                program=f"flash_fwd_T{T}")
        print(f"T={T} B={B} fwd[{'resident' if resident else 'streamed'}] "
              f"bq=bk={bq}: {ms:.2f} ms  {tfs:.1f} TF/s", flush=True)

        out, lse = fwd(q, k, v)
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)
        bqb = max(bq, 512)

        def bwd(qq, kk, vv, dd):
            return fa._flash_bwd_core(qq, kk, vv, dd, lse, delta,
                                      causal=True, scale=scale, block_q=bqb,
                                      block_k=bqb, interpret=False)

        ms, tfs = _time_chained(lambda a, b, c, d: (bwd(a, b, c, d)[1],),
                                (q, k, v, do), 7 * causal_flops,
                                program=f"flash_bwd_T{T}")
        print(f"T={T} B={B} bwd[dkdv+dq] bq=bk={bqb}: {ms:.2f} ms  "
              f"{tfs:.1f} TF/s (combined)", flush=True)


if __name__ == "__main__":
    main()
