"""Operator matrix sweep (VERDICT r2 #7): every exported elementwise/
binary/reduction/shape op × ≥2 shapes × ≥2 dtypes, with NumPy oracles
and finite-difference gradient checks for the differentiable families —
the density of the reference's `tests/python/unittest/test_operator.py`
matrices, organized declaratively.

Tolerance tiers: fp32 sweeps assert the default fp32 tolerances; bf16
sweeps use the bf16 tier (~1e-2) via `assert_almost_equal`'s
dtype-aware defaults.  Degenerate cases (zero-size arrays, size-1 dims,
negative axes) are part of the shape matrix.
"""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.test_utils import (assert_almost_equal,
                                            check_numeric_gradient)

RS = onp.random.RandomState(7)

SHAPES = [(3, 4), (2, 3, 4), (1, 5), (6,)]
DEGENERATE = [(0, 3), (2, 0), (1, 1, 1)]
DTYPES = ["float32", "bfloat16"]


def _data(shape, dtype, domain):
    x = RS.uniform(-2.0, 2.0, size=shape).astype("float32")
    if domain == "positive":
        x = onp.abs(x) + 0.5
    elif domain == "unit":
        x = onp.clip(x * 0.4, -0.9, 0.9)
    elif domain == "ge1":
        x = onp.abs(x) + 1.5
    elif domain == "nonzero":
        x = onp.where(onp.abs(x) < 0.3, 0.5, x)
    return x.astype(dtype)


# (op name, numpy oracle, domain, differentiable)
UNARY = [
    ("abs", onp.abs, "nonzero", True),
    ("negative", lambda x: -x, "any", True),
    ("exp", onp.exp, "any", True),
    ("expm1", onp.expm1, "any", True),
    ("log", onp.log, "positive", True),
    ("log1p", onp.log1p, "positive", True),
    ("log2", onp.log2, "positive", True),
    ("log10", onp.log10, "positive", True),
    ("sqrt", onp.sqrt, "positive", True),
    ("rsqrt", lambda x: 1.0 / onp.sqrt(x), "positive", True),
    ("cbrt", onp.cbrt, "positive", True),
    ("rcbrt", lambda x: 1.0 / onp.cbrt(x), "positive", True),
    ("reciprocal", lambda x: 1.0 / x, "nonzero", True),
    ("square", onp.square, "any", True),
    ("sign", onp.sign, "nonzero", False),
    ("floor", onp.floor, "nonzero", False),
    ("ceil", onp.ceil, "nonzero", False),
    ("trunc", onp.trunc, "nonzero", False),
    ("rint", onp.rint, "nonzero", False),
    ("round", onp.round, "nonzero", False),
    ("sin", onp.sin, "any", True),
    ("cos", onp.cos, "any", True),
    ("tan", onp.tan, "unit", True),
    ("sinh", onp.sinh, "any", True),
    ("cosh", onp.cosh, "any", True),
    ("tanh", onp.tanh, "any", True),
    ("arcsin", onp.arcsin, "unit", True),
    ("arccos", onp.arccos, "unit", True),
    ("arctan", onp.arctan, "any", True),
    ("arcsinh", onp.arcsinh, "any", True),
    ("arccosh", onp.arccosh, "ge1", True),
    ("arctanh", onp.arctanh, "unit", True),
    ("sigmoid", lambda x: 1 / (1 + onp.exp(-x)), "any", True),
    ("softsign", lambda x: x / (1 + onp.abs(x)), "any", True),
    ("relu", lambda x: onp.maximum(x, 0), "nonzero", True),
    ("erf", None, "any", True),   # oracle via scipy-free identity below
    ("erfinv", None, "unit", True),
    ("gamma", None, "positive", False),
    ("gammaln", None, "positive", False),
    ("degrees", onp.degrees, "any", True),
    ("radians", onp.radians, "any", True),
]

try:  # math.erf vectorized — no scipy in the image
    import math

    _erf = onp.vectorize(math.erf)
    _gamma = onp.vectorize(math.gamma)
    _gammaln = onp.vectorize(math.lgamma)
except Exception:  # pragma: no cover
    _erf = _gamma = _gammaln = None


def _oracle(name, fallback):
    if fallback is not None:
        return fallback
    if name == "erf":
        return _erf
    if name == "gamma":
        return _gamma
    if name == "gammaln":
        return _gammaln
    if name == "erfinv":
        from numpy import vectorize

        # inverse via bisection against math.erf — exact enough at 1e-6
        def inv(y):
            lo, hi = -4.0, 4.0
            for _ in range(50):
                mid = (lo + hi) / 2
                if math.erf(mid) < y:
                    lo = mid
                else:
                    hi = mid
            return (lo + hi) / 2

        return vectorize(inv)
    raise KeyError(name)


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shape", SHAPES)
def test_unary_matrix(shape, dtype):
    for name, oracle, domain, _diff in UNARY:
        fn = getattr(mx.nd, name)
        x = _data(shape, dtype, domain)
        got = fn(NDArray(x)).asnumpy().astype("float32")
        want = _oracle(name, oracle)(x.astype("float64")).astype("float32")
        tol = dict(rtol=4e-2, atol=2e-2) if dtype == "bfloat16" else {}
        assert_almost_equal(NDArray(got), NDArray(want.astype(dtype)
                                                  .astype("float32")),
                            names=(f"{name}@{shape}/{dtype}", "oracle"), **tol)


@pytest.mark.parametrize("shape", DEGENERATE)
def test_unary_degenerate_shapes(shape):
    for name, oracle, domain, _diff in UNARY:
        fn = getattr(mx.nd, name)
        x = _data(shape, "float32", domain)
        got = fn(NDArray(x)).asnumpy()
        assert got.shape == x.shape, name


def test_unary_gradients_fp32():
    for name, _oracle_fn, domain, diff in UNARY:
        if not diff:
            continue
        fn = getattr(mx.nd, name)
        x = NDArray(_data((3, 4), "float32", domain))
        check_numeric_gradient(lambda a, f=fn: f(a), [x], rtol=2e-2, atol=2e-3)


BINARY = [
    ("add", onp.add, True),
    ("subtract", onp.subtract, True),
    ("multiply", onp.multiply, True),
    ("divide", onp.divide, True),
    ("maximum", onp.maximum, True),
    ("minimum", onp.minimum, True),
    ("power", None, True),       # positive base below
    ("hypot", onp.hypot, True),
    ("arctan2", onp.arctan2, True),
    ("modulo", onp.mod, False),
]


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shapes", [((3, 4), (3, 4)), ((2, 3, 4), (1, 3, 1)),
                                    ((4,), (2, 1, 4))])
def test_binary_broadcast_matrix(shapes, dtype):
    sa, sb = shapes
    for name, oracle, _diff in BINARY:
        fn = getattr(mx.nd, name)
        a = _data(sa, dtype, "positive" if name == "power" else "nonzero")
        b = _data(sb, dtype, "positive" if name in ("power", "divide", "modulo")
                  else "nonzero")
        got = fn(NDArray(a), NDArray(b)).asnumpy().astype("float32")
        want = (onp.power if name == "power" else oracle)(
            a.astype("float64"), b.astype("float64")).astype(dtype)
        tol = dict(rtol=4e-2, atol=2e-2) if dtype == "bfloat16" else {}
        assert_almost_equal(NDArray(got), NDArray(want.astype("float32")),
                            names=(f"{name}@{shapes}/{dtype}", "oracle"), **tol)


def test_binary_gradients_fp32():
    for name, _o, diff in BINARY:
        if not diff:
            continue
        fn = getattr(mx.nd, name)
        a = NDArray(_data((3, 4), "float32", "positive"))
        b = NDArray(_data((3, 4), "float32", "positive"))
        check_numeric_gradient(lambda x, y, f=fn: f(x, y), [a, b],
                               rtol=2e-2, atol=2e-3)


REDUCTIONS = [
    ("sum", onp.sum), ("mean", onp.mean), ("max", onp.max), ("min", onp.min),
    ("prod", onp.prod), ("nansum", onp.nansum), ("nanprod", onp.nanprod),
]


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("axis", [None, 0, 1, -1, (0, 1)])
def test_reduction_matrix(axis, dtype):
    x = _data((3, 4, 5), dtype, "any")
    for name, oracle in REDUCTIONS:
        fn = getattr(mx.nd, name)
        for keepdims in (False, True):
            got = fn(NDArray(x), axis=axis, keepdims=keepdims).asnumpy()
            want = oracle(x.astype("float64"), axis=axis, keepdims=keepdims)
            want = onp.asarray(want, "float32")
            tol = dict(rtol=2e-2, atol=2e-2) if dtype == "bfloat16" \
                else dict(rtol=2e-5, atol=1e-5)
            onp.testing.assert_allclose(
                onp.asarray(got, "float32").reshape(want.shape), want,
                err_msg=f"{name} axis={axis} keepdims={keepdims} {dtype}",
                **tol)


def test_reduction_gradients_fp32():
    for name in ("sum", "mean", "max", "min", "prod"):
        fn = getattr(mx.nd, name)
        x = NDArray((RS.uniform(0.5, 2.0, size=(3, 4))).astype("float32"))
        check_numeric_gradient(lambda a, f=fn: f(a, axis=1), [x],
                               rtol=2e-2, atol=2e-3)


def test_norm_matrix():
    x = _data((3, 4), "float32", "any")
    for ord_ in (1, 2):
        for axis in (None, 0, 1):
            got = mx.nd.norm(NDArray(x), ord=ord_, axis=axis).asnumpy()
            want = onp.linalg.norm(x, ord=ord_, axis=axis) if axis is not None \
                else (onp.abs(x).sum() if ord_ == 1
                      else onp.sqrt((x ** 2).sum()))
            onp.testing.assert_allclose(got.reshape(onp.shape(want)),
                                        onp.asarray(want, "float32"),
                                        rtol=1e-5, atol=1e-5)


SHAPE_OPS_CASES = [
    ("reshape", lambda x: mx.nd.reshape(x, (4, 3)),
     lambda a: a.reshape(4, 3), (3, 4)),
    ("transpose", lambda x: mx.nd.transpose(x, (1, 0)),
     lambda a: a.T, (3, 4)),
    ("swapaxes", lambda x: mx.nd.swapaxes(x, 0, 2),
     lambda a: a.swapaxes(0, 2), (2, 3, 4)),
    ("expand_dims", lambda x: mx.nd.expand_dims(x, 1),
     lambda a: a[:, None], (3, 4)),
    ("squeeze", lambda x: mx.nd.squeeze(x),
     lambda a: a.squeeze(), (3, 1, 4)),
    ("flip", lambda x: mx.nd.flip(x, 1), lambda a: a[:, ::-1], (3, 4)),
    ("tile", lambda x: mx.nd.tile(x, (2, 3)),
     lambda a: onp.tile(a, (2, 3)), (3, 4)),
    ("repeat", lambda x: mx.nd.repeat(x, 2, axis=1),
     lambda a: onp.repeat(a, 2, 1), (3, 4)),
    ("pad_edge", lambda x: mx.nd.pad(x.reshape(1, 1, 3, 4), mode="edge",
                                     pad_width=(0, 0, 0, 0, 1, 1, 2, 2)),
     lambda a: onp.pad(a.reshape(1, 1, 3, 4),
                       ((0, 0), (0, 0), (1, 1), (2, 2)), mode="edge"), (3, 4)),
    ("slice_axis", lambda x: x.slice_axis(1, 1, 3),
     lambda a: a[:, 1:3], (3, 4)),
    ("reverse", lambda x: mx.nd.reverse(x, axis=0),
     lambda a: a[::-1], (3, 4)),
    ("space_to_depth", lambda x: mx.nd.space_to_depth(x, 2),
     None, (1, 2, 4, 4)),
    ("depth_to_space", lambda x: mx.nd.depth_to_space(x, 2),
     None, (1, 8, 2, 2)),
]


@pytest.mark.parametrize("dtype", DTYPES)
def test_shape_ops_matrix(dtype):
    for name, fn, oracle, shape in SHAPE_OPS_CASES:
        x = _data(shape, dtype, "any")
        got = fn(NDArray(x)).asnumpy()
        if oracle is not None:
            onp.testing.assert_array_equal(
                got.astype("float32"),
                onp.ascontiguousarray(oracle(x)).astype("float32"),
                err_msg=f"{name}/{dtype}")


@pytest.mark.parametrize("dtype", ["float32", "int32"])
def test_indexing_ops_matrix(dtype):
    x = onp.arange(12).reshape(3, 4).astype(dtype)
    # take
    idx = NDArray(onp.asarray([2, 0], "int32"))
    got = mx.nd.take(NDArray(x), idx, axis=0).asnumpy()
    onp.testing.assert_array_equal(got, x[[2, 0]])
    # pick
    p = onp.asarray([1, 3, 0], "int32")
    got = mx.nd.pick(NDArray(x), NDArray(p)).asnumpy()
    onp.testing.assert_array_equal(got, x[onp.arange(3), p])
    # one_hot
    oh = mx.nd.one_hot(NDArray(p), 4).asnumpy()
    onp.testing.assert_array_equal(oh.argmax(1), p)
    # gather_nd: MXNet convention — indices (M, N), row m = coords in dim m
    gi = NDArray(onp.asarray([[0, 1], [2, 1]], "int32"))
    got = mx.nd.gather_nd(NDArray(x), gi).asnumpy()
    onp.testing.assert_array_equal(got, x[[0, 1], [2, 1]])
    # topk / sort / argsort
    v = onp.asarray([[3, 1, 2], [0, 5, 4]], dtype)
    top = mx.nd.topk(NDArray(v), k=2, ret_typ="value").asnumpy()
    onp.testing.assert_array_equal(top, -onp.sort(-v, 1)[:, :2])
    s = mx.nd.sort(NDArray(v)).asnumpy()
    onp.testing.assert_array_equal(s, onp.sort(v, 1))
    a = mx.nd.argsort(NDArray(v)).asnumpy()
    onp.testing.assert_array_equal(a.astype(int), onp.argsort(v, 1))


def test_concat_stack_split_matrix():
    for dtype in DTYPES:
        a = _data((2, 3), dtype, "any")
        b = _data((2, 3), dtype, "any")
        c = mx.nd.concat(NDArray(a), NDArray(b), dim=1).asnumpy()
        assert c.shape == (2, 6)
        s = mx.nd.stack(NDArray(a), NDArray(b), axis=0).asnumpy()
        assert s.shape == (2, 2, 3)
        parts = mx.nd.split_v2(NDArray(a), 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == (2, 1)
        onp.testing.assert_array_equal(
            onp.concatenate([p.asnumpy() for p in parts], 1).astype("float32"),
            a.astype("float32"))


def test_clip_where_comparisons_matrix():
    for dtype in DTYPES:
        x = _data((3, 4), dtype, "any")
        y = _data((3, 4), dtype, "any")
        got = mx.nd.clip(NDArray(x), -0.5, 0.5).asnumpy().astype("float32")
        onp.testing.assert_allclose(got, onp.clip(x.astype("float32"),
                                                  -0.5, 0.5), rtol=1e-2)
        w = mx.nd.where(NDArray((x > 0).astype(dtype)), NDArray(x),
                        NDArray(y)).asnumpy()
        onp.testing.assert_array_equal(w.astype("float32"),
                                       onp.where(x > 0, x, y).astype("float32"))
        for name, op in [("greater", onp.greater), ("lesser", onp.less),
                         ("equal", onp.equal), ("not_equal", onp.not_equal),
                         ("greater_equal", onp.greater_equal),
                         ("lesser_equal", onp.less_equal)]:
            got = getattr(mx.nd, name)(NDArray(x), NDArray(y)).asnumpy()
            onp.testing.assert_array_equal(got.astype(bool), op(x, y))


def test_broadcast_family_matrix():
    a = _data((2, 1, 4), "float32", "nonzero")
    b = _data((1, 3, 1), "float32", "nonzero")
    table = [("broadcast_add", onp.add), ("broadcast_sub", onp.subtract),
             ("broadcast_mul", onp.multiply), ("broadcast_div", onp.divide),
             ("broadcast_maximum", onp.maximum),
             ("broadcast_minimum", onp.minimum),
             ("broadcast_power", onp.power),
             ("broadcast_hypot", onp.hypot)]
    for name, op in table:
        aa = onp.abs(a) + 0.5 if name == "broadcast_power" else a
        got = getattr(mx.nd, name)(NDArray(aa), NDArray(b)).asnumpy()
        onp.testing.assert_allclose(got, op(aa, b), rtol=1e-5, atol=1e-6,
                                    err_msg=name)
    got = mx.nd.broadcast_to(NDArray(b), (2, 3, 4)).asnumpy()
    onp.testing.assert_array_equal(got, onp.broadcast_to(b, (2, 3, 4)))
    got = mx.nd.broadcast_like(NDArray(b), NDArray(a * onp.ones((2, 3, 4),
                                                                "float32"))).asnumpy()
    assert got.shape == (2, 3, 4)


def test_logical_family_matrix():
    x = (RS.rand(3, 4) > 0.5).astype("float32")
    y = (RS.rand(3, 4) > 0.5).astype("float32")
    for name, op in [("logical_and", onp.logical_and),
                     ("logical_or", onp.logical_or),
                     ("logical_xor", onp.logical_xor)]:
        got = getattr(mx.nd, name)(NDArray(x), NDArray(y)).asnumpy()
        onp.testing.assert_array_equal(got.astype(bool), op(x > 0, y > 0),
                                       err_msg=name)
    got = mx.nd.logical_not(NDArray(x)).asnumpy()
    onp.testing.assert_array_equal(got.astype(bool), ~(x > 0))
    for name, op in [("isnan", onp.isnan), ("isinf", onp.isinf),
                     ("isfinite", onp.isfinite)]:
        z = onp.asarray([[1.0, onp.nan, onp.inf, -onp.inf]], "float32")
        got = getattr(mx.nd, name)(NDArray(z)).asnumpy()
        onp.testing.assert_array_equal(got.astype(bool), op(z), err_msg=name)


def test_sequence_ops_matrix():
    x = RS.randn(4, 2, 3).astype("float32")  # (T, B, C)
    vl = onp.asarray([2, 4], "float32")
    last = mx.nd.sequence_last(NDArray(x), NDArray(vl),
                               use_sequence_length=True).asnumpy()
    onp.testing.assert_allclose(last[0], x[1, 0], rtol=1e-6)
    onp.testing.assert_allclose(last[1], x[3, 1], rtol=1e-6)
    masked = mx.nd.sequence_mask(NDArray(x), NDArray(vl),
                                 use_sequence_length=True).asnumpy()
    assert (masked[2:, 0] == 0).all() and (masked[:, 1] == x[:, 1]).all()
    rev = mx.nd.sequence_reverse(NDArray(x), NDArray(vl),
                                 use_sequence_length=True).asnumpy()
    onp.testing.assert_allclose(rev[0, 0], x[1, 0], rtol=1e-6)


def test_smooth_l1_and_softmax_family():
    x = _data((3, 4), "float32", "any")
    got = mx.nd.smooth_l1(NDArray(x), scalar=1.0).asnumpy()
    want = onp.where(onp.abs(x) < 1, 0.5 * x * x, onp.abs(x) - 0.5)
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    for name in ("softmax", "log_softmax", "softmin"):
        got = getattr(mx.nd, name)(NDArray(x), axis=-1).asnumpy()
        e = onp.exp((-x if name == "softmin" else x)
                    - (-x if name == "softmin" else x).max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        want = onp.log(sm) if name == "log_softmax" else sm
        onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                    err_msg=name)
        check_numeric_gradient(
            lambda a, f=getattr(mx.nd, name): f(a, axis=-1),
            [NDArray(x)], rtol=2e-2, atol=2e-3)


# --------------------------------------------------------------------- #
# NN op family matrix (ref test_operator.py conv/pool/norm matrices)
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("cfg", [
    dict(shape=(2, 3, 8, 8), kernel=(3, 3), stride=(1, 1), pad=(1, 1), nf=4),
    dict(shape=(1, 4, 7, 9), kernel=(2, 2), stride=(2, 2), pad=(0, 0), nf=6),
    dict(shape=(2, 4, 6, 6), kernel=(3, 3), stride=(1, 1), pad=(1, 1), nf=4,
         groups=2),
    dict(shape=(2, 3, 10), kernel=(3,), stride=(2,), pad=(1,), nf=5),  # 1D
])
def test_convolution_matrix(cfg, dtype):
    import jax

    nd_sp = len(cfg["kernel"])
    g = cfg.get("groups", 1)
    x = _data(cfg["shape"], dtype, "any")
    w = _data((cfg["nf"], cfg["shape"][1] // g) + cfg["kernel"], dtype, "any")
    b = _data((cfg["nf"],), dtype, "any")
    out = mx.nd.Convolution(NDArray(x), NDArray(w), NDArray(b),
                            kernel=cfg["kernel"], stride=cfg["stride"],
                            pad=cfg["pad"], num_filter=cfg["nf"],
                            num_group=g).asnumpy()
    # oracle via jax in fp32
    import jax.numpy as jnp
    from jax import lax

    sp = "DHW"[-nd_sp:]
    want = lax.conv_general_dilated(
        jnp.asarray(x, jnp.float32), jnp.asarray(w, jnp.float32),
        cfg["stride"], [(p, p) for p in cfg["pad"]],
        dimension_numbers=("NC" + sp, "OI" + sp, "NC" + sp),
        feature_group_count=g)
    want = onp.asarray(want) + b.astype("float32").reshape((1, -1) + (1,) * nd_sp)
    tol = dict(rtol=4e-2, atol=3e-2) if dtype == "bfloat16" \
        else dict(rtol=1e-4, atol=1e-5)
    onp.testing.assert_allclose(out.astype("float32"), want, **tol)


def test_convolution_gradient_fp32():
    x = NDArray(_data((2, 3, 6, 6), "float32", "any"))
    w = NDArray(_data((4, 3, 3, 3), "float32", "any"))
    check_numeric_gradient(
        lambda a, ww: mx.nd.Convolution(a, ww, kernel=(3, 3), stride=(1, 1),
                                        pad=(1, 1), num_filter=4,
                                        no_bias=True),
        [x, w], rtol=5e-2, atol=5e-2)  # fp32 central differences over a
    # 72-position reduction carry ~1e-2 absolute noise


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("pool_type", ["max", "avg"])
@pytest.mark.parametrize("cfg", [
    dict(shape=(2, 3, 8, 8), kernel=(2, 2), stride=(2, 2), pad=(0, 0)),
    dict(shape=(1, 2, 7, 7), kernel=(3, 3), stride=(2, 2), pad=(1, 1)),
])
def test_pooling_matrix(cfg, pool_type, dtype):
    x = _data(cfg["shape"], dtype, "any")
    out = mx.nd.Pooling(NDArray(x), kernel=cfg["kernel"], pool_type=pool_type,
                        stride=cfg["stride"], pad=cfg["pad"]).asnumpy()
    N, C, H, W = cfg["shape"]
    kh, kw = cfg["kernel"]
    sh, sw = cfg["stride"]
    ph, pw = cfg["pad"]
    xp = onp.pad(x.astype("float64"), ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=-onp.inf if pool_type == "max" else 0.0)
    Ho = (H + 2 * ph - kh) // sh + 1
    Wo = (W + 2 * pw - kw) // sw + 1
    want = onp.zeros((N, C, Ho, Wo))
    for i in range(Ho):
        for j in range(Wo):
            win = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            if pool_type == "max":
                want[:, :, i, j] = win.max((2, 3))
            else:
                # count_include_pad=True (reference default)
                want[:, :, i, j] = win.sum((2, 3)) / (kh * kw)
    tol = dict(rtol=3e-2, atol=2e-2) if dtype == "bfloat16" \
        else dict(rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(out.astype("float64"), want, **tol)


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shape", [(4, 6), (2, 5, 6)])
def test_fullyconnected_matrix(shape, dtype):
    x = _data(shape, dtype, "any")
    w = _data((3, shape[-1]), dtype, "any")
    b = _data((3,), dtype, "any")
    out = mx.nd.FullyConnected(NDArray(x), NDArray(w), NDArray(b),
                               num_hidden=3, flatten=False).asnumpy()
    want = x.astype("float32") @ w.astype("float32").T + b.astype("float32")
    tol = dict(rtol=4e-2, atol=2e-2) if dtype == "bfloat16" \
        else dict(rtol=1e-4, atol=1e-5)
    onp.testing.assert_allclose(out.astype("float32"), want, **tol)


@pytest.mark.parametrize("dtype", DTYPES)
def test_norm_layers_matrix(dtype):
    # LayerNorm
    x = _data((2, 5, 8), dtype, "any")
    g = onp.ones(8, dtype)
    b = onp.zeros(8, dtype)
    out = mx.nd.LayerNorm(NDArray(x), NDArray(g), NDArray(b)).asnumpy()
    xf = x.astype("float64")
    want = (xf - xf.mean(-1, keepdims=True)) / onp.sqrt(
        xf.var(-1, keepdims=True) + 1e-5)
    tol = dict(rtol=4e-2, atol=3e-2) if dtype == "bfloat16" \
        else dict(rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(out.astype("float64"), want, **tol)
    # BatchNorm (training stats)
    x = _data((4, 3, 5, 5), dtype, "any")
    g1 = onp.ones(3, dtype)
    b1 = onp.zeros(3, dtype)
    mm = onp.zeros(3, "float32")
    mv = onp.ones(3, "float32")
    out = mx.nd.BatchNorm(NDArray(x), NDArray(g1), NDArray(b1), NDArray(mm),
                          NDArray(mv), training=True)[0].asnumpy()
    xf = x.astype("float64")
    mean = xf.mean((0, 2, 3), keepdims=True)
    var = xf.var((0, 2, 3), keepdims=True)
    want = (xf - mean) / onp.sqrt(var + 1e-5)
    onp.testing.assert_allclose(out.astype("float64"), want, **tol)


def test_activation_family_matrix():
    x = _data((3, 4), "float32", "any")
    table = {
        "relu": lambda a: onp.maximum(a, 0),
        "sigmoid": lambda a: 1 / (1 + onp.exp(-a)),
        "tanh": onp.tanh,
        "softrelu": lambda a: onp.log1p(onp.exp(a)),
        "softsign": lambda a: a / (1 + onp.abs(a)),
    }
    for act, oracle in table.items():
        got = mx.nd.Activation(NDArray(x), act_type=act).asnumpy()
        onp.testing.assert_allclose(got, oracle(x), rtol=1e-5, atol=1e-6,
                                    err_msg=act)
    for slope in (0.1, 0.3):
        got = mx.nd.LeakyReLU(NDArray(x), act_type="leaky",
                              slope=slope).asnumpy()
        onp.testing.assert_allclose(got, onp.where(x > 0, x, slope * x),
                                    rtol=1e-5, atol=1e-6)


def test_embedding_matrix():
    for dtype in DTYPES:
        w = _data((7, 5), dtype, "any")
        idx = onp.asarray([[0, 3], [6, 1]], "int32")
        out = mx.nd.Embedding(NDArray(idx), NDArray(w), input_dim=7,
                              output_dim=5).asnumpy()
        onp.testing.assert_array_equal(out.astype("float32"),
                                       w[idx].astype("float32"))


# ------------------------------------------------------------------ #
# alias + misc sweep (VERDICT r3 #7 closure audit): every exported op
# not covered by the families above, at >=2 shapes x >=2 dtypes where
# the op is dtype-generic.  Aliases are asserted against the SAME
# oracle as their canonical name — a broken alias rebind is a real
# regression class (MXNet user code uses both spellings).
# ------------------------------------------------------------------ #
_ALIAS_BINARY = [
    ("broadcast_plus", onp.add), ("broadcast_minus", onp.subtract),
    ("broadcast_mod", lambda a, b: onp.mod(a, b)),  # divisor-sign (mshadow_op::mod)
    ("broadcast_equal", onp.equal), ("broadcast_not_equal", onp.not_equal),
    ("broadcast_greater", onp.greater),
    ("broadcast_greater_equal", onp.greater_equal),
    ("broadcast_lesser", onp.less),
    ("broadcast_lesser_equal", onp.less_equal),
    ("broadcast_logical_and", onp.logical_and),
    ("broadcast_logical_or", onp.logical_or),
    ("broadcast_logical_xor", onp.logical_xor),
    ("elemwise_add", onp.add), ("elemwise_sub", onp.subtract),
    ("elemwise_mul", onp.multiply), ("elemwise_div", onp.divide),
]


@pytest.mark.parametrize("shapes", [((3, 4), (3, 4)), ((2, 1, 4), (1, 3, 4))])
@pytest.mark.parametrize("dtype", DTYPES)
def test_alias_binary_matrix(shapes, dtype):
    sa, sb = shapes
    a = _data(sa, dtype, "nonzero")
    b = _data(sb, dtype, "nonzero")
    tol = dict(rtol=4e-2, atol=2e-2) if dtype == "bfloat16" else {}
    for name, oracle in _ALIAS_BINARY:
        if name.startswith("elemwise") and sa != sb:
            continue  # elemwise requires equal shapes by contract
        got = getattr(mx.nd, name)(NDArray(a), NDArray(b)).asnumpy()
        ref = oracle(a.astype("float32"), b.astype("float32"))
        assert_almost_equal(got.astype("float32"),
                            onp.asarray(ref, "float32"),
                            names=(f"{name}/{dtype}", "oracle"), **tol)


@pytest.mark.parametrize("dtype", DTYPES)
def test_alias_reduce_and_axes_matrix(dtype):
    tol = dict(rtol=4e-2, atol=2e-2) if dtype == "bfloat16" else {}
    for shape in [(3, 4), (2, 3, 4)]:
        x = _data(shape, dtype, "any")
        for name, oracle in [("sum_axis", onp.sum), ("max_axis", onp.max),
                             ("min_axis", onp.min)]:
            got = getattr(mx.nd, name)(NDArray(x), axis=1).asnumpy()
            assert_almost_equal(got.astype("float32"),
                                oracle(x.astype("float32"), axis=1),
                                names=(f"{name}/{dtype}/{shape}", "oracle"),
                                **tol)
    # broadcast_axis: expand a size-1 dim
    x = _data((2, 1, 3), dtype, "any")
    got = mx.nd.broadcast_axis(NDArray(x), axis=1, size=4).asnumpy()
    onp.testing.assert_array_equal(
        got.astype("float32"),
        onp.broadcast_to(x, (2, 4, 3)).astype("float32"))
    # reshape_like / Flatten / SwapAxis / Concat / SliceChannel
    a = _data((2, 6), dtype, "any")
    b = _data((3, 4), dtype, "any")
    onp.testing.assert_array_equal(
        mx.nd.reshape_like(NDArray(a), NDArray(b)).asnumpy().astype("float32"),
        a.reshape(3, 4).astype("float32"))
    c = _data((2, 3, 4), dtype, "any")
    onp.testing.assert_array_equal(
        mx.nd.Flatten(NDArray(c)).asnumpy().astype("float32"),
        c.reshape(2, 12).astype("float32"))
    onp.testing.assert_array_equal(
        mx.nd.SwapAxis(NDArray(c), dim1=0, dim2=2).asnumpy().astype("float32"),
        onp.swapaxes(c, 0, 2).astype("float32"))
    cc = mx.nd.Concat(NDArray(b), NDArray(b), dim=0).asnumpy()
    onp.testing.assert_array_equal(cc.astype("float32"),
                                   onp.concatenate([b, b], 0).astype("float32"))
    parts = mx.nd.SliceChannel(NDArray(c), num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1, 4)
    onp.testing.assert_array_equal(parts[1].asnumpy().astype("float32"),
                                   c[:, 1:2, :].astype("float32"))


@pytest.mark.parametrize("dtype", DTYPES)
def test_misc_math_ops_matrix(dtype):
    tol = dict(rtol=4e-2, atol=2e-2) if dtype == "bfloat16" else {}
    for shape in [(3, 5), (2, 3, 5)]:
        x = _data(shape, dtype, "any")
        got = mx.nd.hard_sigmoid(NDArray(x)).asnumpy()
        assert_almost_equal(got.astype("float32"),
                            onp.clip(0.2 * x.astype("float32") + 0.5, 0, 1),
                            names=(f"hard_sigmoid/{dtype}", "oracle"), **tol)
    # argmax_channel: per-row argmax over the LAST axis (upstream doc
    # example: [[0,1,2],[3,4,5]] -> [2, 2])
    x = _data((3, 4), dtype, "any")
    got = mx.nd.argmax_channel(NDArray(x)).asnumpy()
    onp.testing.assert_array_equal(got.astype(int),
                                   x.astype("float32").argmax(-1))
    # batch_dot incl. transposes
    a = _data((2, 3, 4), dtype, "any")
    b = _data((2, 4, 5), dtype, "any")
    got = mx.nd.batch_dot(NDArray(a), NDArray(b)).asnumpy()
    ref = onp.einsum("bij,bjk->bik", a.astype("float32"), b.astype("float32"))
    assert_almost_equal(got.astype("float32"), ref,
                        names=(f"batch_dot/{dtype}", "oracle"), **tol)
    got = mx.nd.batch_dot(NDArray(a), NDArray(a), transpose_b=True).asnumpy()
    ref = onp.einsum("bij,bkj->bik", a.astype("float32"), a.astype("float32"))
    assert_almost_equal(got.astype("float32"), ref,
                        names=(f"batch_dot_tb/{dtype}", "oracle"), **tol)
    # khatri_rao (column-wise kron)
    a = _data((2, 3), "float32", "any")
    b = _data((4, 3), "float32", "any")
    got = mx.nd.khatri_rao(NDArray(a), NDArray(b)).asnumpy()
    ref = onp.vstack([onp.kron(a[:, j], b[:, j]).reshape(-1)
                      for j in range(3)]).T.reshape(8, 3)
    assert_almost_equal(got, ref, names=("khatri_rao", "oracle"))


@pytest.mark.parametrize("dtype", DTYPES)
def test_nn_misc_ops_matrix(dtype):
    tol = dict(rtol=4e-2, atol=2e-2) if dtype == "bfloat16" else {}
    for shape in [(3, 6), (2, 4, 6)]:
        x = _data(shape, dtype, "any")
        m = (RS.uniform(size=shape) > 0.3).astype("float32")
        m[..., 0] = 1.0  # at least one unmasked entry per row
        got = mx.nd.masked_log_softmax(NDArray(x), NDArray(m)).asnumpy()
        xf = onp.where(m.astype(bool), x.astype("float32"), -onp.inf)
        ref = xf - onp.log(onp.sum(onp.exp(
            xf - xf.max(-1, keepdims=True)), -1, keepdims=True)) \
            - xf.max(-1, keepdims=True)
        assert_almost_equal(onp.where(m.astype(bool), got.astype("float32"), 0),
                            onp.where(m.astype(bool), ref, 0),
                            names=(f"masked_log_softmax/{dtype}", "oracle"),
                            **tol)
        # SoftmaxOutput forward == softmax
        got = mx.nd.SoftmaxOutput(NDArray(x)).asnumpy()
        e = onp.exp(x.astype("float32") - x.astype("float32").max(-1, keepdims=True))
        assert_almost_equal(got.astype("float32"), e / e.sum(-1, keepdims=True),
                            names=(f"SoftmaxOutput/{dtype}", "oracle"), **tol)
        # gelu (tanh approximation)
        got = mx.nd.gelu(NDArray(x)).asnumpy()
        xf = x.astype("float32")
        ref = 0.5 * xf * (1 + onp.tanh(onp.sqrt(2 / onp.pi)
                                       * (xf + 0.044715 * xf ** 3)))
        assert_almost_equal(got.astype("float32"), ref, rtol=2e-2, atol=2e-2,
                            names=(f"gelu/{dtype}", "oracle"))
    # GroupNorm + batch_norm_stats vs numpy oracles (fp32 only — stats)
    x = _data((2, 6, 4), "float32", "any")
    g = onp.ones((6,), "float32"); bta = onp.zeros((6,), "float32")
    got = mx.nd.GroupNorm(NDArray(x), NDArray(g), NDArray(bta),
                          num_groups=2).asnumpy()
    xr = x.reshape(2, 2, 3 * 4)
    mean = xr.mean(-1, keepdims=True); var = xr.var(-1, keepdims=True)
    ref = ((xr - mean) / onp.sqrt(var + 1e-5)).reshape(2, 6, 4)
    assert_almost_equal(got, ref, rtol=1e-4, atol=1e-4, names=("GroupNorm", "oracle"))
    mean, var = mx.nd.batch_norm_stats(NDArray(x), axis=1)
    onp.testing.assert_allclose(mean.asnumpy(), x.mean(axis=(0, 2)),
                                rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(var.asnumpy(), x.var(axis=(0, 2)),
                                rtol=1e-4, atol=1e-4)


def test_contrib_misc_ops_matrix():
    # arange_like
    x = NDArray(onp.zeros((3, 5), "float32"))
    got = mx.nd.contrib.arange_like(x, start=2.0, step=0.5).asnumpy()
    onp.testing.assert_allclose(got, (2.0 + 0.5 * onp.arange(15)).reshape(3, 5))
    got = mx.nd.contrib.arange_like(x, axis=1).asnumpy()
    onp.testing.assert_allclose(got, onp.arange(5, dtype="float32"))
    # div_sqrt_dim
    a = _data((2, 9), "float32", "any")
    got = mx.nd.contrib.div_sqrt_dim(NDArray(a)).asnumpy()
    onp.testing.assert_allclose(got, a / onp.sqrt(9.0), rtol=1e-6)
    # getnnz
    z = onp.asarray([[0.0, 1.0, 0.0], [2.0, 0.0, 3.0]], "float32")
    assert int(mx.nd.contrib.getnnz(NDArray(z)).asnumpy()) == 3
    onp.testing.assert_array_equal(
        mx.nd.contrib.getnnz(NDArray(z), axis=0).asnumpy().astype(int),
        [1, 1, 1])
    # interleaved qkv attention ops vs explicit einsum oracle
    T, B, H, Dh = 4, 2, 3, 5
    qkv = RS.uniform(-1, 1, size=(T, B, 3 * H * Dh)).astype("float32")
    xq = qkv.reshape(T, B, H, 3, Dh)
    q, k, v = xq[..., 0, :], xq[..., 1, :], xq[..., 2, :]
    qh = onp.transpose(q, (1, 2, 0, 3)).reshape(B * H, T, Dh)
    kh = onp.transpose(k, (1, 2, 0, 3)).reshape(B * H, T, Dh)
    vh = onp.transpose(v, (1, 2, 0, 3)).reshape(B * H, T, Dh)
    got = mx.nd.contrib.interleaved_matmul_selfatt_qk(
        NDArray(qkv), heads=H).asnumpy()
    ref = onp.einsum("bqd,bkd->bqk", qh / onp.sqrt(Dh), kh)
    assert_almost_equal(got, ref, rtol=1e-5, atol=1e-5,
                        names=("interleaved_selfatt_qk", "oracle"))
    att = onp.exp(ref - ref.max(-1, keepdims=True))
    att = att / att.sum(-1, keepdims=True)
    got = mx.nd.contrib.interleaved_matmul_selfatt_valatt(
        NDArray(qkv), NDArray(att.astype("float32")), heads=H).asnumpy()
    ref_out = onp.einsum("bqk,bkd->bqd", att, vh)
    ref_out = ref_out.reshape(B, H, T, Dh).transpose(2, 0, 1, 3).reshape(T, B, H * Dh)
    assert_almost_equal(got, ref_out, rtol=1e-5, atol=1e-5,
                        names=("interleaved_selfatt_valatt", "oracle"))
