"""Multi-step chaining (`Trainer(chain_steps=K)`) — K canonical steps
buffered into ONE lax.scan program (r4 VERDICT item 1: amortize the
per-dispatch host/relay gap in the product path).

Parity bar: losses, weights, optimizer behavior, AND BatchNorm running
stats must match the per-step path exactly over full flushes and a
partial (tail) flush; any read mid-chain must flush first and give the
same values.
"""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu.gluon import Trainer, nn
from incubator_mxnet_tpu.ndarray.ndarray import NDArray

B, D, NCLS = 8, 12, 4


def _net(seed):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16))
    net.add(nn.BatchNorm())          # aux state must ride the chain carry
    net.add(nn.Activation("relu"))
    net.add(nn.Dense(NCLS))
    net.initialize()
    net(NDArray(mx.nd.ones((B, D))._data))
    net.hybridize()
    return net


def _batch(s):
    r = onp.random.RandomState(100 + s)
    x = r.randn(B, D).astype("float32")
    y = r.randint(0, NCLS, B).astype("int32")
    return x, y


def _run(chain_steps, n_steps, read_every=None, opt="sgd",
         opt_args=None, unroll=False):
    net = _net(seed=7)
    tr = Trainer(net.collect_params(), opt,
                 opt_args or {"learning_rate": 0.05, "momentum": 0.9},
                 keep_grads=False, chain_steps=chain_steps,
                 chain_unroll=unroll)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    read = []
    for s in range(n_steps):
        x, y = _batch(s)
        with autograd.record():
            L = loss_fn(net(NDArray(x)), NDArray(y))
        L.backward()
        tr.step(B)
        if read_every and (s + 1) % read_every == 0:
            read.append(float(L.asnumpy().mean()))
    tr.flush()
    params = [p.data().asnumpy() for p in net.collect_params().values()]
    return params, read, tr


@pytest.mark.parametrize("unroll", [False, True])
def test_chained_matches_per_step_including_bn_stats(unroll):
    p1, _r1, tr1 = _run(1, 7)
    p3, _r3, tr3 = _run(3, 7, unroll=unroll)  # 2 full flushes + 1 tail
    assert tr3._chain_steps == 3
    for i, (a, b) in enumerate(zip(p3, p1)):
        onp.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6,
                                    err_msg=f"param {i}")
    assert tr1._optimizer.num_update == tr3._optimizer.num_update == 7


def test_mid_chain_loss_read_flushes_and_matches():
    _p1, r1, _t1 = _run(1, 6, read_every=1)
    _p3, r3, _t3 = _run(3, 6, read_every=1)  # every read forces a flush
    onp.testing.assert_allclose(r3, r1, rtol=2e-5, atol=2e-6)
    # occasional reads (the Speedometer pattern) must also agree
    _p, r1b, _ = _run(1, 6, read_every=3)
    _p, r3b, _ = _run(3, 6, read_every=3)
    onp.testing.assert_allclose(r3b, r1b, rtol=2e-5, atol=2e-6)


def test_mid_chain_param_read_flushes():
    net = _net(seed=9)
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05},
                 keep_grads=False, chain_steps=4)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _batch(0)
    for _ in range(3):  # step 0 warms the staged cache; 2 enqueue
        with autograd.record():
            L = loss_fn(net(NDArray(x)), NDArray(y))
        L.backward()
        tr.step(B)
    assert len(tr._chain_buf) == 2
    w = net[0].weight.data().asnumpy()  # read must flush
    assert len(tr._chain_buf) == 0
    # and give the post-3-step weights (vs an unchained twin)
    net2 = _net(seed=9)
    tr2 = Trainer(net2.collect_params(), "sgd", {"learning_rate": 0.05},
                  keep_grads=False)
    for _ in range(3):
        with autograd.record():
            L = loss_fn(net2(NDArray(x)), NDArray(y))
        L.backward()
        tr2.step(B)
    onp.testing.assert_allclose(w, net2[0].weight.data().asnumpy(),
                                rtol=2e-5, atol=2e-6)


def test_chained_adam_and_scheduler():
    """Optimizer state + per-step lr (scheduler) ride the chain."""
    from incubator_mxnet_tpu import lr_scheduler

    sched = lambda: lr_scheduler.FactorScheduler(step=2, factor=0.5,
                                                 base_lr=1e-2)
    p1, _r, _t = _run(1, 6, opt="adam",
                      opt_args={"lr_scheduler": sched()})
    p3, _r, _t = _run(3, 6, opt="adam",
                      opt_args={"lr_scheduler": sched()})
    for i, (a, b) in enumerate(zip(p3, p1)):
        onp.testing.assert_allclose(a, b, rtol=3e-5, atol=3e-6,
                                    err_msg=f"param {i}")


def test_chained_save_states_flushes(tmp_path):
    net = _net(seed=11)
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.05, "momentum": 0.9},
                 keep_grads=False, chain_steps=4)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _batch(1)
    for _ in range(3):
        with autograd.record():
            L = loss_fn(net(NDArray(x)), NDArray(y))
        L.backward()
        tr.step(B)
    assert tr._chain_buf
    tr.save_states(str(tmp_path / "t.states"))
    assert not tr._chain_buf  # flushed
    assert tr._optimizer.num_update == 3
    # restored counts round-trip
    tr.load_states(str(tmp_path / "t.states"))
    assert tr._optimizer.num_update == 3


def test_chain_steps_refused_loudly_when_config_unsupported():
    """chain_steps>1 with keep_grads=True must warn once, not silently
    run unchained (review r5 finding)."""
    import warnings as _w

    net = _net(seed=13)
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05},
                 keep_grads=True, chain_steps=4)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _batch(0)
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        for _ in range(2):
            with autograd.record():
                L = loss_fn(net(NDArray(x)), NDArray(y))
            L.backward()
            tr.step(B)
    msgs = [str(w.message) for w in rec if "chain_steps" in str(w.message)]
    assert len(msgs) == 1, msgs  # warned, and only once
    assert "keep_grads" in msgs[0]
    assert not tr._chain_buf


def test_chained_on_mesh_matches_single_device():
    """chain_steps on a TP×DP mesh: the real Gluon BERT through the
    PUBLIC loop, chained, must match the unchained single-device oracle
    (the chained program carries SHARDED weights/states and stacks the
    data-axis-sharded batches in-program)."""
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.gluon.block import HybridBlock
    from incubator_mxnet_tpu.gluon.utils import shard_batch
    from incubator_mxnet_tpu.models import bert
    from incubator_mxnet_tpu.parallel import create_mesh
    from incubator_mxnet_tpu.parallel.sharding import shard_params

    V, D, DFF, L, H, Bb, T = 32, 16, 32, 2, 2, 8, 8

    class WithLoss(HybridBlock):
        def __init__(self, net_, **kw):
            super().__init__(**kw)
            self.net = net_

        def forward(self, tokens, labels):
            mlm_logits, _nsp = self.net(tokens)
            logp = mx.nd.log_softmax(mlm_logits.astype("float32"))
            return -(mx.nd.pick(logp, labels).mean())

    def build():
        mx.random.seed(21)
        net_ = bert.BERTForPretraining(vocab_size=V, units=D,
                                       hidden_size=DFF, num_layers=L,
                                       num_heads=H, dropout=0.0)
        net_.initialize()
        net_(NDArray(jnp.ones((Bb, T), jnp.int32)))
        m = WithLoss(net_)
        m.hybridize()
        return net_, m

    def batch(s):
        k = jax.random.PRNGKey(300 + s)
        kx, ky = jax.random.split(k)
        return (jax.random.randint(kx, (Bb, T), 0, V, dtype=jnp.int32),
                jax.random.randint(ky, (Bb, T), 0, V, dtype=jnp.int32))

    def train(model, tr, mesh, n):
        losses = []
        for s in range(n):
            tok, lab = batch(s)
            if mesh is not None:
                tok, lab = shard_batch(tok, mesh), shard_batch(lab, mesh)
            else:
                tok, lab = NDArray(tok), NDArray(lab)
            with autograd.record():
                L_ = model(tok, lab)
            L_.backward()
            tr.step(1)
        tr.flush()
        losses.append(float(L_.asnumpy()))
        return losses

    net1, m1 = build()
    tr1 = Trainer(m1.collect_params(), "sgd",
                  {"learning_rate": 0.1, "momentum": 0.9},
                  keep_grads=False)
    l1 = train(m1, tr1, None, 6)

    net2, m2 = build()
    mesh = create_mesh(jax.devices()[:8], data=4, model=2)
    shard_params(net2, mesh)
    tr2 = Trainer(m2.collect_params(), "sgd",
                  {"learning_rate": 0.1, "momentum": 0.9},
                  keep_grads=False, mesh=mesh, chain_steps=3)
    l2 = train(m2, tr2, mesh, 6)
    assert tr2._chain_steps == 3 and not tr2._chain_buf
    onp.testing.assert_allclose(l2, l1, rtol=3e-5, atol=3e-6)
    for (pa, pb) in zip(m1.collect_params().values(),
                        m2.collect_params().values()):
        onp.testing.assert_allclose(pb.data().asnumpy(),
                                    pa.data().asnumpy(),
                                    rtol=5e-5, atol=5e-6)
