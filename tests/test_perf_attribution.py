"""telemetry.perf: roofline/MFU program attribution and device-memory
watermarks (ISSUE 8 tentpole) — capture from real compiled programs,
achieved-rate gauges, the decode int8-vs-float byte ordering, per-device
shard attribution, and the background watermark poller."""
import math
import time

import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.telemetry import perf


@pytest.fixture
def tel():
    telemetry.enable()
    telemetry.get_registry().clear()
    telemetry.tracer.clear()
    perf.clear()
    yield telemetry
    perf.clear()
    telemetry.get_registry().clear()
    telemetry.tracer.clear()
    telemetry.disable()


def _dot(dtype=jnp.float32):
    a = jnp.ones((64, 64), dtype)
    b = jnp.ones((64, 64), dtype)
    return jax.jit(lambda x, y: x @ y), (a, b)


# --------------------------------------------------------------------- #
# capture / note_timing / roofline_table
# --------------------------------------------------------------------- #
def test_capture_extracts_cost_and_memory_analysis(tel):
    fn, args = _dot()
    pc = perf.capture("matmul64", fn, *args)
    assert pc is not None
    # 64³ MACs → 2·64³ flops, and three 64×64 f32 buffers move
    assert pc.flops == pytest.approx(2 * 64**3, rel=0.1)
    assert pc.bytes_accessed >= 3 * 64 * 64 * 4 * 0.5
    assert pc.expected_bytes > 0
    assert pc.bound_by() in ("compute", "memory")
    assert math.isfinite(pc.intensity) and pc.intensity > 0
    reg = tel.get_registry()
    assert reg.get("program_flops", {"program": "matmul64"}).value == pc.flops
    assert reg.get("program_hbm_bytes",
                   {"program": "matmul64"}).value == pc.bytes_accessed
    assert reg.get("program_expected_bytes",
                   {"program": "matmul64"}).value == pc.expected_bytes


def test_capture_is_once_per_name_unless_forced(tel):
    fn, args = _dot()
    pc1 = perf.capture("once", fn, *args)
    fn2, args2 = _dot(jnp.bfloat16)
    pc2 = perf.capture("once", fn2, *args2)
    assert pc2 is pc1  # second capture skipped: same record back
    pc3 = perf.capture("once", fn2, *args2, force=True)
    assert pc3 is not pc1


def test_note_timing_sets_achieved_rate_gauges(tel):
    fn, args = _dot()
    pc = perf.capture("timed", fn, *args)
    perf.note_timing("timed", 1e-3)
    assert pc.last_seconds == 1e-3
    assert pc.last_mfu == pytest.approx(pc.flops / 1e-3 / perf._peak_flops())
    assert pc.last_gbps == pytest.approx(pc.bytes_accessed / 1e-3 / 1e9)
    assert 0 < pc.last_fraction
    reg = tel.get_registry()
    assert reg.get("program_mfu", {"program": "timed"}).value == pc.last_mfu
    assert reg.get("program_hbm_gbps",
                   {"program": "timed"}).value == pc.last_gbps
    assert reg.get("program_roofline_fraction",
                   {"program": "timed"}).value == pc.last_fraction


def test_note_timing_ignores_uncaptured_and_bad_clock(tel):
    perf.note_timing("ghost", 0.5)       # never captured: no-op
    perf.note_timing(None, 0.5)          # no program: no-op
    fn, args = _dot()
    pc = perf.capture("clocked", fn, *args)
    perf.note_timing("clocked", 0.0)     # non-positive clock: no-op
    assert pc.last_seconds is None
    assert tel.get_registry().get("program_mfu", {"program": "ghost"}) is None


def test_roofline_table_rows_are_name_sorted(tel):
    fn, args = _dot()
    perf.capture("b_prog", fn, *args)
    perf.capture("a_prog", fn, *args, force=True)
    rows = perf.roofline_table()
    assert [r["program"] for r in rows] == ["a_prog", "b_prog"]
    for r in rows:
        assert set(r) >= {"program", "flops", "hbm_bytes", "intensity",
                          "bound_by", "mfu", "hbm_gbps", "roofline_fraction"}


def test_int8_dot_moves_fewer_bytes_than_float(tel):
    """The acceptance ordering the decode programs rely on, pinned on
    bare dots: an int8-weight mixed dot's cost analysis must charge
    fewer bytes than the f32 dot of the same shape."""
    def dot(a, b):
        return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    x = jnp.ones((8, 256), jnp.bfloat16)
    wf = jnp.ones((256, 256), jnp.bfloat16)
    w8 = jnp.ones((256, 256), jnp.int8)

    pf = perf.capture("dot_bf16", jax.jit(dot), x, wf)
    pi = perf.capture("dot_int8", jax.jit(dot), x, w8)
    assert pi.bytes_accessed < pf.bytes_accessed


# --------------------------------------------------------------------- #
# trainer integration
# --------------------------------------------------------------------- #
def test_trainer_full_step_is_attributed(tel):
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, nn
    from incubator_mxnet_tpu.gluon.block import HybridBlock
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    class M(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.d = nn.Dense(4, in_units=6)

        def forward(self, x):
            h = self.d(x)
            return (h * h).mean()

    mx.random.seed(0)
    m = M()
    m.initialize()
    m.hybridize()
    tr = Trainer(m.collect_params(), "sgd", {"learning_rate": 0.1})
    x = NDArray(jnp.ones((2, 6)))
    for _ in range(2):
        with autograd.record():
            loss = m(x)
        loss.backward()
        tr.step(2)
    tr.flush()
    assert tr._perf_program == "trainer_full_step"
    pc = perf.programs().get("trainer_full_step")
    assert pc is not None and pc.flops > 0
    assert pc.last_seconds is not None  # step() fed note_timing
    # re-capture from the retention-free aval skeleton (bench's path)
    assert tr.capture_step_costs() == "trainer_full_step"


def test_trainer_capture_step_costs_without_ctx(tel):
    from incubator_mxnet_tpu.gluon import Trainer, nn

    net = nn.Dense(2, in_units=2)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    assert tr.capture_step_costs() is None  # no full-step ctx yet


# --------------------------------------------------------------------- #
# device-memory watermarks
# --------------------------------------------------------------------- #
def test_per_device_bytes_attributes_shards(tel):
    x = jnp.ones((16, 4), jnp.float32)
    y = jnp.ones((8,), jnp.int32)
    per = perf.per_device_bytes({"a": x, "b": [y]})
    assert per, "no devices attributed"
    assert sum(per.values()) == 16 * 4 * 4 + 8 * 4
    assert perf.per_device_bytes(None) == {}


def test_sample_device_memory_and_peak_tracking(tel):
    keep = jnp.ones((128, 128), jnp.float32)  # pin live bytes
    perf.reset_peaks()
    s1 = perf.sample_device_memory()
    assert s1, "no devices sampled"
    # look at the device actually holding `keep` (the test harness fakes
    # 8 virtual CPU devices; the others legitimately read 0)
    k = perf._dev_key(next(iter(keep.addressable_shards)).device)
    rec = s1[k]
    assert rec["source"] in ("memory_stats", "live_arrays")
    assert rec["bytes_in_use"] >= keep.nbytes
    assert rec["peak_bytes"] >= rec["bytes_in_use"]
    reg = tel.get_registry()
    assert reg.get("device_bytes_in_use", {"device": k}).value \
        == rec["bytes_in_use"]
    assert reg.get("device_peak_bytes", {"device": k}).value \
        == rec["peak_bytes"]
    peak_before = rec["peak_bytes"]
    del keep
    s2 = perf.sample_device_memory()
    assert s2[k]["peak_bytes"] >= peak_before  # the watermark never drops


def test_sample_device_memory_disabled_is_empty():
    telemetry.disable()
    assert perf.sample_device_memory() == {}


def test_watermark_poller_runs_and_stops(tel):
    assert perf.start_poller(interval=0.05)
    assert perf.start_poller(interval=0.05)  # idempotent
    try:
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if tel.get_registry().get(
                    "device_bytes_in_use",
                    {"device": perf._dev_key(jax.devices()[0])}) is not None:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("poller never published a sample")
    finally:
        perf.stop_poller()
    assert perf._poller is None


def test_gate_style_state_watermark_consistency(tel):
    """The cross-check the ZeRO dryrun gate runs, at single-device
    scale: the Trainer's claimed optimizer_state_bytes_per_device must
    match the measured per-device shard attribution of its live state."""
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, nn
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)
    net = nn.Dense(8, in_units=16)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    x = NDArray(jnp.ones((2, 16)))
    for _ in range(2):
        with autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        tr.step(2)
    tr.flush()
    tr._sync_states()
    claimed = tr.optimizer_state_bytes_per_device()
    measured = max(perf.per_device_bytes(list(tr._states.values())).values(),
                   default=0)
    assert claimed > 0 and measured > 0
    assert abs(measured - claimed) <= 0.1 * claimed, \
        f"claimed {claimed} vs measured {measured}"
