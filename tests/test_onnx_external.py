"""ONNX import interop on EXTERNALLY-SHAPED models (r4 VERDICT item 6).

Every other ONNX import test feeds the importer models this framework
itself exported — a closed loop that can't prove interop.  Here the
models are assembled by an INDEPENDENT mini-encoder (field numbers from
the public onnx.proto3, no serde helpers), using ONNX-native idioms the
exporter never emits: BatchNormalization (inference form),
Gemm(transB, beta), Flatten, AveragePool with pads and the default
count_include_pad=0, Constant (tensor attribute), Clip (attr form),
LeakyRelu, Unsqueeze, Dropout, Sum.  Numerics are cross-checked against
torch — a genuinely external oracle.
(Ref parity: upstream `python/mxnet/onnx` import of third-party models,
SURVEY.md §2.6.)
"""
import struct

import numpy as onp
import pytest

from incubator_mxnet_tpu import onnx as mx_onnx


# ---------- independent ONNX wire encoder (onnx.proto3 field nums) ---- #
def vint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field: int, wire: int) -> bytes:
    return vint((field << 3) | wire)


def ld(field: int, payload: bytes) -> bytes:
    return tag(field, 2) + vint(len(payload)) + payload


def iv(field: int, v: int) -> bytes:
    return tag(field, 0) + vint(v)


def fv(field: int, v: float) -> bytes:
    return tag(field, 5) + struct.pack("<f", v)


def tensor(name: str, arr: onp.ndarray) -> bytes:
    out = b"".join(iv(1, d) for d in arr.shape)
    out += iv(2, 1)  # data_type = FLOAT
    out += ld(8, name.encode())
    out += ld(9, onp.ascontiguousarray(arr, onp.float32).tobytes())
    return out


def attr(name: str, value) -> bytes:
    out = ld(1, name.encode())
    if isinstance(value, int):
        out += iv(3, value) + iv(20, 2)          # i / INT
    elif isinstance(value, float):
        out += fv(2, value) + iv(20, 1)          # f / FLOAT
    elif isinstance(value, str):
        out += ld(4, value.encode()) + iv(20, 3)      # s / STRING
    elif isinstance(value, onp.ndarray):
        out += ld(5, tensor("", value)) + iv(20, 4)   # t / TENSOR
    elif isinstance(value, (list, tuple)):
        out += b"".join(iv(8, v) for v in value) + iv(20, 7)  # ints / INTS
    else:
        raise TypeError(value)
    return out


def node(op: str, inputs, outputs, **attrs) -> bytes:
    out = b"".join(ld(1, i.encode()) for i in inputs)
    out += b"".join(ld(2, o.encode()) for o in outputs)
    out += ld(3, (op + "_n").encode())
    out += ld(4, op.encode())
    out += b"".join(ld(5, attr(k, v)) for k, v in attrs.items())
    return out


def value_info(name: str, dims) -> bytes:
    shape = b"".join(ld(1, iv(1, d)) for d in dims)   # dim{dim_value}
    ttype = iv(1, 1) + ld(2, shape)                    # elem_type, shape
    return ld(1, name.encode()) + ld(2, ld(1, ttype))  # TypeProto.tensor_type


def model(nodes, initializers, inputs, outputs) -> bytes:
    g = b"".join(ld(1, n) for n in nodes)
    g += ld(2, b"external_graph")
    g += b"".join(ld(5, tensor(nm, arr)) for nm, arr in initializers)
    g += b"".join(ld(11, value_info(nm, dims)) for nm, dims in inputs)
    g += b"".join(ld(12, value_info(nm, dims)) for nm, dims in outputs)
    opset = ld(1, b"") + iv(2, 17)
    return iv(1, 8) + ld(2, b"external-producer") + ld(7, g) + ld(8, opset)


# --------------------------- fixtures --------------------------------- #
def _cnn_model_bytes(rng):
    """x -> Conv -> BatchNormalization -> Relu -> AveragePool(pads,
    count_include_pad=0) -> Flatten -> Gemm(transB, beta) -> y"""
    Wc = rng.randn(4, 2, 3, 3).astype(onp.float32) * 0.5
    scale = rng.rand(4).astype(onp.float32) + 0.5
    bias = rng.randn(4).astype(onp.float32) * 0.1
    mean = rng.randn(4).astype(onp.float32) * 0.1
    var = rng.rand(4).astype(onp.float32) + 0.5
    Wf = rng.randn(10, 36).astype(onp.float32) * 0.2
    bf = rng.randn(10).astype(onp.float32)
    nodes = [
        node("Conv", ["x", "Wc"], ["c"], kernel_shape=[3, 3]),
        node("BatchNormalization",
             ["c", "scale", "bias", "mean", "var"], ["bn"], epsilon=1e-5),
        node("Relu", ["bn"], ["r"]),
        node("AveragePool", ["r"], ["p"], kernel_shape=[2, 2],
             strides=[2, 2], pads=[1, 1, 1, 1], count_include_pad=0),
        node("Flatten", ["p"], ["f"], axis=1),
        node("Gemm", ["f", "Wf", "bf"], ["y"], transB=1, alpha=1.0,
             beta=1.0),
    ]
    inits = [("Wc", Wc), ("scale", scale), ("bias", bias),
             ("mean", mean), ("var", var), ("Wf", Wf), ("bf", bf)]
    by = model(nodes, inits, [("x", (1, 2, 6, 6))], [("y", (1, 10))])
    return by, (Wc, scale, bias, mean, var, Wf, bf)


def test_external_cnn_idioms_vs_torch(tmp_path):
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    rng = onp.random.RandomState(0)
    by, (Wc, scale, bias, mean, var, Wf, bf) = _cnn_model_bytes(rng)
    p = tmp_path / "external_cnn.onnx"
    p.write_bytes(by)
    m, arg_params, _aux = mx_onnx.import_model(str(p))
    x = rng.randn(1, 2, 6, 6).astype(onp.float32)
    got = onp.asarray(m(x))

    t = torch.from_numpy
    h = F.conv2d(t(x), t(Wc))
    h = F.batch_norm(h, t(mean), t(var), t(scale), t(bias),
                     training=False, eps=1e-5)
    h = F.relu(h)
    h = F.avg_pool2d(h, 2, stride=2, padding=1, count_include_pad=False)
    h = torch.flatten(h, 1)
    want = F.linear(h, t(Wf), t(bf)).numpy()
    onp.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    # avg-pool semantics: count_include_pad=1 must CHANGE the result
    # (catches an importer that ignores the attribute)
    by2, _ = _cnn_model_bytes(onp.random.RandomState(0))
    by2 = by2.replace(
        attr("count_include_pad", 0), attr("count_include_pad", 1))
    p2 = tmp_path / "external_cnn_cip.onnx"
    p2.write_bytes(by2)
    m2, _a, _x = mx_onnx.import_model(str(p2))
    got2 = onp.asarray(m2(x))
    assert not onp.allclose(got2, want, rtol=2e-5, atol=2e-5)
    want2 = F.linear(torch.flatten(
        F.avg_pool2d(F.relu(F.batch_norm(
            F.conv2d(t(x), t(Wc)), t(mean), t(var), t(scale), t(bias),
            training=False, eps=1e-5)), 2, stride=2, padding=1,
            count_include_pad=True), 1), t(Wf), t(bf)).numpy()
    onp.testing.assert_allclose(got2, want2, rtol=2e-5, atol=2e-5)


def test_external_elementwise_idioms(tmp_path):
    rng = onp.random.RandomState(1)
    c = rng.randn(3).astype(onp.float32)
    nodes = [
        node("Constant", [], ["c"], value=c),
        node("Add", ["x", "c"], ["a"]),
        node("Clip", ["a"], ["cl"], min=-1.0, max=1.0),
        node("LeakyRelu", ["cl"], ["lr"], alpha=0.1),
        node("Unsqueeze", ["lr"], ["u"], axes=[0]),
        node("Dropout", ["u"], ["d"]),
        node("Sum", ["d", "d", "d"], ["y"]),
    ]
    by = model(nodes, [], [("x", (2, 3))], [("y", (1, 2, 3))])
    p = tmp_path / "external_elem.onnx"
    p.write_bytes(by)
    m, _a, _x = mx_onnx.import_model(str(p))
    x = rng.randn(2, 3).astype(onp.float32)
    got = onp.asarray(m(x))
    a = onp.clip(x + c, -1.0, 1.0)
    a = onp.where(a >= 0, a, 0.1 * a)
    want = 3.0 * a[None]
    assert got.shape == (1, 2, 3)
    onp.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_external_clip_with_omitted_min_input(tmp_path):
    """ReLU6 idiom: Clip(inputs=["x", "", "six"]) — min omitted via an
    EMPTY input name (legal since opset 11) must clamp only above."""
    six = onp.asarray([6.0], onp.float32)
    nodes = [node("Clip", ["x", "", "six"], ["y"])]
    by = model(nodes, [("six", six)], [("x", (4,))], [("y", (4,))])
    p = tmp_path / "external_clip.onnx"
    p.write_bytes(by)
    m, _a, _x = mx_onnx.import_model(str(p))
    x = onp.asarray([-3.0, 0.5, 6.5, 100.0], onp.float32)
    onp.testing.assert_allclose(
        onp.asarray(m(x)), onp.asarray([-3.0, 0.5, 6.0, 6.0]), rtol=1e-6)


def test_external_pad_shape_constantofshape(tmp_path):
    """Shape -> ConstantOfShape -> Add with a reflect-Pad branch — the
    shape-programming idiom external exporters emit constantly."""
    nodes = [
        node("Pad", ["x"], ["p"], pads=[0, 1, 0, 1], mode="reflect"),
        node("Shape", ["p"], ["s"]),
        node("ConstantOfShape", ["s"], ["z"],
             value=onp.asarray([2.5], onp.float32)),
        node("Add", ["p", "z"], ["y"]),
    ]
    by = model(nodes, [], [("x", (2, 3))], [("y", (2, 5))])
    p = tmp_path / "external_shapeprog.onnx"
    p.write_bytes(by)
    m, _a, _x = mx_onnx.import_model(str(p))
    x = onp.arange(6, dtype=onp.float32).reshape(2, 3)
    got = onp.asarray(m(x))
    want = onp.pad(x, ((0, 0), (1, 1)), mode="reflect") + 2.5
    onp.testing.assert_allclose(got, want, rtol=1e-6)


def test_serde_decodes_tensor_attribute_roundtrip():
    """serde's own encoder/decoder round-trips tensor attributes (the
    Constant idiom) so exported graphs may carry them too."""
    from incubator_mxnet_tpu.onnx import serde

    arr = onp.arange(6, dtype=onp.float32).reshape(2, 3)
    n = serde.Node(op_type="Constant", name="k", inputs=[],
                   outputs=["c"], attrs={"value": arr})
    g = serde.Graph()
    g.nodes.append(n)
    g.name = "g"
    g.outputs.append(("c", (2, 3), serde.FLOAT))
    m = serde.Model(graph=g)
    dec = serde.decode_model(serde.encode_model(m))
    onp.testing.assert_array_equal(dec.graph.nodes[0].attrs["value"], arr)
