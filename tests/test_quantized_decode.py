"""Weight-quantized decode (`contrib.quantization.quantize_for_decode`
+ `models.generation`'s int8 path).

Small-batch decode is weight-streaming-bound; the quantized path
streams per-channel int8 weights through the compiled decode programs
with the dequant scale in the matmul epilogue.  The quality contract
(ISSUE 7 acceptance): greedy token parity >= 95% vs the float path and
perplexity delta <= 0.5% on a held-out batch — pinned here for BOTH
dequant strategies (weight-only mixed dot, dynamic activation int8).
"""
import os

import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.contrib.quantization import (DecodeQuantConfig,
                                                      dequantize_decode,
                                                      quantize_for_decode)
from incubator_mxnet_tpu.models.generation import lm_generate, lm_score
from incubator_mxnet_tpu.models.transformer import Transformer, TransformerLM
from incubator_mxnet_tpu.ndarray.ndarray import NDArray

V, C, DFF, L, H, MAXLEN = 97, 32, 64, 2, 4, 64


def _net(seed=0):
    mx.random.seed(seed)
    net = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=MAXLEN, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))  # materialize shapes
    return net


def _nmt_net(V=41):
    mx.random.seed(2)
    net = Transformer(src_vocab=V, tgt_vocab=V, units=32, hidden_size=64,
                      num_layers=2, num_heads=4, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)),
        NDArray(jnp.ones((1, 3), jnp.int32)))
    return net


def _prompt(key, B=2, P=5):
    return onp.array(jax.random.randint(jax.random.PRNGKey(key), (B, P),
                                        0, V), dtype="int32")


# ------------------------------------------------------------------ #
# quality contract
# ------------------------------------------------------------------ #
@pytest.mark.parametrize("act_quant", ["none", "dynamic"])
def test_greedy_parity_vs_float(act_quant):
    net = _net()
    prompt = _prompt(3)
    N = 20
    base = onp.asarray(net.generate(prompt, N))
    net.quantize_for_decode(act_quant=act_quant)
    q = onp.asarray(net.generate(prompt, N))
    parity = (q[:, prompt.shape[1]:] == base[:, prompt.shape[1]:]).mean()
    assert parity >= 0.95, f"{act_quant}: greedy parity {parity} < 0.95"
    # prompt echoed untouched
    onp.testing.assert_array_equal(q[:, :prompt.shape[1]], prompt)


@pytest.mark.parametrize("act_quant", ["none", "dynamic"])
def test_perplexity_delta_within_tolerance(act_quant):
    net = _net()
    held_out = onp.array(jax.random.randint(jax.random.PRNGKey(17), (4, 32),
                                            0, V), dtype="int32")
    ppl_f = float(onp.exp(-onp.asarray(lm_score(net, held_out)).mean()))
    net.quantize_for_decode(act_quant=act_quant)
    ppl_q = float(onp.exp(-onp.asarray(lm_score(net, held_out)).mean()))
    delta = abs(ppl_q - ppl_f) / ppl_f
    assert delta <= 0.005, \
        f"{act_quant}: perplexity delta {delta:.4%} > 0.5% " \
        f"(float {ppl_f:.3f}, int8 {ppl_q:.3f})"


def test_quantize_head_still_within_tolerance():
    net = _net()
    held_out = onp.array(jax.random.randint(jax.random.PRNGKey(19), (2, 24),
                                            0, V), dtype="int32")
    ppl_f = float(onp.exp(-onp.asarray(lm_score(net, held_out)).mean()))
    net.quantize_for_decode(act_quant="none", quantize_head=True)
    ppl_q = float(onp.exp(-onp.asarray(lm_score(net, held_out)).mean()))
    assert abs(ppl_q - ppl_f) / ppl_f <= 0.005


# ------------------------------------------------------------------ #
# beam search under quantization
# ------------------------------------------------------------------ #
def test_beam_scores_monotonic_and_beam1_matches_greedy():
    net = _net()
    prompt = _prompt(5, B=1, P=4)
    net.quantize_for_decode(act_quant="none")
    seqs, scores = net.beam_search(prompt, 6, beam_size=4)
    s = onp.asarray(scores[0])
    assert onp.isfinite(s).all()
    assert (s[:-1] >= s[1:] - 1e-6).all(), "beams not sorted best-first"
    # K=1 beam reproduces the quantized greedy chain exactly (same
    # compiled numerics)
    seqs1, _ = net.beam_search(prompt, 6, beam_size=1)
    greedy = onp.asarray(net.generate(prompt, 6))
    onp.testing.assert_array_equal(onp.asarray(seqs1[:, 0]), greedy)


# ------------------------------------------------------------------ #
# program-cache keying on the quant config
# ------------------------------------------------------------------ #
def test_program_cache_keys_on_quant_config():
    net = _net()
    prompt = _prompt(7)
    net.generate(prompt, 3)
    assert len(net._gen_programs) == 1
    net.quantize_for_decode(act_quant="none")
    net.generate(prompt, 3)
    assert len(net._gen_programs) == 2  # int8 program is distinct
    net.generate(prompt, 3)
    assert len(net._gen_programs) == 2  # ...and reused
    net.quantize_for_decode(act_quant="dynamic")
    net.generate(prompt, 3)
    assert len(net._gen_programs) == 3  # strategy is part of the key
    dequantize_decode(net)
    net.generate(prompt, 3)
    assert len(net._gen_programs) == 3  # float program reused
    # explicit quantized=False on a quantized net → float program too
    net.quantize_for_decode(act_quant="none")
    net.generate(prompt, 3, quantized=False)
    assert len(net._gen_programs) == 3


def test_quantized_true_requires_the_pass():
    net = _net()
    with pytest.raises(ValueError):
        lm_generate(net, _prompt(1), 2, quantized=True)


def test_bad_act_quant_rejected():
    with pytest.raises(ValueError):
        DecodeQuantConfig(act_quant="int4")


# ------------------------------------------------------------------ #
# checkpoints + weight updates
# ------------------------------------------------------------------ #
def test_params_roundtrip_of_quantized_net(tmp_path):
    """quantize_for_decode is runtime-only: .params keeps the float
    weights, a fresh net loads them bit-exactly, and re-quantizing
    reproduces the quantized chain."""
    net = _net()
    prompt = _prompt(11)
    base = onp.asarray(net.generate(prompt, 8))
    net.quantize_for_decode(act_quant="none")
    q = onp.asarray(net.generate(prompt, 8))

    path = str(tmp_path / "quantized_lm.params")
    net.save_parameters(path)
    twin = _net(seed=1)  # different init — must be fully overwritten
    twin.load_parameters(path)
    onp.testing.assert_array_equal(onp.asarray(twin.generate(prompt, 8)),
                                   base)
    twin.quantize_for_decode(act_quant="none")
    onp.testing.assert_array_equal(onp.asarray(twin.generate(prompt, 8)), q)


def test_weight_update_requantizes_lazily():
    """Training (or cast) replaces parameter buffers; the quantized
    copies are keyed on buffer identity, so the next generate call
    consumes fresh int8 weights without re-running the pass."""
    net = _net()
    prompt = _prompt(13)
    net.quantize_for_decode(act_quant="none")
    net.generate(prompt, 4)
    n_programs = len(net._gen_programs)
    net.head.weight.set_data(net.head.weight.data() * -1.0)
    lyr = net._layers[0]
    lyr.ffn.ffn_dense1.weight.set_data(lyr.ffn.ffn_dense1.weight.data() * 0.5)
    out = onp.asarray(net.generate(prompt, 4))
    assert len(net._gen_programs) == n_programs  # no retrace
    # oracle: an identical net quantized AFTER the same update
    twin = _net()
    twin.head.weight.set_data(twin.head.weight.data() * -1.0)
    t = twin._layers[0]
    t.ffn.ffn_dense1.weight.set_data(t.ffn.ffn_dense1.weight.data() * 0.5)
    twin.quantize_for_decode(act_quant="none")
    onp.testing.assert_array_equal(out, onp.asarray(twin.generate(prompt, 4)))


# ------------------------------------------------------------------ #
# NMT decoder quantization
# ------------------------------------------------------------------ #
def test_nmt_quantized_translate_parity():
    net = _nmt_net()
    src = onp.array(jax.random.randint(jax.random.PRNGKey(5), (2, 6),
                                       1, 41), dtype="int32")
    base = onp.asarray(net.translate(src, 5))
    net.quantize_for_decode(act_quant="none")
    q = onp.asarray(net.translate(src, 5))
    assert (q == base).mean() >= 0.95
    # beam path: scores sorted best-first under quantization
    _, scores = net.translate(src, 5, beam_size=3)
    s = onp.asarray(scores)
    assert (s[:, :-1] >= s[:, 1:] - 1e-6).all()


def test_unsupported_net_rejected():
    from incubator_mxnet_tpu.gluon import nn

    blk = nn.Dense(4, in_units=4)
    with pytest.raises(TypeError):
        quantize_for_decode(blk)


# ------------------------------------------------------------------ #
# telemetry: the halved weight-streaming floor is observable
# ------------------------------------------------------------------ #
def test_decode_weight_bytes_gauge():
    net = _net()
    prompt = _prompt(23)
    telemetry.enable()
    try:
        net.generate(prompt, 2)
        reg = telemetry.get_registry()
        f_bytes = reg.get("decode_weight_bytes",
                          {"path": "float"}).value
        net.quantize_for_decode(act_quant="none")
        net.generate(prompt, 2)
        q_bytes = reg.get("decode_weight_bytes",
                          {"path": "int8"}).value
    finally:
        telemetry.disable()
        telemetry.get_registry().reset()
    assert f_bytes > 0 and q_bytes > 0
    # fp32 test net: int8 + fp32 scales must stream well under half
    # the float-path weight bytes (head stays float by default)
    assert q_bytes < 0.6 * f_bytes, (q_bytes, f_bytes)
