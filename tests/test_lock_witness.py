"""Runtime lock-witness tests: factory patching and creation-site
filtering, per-thread edge recording, cycle detection, the static-graph
subset cross-check, and the disabled path's zero overhead.

The witness may already be live for the whole session
(MXTPU_LOCK_WITNESS=1 runs install it from conftest before the package
import); the `isolated` fixture snapshots and restores the global
recorder state so these tests neither lose the session's edges nor leak
their synthetic ones into the end-of-session assert_clean()."""
import os
import threading

import pytest

from incubator_mxnet_tpu import lock_witness as lw

SRC_ORDERED = """\
import threading
a = threading.Lock()
b = threading.Lock()

def ab():
    with a:
        with b:
            pass
"""

SRC_CYCLE = SRC_ORDERED + """\

def ba():
    with b:
        with a:
            pass
"""

SRC_LOCKS_ONLY = """\
import threading
c = threading.Lock()
d = threading.Lock()
"""


@pytest.fixture
def isolated(tmp_path):
    """Witness tracking scoped to tmp_path, session state restored."""
    was_installed = lw.installed()
    saved_roots = lw._track_roots
    saved_edges = dict(lw._edges)
    saved_contention = lw._contention_total
    lw.uninstall()
    lw._edges.clear()
    lw._contention_total = 0.0
    lw.install(force=True, track_roots=[str(tmp_path)])
    try:
        yield lw
    finally:
        lw.uninstall()
        lw._edges.clear()
        lw._edges.update(saved_edges)
        lw._contention_total = saved_contention
        if was_installed:
            lw.install(force=True,
                       track_roots=[r.rstrip(os.sep) for r in saved_roots])


def _load(tmp_path, name, src):
    """Exec fixture source with creation frames pointing at a real file
    under the tracked root — the witness keys locks by creation site."""
    path = tmp_path / name
    path.write_text(src)
    ns = {}
    exec(compile(src, str(path), "exec"), ns)
    return path, ns


def _run_in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


def test_creation_site_filtering(isolated, tmp_path):
    _, ns = _load(tmp_path, "wit_tracked.py", SRC_LOCKS_ONLY)
    assert isinstance(ns["c"], lw._WitnessLock)
    # locks created OUTSIDE the tracked roots come back raw
    foreign = threading.Lock()
    assert not isinstance(foreign, lw._WitnessLock)
    # and the stdlib's own internals (Condition's waiter locks etc.)
    # are never wrapped: Condition over a tracked lock still works
    cond = threading.Condition(ns["c"])
    with cond:
        assert not cond.wait(timeout=0.01)


def test_edges_recorded_per_thread(isolated, tmp_path):
    _, ns = _load(tmp_path, "wit_ab.py", SRC_ORDERED)
    _run_in_thread(ns["ab"])
    obs = lw.edges()
    assert len(obs) == 1
    ((src, dst), meta), = obs.items()
    assert src[1] == 2 and dst[1] == 3      # creation lines of a, b
    assert meta["count"] == 1
    assert meta["stack"]
    # same order again: count bumps, no new edge
    _run_in_thread(ns["ab"])
    assert lw.edges()[(src, dst)]["count"] == 2
    assert lw.check_acyclic() == []


def test_try_acquire_is_not_an_edge(isolated, tmp_path):
    _, ns = _load(tmp_path, "wit_try.py", SRC_LOCKS_ONLY)
    with ns["c"]:
        assert ns["d"].acquire(timeout=0.5)  # bounded: no c->d edge
        ns["d"].release()
    assert lw.edges() == {}


def test_cycle_detection(isolated, tmp_path):
    _, ns = _load(tmp_path, "wit_cycle.py", SRC_CYCLE)
    _run_in_thread(ns["ab"])
    _run_in_thread(ns["ba"])                 # opposite order
    cycles = lw.check_acyclic()
    assert cycles, "AB + BA must form an observed cycle"
    with pytest.raises(AssertionError, match="lock-order cycle"):
        lw.assert_clean()


def test_static_subset_check(isolated, tmp_path):
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.tpulint.analyzer import Project
    from tools.tpulint import lock_rules

    path, ns = _load(tmp_path, "wit_sub.py", SRC_ORDERED)
    _run_in_thread(ns["ab"])
    graph = lock_rules.build_lock_graph(Project([str(path)]))
    # the analyzer saw `ab`, so the observed edge is in the static graph
    assert lw.check_static_subset(graph=graph) == []
    assert lw.assert_clean(graph=graph)["edges"] == 1

    # now an acquisition order the analyzer has never seen: locks from
    # a file with NO acquiring functions, ordered by the test itself
    lw.reset()
    path2, ns2 = _load(tmp_path, "wit_sub2.py", SRC_LOCKS_ONLY)
    with ns2["c"]:
        with ns2["d"]:
            pass
    graph2 = lock_rules.build_lock_graph(Project([str(path2)]))
    problems = lw.check_static_subset(graph=graph2)
    assert problems and "missing from the static graph" in problems[0]
    with pytest.raises(AssertionError, match="missing from"):
        lw.assert_clean(graph=graph2)


def test_contention_is_accumulated(isolated, tmp_path):
    _, ns = _load(tmp_path, "wit_cont.py", SRC_LOCKS_ONLY)
    c = ns["c"]
    c.acquire()
    t = threading.Thread(target=lambda: (c.acquire(), c.release()))
    t.start()
    import time
    time.sleep(0.05)
    c.release()
    t.join()
    assert lw.stats()["contention_seconds"] > 0.0
    lw.snapshot()       # telemetry disabled: must be a silent no-op


def test_disabled_path_zero_overhead(monkeypatch):
    """Without the env gate nothing is patched: threading.Lock stays
    the raw factory and install() declines."""
    was_installed = lw.installed()
    saved_roots = lw._track_roots
    lw.uninstall()
    monkeypatch.delenv("MXTPU_LOCK_WITNESS", raising=False)
    try:
        assert lw.install() is False         # env gate holds
        assert threading.Lock is lw._orig_lock
        assert threading.RLock is lw._orig_rlock
        assert not isinstance(threading.Lock(), lw._WitnessLock)
    finally:
        if was_installed:
            lw.install(force=True,
                       track_roots=[r.rstrip(os.sep) for r in saved_roots])
