"""Native operator plugin ABI (`mx.library.load` ≡ MXLoadLib).

Compiles `native/plugin_example.cc` against the jaxlib XLA FFI headers
at test time (g++, no pybind11), loads it, and drives the loaded op
through the exact user surfaces the reference's custom-op libraries
support: eager call, autograd training, and hybridized (jit) blocks.
(Ref: `python/mxnet/library.py` + `example/extensions/lib_custom_op`,
SURVEY.md §2.3.)
"""
import shutil

import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, library
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


@pytest.fixture(scope="module")
def plugin():
    if shutil.which("g++") is None:
        pytest.skip("no g++ in this environment")
    so = library.build_example_plugin()
    if "sqrelu" not in library.loaded_ops():
        installed = library.load(so, verbose=False)
        assert installed == ["sqrelu"]
    return so


def test_load_rejects_non_plugin(tmp_path):
    bogus = tmp_path / "not_a_plugin.so"
    bogus.write_bytes(b"\x7fELF junk")
    with pytest.raises(OSError):
        library.load(str(bogus))


def test_loaded_op_forward(plugin):
    x = NDArray(jnp.asarray([[-2.0, -0.5, 0.0, 0.5, 2.0]], jnp.float32))
    y = mx.nd.sqrelu(x).asnumpy()
    onp.testing.assert_allclose(y, [[0.0, 0.0, 0.0, 0.25, 4.0]], rtol=1e-6)


def test_loaded_op_custom_grad(plugin):
    x = NDArray(jnp.asarray([-1.0, 0.5, 3.0], jnp.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.sqrelu(x)
        L = y.sum()
    L.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [0.0, 1.0, 6.0], rtol=1e-6)


def test_loaded_op_inside_hybridized_block(plugin):
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.gluon.block import HybridBlock

    class Net(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.dense = nn.Dense(4, in_units=3)

        def forward(self, x):
            return mx.nd.sqrelu(self.dense(x))

    mx.random.seed(0)
    net = Net()
    net.initialize()
    x = NDArray(onp.random.RandomState(0).randn(2, 3).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    onp.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)
    # and it trains through the tape
    with autograd.record():
        L = net(x).sum()
    L.backward()
    g = net.dense.weight.grad()
    assert onp.abs(g.asnumpy()).sum() > 0
