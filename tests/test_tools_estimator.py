"""Tools (im2rec/parse_log/bandwidth) + Estimator handlers
(SURVEY.md §2.8 tools inventory; r1 padded-file finding: estimator)."""
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.ndarray.ndarray import NDArray

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_im2rec_list_and_pack(tmp_path):
    from PIL import Image

    root = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        (root / cls).mkdir(parents=True)
        for i in range(3):
            arr = onp.random.RandomState(i).randint(0, 255, (16, 16, 3),
                                                    dtype=onp.uint8)
            Image.fromarray(arr).save(root / cls / f"{i}.jpg")
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    import importlib

    im2rec = importlib.import_module("im2rec")
    prefix = str(tmp_path / "train")
    entries = im2rec.make_list(str(root), prefix, recursive=True)
    assert len(entries) == 6
    n = im2rec.pack(prefix + ".lst", str(root))
    assert n == 6
    # consume through ImageRecordIter
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=3,
                               use_native=False)
    b = next(iter(it))
    assert b.data[0].shape == (3, 3, 16, 16)


def test_parse_log(tmp_path):
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    import importlib

    parse_log = importlib.import_module("parse_log")
    log = ("Epoch[0] Batch [50]\tSpeed: 100.5 samples/sec\taccuracy=0.5\n"
           "Epoch[0] Batch [100]\tSpeed: 200.5 samples/sec\taccuracy=0.6\n"
           "Epoch[0] Train-accuracy=0.61\n"
           "Epoch[0] Validation-accuracy=0.55\n")
    res = parse_log.parse(log.splitlines())
    assert len(res["batches"]) == 2
    ep = res["epochs"][0]
    assert ep["mean_speed"] == pytest.approx(150.5)
    assert ep["validation-accuracy"] == pytest.approx(0.55)


def test_bandwidth_tool_runs():
    sys.path.insert(0, os.path.join(_ROOT, "tools", "bandwidth"))
    import importlib

    measure = importlib.import_module("measure")
    res = measure.measure([0.25], n_devices=8, runs=2)
    assert res and res[0]["GBps"] > 0


def test_estimator_handlers_and_early_stopping(tmp_path):
    from incubator_mxnet_tpu.gluon import Trainer, loss as loss_mod, nn
    from incubator_mxnet_tpu.gluon.contrib.estimator import (
        CheckpointHandler, EarlyStoppingHandler, Estimator, EventHandler)

    mx.random.seed(0)
    net = nn.Dense(2, in_units=4)
    net.initialize()
    net(NDArray(jnp.ones((2, 4))))
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})

    rng = onp.random.RandomState(0)
    X = rng.randn(32, 4).astype("float32")
    Y = (X.sum(1) > 0).astype("float32")
    batches = [(NDArray(jnp.asarray(X[i:i + 8])), NDArray(jnp.asarray(Y[i:i + 8])))
               for i in range(0, 32, 8)]

    events = []

    class Recorder(EventHandler):
        def train_begin(self, est):
            events.append("train_begin")

        def epoch_end(self, est):
            events.append(f"epoch_end{est.epoch}")

        def train_end(self, est):
            events.append("train_end")

    est = Estimator(net, loss_mod.SoftmaxCrossEntropyLoss(), trainer=trainer,
                    event_handlers=[
                        Recorder(),
                        CheckpointHandler(str(tmp_path), save_best=True,
                                          monitor="accuracy"),
                        EarlyStoppingHandler("accuracy", patience=50)])
    history = est.fit(batches, val_data=batches, epochs=3)
    assert len(history) == 3
    assert "val_accuracy" in history[-1]
    assert events[0] == "train_begin" and events[-1] == "train_end"
    assert os.path.exists(tmp_path / "model-0002.params")
    assert os.path.exists(tmp_path / "model-best.params")


def test_estimator_early_stopping_fires():
    from incubator_mxnet_tpu.gluon import Trainer, loss as loss_mod, nn
    from incubator_mxnet_tpu.gluon.contrib.estimator import (
        EarlyStoppingHandler, Estimator)

    mx.random.seed(1)
    net = nn.Dense(2, in_units=4)
    net.initialize()
    net(NDArray(jnp.ones((2, 4))))
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.0})
    X = NDArray(jnp.zeros((8, 4)))
    Y = NDArray(jnp.zeros((8,)))
    batches = [(X, Y)]
    est = Estimator(net, loss_mod.SoftmaxCrossEntropyLoss(), trainer=trainer,
                    event_handlers=[EarlyStoppingHandler("accuracy",
                                                         patience=2)])
    history = est.fit(batches, val_data=batches, epochs=50)
    assert len(history) < 50  # stopped early (metric flat at lr=0)


def test_bandwidth_tool_runs_and_reports():
    """tools/bandwidth/measure.py produces structured GB/s results on the
    CPU mesh (where it measures host memcpy — documented caveat; the
    tool is validated structurally, numbers are meaningful on ICI)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bw_measure",
        os.path.join(os.path.dirname(__file__), "..", "tools", "bandwidth",
                     "measure.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    res = mod.measure([0.5, 1.0], n_devices=4, runs=2)
    assert len(res) == 2
    for r in res:
        assert set(r) == {"size_mb", "time_ms", "GBps"}
        assert r["time_ms"] > 0 and r["GBps"] > 0
    # bigger buffers should not report wildly discontinuous bandwidth
    assert 0.01 < res[1]["GBps"] / max(res[0]["GBps"], 1e-9) < 100
