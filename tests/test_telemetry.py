"""Telemetry subsystem: registry semantics, histogram percentiles, span
nesting, exporter formats, the disabled no-op path, and the Trainer
integration (real step() reporting through the registry)."""
import json
import math

import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, telemetry
from incubator_mxnet_tpu.gluon import Trainer, nn
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.telemetry import exporters
from incubator_mxnet_tpu.telemetry.registry import (Counter, Gauge, Histogram,
                                                    Registry, log_buckets)


@pytest.fixture
def tel():
    """Enabled telemetry with a clean slate, restored to OFF after."""
    telemetry.enable()
    telemetry.get_registry().clear()
    telemetry.tracer.clear()
    yield telemetry
    telemetry.get_registry().clear()
    telemetry.tracer.clear()
    telemetry.disable()


# --------------------------------------------------------------------- #
# registry semantics
# --------------------------------------------------------------------- #
def test_counter_gauge_basics(tel):
    c = tel.counter("reqs_total")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    g = tel.gauge("depth")
    g.set(7)
    g.inc()
    g.dec(3)
    assert g.value == 5.0


def test_get_or_create_is_idempotent_and_label_keyed(tel):
    a = tel.counter("x_total", labels={"k": "a"})
    b = tel.counter("x_total", labels={"k": "b"})
    assert a is not b
    assert tel.counter("x_total", labels={"k": "a"}) is a
    # label order must not matter
    g1 = tel.gauge("y", labels={"p": "1", "q": "2"})
    g2 = tel.gauge("y", labels={"q": "2", "p": "1"})
    assert g1 is g2


def test_kind_conflict_raises(tel):
    tel.counter("dual")
    with pytest.raises(TypeError, match="already registered as counter"):
        tel.gauge("dual")


def test_reset_zeroes_but_keeps_registrations(tel):
    c = tel.counter("z_total")
    c.inc(9)
    tel.reset()
    assert tel.counter("z_total") is c
    assert c.value == 0.0


# --------------------------------------------------------------------- #
# histogram
# --------------------------------------------------------------------- #
def test_log_buckets_cover_range():
    b = log_buckets(1e-3, 1e1, per_decade=2)
    assert b[0] == pytest.approx(1e-3)
    assert b[-1] >= 1e1
    assert list(b) == sorted(b)


def test_histogram_counts_and_overflow(tel):
    h = tel.histogram("lat", buckets=[0.1, 1.0, 10.0])
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(56.05)
    assert h.bucket_counts() == [1, 2, 1, 1]  # last = +Inf overflow


def test_histogram_percentiles_within_observed_range(tel):
    h = tel.histogram("step_s")
    vals = [0.01 * (i + 1) for i in range(100)]  # 0.01 .. 1.0
    for v in vals:
        h.observe(v)
    p = h.percentiles()
    assert 0.01 <= p["p50"] <= 1.0
    assert p["p50"] < p["p95"] <= p["p99"]
    # interpolation never exceeds the observed extremes
    assert p["p99"] <= max(vals)
    assert h.percentile(0.0) >= min(vals)


def test_histogram_empty_is_nan(tel):
    assert math.isnan(tel.histogram("never").percentile(0.5))


# --------------------------------------------------------------------- #
# disabled path is a no-op
# --------------------------------------------------------------------- #
def test_disabled_updates_are_dropped():
    telemetry.disable()
    r = Registry()
    c = r.counter("off_total")
    g = r.gauge("off_g")
    h = r.histogram("off_h")
    c.inc()
    g.set(5)
    h.observe(1.0)
    assert c.value == 0.0 and g.value == 0.0 and h.count == 0


def test_disabled_span_records_nothing():
    telemetry.disable()
    telemetry.tracer.clear()
    with telemetry.span("ghost"):
        pass
    assert telemetry.spans() == []


def test_decorator_bound_while_disabled_follows_toggle(tel):
    tel.disable()

    @telemetry.span("late_bind")
    def fn():
        return 42

    assert fn() == 42
    assert telemetry.spans() == []
    tel.enable()
    assert fn() == 42
    assert [s.name for s in telemetry.spans()] == ["late_bind"]


# --------------------------------------------------------------------- #
# span nesting / steps
# --------------------------------------------------------------------- #
def test_span_nesting_depth_and_parent(tel):
    with tel.span("outer"):
        with tel.span("inner"):
            pass
    recs = {s.name: s for s in tel.spans()}
    assert recs["inner"].depth == 1 and recs["inner"].parent == "outer"
    assert recs["outer"].depth == 0 and recs["outer"].parent is None
    # inner finished first, and is contained in outer's interval
    assert recs["outer"].t0 <= recs["inner"].t0
    assert recs["inner"].t0 + recs["inner"].dur \
        <= recs["outer"].t0 + recs["outer"].dur + 1e-9


def test_mark_step_groups_spans(tel):
    tel.mark_step()
    with tel.span("a"):
        pass
    tel.mark_step()
    with tel.span("b"):
        pass
    assert [s.name for s in tel.spans(step=1)] == ["a"]
    assert [s.name for s in tel.spans(step=2)] == ["b"]
    assert tel.current_step() == 2


# --------------------------------------------------------------------- #
# exporters
# --------------------------------------------------------------------- #
def test_prometheus_text_format(tel):
    tel.counter("bytes_total", labels={"dir": "push"}).inc(128)
    tel.gauge("monitor/fc1/mean_abs").set(0.5)
    h = tel.histogram("lat_s", buckets=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    text = exporters.prometheus_text(tel.get_registry())
    assert '# TYPE bytes_total counter' in text
    assert 'bytes_total{dir="push"} 128.0' in text
    # slashes sanitized, original kept in HELP
    assert "# HELP monitor_fc1_mean_abs" in text
    assert "monitor_fc1_mean_abs 0.5" in text
    # cumulative buckets ending at +Inf == count
    assert 'lat_s_bucket{le="0.1"} 1' in text
    assert 'lat_s_bucket{le="1.0"} 2' in text
    assert 'lat_s_bucket{le="+Inf"} 2' in text
    assert "lat_s_count 2" in text


def test_jsonl_lines_parse_and_carry_percentiles(tel):
    tel.counter("n_total").inc(3)
    h = tel.histogram("d_s")
    h.observe(0.2)
    recs = [json.loads(l) for l in exporters.jsonl_lines(tel.get_registry())]
    by_name = {r["name"]: r for r in recs}
    assert by_name["n_total"]["value"] == 3.0
    assert by_name["d_s"]["count"] == 1
    assert by_name["d_s"]["p50"] == pytest.approx(0.2, rel=0.3)


def test_dump_writes_all_three_files(tel, tmp_path):
    tel.counter("one_total").inc()
    with tel.span("dumped"):
        pass
    paths = tel.dump(str(tmp_path))
    assert "one_total 1.0" in open(paths["prom"]).read()
    lines = [json.loads(l) for l in open(paths["jsonl"])]
    assert lines
    trace = json.load(open(paths["trace"]))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "dumped" in names


def test_chrome_trace_merges_profiler_events(tel):
    from incubator_mxnet_tpu import profiler

    was = profiler._config["aggregate_stats"]
    profiler.set_config(aggregate_stats=True)
    try:
        with tel.span("host_side"):
            pass
        profiler.record_host_event("prof_ev", "event", 0.0, 0.001)
    finally:
        profiler.set_config(aggregate_stats=was)
    trace = exporters.chrome_trace()
    cats = {e["name"]: e.get("cat") for e in trace["traceEvents"]}
    assert cats.get("host_side") == "telemetry"
    assert cats.get("prof_ev") == "event"  # profiler events interleave
    # the span was mirrored into the profiler stream too — the merge
    # must dedup it, not show it twice
    assert sum(1 for e in trace["traceEvents"]
               if e["name"] == "host_side") == 1


# --------------------------------------------------------------------- #
# integration: Trainer / Speedometer / Monitor
# --------------------------------------------------------------------- #
def test_trainer_step_reports_metrics_and_nested_spans(tel):
    mx.random.seed(0)
    net = nn.Dense(4)
    net.initialize()
    # fuse_step=False exercises the kvstore push/pull path
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 fuse_step=False)
    x = NDArray(jnp.ones((2, 3)))
    for _ in range(3):
        with autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        tr.step(2)
    assert tel.histogram("trainer_step_seconds").count == 3
    assert tel.counter("trainer_steps_total").value == 3
    assert tel.counter("kvstore_push_bytes_total").value > 0
    assert tel.counter("kvstore_pull_bytes_total").value > 0
    assert tel.histogram("kvstore_push_seconds").count > 0
    by_name = {}
    for s in tel.spans():
        by_name.setdefault(s.name, s)
    assert "trainer/step" in by_name
    inner = by_name.get("trainer/allreduce") or by_name.get("trainer/update")
    assert inner is not None and inner.parent == "trainer/step"
    assert tel.current_step() == 3


def test_speedometer_reports_through_telemetry(tel, caplog):
    import collections
    import logging

    from incubator_mxnet_tpu import callback

    P = collections.namedtuple("P", ["epoch", "nbatch", "eval_metric",
                                     "locals"])
    sp = callback.Speedometer(batch_size=8, frequent=2)
    with caplog.at_level(logging.INFO):
        for i in range(1, 5):
            sp(P(0, i, None, None))
    g = tel.get_registry().get("speedometer_samples_per_sec")
    assert g is not None and g.value > 0
    h = tel.get_registry().get("speedometer_step_seconds")
    assert h is not None and h.count == 2
    # the printed line format is unchanged
    assert any("Speed:" in r.message and "samples/sec" in r.message
               for r in caplog.records)


def test_monitor_batches_host_fetch_and_sets_gauges(tel, monkeypatch):
    import jax

    from incubator_mxnet_tpu.monitor import Monitor

    calls = []
    real = jax.device_get

    def counting(x):
        calls.append(x)
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    mon = Monitor(interval=1)
    mon.tic()
    mon.activated = True
    mon._capture_tree("fc1_output", NDArray(jnp.ones((2, 3))))
    mon._capture_tree("fc2_output", NDArray(2 * jnp.ones((4,))))
    res = mon.toc()
    assert [(n, v) for _, n, v in res] == [("fc1_output", 1.0),
                                           ("fc2_output", 2.0)]
    # ONE batched transfer for both captured arrays
    assert len(calls) == 1
    g = tel.get_registry().get("monitor/fc1_output/mean_abs")
    assert g is not None and g.value == pytest.approx(1.0)


def test_pipeline_schedule_gauges(tel):
    from incubator_mxnet_tpu.parallel.pipeline import _record_schedule

    _record_schedule("gpipe", 4, 8)
    _record_schedule("1f1b", 4, 8)
    reg = tel.get_registry()
    assert reg.get("pipeline_bubble_fraction",
                   {"schedule": "gpipe"}).value == pytest.approx(3 / 11)
    assert reg.get("pipeline_bubble_fraction",
                   {"schedule": "1f1b"}).value == pytest.approx(6 / 22)
    assert reg.get("pipeline_stages", {"schedule": "1f1b"}).value == 4
    assert reg.get("pipeline_bubble_ticks",
                   {"schedule": "1f1b"}).value == 6


def test_nbytes_of_uses_aval_metadata_only(tel):
    x = jnp.ones((4, 8), jnp.float32)
    assert tel.nbytes_of(x) == 4 * 8 * 4
    assert tel.nbytes_of(NDArray(jnp.ones((2,), jnp.bfloat16))._data) == 4
    assert tel.nbytes_of(object()) == 0


# --------------------------------------------------------------------- #
# exporter label hygiene (ISSUE 8 satellites)
# --------------------------------------------------------------------- #
def test_prometheus_label_values_are_escaped(tel):
    tel.counter("esc_total",
                labels={"path": 'C:\\tmp\\"x"\nnext'}).inc()
    text = exporters.prometheus_text(tel.get_registry())
    # backslash → \\, quote → \", newline → \n; the line stays one line
    assert 'path="C:\\\\tmp\\\\\\"x\\"\\nnext"' in text
    for line in text.splitlines():
        if line.startswith("esc_total"):
            assert line.endswith(" 1.0")
            break
    else:
        raise AssertionError(f"no esc_total sample line in:\n{text}")


def test_prometheus_duplicate_timeseries_dropped(tel):
    # two distinct registry names sanitize to the SAME exposition name:
    # the second sample would be invalid exposition and must be dropped
    tel.gauge("a/b").set(1.0)
    tel.gauge("a_b").set(2.0)
    text = exporters.prometheus_text(tel.get_registry())
    assert text.count("\na_b ") + text.count("a_b ") >= 1
    samples = [l for l in text.splitlines()
               if l.startswith("a_b ") or l.startswith("a_b{")]
    assert len(samples) == 1, f"duplicate series survived: {samples}"
    assert "# duplicate timeseries dropped" in text
    # same-name different-labels is NOT a duplicate
    tel.gauge("c", labels={"k": "1"}).set(1.0)
    tel.gauge("c", labels={"k": "2"}).set(2.0)
    text = exporters.prometheus_text(tel.get_registry())
    assert 'c{k="1"} 1.0' in text and 'c{k="2"} 2.0' in text


# --------------------------------------------------------------------- #
# histogram edge cases (ISSUE 8 satellites)
# --------------------------------------------------------------------- #
def test_histogram_zero_and_negative_observations(tel):
    h = tel.histogram("edge_s", buckets=[0.1, 1.0])
    h.observe(0.0)
    h.observe(-3.0)
    assert h.count == 2
    assert h.sum == pytest.approx(-3.0)
    assert h.bucket_counts()[0] == 2  # at/below zero land in bucket 0
    p = h.percentile(0.99)
    assert not math.isnan(p)
    assert -3.0 <= p <= 0.1  # clamped to the observed range


def test_histogram_single_sample_percentiles_collapse(tel):
    h = tel.histogram("one_s")
    h.observe(0.42)
    p = h.percentiles()
    # with one sample every percentile is that sample (clamped to the
    # observed min == max), not a bucket-edge interpolation artifact
    assert p["p50"] == pytest.approx(0.42)
    assert p["p95"] == pytest.approx(0.42)
    assert p["p99"] == pytest.approx(0.42)


def test_histogram_cross_thread_observations(tel):
    import threading

    h = tel.histogram("mt_s", buckets=[0.5])
    n, per = 8, 500

    def work():
        for _ in range(per):
            h.observe(0.25)

    threads = [threading.Thread(target=work) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == n * per
    assert h.sum == pytest.approx(0.25 * n * per)
    assert h.bucket_counts()[0] == n * per


# --------------------------------------------------------------------- #
# the ISSUE 8 layer rides the near-zero disabled path
# --------------------------------------------------------------------- #
def test_perf_layer_disabled_overhead_budget():
    import time as _t

    from incubator_mxnet_tpu.telemetry import flight_recorder, perf

    telemetry.disable()
    # earlier telemetry-enabled tests may have captured programs into
    # the module-global table; the invariant here is that the DISABLED
    # path adds nothing, not that the table is empty
    before = dict(perf.programs())
    assert perf.capture("off_prog", None) is None
    assert perf.capture_compiled("off_prog", None) is None
    assert perf.sample_device_memory() == {}
    assert not perf.start_poller()
    n = 20000
    t0 = _t.perf_counter()
    for i in range(n):
        perf.note_timing("off_prog", 0.1)
        flight_recorder._on_step(i)
    per_call = (_t.perf_counter() - t0) / (2 * n)
    # generous CI bound: each disabled call is one flag/attribute read,
    # microseconds would already mean a broken fast path
    assert per_call < 5e-6, f"disabled path costs {per_call * 1e9:.0f} ns/call"
    assert perf.programs() == before
    assert not flight_recorder.installed()
