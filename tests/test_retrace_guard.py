"""RetraceGuard: shape-driven recompilation storms raise; a stable
hybridized training loop stays comfortably inside the budget."""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.gluon import Trainer, loss as gloss, nn
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.retrace_guard import (DEFAULT_BUDGET, PROGRAM_NAMES,
                                               RetraceError, RetraceGuard)


def _make_step():
    # a FRESH function object per test: jax's tracing caches are keyed on
    # the underlying callable, so a shared module-level fn would carry
    # compile counts across tests
    def storm_step(x):
        return x * 2 + 1

    return jax.jit(storm_step)


def test_shape_storm_raises():
    step = _make_step()
    with pytest.raises(RetraceError, match="retrace budget exceeded"):
        with RetraceGuard(budget=3, watch={"storm_step"}):
            for n in range(1, 8):          # 7 distinct shapes -> 7 compiles
                step(jnp.ones((n,)))


def test_stable_shapes_stay_under_budget():
    step = _make_step()
    with RetraceGuard(budget=3, watch={"storm_step"}) as guard:
        for _ in range(50):                # one shape -> one compile
            step(jnp.ones((4,)))
    assert guard.counts["storm_step"] == 1


def test_check_reports_all_offenders():
    step = _make_step()
    guard = RetraceGuard(budget=1, watch={"storm_step"})
    with pytest.raises(RetraceError) as ei:
        with guard:
            for n in range(1, 5):
                step(jnp.ones((n,)))
    assert "storm_step: 4 compiles" in str(ei.value)
    assert guard.violations() == {"storm_step": 4}


def test_unwatched_names_never_trip():
    step = _make_step()
    with RetraceGuard(budget=0, watch={"something_else"}) as guard:
        for n in range(8, 12):
            step(jnp.ones((n,)))
    # still tallied for diagnosis, just not budget-enforced
    assert guard.counts["storm_step"] == 4


def test_stable_training_loop_under_budget():
    """The fused chained step (forward+loss+backward+optimizer) compiles a
    handful of programs on the first iteration and then reuses them."""
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = NDArray(onp.random.RandomState(0).randn(8, 5).astype("float32"))
    y = NDArray(onp.random.RandomState(1).randint(0, 4, 8).astype("int32"))
    loss_fn = gloss.SoftmaxCrossEntropyLoss()

    with RetraceGuard(budget=DEFAULT_BUDGET, watch=PROGRAM_NAMES) as guard:
        net(x)
        net.hybridize()
        tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
        for _ in range(8):
            with autograd.record():
                L = loss_fn(net(x), y)
            L.backward()
            tr.step(1)
    watched = {n: c for n, c in guard.counts.items() if n in PROGRAM_NAMES}
    # every program compiled at most a few times total, nowhere near budget
    assert watched, "guard saw no program compilations at all"
    assert all(c <= 8 for c in watched.values()), watched


def test_telemetry_feed_counts_compiles():
    """With telemetry on, every compile feeds retraces_total and the
    per-program retrace_compiles gauge — concurrently with (and without
    disturbing) the conftest guard's own subscription."""
    from incubator_mxnet_tpu import telemetry

    telemetry.enable()
    telemetry.get_registry().clear()
    try:
        step = _make_step()
        for n in range(1, 4):              # 3 distinct shapes -> 3 compiles
            step(jnp.ones((n,)))
        assert telemetry.counter("retraces_total").value >= 3
        g = telemetry.get_registry().get("retrace_compiles",
                                         {"program": "storm_step"})
        assert g is not None and g.value >= 3
    finally:
        telemetry.get_registry().clear()
        telemetry.disable()


def test_feed_removed_with_disable():
    from incubator_mxnet_tpu import telemetry
    from incubator_mxnet_tpu.retrace_guard import _monitor

    telemetry.enable()
    n_subs = len(_monitor._sinks)
    telemetry.disable()
    assert len(_monitor._sinks) == n_subs - 1
