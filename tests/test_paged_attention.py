"""Decode-side paged-attention kernel stack (`ops/paged_attention.py`,
ISSUE 15): single-query Pallas kernel + int8 KV pools + small-T fused
attention.

The load-bearing contracts:

* **Kernel/dense parity** — the online-softmax Pallas kernel (grid over
  (lane, head), KV pages read straight from the pool) agrees with the
  dense-gather reference to fp32 roundoff for ragged per-lane lengths
  and permuted block tables, in f32 and bf16, with and without int8
  pages.
* **Path isolation** — an engine runs ONE attention impl for its whole
  life; within the forced-pallas path eviction bit-identity holds
  exactly, and across paths greedy tokens agree (dispatch never mixes
  impls, so the cheaper CPU contract — byte-identity on the dense
  default — is pinned in test_serving.py and untouched here).
* **int8 KV quality/capacity** — engine-level greedy parity >= 95% vs
  the float-KV engine, teacher-forced perplexity delta <= 0.5% under
  KV fake-quant, and >= 1.8x resident sequences at equal pool bytes vs
  bf16 KV.
* **Small-T fused path** — `attention_small_t` matches the reference
  within bf16 tolerance and its dispatch gate only opens on TPU below
  the Pallas crossover.
"""
import math
import time

import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.contrib.quantization import quantize_kv
from incubator_mxnet_tpu.models import generation as G
from incubator_mxnet_tpu.models.transformer import TransformerLM
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.ops.flash_attention import (_use_small_t,
                                                     attention_reference,
                                                     attention_small_t,
                                                     flash_attention)
from incubator_mxnet_tpu.ops.paged_attention import (default_impl,
                                                     paged_attention,
                                                     paged_attention_dense)
from incubator_mxnet_tpu.serving import ServingEngine

V, C, DFF, L, H, MAXLEN = 61, 16, 32, 1, 2, 64
P1 = onp.array([3, 7, 11, 2, 9], onp.int32)
P2 = onp.array([5, 1, 2], onp.int32)
_POLL = 0.001


# --------------------------------------------------------------------- #
# kernel-level parity vs the dense-gather reference
# --------------------------------------------------------------------- #
def _rand_pool(key, shape, dtype):
    return jax.random.normal(key, shape).astype(dtype)


def _paged_case(seed, B=3, heads=2, D=16, bs=8, nbps=4, dtype=jnp.float32):
    """Random pool + permuted tables + ragged per-lane positions."""
    keys = jax.random.split(jax.random.PRNGKey(seed), 4)
    nblocks = B * nbps + 3  # spare blocks hold garbage the walk must skip
    pool_k = _rand_pool(keys[0], (nblocks, heads, bs, D), dtype)
    pool_v = _rand_pool(keys[1], (nblocks, heads, bs, D), dtype)
    q = _rand_pool(keys[2], (B, heads, D), dtype)
    tables = jax.random.permutation(keys[3],
                                    jnp.arange(B * nbps, dtype=jnp.int32))
    tables = tables.reshape(B, nbps)
    # ragged: lane 0 one token, lane 1 mid-block, lane 2 pool-full
    pos = jnp.array([0, bs + 3, bs * nbps - 1][:B], jnp.int32)
    return q, pool_k, pool_v, tables, pos


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-5),
                                       (jnp.bfloat16, 2e-2)])
def test_pallas_kernel_matches_dense_ragged(dtype, tol):
    q, pk, pv, tables, pos = _paged_case(0, dtype=dtype)
    dense = paged_attention(q, pk, pv, tables, pos, impl="dense")
    pallas = paged_attention(q, pk, pv, tables, pos, impl="pallas",
                             interpret=True)
    assert pallas.dtype == q.dtype and pallas.shape == q.shape
    onp.testing.assert_allclose(onp.asarray(pallas, onp.float32),
                                onp.asarray(dense, onp.float32), atol=tol)


def test_pallas_kernel_matches_dense_int8_pages():
    q, pk, pv, tables, pos = _paged_case(1)
    qk, sk = quantize_kv(pk)
    qv, sv = quantize_kv(pv)
    dense = paged_attention(q, qk, qv, tables, pos,
                            scale_k=sk, scale_v=sv, impl="dense")
    pallas = paged_attention(q, qk, qv, tables, pos,
                             scale_k=sk, scale_v=sv, impl="pallas",
                             interpret=True)
    onp.testing.assert_allclose(onp.asarray(pallas), onp.asarray(dense),
                                atol=2e-5)
    # quantization error itself stays small vs the float pool
    ref = paged_attention(q, pk, pv, tables, pos, impl="dense")
    onp.testing.assert_allclose(onp.asarray(dense), onp.asarray(ref),
                                atol=0.05)


def test_paged_attention_validates_impl():
    q, pk, pv, tables, pos = _paged_case(2, B=1, nbps=1)
    with pytest.raises(ValueError):
        paged_attention(q, pk, pv, tables, pos, impl="banana")
    assert default_impl("tpu") == "pallas"
    assert default_impl("cpu") == "dense"


def test_quantize_kv_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(3), (5, 2, 8, 16)) * 4.0
    qx, scale = quantize_kv(x)
    assert qx.dtype == jnp.int8 and scale.dtype == jnp.float32
    assert scale.shape == x.shape[:-1]
    back = qx.astype(jnp.float32) * scale[..., None]
    err = onp.abs(onp.asarray(back - x))
    # symmetric per-vector int8: error bounded by half a quant step
    bound = onp.asarray(scale)[..., None] * 0.5 + 1e-7
    assert (err <= bound).all()
    # all-zero vectors survive (amax clamp, no division blow-up)
    qz, sz = quantize_kv(jnp.zeros((2, 3)))
    assert (onp.asarray(qz) == 0).all() and onp.isfinite(onp.asarray(sz)).all()


# --------------------------------------------------------------------- #
# engine-level: forced-pallas path
# --------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def net():
    mx.random.seed(0)
    n = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                      num_heads=H, max_len=MAXLEN, dropout=0.0)
    n.initialize()
    n(NDArray(jnp.ones((1, 4), jnp.int32)))
    return n


@pytest.fixture(scope="module")
def pallas_engine(net):
    eng = ServingEngine(net, max_batch=2, block_size=8,
                        attn_impl="pallas", poll_interval=_POLL)
    assert eng.attn_impl == "pallas"
    yield eng
    try:
        eng.close()
    except Exception:
        pass


def _slow_step(seconds):
    def hook(phase):
        if phase == "step":
            time.sleep(seconds)
    return hook


def _wait(pred, timeout=30.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.002)
    return False


def test_pallas_engine_cobatched_matches_dense_engine(net, pallas_engine):
    """Co-batched prefill+decode under the kernel path agrees with the
    dense-gather engine on greedy tokens (fp32-roundoff softmax
    differences may flip a near-tie, hence >= rather than ==)."""
    with net.serve(max_batch=2, block_size=8, poll_interval=_POLL) as ref:
        assert ref.attn_impl == "dense"
        ra, rb = ref.submit(P1, 10), ref.submit(P2, 10)
        base_a, base_b = ra.result(timeout=60), rb.result(timeout=60)
    pa, pb = pallas_engine.submit(P1, 10), pallas_engine.submit(P2, 10)
    got_a, got_b = pa.result(timeout=60), pb.result(timeout=60)
    pallas_engine.drain(timeout=30)
    hits = sum(x == y for x, y in zip(got_a + got_b, base_a + base_b))
    assert hits / 20 >= 0.9, (got_a, got_b, base_a, base_b)


def test_eviction_bit_identity_under_pallas(pallas_engine):
    """The eviction-exactness contract survives the kernel path: a
    cancelled neighbour leaves the survivor byte-identical (within the
    SAME impl — the guarantee dispatch must not silently break)."""
    from incubator_mxnet_tpu.serving import RequestCancelled
    eng = pallas_engine
    ra, rb = eng.submit(P1, 10), eng.submit(P2, 10)
    base = ra.result(timeout=60)
    rb.result(timeout=60)
    assert eng.drain(timeout=30)
    eng.set_fault_hook(_slow_step(0.02))
    ra, rb = eng.submit(P1, 10), eng.submit(P2, 10)
    assert _wait(lambda: len(rb.tokens) >= 3)
    rb.cancel()
    assert ra.result(timeout=60) == base
    with pytest.raises(RequestCancelled):
        rb.result(timeout=60)
    eng.set_fault_hook(None)
    assert eng.submit(P1, 10).result(timeout=60) == base
    eng.drain(timeout=30)


# --------------------------------------------------------------------- #
# int8 KV pools: quality + capacity
# --------------------------------------------------------------------- #
def test_int8_kv_engine_greedy_parity(net):
    prompts = [P1, P2, onp.array([2, 9, 4, 1], onp.int32)]
    with net.serve(max_batch=2, block_size=8, poll_interval=_POLL) as ref:
        base = [ref.submit(p, 12).result(timeout=60) for p in prompts]
    kv8 = ServingEngine(net, max_batch=2, block_size=8,
                        kv_dtype="int8", poll_interval=_POLL)
    try:
        assert kv8.kv_dtype == "int8"
        got = [kv8.submit(p, 12).result(timeout=60) for p in prompts]
    finally:
        kv8.close()
    tot = sum(len(t) for t in base)
    hits = sum(a == b for ta, tb in zip(base, got) for a, b in zip(ta, tb))
    assert hits / tot >= 0.95, f"int8-KV greedy parity {hits}/{tot}"


def test_int8_kv_perplexity_delta():
    """Teacher-forced fake-quant of K/V (exactly what the pool stores)
    moves held-out perplexity by <= 0.5%."""
    mx.random.seed(1)
    net = TransformerLM(vocab=97, units=32, hidden_size=64, num_layers=2,
                        num_heads=4, max_len=64, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))
    held = onp.array(jax.random.randint(jax.random.PRNGKey(17), (4, 32),
                                        0, 97), dtype="int32")
    acts = tuple(lyr.ffn._act for lyr in net._layers)

    def tf_logits(fake):
        p = G._gather_params(net, held.shape[1])
        dt = p["embed"].dtype
        B, T = held.shape
        units = p["embed"].shape[1]
        h = p["embed"][held].astype(dt) * math.sqrt(units) \
            + p["pe"][:T].astype(dt)
        for lp, act in zip(p["layers"], acts):
            x = G._ln(h, *lp["ln1"])
            q, k, v = G._qkv_heads(G._dense(x, *lp["qkv"]), 4)
            kt = k.transpose(0, 2, 1, 3)
            vt = v.transpose(0, 2, 1, 3)
            if fake:
                qk, sk = quantize_kv(kt)
                qv, sv = quantize_kv(vt)
                kt = (qk.astype(jnp.float32) * sk[..., None]).astype(dt)
                vt = (qv.astype(jnp.float32) * sv[..., None]).astype(dt)
            a = flash_attention(q.transpose(0, 2, 1, 3), kt, vt,
                                causal=True).transpose(0, 2, 1, 3)
            h = h + G._dense(a.astype(dt).reshape(B, T, units), *lp["proj"])
            h = h + G._ffn_fwd(G._ln(h, *lp["ln2"]), lp, act)
        return G._logits_of(p, h.reshape(B * T, units)).reshape(B, T, -1)

    def ppl(logits):
        lp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        nll = -jnp.take_along_axis(
            lp, jnp.asarray(held[:, 1:, None]), axis=-1).mean()
        return float(jnp.exp(nll))

    ppl_f, ppl_q = ppl(tf_logits(False)), ppl(tf_logits(True))
    delta = abs(ppl_q - ppl_f) / ppl_f
    assert delta <= 0.005, \
        f"KV-quant perplexity delta {delta:.4%} > 0.5% " \
        f"(float {ppl_f:.3f}, int8-KV {ppl_q:.3f})"


def test_int8_kv_capacity_vs_bf16_at_equal_bytes():
    """ISSUE 15 acceptance: at equal pool bytes, int8 KV holds >= 1.8x
    the resident sequences of bf16 KV (D=64 so the per-vector fp32
    scale amortizes: 128 B vs 64+4 B per head-token)."""
    mx.random.seed(2)
    net = TransformerLM(vocab=31, units=128, hidden_size=64, num_layers=1,
                        num_heads=2, max_len=64, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))
    net.cast("bfloat16")
    bf = ServingEngine(net, max_batch=1, block_size=8)
    q8 = ServingEngine(net, max_batch=1, block_size=8, kv_dtype="int8")
    try:
        budget = bf.kv_pool_bytes
        nbps = bf.max_seq_len // 8
        res_bf = bf.stats()["blocks_total"] // nbps
        # blocks an int8 pool fits into the SAME byte budget
        res_q8 = (budget // q8.kv_block_bytes) // nbps
        ratio = res_q8 / res_bf
        assert ratio >= 1.8, \
            f"int8 KV fits only {ratio:.2f}x bf16 residents " \
            f"({bf.kv_bytes_per_token} vs {q8.kv_bytes_per_token} B/token)"
        assert bf.kv_bytes_per_token / q8.kv_bytes_per_token >= 1.8
    finally:
        bf.close()
        q8.close()


# --------------------------------------------------------------------- #
# small-T fused attention
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("causal", [False, True])
def test_small_t_fused_matches_reference(causal):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(5), 3)
    shape = (2, 2, 160, 32)  # 160^2 sits inside [128^2, 512^2)
    q = jax.random.normal(k1, shape).astype(jnp.bfloat16)
    k = jax.random.normal(k2, shape).astype(jnp.bfloat16)
    v = jax.random.normal(k3, shape).astype(jnp.bfloat16)
    ref = attention_reference(q, k, v, causal=causal)
    got = attention_small_t(q, k, v, causal=causal)
    assert got.dtype == q.dtype
    onp.testing.assert_allclose(onp.asarray(got, onp.float32),
                                onp.asarray(ref, onp.float32),
                                atol=3e-2, rtol=3e-2)


def test_small_t_dispatch_gate():
    bf16, f32 = jnp.bfloat16, jnp.float32
    assert _use_small_t("tpu", 160, 160, bf16)
    assert _use_small_t("tpu", 128, 128, bf16)          # lower edge in
    assert not _use_small_t("tpu", 64, 64, bf16)        # tiny: XLA wins
    assert not _use_small_t("tpu", 512, 512, bf16)      # Pallas crossover
    assert not _use_small_t("cpu", 160, 160, bf16)      # never on CPU
    assert not _use_small_t("tpu", 160, 160, f32)       # bf16-only path
