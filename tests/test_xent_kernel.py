"""Fused sparse softmax-xent: kernel math (interpret mode), public op
routing, and gluon loss integration.

The kernel uses no TPU-only primitives, so interpret mode runs the REAL
kernel on CPU — unlike the dropout kernel, CI covers the Mosaic-side
math here, not just a reference branch.  (TPU-compiled parity is pinned
live by benchmark/xent_tpu_smoke.py-style checks in bert_ablate runs.)
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

from incubator_mxnet_tpu.ops import xent_kernel as xk


def _oracle(x, lab):
    xf = x.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(xf, axis=-1)
    pick = jnp.take_along_axis(xf, lab[..., None], axis=-1)[..., 0]
    return lse - pick, lse


@pytest.mark.parametrize("N,V,dt", [
    (256, 1000, jnp.float32),
    (128, 3841, jnp.bfloat16),   # ragged vocab tail
    (8, 130, jnp.float32),       # tiny, single ragged block
    (24, 515, jnp.bfloat16),     # rows not a multiple of 8 -> br=8 path
    (16, 128, jnp.float32),      # exact single block, no tail masking
])
def test_kernel_interpret_fwd_bwd_parity(N, V, dt):
    x = (jax.random.normal(jax.random.PRNGKey(0), (N, V), jnp.float32)
         * 3).astype(dt)
    lab = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, V)
    nll, lse = xk.run_interpret(x, lab)
    nll_ref, lse_ref = _oracle(x, lab)
    onp.testing.assert_allclose(onp.asarray(nll), onp.asarray(nll_ref),
                                rtol=2e-5, atol=2e-5)
    onp.testing.assert_allclose(onp.asarray(lse), onp.asarray(lse_ref),
                                rtol=2e-5, atol=2e-5)

    g = jax.random.normal(jax.random.PRNGKey(2), (N,), jnp.float32)
    dx = xk.run_interpret_bwd(x, lab, lse_ref, g)
    xf = x.astype(jnp.float32)
    dx_ref = ((jnp.exp(xf - lse_ref[:, None])
               - jax.nn.one_hot(lab, V, dtype=jnp.float32))
              * g[:, None]).astype(dt)
    onp.testing.assert_allclose(onp.asarray(dx.astype(jnp.float32)),
                                onp.asarray(dx_ref.astype(jnp.float32)),
                                rtol=2e-2, atol=2e-2)


def test_extreme_logits_stable():
    """Online-softmax must survive +-large logits and -inf-free rows."""
    x = jnp.array([[8e4, -8e4, 0.0, 1.0] + [0.0] * 124,
                   [-8e4] * 128], jnp.float32)
    lab = jnp.array([0, 3])
    nll, lse = xk.run_interpret(x, lab)
    nll_ref, _ = _oracle(x, lab)
    assert onp.isfinite(onp.asarray(nll)).all()
    onp.testing.assert_allclose(onp.asarray(nll), onp.asarray(nll_ref),
                                rtol=1e-6, atol=1e-6)


def test_public_op_grad_matches_oracle():
    """fused_sparse_xent through jax.grad (CPU reference branch)."""
    N, V = 64, 777
    x = jax.random.normal(jax.random.PRNGKey(0), (N, V), jnp.float32)
    lab = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, V)

    g1 = jax.grad(lambda x: xk.fused_sparse_xent(x, lab).mean())(x)
    g2 = jax.grad(lambda x: _oracle(x, lab)[0].mean())(x)
    onp.testing.assert_allclose(onp.asarray(g1), onp.asarray(g2),
                                rtol=1e-5, atol=1e-6)


def test_public_op_3d_leading_dims():
    B, T, V = 4, 8, 600
    x = jax.random.normal(jax.random.PRNGKey(0), (B, T, V), jnp.float32)
    lab = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, V)
    nll = xk.fused_sparse_xent(x, lab)
    assert nll.shape == (B, T)
    ref = _oracle(x.reshape(-1, V), lab.reshape(-1))[0].reshape(B, T)
    onp.testing.assert_allclose(onp.asarray(nll), onp.asarray(ref),
                                rtol=1e-5, atol=1e-6)


def test_gluon_loss_routing_gate():
    """SoftmaxCrossEntropyLoss: the fused gate only opens on TPU
    backends for large-V last-axis sparse labels — and the CPU value
    equals the jnp path regardless."""
    from incubator_mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    loss = SoftmaxCrossEntropyLoss()
    p = jax.random.normal(jax.random.PRNGKey(0), (4, 6, 700), jnp.float32)
    smalls = jax.random.normal(jax.random.PRNGKey(0), (4, 6, 10), jnp.float32)
    # gate shape logic (backend-independent pieces)
    assert p.shape[-1] >= xk.FUSED_MIN_CLASSES
    assert smalls.shape[-1] < xk.FUSED_MIN_CLASSES
    lab = jax.random.randint(jax.random.PRNGKey(1), (4, 6), 0, 10)
    out = loss(NDArray(smalls), NDArray(lab))
    ref = -jnp.take_along_axis(jax.nn.log_softmax(smalls, -1),
                               lab[..., None], axis=-1)[..., 0].mean(-1)
    onp.testing.assert_allclose(onp.asarray(out.asnumpy()),
                                onp.asarray(ref), rtol=1e-5, atol=1e-6)


def test_nd_softmax_cross_entropy_value():
    """mx.nd.softmax_cross_entropy unchanged semantics (sum of nll)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    x = jax.random.normal(jax.random.PRNGKey(0), (32, 600), jnp.float32)
    lab = jax.random.randint(jax.random.PRNGKey(1), (32,), 0, 600)
    out = mx.nd.softmax_cross_entropy(NDArray(x), NDArray(lab))
    ref = float(_oracle(x, lab)[0].sum())
    assert abs(float(out.asnumpy()) - ref) < 1e-3 * abs(ref)


def _smooth_oracle(x, lab, eps):
    """Dense log_softmax-based smoothed CE (the pre-r5 LabelSmoothedCELoss
    math) — what the streamed kernel must reproduce."""
    xf = x.astype(jnp.float32)
    logp = jax.nn.log_softmax(xf, axis=-1)
    nll = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
    smooth = -jnp.mean(logp, axis=-1)
    return (1 - eps) * nll + eps * smooth


@pytest.mark.parametrize("N,V,dt,eps", [
    (128, 1000, jnp.float32, 0.1),
    (64, 3841, jnp.bfloat16, 0.1),    # ragged vocab tail: sum-mask path
    (24, 515, jnp.bfloat16, 0.3),     # br=8 rows, large eps
])
def test_kernel_interpret_smoothed_parity(N, V, dt, eps):
    x = (jax.random.normal(jax.random.PRNGKey(0), (N, V), jnp.float32)
         * 3).astype(dt)
    lab = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, V)
    loss, lse = xk.run_interpret(x, lab, smoothing=eps)
    ref = _smooth_oracle(x, lab, eps)
    onp.testing.assert_allclose(onp.asarray(loss), onp.asarray(ref),
                                rtol=3e-5, atol=3e-5)

    g = jax.random.normal(jax.random.PRNGKey(2), (N,), jnp.float32)
    dx = xk.run_interpret_bwd(x, lab, lse, g, smoothing=eps)
    dx_ref = jax.vmap(lambda xi, li, gi: gi * jax.grad(
        lambda z: _smooth_oracle(z[None], li[None], eps)[0])(xi))(
        x.astype(jnp.float32), lab, g).astype(dt)
    onp.testing.assert_allclose(onp.asarray(dx.astype(jnp.float32)),
                                onp.asarray(dx_ref.astype(jnp.float32)),
                                rtol=2e-2, atol=2e-2)


def test_smoothed_public_op_grad_matches_oracle():
    """fused_smoothed_xent through jax.grad (CPU reference branch)."""
    N, V, eps = 48, 777, 0.1
    x = jax.random.normal(jax.random.PRNGKey(0), (N, V), jnp.float32)
    lab = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, V)

    g1 = jax.grad(lambda x: xk.fused_smoothed_xent(x, lab, eps).mean())(x)
    g2 = jax.grad(lambda x: _smooth_oracle(x, lab, eps).mean())(x)
    onp.testing.assert_allclose(onp.asarray(g1), onp.asarray(g2),
                                rtol=1e-5, atol=1e-6)
    # eps=0 degenerates to the plain sparse xent
    v0 = xk.fused_smoothed_xent(x, lab, 0.0)
    onp.testing.assert_allclose(onp.asarray(v0),
                                onp.asarray(xk.fused_sparse_xent(x, lab)),
                                rtol=1e-6, atol=1e-6)


def test_label_smoothed_loss_block_fused_gate():
    """models.transformer.LabelSmoothedCELoss: value identical whether
    the streamed path would fuse or not (CPU exercises the reference
    branch of the same decomposition) + ignore_index rows drop out."""
    from incubator_mxnet_tpu.models.transformer import LabelSmoothedCELoss
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    B, T, V, eps = 3, 5, 900, 0.1
    x = jax.random.normal(jax.random.PRNGKey(0), (B, T, V), jnp.float32)
    lab = onp.array(
        jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, V))
    lab[0, :2] = -1  # ignored positions
    loss = LabelSmoothedCELoss(smoothing=eps)
    out = float(loss(NDArray(x), NDArray(jnp.asarray(lab))).asnumpy())
    per = onp.asarray(_smooth_oracle(x, jnp.asarray(lab) % V, eps))
    valid = (lab != -1)
    ref = float((per * valid).sum() / valid.sum())
    assert abs(out - ref) < 1e-4 * abs(ref)
