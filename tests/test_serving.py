"""Continuous-batching serving engine (`serving/`): the robustness
envelope (ISSUE 12).

The load-bearing contracts:

* **Greedy parity** — engine output token-for-token equals
  `lm_generate` (the paged decode re-implements the cached step
  against a shared pool; parity pins its numerics).
* **Eviction bit-identity** — cancelling/timing-out one sequence
  mid-batch leaves survivors' outputs byte-identical to an unperturbed
  run (lanes are independent; masked scratch reads contribute exactly
  0.0), and the freed blocks are reused by a later admission.
* **Overload safety** — a full queue SHEDS (counted, no deadlock), SLO
  estimates shed late requests, deadlines evict mid-batch,
  abandoned streams release their KV blocks, close() joins the
  scheduler thread, and scheduler errors are parked and re-raised.

Everything runs tiny nets, small token counts and 1 ms polls: the
tier-1 870 s budget is nearly saturated, so shared module-scope
engines keep the compile count at a handful.
"""
import threading
import time

import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.models.generation import lm_generate, lm_stream
from incubator_mxnet_tpu.models.transformer import TransformerLM
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.serving import (BlockPool, RequestCancelled,
                                         RequestFailed, RequestShed,
                                         RequestTimedOut, ServingEngine)

V, C, DFF, L, H, MAXLEN = 61, 16, 32, 1, 2, 64
P1 = onp.array([3, 7, 11, 2, 9], onp.int32)
P2 = onp.array([5, 1, 2], onp.int32)
_POLL = 0.001


def _wait(pred, timeout=30.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.002)
    return False


def _slow_step(seconds):
    def hook(phase):
        if phase == "step":
            time.sleep(seconds)
    return hook


@pytest.fixture(scope="module")
def net():
    mx.random.seed(0)
    n = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                      num_heads=H, max_len=MAXLEN, dropout=0.0)
    n.initialize()
    n(NDArray(jnp.ones((1, 4), jnp.int32)))
    return n


@pytest.fixture(scope="module")
def engine(net):
    """The shared float engine: one compiled step program + a couple of
    prefill buckets for the whole module."""
    eng = ServingEngine(net, max_batch=2, block_size=8,
                        poll_interval=_POLL)
    yield eng
    try:
        eng.close()
    except Exception:
        pass


@pytest.fixture
def clean_engine(engine):
    """The shared engine with hooks/budgets reset before AND after."""
    engine.set_fault_hook(None)
    engine.set_ttft_budget(None)
    yield engine
    engine.drain(timeout=30)
    engine.set_fault_hook(None)
    engine.set_ttft_budget(None)


# --------------------------------------------------------------------- #
# block pool accounting
# --------------------------------------------------------------------- #
def test_block_pool_deterministic_and_guarded():
    pool = BlockPool(6)                    # scratch + 5 usable
    assert pool.num_free == 5
    a = pool.alloc(3)
    assert a == [1, 2, 3]                  # lowest-first, deterministic
    assert pool.alloc(3) is None           # all-or-nothing
    pool.free([2])
    assert pool.alloc(1) == [2]            # freed id reused first
    with pytest.raises(ValueError):
        pool.free([2, 2])                  # double free
    with pytest.raises(ValueError):
        pool.free([0])                     # scratch is not freeable
    with pytest.raises(ValueError):
        BlockPool(1)


# --------------------------------------------------------------------- #
# parity + streaming
# --------------------------------------------------------------------- #
def test_greedy_parity_with_lm_generate(net, clean_engine):
    ref = onp.asarray(lm_generate(net, P1[None, :], 8))[0, len(P1):]
    got = clean_engine.submit(P1, 8).result(timeout=60)
    assert got == ref.tolist()
    # co-batched with a second request: both still exact
    r1 = clean_engine.submit(P1, 8)
    r2 = clean_engine.submit(P2, 6)
    ref2 = onp.asarray(lm_generate(net, P2[None, :], 6))[0, len(P2):]
    assert r1.result(timeout=60) == ref.tolist()
    assert r2.result(timeout=60) == ref2.tolist()


def test_lm_stream_yields_and_finishes(net, clean_engine):
    # N=8 reuses the parity test's reference program (per-net LRU)
    ref = onp.asarray(lm_generate(net, P1[None, :], 8))[0, len(P1):]
    toks = list(lm_stream(net, P1, 8, engine=clean_engine))
    assert toks == ref.tolist()


def test_eos_and_single_token_retire(net, clean_engine):
    full = onp.asarray(lm_generate(net, P1[None, :], 8))[0, len(P1):]
    # max_new=1: the prefill emits the only token, no decode step runs;
    # greedy prefix property: it equals token 0 of the longer reference
    assert clean_engine.submit(P1, 1).result(timeout=60) == [int(full[0])]
    # eos freezes a sequence at the first eos token (host-side retire)
    eos = int(full[0])
    old = clean_engine._eos
    clean_engine._eos = eos
    try:
        assert clean_engine.submit(P1, 8).result(timeout=60) == [eos]
    finally:
        clean_engine._eos = old


# --------------------------------------------------------------------- #
# eviction correctness (the acceptance-criterion pair)
# --------------------------------------------------------------------- #
def test_mid_batch_eviction_leaves_survivor_bit_identical(clean_engine):
    eng = clean_engine
    # run A: unperturbed co-batch
    ra = eng.submit(P1, 10)
    rb = eng.submit(P2, 10)
    base = ra.result(timeout=60)
    rb.result(timeout=60)
    assert eng.drain(timeout=30)
    # run B: same submissions (allocator state reset => identical block
    # layout), neighbour cancelled mid-generation
    eng.set_fault_hook(_slow_step(0.02))   # widen the cancel window
    ra = eng.submit(P1, 10)
    rb = eng.submit(P2, 10)
    assert _wait(lambda: len(rb.tokens) >= 3)
    rb.cancel()
    assert ra.result(timeout=60) == base
    with pytest.raises(RequestCancelled):
        rb.result(timeout=60)
    eng.set_fault_hook(None)
    # run C: solo — scratch-block garbage from the neighbour never
    # reaches the survivor (masked positions contribute exactly 0)
    assert eng.submit(P1, 10).result(timeout=60) == base


def test_evicted_blocks_are_reused(clean_engine):
    eng = clean_engine
    eng.set_fault_hook(_slow_step(0.02))
    r1 = eng.submit(P1, 20)
    assert _wait(lambda: r1.status == "running")
    held = set(r1.block_ids)
    assert held
    r1.cancel()
    with pytest.raises(RequestCancelled):
        r1.result(timeout=30)
    eng.set_fault_hook(None)
    r3 = eng.submit(P2, 6)
    r3.result(timeout=60)
    assert set(r3.block_ids) & held       # freed blocks re-allocated
    st = eng.stats()
    assert st["blocks_free"] == st["blocks_total"]
    assert st["evicted"].get("cancel", 0) >= 1


def test_deadline_evicts_mid_batch(clean_engine):
    eng = clean_engine
    eng.set_fault_hook(_slow_step(0.02))
    req = eng.submit(P1, 50, deadline=0.08)
    with pytest.raises(RequestTimedOut):
        req.result(timeout=30)
    assert req.status == "evicted"
    assert 0 < len(req.tokens) < 50       # partial progress, then evicted
    st = eng.stats()
    assert st["evicted"].get("timeout", 0) >= 1


# --------------------------------------------------------------------- #
# overload: bounded queue, shedding, no deadlock
# --------------------------------------------------------------------- #
def test_queue_saturation_sheds_without_deadlock(net):
    eng = ServingEngine(net, max_batch=1, block_size=8, max_queue=2,
                        poll_interval=_POLL,
                        fault_hook=_slow_step(0.02))
    try:
        reqs = [eng.submit(P2, 6) for _ in range(8)]
        shed = [r for r in reqs if r.status == "shed"]
        assert shed                        # bounded queue sheds, not blocks
        for r in shed:
            with pytest.raises(RequestShed) as ei:
                r.result(timeout=5)
            assert ei.value.reason == "queue_full"
        assert eng.drain(timeout=60)       # the admitted ones all finish
        done = [r for r in reqs if r.status == "done"]
        assert len(done) + len(shed) == len(reqs)
        assert eng.stats()["shed"]["queue_full"] == len(shed)
        # blocking submit waits for space instead of shedding
        r = eng.submit(P2, 2, block=True, timeout=30)
        assert r.result(timeout=30)
    finally:
        eng.close()


def test_slo_budget_sheds_estimated_late_requests(clean_engine):
    eng = clean_engine
    # seed the prefill EWMA, then make the TTFT estimate impossible
    eng.submit(P2, 2).result(timeout=60)
    eng.set_fault_hook(_slow_step(0.05))
    occupants = [eng.submit(P1, 12), eng.submit(P2, 12)]  # fill lanes
    assert _wait(lambda: all(r.status == "running" for r in occupants))
    eng.set_ttft_budget(1e-4)              # after the lanes are taken
    late = eng.submit(P2, 4)
    with pytest.raises(RequestShed) as ei:
        late.result(timeout=30)
    assert ei.value.reason == "slo"
    eng.set_ttft_budget(None)
    eng.set_fault_hook(None)
    for r in occupants:
        r.result(timeout=60)


def test_abandoned_stream_releases_blocks(clean_engine):
    eng = clean_engine
    eng.set_fault_hook(_slow_step(0.02))
    req = eng.submit(P1, 30)
    it = req.stream()
    assert isinstance(next(it), int)
    it.close()                             # caller walks away mid-stream
    assert _wait(lambda: eng.stats()["blocks_free"]
                 == eng.stats()["blocks_total"])
    assert req.status == "cancelled"
    eng.set_fault_hook(None)


# --------------------------------------------------------------------- #
# lifecycle: drain/close semantics, error handoff
# --------------------------------------------------------------------- #
def test_close_joins_scheduler_and_rejects_new_work(net):
    eng = ServingEngine(net, max_batch=1, block_size=8,
                        poll_interval=_POLL)
    thread = eng._thread
    eng.close()
    assert not thread.is_alive()           # tpulint TPU012: joined
    with pytest.raises(RuntimeError):
        eng.submit(P2, 2)
    eng.close()                            # idempotent


def test_close_aborts_inflight_requests(net):
    eng = ServingEngine(net, max_batch=1, block_size=8, max_queue=4,
                        poll_interval=_POLL,
                        fault_hook=_slow_step(0.05))
    running = eng.submit(P1, 50)
    queued = eng.submit(P2, 50)
    assert _wait(lambda: running.status == "running")
    eng.close()
    for r in (running, queued):
        assert r.status in ("cancelled",)
        with pytest.raises(RequestCancelled):
            r.result(timeout=5)


def test_scheduler_error_is_parked_and_reraised(net):
    boom = RuntimeError("injected scheduler fault")

    def hook(phase):
        if phase == "step":
            raise boom

    eng = ServingEngine(net, max_batch=1, block_size=8,
                        poll_interval=_POLL, fault_hook=hook)
    req = eng.submit(P2, 8)
    with pytest.raises(RequestFailed):
        req.result(timeout=30)
    assert req.status == "failed"
    with pytest.raises(RequestFailed):     # dead engine refuses work
        eng.submit(P2, 2)
    with pytest.raises(RequestFailed) as ei:
        eng.close()
    assert ei.value.__cause__ is boom
    eng.close()                            # after the re-raise: clean


def test_submit_validation(clean_engine):
    with pytest.raises(ValueError):
        clean_engine.submit(onp.zeros((0,), onp.int32), 2)
    with pytest.raises(ValueError):
        clean_engine.submit(P1, 0)
    with pytest.raises(ValueError):
        clean_engine.submit(P1, MAXLEN)    # P + N > max_seq_len
    with pytest.raises(ValueError):
        ServingEngine(clean_engine._net, max_batch=0)
    with pytest.raises(ValueError):
        ServingEngine(clean_engine._net, block_size=12)  # not a pow2


def test_concurrent_submitters_are_thread_safe(net, clean_engine):
    ref = onp.asarray(lm_generate(net, P2[None, :], 4))[0, len(P2):]
    results = [None] * 6

    def worker(i):
        results[i] = clean_engine.submit(P2, 4,
                                         block=True,
                                         timeout=60).result(timeout=60)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(results))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert all(r == ref.tolist() for r in results)


# --------------------------------------------------------------------- #
# telemetry + int8 path
# --------------------------------------------------------------------- #
def test_serving_metrics_are_recorded(net):
    from incubator_mxnet_tpu import telemetry

    telemetry.enable()
    try:
        reg = telemetry.get_registry()
        eng = ServingEngine(net, max_batch=1, block_size=8, max_queue=1,
                            poll_interval=_POLL,
                            fault_hook=_slow_step(0.02))
        try:
            reqs = [eng.submit(P2, 4) for _ in range(4)]
            assert eng.drain(timeout=60)
            deadline = eng.submit(P1, 50, deadline=0.05)
            with pytest.raises(RequestTimedOut):
                deadline.result(timeout=30)
        finally:
            eng.close()
        assert reg.get("serving_admitted_total").value >= 2
        assert reg.get("serving_shed_total",
                       {"reason": "queue_full"}).value >= 1
        assert reg.get("serving_evicted_total",
                       {"reason": "timeout"}).value >= 1
        assert reg.get("serving_queue_depth") is not None
        assert reg.get("serving_batch_occupancy").value >= 1
        assert reg.get("serving_kv_blocks_in_use") is not None
        ttft = reg.get("serving_ttft_seconds", {"path": "float"})
        tpot = reg.get("serving_tpot_seconds", {"path": "float"})
        assert ttft.snapshot()["count"] >= 1
        assert tpot.snapshot()["count"] >= 1
        # serving-path labels on the existing decode SLO gauges
        assert reg.get("decode_ttft_seconds",
                       {"path": "serving_float"}).value > 0
        del reqs
    finally:
        telemetry.disable()
        telemetry.get_registry().reset()


def test_int8_engine_matches_quantized_lm_generate():
    mx.random.seed(0)
    net = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=MAXLEN, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))
    net.cast("bfloat16")
    net.quantize_for_decode(act_quant="none")
    ref = onp.asarray(lm_generate(net, P1[None, :], 8))[0, len(P1):]
    with net.serve(max_batch=2, block_size=8,
                   poll_interval=_POLL) as eng:
        assert eng._path == "int8"
        assert eng.submit(P1, 8).result(timeout=60) == ref.tolist()
        # serve() caches and reuses the engine for equal config
        assert net.serve() is eng
