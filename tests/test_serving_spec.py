"""Speculative decoding in the serving engine (ISSUE 19).

The load-bearing contracts:

* **Greedy bit-identity** — with ``speculate_k > 0`` and ANY draft
  (including a deliberately bad one that proposes near-garbage), every
  lane's emitted tokens are bit-identical to non-speculative decode
  (`lm_generate` parity), including lanes that survive a mid-burst
  eviction.  Speculation must be a pure throughput lever, never an
  output change.
* **Stochastic exactness** — with temperature sampling, the
  accept/reject + residual-resample recipe keeps the TARGET's output
  distribution: a χ² test over a tiny vocab pins the first
  speculatively-emitted token's marginal against the analytically
  computed one.
* **Accounting** — one `BlockPool` allocation covers both the target
  and draft pools; every block returns on drain, and the worst-case
  reservation covers the k in-flight speculative positions (a
  full-length sequence never writes a neighbour's pages).

Shared module-scope engines keep the compile count at a handful
(tier-1 budget discipline, as in tests/test_serving.py).
"""
import time

import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.models.generation import lm_generate
from incubator_mxnet_tpu.models.transformer import TransformerLM
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.serving import (BlockPool, RequestCancelled,
                                         ServingEngine)

V, C, DFF, L, H, MAXLEN = 61, 16, 32, 1, 2, 64
P1 = onp.array([3, 7, 11, 2, 9], onp.int32)
P2 = onp.array([5, 1, 2], onp.int32)
_POLL = 0.001


def _wait(pred, timeout=30.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.002)
    return False


def _slow_step(seconds):
    def hook(phase):
        if phase == "step":
            time.sleep(seconds)
    return hook


def _mk_net(seed, vocab=V, units=C, hidden=DFF, layers=L, heads=H,
            max_len=MAXLEN):
    mx.random.seed(seed)
    n = TransformerLM(vocab=vocab, units=units, hidden_size=hidden,
                      num_layers=layers, num_heads=heads,
                      max_len=max_len, dropout=0.0)
    n.initialize()
    n(NDArray(jnp.ones((1, 4), jnp.int32)))
    return n


@pytest.fixture(scope="module")
def net():
    return _mk_net(0)


@pytest.fixture(scope="module")
def bad_draft():
    """A deliberately-bad draft: a different random net (tiny, 1 head)
    whose greedy proposals almost never match the target's argmax —
    speculation must still be exact, just slow."""
    return _mk_net(1234, units=8, hidden=16, heads=1)


@pytest.fixture(scope="module")
def spec_engine(net, bad_draft):
    """The shared greedy speculative engine (bad draft, k=3)."""
    eng = ServingEngine(net, max_batch=2, block_size=8,
                        poll_interval=_POLL, speculate_k=3,
                        draft_net=bad_draft)
    yield eng
    try:
        eng.close()
    except Exception:
        pass


@pytest.fixture
def clean_spec_engine(spec_engine):
    spec_engine.set_fault_hook(None)
    yield spec_engine
    spec_engine.drain(timeout=30)
    spec_engine.set_fault_hook(None)


# --------------------------------------------------------------------- #
# greedy bit-identity (the acceptance-criterion pair)
# --------------------------------------------------------------------- #
def test_spec_greedy_bit_identical_bad_draft(net, clean_spec_engine):
    eng = clean_spec_engine
    ref1 = onp.asarray(lm_generate(net, P1[None, :], 8))[0, len(P1):]
    got = eng.submit(P1, 8).result(timeout=60)
    assert got == ref1.tolist()
    # co-batched lanes stay independent and exact
    r1 = eng.submit(P1, 8)
    r2 = eng.submit(P2, 6)
    ref2 = onp.asarray(lm_generate(net, P2[None, :], 6))[0, len(P2):]
    assert r1.result(timeout=60) == ref1.tolist()
    assert r2.result(timeout=60) == ref2.tolist()
    # mid-window truncation: max_new below / not a multiple of k+1
    for n in (1, 2, 5):
        refn = onp.asarray(lm_generate(net, P1[None, :], n))[0, len(P1):]
        assert eng.submit(P1, n).result(timeout=60) == refn.tolist()


def test_spec_mid_batch_eviction_bit_identity(clean_spec_engine):
    eng = clean_spec_engine
    # run A: unperturbed co-batch
    ra = eng.submit(P1, 10)
    rb = eng.submit(P2, 10)
    base = ra.result(timeout=60)
    rb.result(timeout=60)
    assert eng.drain(timeout=30)
    # run B: neighbour cancelled mid-generation — the survivor must be
    # bit-identical even though the cancel lands mid speculative burst
    eng.set_fault_hook(_slow_step(0.02))
    ra = eng.submit(P1, 10)
    rb = eng.submit(P2, 10)
    assert _wait(lambda: len(rb.tokens) >= 3)
    rb.cancel()
    assert ra.result(timeout=60) == base
    with pytest.raises(RequestCancelled):
        rb.result(timeout=60)
    eng.set_fault_hook(None)
    # run C: solo — rejected-position garbage and the evicted lane's
    # scratch writes never reach the survivor
    assert eng.submit(P1, 10).result(timeout=60) == base


def test_spec_full_length_window_runs_off_the_end(net, bad_draft):
    """A lane at max_seq_len: the speculative window's trailing
    positions exceed the sequence cap and must land in scratch, not
    wrap into a neighbour's pages (the guard in `_token_forward`)."""
    with ServingEngine(net, max_batch=2, block_size=8, max_seq_len=32,
                       poll_interval=_POLL, speculate_k=4,
                       draft_net=bad_draft) as eng:
        ref = onp.asarray(lm_generate(net, P1[None, :], 27))[0, len(P1):]
        assert eng.submit(P1, 27).result(timeout=60) == ref.tolist()
        st = eng.stats()
        assert st["blocks_free"] == st["blocks_total"]


# --------------------------------------------------------------------- #
# accounting + telemetry surface
# --------------------------------------------------------------------- #
def test_spec_blocks_returned_and_stats_surface(clean_spec_engine):
    eng = clean_spec_engine
    req = eng.submit(P1, 8)
    assert req.result(timeout=60)
    assert eng.drain(timeout=30)
    st = eng.stats()
    assert st["blocks_free"] == st["blocks_total"]
    spec = st["speculate"]
    assert spec["k"] == 3
    assert spec["proposed"] >= spec["accepted"] >= 0
    assert spec["steps"] >= 1
    # the bad draft guarantees rejections (rollback attribution)
    assert spec["rollback"].get("rejected", 0) >= 1
    # per-request acceptance accounting
    assert req.spec_proposed > 0
    assert 0.0 <= req.spec_accept_rate <= 1.0
    # varz + flight recorder explain the speculation config
    vz = eng.varz_config()["speculate"]
    assert vz["k"] == 3 and vz["greedy"] is True
    assert "net[" in vz["draft"]
    fs = eng._flight_section()
    assert fs["speculate"]["k"] == 3


def test_spec_reservation_covers_window(net, bad_draft):
    """_blocks_needed grows by the k in-flight positions: a request
    whose last token sits flush on a block boundary needs one more
    block under speculation than without."""
    eng_args = dict(max_batch=1, block_size=8, max_seq_len=64,
                    poll_interval=_POLL)
    with ServingEngine(net, **eng_args) as plain, \
            ServingEngine(net, speculate_k=4, draft_net=bad_draft,
                          **eng_args) as spec:
        # P+N = 16 → 2 blocks plain; the window writes up to position
        # P+N-2+k = 18 → 3 blocks under speculation
        assert plain._blocks_needed(8, 8) == 2
        assert spec._blocks_needed(8, 8) == 3
        # ... but never past the sequence cap
        assert spec._blocks_needed(8, 56) == 8
    assert BlockPool.covers(3, 8, 18)
    assert not BlockPool.covers(2, 8, 18)
    assert not BlockPool.covers(2, 8, -1)


def test_spec_config_validation(net, bad_draft):
    with pytest.raises(ValueError):
        ServingEngine(net, speculate_k=-1)
    with pytest.raises(ValueError):        # self-draft needs the int8 mark
        ServingEngine(net, speculate_k=2)
    small = _mk_net(7, vocab=V + 2, units=8, hidden=16, heads=1)
    with pytest.raises(ValueError):        # vocab mismatch
        ServingEngine(net, speculate_k=2, draft_net=small)
    shorty = _mk_net(8, units=8, hidden=16, heads=1, max_len=16)
    with pytest.raises(ValueError):        # draft can't cover max_seq_len
        ServingEngine(net, speculate_k=2, draft_net=shorty)


# --------------------------------------------------------------------- #
# int8 self-draft (PR 7's quantize_for_decode as the draft)
# --------------------------------------------------------------------- #
def test_spec_int8_self_draft_exact_with_high_acceptance():
    net2 = _mk_net(3)
    net2.quantize_for_decode(act_quant="none")
    ref = onp.asarray(lm_generate(net2, P1[None, :], 12,
                                  quantized=False))[0, len(P1):]
    with ServingEngine(net2, max_batch=2, block_size=8,
                       poll_interval=_POLL, speculate_k=4,
                       quantized=False) as eng:
        assert eng.varz_config()["speculate"]["draft"] == "self-int8"
        got = eng.submit(P1, 12).result(timeout=60)
        assert got == ref.tolist()         # float-target exactness
        spec = eng.stats()["speculate"]
        # int8 argmax tracks the float target closely — that's the
        # whole premise of self-speculation
        assert spec["accepted"] > 0
        assert spec["accept_rate"] > 0.5


# --------------------------------------------------------------------- #
# stochastic exactness: χ² against the analytic target distribution
# --------------------------------------------------------------------- #
def test_spec_stochastic_matches_target_distribution():
    """Fixed keys, tiny vocab: the marginal of the FIRST speculatively
    produced token (index 1; index 0 comes from prefill) over many
    seeds must match sum_t0 p(t0) · p(t1 | prompt+t0) computed from
    the raw net forward.  The deliberately-bad draft forces the
    rejection + residual-resample path to carry real probability
    mass."""
    vv, temp, n_seeds = 13, 1.0, 600
    tnet = _mk_net(0, vocab=vv, max_len=32)
    tdraft = _mk_net(999, vocab=vv, units=8, hidden=16, heads=1,
                     max_len=32)
    prompt = onp.array([3, 7, 2], onp.int32)

    def probs_after(prefix):
        lg = onp.asarray(
            tnet(NDArray(jnp.asarray(prefix, jnp.int32)[None, :]))
            ._data)[0, -1].astype(onp.float64)
        z = lg / temp
        z -= z.max()
        p = onp.exp(z)
        return p / p.sum()

    p0 = probs_after(prompt)
    marg = onp.zeros(vv)
    for t0 in range(vv):
        marg += p0[t0] * probs_after(onp.concatenate([prompt, [t0]]))

    counts = onp.zeros(vv)
    with ServingEngine(tnet, max_batch=4, block_size=8,
                       poll_interval=_POLL, temperature=temp, top_k=0,
                       speculate_k=3, draft_net=tdraft) as eng:
        pending = []
        for s in range(n_seeds):
            pending.append(eng.submit(prompt, 2, seed=s))
            if len(pending) >= 16:
                for r in pending:
                    counts[r.result(timeout=120)[1]] += 1
                pending = []
        for r in pending:
            counts[r.result(timeout=120)[1]] += 1
        spec = eng.stats()["speculate"]
    assert spec["rollback"].get("rejected", 0) >= 1   # residual exercised
    exp = marg * n_seeds
    mask = exp >= 5
    chi2 = ((counts[mask] - exp[mask]) ** 2 / exp[mask]).sum()
    dof = int(mask.sum()) - 1
    lump_exp, lump_obs = exp[~mask].sum(), counts[~mask].sum()
    if lump_exp > 0:
        chi2 += (lump_obs - lump_exp) ** 2 / lump_exp
        dof += 1
    # 99.9th percentile of χ²(12) ≈ 32.9; fixed seeds make this
    # deterministic — 40 leaves room for numerics drift, not for a
    # broken sampler (a wrong acceptance rule lands in the hundreds)
    assert chi2 < 40.0, f"chi2={chi2:.1f} (dof={dof}), counts={counts}"


# --------------------------------------------------------------------- #
# int8 KV pool composes with speculation
# --------------------------------------------------------------------- #
def test_spec_kv8_matches_nonspec_kv8(net, bad_draft):
    kw = dict(max_batch=2, block_size=8, poll_interval=_POLL,
              kv_dtype="int8")
    with ServingEngine(net, speculate_k=3, draft_net=bad_draft,
                       **kw) as spec_eng:
        got = spec_eng.submit(P1, 12).result(timeout=60)
    with ServingEngine(net, **kw) as plain_eng:
        ref = plain_eng.submit(P1, 12).result(timeout=60)
    # speculation composes with the quantized pool bit-exactly: the
    # verifier quantizes window K/V with the same per-head recipe the
    # sequential step uses
    assert got == ref
