"""Symbol graph / Executor / Module legacy path (SURVEY.md §2.2, §3.4;
ref tests/python/unittest/test_symbol.py, test_module.py)."""
import jax.numpy as jnp
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import symbol as sym_mod
from incubator_mxnet_tpu.ndarray.ndarray import NDArray

sym = mx.sym


def _nd(a):
    return NDArray(jnp.asarray(onp.asarray(a, "float32")))


def test_symbol_compose_and_eval():
    x = sym.Variable("x")
    y = sym.Variable("y")
    z = (x + y) * x
    out = sym_mod.evaluate(z, {"x": _nd([2.0]), "y": _nd([3.0])})
    assert float(out.asnumpy()[0]) == 10.0


def test_symbol_json_roundtrip(tmp_path):
    x = sym.Variable("data")
    w = sym.Variable("w")
    z = sym.FullyConnected(data=x, weight=w, num_hidden=3, no_bias=True) \
        if hasattr(sym, "FullyConnected") else (x * w)
    f = str(tmp_path / "sym.json")
    z.save(f)
    z2 = sym_mod.load(f)
    assert sorted(z2.list_arguments()) == sorted(z.list_arguments())


def test_executor_forward_backward():
    x = sym.Variable("x")
    ex = (x * x).bind(args={"x": _nd([1.0, 2.0, 3.0])})
    outs = ex.forward()
    got = outs[0].asnumpy()
    onp.testing.assert_allclose(got, [1.0, 4.0, 9.0], rtol=1e-6)
    ex.backward(out_grads=_nd([1.0, 1.0, 1.0]))
    g = ex.grad_arrays[0] if hasattr(ex, "grad_arrays") else ex.grad_dict["x"]
    onp.testing.assert_allclose(g.asnumpy(), [2.0, 4.0, 6.0], rtol=1e-6)


def test_module_fit_linear_regression():
    """Module.fit on a learnable toy problem (3.4 legacy stack)."""
    rng = onp.random.RandomState(0)
    X = rng.randn(200, 4).astype("float32")
    W = onp.array([[1.0, -2.0, 0.5, 3.0]], "float32")
    Y = (X @ W.T > 0).astype("float32").ravel()

    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=2, name="fc")
    out = sym.SoftmaxOutput(data=net, name="softmax")
    mod = mx.mod.Module(out, data_names=("data",), label_names=("softmax_label",))
    it = mx.io.NDArrayIter(X, Y, batch_size=20, shuffle=True)
    mod.fit(it, num_epoch=5,
            optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    it_eval = mx.io.NDArrayIter(X, Y, batch_size=20)
    metric = mx.metric.Accuracy()
    mod.score(it_eval, metric)
    assert metric.get()[1] > 0.85


def test_bucketing_module_variable_length():
    def sym_gen(seq_len):
        data = sym.Variable("data")
        out = sym.FullyConnected(data=data, num_hidden=2, name="fc")
        out = sym.SoftmaxOutput(data=out, name="softmax")
        return out, ("data",), ("softmax_label",)

    bm = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    rng = onp.random.RandomState(1)
    X8 = rng.randn(16, 8).astype("float32")
    Y = (X8.sum(1) > 0).astype("float32")
    bm.bind(data_shapes=[("data", (4, 8))])
    bm.init_params()
    bm.init_optimizer(optimizer="sgd")
    from incubator_mxnet_tpu.io.io import DataBatch

    batch = DataBatch(data=[_nd(X8[:4])], label=[_nd(Y[:4])], bucket_key=8)
    bm.forward(batch)
    outs = bm.get_outputs()
    assert outs[0].shape == (4, 2)
