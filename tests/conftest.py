"""Test harness config (SURVEY.md §4 conclusions):

- force the CPU backend with 8 virtual devices
  (`xla_force_host_platform_device_count`) so every DP/TP/PP/SP/EP test
  runs on a faked mesh with no TPU — the translation of the reference's
  `tools/launch.py --launcher local` multi-process-on-one-host testing.
- must run BEFORE any computation: jax is preloaded by the image's
  sitecustomize and the default platform would claim the TPU tunnel.
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

if os.environ.get("MXTPU_CHECK_TRACER_LEAKS") == "1":
    # surfaces tracers that escape their trace (stashed on self, returned
    # through closures); ~2x tracing overhead, so opt-in
    jax.config.update("jax_check_tracer_leaks", True)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _retrace_guard(request):
    """Fail any test whose watched programs recompile beyond the budget.

    Counting is keyed by callable name (the only identity JAX's compile
    log carries), so the guard watches only the package's jitted program
    names and the budget is per-test.  MXTPU_RETRACE_GUARD=0 disables;
    MXTPU_RETRACE_BUDGET overrides the default of 64.
    """
    if os.environ.get("MXTPU_RETRACE_GUARD", "1") == "0":
        yield
        return
    from incubator_mxnet_tpu.retrace_guard import PROGRAM_NAMES, RetraceGuard

    with RetraceGuard(watch=PROGRAM_NAMES) as guard:
        yield guard


@pytest.fixture
def mesh8():
    import incubator_mxnet_tpu.parallel as par

    return par.create_mesh(data=8)


@pytest.fixture
def mesh42():
    import incubator_mxnet_tpu.parallel as par

    return par.create_mesh(data=4, model=2)
