"""Test harness config (SURVEY.md §4 conclusions):

- force the CPU backend with 8 virtual devices
  (`xla_force_host_platform_device_count`) so every DP/TP/PP/SP/EP test
  runs on a faked mesh with no TPU — the translation of the reference's
  `tools/launch.py --launcher local` multi-process-on-one-host testing.
- must run BEFORE any computation: jax is preloaded by the image's
  sitecustomize and the default platform would claim the TPU tunnel.
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))

# Lock witness (MXTPU_LOCK_WITNESS=1): must be installed BEFORE the
# package is imported so module-level locks (telemetry registries,
# flight recorder) are created through the patched factories.  The
# module is loaded by file path and pre-registered in sys.modules —
# a normal `from incubator_mxnet_tpu import lock_witness` would run
# the package __init__ first, creating those locks un-witnessed.
_LOCK_WITNESS = None
if os.environ.get("MXTPU_LOCK_WITNESS") == "1":
    import importlib.util
    import sys

    _spec = importlib.util.spec_from_file_location(
        "incubator_mxnet_tpu.lock_witness",
        os.path.join(os.path.dirname(__file__), "..",
                     "incubator_mxnet_tpu", "lock_witness.py"))
    _LOCK_WITNESS = importlib.util.module_from_spec(_spec)
    sys.modules["incubator_mxnet_tpu.lock_witness"] = _LOCK_WITNESS
    _spec.loader.exec_module(_LOCK_WITNESS)
    _LOCK_WITNESS.install()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

if os.environ.get("MXTPU_CHECK_TRACER_LEAKS") == "1":
    # surfaces tracers that escape their trace (stashed on self, returned
    # through closures); ~2x tracing overhead, so opt-in
    jax.config.update("jax_check_tracer_leaks", True)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _retrace_guard(request):
    """Fail any test whose watched programs recompile beyond the budget.

    Counting is keyed by callable name (the only identity JAX's compile
    log carries), so the guard watches only the package's jitted program
    names and the budget is per-test.  MXTPU_RETRACE_GUARD=0 disables;
    MXTPU_RETRACE_BUDGET overrides the default of 64.
    """
    if os.environ.get("MXTPU_RETRACE_GUARD", "1") == "0":
        yield
        return
    from incubator_mxnet_tpu.retrace_guard import PROGRAM_NAMES, RetraceGuard

    with RetraceGuard(watch=PROGRAM_NAMES) as guard:
        yield guard


def pytest_sessionfinish(session, exitstatus):
    """Witness contract at end of a MXTPU_LOCK_WITNESS=1 run: the
    observed held-while-acquiring graph must be acyclic and a subset
    of tpulint's static lock graph."""
    if _LOCK_WITNESS is None or not _LOCK_WITNESS.installed():
        return
    stats = _LOCK_WITNESS.assert_clean()
    print(f"\nlock witness: {stats['edges']} edge(s) over "
          f"{stats['tracked_locks']} tracked lock(s), acyclic, "
          f"all in the static graph "
          f"(contention {stats['contention_seconds']:.3f}s)")


@pytest.fixture
def mesh8():
    import incubator_mxnet_tpu.parallel as par

    return par.create_mesh(data=8)


@pytest.fixture
def mesh42():
    import incubator_mxnet_tpu.parallel as par

    return par.create_mesh(data=4, model=2)
