"""Pending composition (`block._try_chain`): the canonical
`L = loss_fn(net(x), y); L.backward(); trainer.step()` pattern with a
SEPARATE loss block must fuse into one program AND match the eager
oracle exactly."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.gluon import Trainer, loss as gloss, nn
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def _net():
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    return net


X = onp.random.RandomState(0).randn(8, 5).astype("float32")
Y = onp.random.RandomState(1).randint(0, 4, 8).astype("int32")


def _train(net, hybridize, steps=4, keep_grads=True):
    x, y = NDArray(X), NDArray(Y)
    if hybridize:
        net(x)
        net.hybridize()
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9},
                 keep_grads=keep_grads)
    for _ in range(steps):
        with autograd.record():
            out = net(x)
            L = loss_fn(out, y)
        L.backward()
        tr.step(1)
    return net, tr, out, L


def _params(net):
    return [p.data().asnumpy() for p in net.collect_params().values()]


def test_chained_separate_loss_fuses_and_matches_eager():
    net1, tr1, out1, L1 = _train(_net(), hybridize=True)
    assert tr1._fullstep_ctx is not None, "chain did not reach the full step"
    net2, tr2, out2, L2 = _train(_net(), hybridize=False)
    for a, b in zip(_params(net1), _params(net2)):
        assert onp.allclose(a, b, atol=2e-5), "chained != eager"
    # upstream logits stay readable after the fused step (metric pattern)
    assert onp.allclose(out1.asnumpy(), out2.asnumpy(), atol=1e-4)
    assert onp.allclose(L1.asnumpy(), L2.asnumpy(), atol=1e-5)


def test_chained_keep_grads_false_reads_raise():
    net, tr, out, L = _train(_net(), hybridize=True, keep_grads=False)
    p = list(net.collect_params().values())[0]
    with pytest.raises(mx.MXNetError, match="keep_grads"):
        p.grad().asnumpy()
    # params still updated (loss readable)
    assert onp.isfinite(L.asnumpy()).all()


def test_chained_grads_match_eager():
    net1, tr1, _, _ = _train(_net(), hybridize=True, steps=1)
    net2, tr2, _, _ = _train(_net(), hybridize=False, steps=1)
    g1 = [p.grad().asnumpy() for p in net1.collect_params().values()]
    g2 = [p.grad().asnumpy() for p in net2.collect_params().values()]
    for a, b in zip(g1, g2):
        assert onp.allclose(a, b, atol=1e-5)


def test_two_stage_chain():
    """net → head → loss: chains compose recursively into one pending."""
    mx.random.seed(0)
    body = nn.Dense(16, activation="relu")
    head = nn.Dense(4)
    body.initialize(); head.initialize()
    x, y = NDArray(X), NDArray(Y)
    head(body(x))
    body.hybridize(); head.hybridize()
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    params = {**body.collect_params(), **head.collect_params()}
    tr = Trainer(params, "sgd", {"learning_rate": 0.1})
    for _ in range(2):
        with autograd.record():
            L = loss_fn(head(body(x)), y)
        L.backward()
        tr.step(1)
    assert tr._fullstep_ctx is not None, "two-stage chain did not fuse"

    # eager oracle
    mx.random.seed(0)
    body2 = nn.Dense(16, activation="relu")
    head2 = nn.Dense(4)
    body2.initialize(); head2.initialize()
    params2 = {**body2.collect_params(), **head2.collect_params()}
    tr2 = Trainer(params2, "sgd", {"learning_rate": 0.1})
    for _ in range(2):
        with autograd.record():
            L2 = loss_fn(head2(body2(x)), y)
        L2.backward()
        tr2.step(1)
    # construction order, not name-sort: the global name counter makes
    # alphabetical order digit-boundary-dependent across the two nets
    for (a, b) in zip(params, params2):
        assert params[a].shape == params2[b].shape, (a, b)
        assert onp.allclose(params[a].data().asnumpy(),
                            params2[b].data().asnumpy(), atol=2e-5)


def test_chain_with_input_grad_falls_back_correctly():
    """x.attach_grad(): input grads need the staged path — numerics must
    still match the eager oracle."""
    net = _net()
    x, y = NDArray(X), NDArray(Y)
    net(x)
    net.hybridize()
    x.attach_grad()
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        L = loss_fn(net(x), y)
    L.backward()
    gx_hyb = x.grad.asnumpy()

    net2 = _net()
    x2 = NDArray(X)
    x2.attach_grad()
    with autograd.record():
        L2 = loss_fn(net2(x2), y)
    L2.backward()
    assert onp.allclose(gx_hyb, x2.grad.asnumpy(), atol=1e-5)


def test_chained_with_batchnorm_aux_updates():
    """BN moving stats (aux params) must advance through the chained
    program identically to the eager path."""
    def bn_net():
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16), nn.BatchNorm(), nn.Dense(4))
        net.initialize()
        return net

    x, y = NDArray(X), NDArray(Y)
    loss_fn = gloss.SoftmaxCrossEntropyLoss()

    def run(hyb):
        net = bn_net()
        if hyb:
            net(x)
            net.hybridize()
        tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
        for _ in range(3):
            with autograd.record():
                L = loss_fn(net(x), y)
            L.backward()
            tr.step(1)
        return net

    n1, n2 = run(True), run(False)
    # zip in CONSTRUCTION order (dict insertion): the two nets have
    # identical structure but auto-numbered names from a global counter
    # — name-sorting diverges once the counter crosses a digit boundary
    # (dense9_... vs dense10_...), which depends on how many blocks
    # earlier tests created
    for (k1, p1), (k2, p2) in zip(n1.collect_params().items(),
                                  n2.collect_params().items()):
        assert p1.shape == p2.shape, (k1, k2)
        assert onp.allclose(p1.data().asnumpy(), p2.data().asnumpy(),
                            atol=2e-5), k1


def test_chained_shared_parameter_dedup():
    """A Parameter used by BOTH halves of a chain must be donated once
    and receive the SUM of its gradients (tied-weight pattern)."""
    mx.random.seed(0)
    shared = nn.Dense(5, use_bias=False, in_units=5)
    shared.initialize()
    x, y = NDArray(X), NDArray(Y[:8] % 5)
    loss_fn = gloss.SoftmaxCrossEntropyLoss()

    # upstream: shared; downstream head: shared AGAIN then loss
    up = nn.HybridSequential(); up.add(shared)
    down = nn.HybridSequential(); down.add(shared)
    up(x); down(up(x))
    up.hybridize(); down.hybridize()
    tr = Trainer(up.collect_params(), "sgd", {"learning_rate": 0.1})
    for _ in range(2):
        with autograd.record():
            L = loss_fn(down(up(x)), y)
        L.backward()
        tr.step(1)
    chained_w = shared.weight.data().asnumpy().copy()

    # eager oracle
    mx.random.seed(0)
    shared2 = nn.Dense(5, use_bias=False, in_units=5)
    shared2.initialize()
    up2 = nn.Sequential(); up2.add(shared2)
    tr2 = Trainer({"w": shared2.weight}, "sgd", {"learning_rate": 0.1})
    for _ in range(2):
        with autograd.record():
            L2 = loss_fn(shared2(shared2(x)), y)
        L2.backward()
        tr2.step(1)
    assert onp.allclose(chained_w, shared2.weight.data().asnumpy(), atol=2e-5)


def test_backward_duplicate_heads_accumulates():
    """backward([L, L]) doubles the cotangent — lazy path must not
    silently dedup (it falls back to the eager walk)."""
    net = _net()
    x, y = NDArray(X), NDArray(Y)
    net(x)
    net.hybridize()
    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        L = loss_fn(net(x), y)
    autograd.backward([L, L])
    g2x = [p.grad().asnumpy() for p in net.collect_params().values()]

    net2 = _net()
    with autograd.record():
        L2 = loss_fn(net2(x), y)
    autograd.backward([L2])
    g1x = [p.grad().asnumpy() for p in net2.collect_params().values()]
    for a, b in zip(g2x, g1x):
        assert onp.allclose(a, 2 * b, atol=1e-5)
