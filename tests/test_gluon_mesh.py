"""Gluon ↔ mesh unification: the real `models/bert.py` (Gluon layers,
flash attention) trains TP×DP through the PUBLIC API —
``autograd.record() → backward() → Trainer.step()`` — on a multi-device
mesh, with loss/param parity against the single-device oracle.

This is the BASELINE.json north-star sentence ("mxnet.gluon.Trainer ...
scales across a TPU pod") made into CI: `shard_params` places the
params by structural-path rules, GSPMD inserts the ICI collectives
inside the Trainer's fused fwd+bwd+update program, and the training
loop itself is unchanged from the single-chip one.
(Ref concept replaced: `group2ctx` + DataParallelExecutorGroup,
SURVEY.md §2.4.)
"""
import warnings

import jax
import jax.numpy as jnp
import numpy as onp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.gluon import Trainer
from incubator_mxnet_tpu.gluon.block import HybridBlock
from incubator_mxnet_tpu.gluon.utils import shard_batch, split_and_load
from incubator_mxnet_tpu.models import bert
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.parallel import create_mesh
from incubator_mxnet_tpu.parallel.sharding import shard_params

V, D, DFF, L, H, B, T = 64, 32, 64, 2, 4, 8, 16


class PretrainWithLoss(HybridBlock):
    def __init__(self, net_, **kw):
        super().__init__(**kw)
        self.net = net_

    def forward(self, tokens, labels):
        mlm_logits, nsp_logits = self.net(tokens)
        logp = mx.nd.log_softmax(mlm_logits.astype("float32"))
        mlm = -(mx.nd.pick(logp, labels).mean())
        nsp_logp = mx.nd.log_softmax(nsp_logits.astype("float32"))
        return mlm - (nsp_logp[:, 0].mean())


def _build():
    mx.random.seed(0)
    net = bert.BERTForPretraining(vocab_size=V, units=D, hidden_size=DFF,
                                  num_layers=L, num_heads=H, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((B, T), jnp.int32)))  # materialize deferred shapes
    model = PretrainWithLoss(net)
    model.hybridize()
    return net, model


def _batch(step):
    k = jax.random.PRNGKey(100 + step)
    kx, ky = jax.random.split(k)
    tokens = jax.random.randint(kx, (B, T), 0, V, dtype=jnp.int32)
    labels = jax.random.randint(ky, (B, T), 0, V, dtype=jnp.int32)
    return tokens, labels


def _train(model, trainer, n_steps, mesh=None):
    losses = []
    for s in range(n_steps):
        tokens, labels = _batch(s)
        if mesh is not None:
            tokens = shard_batch(tokens, mesh)
            labels = shard_batch(labels, mesh)
        else:
            tokens, labels = NDArray(tokens), NDArray(labels)
        with autograd.record():
            loss = model(tokens, labels)
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    return losses


def _params_host(net):
    return {n: onp.asarray(jax.device_get(p.data()._data))
            for n, p in net._collect_params_with_prefix().items()}


def test_gluon_bert_tp_dp_parity():
    """TP=2 × DP=2 Gluon BERT == single-device run, through Trainer."""
    # oracle
    net0, model0 = _build()
    tr0 = Trainer(model0.collect_params(), "sgd",
                  {"learning_rate": 0.1, "momentum": 0.9})
    losses0 = _train(model0, tr0, 3)

    # sharded
    net1, model1 = _build()
    mesh = create_mesh(jax.devices()[:4], data=2, model=2)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # no fallback warnings allowed
        report = shard_params(net1, mesh)
    # the rules must actually bite on the real model
    assert report["bert.encoder.layer0.attention.qkv.weight"] == P("model", None)
    assert report["bert.encoder.layer0.attention.proj.weight"] == P(None, "model")
    assert report["bert.encoder.layer0.ffn.ffn_dense1.weight"] == P("model", None)
    assert report["bert.encoder.layer0.ffn.ffn_dense2.weight"] == P(None, "model")
    assert report["bert.word_embed.weight"] == P("model", None)
    assert report["mlm_decoder.weight"] == P("model", None)
    assert report.coverage > 0.5
    qkv = net1.bert.encoder.layer0.attention.qkv.weight
    sh = qkv.data()._data.sharding
    assert isinstance(sh, NamedSharding)
    assert qkv.data()._data.addressable_shards[0].data.shape == (3 * D // 2, D)

    tr1 = Trainer(model1.collect_params(), "sgd",
                  {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh)
    losses1 = _train(model1, tr1, 3, mesh=mesh)

    onp.testing.assert_allclose(losses0, losses1, rtol=2e-4, atol=2e-5)
    p0, p1 = _params_host(net0), _params_host(net1)
    assert p0.keys() == p1.keys()
    for n in p0:
        onp.testing.assert_allclose(p0[n], p1[n], rtol=2e-3, atol=1e-4,
                                    err_msg=n)
    # params must STILL be sharded after stepping (no silent resharding
    # to replicated through the donated update)
    sh_after = net1.bert.encoder.layer0.attention.qkv.weight.data()._data.sharding
    assert isinstance(sh_after, NamedSharding)
    assert sh_after.spec == P("model", None)
    # optimizer state (momentum + fp32 master) rides the param sharding,
    # plus — ZeRO-1 default-on for a data>1 mesh — a "data" partition on
    # the first spec-free divisible dim (gspmd tier on TP x DP meshes)
    st = tr1._states[tr1._param2idx[qkv.name]]
    st_leaves = [l for l in jax.tree_util.tree_leaves(st)
                 if hasattr(l, "shape") and l.shape == qkv.shape]
    assert st_leaves, "expected same-shape optimizer state leaves"
    for l in st_leaves:
        assert isinstance(l.sharding, NamedSharding)
        assert l.sharding.spec in (P("model", "data"), P("model", None))
        assert l.sharding.spec[0] == "model"


def test_gluon_bert_dp_only_grad_sync():
    """Pure DP on 8 devices: per-device half-batches see different data;
    parity with the single-device full-batch run proves the gradient
    psum happened inside the fused step."""
    net0, model0 = _build()
    tr0 = Trainer(model0.collect_params(), "sgd", {"learning_rate": 0.1})
    losses0 = _train(model0, tr0, 2)

    net1, model1 = _build()
    mesh = create_mesh(data=8)
    shard_params(net1, mesh, warn=False)  # no 'model' axis: all replicated, ok
    tr1 = Trainer(model1.collect_params(), "sgd", {"learning_rate": 0.1},
                  mesh=mesh)
    losses1 = _train(model1, tr1, 2, mesh=mesh)
    onp.testing.assert_allclose(losses0, losses1, rtol=2e-4, atol=2e-5)
    for n, a in _params_host(net0).items():
        onp.testing.assert_allclose(a, _params_host(net1)[n], rtol=2e-3,
                                    atol=1e-4, err_msg=n)


def test_shard_params_report_warns_on_fallback():
    """A matched rule whose dim doesn't divide the mesh must WARN, not
    silently replicate (VERDICT r2 Weak #3)."""
    mx.random.seed(1)
    net = bert.BERTModel(vocab_size=V, units=24, hidden_size=48, num_layers=1,
                         num_heads=3, dropout=0.0)  # 3 heads: 72 % 16 != 0
    net.initialize()
    net(NDArray(jnp.ones((2, 8), jnp.int32)))
    mesh = create_mesh(jax.devices()[:2], model=2)
    import incubator_mxnet_tpu.parallel.sharding as shmod
    rules = [(r"qkv\.weight$", P(None, "nonexistent_axis"))]
    with pytest.warns(UserWarning, match="fell back"):
        rep = shmod.shard_params(net, mesh, rules=rules)
    assert "encoder.layer0.attention.qkv.weight" in rep.fallbacks
    assert rep.coverage == 0.0


def test_trainer_infers_mesh_from_params():
    net, model = _build()
    mesh = create_mesh(jax.devices()[:4], data=2, model=2)
    shard_params(net, mesh)
    tr = Trainer(model.collect_params(), "sgd", {"learning_rate": 0.1})
    assert tr._get_mesh() is mesh


def test_split_and_load_mesh_mode():
    mesh = create_mesh(data=4)
    x = onp.arange(32, dtype=onp.float32).reshape(8, 4)
    out = split_and_load(x, mesh=mesh)
    assert isinstance(out, NDArray)
    assert len(out._data.addressable_shards) >= 4
    onp.testing.assert_array_equal(onp.asarray(jax.device_get(out._data)), x)


def test_gluon_bert_tp_dp_with_dropout_composes():
    """Dropout-enabled BERT must still train sharded (the threefry path
    engages under GSPMD — the Pallas PRNG kernel is gated to
    single-device processes).  Same seed → same mask on both runs, so
    full parity holds even with dropout on."""
    def build():
        mx.random.seed(0)
        net = bert.BERTForPretraining(vocab_size=V, units=D, hidden_size=DFF,
                                      num_layers=L, num_heads=H, dropout=0.1)
        net.initialize()
        net(NDArray(jnp.ones((B, T), jnp.int32)))
        model = PretrainWithLoss(net)
        model.hybridize()
        return net, model

    net0, model0 = build()
    tr0 = Trainer(model0.collect_params(), "sgd", {"learning_rate": 0.1})
    losses0 = _train(model0, tr0, 2)

    net1, model1 = build()
    mesh = create_mesh(jax.devices()[:4], data=2, model=2)
    shard_params(net1, mesh)
    tr1 = Trainer(model1.collect_params(), "sgd", {"learning_rate": 0.1},
                  mesh=mesh)
    losses1 = _train(model1, tr1, 2, mesh=mesh)
    onp.testing.assert_allclose(losses0, losses1, rtol=3e-4, atol=3e-5)
    for n, a in _params_host(net0).items():
        onp.testing.assert_allclose(a, _params_host(net1)[n], rtol=2e-3,
                                    atol=1e-4, err_msg=n)


def test_fsdp_spec_ignores_size_one_axis():
    """dp_axis over a size-1 mesh axis must NOT count as sharded."""
    import warnings as _w

    mx.random.seed(2)
    net = bert.BERTModel(vocab_size=V, units=D, hidden_size=DFF, num_layers=1,
                         num_heads=H, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((2, 8), jnp.int32)))
    mesh = create_mesh(jax.devices()[:2], data=1, model=2)
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        rep = shard_params(net, mesh, dp_axis="data", min_fsdp_elems=1)
    for name, spec in rep.sharded.items():
        assert "data" not in tuple(spec), (name, spec)
