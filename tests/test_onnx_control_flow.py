"""ONNX control-flow export (r3 VERDICT item 6): lax.scan/while/cond →
ONNX Loop/If, so the lax.scan-based RNN zoo exports; plus BFLOAT16
initializers and the serde attribute-field fix (floats/ints live at
proto fields 7/8 — r3 emitted them at 6/7, colliding with the graph
attr field every real consumer reads).
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.onnx.export_model import export_block, export_jaxpr
from incubator_mxnet_tpu.onnx.import_model import ONNXModel
from incubator_mxnet_tpu.onnx.serde import (
    ATTR_GRAPH, BFLOAT16, decode_model, encode_model)
from jax import lax


def _roundtrip(f, *args, names=None):
    names = names or [f"x{i}" for i in range(len(args))]
    jx = jax.make_jaxpr(f)(*args)
    m = export_jaxpr(jx, names, ["y"])
    om = ONNXModel(decode_model(encode_model(m)))
    got = om._jit(*args)
    want = f(*args)
    gl = got if isinstance(got, tuple) else (got,)
    wl = want if isinstance(want, tuple) else (want,)
    for g, w in zip(gl, wl):
        onp.testing.assert_allclose(onp.asarray(g), onp.asarray(w),
                                    rtol=1e-5, atol=1e-6)
    return m


def test_scan_exports_as_loop():
    w = jax.random.normal(jax.random.PRNGKey(1), (4, 4)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 4))

    def f(x, w):
        def body(c, xt):
            c = jnp.tanh(c @ w + xt)
            return c, c * 2.0
        c, ys = lax.scan(body, jnp.zeros((4,)), x)
        return c + ys.sum(0)

    m = _roundtrip(f, x, w)
    loops = [n for n in m.graph.nodes if n.op_type == "Loop"]
    assert len(loops) == 1 and "body" in loops[0].attrs


def test_scan_reverse_ys_order():
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 4))

    def f(x):
        def body(c, xt):
            c = c * 0.5 + xt
            return c, c
        _, ys = lax.scan(body, jnp.zeros((4,)), x, reverse=True)
        return ys

    _roundtrip(f, x)


def test_while_loop_exports_as_loop():
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 4))

    def f(x):
        def cond(s):
            return s[0] < 10.0

        def body(s):
            return (s[0] + s[1].sum(), s[1] * 0.9)

        return lax.while_loop(cond, body, (jnp.float32(0.0), x))[1]

    _roundtrip(f, x)


def test_cond_exports_as_if():
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 4))

    def f(x):
        return lax.cond(x.sum() > 0, lambda v: v * 2.0,
                        lambda v: v - 1.0, x)

    m = _roundtrip(f, x)
    ifs = [n for n in m.graph.nodes if n.op_type == "If"]
    assert len(ifs) == 1
    assert "then_branch" in ifs[0].attrs and "else_branch" in ifs[0].attrs


@pytest.mark.parametrize("cls", [gluon.rnn.LSTM, gluon.rnn.GRU,
                                 gluon.rnn.RNN])
def test_rnn_layer_roundtrips(cls, tmp_path):
    """THE r3 gap: the lax.scan-based RNN zoo now exports (reference
    parity: python/mxnet/onnx exported RNN models)."""
    mx.random.seed(0)
    net = cls(hidden_size=8, num_layers=1)
    net.initialize()
    x = NDArray(jnp.asarray(
        jax.random.normal(jax.random.PRNGKey(2), (6, 2, 4))))  # (T, B, C)
    want = net(x).asnumpy()
    path = str(tmp_path / "rnn.onnx")
    export_block(net, [x], path)
    from incubator_mxnet_tpu.onnx import import_model as _imp_fn
    om, _arg, _aux = _imp_fn(path)
    got = om(x).asnumpy()
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=1e-4, atol=1e-5)


def test_bf16_initializer_roundtrip():
    """bf16 weights export as BFLOAT16 tensors (r3 silently upcast to
    fp32) and survive the byte round-trip."""
    w = jnp.asarray([[1.5, -2.25], [0.125, 3.0]], jnp.bfloat16)

    def f(x):
        return (x @ w).astype(jnp.float32)

    x = jnp.ones((3, 2), jnp.bfloat16)
    jx = jax.make_jaxpr(f)(x)
    m = export_jaxpr(jx, ["x"], ["y"])
    m2 = decode_model(encode_model(m))
    bf16_inits = [k for k, v in m2.graph.initializers.items()
                  if str(v.dtype) == "bfloat16"]
    assert bf16_inits, "no BFLOAT16 initializer survived"
    om = ONNXModel(m2)
    onp.testing.assert_allclose(onp.asarray(om._jit(x)),
                                onp.asarray(f(x)), rtol=1e-2)


def test_attr_field_numbers_match_onnx_proto():
    """Byte-level pin of AttributeProto encoding: ints at FIELD 8 with
    type INTS(7), floats at FIELD 7 with type FLOATS(6), subgraphs at
    FIELD 6 with type GRAPH(5) — r3 wrote ints/floats at 6/7, which a
    real ONNX parser reads as a graph/floats."""
    from incubator_mxnet_tpu.onnx.serde import _encode_attr

    b = _encode_attr("axes", [0, 2])
    # name: tag 0x0A len 4 'axes'; ints: tag 0x40 (field 8, varint) x2;
    # type: tag 0xA0 0x01 (field 20) value 7
    assert b.startswith(b"\x0a\x04axes")
    assert b"\x40\x00" in b and b"\x40\x02" in b
    assert b.endswith(b"\xa0\x01\x07")

    bf = _encode_attr("alpha_list", [1.0, 2.0])
    # floats: tag 0x3D (field 7, wire 5 fixed32)
    assert b"\x3d" in bf and bf.endswith(b"\xa0\x01\x06")


def test_scalar_initializer_stays_scalar():
    """ascontiguousarray promotes 0-d to 1-d; the encoder must restore
    the true rank (reverse-scan Gather indices depend on it)."""
    from incubator_mxnet_tpu.onnx.serde import _decode_tensor, _encode_tensor

    name, arr = _decode_tensor(_encode_tensor("s", onp.asarray(7, "int64")))
    assert arr.shape == ()
