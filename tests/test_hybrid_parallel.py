"""5-axis hybrid-parallel train step vs. single-device oracle.

Translation of the reference's multi-process-on-one-host distributed
tests (`tests/nightly/dist_sync_kvstore.py` via `--launcher local`,
SURVEY.md §4): an 8-virtual-device CPU mesh stands in for the TPU
slice; losses and updated parameters of the sharded step must match
the unsharded reference step bit-for-tolerance.
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

from incubator_mxnet_tpu.parallel import hybrid


def _run_config(mesh_axes, cfg, steps=2, tol=2e-4):
    devs = jax.devices()
    order = ["data", "model", "pipe", "seq", "expert"]
    sizes = tuple(mesh_axes.get(a, 1) for a in order)
    n = int(onp.prod(sizes))
    mesh = jax.sharding.Mesh(onp.asarray(devs[:n]).reshape(sizes), tuple(order))

    key = jax.random.PRNGKey(0)
    params = hybrid.init_params(key, cfg)
    ref_params = jax.tree_util.tree_map(jnp.copy, params)

    B = max(2 * mesh_axes.get("data", 1), mesh_axes.get("data", 1) * cfg.microbatches)
    T = 4 * mesh_axes.get("seq", 1)
    kx, ky = jax.random.split(jax.random.PRNGKey(7))
    x = jax.random.randint(kx, (B, T), 0, cfg.vocab, dtype=jnp.int32)
    y = jax.random.randint(ky, (B, T), 0, cfg.vocab, dtype=jnp.int32)

    step = hybrid.make_train_step(mesh, cfg)
    sharded = hybrid.shard_params_to_mesh(params, mesh, cfg)

    ref_grad = jax.jit(jax.value_and_grad(
        lambda p: hybrid.reference_loss(p, x, y, cfg)))

    for i in range(steps):
        sharded, loss = step(sharded, x, y)
        ref_loss, g = ref_grad(ref_params)
        ref_params = jax.tree_util.tree_map(
            lambda p, gg: p - cfg.lr * gg, ref_params, g)
        assert onp.isfinite(float(loss)), f"step {i}: non-finite sharded loss"
        onp.testing.assert_allclose(float(loss), float(ref_loss), rtol=tol,
                                    err_msg=f"loss mismatch at step {i}")
    for name in sharded:
        got = onp.asarray(jax.device_get(sharded[name]))
        want = onp.asarray(jax.device_get(ref_params[name]))
        onp.testing.assert_allclose(
            got, want, rtol=5e-3, atol=5 * tol,
            err_msg=f"param {name} diverged after {steps} sharded steps")


def test_dp_tp_sp():
    """data=2 × model=2 × seq=2 — DP grads + Megatron TP + ring attention."""
    cfg = hybrid.HybridConfig(n_stages=1, layers_per_stage=2, microbatches=2)
    _run_config({"data": 2, "model": 2, "seq": 2}, cfg)


def test_pp_ep_dp():
    """data=2 × pipe=2 × expert=2 — GPipe schedule + MoE all_to_all."""
    cfg = hybrid.HybridConfig(n_stages=2, layers_per_stage=1, microbatches=2)
    _run_config({"data": 2, "pipe": 2, "expert": 2}, cfg)


def test_tp_pp_sp():
    """model=2 × pipe=2 × seq=2 — no data axis; TP+PP+SP compose."""
    cfg = hybrid.HybridConfig(n_stages=2, layers_per_stage=1, microbatches=2)
    _run_config({"model": 2, "pipe": 2, "seq": 2}, cfg)


def test_all_axes_degenerate_ok():
    """All five axes present, three of them size 1 — the exact shape
    dryrun_multichip uses for 8 devices."""
    mesh = hybrid.mesh_for(8)
    assert set(mesh.axis_names) == {"data", "model", "pipe", "seq", "expert"}
    cfg = hybrid.HybridConfig(n_stages=mesh.shape["pipe"], layers_per_stage=1,
                              microbatches=2)
    params = hybrid.shard_params_to_mesh(
        hybrid.init_params(jax.random.PRNGKey(1), cfg), mesh, cfg)
    B = mesh.shape["data"] * cfg.microbatches
    T = 4 * mesh.shape["seq"]
    x = jnp.zeros((B, T), jnp.int32)
    y = jnp.zeros((B, T), jnp.int32)
    step = hybrid.make_train_step(mesh, cfg)
    params, loss = step(params, x, y)
    assert onp.isfinite(float(loss))
