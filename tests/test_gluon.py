"""Gluon core tests (model: tests/python/unittest/test_gluon.py)."""
import os

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.test_utils import assert_almost_equal, with_seed


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(3, 4))
    p.initialize(init=mx.init.One())
    assert p.data().shape == (3, 4)
    assert_almost_equal(p.data(), onp.ones((3, 4)))
    assert p.list_data()[0] is p.data()
    p.set_data(mx.nd.zeros((3, 4)))
    assert_almost_equal(p.data(), onp.zeros((3, 4)))
    assert p.grad() is not None


def test_parameter_deferred_init():
    net = nn.Dense(8)
    net.initialize()
    with pytest.raises(Exception):
        net.weight.data()  # deferred until first forward
    out = net(mx.nd.ones((2, 5)))
    assert net.weight.shape == (8, 5)
    assert out.shape == (2, 8)


def test_dense_flatten():
    net = nn.Dense(4, flatten=True)
    net.initialize()
    out = net(mx.nd.ones((2, 3, 5)))
    assert out.shape == (2, 4)
    assert net.weight.shape == (4, 15)
    net2 = nn.Dense(4, flatten=False)
    net2.initialize()
    out2 = net2(mx.nd.ones((2, 3, 5)))
    assert out2.shape == (2, 3, 4)


def test_sequential_and_children():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(8))
    net.initialize()
    out = net(mx.nd.ones((4, 10)))
    assert out.shape == (4, 8)
    assert len(net) == 2
    assert net[0]._units == 16
    params = net.collect_params()
    assert len(params) == 4  # 2 weights + 2 biases


def test_block_naming():
    net1 = nn.Dense(2)
    net2 = nn.Dense(2)
    assert net1.prefix != net2.prefix
    named = nn.Dense(2, prefix="custom_")
    assert named.prefix == "custom_"
    assert named.weight.name == "custom_weight"


def test_gradient_flow_through_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="tanh"), nn.Dense(1))
    net.initialize(mx.init.Xavier())
    x = mx.nd.ones((4, 3))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    for name, p in net.collect_params().items():
        g = p.grad().asnumpy()
        assert g.shape == p.shape
    # at least the output layer weight grad must be nonzero
    assert onp.abs(net[1].weight.grad().asnumpy()).sum() > 0


def test_hybridize_matches_eager():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(onp.random.rand(3, 7).astype("f"))
    eager = net(x).asnumpy()
    net.hybridize()
    compiled = net(x).asnumpy()
    assert_almost_equal(eager, compiled, rtol=1e-5, atol=1e-6)
    # changed shape triggers transparent re-specialization (CachedOp cache)
    y = mx.nd.array(onp.random.rand(5, 7).astype("f"))
    assert net(y).shape == (5, 4)


def test_aval_cache_is_lru_bounded(monkeypatch):
    """tpulint TPU010 regression: the per-block aval-spec cache must not
    grow one entry per distinct input signature forever — it is an LRU
    capped at _AVAL_CACHE_CAP, evicting oldest-first."""
    from incubator_mxnet_tpu.gluon import block as block_mod

    monkeypatch.setattr(block_mod, "_AVAL_CACHE_CAP", 3)
    net = nn.Dense(4, in_units=7)
    net.initialize(mx.init.One())
    net.hybridize()
    for batch in range(1, 7):       # 6 distinct signatures, cap 3
        x = mx.nd.ones((batch, 7))
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        assert len(net._aval_cache) <= 3
    assert len(net._aval_cache) == 3
    # the surviving entries are the most recent — a repeat of the LAST
    # shape hits the cache without growing it
    before = list(net._aval_cache)
    with autograd.record():
        net(mx.nd.ones((6, 7))).sum().backward()
    assert list(net._aval_cache) == before


def test_lru_helpers_evict_oldest_and_refresh_on_hit():
    from collections import OrderedDict

    from incubator_mxnet_tpu.gluon.block import _lru_hit, _lru_store

    c = OrderedDict()
    for k in "abcd":
        _lru_store(c, k, k.upper(), 3)
    assert list(c) == ["b", "c", "d"]      # "a" evicted at cap 3
    assert _lru_hit(c, "b") == "B"
    assert list(c) == ["c", "d", "b"]      # hit refreshes recency
    _lru_store(c, "e", "E", 3)
    assert list(c) == ["d", "b", "e"]      # LRU "c" evicted, not "b"
    assert _lru_hit(c, "zzz") is None


def test_hybridize_backward():
    net = nn.Dense(3)
    net.initialize(mx.init.One())
    net.hybridize()
    x = mx.nd.ones((2, 4))
    with autograd.record():
        loss = (net(x) * 2).sum()
    loss.backward()
    assert_almost_equal(net.weight.grad(), 4 * onp.ones((3, 4)))
    assert_almost_equal(net.bias.grad(), 4 * onp.ones(3))


def test_batchnorm_running_stats():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = mx.nd.array(onp.random.rand(8, 3, 4, 4).astype("f") * 5)
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    assert onp.abs(rm).sum() > 0  # updated from zeros
    # inference mode uses running stats, output differs from training
    out_train_mean = bn(x).asnumpy().mean()
    assert onp.isfinite(out_train_mean)


def test_batchnorm_hybrid_state_channel():
    bn = nn.BatchNorm(in_channels=2)
    bn.initialize()
    bn.hybridize()
    x = mx.nd.array(onp.random.rand(4, 2, 3, 3).astype("f") * 2 + 1)
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    assert onp.abs(rm).sum() > 0  # state flowed out of the jitted program


def test_dropout_train_vs_eval():
    do = nn.Dropout(0.5)
    x = mx.nd.ones((100, 100))
    out_eval = do(x)
    assert_almost_equal(out_eval, x.asnumpy())  # identity in inference
    with autograd.record():
        out_train = do(x)
    frac_zero = (out_train.asnumpy() == 0).mean()
    assert 0.3 < frac_zero < 0.7


def test_embedding_layer():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    out = emb(mx.nd.array([[1, 2], [3, 4]]))
    assert out.shape == (2, 2, 4)


def test_layernorm_math():
    ln = nn.LayerNorm(in_channels=6)
    ln.initialize()
    x = mx.nd.array(onp.random.rand(2, 6).astype("f") * 3)
    out = ln(x).asnumpy()
    assert_almost_equal(out.mean(axis=-1), onp.zeros(2), atol=1e-5)
    assert_almost_equal(out.std(axis=-1), onp.ones(2), rtol=1e-2, atol=1e-2)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = mx.nd.ones((2, 5))
    ref = net(x).asnumpy()
    path = str(tmp_path / "model.params")
    net.save_parameters(path)

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net2.initialize()
    net2(x)  # resolve shapes
    net2.load_parameters(path)
    assert_almost_equal(net2(x), ref)


def test_activations():
    x = mx.nd.array([[-1.0, 0.0, 1.0]])
    for act, fn in [(nn.Activation("relu"), lambda v: onp.maximum(v, 0)),
                    (nn.LeakyReLU(0.1), lambda v: onp.where(v > 0, v, 0.1 * v)),
                    (nn.ELU(1.0), lambda v: onp.where(v > 0, v, onp.exp(v) - 1))]:
        assert_almost_equal(act(x), fn(x.asnumpy()), rtol=1e-4, atol=1e-5)
    prelu = nn.PReLU()
    prelu.initialize()
    out = prelu(x)
    assert_almost_equal(out, onp.where(x.asnumpy() > 0, x.asnumpy(), 0.25 * x.asnumpy()))
    g = nn.GELU()
    assert g(x).shape == (1, 3)
    s = nn.Swish()
    assert s(x).shape == (1, 3)


def test_cast_bf16():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.cast("bfloat16")
    assert "bfloat16" in str(net.weight.data()._data.dtype)
    out = net(mx.nd.ones((2, 3)).astype("bfloat16"))
    assert "bfloat16" in str(out._data.dtype)


def test_block_summary_and_repr():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3))
    net.initialize()
    s = net.summary()
    assert "Dense" in s
    assert "HybridSequential" in repr(net)


def test_constant_param():
    c = gluon.Constant("const", onp.array([1.0, 2.0]))
    assert_almost_equal(c.data(), onp.array([1.0, 2.0]))
    assert c.grad_req == "null"
