"""Custom-op bridge (pure_callback) + INT8 quantization (VERDICT r1
weak items: custom op bridge absent, INT8 absent)."""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


# ---------------- custom op ------------------------------------------- #
class _NpSigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], 1.0 / (1.0 + onp.exp(-in_data[0])))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))


@mx.operator.register("np_sigmoid")
class _NpSigmoidProp(mx.operator.CustomOpProp):
    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def create_operator(self, ctx, shapes, dtypes):
        return _NpSigmoid()


def test_custom_op_forward_eager_and_jit():
    x = onp.random.RandomState(0).randn(3, 4).astype("float32")
    out = mx.nd.Custom(NDArray(jnp.asarray(x)), op_type="np_sigmoid")
    want = 1 / (1 + onp.exp(-x))
    onp.testing.assert_allclose(out.asnumpy(), want, rtol=1e-6)

    # inside jit (the GIL-callback-under-engine equivalence)
    @jax.jit
    def f(xr):
        return mx.operator.Custom(NDArray(xr), op_type="np_sigmoid")._data

    onp.testing.assert_allclose(onp.asarray(f(jnp.asarray(x))), want, rtol=1e-6)


def test_custom_op_backward_through_tape():
    x = NDArray(jnp.asarray(onp.random.RandomState(1).randn(2, 3), jnp.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="np_sigmoid")
        s = y.sum()
    s.backward()
    sig = 1 / (1 + onp.exp(-x.asnumpy()))
    onp.testing.assert_allclose(x.grad.asnumpy(), sig * (1 - sig), rtol=1e-5)


# ---------------- int8 quantization ----------------------------------- #
def test_quantize_weight_roundtrip():
    from incubator_mxnet_tpu.contrib.quantization import quantize_weight

    w = onp.random.RandomState(2).randn(8, 16).astype("float32")
    q, scale = quantize_weight(jnp.asarray(w))
    assert q.dtype == jnp.int8
    deq = onp.asarray(q, dtype="float32") * onp.asarray(scale)
    onp.testing.assert_allclose(deq, w, atol=onp.abs(w).max() / 127 + 1e-6)


@pytest.mark.parametrize("mode", ["minmax", "entropy"])
def test_calibrate_modes(mode):
    from incubator_mxnet_tpu.contrib.quantization import calibrate

    acts = [onp.random.RandomState(i).randn(100).astype("float32")
            for i in range(3)]
    t = calibrate(acts, mode)
    assert 0 < t <= max(onp.abs(a).max() for a in acts) + 1e-6


def test_quantize_net_accuracy():
    """PTQ'd MLP must stay close to the fp32 net on held-out data."""
    from incubator_mxnet_tpu.contrib.quantization import quantize_net
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"))
    net.add(nn.Dense(8))
    net.initialize()
    rng = onp.random.RandomState(3)
    calib = [NDArray(jnp.asarray(rng.randn(16, 10), jnp.float32))
             for _ in range(4)]
    x = NDArray(jnp.asarray(rng.randn(16, 10), jnp.float32))
    want = net(x).asnumpy()
    quantize_net(net, calib, calib_mode="minmax")
    got = net(x).asnumpy()
    err = onp.abs(got - want).max() / (onp.abs(want).max() + 1e-6)
    # two stacked int8 layers on RANDOM (untrained) weights/data: ~2-9%
    # compounded worst-case error is expected for symmetric per-tensor
    # activation scales; trained nets with calibration data do better
    assert err < 0.15, f"int8 relative error too high: {err}"


def test_features_reports_int8_now():
    from incubator_mxnet_tpu import runtime

    assert runtime.Features().is_enabled("INT8")


def test_custom_op_backward_bf16_primals():
    """Cotangents must come back in the PRIMAL dtype (r2 review: bf16
    primals + fp32 host callback)."""
    x = NDArray(jnp.asarray(onp.random.RandomState(4).randn(2, 3),
                            jnp.bfloat16))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="np_sigmoid")
        s = y.astype("float32").sum()
    s.backward()
    assert x.grad._data.dtype == jnp.bfloat16
    assert onp.isfinite(onp.asarray(x.grad._data, dtype="float32")).all()


def test_int8_conv_close_to_fp32():
    """int8 conv vs fp32 oracle within quantization tolerance
    (ref quantized_conv.cc parity; VERDICT r2 #5)."""
    import jax
    from incubator_mxnet_tpu.contrib.quantization import (QuantizedConv,
                                                          quantize_weight,
                                                          int8_conv)
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    conv = nn.Conv2D(16, 3, strides=2, padding=1, in_channels=8)
    conv.initialize()
    x = NDArray(jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16, 16)))
    ref = conv(x).asnumpy()
    q = QuantizedConv(conv, act_threshold=float(onp.abs(x.asnumpy()).max()))
    out = q(x).asnumpy()
    denom = onp.abs(ref).max()
    assert onp.abs(out - ref).max() / denom < 0.05, \
        onp.abs(out - ref).max() / denom


def test_int8_grouped_conv():
    import jax
    from incubator_mxnet_tpu.contrib.quantization import QuantizedConv
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    conv = nn.Conv2D(8, 3, padding=1, groups=4, in_channels=8)
    conv.initialize()
    x = NDArray(jax.random.normal(jax.random.PRNGKey(2), (2, 8, 10, 10)))
    ref = conv(x).asnumpy()
    q = QuantizedConv(conv, act_threshold=float(onp.abs(x.asnumpy()).max()))
    out = q(x).asnumpy()
    assert onp.abs(out - ref).max() / onp.abs(ref).max() < 0.06


def test_quantize_net_resnet18():
    """quantize_net swaps EVERY conv+dense in a real model-zoo resnet
    and the quantized forward tracks the fp32 logits."""
    import jax
    from incubator_mxnet_tpu.contrib.quantization import (quantize_net,
                                                          _QuantizedWrapper)
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet18_v1

    mx.random.seed(0)
    net = resnet18_v1(classes=10)
    net.initialize()
    x = NDArray(jax.random.normal(jax.random.PRNGKey(3), (2, 3, 32, 32)))
    ref = net(x).asnumpy()

    calib = [NDArray(jax.random.normal(jax.random.PRNGKey(10 + i), (2, 3, 32, 32)))
             for i in range(2)]
    quantize_net(net, calib)

    n_quant = [0]

    def count(block):
        for c in block._children.values():
            if isinstance(c, _QuantizedWrapper):
                n_quant[0] += 1
            else:
                count(c)

    count(net)
    # resnet18: 1 stem conv + 16 block convs + 3 downsample convs + 1 dense
    assert n_quant[0] >= 20, n_quant[0]
    out = net(x).asnumpy()
    # random-weight logits are near zero; compare on absolute scale
    assert onp.abs(out - ref).max() / max(onp.abs(ref).max(), 1e-3) < 0.25
    # top-1 agreement on the batch
    assert (out.argmax(1) == ref.argmax(1)).all()


def test_quantize_net_invalidates_cached_program():
    """An already-hybridized net must NOT keep serving the stale fp32
    jit after quantization (r3 review finding)."""
    import jax
    from incubator_mxnet_tpu.contrib.quantization import quantize_net
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=6))
    net.add(nn.Dense(4, in_units=8))
    net.initialize()
    net.hybridize()
    x = NDArray(jax.random.normal(jax.random.PRNGKey(0), (2, 6)))
    before = net(x).asnumpy()  # builds the fp32 cached program
    quantize_net(net, [x])
    after = net(x).asnumpy()
    assert not onp.array_equal(before, after), \
        "quantized net still served the cached fp32 program"
    onp.testing.assert_allclose(after, before, rtol=0.1, atol=0.05)
