"""BASELINE.md's Measured table must match the committed bench
artifacts byte-for-byte (r3 VERDICT item 8: one source of perf truth).
"""
import os
import subprocess
import sys


def test_baseline_measured_table_in_sync():
    root = os.path.join(os.path.dirname(__file__), "..")
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "gen_baseline.py"),
         "--check"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr + proc.stdout
