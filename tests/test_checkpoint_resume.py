"""Checkpoint/resume + elastic autoresume (VERDICT r1 #7; SURVEY.md
§5.3/§5.4 — the build must EXCEED the reference here).

ISSUE 11 additions: async on-device snapshot isolation, manifest
fault-injection (truncation / missing manifest / checksum mismatch /
partially-renamed tmp dir), mesh-resize restore of ZeRO-1 state, the
inflight-aware prune, and write retry-with-backoff."""
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
import incubator_mxnet_tpu.parallel as par
from incubator_mxnet_tpu import autograd, telemetry
from incubator_mxnet_tpu.gluon import Trainer, nn
from incubator_mxnet_tpu.gluon import zero as zero_mod
from incubator_mxnet_tpu.gluon.block import HybridBlock
from incubator_mxnet_tpu.gluon.utils import shard_batch
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.utils.checkpoint import (CheckpointCorrupt,
                                                  CheckpointManager)

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _train_steps(net, trainer, n, start=1):
    for step in range(start, start + n):
        key = jax.random.PRNGKey(1000 + step)
        x = NDArray(jax.random.normal(key, (2, 6)))
        with autograd.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(1)


def _make(seed=0):
    mx.random.seed(seed)
    net = nn.Dense(4, in_units=6)
    net.initialize()
    net(NDArray(jnp.ones((2, 6))))
    trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 0.05})
    return net, trainer


def test_full_state_roundtrip(tmp_path):
    net, trainer = _make()
    _train_steps(net, trainer, 3)
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(3, net=net, trainer=trainer, iterator_state={"cursor": 42},
             extra={"epoch": 1})
    w_before = net.weight.data().asnumpy()

    net2, trainer2 = _make(seed=9)  # different init — restore must override
    mgr2 = CheckpointManager(str(tmp_path))
    info = mgr2.restore(net=net2, trainer=trainer2)
    assert info["step"] == 3
    assert info["iterator_state"] == {"cursor": 42}
    assert info["extra"] == {"epoch": 1}
    onp.testing.assert_array_equal(net2.weight.data().asnumpy(), w_before)
    # optimizer state (adam m/v + counts) restored
    assert trainer2._optimizer.num_update == trainer._optimizer.num_update
    # continued training is BIT-EXACT vs the uninterrupted run
    _train_steps(net, trainer, 2, start=4)
    _train_steps(net2, trainer2, 2, start=4)
    onp.testing.assert_array_equal(net.weight.data().asnumpy(),
                                   net2.weight.data().asnumpy())


def test_async_save_and_retention(tmp_path):
    net, trainer = _make()
    mgr = CheckpointManager(str(tmp_path), keep=2, async_save=True)
    for s in (1, 2, 3, 4):
        _train_steps(net, trainer, 1, start=s)
        mgr.save(s, net=net, trainer=trainer)
    mgr.wait()
    assert mgr.all_steps() == [3, 4]  # pruned to keep=2
    assert mgr.latest_step() == 4


def test_close_joins_worker_and_flushes(tmp_path):
    """tpulint TPU012 regression: close() must flush queued saves and
    JOIN the worker (previously the daemon thread was never joined —
    interpreter exit could kill it mid-write)."""
    net, trainer = _make()
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    mgr.save(1, net=net, trainer=trainer)
    worker = mgr._worker
    assert worker is not None and worker.is_alive()
    mgr.close()
    assert not worker.is_alive()          # joined, not abandoned
    assert mgr._worker is None
    assert mgr.all_steps() == [1]         # queued write landed before join
    mgr.close()                           # idempotent
    # save() after close() restarts the worker transparently
    mgr.save(2, net=net, trainer=trainer)
    mgr.close()
    assert mgr.all_steps() == [1, 2]


def test_close_as_context_manager(tmp_path):
    net, trainer = _make()
    with CheckpointManager(str(tmp_path), async_save=True) as mgr:
        mgr.save(1, net=net, trainer=trainer)
    assert mgr._worker is None
    assert mgr.all_steps() == [1]


def test_worker_error_surfaces_on_close(tmp_path):
    """tpulint TPU011 regression: the worker's error handoff is now
    lock-guarded and close()/wait() re-raise the pending exception."""
    net, trainer = _make()
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    boom = RuntimeError("disk full")
    with mgr._err_lock:
        mgr._error = boom                 # as if _drain had failed
    with pytest.raises(RuntimeError, match="disk full"):
        mgr.close()
    # the error is consumed — the manager is usable again
    mgr.save(1, net=net, trainer=trainer)
    mgr.close()
    assert mgr.all_steps() == [1]


def test_kill_and_resume_bit_exact(tmp_path):
    """Kill a training process mid-run; autoresume restarts it; the final
    weights equal an uninterrupted run (≤1 step of work lost, replayed)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            env.pop(k)
    worker = os.path.join(_ROOT, "tests", "ckpt_worker.py")

    # uninterrupted reference
    ref_out = str(tmp_path / "ref.npy")
    subprocess.run([sys.executable, worker, str(tmp_path / "ck_ref"), "8",
                    "-1", ref_out], env=env, check=True, timeout=300,
                   capture_output=True, text=True)

    # crashing run under the autoresume supervisor
    crash_out = str(tmp_path / "crash.npy")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "autoresume.py"),
         "--max-restarts", "2", "--",
         sys.executable, worker, str(tmp_path / "ck_crash"), "8", "5",
         crash_out],
        env=env, timeout=600, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "restarting" in proc.stderr
    onp.testing.assert_array_equal(onp.load(ref_out), onp.load(crash_out))


def test_async_snapshot_isolated_from_later_steps(tmp_path):
    """The on-device snapshot really decouples the save from the step
    loop: keep training IMMEDIATELY after an async save() and the
    checkpoint must still hold the state as of save time, not the
    mutated buffers."""
    net, trainer = _make()
    _train_steps(net, trainer, 3)
    w_at_save = net.weight.data().asnumpy()
    nu_at_save = trainer._optimizer.num_update
    with CheckpointManager(str(tmp_path), async_save=True) as mgr:
        mgr.save(3, net=net, trainer=trainer)
        _train_steps(net, trainer, 4, start=4)  # mutates params + state
    net2, trainer2 = _make(seed=9)
    info = CheckpointManager(str(tmp_path)).restore(net=net2,
                                                    trainer=trainer2)
    assert info["step"] == 3
    onp.testing.assert_array_equal(net2.weight.data().asnumpy(), w_at_save)
    assert trainer2._optimizer.num_update == nu_at_save


def _saved_two_steps(tmp_path):
    net, trainer = _make()
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    for s in (1, 2):
        _train_steps(net, trainer, 1, start=s)
        mgr.save(s, net=net, trainer=trainer)
    return net, trainer, mgr


def _step_file(mgr, step, name):
    return os.path.join(mgr._step_dir(step), name)


def test_restore_skips_truncated_array_file(tmp_path):
    net, trainer, mgr = _saved_two_steps(tmp_path)
    path = _step_file(mgr, 2, "arrays-proc0")
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 8)
    assert mgr.all_steps() == [1]  # size-vs-manifest check demotes step 2
    net2, trainer2 = _make(seed=9)
    with pytest.warns(RuntimeWarning, match="incomplete"):
        info = mgr.restore(net=net2, trainer=trainer2)
    assert info["step"] == 1


def test_restore_skips_missing_manifest(tmp_path):
    net, trainer, mgr = _saved_two_steps(tmp_path)
    os.remove(_step_file(mgr, 2, "manifest-proc0.json"))
    assert mgr.all_steps() == [1]  # format-2 dir without manifest
    with pytest.warns(RuntimeWarning, match="incomplete"):
        info = mgr.restore(net=_make(seed=9)[0])
    assert info["step"] == 1


def test_restore_skips_checksum_mismatch(tmp_path):
    """Silent corruption (size unchanged, bytes flipped) passes the
    cheap completeness check but fails restore-time CRC validation —
    skipped with a warning, previous step restored."""
    net, trainer, mgr = _saved_two_steps(tmp_path)
    path = _step_file(mgr, 2, "arrays-proc0")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size - 40)
        b = f.read(1)
        f.seek(size - 40)
        f.write(bytes([b[0] ^ 0xFF]))
    assert mgr.all_steps() == [1, 2]  # completeness can't see bit rot
    net2, trainer2 = _make(seed=9)
    with pytest.warns(RuntimeWarning, match="falling back"):
        info = mgr.restore(net=net2, trainer=trainer2)
    assert info["step"] == 1
    with pytest.raises(CheckpointCorrupt):
        mgr.restore(step=2, net=net2)  # a pinned corrupt step RAISES


def test_restore_skips_partially_renamed_tmp_dir(tmp_path):
    """Crash mid-commit: some shard files renamed into the final dir but
    no meta.json yet, plus a leftover tmp dir.  Restore warns and falls
    back; a fresh manager sweeps this process's stale tmp dirs."""
    import shutil

    net, trainer, mgr = _saved_two_steps(tmp_path)
    partial = mgr._step_dir(3)
    os.makedirs(partial)
    shutil.copy(_step_file(mgr, 2, "state-proc0.pkl"),
                os.path.join(partial, "state-proc0.pkl"))
    tmp_left = mgr._step_dir(4) + ".tmp-0"
    os.makedirs(tmp_left)
    with open(os.path.join(tmp_left, "junk"), "w") as f:
        f.write("x")
    with pytest.warns(RuntimeWarning, match="incomplete"):
        info = mgr.restore(net=_make(seed=9)[0])
    assert info["step"] == 2
    CheckpointManager(str(tmp_path))  # constructor sweeps stale tmp dirs
    assert not os.path.exists(tmp_left)
    assert os.path.exists(partial)  # partial FINAL dirs are kept (evidence)


def test_prune_never_deletes_inflight_step(tmp_path):
    """A committed step whose write is (still) marked in flight must
    survive pruning — out-of-order async commits would otherwise let a
    newer save evict a step the worker is mid-write on."""
    net, trainer = _make()
    mgr = CheckpointManager(str(tmp_path), keep=1, async_save=False)
    for s in (1, 2):
        _train_steps(net, trainer, 1, start=s)
        mgr.save(s, net=net, trainer=trainer)
    assert mgr.all_steps() == [2]  # keep=1 pruned step 1
    with mgr._inflight_lock:
        mgr._inflight.add(2)
    _train_steps(net, trainer, 1, start=3)
    mgr.save(3, net=net, trainer=trainer)
    assert mgr.all_steps() == [2, 3]  # 2 was due for eviction but inflight
    with mgr._inflight_lock:
        mgr._inflight.discard(2)
    _train_steps(net, trainer, 1, start=4)
    mgr.save(4, net=net, trainer=trainer)
    assert mgr.all_steps() == [4]


def test_write_retries_transient_failures(tmp_path, monkeypatch):
    """Transient OSErrors retry with backoff; a hard failure surfaces
    on wait()/close() after the budget."""
    from incubator_mxnet_tpu.utils import serialization

    net, trainer = _make()
    real = serialization.save_ndarrays
    fails = {"n": 2}

    def flaky(path, arrays):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError("simulated transient write failure")
        return real(path, arrays)

    monkeypatch.setattr(serialization, "save_ndarrays", flaky)
    mgr = CheckpointManager(str(tmp_path), async_save=True, retries=3,
                            retry_backoff=0.01)
    mgr.save(1, net=net, trainer=trainer)
    mgr.close()
    assert mgr.all_steps() == [1]
    assert fails["n"] == 0

    monkeypatch.setattr(
        serialization, "save_ndarrays",
        lambda *a, **k: (_ for _ in ()).throw(OSError("disk gone")))
    mgr2 = CheckpointManager(str(tmp_path / "hard"), async_save=True,
                             retries=1, retry_backoff=0.01)
    mgr2.save(1, net=net, trainer=trainer)
    with pytest.raises(OSError, match="disk gone"):
        mgr2.close()


def test_async_save_telemetry(tmp_path):
    """The async path reports stall/write/bytes telemetry, and the
    caller-visible stall is far below the full write time."""
    telemetry.enable()
    telemetry.get_registry().clear()
    try:
        net, trainer = _make()
        _train_steps(net, trainer, 1)
        with CheckpointManager(str(tmp_path), async_save=True) as mgr:
            for s in (1, 2, 3):
                mgr.save(s, net=net, trainer=trainer)
        stall = telemetry.histogram("checkpoint_step_stall_seconds")
        write = telemetry.histogram("checkpoint_write_seconds")
        assert stall.count == 3
        assert write.count == 3
        assert telemetry.counter("checkpoint_bytes_total").value > 0
    finally:
        telemetry.get_registry().clear()
        telemetry.disable()


class _ResizeMLP(HybridBlock):
    """Tiny MLP with param sizes (30, 5, 15, 3) not all divisible by
    either mesh size — exercises re-flat-pad on BOTH D=8 and D=4."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.fc1 = nn.Dense(5, in_units=6, activation="tanh")
        self.fc2 = nn.Dense(3, in_units=5)

    def forward(self, x, y):
        pred = self.fc2(self.fc1(x))
        return ((pred - y) ** 2).mean()


def _make_mesh_mlp(mesh, seed=0):
    mx.random.seed(seed)
    model = _ResizeMLP()
    model.initialize()
    model(NDArray(jnp.ones((8, 6))), NDArray(jnp.ones((8, 3))))
    model.hybridize()
    trainer = Trainer(model.collect_params(), "sgd",
                      {"learning_rate": 0.05, "momentum": 0.9}, mesh=mesh)
    return model, trainer


def _train_mesh_steps(model, trainer, mesh, n, start=1):
    losses = []
    for step in range(start, start + n):
        key = jax.random.PRNGKey(2000 + step)
        kx, ky = jax.random.split(key)
        x = shard_batch(jax.random.normal(kx, (8, 6)), mesh)
        y = shard_batch(jax.random.normal(ky, (8, 3)), mesh)
        with autograd.record():
            loss = model(x, y)
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    return losses


def test_mesh_resize_restore_8_to_4(tmp_path, mesh8):
    """Elastic resume: ZeRO-1 state saved on data=8 restores onto a
    data=4 mesh — re-flat-padded and re-sliced shard-local — and the
    continued loss curve matches the uninterrupted data=8 run."""
    model, trainer = _make_mesh_mlp(mesh8)
    _train_mesh_steps(model, trainer, mesh8, 3)
    trainer.flush()
    assert trainer._zero_sig() == ("explicit", "data", 8)
    assert any(isinstance(s, zero_mod.Zero1State)
               for s in trainer._states.values())
    momentum_at_save = trainer.host_states()
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(3, net=model, trainer=trainer)

    mesh4 = par.create_mesh(data=4)
    model2, trainer2 = _make_mesh_mlp(mesh4, seed=9)
    info = mgr.restore(net=model2, trainer=trainer2)
    assert info["step"] == 3
    assert trainer2._zero_sig() == ("explicit", "data", 4)
    # state eagerly re-adopted onto the NEW data axis, shard-local
    zs = [s for s in trainer2._states.values()
          if isinstance(s, zero_mod.Zero1State)]
    assert zs and all(z.meta.D == 4 for z in zs)
    for k, st in trainer2._states.items():
        want = momentum_at_save[k]
        got = zero_mod.host_canonical(st) \
            if isinstance(st, zero_mod.Zero1State) else st
        for a, b in zip(jax.tree_util.tree_leaves(want),
                        jax.tree_util.tree_leaves(got)):
            onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                        rtol=1e-6, atol=1e-7)
    # loss-curve continuity: resized resume tracks the uninterrupted run
    ref = _train_mesh_steps(model, trainer, mesh8, 2, start=4)
    got = _train_mesh_steps(model2, trainer2, mesh4, 2, start=4)
    onp.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-5)
    p_ref = {n: onp.asarray(jax.device_get(p.data()._data))
             for n, p in model._collect_params_with_prefix().items()}
    p_got = {n: onp.asarray(jax.device_get(p.data()._data))
             for n, p in model2._collect_params_with_prefix().items()}
    for n in p_ref:
        onp.testing.assert_allclose(p_ref[n], p_got[n], err_msg=n,
                                    rtol=2e-3, atol=1e-4)


def test_zero_reshard_roundtrip(mesh8):
    """gluon.zero.reshard: D=8 → D=4 → canonical equals the original
    canonical (pure re-flat-pad + re-slice, no value drift)."""
    import math

    mesh4 = par.create_mesh(data=4)
    state = {"mom": jnp.arange(23, dtype=jnp.float32)}  # 23 % 8 != 0
    w = jnp.zeros((23,), jnp.float32)
    z8 = zero_mod.adopt(state, w, 8, mesh8, "data", mp=False)
    z4 = zero_mod.reshard(z8, 4, mesh4, "data")
    assert z4.meta.D == 4
    assert z4.meta.npad == -(-23 // 4) * 4
    onp.testing.assert_array_equal(
        onp.asarray(zero_mod.canonical(z4)["mom"]),
        onp.asarray(state["mom"]))
    assert zero_mod.reshard(z4, 4, mesh4, "data") is z4  # same-D no-op


def test_autoresume_heartbeat_kills_hung_job(tmp_path):
    """A job that stops heartbeating is detected and killed (the
    barrier-timeout failure mode), then the restart budget applies."""
    hb = str(tmp_path / "hb")
    hang = str(tmp_path / "hang.py")
    with open(hang, "w") as f:
        f.write(
            "import sys, time\n"
            f"open({hb!r}, 'w').write('x')\n"
            "time.sleep(600)\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "autoresume.py"),
         "--max-restarts", "0", "--heartbeat-file", hb,
         "--heartbeat-timeout", "2", "--poll-interval", "0.2", "--",
         sys.executable, hang],
        timeout=120, capture_output=True, text=True)
    assert proc.returncode != 0
    assert "heartbeat stale" in proc.stderr
