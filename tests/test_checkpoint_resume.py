"""Checkpoint/resume + elastic autoresume (VERDICT r1 #7; SURVEY.md
§5.3/§5.4 — the build must EXCEED the reference here)."""
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.gluon import Trainer, nn
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.utils.checkpoint import CheckpointManager

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _train_steps(net, trainer, n, start=1):
    for step in range(start, start + n):
        key = jax.random.PRNGKey(1000 + step)
        x = NDArray(jax.random.normal(key, (2, 6)))
        with autograd.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(1)


def _make(seed=0):
    mx.random.seed(seed)
    net = nn.Dense(4, in_units=6)
    net.initialize()
    net(NDArray(jnp.ones((2, 6))))
    trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 0.05})
    return net, trainer


def test_full_state_roundtrip(tmp_path):
    net, trainer = _make()
    _train_steps(net, trainer, 3)
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(3, net=net, trainer=trainer, iterator_state={"cursor": 42},
             extra={"epoch": 1})
    w_before = net.weight.data().asnumpy()

    net2, trainer2 = _make(seed=9)  # different init — restore must override
    mgr2 = CheckpointManager(str(tmp_path))
    info = mgr2.restore(net=net2, trainer=trainer2)
    assert info["step"] == 3
    assert info["iterator_state"] == {"cursor": 42}
    assert info["extra"] == {"epoch": 1}
    onp.testing.assert_array_equal(net2.weight.data().asnumpy(), w_before)
    # optimizer state (adam m/v + counts) restored
    assert trainer2._optimizer.num_update == trainer._optimizer.num_update
    # continued training is BIT-EXACT vs the uninterrupted run
    _train_steps(net, trainer, 2, start=4)
    _train_steps(net2, trainer2, 2, start=4)
    onp.testing.assert_array_equal(net.weight.data().asnumpy(),
                                   net2.weight.data().asnumpy())


def test_async_save_and_retention(tmp_path):
    net, trainer = _make()
    mgr = CheckpointManager(str(tmp_path), keep=2, async_save=True)
    for s in (1, 2, 3, 4):
        _train_steps(net, trainer, 1, start=s)
        mgr.save(s, net=net, trainer=trainer)
    mgr.wait()
    assert mgr.all_steps() == [3, 4]  # pruned to keep=2
    assert mgr.latest_step() == 4


def test_close_joins_worker_and_flushes(tmp_path):
    """tpulint TPU012 regression: close() must flush queued saves and
    JOIN the worker (previously the daemon thread was never joined —
    interpreter exit could kill it mid-write)."""
    net, trainer = _make()
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    mgr.save(1, net=net, trainer=trainer)
    worker = mgr._worker
    assert worker is not None and worker.is_alive()
    mgr.close()
    assert not worker.is_alive()          # joined, not abandoned
    assert mgr._worker is None
    assert mgr.all_steps() == [1]         # queued write landed before join
    mgr.close()                           # idempotent
    # save() after close() restarts the worker transparently
    mgr.save(2, net=net, trainer=trainer)
    mgr.close()
    assert mgr.all_steps() == [1, 2]


def test_close_as_context_manager(tmp_path):
    net, trainer = _make()
    with CheckpointManager(str(tmp_path), async_save=True) as mgr:
        mgr.save(1, net=net, trainer=trainer)
    assert mgr._worker is None
    assert mgr.all_steps() == [1]


def test_worker_error_surfaces_on_close(tmp_path):
    """tpulint TPU011 regression: the worker's error handoff is now
    lock-guarded and close()/wait() re-raise the pending exception."""
    net, trainer = _make()
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    boom = RuntimeError("disk full")
    with mgr._err_lock:
        mgr._error = boom                 # as if _drain had failed
    with pytest.raises(RuntimeError, match="disk full"):
        mgr.close()
    # the error is consumed — the manager is usable again
    mgr.save(1, net=net, trainer=trainer)
    mgr.close()
    assert mgr.all_steps() == [1]


def test_kill_and_resume_bit_exact(tmp_path):
    """Kill a training process mid-run; autoresume restarts it; the final
    weights equal an uninterrupted run (≤1 step of work lost, replayed)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            env.pop(k)
    worker = os.path.join(_ROOT, "tests", "ckpt_worker.py")

    # uninterrupted reference
    ref_out = str(tmp_path / "ref.npy")
    subprocess.run([sys.executable, worker, str(tmp_path / "ck_ref"), "8",
                    "-1", ref_out], env=env, check=True, timeout=300,
                   capture_output=True, text=True)

    # crashing run under the autoresume supervisor
    crash_out = str(tmp_path / "crash.npy")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "autoresume.py"),
         "--max-restarts", "2", "--",
         sys.executable, worker, str(tmp_path / "ck_crash"), "8", "5",
         crash_out],
        env=env, timeout=600, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "restarting" in proc.stderr
    onp.testing.assert_array_equal(onp.load(ref_out), onp.load(crash_out))


def test_autoresume_heartbeat_kills_hung_job(tmp_path):
    """A job that stops heartbeating is detected and killed (the
    barrier-timeout failure mode), then the restart budget applies."""
    hb = str(tmp_path / "hb")
    hang = str(tmp_path / "hang.py")
    with open(hang, "w") as f:
        f.write(
            "import sys, time\n"
            f"open({hb!r}, 'w').write('x')\n"
            "time.sleep(600)\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "autoresume.py"),
         "--max-restarts", "0", "--heartbeat-file", hb,
         "--heartbeat-timeout", "2", "--poll-interval", "0.2", "--",
         sys.executable, hang],
        timeout=120, capture_output=True, text=True)
    assert proc.returncode != 0
    assert "heartbeat stale" in proc.stderr
