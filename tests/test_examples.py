"""Example scripts as CI gates (VERDICT r1 #3; ref `tests/python/train/`
small-real-training accuracy gates, SURVEY.md §4 "Training integration").

Each example runs in-process with a reduced configuration; the MNIST
gate enforces the reference's ≥98% accuracy bar.
"""
import os
import sys

import pytest

_EX = os.path.join(os.path.dirname(__file__), "..", "examples")
for sub in ("gluon", "image_classification", "nlp", "face"):
    sys.path.insert(0, os.path.join(_EX, sub))


def test_mnist_gate():
    import importlib

    mnist = importlib.import_module("mnist")
    acc = mnist.main(["--epochs", "3", "--train-samples", "2000"])
    assert acc >= 0.98, f"MNIST gate failed: {acc}"


def test_image_classification_train_smoke():
    import importlib

    train_mod = importlib.import_module("train")
    args = train_mod.build_parser().parse_args(
        ["--network", "resnet18_v1", "--image-shape", "3,32,32",
         "--batch-size", "8", "--num-epochs", "1", "--max-batches", "4",
         "--synthetic-samples", "64"])
    img_s, acc = train_mod.train(args)
    assert img_s > 0
    assert 0.0 <= acc <= 1.0


def test_benchmark_score_smoke():
    import importlib

    bs = importlib.import_module("benchmark_score")
    args = bs.build_parser().parse_args(
        ["--network", "resnet18_v1", "--image-shape", "3,32,32",
         "--num-classes", "10", "--batch-sizes", "2", "--num-batches", "3"])
    results = bs.score(args)
    assert results and results[0][1] > 0


def test_pipeline_bert_example_gate():
    """GluonPipeline example: loss must drop on the copy task."""
    import importlib

    mod = importlib.import_module("pipeline_bert")
    first, last = mod.main(["--steps", "12"])
    assert last < first * 0.7, (first, last)


def test_transformer_learns_copy_task():
    import importlib

    tt = importlib.import_module("train_transformer")
    args = tt.build_parser().parse_args(
        ["--model", "tiny", "--steps", "80", "--batch-size", "32",
         "--seq-len", "8", "--vocab", "16", "--warmup", "10"])
    acc = tt.train(args)
    assert acc > 0.9, f"copy-task greedy accuracy too low: {acc}"


def test_arcface_sharded_learns():
    import importlib

    af = importlib.import_module("train_arcface")
    args = af.build_parser().parse_args(
        ["--steps", "60", "--num-identities", "16", "--batch-size", "32",
         "--data-parallel", "4", "--model-parallel", "2"])
    acc = af.train(args)
    assert acc > 0.9, f"arcface sharded training failed to separate ids: {acc}"


def test_word_language_model_learns():
    """The LSTM LM must compress the Markov corpus below uniform ppl."""
    import importlib

    wlm = importlib.import_module("word_language_model")
    final_ppl, uniform = wlm.main(
        ["--epochs", "3", "--corpus-tokens", "6000", "--vocab", "50",
         "--bptt", "16", "--batch-size", "10", "--emsize", "48",
         "--nhid", "48", "--lr", "10", "--log-interval", "1000"])
    assert final_ppl < 0.9 * uniform, \
        f"LM did not learn: ppl {final_ppl} vs uniform {uniform}"


def test_dc_gan_adversarial_smoke():
    """DCGAN: both losses finite, discriminator not saturated to 0."""
    import importlib

    gan = importlib.import_module("dc_gan")
    hist = gan.main(["--epochs", "1", "--max-batches", "8",
                     "--batch-size", "16", "--ngf", "16", "--ndf", "16",
                     "--num-samples", "128", "--log-interval", "2"])
    assert hist, "no loss history recorded"
    import numpy as onp

    d_losses = [d for d, _ in hist]
    g_losses = [g for _, g in hist]
    assert all(onp.isfinite(d_losses)) and all(onp.isfinite(g_losses))
    assert d_losses[-1] > 1e-3, "discriminator saturated (mode collapse)"


def test_long_context_ring_lm_learns():
    """Induction across ring-shard boundaries: only cross-shard attention
    can solve the task (period == T/seq_parallel * 8 > one shard)."""
    import importlib

    lm = importlib.import_module("long_context_lm")
    losses = lm.main(["--seq-len", "64", "--steps", "300", "--d-model", "64",
                      "--d-ff", "128", "--seq-parallel", "8",
                      "--data-parallel", "1", "--batch-size", "8",
                      "--log-interval", "100"])
    assert losses[0] > 3.5, "should start near uniform"
    assert losses[-1] < 1.0, f"ring LM did not learn: {losses}"
