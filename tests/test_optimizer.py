"""Optimizer numeric tests vs hand-written NumPy references.

The reference's `test_optimizer.py` pattern (SURVEY.md §4): each update
rule is replayed in pure NumPy for several steps and compared, plus
behavioral tests (quadratic convergence), hyper-parameter plumbing
(lr_mult/wd_mult/clip/rescale), multi-precision, and Updater state I/O.
"""
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import optimizer as opt_mod
from incubator_mxnet_tpu.ndarray.ndarray import NDArray

SHAPE = (4, 3)


def _wg(seed=0):
    rng = onp.random.RandomState(seed)
    w = rng.uniform(-1, 1, SHAPE).astype("float32")
    gs = [rng.uniform(-1, 1, SHAPE).astype("float32") for _ in range(3)]
    return w, gs


def _run_opt(name, np_ref, opt_kwargs, steps=3, rtol=1e-5, atol=1e-6):
    """Run N updates through the framework and through np_ref; compare."""
    w0, gs = _wg()
    opt = opt_mod.create(name, **opt_kwargs)
    wnd = NDArray(jnp.asarray(w0))
    state = opt.create_state(0, wnd)
    for g in gs[:steps]:
        state = opt.update(0, wnd, NDArray(jnp.asarray(g)), state)
    w_ref = np_ref(w0.copy(), gs[:steps], **opt_kwargs)
    onp.testing.assert_allclose(wnd.asnumpy(), w_ref, rtol=rtol, atol=atol)


# ---- NumPy reference implementations --------------------------------- #
def ref_sgd(w, gs, learning_rate=0.1, wd=0.0, **_):
    for g in gs:
        w -= learning_rate * (g + wd * w)
    return w


def ref_sgd_mom(w, gs, learning_rate=0.1, momentum=0.9, wd=0.0, **_):
    mom = onp.zeros_like(w)
    for g in gs:
        g = g + wd * w
        mom = momentum * mom - learning_rate * g
        w = w + mom
    return w


def ref_nag(w, gs, learning_rate=0.1, momentum=0.9, wd=0.0, **_):
    mom = onp.zeros_like(w)
    for g in gs:
        g = g + wd * w
        mom = momentum * mom + g
        w = w - learning_rate * (g + momentum * mom)
    return w


def ref_adam(w, gs, learning_rate=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8,
             wd=0.0, **_):
    m = onp.zeros_like(w)
    v = onp.zeros_like(w)
    for t, g in enumerate(gs, 1):
        g = g + wd * w
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * g * g
        lr_t = learning_rate * onp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
        w = w - lr_t * m / (onp.sqrt(v) + epsilon)
    return w


def ref_adamw(w, gs, learning_rate=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8,
              wd=0.01, **_):
    m = onp.zeros_like(w)
    v = onp.zeros_like(w)
    for t, g in enumerate(gs, 1):
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * g * g
        lr_t = learning_rate * onp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
        w = w - lr_t * m / (onp.sqrt(v) + epsilon) - learning_rate * wd * w
    return w


def ref_rmsprop(w, gs, learning_rate=0.01, rho=0.9, epsilon=1e-8, **_):
    n = onp.zeros_like(w)
    for g in gs:
        n = rho * n + (1 - rho) * g * g
        w = w - learning_rate * g / (onp.sqrt(n) + epsilon)
    return w


def ref_adagrad(w, gs, learning_rate=0.05, eps=1e-7, **_):
    h = onp.zeros_like(w)
    for g in gs:
        h = h + g * g
        w = w - learning_rate * g / (onp.sqrt(h) + eps)
    return w


def ref_adadelta(w, gs, rho=0.9, epsilon=1e-5, **_):
    ag = onp.zeros_like(w)
    ad = onp.zeros_like(w)
    for g in gs:
        ag = rho * ag + (1 - rho) * g * g
        d = onp.sqrt(ad + epsilon) / onp.sqrt(ag + epsilon) * g
        ad = rho * ad + (1 - rho) * d * d
        w = w - d
    return w


def ref_signum(w, gs, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **_):
    mom = onp.zeros_like(w)
    for g in gs:
        mom = momentum * mom - (1 - momentum) * g
        w = (1 - learning_rate * wd_lh) * w + learning_rate * onp.sign(mom)
    return w


def ref_adamax(w, gs, learning_rate=0.002, beta1=0.9, beta2=0.999, **_):
    m = onp.zeros_like(w)
    u = onp.zeros_like(w)
    for t, g in enumerate(gs, 1):
        lr_t = learning_rate / (1 - beta1 ** t)
        m = beta1 * m + (1 - beta1) * g
        u = onp.maximum(beta2 * u, onp.abs(g))
        w = w - lr_t * m / (u + 1e-8)
    return w


def ref_ftrl(w, gs, learning_rate=0.1, lamda1=0.01, beta=1.0, wd=0.0, **_):
    z = onp.zeros_like(w)
    n = onp.zeros_like(w)
    for g in gs:
        n_new = n + g * g
        sigma = (onp.sqrt(n_new) - onp.sqrt(n)) / learning_rate
        z = z + g - sigma * w
        n = n_new
        w = onp.where(onp.abs(z) > lamda1,
                      -(z - onp.sign(z) * lamda1)
                      / ((beta + onp.sqrt(n)) / learning_rate + wd), 0.0)
    return w.astype("float32")


def ref_lars(w, gs, learning_rate=0.1, momentum=0.9, eta=0.001, epsilon=1e-8,
             wd=0.0, **_):
    mom = onp.zeros_like(w)
    for g in gs:
        wn = onp.linalg.norm(w)
        gn = onp.linalg.norm(g)
        local = eta * wn / (gn + wd * wn + epsilon) if wn > 0 and gn > 0 else 1.0
        g = g + wd * w
        mom = momentum * mom + local * learning_rate * g
        w = w - mom
    return w


def ref_lamb(w, gs, learning_rate=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6,
             wd=0.0, **_):
    m = onp.zeros_like(w)
    v = onp.zeros_like(w)
    for t, g in enumerate(gs, 1):
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * g * g
        m_hat = m / (1 - beta1 ** t)
        v_hat = v / (1 - beta2 ** t)
        upd = m_hat / (onp.sqrt(v_hat) + epsilon) + wd * w
        wn = onp.linalg.norm(w)
        un = onp.linalg.norm(upd)
        ratio = wn / un if wn > 0 and un > 0 else 1.0
        w = w - learning_rate * ratio * upd
    return w


_CASES = [
    ("sgd", ref_sgd, {"learning_rate": 0.1, "wd": 0.01}),
    ("sgd", ref_sgd_mom, {"learning_rate": 0.1, "momentum": 0.9}),
    ("nag", ref_nag, {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", ref_adam, {"learning_rate": 0.01}),
    ("adamw", ref_adamw, {"learning_rate": 0.01, "wd": 0.01}),
    ("rmsprop", ref_rmsprop, {"learning_rate": 0.01, "momentum": 0.0}),
    ("adagrad", ref_adagrad, {"learning_rate": 0.05}),
    ("adadelta", ref_adadelta, {}),
    ("signum", ref_signum, {"learning_rate": 0.01, "momentum": 0.9}),
    ("adamax", ref_adamax, {}),
    ("ftrl", ref_ftrl, {"learning_rate": 0.1}),
    ("lars", ref_lars, {"learning_rate": 0.1, "momentum": 0.9}),
    ("lamb", ref_lamb, {"learning_rate": 0.01}),
]


@pytest.mark.parametrize("name,ref,kwargs", _CASES,
                         ids=[f"{c[0]}-{i}" for i, c in enumerate(_CASES)])
def test_update_matches_numpy(name, ref, kwargs):
    _run_opt(name, ref, kwargs, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("name", ["sgd", "nag", "adam", "adamw", "rmsprop",
                                  "adagrad", "adadelta", "ftrl", "lamb",
                                  "lars", "signum", "nadam", "adamax",
                                  "dcasgd", "sgld"])
def test_optimizer_decreases_quadratic(name):
    """Behavioral: every registered optimizer reduces ||w||^2."""
    mx.random.seed(7)  # SGLD noise must be reproducible
    kwargs = {"learning_rate": 0.05}
    steps = 10
    if name == "sgld":
        kwargs["learning_rate"] = 0.01
        steps = 50  # let the drift term dominate the injected noise
    opt = opt_mod.create(name, **kwargs)
    w = NDArray(jnp.asarray(onp.full(SHAPE, 2.0, "float32")))
    state = opt.create_state(0, w)
    f0 = float((w.asnumpy() ** 2).sum())
    for _ in range(steps):
        g = NDArray(2.0 * w._data)  # d/dw ||w||^2
        state = opt.update(0, w, g, state)
    f1 = float((w.asnumpy() ** 2).sum())
    assert f1 < f0, f"{name}: {f0} -> {f1}"


def test_rescale_and_clip():
    w0 = onp.ones(SHAPE, "float32")
    g = onp.full(SHAPE, 10.0, "float32")
    opt = opt_mod.create("sgd", learning_rate=1.0, rescale_grad=0.1,
                        clip_gradient=0.5)
    w = NDArray(jnp.asarray(w0))
    opt.update(0, w, NDArray(jnp.asarray(g)), None)
    # g*0.1 = 1.0 clipped to 0.5 -> w = 1 - 0.5
    onp.testing.assert_allclose(w.asnumpy(), 0.5 * onp.ones(SHAPE), rtol=1e-6)


def test_lr_wd_mult():
    w0 = onp.ones(SHAPE, "float32")
    g = onp.ones(SHAPE, "float32")
    opt = opt_mod.create("sgd", learning_rate=0.1, wd=0.1)
    opt.set_lr_mult({0: 0.5})
    opt.set_wd_mult({0: 0.0})
    w = NDArray(jnp.asarray(w0))
    opt.update(0, w, NDArray(jnp.asarray(g)), None)
    onp.testing.assert_allclose(w.asnumpy(), w0 - 0.05 * g, rtol=1e-6)


def test_multi_precision_master_weights():
    w0 = onp.random.RandomState(0).uniform(-1, 1, SHAPE).astype("float32")
    opt = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9,
                        multi_precision=True)
    w = NDArray(jnp.asarray(w0, jnp.bfloat16))
    state = opt.create_state_multi_precision(0, w)
    assert state[0].dtype == jnp.float32  # fp32 master
    g = NDArray(jnp.asarray(onp.ones(SHAPE, "float32"), jnp.bfloat16))
    state = opt.update_multi_precision(0, w, g, state)
    assert w._data.dtype == jnp.bfloat16
    # master tracks full precision: one momentum-SGD step from w0
    onp.testing.assert_allclose(onp.asarray(state[0]), w0 - 0.1, rtol=1e-3, atol=1e-3)


def test_lr_scheduler_plumbs_into_update():
    from incubator_mxnet_tpu import lr_scheduler

    sched = lr_scheduler.FactorScheduler(step=2, factor=0.5, base_lr=1.0)
    opt = opt_mod.create("sgd", learning_rate=1.0, lr_scheduler=sched)
    w = NDArray(jnp.zeros(SHAPE))
    g = NDArray(jnp.ones(SHAPE))
    lr0 = opt.learning_rate
    for _ in range(4):
        opt.update(0, w, g, None)
    lr4 = opt.learning_rate
    assert lr4 < lr0  # factor decay kicked in via num_update
    assert opt.num_update == 4


def test_custom_optimizer_legacy_update_override():
    """Subclasses overriding only update() (the reference extension point)
    must keep working through update_multi_precision (r2 review fix)."""

    class MyOpt(opt_mod.Optimizer):
        def create_state(self, index, weight):
            return None

        def update(self, index, weight, grad, state):
            weight._data = weight._data - 0.5 * grad._data
            return state

    opt = MyOpt()
    w = NDArray(jnp.ones(SHAPE))
    state = opt.create_state_multi_precision(0, w)
    opt.update_multi_precision(0, w, NDArray(jnp.ones(SHAPE)), state)
    onp.testing.assert_allclose(w.asnumpy(), 0.5 * onp.ones(SHAPE), rtol=1e-6)


def test_updater_states_roundtrip():
    opt = opt_mod.create("adam", learning_rate=0.01)
    upd = opt_mod.get_updater(opt)
    w = NDArray(jnp.ones(SHAPE))
    upd(0, NDArray(jnp.ones(SHAPE)), w)
    blob = upd.get_states()
    upd2 = opt_mod.get_updater(opt_mod.create("adam", learning_rate=0.01))
    upd2.set_states(blob)
    assert 0 in upd2.states
    m1 = onp.asarray(upd.states[0][0])
    m2 = onp.asarray(upd2.states[0][0])
    onp.testing.assert_allclose(m1, m2, rtol=1e-6)


def test_create_by_name_and_instance():
    o1 = opt_mod.create("sgd", learning_rate=0.3)
    assert isinstance(o1, opt_mod.SGD) and o1.learning_rate == 0.3
    o2 = opt_mod.create(o1)
    assert o2 is o1
    with pytest.raises(Exception):
        opt_mod.create("definitely_not_an_optimizer")


def test_nadam_schedule_in_state():
    """Nadam's momentum-schedule product lives in per-param state (pure)."""
    opt = opt_mod.create("nadam", learning_rate=0.01)
    w = NDArray(jnp.ones(SHAPE))
    state = opt.create_state(0, w)
    assert len(state) == 3  # (m, v, m_schedule)
    s1 = opt.update(0, w, NDArray(jnp.ones(SHAPE)), state)
    assert float(s1[2]) < 1.0  # schedule product advanced
