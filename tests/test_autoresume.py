"""tools/autoresume.py unit coverage (ISSUE 11 satellite — the
supervisor previously had zero tests of its own; the kill-and-resume
integration lives in test_checkpoint_resume.py and ci/resume_smoke.py).

Covers the hardened contract: exponential backoff between restarts,
SIGTERM→grace→SIGKILL escalation for hung children, and propagation of
the child's final exit code (128+signum for signal deaths)."""
import os
import signal
import subprocess
import sys
import time

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

import autoresume  # noqa: E402


def _run(args, timeout=120):
    # fast default poll so the supervisor notices child exits promptly;
    # tests passing their own --poll-interval override it (last wins)
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "autoresume.py"),
         "--poll-interval", "0.05"]
        + args, timeout=timeout, capture_output=True, text=True)


def test_exit_code_mapping():
    assert autoresume._exit_code(0) == 0
    assert autoresume._exit_code(7) == 7
    assert autoresume._exit_code(-signal.SIGTERM) == 128 + signal.SIGTERM
    assert autoresume._exit_code(-signal.SIGKILL) == 128 + signal.SIGKILL


def test_success_passthrough(tmp_path):
    rc = autoresume.supervise([sys.executable, "-c", "pass"],
                              max_restarts=0)
    assert rc == 0


def test_final_exit_code_propagates(tmp_path):
    """After the restart budget is exhausted the supervisor exits with
    the CHILD's final exit code, not a generic 1."""
    proc = _run(["--max-restarts", "1", "--backoff", "0.05", "--",
                 sys.executable, "-c", "import sys; sys.exit(7)"])
    assert proc.returncode == 7
    assert "restart budget exhausted" in proc.stderr


def test_signal_death_maps_to_128_plus_signum(tmp_path):
    proc = _run(["--max-restarts", "0", "--",
                 sys.executable, "-c",
                 "import os, signal; os.kill(os.getpid(), signal.SIGTERM)"])
    assert proc.returncode == 128 + signal.SIGTERM


def test_exponential_backoff_between_restarts(tmp_path):
    """Consecutive restarts sleep backoff, 2*backoff, ... — visible both
    in the log lines and in the wall clock."""
    t0 = time.time()
    proc = _run(["--max-restarts", "3", "--backoff", "0.2", "--",
                 sys.executable, "-c", "import sys; sys.exit(3)"])
    elapsed = time.time() - t0
    assert proc.returncode == 3
    assert "restarting in 0.2s (1/3)" in proc.stderr
    assert "restarting in 0.4s (2/3)" in proc.stderr
    assert "restarting in 0.8s (3/3)" in proc.stderr
    assert elapsed >= 0.2 + 0.4 + 0.8


def test_backoff_capped(tmp_path):
    proc = _run(["--max-restarts", "2", "--backoff", "0.2",
                 "--backoff-max", "0.3", "--",
                 sys.executable, "-c", "import sys; sys.exit(3)"])
    assert "restarting in 0.2s (1/2)" in proc.stderr
    assert "restarting in 0.3s (2/2)" in proc.stderr


def test_hung_child_gets_sigterm_then_exits(tmp_path):
    """A stale-heartbeat child that honors SIGTERM is terminated
    gracefully (no SIGKILL) — the window the flight recorder and the
    checkpoint worker rely on."""
    hb = str(tmp_path / "hb")
    marker = str(tmp_path / "got_term")
    hang = str(tmp_path / "hang.py")
    with open(hang, "w") as f:
        f.write(
            "import signal, sys, time\n"
            f"open({hb!r}, 'w').write('x')\n"
            "def onterm(sig, frame):\n"
            f"    open({marker!r}, 'w').write('term')\n"
            "    sys.exit(9)\n"
            "signal.signal(signal.SIGTERM, onterm)\n"
            "time.sleep(600)\n")
    proc = _run(["--max-restarts", "0", "--heartbeat-file", hb,
                 "--heartbeat-timeout", "1", "--poll-interval", "0.1",
                 "--grace", "10", "--", sys.executable, hang])
    assert proc.returncode == 9          # child's graceful exit code
    assert "heartbeat stale" in proc.stderr
    assert os.path.exists(marker)        # SIGTERM handler actually ran


def test_hung_child_ignoring_sigterm_is_sigkilled(tmp_path):
    """Escalation backstop: a child wedged past SIGTERM is SIGKILLed
    after the grace window."""
    hb = str(tmp_path / "hb")
    hang = str(tmp_path / "hang.py")
    with open(hang, "w") as f:
        f.write(
            "import signal, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            f"open({hb!r}, 'w').write('x')\n"
            "time.sleep(600)\n")
    proc = _run(["--max-restarts", "0", "--heartbeat-file", hb,
                 "--heartbeat-timeout", "1", "--poll-interval", "0.1",
                 "--grace", "0.5", "--", sys.executable, hang])
    assert proc.returncode == 128 + signal.SIGKILL
    assert "heartbeat stale" in proc.stderr


def test_supervisor_forwards_sigterm_to_child(tmp_path):
    """Preemption hits the supervisor first: it forwards the signal to
    the child (grace escalation) and exits with the child's code —
    never orphaning the training process."""
    marker = str(tmp_path / "child_term")
    child = str(tmp_path / "child.py")
    with open(child, "w") as f:
        f.write(
            "import signal, sys, time\n"
            "def onterm(sig, frame):\n"
            f"    open({marker!r}, 'w').write('term')\n"
            "    sys.exit(11)\n"
            "signal.signal(signal.SIGTERM, onterm)\n"
            "print('READY', flush=True)\n"
            "time.sleep(600)\n")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_ROOT, "tools", "autoresume.py"),
         "--max-restarts", "0", "--poll-interval", "0.1", "--",
         sys.executable, child],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    # wait for the grandchild to be up before signalling the supervisor
    deadline = time.time() + 60
    line = ""
    while time.time() < deadline and "READY" not in line:
        line += proc.stdout.readline()
    assert "READY" in line
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=60)
    assert proc.returncode == 11         # child's exit code, propagated
    assert "forwarding to job" in err
    assert os.path.exists(marker)        # child saw the forwarded TERM


def test_no_command_is_usage_error():
    proc = _run(["--max-restarts", "0"])
    assert proc.returncode == 2
    assert "no command given" in proc.stderr
