"""Worker for the kill-and-resume test: trains a small net with
deterministic per-step data, checkpointing every step; optionally
crashes at a given step (first run only) to exercise autoresume."""
import os
import sys


def main():
    import jax
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, nn
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.utils.checkpoint import CheckpointManager

    ckpt_dir = sys.argv[1]
    total_steps = int(sys.argv[2])
    crash_at = int(sys.argv[3])  # -1 = never
    out_file = sys.argv[4]
    heartbeat = sys.argv[5] if len(sys.argv) > 5 else None

    mx.random.seed(0)
    net = nn.Dense(4, in_units=6)
    net.initialize()
    net(NDArray(jnp.ones((2, 6))))
    trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 0.05})
    mgr = CheckpointManager(ckpt_dir, keep=2, async_save=False)

    start = 0
    if mgr.latest_step() is not None:
        info = mgr.restore(net=net, trainer=trainer)
        start = info["step"]
        print(f"resumed from step {start}", flush=True)

    for step in range(start + 1, total_steps + 1):
        # deterministic per-step batch: resume must replay identically
        key = jax.random.PRNGKey(1000 + step)
        x = NDArray(jax.random.normal(key, (2, 6)))
        with autograd.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(1)
        mgr.save(step, net=net, trainer=trainer)
        if heartbeat:
            with open(heartbeat, "w") as f:
                f.write(str(step))
        if step == crash_at and not os.path.exists(out_file + ".crashed"):
            open(out_file + ".crashed", "w").close()
            print(f"simulated crash at step {step}", flush=True)
            os._exit(17)

    mgr.wait()
    import numpy as onp

    onp.save(out_file, net.weight.data().asnumpy())
    print(f"done at step {total_steps}", flush=True)


if __name__ == "__main__":
    main()
