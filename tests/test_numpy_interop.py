"""mx.np / mx.npx interoperability (VERDICT r1 #8; ref
`test_numpy_interoperability.py` / `test_numpy_op.py` patterns)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx

np = mx.np
npx = mx.npx


def test_ndarray_type_and_creation():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    assert isinstance(a, np.ndarray)
    assert isinstance(a, mx.nd.NDArray)  # subtype of the core handle
    assert a.shape == (2, 2) and str(a.dtype) == "float32"
    for f, want in [(lambda: np.zeros((2, 3)), onp.zeros((2, 3))),
                    (lambda: np.ones((2, 3)), onp.ones((2, 3))),
                    (lambda: np.full((2,), 7.0), onp.full((2,), 7.0)),
                    (lambda: np.arange(5), onp.arange(5)),
                    (lambda: np.eye(3), onp.eye(3)),
                    (lambda: np.linspace(0, 1, 5), onp.linspace(0, 1, 5))]:
        got = f()
        assert isinstance(got, np.ndarray)
        onp.testing.assert_allclose(got.asnumpy(), want, rtol=1e-6)


def test_type_propagates_through_ops():
    a = np.array([1.0, 2.0, 3.0])
    b = np.array([4.0, 5.0, 6.0])
    for out in (a + b, a * 2, np.tanh(a), np.dot(a, b), a[1:], a.reshape(3, 1),
                np.concatenate([a, b]), np.where(a > 1, a, b)):
        assert isinstance(out, np.ndarray), type(out)


def test_numpy_broadcasting_and_promotion():
    a = np.ones((3, 1)) * 2
    b = np.arange(4).astype("float32")
    c = a + b  # (3,1)+(4,) -> (3,4) numpy broadcasting
    assert c.shape == (3, 4)
    i = np.array([1, 2], dtype="int32")
    f = np.array([0.5, 0.5], dtype="float32")
    assert "float" in str((i + f).dtype)


def test_boolean_mask_indexing():
    a = np.arange(6).astype("float32")
    m = a > 2
    got = a[m]
    onp.testing.assert_allclose(got.asnumpy(), [3, 4, 5])


def test_reductions_and_linalg():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    assert float(np.sum(a).asnumpy()) == 10.0
    assert float(np.mean(a).asnumpy()) == 2.5
    onp.testing.assert_allclose(np.linalg.norm(a).asnumpy(),
                                onp.linalg.norm([[1, 2], [3, 4]]), rtol=1e-6)
    inv = np.linalg.inv(a)
    assert isinstance(inv, np.ndarray)
    onp.testing.assert_allclose((np.dot(a, inv)).asnumpy(), onp.eye(2), atol=1e-5)


def test_random_namespace():
    np.random.seed(0)
    u = np.random.uniform(0, 1, size=(100,))
    assert isinstance(u, np.ndarray)
    assert 0 <= float(u.asnumpy().min()) and float(u.asnumpy().max()) <= 1
    np.random.seed(0)
    u2 = np.random.uniform(0, 1, size=(100,))
    onp.testing.assert_array_equal(u.asnumpy(), u2.asnumpy())
    r = np.random.randint(0, 5, size=(50,))
    assert r.asnumpy().max() < 5


def test_autograd_through_np_ops():
    from incubator_mxnet_tpu import autograd

    x = np.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = np.sum(np.tanh(x) ** 2)
    y.backward()
    g = x.grad.asnumpy()
    want = 2 * onp.tanh([1, 2, 3]) * (1 - onp.tanh([1, 2, 3]) ** 2)
    onp.testing.assert_allclose(g, want, rtol=1e-5)


def test_nd_np_conversion():
    a = mx.nd.array([[1.0, 2.0]])
    b = np.from_nd(a)
    assert isinstance(b, np.ndarray)
    onp.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
    c = b.as_nd_ndarray()
    assert type(c) is mx.nd.NDArray
    onp.testing.assert_array_equal(c.asnumpy(), b.asnumpy())


def test_npx_ops():
    x = np.array([[-1.0, 2.0], [3.0, -4.0]])
    r = npx.relu(x)
    assert isinstance(r, np.ndarray)
    onp.testing.assert_allclose(r.asnumpy(), [[0, 2], [3, 0]])
    s = npx.softmax(x)
    onp.testing.assert_allclose(s.asnumpy().sum(-1), [1, 1], rtol=1e-5)
    oh = npx.one_hot(np.array([0, 1]), 3)
    assert oh.shape == (2, 3)


def test_npx_np_mode_flags():
    assert not npx.is_np_array()
    npx.set_np()
    assert npx.is_np_array() and npx.is_np_shape()
    npx.reset_np()
    assert not npx.is_np_array()


def test_np_constants_and_tolist():
    assert np.pi == pytest.approx(onp.pi)
    assert np.inf == onp.inf
    a = np.array([[1, 2]])
    assert a.tolist() == [[1, 2]]
