"""Vision (channel-parallel) TP rules — the first non-transformer
consumer of the sharding rules engine (r4 VERDICT item 8).

`TP_RULES_VISION` shards conv weights (OIHW) on the OUT-channel dim and
Dense classifier weights column-parallel over the 'model' mesh axis;
BN/bias stay replicated by rule.  Parity: forward + backward + one
Trainer step of a small conv net on a model=2 mesh must match the
single-device oracle bit-for-bit-close, and the report must account for
100% of matrix-param elements.
(Ref concept replaced: `group2ctx` manual placement, SURVEY.md §2.4.)
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.parallel import create_mesh
from incubator_mxnet_tpu.parallel.sharding import (TP_RULES_VISION,
                                                   shard_params)

B, C, HW, NCLS = 4, 3, 16, 10


def _make_net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1))
    net.add(nn.BatchNorm())
    net.add(nn.Activation("relu"))
    net.add(nn.Conv2D(16, 3, strides=2, padding=1))
    net.add(nn.Activation("relu"))
    net.add(nn.GlobalAvgPool2D())
    net.add(nn.Dense(NCLS))
    net.initialize()
    net(NDArray(jnp.ones((B, C, HW, HW), jnp.float32)))
    net.hybridize()
    return net


def _batch(step):
    k = jax.random.PRNGKey(50 + step)
    kx, ky = jax.random.split(k)
    x = jax.random.normal(kx, (B, C, HW, HW), jnp.float32)
    y = jax.random.randint(ky, (B,), 0, NCLS, dtype=jnp.int32)
    return x, y


def _train(net, trainer, n_steps):
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for s in range(n_steps):
        x, y = _batch(s)
        with autograd.record():
            L = loss_fn(net(NDArray(x)), NDArray(y))
        L.backward()
        trainer.step(B)
        losses.append(float(L.asnumpy().mean()))
    return losses


def test_vision_tp_rules_shard_and_account():
    net = _make_net()
    mesh = create_mesh(jax.devices()[:2], model=2)
    report = shard_params(net, mesh, rules=TP_RULES_VISION)
    # both convs and the classifier matched; out-channels divide by 2
    conv_specs = [s for n, s in report.sharded.items() if ".weight" in n]
    assert len(conv_specs) == 3, report.summary()
    assert not report.unmatched
    assert report.accounted == 1.0
    assert report.coverage == 1.0  # every matrix param sharded here


def test_vision_tp_parity_with_single_device():
    oracle = _make_net(seed=1)
    tr_o = gluon.Trainer(oracle.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    lo = _train(oracle, tr_o, 3)

    net = _make_net(seed=1)
    mesh = create_mesh(jax.devices()[:2], model=2)
    report = shard_params(net, mesh, rules=TP_RULES_VISION)
    assert report.sharded
    tr_s = gluon.Trainer(net.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9},
                         mesh=mesh)
    ls = _train(net, tr_s, 3)
    onp.testing.assert_allclose(ls, lo, rtol=2e-5, atol=1e-6)
    for (n, po), ps in zip(oracle.collect_params().items(),
                           net.collect_params().values()):
        onp.testing.assert_allclose(ps.data().asnumpy(),
                                    po.data().asnumpy(),
                                    rtol=3e-5, atol=3e-6, err_msg=n)


def test_vision_tp_nondividing_head_falls_back_loud():
    """A classifier whose out-dim the model axis can't divide must fall
    back to replication WITH the reason recorded (never silently)."""
    mx.random.seed(2)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1))
    net.add(nn.GlobalAvgPool2D())
    net.add(nn.Dense(7))  # 7 % 2 != 0 on BOTH dims of (7, 8)? in=8 ok
    net.initialize()
    net(NDArray(jnp.ones((2, 3, 8, 8), jnp.float32)))
    mesh = create_mesh(jax.devices()[:2], model=2)
    with pytest.warns(UserWarning, match="fell back"):
        report = shard_params(net, mesh, rules=[
            (r"(gamma|beta|bias|running_mean|running_var)$",
             jax.sharding.PartitionSpec()),
            # out-channel ONLY (no second-dim fallback) to force the trap
            (r"\.weight$", jax.sharding.PartitionSpec("model")),
        ])
    assert any("7" in why for _w, why in report.fallbacks.values())
    assert report.accounted == 1.0  # fallback reason counts as accounted
