"""Gluon RNN family: fused lax.scan layers vs cell unroll vs NumPy
references (SURVEY.md §2.3 "RNN"; no r1 coverage existed)."""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.gluon import rnn
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def _x(T=5, N=3, C=4, seed=0):
    return NDArray(jax.random.normal(jax.random.PRNGKey(seed), (T, N, C)))


@pytest.mark.parametrize("layer_cls,n_states", [
    (lambda: rnn.RNN(6), 1),
    (lambda: rnn.LSTM(6), 2),
    (lambda: rnn.GRU(6), 1),
], ids=["rnn", "lstm", "gru"])
def test_layer_shapes_and_states(layer_cls, n_states):
    mx.random.seed(0)
    layer = layer_cls()
    layer.initialize()
    x = _x()
    y = layer(x)
    assert y.shape == (5, 3, 6)
    states = layer.begin_state(3)
    y2, new_states = layer(x, states)
    assert y2.shape == (5, 3, 6)
    assert len(new_states) == n_states
    onp.testing.assert_allclose(y.asnumpy(), y2.asnumpy(), rtol=1e-5, atol=1e-6)


def test_lstm_ntc_layout():
    mx.random.seed(1)
    tnc = rnn.LSTM(6, layout="TNC")
    tnc.initialize()
    x = _x()
    y_tnc = tnc(x).asnumpy()
    ntc = rnn.LSTM(6, layout="NTC")
    ntc.initialize()
    ntc(x.swapaxes(0, 1))  # materialize deferred shape
    ntc.parameters.set_data(tnc.parameters.data())
    y_ntc = ntc(x.swapaxes(0, 1)).asnumpy()
    onp.testing.assert_allclose(y_ntc.swapaxes(0, 1), y_tnc, rtol=1e-5, atol=1e-6)


def test_bidirectional_lstm():
    mx.random.seed(2)
    bi = rnn.LSTM(6, bidirectional=True)
    bi.initialize()
    y = bi(_x())
    assert y.shape == (5, 3, 12)  # fwd ++ bwd hidden


def test_cells_unroll():
    mx.random.seed(3)
    for cell_cls in (rnn.RNNCell, rnn.LSTMCell, rnn.GRUCell):
        cell = cell_cls(6, input_size=4)
        cell.initialize()
        x = _x(seed=4)
        out, states = cell.unroll(5, x, layout="TNC")
        assert out.shape == (5, 3, 6)


def test_lstm_cell_vs_numpy_reference():
    """One LSTMCell step against the hand-written gate math."""
    mx.random.seed(5)
    cell = rnn.LSTMCell(4, input_size=3)
    cell.initialize()
    x = NDArray(jax.random.normal(jax.random.PRNGKey(9), (2, 3)))
    h0 = NDArray(jnp.zeros((2, 4)))
    c0 = NDArray(jnp.zeros((2, 4)))
    out, (h1, c1) = cell(x, [h0, c0])

    p = {k.split("_", 1)[-1] if not k.startswith(cell.prefix) else
         k[len(cell.prefix):]: v.data().asnumpy()
         for k, v in cell.collect_params().items()}
    xi = x.asnumpy()
    gates = xi @ p["i2h_weight"].T + p["i2h_bias"] + \
        onp.zeros((2, 4)) @ p["h2h_weight"].T + p["h2h_bias"]
    i, f, g, o = onp.split(gates, 4, axis=1)
    sig = lambda v: 1 / (1 + onp.exp(-v))
    c_ref = sig(f) * 0 + sig(i) * onp.tanh(g)
    h_ref = sig(o) * onp.tanh(c_ref)
    onp.testing.assert_allclose(h1.asnumpy(), h_ref, rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(c1.asnumpy(), c_ref, rtol=1e-5, atol=1e-5)


def test_rnn_trains():
    """LSTM learns to output the last input's sign (grad flow check)."""
    from incubator_mxnet_tpu.gluon import Trainer, nn as gnn

    mx.random.seed(6)
    net = rnn.LSTM(8)
    head = gnn.Dense(1, flatten=False)
    net.initialize()
    head.initialize()
    params = dict(net.collect_params())
    params.update(head.collect_params())
    trainer = Trainer(params, "adam", {"learning_rate": 0.02})
    key = jax.random.PRNGKey(0)
    losses = []
    for step in range(60):
        key, sub = jax.random.split(key)
        x = jax.random.normal(sub, (6, 4, 2))
        target = jnp.sign(x[-1, :, :1])
        with autograd.record():
            h = net(NDArray(x))
            pred = head(h[-1])  # tape-aware slice: grads reach the LSTM
            loss = ((pred - NDArray(target)) ** 2).mean()
        loss.backward()
        trainer.step(4)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_modifier_cells():
    mx.random.seed(7)
    cell = rnn.ResidualCell(rnn.GRUCell(4, input_size=4))
    cell.initialize()
    out, _ = cell.unroll(3, _x(T=3, C=4, seed=8))
    assert out.shape == (3, 3, 4)
    seq = rnn.SequentialRNNCell()
    seq.add(rnn.LSTMCell(5, input_size=4))
    seq.add(rnn.GRUCell(6, input_size=5))
    seq.initialize()
    out, _ = seq.unroll(3, _x(T=3, C=4, seed=9))
    assert out.shape == (3, 3, 6)
