"""Copy-on-write prefix caching + chunked prefill (ISSUE 20).

The load-bearing contracts:

* **Cache-hit bit-identity** — a request admitted with its prefix
  blocks already resident produces byte-identical greedy output to a
  cold admission (and to `lm_generate`): bound blocks are read-only,
  chunk boundaries don't change per-position K/V or logits.
* **Refcount exactness** — shared blocks are decref'd, never
  double-freed: evict-while-shared, cancel-mid-chunked-prefill, and
  two requests racing to admit the same new prefix all leave the pool
  fully drained with the cache intact.
* **Collision safety** — `lookup` verifies token slices, not just the
  32-bit chain hash, so a forced hash collision is a miss, never a
  wrong binding.

Tiny nets, small chunks (prefill_chunk=4 exercises many chunk
boundaries per prompt), shared module-scope engine to bound compiles.
"""
import time

import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.models.generation import lm_generate
from incubator_mxnet_tpu.models.transformer import TransformerLM
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.serving import (BlockPool, RequestCancelled,
                                         ServingEngine)

V, C, DFF, L, H, MAXLEN = 61, 16, 32, 1, 2, 64
_POLL = 0.001

_RS = onp.random.RandomState(42)
PREF = _RS.randint(0, V, size=16).astype(onp.int32)    # 2 full blocks @ 8
TAIL_A = _RS.randint(0, V, size=5).astype(onp.int32)
TAIL_B = _RS.randint(0, V, size=5).astype(onp.int32)
PA = onp.concatenate([PREF, TAIL_A])                   # P=21: 6 chunks @ 4
PB = onp.concatenate([PREF, TAIL_B])
PLONG = _RS.randint(0, V, size=33).astype(onp.int32)   # 9 chunks @ 4


def _wait(pred, timeout=30.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.002)
    return False


@pytest.fixture(scope="module")
def net():
    mx.random.seed(0)
    n = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                      num_heads=H, max_len=MAXLEN, dropout=0.0)
    n.initialize()
    n(NDArray(jnp.ones((1, 4), jnp.int32)))
    return n


@pytest.fixture(scope="module")
def engine(net):
    """Shared chunked engine: ONE prefill-chunk program + one step
    program for the whole module (prefill_chunk=4 makes every prompt
    here span several chunk boundaries)."""
    eng = ServingEngine(net, max_batch=2, block_size=8, prefill_chunk=4,
                        poll_interval=_POLL)
    yield eng
    try:
        eng.close()
    except Exception:
        pass


@pytest.fixture
def clean_engine(engine):
    engine.set_fault_hook(None)
    yield engine
    engine.drain(timeout=30)
    engine.set_fault_hook(None)


# --------------------------------------------------------------------- #
# pool: content addressing, refcounts, LRU
# --------------------------------------------------------------------- #
def test_pool_lookup_register_roundtrip():
    pool = BlockPool(8, 4)                 # scratch + 7, block_size 4
    toks = list(range(100, 111))           # P=11: 2 full blocks
    ids = pool.alloc(3)
    pool.register(toks, ids)               # publishes ids[0], ids[1]
    assert pool.lookup(toks) == (ids[:2], 8)
    # the last prompt position is never served from cache: P=8 walks
    # (P-1)//bs = 1 block only
    assert pool.lookup(toks[:8]) == (ids[:1], 4)
    # divergence after block 0: only the shared block binds
    assert pool.lookup(toks[:4] + [1, 2, 3, 4, 9]) == (ids[:1], 4)
    # a never-seen prefix misses entirely
    assert pool.lookup([9] * 11) == ([], 0)


def test_pool_refcounts_shared_free_and_lru_harvest():
    pool = BlockPool(6, 4)                 # scratch + 5
    toks = list(range(1, 9))               # 2 full blocks
    a = pool.alloc(2)
    pool.register(toks, a)
    hits, clen = pool.lookup(toks + [7])
    assert hits == a and clen == 8
    pool.bind(hits)                        # second owner: refcount 2
    assert pool.num_shared == 2
    pool.free(a)                           # decref: still allocated
    assert pool.num_allocated == 2 and pool.num_shared == 0
    pool.free(a)                           # last ref: parks evictable
    assert pool.num_allocated == 0 and pool.num_free == 5
    with pytest.raises(ValueError):
        pool.free(a)                       # double free still fails fast
    # content survives refcount 0: a new request still hits
    assert pool.lookup(toks + [7]) == (a, 8)
    # never-cached free blocks are preferred over harvesting the cache
    assert pool.alloc(3) == [3, 4, 5]
    assert pool.lookup(toks + [7])[1] == 8
    # exhaustion harvests cached blocks oldest-first, dropping entries
    assert set(pool.alloc(2)) == set(a)
    assert pool.lookup(toks + [7]) == ([], 0)
    assert pool.num_cached == 0


def test_pool_bind_rollback_keeps_cache():
    pool = BlockPool(6, 4)
    toks = list(range(10, 18))
    a = pool.alloc(2)
    pool.register(toks, a)
    pool.free(a)                           # evictable, refcount 0
    hits, _ = pool.lookup(toks + [3])
    pool.bind(hits)
    pool.unbind(hits)                      # admission rolled back
    assert pool.num_allocated == 0
    assert pool.lookup(toks + [3]) == (a, 8)   # still resident


def test_pool_hash_collision_is_a_miss(monkeypatch):
    pool = BlockPool(8, 4)
    # force EVERY chain hash to collide: token verification is now the
    # only thing between a collision and a wrong binding
    monkeypatch.setattr(BlockPool, "_chain",
                        staticmethod(lambda h, sl: 1))
    t1 = [1, 2, 3, 4, 5, 6, 7, 8]
    a = pool.alloc(2)
    pool.register(t1, a)
    t2 = [9, 9, 9, 9, 5, 6, 7, 8]          # same hash, different tokens
    assert pool.lookup(t2 + [0]) == ([], 0)
    assert pool.lookup(t1 + [0]) == (a, 8)  # the real prefix still hits


def test_pool_register_first_wins():
    pool = BlockPool(8, 4)
    toks = list(range(20, 28))
    a = pool.alloc(2)
    b = pool.alloc(2)
    pool.register(toks, a)
    pool.register(toks, b)                 # racing loser: a no-op
    assert pool.lookup(toks + [0]) == (a, 8)
    pool.free(b)                           # loser's blocks were private:
    assert pool.num_free == 5              # straight back to the heap
    pool.free(a)
    assert pool.num_free == 7


# --------------------------------------------------------------------- #
# engine: chunked prefill + cache-hit bit-identity
# --------------------------------------------------------------------- #
def test_chunked_prefill_parity_with_lm_generate(net, clean_engine):
    # 21-token prompt through 6 chunks of 4: per-position K/V and the
    # first-token logits must be byte-identical to the monolithic path
    ref = onp.asarray(lm_generate(net, PA[None, :], 8))[0, len(PA):]
    cold = clean_engine.submit(PA, 8)
    assert cold.result(timeout=60) == ref.tolist()
    st = clean_engine.stats()
    assert st["prefix_cache"]["misses"] >= 1


def test_cache_hit_bit_identical_to_cold(net, clean_engine):
    ref = onp.asarray(lm_generate(net, PA[None, :], 8))[0, len(PA):]
    hits0 = clean_engine.stats()["prefix_cache"]["hits"]
    req = clean_engine.submit(PA, 8)       # PREF+TAIL_A registered above
    assert req.result(timeout=60) == ref.tolist()
    st = clean_engine.stats()
    assert st["prefix_cache"]["hits"] == hits0 + 1
    adm = next(e for e in req.trace.snapshot() if e["name"] == "admitted")
    assert adm["cached_tokens"] == 16      # 2 of 3 prompt blocks bound
    assert adm["chunks"] == 2              # only the 5-token tail chunks
    assert st["blocks_free"] == st["blocks_total"]


def test_evict_while_shared_decrefs_exactly(net, clean_engine):
    eng = clean_engine
    ref_b = onp.asarray(lm_generate(net, PB[None, :], 10))[0, len(PB):]
    eng.set_fault_hook(lambda ph: time.sleep(0.02) if ph == "step"
                       else None)
    ra = eng.submit(PA, 20)                # both bind PREF's 2 blocks
    rb = eng.submit(PB, 10)
    assert _wait(lambda: len(rb.tokens) >= 2)
    assert eng._pool.num_shared >= 2       # genuinely shared right now
    ra.cancel()                            # evict one sharer mid-decode
    with pytest.raises(RequestCancelled):
        ra.result(timeout=30)
    assert rb.result(timeout=60) == ref_b.tolist()   # survivor exact
    eng.set_fault_hook(None)
    st = eng.stats()
    assert st["blocks_free"] == st["blocks_total"]
    assert eng._pool.num_allocated == 0    # every refcount drained


def test_cancel_mid_chunked_prefill_releases_only_private(net,
                                                          clean_engine):
    eng = clean_engine
    ref = onp.asarray(lm_generate(net, PLONG[None, :], 6))[0, len(PLONG):]
    assert eng.submit(PLONG, 6).result(timeout=60) == ref.tolist()
    cached_before = eng._pool.num_cached   # PLONG registered 4 blocks
    assert cached_before >= 4
    # a prompt sharing ONE block with PLONG, then diverging: 25 tokens
    # of tail, slowed to ~0.05 s per chunk so cancel lands mid-prefill
    pb = onp.concatenate([PLONG[:8],
                          _RS.randint(0, V, size=25).astype(onp.int32)])
    eng.set_fault_hook(lambda ph: time.sleep(0.05) if ph == "prefill"
                       else None)
    req = eng.submit(pb, 6)
    assert _wait(lambda: eng._pool.num_allocated > 0)
    req.cancel()
    with pytest.raises(RequestCancelled):
        req.result(timeout=30)
    eng.set_fault_hook(None)
    assert eng.drain(timeout=30)
    st = eng.stats()
    assert st["blocks_free"] == st["blocks_total"]
    assert eng._pool.num_allocated == 0
    # only the PRIVATE blocks were released to the heap — the shared
    # registered content survived the cancel and still serves hits
    assert eng._pool.num_cached == cached_before
    hits0 = st["prefix_cache"]["hits"]
    assert eng.submit(PLONG, 6).result(timeout=60) == ref.tolist()
    assert eng.stats()["prefix_cache"]["hits"] == hits0 + 1


def test_race_to_admit_same_new_prefix(net, clean_engine):
    eng = clean_engine
    fresh = _RS.randint(0, V, size=21).astype(onp.int32)   # unseen prefix
    ref = onp.asarray(lm_generate(net, fresh[None, :], 6))[0, len(fresh):]
    # both lanes admit the same never-cached prefix in the same tick:
    # whichever finishes first registers; the loser's registration is a
    # first-wins no-op and its blocks stay private — correct either way
    r1 = eng.submit(fresh, 6)
    r2 = eng.submit(fresh, 6)
    assert r1.result(timeout=60) == ref.tolist()
    assert r2.result(timeout=60) == ref.tolist()
    st = eng.stats()
    assert st["blocks_free"] == st["blocks_total"]
    assert eng._pool.num_allocated == 0
    # the winner's registration serves a third arrival from cache
    hits0 = st["prefix_cache"]["hits"]
    assert eng.submit(fresh, 6).result(timeout=60) == ref.tolist()
    assert eng.stats()["prefix_cache"]["hits"] == hits0 + 1


def test_speculation_composes_with_prefix_cache(net):
    # the draft pool shares tables and block ids with the target pool,
    # so a cache-hit admission binds DRAFT pages too (written by the
    # registrant's draft chunk prefill over the same block ids)
    mx.random.seed(3)
    draft = TransformerLM(vocab=V, units=8, hidden_size=16, num_layers=1,
                          num_heads=1, max_len=MAXLEN, dropout=0.0)
    draft.initialize()
    draft(NDArray(jnp.ones((1, 4), jnp.int32)))
    ref = onp.asarray(lm_generate(net, PA[None, :], 8))[0, len(PA):]
    with ServingEngine(net, max_batch=2, block_size=8, prefill_chunk=4,
                       speculate_k=3, draft_net=draft,
                       poll_interval=_POLL) as eng:
        cold = eng.submit(PA, 8).result(timeout=60)
        assert cold == ref.tolist()        # spec greedy == lm_generate
        hit = eng.submit(PA, 8).result(timeout=60)
        assert hit == cold                 # cache hit: bit-identical
        st = eng.stats()
        assert st["prefix_cache"]["hits"] >= 1
        assert st["speculate"]["proposed"] > 0   # spec really ran
        assert st["blocks_free"] == st["blocks_total"]
        assert eng._pool.num_allocated == 0
