"""KV-cache autoregressive generation (`models.generation`).

The decode program re-implements the LM forward against a cache, so
the load-bearing test is PARITY: greedy generate must reproduce, token
for token, the argmax chain of the full teacher-forced forward — the
training-path numerics as oracle, prefix by prefix.
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.models.generation import lm_generate
from incubator_mxnet_tpu.models.transformer import TransformerLM
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


V, C, DFF, L, H, MAXLEN = 97, 32, 64, 2, 4, 64


def _net(dropout=0.0):
    mx.random.seed(0)
    net = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=MAXLEN, dropout=dropout)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))  # materialize shapes
    return net


def _greedy_oracle(net, prompt, n):
    """Argmax chain through the FULL model forward (the training path),
    one prefix at a time."""
    toks = onp.array(prompt)
    for _ in range(n):
        logits = net(NDArray(jnp.asarray(toks))).asnumpy()
        nxt = logits[:, -1].argmax(-1).astype("int32")
        toks = onp.concatenate([toks, nxt[:, None]], axis=1)
    return toks


def test_greedy_matches_full_forward_argmax():
    net = _net()
    prompt = onp.array(jax.random.randint(jax.random.PRNGKey(3), (2, 5),
                                          0, V), dtype="int32")
    out = onp.asarray(net.generate(NDArray(jnp.asarray(prompt)), 7))
    want = _greedy_oracle(net, prompt, 7)
    onp.testing.assert_array_equal(out, want)


def test_single_token_and_cache_reuse():
    net = _net()
    prompt = onp.zeros((1, 3), "int32")
    a = onp.asarray(net.generate(prompt, 1))
    assert a.shape == (1, 4)
    onp.testing.assert_array_equal(a, _greedy_oracle(net, prompt, 1))
    # second call with the same signature reuses the compiled program
    assert len(net._gen_programs) == 1
    b = onp.asarray(net.generate(prompt, 1))
    assert len(net._gen_programs) == 1
    onp.testing.assert_array_equal(a, b)
    # weights are ARGUMENTS: updating them changes the output through
    # the SAME compiled program (no retrace)
    net.head.weight.set_data(net.head.weight.data() * -1.0)
    c = onp.asarray(net.generate(prompt, 1))
    assert len(net._gen_programs) == 1
    onp.testing.assert_array_equal(c, _greedy_oracle(net, prompt, 1))


def test_sampling_seeded_and_shaped():
    net = _net()
    prompt = onp.ones((2, 4), "int32")
    s1 = onp.asarray(lm_generate(net, prompt, 6, temperature=1.0, top_k=8,
                                 seed=11))
    s2 = onp.asarray(lm_generate(net, prompt, 6, temperature=1.0, top_k=8,
                                 seed=11))
    s3 = onp.asarray(lm_generate(net, prompt, 6, temperature=1.0, top_k=8,
                                 seed=12))
    assert s1.shape == (2, 10)
    onp.testing.assert_array_equal(s1, s2)  # seeded reproducibility
    assert (s1 != s3).any()                 # seeds matter
    assert (s1 >= 0).all() and (s1 < V).all()


def test_eos_freezes_sequence():
    net = _net()
    prompt = onp.array([[1, 2, 3]], "int32")
    greedy = onp.asarray(net.generate(prompt, 6))
    eos = int(greedy[0, 3])  # the first generated token
    out = onp.asarray(net.generate(prompt, 6, eos_id=eos))
    # after first emission of eos, every later position IS eos
    gen = out[0, 3:]
    hit = onp.argmax(gen == eos)
    assert (gen[hit:] == eos).all()


def test_max_len_guard():
    net = _net()
    with pytest.raises(ValueError):
        net.generate(onp.zeros((1, 60), "int32"), 10)  # 70 > 64


def test_max_new_tokens_validated():
    net = _net()
    with pytest.raises(ValueError):
        net.generate(onp.zeros((1, 3), "int32"), 0)
    with pytest.raises(ValueError):
        net.generate(onp.zeros((1, 3), "int32"), -2)


# ------------------------------------------------------------------ #
# beam search
# ------------------------------------------------------------------ #
def _seq_logprob(net, seq, P):
    """Cumulative log-prob of seq[P:] under the full teacher-forced
    forward (the training path) — the oracle for beam scores."""
    logits = net(NDArray(jnp.asarray(seq[None]))).asnumpy()
    logp = onp.asarray(jax.nn.log_softmax(jnp.asarray(logits[0]), -1))
    return float(sum(logp[t - 1, seq[t]] for t in range(P, len(seq))))


def test_beam1_equals_greedy():
    net = _net()
    prompt = onp.array([[5, 9, 2]], "int32")
    seqs, scores = net.beam_search(prompt, 6, beam_size=1)
    greedy = onp.asarray(net.generate(prompt, 6))
    onp.testing.assert_array_equal(onp.asarray(seqs[:, 0]), greedy)
    assert scores.shape == (1, 1)


def test_beam_finds_global_best_exhaustive():
    """K = V, N = 2: the beam's K*V candidates at the second step COVER
    the whole length-2 continuation space, so its top-1 must be the
    global argmax — verified by brute force over all V^2 continuations
    with the training forward as oracle."""
    prompt = onp.array([[3, 7]], "int32")
    small_V = 9  # tiny vocab so beam_size == V is cheap
    mx.random.seed(1)
    tiny = TransformerLM(vocab=small_V, units=16, hidden_size=32,
                         num_layers=1, num_heads=2, max_len=16,
                         dropout=0.0)
    tiny.initialize()
    tiny(NDArray(jnp.ones((1, 2), jnp.int32)))
    seqs, scores = tiny.beam_search(prompt, 2, beam_size=small_V)

    best, best_lp = None, -1e30
    for a in range(small_V):
        for b in range(small_V):
            seq = onp.array([3, 7, a, b], "int32")
            lp = _seq_logprob(tiny, seq, 2)
            if lp > best_lp:
                best, best_lp = seq, lp
    onp.testing.assert_array_equal(onp.asarray(seqs[0, 0]), best)
    assert abs(float(scores[0, 0]) - best_lp) < 1e-4


def test_beam_scores_sorted_and_match_oracle():
    net = _net()
    prompt = onp.array([[1, 2, 3, 4]], "int32")
    K, N = 4, 5
    seqs, scores = net.beam_search(prompt, N, beam_size=K)
    assert seqs.shape == (1, K, 4 + N) and scores.shape == (1, K)
    s = onp.asarray(scores[0])
    assert (s[:-1] >= s[1:] - 1e-6).all(), "beams not sorted best-first"
    # every beam's reported score is the true cumulative log-prob of
    # its sequence under the training forward
    for j in range(K):
        lp = _seq_logprob(net, onp.asarray(seqs[0, j]), 4)
        assert abs(lp - float(s[j])) < 1e-3, (j, lp, float(s[j]))
    # prompt preserved on every beam
    onp.testing.assert_array_equal(
        onp.asarray(seqs[0, :, :4]), onp.tile(prompt, (K, 1)))


def test_beam_eos_freezing_and_length_penalty():
    net = _net()
    prompt = onp.array([[2, 4, 6]], "int32")
    # pick eos = the greedy first token so the top beam finishes at once
    eos = int(onp.asarray(net.generate(prompt, 1))[0, -1])
    seqs, scores = net.beam_search(prompt, 5, beam_size=3, eos_id=eos)
    row = onp.asarray(seqs[0])
    for j in range(3):
        gen = row[j, 3:]
        hits = onp.where(gen == eos)[0]
        if hits.size:  # after first eos, everything is eos
            assert (gen[hits[0]:] == eos).all()
    # alpha only reorders/normalizes — shapes and sortedness hold
    seqs2, scores2 = net.beam_search(prompt, 5, beam_size=3, eos_id=eos,
                                     alpha=1.0)
    s2 = onp.asarray(scores2[0])
    assert (s2[:-1] >= s2[1:] - 1e-6).all()


def test_beam_validation():
    net = _net()
    with pytest.raises(ValueError):
        net.beam_search(onp.zeros((1, 3), "int32"), 4, beam_size=0)
    with pytest.raises(ValueError):
        net.beam_search(onp.zeros((1, 3), "int32"), 4, beam_size=V + 1)


# ------------------------------------------------------------------ #
# NMT translate (encoder-decoder)
# ------------------------------------------------------------------ #
from incubator_mxnet_tpu.models.transformer import Transformer


def _nmt_net(V=41):
    mx.random.seed(2)
    net = Transformer(src_vocab=V, tgt_vocab=V, units=32, hidden_size=64,
                      num_layers=2, num_heads=4, dropout=0.0)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)),
        NDArray(jnp.ones((1, 3), jnp.int32)))
    return net


def _nmt_greedy_oracle(net, src, n, vl=None):
    """Argmax chain through the FULL encoder-decoder forward (the
    training path) with the BOS=0 convention."""
    B = src.shape[0]
    tgt_in = onp.zeros((B, 1), "int32")
    out = []
    for _ in range(n):
        args = [NDArray(jnp.asarray(src)), NDArray(jnp.asarray(tgt_in))]
        if vl is not None:
            args.append(NDArray(jnp.asarray(vl)))
        logits = net(*args).asnumpy()
        nxt = logits[:, -1].argmax(-1).astype("int32")
        out.append(nxt)
        tgt_in = onp.concatenate([tgt_in, nxt[:, None]], axis=1)
    return onp.stack(out, axis=1)


def test_nmt_greedy_matches_full_forward():
    net = _nmt_net()
    src = onp.array(jax.random.randint(jax.random.PRNGKey(5), (2, 6),
                                       1, 41), dtype="int32")
    out = onp.asarray(net.translate(src, 5))
    onp.testing.assert_array_equal(out, _nmt_greedy_oracle(net, src, 5))


def test_nmt_src_mask_respected():
    """src_valid_length must change the translation exactly as it
    changes the training forward."""
    net = _nmt_net()
    src = onp.array(jax.random.randint(jax.random.PRNGKey(6), (2, 8),
                                       1, 41), dtype="int32")
    vl = onp.array([5, 8], "int32")
    out = onp.asarray(net.translate(src, 4, src_valid_length=vl))
    want = _nmt_greedy_oracle(net, src, 4, vl=vl)
    onp.testing.assert_array_equal(out, want)


def test_nmt_beam1_equals_greedy_and_scores():
    net = _nmt_net()
    src = onp.array(jax.random.randint(jax.random.PRNGKey(7), (1, 5),
                                       1, 41), dtype="int32")
    greedy = onp.asarray(net.translate(src, 4))
    seqs, scores = net.translate(src, 4, beam_size=3)
    seqs, scores = onp.asarray(seqs), onp.asarray(scores)
    s = scores[0]
    assert (s[:-1] >= s[1:] - 1e-6).all()
    # beam search may beat greedy but never scores below it
    def _chain_lp(tgt):
        tgt_in = onp.concatenate([[0], tgt[:-1]])[None].astype("int32")
        logits = net(NDArray(jnp.asarray(src)),
                     NDArray(jnp.asarray(tgt_in))).asnumpy()
        logp = onp.asarray(jax.nn.log_softmax(jnp.asarray(logits[0]), -1))
        return float(sum(logp[t, tgt[t]] for t in range(len(tgt))))
    assert float(s[0]) >= _chain_lp(greedy[0]) - 1e-4
    # each beam's score is the true cumulative log-prob under the
    # training forward (BOS-prefixed teacher forcing)
    for j in range(3):
        tgt_in = onp.concatenate([[0], seqs[0, j][:-1]])[None].astype("int32")
        logits = net(NDArray(jnp.asarray(src)),
                     NDArray(jnp.asarray(tgt_in))).asnumpy()
        logp = onp.asarray(jax.nn.log_softmax(jnp.asarray(logits[0]), -1))
        lp = float(sum(logp[t, seqs[0, j, t]] for t in range(4)))
        assert abs(lp - float(s[j])) < 1e-3, (j, lp, float(s[j]))


def test_nmt_trained_copy_task_translates():
    """A briefly-trained copy-task model must reproduce its source via
    translate — the end-to-end train->translate product path."""
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.models.transformer import LabelSmoothedCELoss

    net = _nmt_net(V=17)
    loss_fn = LabelSmoothedCELoss(smoothing=0.0)
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 3e-3})
    rng = onp.random.RandomState(0)
    for i in range(150):
        src = rng.randint(2, 17, (8, 6)).astype("int32")
        bos = onp.zeros((8, 1), "int32")
        tgt_in = onp.concatenate([bos, src[:, :-1]], 1)
        with autograd.record():
            L = loss_fn(net(NDArray(jnp.asarray(src)),
                            NDArray(jnp.asarray(tgt_in))),
                        NDArray(jnp.asarray(src)))
        L.backward()
        tr.step(1)
    src = rng.randint(2, 17, (4, 6)).astype("int32")
    out = onp.asarray(net.translate(src, 6))
    acc = (out == src).mean()
    assert acc > 0.8, f"copy-task translate accuracy {acc}"


def test_nmt_beam_sampling_conflict_raises():
    net = _nmt_net()
    src = onp.ones((1, 4), "int32")
    with pytest.raises(ValueError):
        net.translate(src, 3, beam_size=2, temperature=0.7)


def test_nmt_max_length_guard():
    """ADVICE r5 #1: nmt_translate must validate like lm_generate does
    — max_len AND src length against net._max_length (the attribute was
    dead while lm_generate enforced net._max_len)."""
    net = _nmt_net()
    limit = net._max_length
    src = onp.ones((1, 4), "int32")
    with pytest.raises(ValueError, match="max_length"):
        net.translate(src, limit + 1)
    with pytest.raises(ValueError, match="max_length"):
        net.translate(onp.ones((1, limit + 1), "int32"), 3)
    # at the limit itself the guard stays quiet (only shape cost)
    net.translate(src, 2)  # well inside — sanity


# ------------------------------------------------------------------ #
# prompt-length bucketing + program-cache LRU (ADVICE r5 #3)
# ------------------------------------------------------------------ #
from incubator_mxnet_tpu.models.generation import bucket_length


def test_bucket_length_rule():
    assert bucket_length(0) == 16
    assert bucket_length(5) == 16
    assert bucket_length(16) == 16
    assert bucket_length(17) == 32
    assert bucket_length(33) == 64
    assert bucket_length(3, floor=2) == 4
    with pytest.raises(ValueError):
        bucket_length(-1)


def test_pad_to_bucket_token_identical_and_one_program_per_bucket():
    """The bucketed program right-pads the prompt and threads the true
    length through as a traced argument — tokens must be IDENTICAL to
    the exact-shape program's (right-padding under a causal mask cannot
    touch valid positions, and decode overwrites pad cache slots
    position by position)."""
    net = _net()
    outs = {}
    for P in (3, 5, 7):  # one bucket (16) for all three lengths
        prompt = onp.array(jax.random.randint(jax.random.PRNGKey(P),
                                              (2, P), 0, V), dtype="int32")
        outs[P] = onp.asarray(net.generate(prompt, 6, pad_to_bucket=True))
        want = onp.asarray(net.generate(prompt, 6))
        onp.testing.assert_array_equal(outs[P], want)
    # 3 exact-shape programs + ONE shared bucketed program
    sigs = list(net._gen_programs)
    assert sum(1 for s in sigs if s[-1] is True) == 1
    # bucket never exceeds max_len - N: a prompt near the cap still works
    prompt = onp.array(jax.random.randint(jax.random.PRNGKey(0), (2, 50),
                                          0, V), dtype="int32")
    out = onp.asarray(net.generate(prompt, 6, pad_to_bucket=True))  # 56<=58
    onp.testing.assert_array_equal(
        out, onp.asarray(net.generate(prompt, 6)))


def test_gen_program_cache_lru_cap():
    net = _net()
    net._gen_program_cache_cap = 3
    for P in (2, 3, 4, 5, 6):
        net.generate(onp.ones((1, P), "int32"), 1)
    assert len(net._gen_programs) == 3
    # most-recent signatures survive (P = 4, 5, 6)
    assert {s[1] for s in net._gen_programs} == {4, 5, 6}
    # a cache hit refreshes recency: touch P=4, insert P=7 → 5 evicted
    net.generate(onp.ones((1, 4), "int32"), 1)
    net.generate(onp.ones((1, 7), "int32"), 1)
    assert {s[1] for s in net._gen_programs} == {4, 6, 7}


def test_pe_cache_lru_cap():
    from incubator_mxnet_tpu.models.transformer import _PE_TABLE_MAX

    mx.random.seed(5)
    big = TransformerLM(vocab=31, units=16, hidden_size=32, num_layers=1,
                        num_heads=2, max_len=_PE_TABLE_MAX + 1,
                        dropout=0.0)
    big.initialize()
    big(NDArray(jnp.ones((1, 4), jnp.int32)))
    assert big._pe is None  # width-keyed eager-table regime
    big._pe_cache_cap = 2
    for P in (3, 4, 5, 6):
        big.generate(onp.ones((1, P), "int32"), 2)
    assert len(big._pe_cache) == 2
    assert set(big._pe_cache) == {7, 8}  # the two most recent widths


def test_long_maxlen_in_program_pe():
    """max_len > _PE_TABLE_MAX: the forward computes pe IN-PROGRAM (no
    O(max_len*units) constant in the compiled program — the r5 fix for
    the 256 MB HLO literal at max_len=65536) and generate takes the
    width-keyed eager table path.  Parity vs a small-max_len twin with
    identical weights pins both branches."""
    from incubator_mxnet_tpu.models.transformer import _PE_TABLE_MAX

    mx.random.seed(4)
    big = TransformerLM(vocab=61, units=16, hidden_size=32, num_layers=1,
                        num_heads=2, max_len=_PE_TABLE_MAX + 1,
                        dropout=0.0)
    big.initialize()
    big(NDArray(jnp.ones((1, 4), jnp.int32)))
    assert big._pe is None  # in-program regime
    mx.random.seed(4)
    small = TransformerLM(vocab=61, units=16, hidden_size=32,
                          num_layers=1, num_heads=2, max_len=64,
                          dropout=0.0)
    small.initialize()
    small(NDArray(jnp.ones((1, 4), jnp.int32)))
    assert small._pe is not None  # table regime

    toks = onp.array(jax.random.randint(jax.random.PRNGKey(8), (2, 9),
                                        0, 61), dtype="int32")
    a = big(NDArray(jnp.asarray(toks))).asnumpy()
    b = small(NDArray(jnp.asarray(toks))).asnumpy()
    onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                rtol=2e-5, atol=2e-5)
    # hybridized parity too (the compiled program carries no pe table)
    big.hybridize()
    c = big(NDArray(jnp.asarray(toks))).asnumpy()
    onp.testing.assert_allclose(onp.asarray(c), onp.asarray(a),
                                rtol=2e-5, atol=2e-5)
    # generate on the long-max_len net: width-keyed eager pe path
    out = onp.asarray(big.generate(toks[:, :5], 3))
    want = onp.asarray(small.generate(toks[:, :5], 3))
    onp.testing.assert_array_equal(out, want)
    assert set(big._pe_cache) == {8}  # only the P+N rows were built
