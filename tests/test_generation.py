"""KV-cache autoregressive generation (`models.generation`).

The decode program re-implements the LM forward against a cache, so
the load-bearing test is PARITY: greedy generate must reproduce, token
for token, the argmax chain of the full teacher-forced forward — the
training-path numerics as oracle, prefix by prefix.
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.models.generation import lm_generate
from incubator_mxnet_tpu.models.transformer import TransformerLM
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


V, C, DFF, L, H, MAXLEN = 97, 32, 64, 2, 4, 64


def _net(dropout=0.0):
    mx.random.seed(0)
    net = TransformerLM(vocab=V, units=C, hidden_size=DFF, num_layers=L,
                        num_heads=H, max_len=MAXLEN, dropout=dropout)
    net.initialize()
    net(NDArray(jnp.ones((1, 4), jnp.int32)))  # materialize shapes
    return net


def _greedy_oracle(net, prompt, n):
    """Argmax chain through the FULL model forward (the training path),
    one prefix at a time."""
    toks = onp.array(prompt)
    for _ in range(n):
        logits = net(NDArray(jnp.asarray(toks))).asnumpy()
        nxt = logits[:, -1].argmax(-1).astype("int32")
        toks = onp.concatenate([toks, nxt[:, None]], axis=1)
    return toks


def test_greedy_matches_full_forward_argmax():
    net = _net()
    prompt = onp.array(jax.random.randint(jax.random.PRNGKey(3), (2, 5),
                                          0, V), dtype="int32")
    out = onp.asarray(net.generate(NDArray(jnp.asarray(prompt)), 7))
    want = _greedy_oracle(net, prompt, 7)
    onp.testing.assert_array_equal(out, want)


def test_single_token_and_cache_reuse():
    net = _net()
    prompt = onp.zeros((1, 3), "int32")
    a = onp.asarray(net.generate(prompt, 1))
    assert a.shape == (1, 4)
    onp.testing.assert_array_equal(a, _greedy_oracle(net, prompt, 1))
    # second call with the same signature reuses the compiled program
    assert len(net._gen_programs) == 1
    b = onp.asarray(net.generate(prompt, 1))
    assert len(net._gen_programs) == 1
    onp.testing.assert_array_equal(a, b)
    # weights are ARGUMENTS: updating them changes the output through
    # the SAME compiled program (no retrace)
    net.head.weight.set_data(net.head.weight.data() * -1.0)
    c = onp.asarray(net.generate(prompt, 1))
    assert len(net._gen_programs) == 1
    onp.testing.assert_array_equal(c, _greedy_oracle(net, prompt, 1))


def test_sampling_seeded_and_shaped():
    net = _net()
    prompt = onp.ones((2, 4), "int32")
    s1 = onp.asarray(lm_generate(net, prompt, 6, temperature=1.0, top_k=8,
                                 seed=11))
    s2 = onp.asarray(lm_generate(net, prompt, 6, temperature=1.0, top_k=8,
                                 seed=11))
    s3 = onp.asarray(lm_generate(net, prompt, 6, temperature=1.0, top_k=8,
                                 seed=12))
    assert s1.shape == (2, 10)
    onp.testing.assert_array_equal(s1, s2)  # seeded reproducibility
    assert (s1 != s3).any()                 # seeds matter
    assert (s1 >= 0).all() and (s1 < V).all()


def test_eos_freezes_sequence():
    net = _net()
    prompt = onp.array([[1, 2, 3]], "int32")
    greedy = onp.asarray(net.generate(prompt, 6))
    eos = int(greedy[0, 3])  # the first generated token
    out = onp.asarray(net.generate(prompt, 6, eos_id=eos))
    # after first emission of eos, every later position IS eos
    gen = out[0, 3:]
    hit = onp.argmax(gen == eos)
    assert (gen[hit:] == eos).all()


def test_max_len_guard():
    net = _net()
    with pytest.raises(ValueError):
        net.generate(onp.zeros((1, 60), "int32"), 10)  # 70 > 64


def test_max_new_tokens_validated():
    net = _net()
    with pytest.raises(ValueError):
        net.generate(onp.zeros((1, 3), "int32"), 0)
    with pytest.raises(ValueError):
        net.generate(onp.zeros((1, 3), "int32"), -2)
