"""Trainer fused-step tests (VERDICT r1 #2).

Verifies the PUBLIC training path — autograd.record() → backward() →
Trainer.step() — is numerically identical to (a) the eager per-param
reference path and (b) a hand-rolled raw-JAX train loop (the r1
bench.py pattern), so the bench's MFU is earned by the framework API.
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.gluon import Trainer, nn
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def _make_net(seed=0, dtype=None):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"))
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize()
    x = mx.nd.ones((8, 12))
    net(x)  # materialize deferred shapes
    if dtype is not None:
        net.cast(dtype)
    return net


def _data(seed=1):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    x = jax.random.normal(k1, (8, 12), jnp.float32)
    y = jax.random.normal(k2, (8, 4), jnp.float32)
    return x, y


def _train_steps(net, trainer, x, y, n=4):
    for _ in range(n):
        with autograd.record():
            out = net(NDArray(x))
            loss = ((out - NDArray(y)) ** 2).mean()
        loss.backward()
        trainer.step(1)
    return [onp.asarray(p.data().asnumpy())
            for p in net.collect_params().values()]


@pytest.mark.parametrize("opt,opt_args", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-3}),
    ("lamb", {"learning_rate": 1e-3}),
    ("nadam", {"learning_rate": 1e-3}),
    ("rmsprop", {"learning_rate": 1e-3, "centered": True}),
    ("ftrl", {"learning_rate": 0.1}),
])
def test_fused_matches_eager(opt, opt_args):
    x, y = _data()
    net_a = _make_net()
    net_b = _make_net()
    tr_a = Trainer(net_a.collect_params(), opt, dict(opt_args), fuse_step=True)
    tr_b = Trainer(net_b.collect_params(), opt, dict(opt_args), fuse_step=False)
    pa = _train_steps(net_a, tr_a, x, y)
    pb = _train_steps(net_b, tr_b, x, y)
    assert tr_a._fused_fn is not None, "fused path was not taken"
    for a, b in zip(pa, pb):
        onp.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_trainer_matches_handrolled_sgd_momentum():
    """The r1 bench pattern (raw value_and_grad + momentum SGD) must equal
    the public record/backward/Trainer.step path bit-for-bit-ish."""
    from incubator_mxnet_tpu.gluon.block import functionalize

    x, y = _data(seed=3)
    lr, mom = 0.05, 0.9

    # --- public Gluon path ------------------------------------------- #
    net = _make_net(seed=7)
    net.hybridize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": lr, "momentum": mom})
    public = _train_steps(net, trainer, x, y, n=5)

    # --- hand-rolled raw JAX path ------------------------------------ #
    net2 = _make_net(seed=7)
    apply_fn, train_raws, aux_raws = functionalize(net2, mx.nd.NDArray(x))
    rng = jax.random.PRNGKey(0)

    def loss_fn(params, xx, yy):
        out, _ = apply_fn(params, aux_raws, rng, xx)
        return jnp.mean((out - yy) ** 2)

    def step(params, vel, xx, yy):
        loss, grads = jax.value_and_grad(loss_fn)(params, xx, yy)
        vel = jax.tree_util.tree_map(lambda v, g: mom * v - lr * g, vel, grads)
        params = jax.tree_util.tree_map(lambda p, v: p + v, params, vel)
        return params, vel, loss

    step = jax.jit(step)
    params = train_raws
    vel = jax.tree_util.tree_map(jnp.zeros_like, params)
    for _ in range(5):
        params, vel, _ = step(params, vel, x, y)

    # match by structural names ('0.weight' etc.) — stable across instances
    hand_by_id = {id(p): r for p, r in
                  zip([q for q in net2.collect_params().values()
                       if q.grad_req != "null"], params)}
    hand_struct = {k: hand_by_id[id(p)]
                   for k, p in net2._collect_params_with_prefix().items()
                   if id(p) in hand_by_id}
    pub_by_id = {id(p): a for p, a in zip(net.collect_params().values(), public)}
    pub_struct = {k: pub_by_id[id(p)]
                  for k, p in net._collect_params_with_prefix().items()}
    for k, r in hand_struct.items():
        onp.testing.assert_allclose(
            pub_struct[k], onp.asarray(r), rtol=1e-5, atol=1e-6)


def test_fused_multi_precision():
    """bf16 params + fp32 master weights through the fused path."""
    x, y = _data(seed=5)
    net_a = _make_net(seed=2, dtype="bfloat16")
    net_b = _make_net(seed=2, dtype="bfloat16")
    args = {"learning_rate": 0.05, "momentum": 0.9, "multi_precision": True}
    tr_a = Trainer(net_a.collect_params(), "sgd", dict(args), fuse_step=True)
    tr_b = Trainer(net_b.collect_params(), "sgd", dict(args), fuse_step=False)
    xb = x.astype(jnp.bfloat16)
    pa = _train_steps(net_a, tr_a, xb, y)
    pb = _train_steps(net_b, tr_b, xb, y)
    assert tr_a._fused_fn is not None
    for a, b in zip(pa, pb):
        onp.testing.assert_allclose(a.astype(onp.float32), b.astype(onp.float32),
                                    rtol=2e-2, atol=2e-2)
    # master weights exist and are fp32
    st = next(iter(tr_a._states.values()))
    assert st[0].dtype == jnp.float32


def test_fused_respects_mults_and_scheduler():
    from incubator_mxnet_tpu import lr_scheduler

    x, y = _data(seed=9)
    net_a = _make_net(seed=4)
    net_b = _make_net(seed=4)
    for net in (net_a, net_b):
        list(net.collect_params().values())[0].lr_mult = 0.1
        list(net.collect_params().values())[1].wd_mult = 0.0
    sched_a = lr_scheduler.FactorScheduler(step=2, factor=0.5)
    sched_b = lr_scheduler.FactorScheduler(step=2, factor=0.5)
    tr_a = Trainer(net_a.collect_params(), "sgd",
                   {"learning_rate": 0.05, "momentum": 0.9, "wd": 0.01,
                    "lr_scheduler": sched_a}, fuse_step=True)
    tr_b = Trainer(net_b.collect_params(), "sgd",
                   {"learning_rate": 0.05, "momentum": 0.9, "wd": 0.01,
                    "lr_scheduler": sched_b}, fuse_step=False)
    pa = _train_steps(net_a, tr_a, x, y, n=6)
    pb = _train_steps(net_b, tr_b, x, y, n=6)
    assert tr_a._fused_fn is not None
    for a, b in zip(pa, pb):
        onp.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_fused_fallback_on_compression():
    """Gradient compression must force the reference kvstore path."""
    x, y = _data()
    net = _make_net(seed=11)
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05},
                 compression_params={"type": "2bit", "threshold": 0.5})
    _train_steps(net, tr, x, y, n=1)
    assert tr._fused_fn is None  # fell back


def test_input_grads_survive_trainer_step():
    """x.attach_grad() + hybridized net + trainer.step: the input grad
    must be real (code-review r2 finding: the single-program step path
    must fall back when non-parameter inputs want gradients)."""
    x, y = _data(seed=21)
    net = _make_net(seed=8)
    net.hybridize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.05, "momentum": 0.9})
    xnd = NDArray(x)
    xnd.attach_grad()
    with autograd.record():
        out = net(xnd)
        loss = ((out - NDArray(y)) ** 2).mean()
    loss.backward()
    trainer.step(1)
    gx = xnd.grad.asnumpy()
    assert onp.isfinite(gx).all() and onp.abs(gx).sum() > 0

    # oracle
    from incubator_mxnet_tpu.gluon.block import functionalize

    net2 = _make_net(seed=8)
    apply_fn, train_raws, aux_raws = functionalize(net2, mx.nd.NDArray(x))
    rng = jax.random.PRNGKey(0)

    def f(xx):
        out, _ = apply_fn(train_raws, aux_raws, rng, xx)
        return jnp.mean((out - y) ** 2)

    onp.testing.assert_allclose(gx, onp.asarray(jax.grad(f)(x)),
                                rtol=1e-5, atol=1e-6)


def test_record_backward_grads_match_jax_oracle():
    """Residual-sharing hybrid backward == jax.grad of the same function."""
    from incubator_mxnet_tpu.gluon.block import functionalize

    x, _ = _data(seed=13)
    net = _make_net(seed=6)
    net.hybridize()
    with autograd.record():
        out = net(NDArray(x))
        loss = (out ** 2).sum()
    loss.backward()
    got = {p.name: onp.asarray(p.grad().asnumpy())
           for p in net.collect_params().values() if p.grad_req != "null"}

    net2 = _make_net(seed=6)
    apply_fn, train_raws, aux_raws = functionalize(net2, mx.nd.NDArray(x))
    rng = jax.random.PRNGKey(0)

    def f(params):
        out, _ = apply_fn(params, aux_raws, rng, x)
        return (out ** 2).sum()

    oracle = jax.grad(f)(train_raws)
    tp = [p for p in net2.collect_params().values() if p.grad_req != "null"]
    got2 = {p.name: onp.asarray(g) for p, g in zip(tp, oracle)}
    for (n1, g1), (n2, g2) in zip(sorted(got.items()), sorted(got2.items())):
        onp.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-6)


def test_full_step_throttles_runahead_without_keep_grads():
    """keep_grads=False still bounds the async dispatch queue: the
    forward outputs of every in-flight chained step are real buffers
    (ADVICE r2 medium) — the sync leaf must be tracked regardless."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, nn

    mx.random.seed(0)
    net = nn.Dense(8, in_units=8)
    net.initialize()
    net.hybridize()
    loss_fn = mx.gluon.loss.L2Loss()
    # byte-budgeted: with a tiny budget the queue must drain; the sync
    # is skipped entirely only when held bytes are small vs budget
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.01},
                 keep_grads=False, max_inflight_bytes=64)
    x = NDArray(onp.random.RandomState(0).randn(4, 8).astype("float32"))
    y = NDArray(onp.zeros((4, 8), "float32"))
    for _ in range(10):
        with autograd.record():
            L = loss_fn(net(x), y)  # canonical: no .mean() — chains
        L.backward()
        tr.step(4)
    assert tr._fullstep_ctx is not None, "full-step path must engage"
    assert len(tr._inflight) <= 2  # depth=2 at this budget


def test_loss_hybridize_opt_out_allows_python_control_flow():
    """Loss(hybridize=False) keeps the reference's eager semantics for
    data-dependent control flow (ADVICE r2)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon.loss import Loss

    class BranchyLoss(Loss):
        def forward(self, pred, label):
            d = (pred - label).abs().mean()
            if float(d.asnumpy()) > 1.0:  # data-dependent python branch
                return d * 2
            return d

    loss_fn = BranchyLoss(hybridize=False)
    p = NDArray(onp.full((2, 2), 3.0, "float32"))
    l = NDArray(onp.zeros((2, 2), "float32"))
    out = loss_fn(p, l)
    assert abs(float(out.asnumpy()) - 6.0) < 1e-5


def test_explicit_inflight_step_cap_honored():
    """Trainer(max_inflight_steps=N) must cap the one-program path's
    run-ahead even when the byte budget would allow more."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, nn

    mx.random.seed(0)
    net = nn.Dense(8, in_units=8)
    net.initialize()
    net.hybridize()
    loss_fn = mx.gluon.loss.L2Loss()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.01},
                 keep_grads=False, max_inflight_steps=3)
    x = NDArray(onp.random.RandomState(0).randn(4, 8).astype("float32"))
    y = NDArray(onp.zeros((4, 8), "float32"))
    for _ in range(10):
        with autograd.record():
            L = loss_fn(net(x), y)
        L.backward()
        tr.step(4)
    assert tr._fullstep_ctx is not None, "full-step path must engage"
    assert len(tr._inflight) <= 3


def test_full_step_failure_rolls_back_and_recovers():
    """A mid-flight failure of the fused-step program must (a) propagate,
    (b) roll back the host update counts, (c) drop the fullstep ctx, and
    (d) leave the trainer able to rebuild and train on the next step
    (ADVICE r4: trainer.py fullstep exception safety)."""
    net = _make_net(seed=3)
    net.hybridize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.05, "momentum": 0.9})
    x, y = _data(seed=4)
    loss_fn = mx.gluon.loss.L2Loss()

    def _steps(n):
        for _ in range(n):
            with autograd.record():
                L = loss_fn(net(NDArray(x)), NDArray(y))  # canonical chain
            L.backward()
            trainer.step(x.shape[0])

    _steps(3)  # reach fused full-step steady state
    opt = trainer._optimizer
    ctx = trainer._fullstep_ctx
    assert ctx is not None
    counts_before = dict(opt._index_update_count)
    nu_before = opt.num_update
    ctx["fn"] = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("synthetic transient failure"))
    with pytest.raises(RuntimeError, match="synthetic"):
        _steps(1)
    assert trainer._fullstep_ctx is None
    assert dict(opt._index_update_count) == counts_before
    assert opt.num_update == nu_before
    # recovery: the next step rebuilds the ctx from live host state
    w0 = onp.asarray(net[0].weight.data().asnumpy())
    _steps(1)
    assert trainer._fullstep_ctx is not None
    assert opt.num_update == nu_before + 1
    assert not onp.allclose(w0, net[0].weight.data().asnumpy())
