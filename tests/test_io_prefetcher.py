"""Async device-feed input pipeline (io/prefetcher.py, ISSUE 3):
overlap proof, sync-parity, sharded staging, reset/epoch behavior, and
thread/future cleanup for all three feed paths."""
import threading
import time

import numpy as onp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
from incubator_mxnet_tpu.io.prefetcher import (DevicePrefetcher,
                                               batch_sharding, to_device)
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.parallel import use_mesh


def _join_threads(prefix="mxtpu-prefetch", timeout=5.0):
    """Wait for all pipeline worker threads to exit; return stragglers."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name.startswith(prefix) and t.is_alive()]
        if not alive:
            return []
        time.sleep(0.02)
    return [t.name for t in threading.enumerate()
            if t.name.startswith(prefix) and t.is_alive()]


# --------------------------------------------------------------------- #
# overlap microbenchmark (acceptance criterion)
# --------------------------------------------------------------------- #
def test_prefetch_overlap_pipelines_fetch_and_compute():
    """With fetch ≈ compute ≈ 5 ms, the prefetched loop must run at
    ≈ max(fetch, compute) per step (the sync loop pays the sum)."""
    fetch_s, compute_s, n = 0.005, 0.005, 30

    def slow_batches():
        for i in range(n):
            time.sleep(fetch_s)  # a stalling host dataset
            yield onp.full((4, 4), i, dtype=onp.float32)

    # sync: fetch then compute, serial
    t0 = time.perf_counter()
    for _ in slow_batches():
        time.sleep(compute_s)
    sync_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    seen = 0
    for _ in DevicePrefetcher(slow_batches(), depth=2, mesh=False):
        time.sleep(compute_s)
        seen += 1
    pipe_t = time.perf_counter() - t0

    assert seen == n
    ideal = n * max(fetch_s, compute_s)
    # criterion: per-step wall ≤ max(fetch, compute) + 25% (plus a
    # fixed 60 ms allowance for thread startup/scheduler jitter in CI)
    assert pipe_t <= ideal * 1.25 + 0.06, \
        f"no overlap: pipelined {pipe_t:.3f}s vs ideal {ideal:.3f}s " \
        f"(sync {sync_t:.3f}s)"
    # sanity: the sync loop really pays ~the sum
    assert sync_t >= 0.8 * n * (fetch_s + compute_s)


# --------------------------------------------------------------------- #
# byte-parity with the synchronous paths
# --------------------------------------------------------------------- #
def _loader_bytes(loader):
    return [[a.asnumpy().tobytes() for a in b] for b in loader]


def test_dataloader_prefetch_byte_identical():
    rng = onp.random.RandomState(0)
    ds = ArrayDataset(rng.randn(20, 3).astype("float32"),
                      rng.randint(0, 5, 20).astype("int32"))
    sync = _loader_bytes(DataLoader(ds, batch_size=4))
    for workers in (0, 2):
        pref = _loader_bytes(DataLoader(ds, batch_size=4,
                                        num_workers=workers,
                                        prefetch_to_device=2, mesh=False))
        assert pref == sync
    assert not _join_threads()


def test_prefetching_iter_device_parity():
    X = onp.random.RandomState(1).randn(16, 5).astype("float32")
    Y = onp.arange(16, dtype="float32")
    plain = [(b.data[0].asnumpy().tobytes(), b.label[0].asnumpy().tobytes(),
              b.pad)
             for b in mx.io.NDArrayIter(X, Y, batch_size=4)]
    pit = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, Y, batch_size=4),
                                prefetch_to_device=True)
    moved = [(b.data[0].asnumpy().tobytes(), b.label[0].asnumpy().tobytes(),
              b.pad) for b in pit]
    pit.close()
    assert moved == plain


# --------------------------------------------------------------------- #
# sharded staging under a mesh
# --------------------------------------------------------------------- #
def test_prefetch_sharding_under_mesh(mesh8):
    ds = ArrayDataset(onp.arange(64, dtype="float32").reshape(16, 4),
                      onp.arange(16, dtype="float32"))
    loader = DataLoader(ds, batch_size=8, prefetch_to_device=2, mesh=mesh8)
    batches = list(loader)
    assert len(batches) == 2
    for data, label in batches:
        assert data._data.sharding == NamedSharding(mesh8, P("data", None))
        assert label._data.sharding == NamedSharding(mesh8, P("data"))
    # values survive the sharded placement bit-exactly
    got = onp.concatenate([d.asnumpy() for d, _ in batches])
    assert got.tobytes() == onp.arange(64, dtype="float32").tobytes()


def test_prefetch_active_mesh_pickup(mesh8):
    """mesh=None resolves the ambient use_mesh() mesh at epoch start."""
    src = [onp.ones((8, 2), onp.float32)]
    with use_mesh(mesh8):
        (out,) = list(DevicePrefetcher(iter(src), depth=1))
    assert out.sharding == NamedSharding(mesh8, P("data", None))


def test_to_device_replicates_indivisible_batch(mesh8):
    # batch 6 % 8 != 0: replicate instead of failing mid-epoch
    out = to_device(onp.ones((6, 2), onp.float32), mesh=mesh8)
    assert out.sharding == NamedSharding(mesh8, P())


def test_batch_sharding_is_shard_batch_placement(mesh8):
    from incubator_mxnet_tpu.gluon.utils import shard_batch

    x = onp.arange(32, dtype="float32").reshape(8, 4)
    via_helper = to_device(x, mesh=mesh8)
    via_shard_batch = shard_batch(NDArray(onp.asarray(x)), mesh8)
    assert via_helper.sharding == via_shard_batch._data.sharding


# --------------------------------------------------------------------- #
# epoch boundaries, reset, and the reset() race
# --------------------------------------------------------------------- #
def test_prefetching_iter_reset_mid_epoch_no_pollution():
    """reset() while the worker is parked on a full queue must reap it;
    the next epoch must replay the FULL sequence (no stale batches)."""
    X = onp.arange(64, dtype="float32").reshape(64, 1)
    Y = onp.arange(64, dtype="float32")
    expect = [b.label[0].asnumpy().tolist()
              for b in mx.io.NDArrayIter(X, Y, batch_size=4)]
    pit = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, Y, batch_size=4),
                                prefetch_depth=2)
    for _ in range(5):  # repeated mid-epoch resets (the race scenario)
        pit.next()
        pit.next()
        t0 = time.perf_counter()
        pit.reset()
        assert time.perf_counter() - t0 < 2.0, "reset hung joining worker"
    got = [b.label[0].asnumpy().tolist() for b in pit]
    assert got == expect
    pit.close()
    assert not _join_threads(prefix="mxtpu-prefetching-iter")


def test_prefetching_iter_epoch_boundary():
    X = onp.zeros((12, 2), "float32")
    pit = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, onp.zeros(12, "float32"),
                                                  batch_size=4))
    assert len(list(pit)) == 3
    with pytest.raises(StopIteration):
        pit.next()
    pit.reset()
    assert len(list(pit)) == 3
    pit.close()


def test_device_prefetcher_multi_epoch_reiterates_source():
    epochs = []

    class Source:
        def __iter__(self):
            epochs.append(len(epochs))
            return iter([onp.ones(2, onp.float32)] * 3)

    pf = DevicePrefetcher(Source(), depth=1, mesh=False)
    assert len(list(pf)) == 3
    assert len(list(pf)) == 3
    assert epochs == [0, 1]


# --------------------------------------------------------------------- #
# early exit: futures cancelled, threads reaped, sampler streamed
# --------------------------------------------------------------------- #
class _CountingDataset(ArrayDataset):
    def __init__(self, *args):
        super().__init__(*args)
        self.fetches = 0

    def __getitem__(self, idx):
        self.fetches += 1
        return super().__getitem__(idx)


def test_dataloader_streaming_sampler_not_materialized():
    """The threaded path must pull the batch sampler lazily."""
    pulled = []

    class StreamingSampler:
        def __iter__(self):
            for i in range(100):
                pulled.append(i)
                yield [i % 10]

    ds = _CountingDataset(onp.arange(10, dtype="float32"))
    loader = DataLoader(ds, batch_sampler=StreamingSampler(), num_workers=2,
                        prefetch=2)
    it = iter(loader)
    next(it)
    next(it)
    it.close()
    # 2 consumed + at most prefetch+1 in flight + 1 lookahead — nowhere
    # near the 100 an eager list() would have pulled
    assert len(pulled) <= 8, f"sampler materialized: {len(pulled)} pulled"
    assert ds.fetches <= 8


def test_dataloader_early_break_cancels_and_cleans_up():
    ds = _CountingDataset(onp.arange(400, dtype="float32"))
    loader = DataLoader(ds, batch_size=4, num_workers=2,
                        prefetch_to_device=2, mesh=False)
    for i, _ in enumerate(loader):
        if i == 1:
            break
    assert not _join_threads(), "prefetch threads leaked after break"
    # in-flight bound: consumed(2) + device queue(2+stage 2) + pool
    # prefetch window(5) of 4 samples each, far below the 400 total
    assert ds.fetches <= 11 * 4, f"early break kept fetching: {ds.fetches}"
    # the loader is reusable after an early break
    assert len(list(loader)) == 100


def test_device_prefetcher_close_mid_epoch():
    def gen():
        for i in range(1000):
            yield onp.full(3, i, onp.float32)

    pf = DevicePrefetcher(gen(), depth=2, mesh=False)
    it = iter(pf)
    next(it)
    it.close()
    assert not _join_threads()


def test_device_prefetcher_error_propagates():
    def bad():
        yield onp.ones(2, onp.float32)
        raise ValueError("boom in fetch")

    it = iter(DevicePrefetcher(bad(), mesh=False))
    next(it)
    with pytest.raises(ValueError, match="boom in fetch"):
        next(it)
    assert not _join_threads()


# --------------------------------------------------------------------- #
# telemetry
# --------------------------------------------------------------------- #
def test_pipeline_metrics_recorded():
    reg = telemetry.get_registry()
    was_on = telemetry.enabled()
    telemetry.enable()
    try:
        wait = telemetry.histogram("data_wait_seconds")
        h2d = telemetry.counter("h2d_bytes_total")
        wait0, h2d0 = wait.count, h2d.value
        ds = ArrayDataset(onp.ones((8, 4), "float32"))
        for _ in DataLoader(ds, batch_size=2, prefetch_to_device=2,
                            mesh=False):
            pass
        assert wait.count - wait0 >= 4
        assert h2d.value - h2d0 == 8 * 4 * 4  # fp32 data bytes staged
        assert reg.get("prefetch_queue_depth") is not None
    finally:
        if not was_on:
            telemetry.disable()


def test_prefetched_trainer_loop_end_to_end():
    """The full consumption path: DataLoader(prefetch_to_device) →
    autograd.record → Trainer.step matches the sync loop's params."""
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, nn

    rng = onp.random.RandomState(3)
    X = rng.randn(16, 5).astype("float32")
    Y = rng.randn(16, 1).astype("float32")

    def train(prefetch):
        mx.random.seed(0)
        net = nn.Dense(1)
        net.initialize()
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.05})
        loader = DataLoader(ArrayDataset(X, Y), batch_size=4,
                            prefetch_to_device=2 if prefetch else False,
                            mesh=False)
        for _ in range(2):
            for data, label in loader:
                with autograd.record():
                    err = net(data) - label
                    loss = (err * err).sum()
                loss.backward()
                trainer.step(4)
        trainer.flush()
        # positional: block name counters differ across instantiations
        return [v.data().asnumpy()
                for v in net.collect_params().values()]

    sync_p, pref_p = train(False), train(True)
    assert len(sync_p) == len(pref_p)
    for a, b in zip(sync_p, pref_p):
        onp.testing.assert_array_equal(a, b)
