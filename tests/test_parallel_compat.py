"""parallel/compat.py: the shard_map API-generation shim (ISSUE 5
satellite).  Covers BOTH import paths (top-level `jax.shard_map` vs
`jax.experimental.shard_map`) and the check_vma <-> check_rep kwarg
translation in each direction, under mocked jax modules — the installed
jax only ever exercises one side of each branch.
"""
import importlib
import sys
import types

import pytest

import incubator_mxnet_tpu.parallel.compat as compat


# ---------------------------------------------------------------------------
# kwarg translation (monkeypatched resolver state, no reload needed)
# ---------------------------------------------------------------------------

def _capture_impl(accepted):
    """A fake resolved shard_map recording the kwargs it receives."""
    calls = []

    def impl(*args, **kwargs):
        calls.append((args, dict(kwargs)))
        return "mapped"

    return impl, calls, set(accepted)


_NEW_API = ("f", "mesh", "in_specs", "out_specs", "check_vma", "axis_names")
_OLD_API = ("f", "mesh", "in_specs", "out_specs", "check_rep", "auto")


def test_check_vma_translates_to_check_rep_on_old_jax(monkeypatch):
    impl, calls, acc = _capture_impl(_OLD_API)
    monkeypatch.setattr(compat, "_shard_map", impl)
    monkeypatch.setattr(compat, "_ACCEPTED", acc)
    assert compat.shard_map(lambda x: x, check_vma=False) == "mapped"
    (_, kwargs), = calls
    assert kwargs == {"check_rep": False}


def test_check_rep_translates_to_check_vma_on_new_jax(monkeypatch):
    impl, calls, acc = _capture_impl(_NEW_API)
    monkeypatch.setattr(compat, "_shard_map", impl)
    monkeypatch.setattr(compat, "_ACCEPTED", acc)
    assert compat.shard_map(lambda x: x, check_rep=False) == "mapped"
    (_, kwargs), = calls
    assert kwargs == {"check_vma": False}


@pytest.mark.parametrize("api,kw", [(_OLD_API, "check_rep"),
                                    (_NEW_API, "check_vma")])
def test_native_spelling_passes_through_untranslated(monkeypatch, api, kw):
    impl, calls, acc = _capture_impl(api)
    monkeypatch.setattr(compat, "_shard_map", impl)
    monkeypatch.setattr(compat, "_ACCEPTED", acc)
    compat.shard_map(lambda x: x, **{kw: True})
    (_, kwargs), = calls
    assert kwargs == {kw: True}, \
        "the implementation's own spelling must never be rewritten"


def test_unintrospectable_impl_passes_kwargs_verbatim(monkeypatch):
    # exotic wrappers whose signature inspect can't read: _ACCEPTED is
    # None and the shim must not guess — kwargs go through untouched
    impl, calls, _ = _capture_impl(())
    monkeypatch.setattr(compat, "_shard_map", impl)
    monkeypatch.setattr(compat, "_ACCEPTED", None)
    compat.shard_map(lambda x: x, check_vma=True)
    (_, kwargs), = calls
    assert kwargs == {"check_vma": True}


def test_positional_args_forwarded(monkeypatch):
    impl, calls, acc = _capture_impl(_OLD_API)
    monkeypatch.setattr(compat, "_shard_map", impl)
    monkeypatch.setattr(compat, "_ACCEPTED", acc)
    f = lambda x: x  # noqa: E731
    compat.shard_map(f, "MESH", check_vma=True)
    (args, kwargs), = calls
    assert args == (f, "MESH") and kwargs == {"check_rep": True}


# ---------------------------------------------------------------------------
# import-path resolution (reload under mocked jax module trees)
# ---------------------------------------------------------------------------

def _reload_with_fake_jax(fake_modules, check):
    """Reload compat with `fake_modules` shadowing jax in sys.modules and
    run `check(reloaded_module)` while the fake is live; ALWAYS restores
    the real modules and re-reloads compat back to its true state."""
    saved = {}
    names = set(fake_modules) | {
        n for n in sys.modules
        if n == "jax" or n.startswith(("jax.", "jaxlib"))}
    for n in names:
        saved[n] = sys.modules.pop(n, None)
    sys.modules.update(fake_modules)
    try:
        check(importlib.reload(compat))
    finally:
        for n in fake_modules:
            sys.modules.pop(n, None)
        for n, mod in saved.items():
            if mod is not None:
                sys.modules[n] = mod
        importlib.reload(compat)


def _fake_shard_map(check_kw):
    # a real function so inspect.signature works on the reloaded module
    if check_kw == "check_vma":
        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=True):
            return ("new-api", check_vma)
    else:
        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_rep=True):
            return ("old-api", check_rep)
    return shard_map


def test_resolves_toplevel_jax_shard_map():
    # jax >= 0.6 layout: `from jax import shard_map` succeeds
    jx = types.ModuleType("jax")
    jx.shard_map = _fake_shard_map("check_vma")

    def check(mod):
        assert mod._shard_map is jx.shard_map
        assert "check_vma" in mod._ACCEPTED
        # legacy spelling translated forward on this layout
        assert mod.shard_map(lambda x: x, check_rep=False) \
            == ("new-api", False)

    _reload_with_fake_jax({"jax": jx}, check)


def test_falls_back_to_experimental_shard_map():
    # jax 0.4.x layout: no top-level attr, submodule carries it
    jx = types.ModuleType("jax")
    exp = types.ModuleType("jax.experimental")
    sub = types.ModuleType("jax.experimental.shard_map")
    sub.shard_map = _fake_shard_map("check_rep")
    jx.experimental = exp
    exp.shard_map = sub

    def check(mod):
        assert mod._shard_map is sub.shard_map
        assert "check_rep" in mod._ACCEPTED
        # modern spelling translated back on this layout
        assert mod.shard_map(lambda x: x, check_vma=False) \
            == ("old-api", False)

    _reload_with_fake_jax({"jax": jx, "jax.experimental": exp,
                           "jax.experimental.shard_map": sub}, check)


def test_installed_jax_resolves_a_callable():
    # whatever generation is installed, the shim must have bound a real
    # implementation at import time
    assert callable(compat._shard_map)
    assert compat._ACCEPTED is None or (
        "check_rep" in compat._ACCEPTED or "check_vma" in compat._ACCEPTED)
