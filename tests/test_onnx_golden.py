"""ONNX wire-format golden-byte fixtures (VERDICT r2 #6).

The serde is a hand-rolled protobuf encoder; one byte off the onnx.proto3
schema and every exported file is unreadable by real ONNX consumers —
and a self-referential round-trip would never notice.  These fixtures
are assembled BY HAND in this file, field number by field number from
the public onnx.proto3 (field tags written as explicit byte literals,
independently of serde's helpers), and pinned in both directions:

  encode: serde.encode_model(model) must produce EXACTLY these bytes
          (the encoder is deterministic, so byte equality is a valid
          regression guard)
  decode: serde.decode_model(golden) must recover the model

onnx.proto3 field numbers used (same table as serde.py's docstring):
  ModelProto:    ir_version=1, producer_name=2, graph=7, opset_import=8
  OperatorSetId: domain=1, version=2
  GraphProto:    node=1, name=2, initializer=5, input=11, output=12
  NodeProto:     input=1, output=2, name=3, op_type=4, attribute=5
  AttributeProto:name=1, f=2, i=3, s=4, floats=6, ints=7, type=20
  TensorProto:   dims=1, data_type=2, name=8, raw_data=9
  ValueInfoProto:name=1, type=2; TypeProto.tensor_type=1;
  Tensor.elem_type=1, shape=2; TensorShapeProto.dim=1; Dim.dim_value=1
"""
import struct

import numpy as onp

from incubator_mxnet_tpu.onnx import serde


def LD(tag_byte: int, payload: bytes) -> bytes:
    """length-delimited field, explicit pre-computed tag byte."""
    assert len(payload) < 128  # all fixture payloads fit 1-byte varints
    return bytes([tag_byte, len(payload)]) + payload


def value_info(tag_byte: int, name: bytes, dims) -> bytes:
    # TensorShapeProto: repeated dim, each Dim{dim_value=1 varint}
    shape = b"".join(LD(0x0A, bytes([0x08, d])) for d in dims)
    tensor_type = bytes([0x08, 0x01]) + LD(0x12, shape)  # elem_type=FLOAT
    type_proto = LD(0x0A, tensor_type)                   # TypeProto.tensor_type
    return LD(tag_byte, LD(0x0A, name) + LD(0x12, type_proto))


def golden_relu_model() -> bytes:
    """ModelProto{ ir=8, producer, graph{ Relu node, io (2,3) f32 }, opset 17 }"""
    node = (LD(0x0A, b"x")          # NodeProto.input = "x"
            + LD(0x12, b"y")        # .output = "y"
            + LD(0x1A, b"y_node")   # .name
            + LD(0x22, b"Relu"))    # .op_type
    graph = (LD(0x0A, node)                    # GraphProto.node
             + LD(0x12, b"g")                  # .name
             + value_info(0x5A, b"x", (2, 3))  # .input  (field 11)
             + value_info(0x62, b"y", (2, 3))) # .output (field 12)
    opset = LD(0x0A, b"") + bytes([0x10, 0x11])  # domain "", version 17
    return (bytes([0x08, 0x08])                  # ir_version = 8
            + LD(0x12, b"incubator_mxnet_tpu")   # producer_name
            + LD(0x3A, graph)                    # graph (field 7)
            + LD(0x42, opset))                   # opset_import (field 8)


def build_relu_model() -> serde.Model:
    g = serde.Graph("g")
    g.nodes.append(serde.Node("Relu", ["x"], ["y"], "y_node"))
    g.inputs.append(("x", (2, 3), serde.FLOAT))
    g.outputs.append(("y", (2, 3), serde.FLOAT))
    return serde.Model(g)


def test_encoder_matches_golden_bytes():
    assert serde.encode_model(build_relu_model()) == golden_relu_model()


def test_decoder_reads_golden_bytes():
    m = serde.decode_model(golden_relu_model())
    assert m.producer == "incubator_mxnet_tpu"
    assert m.opset == 17
    g = m.graph
    assert g.name == "g"
    assert len(g.nodes) == 1
    n = g.nodes[0]
    assert (n.op_type, n.inputs, n.outputs, n.name) == \
        ("Relu", ["x"], ["y"], "y_node")
    assert g.inputs == [("x", (2, 3), serde.FLOAT)]
    assert g.outputs == [("y", (2, 3), serde.FLOAT)]


def test_initializer_raw_data_layout():
    """TensorProto: dims(1) data_type(2) name(8) raw_data(9), raw_data
    little-endian fp32 — the layout every ONNX runtime accepts."""
    arr = onp.asarray([[1.5, -2.0]], onp.float32)
    got = serde._encode_tensor("w", arr)
    want = (bytes([0x08, 0x01, 0x08, 0x02])      # dims 1, 2
            + bytes([0x10, 0x01])                # data_type = FLOAT
            + LD(0x42, b"w")                     # name (field 8)
            + LD(0x4A, struct.pack("<2f", 1.5, -2.0)))  # raw_data (field 9)
    assert got == want
    name, back = serde._decode_tensor(want)
    assert name == "w"
    onp.testing.assert_array_equal(back, arr)


def test_negative_int_attribute_ten_byte_varint():
    """Protobuf int64: negative values encode as 10-byte two's-complement
    varints (axis=-1 must survive; naive encoders truncate)."""
    enc = serde._encode_attr("axis", -1)
    # name field
    assert enc.startswith(LD(0x0A, b"axis"))
    rest = enc[len(LD(0x0A, b"axis")):]
    # i field (3, varint): tag 0x18 then 10 bytes 0xFF..0x01
    assert rest[:1] == b"\x18"
    assert rest[1:11] == b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01"
    # and the reader sign-extends it back
    name, val = serde._decode_attr(enc)
    assert (name, val) == ("axis", -1)


def test_varint_multibyte_lengths():
    """Payloads >127 bytes must use multi-byte varint lengths."""
    arr = onp.zeros(64, onp.float32)  # raw_data = 256 bytes
    enc = serde._encode_tensor("big", arr)
    name, back = serde._decode_tensor(enc)
    assert name == "big" and back.shape == (64,)
    # the raw_data length 256 encodes as varint 0x80 0x02
    assert b"\x4a\x80\x02" in enc
