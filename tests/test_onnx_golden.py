"""ONNX wire-format golden-byte fixtures (VERDICT r2 #6).

The serde is a hand-rolled protobuf encoder; one byte off the onnx.proto3
schema and every exported file is unreadable by real ONNX consumers —
and a self-referential round-trip would never notice.  These fixtures
are assembled BY HAND in this file, field number by field number from
the public onnx.proto3 (field tags written as explicit byte literals,
independently of serde's helpers), and pinned in both directions:

  encode: serde.encode_model(model) must produce EXACTLY these bytes
          (the encoder is deterministic, so byte equality is a valid
          regression guard)
  decode: serde.decode_model(golden) must recover the model

onnx.proto3 field numbers used (same table as serde.py's docstring):
  ModelProto:    ir_version=1, producer_name=2, graph=7, opset_import=8
  OperatorSetId: domain=1, version=2
  GraphProto:    node=1, name=2, initializer=5, input=11, output=12
  NodeProto:     input=1, output=2, name=3, op_type=4, attribute=5
  AttributeProto:name=1, f=2, i=3, s=4, floats=6, ints=7, type=20
  TensorProto:   dims=1, data_type=2, name=8, raw_data=9
  ValueInfoProto:name=1, type=2; TypeProto.tensor_type=1;
  Tensor.elem_type=1, shape=2; TensorShapeProto.dim=1; Dim.dim_value=1
"""
import struct

import numpy as onp

from incubator_mxnet_tpu.onnx import serde


def LD(tag_byte: int, payload: bytes) -> bytes:
    """length-delimited field, explicit pre-computed tag byte."""
    assert len(payload) < 128  # all fixture payloads fit 1-byte varints
    return bytes([tag_byte, len(payload)]) + payload


def value_info(tag_byte: int, name: bytes, dims) -> bytes:
    # TensorShapeProto: repeated dim, each Dim{dim_value=1 varint}
    shape = b"".join(LD(0x0A, bytes([0x08, d])) for d in dims)
    tensor_type = bytes([0x08, 0x01]) + LD(0x12, shape)  # elem_type=FLOAT
    type_proto = LD(0x0A, tensor_type)                   # TypeProto.tensor_type
    return LD(tag_byte, LD(0x0A, name) + LD(0x12, type_proto))


def golden_relu_model() -> bytes:
    """ModelProto{ ir=8, producer, graph{ Relu node, io (2,3) f32 }, opset 17 }"""
    node = (LD(0x0A, b"x")          # NodeProto.input = "x"
            + LD(0x12, b"y")        # .output = "y"
            + LD(0x1A, b"y_node")   # .name
            + LD(0x22, b"Relu"))    # .op_type
    graph = (LD(0x0A, node)                    # GraphProto.node
             + LD(0x12, b"g")                  # .name
             + value_info(0x5A, b"x", (2, 3))  # .input  (field 11)
             + value_info(0x62, b"y", (2, 3))) # .output (field 12)
    opset = LD(0x0A, b"") + bytes([0x10, 0x11])  # domain "", version 17
    return (bytes([0x08, 0x08])                  # ir_version = 8
            + LD(0x12, b"incubator_mxnet_tpu")   # producer_name
            + LD(0x3A, graph)                    # graph (field 7)
            + LD(0x42, opset))                   # opset_import (field 8)


def build_relu_model() -> serde.Model:
    g = serde.Graph("g")
    g.nodes.append(serde.Node("Relu", ["x"], ["y"], "y_node"))
    g.inputs.append(("x", (2, 3), serde.FLOAT))
    g.outputs.append(("y", (2, 3), serde.FLOAT))
    return serde.Model(g)


def test_encoder_matches_golden_bytes():
    assert serde.encode_model(build_relu_model()) == golden_relu_model()


def test_decoder_reads_golden_bytes():
    m = serde.decode_model(golden_relu_model())
    assert m.producer == "incubator_mxnet_tpu"
    assert m.opset == 17
    g = m.graph
    assert g.name == "g"
    assert len(g.nodes) == 1
    n = g.nodes[0]
    assert (n.op_type, n.inputs, n.outputs, n.name) == \
        ("Relu", ["x"], ["y"], "y_node")
    assert g.inputs == [("x", (2, 3), serde.FLOAT)]
    assert g.outputs == [("y", (2, 3), serde.FLOAT)]


def test_initializer_raw_data_layout():
    """TensorProto: dims(1) data_type(2) name(8) raw_data(9), raw_data
    little-endian fp32 — the layout every ONNX runtime accepts."""
    arr = onp.asarray([[1.5, -2.0]], onp.float32)
    got = serde._encode_tensor("w", arr)
    want = (bytes([0x08, 0x01, 0x08, 0x02])      # dims 1, 2
            + bytes([0x10, 0x01])                # data_type = FLOAT
            + LD(0x42, b"w")                     # name (field 8)
            + LD(0x4A, struct.pack("<2f", 1.5, -2.0)))  # raw_data (field 9)
    assert got == want
    name, back = serde._decode_tensor(want)
    assert name == "w"
    onp.testing.assert_array_equal(back, arr)


def test_negative_int_attribute_ten_byte_varint():
    """Protobuf int64: negative values encode as 10-byte two's-complement
    varints (axis=-1 must survive; naive encoders truncate)."""
    enc = serde._encode_attr("axis", -1)
    # name field
    assert enc.startswith(LD(0x0A, b"axis"))
    rest = enc[len(LD(0x0A, b"axis")):]
    # i field (3, varint): tag 0x18 then 10 bytes 0xFF..0x01
    assert rest[:1] == b"\x18"
    assert rest[1:11] == b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01"
    # and the reader sign-extends it back
    name, val = serde._decode_attr(enc)
    assert (name, val) == ("axis", -1)


def test_varint_multibyte_lengths():
    """Payloads >127 bytes must use multi-byte varint lengths."""
    arr = onp.zeros(64, onp.float32)  # raw_data = 256 bytes
    enc = serde._encode_tensor("big", arr)
    name, back = serde._decode_tensor(enc)
    assert name == "big" and back.shape == (64,)
    # the raw_data length 256 encodes as varint 0x80 0x02
    assert b"\x4a\x80\x02" in enc


# ---------------------------------------------------------------------- #
# r4: multi-node golden fixture — attributes + initializers + subgraphs
# ---------------------------------------------------------------------- #
def _vint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def LDV(tag_byte: int, payload: bytes) -> bytes:
    """length-delimited field with a FULL varint length (subgraph-sized
    payloads exceed 127 bytes)."""
    return bytes([tag_byte]) + _vint(len(payload)) + payload


def tensor_f32(name: bytes, dims, values) -> bytes:
    out = b"".join(bytes([0x08, d]) for d in dims)   # dims (field 1)
    out += bytes([0x10, 0x01])                       # data_type FLOAT
    out += LDV(0x42, name)                           # name (field 8)
    out += LDV(0x4A, struct.pack(f"<{len(values)}f", *values))  # raw_data
    return out


def tensor_bool_scalar(name: bytes, value: bool) -> bytes:
    out = bytes([0x10, 0x09])                        # data_type BOOL
    out += LDV(0x42, name)
    out += LDV(0x4A, bytes([1 if value else 0]))
    return out


def golden_multinode_model() -> bytes:
    """MatMul -> Add(bias initializer) -> Concat(axis attr) -> If(pred
    initializer) with one-node then/else subgraphs capturing the outer
    tensor lexically.  Every AttributeProto field the exporter emits is
    exercised: i(3)+type INT, g(6)+type GRAPH."""
    mm = (LDV(0x0A, b"x") + LDV(0x0A, b"x") + LDV(0x12, b"m")
          + LDV(0x1A, b"m_node") + LDV(0x22, b"MatMul"))
    add = (LDV(0x0A, b"m") + LDV(0x0A, b"b") + LDV(0x12, b"a")
           + LDV(0x1A, b"a_node") + LDV(0x22, b"Add"))
    # Concat attr: name 'axis', i=0, type INT(2)
    axis_attr = LDV(0x0A, b"axis") + bytes([0x18, 0x00]) \
        + bytes([0xA0, 0x01, 0x02])
    cat = (LDV(0x0A, b"a") + LDV(0x0A, b"a") + LDV(0x12, b"c")
           + LDV(0x1A, b"c_node") + LDV(0x22, b"Concat")
           + LDV(0x2A, axis_attr))
    # then branch: Mul(c, two) -> ty ; local initializer two=2.0 scalar
    t_node = (LDV(0x0A, b"c") + LDV(0x0A, b"two") + LDV(0x12, b"ty")
              + LDV(0x1A, b"ty_node") + LDV(0x22, b"Mul"))
    then_g = (LDV(0x0A, t_node) + LDV(0x12, b"tg")
              + LDV(0x2A, tensor_f32(b"two", (), [2.0]))
              + value_info(0x62, b"ty", (4, 2)))
    e_node = (LDV(0x0A, b"c") + LDV(0x12, b"ey")
              + LDV(0x1A, b"ey_node") + LDV(0x22, b"Identity"))
    else_g = (LDV(0x0A, e_node) + LDV(0x12, b"eg")
              + value_info(0x62, b"ey", (4, 2)))
    then_attr = LDV(0x0A, b"then_branch") + LDV(0x32, then_g) \
        + bytes([0xA0, 0x01, 0x05])
    else_attr = LDV(0x0A, b"else_branch") + LDV(0x32, else_g) \
        + bytes([0xA0, 0x01, 0x05])
    iff = (LDV(0x0A, b"p") + LDV(0x12, b"y") + LDV(0x1A, b"y_node")
           + LDV(0x22, b"If") + LDV(0x2A, then_attr) + LDV(0x2A, else_attr))
    graph = (LDV(0x0A, mm) + LDV(0x0A, add) + LDV(0x0A, cat)
             + LDV(0x0A, iff)
             + LDV(0x12, b"g")
             + LDV(0x2A, tensor_f32(b"b", (2,), [1.0, -1.0]))
             + LDV(0x2A, tensor_bool_scalar(b"p", True))
             + value_info(0x5A, b"x", (2, 2))
             + value_info(0x62, b"y", (4, 2)))
    opset = LDV(0x0A, b"") + bytes([0x10, 0x11])
    return (bytes([0x08, 0x08])
            + LDV(0x12, b"incubator_mxnet_tpu")
            + LDV(0x3A, graph)
            + LDV(0x42, opset))


def test_multinode_golden_bytes_encode_exact():
    """The serde encoder must reproduce the hand-assembled wire bytes
    byte-for-byte — attributes (ints at field 8... here INT at 3 and
    GRAPH at 6), nested subgraphs, scalar + vector initializers."""
    import numpy as onp

    then_g = serde.Graph("tg")
    then_g.nodes.append(serde.Node("Mul", ["c", "two"], ["ty"]))
    then_g.initializers["two"] = onp.asarray(2.0, "float32")
    then_g.outputs.append(("ty", (4, 2), serde.FLOAT))
    else_g = serde.Graph("eg")
    else_g.nodes.append(serde.Node("Identity", ["c"], ["ey"]))
    else_g.outputs.append(("ey", (4, 2), serde.FLOAT))

    g = serde.Graph("g")
    g.nodes.append(serde.Node("MatMul", ["x", "x"], ["m"]))
    g.nodes.append(serde.Node("Add", ["m", "b"], ["a"]))
    g.nodes.append(serde.Node("Concat", ["a", "a"], ["c"],
                              attrs={"axis": 0}))
    g.nodes.append(serde.Node("If", ["p"], ["y"],
                              attrs={"then_branch": then_g,
                                     "else_branch": else_g}))
    g.initializers["b"] = onp.asarray([1.0, -1.0], "float32")
    g.initializers["p"] = onp.asarray(True)
    g.inputs.append(("x", (2, 2), serde.FLOAT))
    g.outputs.append(("y", (4, 2), serde.FLOAT))
    got = serde.encode_model(serde.Model(g))
    want = golden_multinode_model()
    assert got == want, (got.hex(), want.hex())


def test_multinode_golden_decodes_and_executes():
    """Decode the hand bytes and RUN them: y = concat(x@x + b) * 2."""
    import jax.numpy as jnp
    import numpy as onp

    from incubator_mxnet_tpu.onnx.import_model import ONNXModel

    m = serde.decode_model(golden_multinode_model())
    om = ONNXModel(m)
    x = onp.asarray([[1.0, 2.0], [3.0, 0.5]], "float32")
    want = onp.concatenate([x @ x + [1.0, -1.0]] * 2, 0) * 2.0
    got = onp.asarray(om._jit(jnp.asarray(x)))
    onp.testing.assert_allclose(got, want, rtol=1e-6)
