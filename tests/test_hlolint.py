"""hlolint parser + fact-extractor + contract tests.

Everything here runs against the committed fixtures under
tests/fixtures/hlolint/ (real lowered/compiled programs — see
regen.py there) plus small synthetic modules for the corner cases; NO
test in this file invokes a compile, so parser regressions surface in
milliseconds, not after a jit.
"""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import hlolint
from tools.hlolint import facts as hfacts
from tools.hlolint import contracts as hcontracts

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "hlolint")


def _read(name):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as fh:
        return fh.read()


@pytest.fixture(scope="module")
def mono():
    return hlolint.parse_hlo(_read("monolithic_step.hlo.txt"))


@pytest.fixture(scope="module")
def zero():
    return hlolint.parse_hlo(_read("zero_bucketed_step.hlo.txt"))


@pytest.fixture(scope="module")
def int8():
    return hlolint.parse_hlo(_read("int8_decode.hlo.txt"))


@pytest.fixture(scope="module")
def int8_stablehlo():
    return hlolint.parse_stablehlo(_read("int8_decode.stablehlo.txt"))


# the int8 fixture's quantized weight shapes (regen.py prints them)
INT8_WEIGHT_SHAPES = [(8, 8), (8, 16), (16, 8), (24, 8)]


# --------------------------------------------------------------------- #
# parser: real fixtures
# --------------------------------------------------------------------- #
class TestParserFixtures:
    def test_header(self, mono, zero):
        assert mono.is_scheduled and zero.is_scheduled
        assert mono.num_partitions == 8
        assert zero.num_partitions == 8
        assert mono.entry is not None and mono.entry.is_entry

    def test_every_computation_parses(self, mono, zero, int8):
        # one parsed computation per textual head — a head the parser
        # chokes on silently drops its whole body (that bug hid a
        # `while` once)
        for name in ("monolithic_step.hlo.txt", "zero_bucketed_step.hlo.txt",
                     "int8_decode.hlo.txt"):
            text = _read(name)
            raw_heads = sum(
                1 for line in text.splitlines()
                if line.rstrip().endswith("{") and "->" in line
                and not line.startswith("HloModule"))
            parsed = hlolint.parse_hlo(text)
            assert len(parsed.computations) == raw_heads, name

    def test_alias_header(self, zero, mono):
        # the bucketed ZeRO step donates weights+states: 9 aliased
        # inputs in the fixture; the alias list's nested braces must
        # not truncate the parse
        assert len(zero.input_output_alias) == 9
        out_idx, param, p_idx, kind = zero.input_output_alias[0]
        assert out_idx == (0,) and param == 7 and kind == "may-alias"
        # the monolithic step donates too (weights + optimizer state)
        assert len(mono.input_output_alias) == 9

    def test_instruction_shape_bytes(self, mono):
        root = mono.entry.root
        assert root is not None and root.is_root
        # entry params have known byte sizes
        p_bytes = sum(i.result_bytes for i in mono.entry.parameters())
        assert p_bytes > 0

    def test_collectives_and_async_pairs(self, zero):
        colls = list(zero.collectives())
        kinds = {c.opcode for c in colls}
        assert "reduce-scatter" in kinds
        for c in colls:
            if c.opcode.endswith("-start"):
                continue
            assert c.attrs.get("replica_groups") is not None

    def test_while_bodies_counted(self, int8):
        stats = hfacts.while_fusion_stats(int8)
        assert stats["while"] == 3
        assert stats["fusion"] > 0
        assert stats["max_fusion_instructions"] > 1


# --------------------------------------------------------------------- #
# parser: synthetic corner cases
# --------------------------------------------------------------------- #
_SYNTH = """\
HloModule synth, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, must-alias) }, num_partitions=8

%add_comp (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %add = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[64], p1: f32[64], p2: f64[4]) -> (f32[64], f32[8]) {
  %p0 = f32[64]{0} parameter(0)
  %p1 = f32[64]{0} parameter(1)
  %p2 = f64[4]{0} parameter(2)
  %ar = f32[64]{0} all-reduce(f32[64]{0} %p0), channel_id=1, replica_groups=[2,4]<=[8], use_global_device_ids=true, to_apply=%add_comp
  %rs-start = f32[8]{0} reduce-scatter-start(f32[64]{0} %p1), channel_id=2, replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add_comp
  %cp = f32[64]{0} collective-permute(f32[64]{0} %ar), channel_id=3, source_target_pairs={{0,4},{4,0},{1,5},{5,1}}
  %red = bf16[8]{0} reduce(f32[64]{0} %cp, f32[] %p0), dimensions={0}, to_apply=%add_comp
  %outfeed = token[] outfeed(f32[64]{0} %cp)
  %rs = f32[8]{0} reduce-scatter-done(f32[8]{0} %rs-start)
  ROOT %t = (f32[64], f32[8]) tuple(f32[64]{0} %ar, f32[8]{0} %rs)
}
"""


class TestParserSynthetic:
    @pytest.fixture(scope="class")
    def mod(self):
        return hlolint.parse_hlo(_SYNTH)

    def test_alias_kinds(self, mod):
        assert mod.input_output_alias == [
            ((0,), 0, (), "may-alias"), ((1,), 2, (), "must-alias")]

    def test_iota_replica_groups(self, mod):
        ar = mod.entry.by_name["ar"]
        groups = ar.replica_group_members(8)
        assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def test_explicit_replica_groups(self, mod):
        rs = mod.entry.by_name["rs-start"]
        assert rs.replica_group_members(8) == [[0, 1, 2, 3, 4, 5, 6, 7]]

    def test_async_pairing_by_operand_not_name(self, mod):
        pairs = mod.async_pairs()
        assert [(s.name, d.name) for s, d in pairs] == [("rs-start", "rs")]

    def test_axis_attribution(self, mod):
        inv = hfacts.collective_inventory(
            mod, axis_order=["data", "model"],
            axis_sizes={"data": 2, "model": 4})
        # iota [2,4]<=[8]: group {0..3} = stride-1 members -> model axis
        assert inv["per_axis"]["all-reduce[model]"]["count"] == 1
        # full group {0..7} spans both axes
        assert inv["per_axis"]["reduce-scatter[data+model]"]["count"] == 1
        # permute pairs step |4| = data stride
        assert inv["per_axis"]["collective-permute[data]"]["count"] == 1
        assert inv["n_async"] == 1

    def test_async_bytes_counted_once(self, mod):
        inv = hfacts.collective_inventory(mod)
        # reduce-scatter: only the -start half counts (f32[8] = 32 B),
        # the -done consumes it and must not double-count
        assert inv["per_op"]["reduce-scatter"] == {"count": 1, "bytes": 32}

    def test_f64_flag_and_census(self, mod):
        census = hfacts.dtype_census(mod)
        assert census["has_f64"]
        assert census["dtypes"]["f64"]["bytes"] == 32

    def test_sub_f32_accumulator(self, mod):
        accs = hfacts.reduction_accumulators(mod)
        assert [a["instruction"] for a in accs] == ["red"]
        assert accs[0]["dtype"] == "bf16"

    def test_host_transfers(self, mod):
        ht = hfacts.host_transfers(mod)
        assert ht["count"] == 1
        assert ht["ops"][0]["opcode"] == "outfeed"

    def test_empty_replica_groups_means_all(self):
        text = _SYNTH.replace("replica_groups={{0,1,2,3,4,5,6,7}}",
                              "replica_groups={}")
        mod = hlolint.parse_hlo(text)
        rs = mod.entry.by_name["rs-start"]
        assert rs.replica_group_members(8) == [[0, 1, 2, 3, 4, 5, 6, 7]]

    def test_float_weight_materialization_detects(self):
        text = _SYNTH.replace("%cp = f32[64]{0}", "%cp = bf16[16,4]{1,0}")
        mod = hlolint.parse_hlo(text)
        hits = hfacts.float_weight_materializations(mod, [(4, 16)])
        assert len(hits) == 1 and hits[0]["shape"] == [16, 4]


# --------------------------------------------------------------------- #
# StableHLO view
# --------------------------------------------------------------------- #
class TestStableHlo:
    def test_i8_census(self, int8_stablehlo):
        dts = int8_stablehlo.dtypes()
        assert dts.get("s8", 0) > 0
        assert dts.get("bf16", 0) > 0

    def test_weight_arg_types_seen(self, int8_stablehlo):
        # the signature line carries the packed s8 weight arg types
        for dims in INT8_WEIGHT_SHAPES:
            shapes = int8_stablehlo.shapes_with_dims(dims)
            assert any(sh.dtype == "s8" for sh in shapes), dims

    def test_no_donation_in_decode(self, int8_stablehlo):
        assert int8_stablehlo.donated_args == []

    def test_donor_attrs_synthetic(self):
        text = (
            "module @jit_f attributes {mhlo.num_partitions = 1 : i32} {\n"
            "  func.func public @main(%arg0: tensor<64xf32>, "
            "%arg1: tensor<64xf32> {jax.buffer_donor = true}, "
            "%arg2: tensor<4x2xf32> {tf.aliasing_output = 0 : i32}) "
            "-> (tensor<64xf32>) {\n"
            "    %0 = stablehlo.add %arg0, %arg1 : tensor<64xf32>\n"
            "    return %0 : tensor<64xf32>\n"
            "  }\n"
            "}\n")
        smod = hlolint.parse_stablehlo(text)
        assert smod.donated_args == [1, 2]
        assert smod.aliased_args == [2]
        assert smod.dtypes()["f32"] >= 5

    def test_donation_coverage(self):
        hlo = ("HloModule jit_f, is_scheduled=true, "
               "input_output_alias={ {0}: (1, {}, may-alias) }\n\n"
               "ENTRY %main (p0: f32[64], p1: f32[64]) -> f32[64] {\n"
               "  %p0 = f32[64]{0} parameter(0)\n"
               "  %p1 = f32[64]{0} parameter(1)\n"
               "  ROOT %add = f32[64]{0} add(f32[64]{0} %p0, f32[64]{0} %p1)\n"
               "}\n")
        sh = ("module @jit_f {\n"
              "  func.func public @main(%arg0: tensor<64xf32> "
              "{jax.buffer_donor = true}, %arg1: tensor<64xf32> "
              "{jax.buffer_donor = true}) -> (tensor<64xf32>) {\n"
              "    return %arg0 : tensor<64xf32>\n  }\n}\n")
        don = hfacts.donation(hlolint.parse_hlo(hlo),
                              hlolint.parse_stablehlo(sh))
        # 2 donated, 1 actually aliased -> coverage 0.5
        assert don == {"aliased": 1, "aliased_params": [1],
                       "donated": 2, "coverage": 0.5}


# --------------------------------------------------------------------- #
# fact summaries over the fixtures (what the CI gate consumes)
# --------------------------------------------------------------------- #
class TestFixtureFacts:
    def test_mono_collectives(self, mono):
        s = hlolint.fact_summary(mono, axis_order=["data"],
                                 axis_sizes={"data": 8})
        per_op = s["collectives"]["per_op"]
        assert per_op["all-reduce"]["count"] > 0
        assert "reduce-scatter" not in per_op
        assert set(s["collectives"]["per_axis"]) == {"all-reduce[data]"}
        assert not s["dtypes"]["has_f64"]
        assert s["host_transfers"]["count"] == 0

    def test_zero_bucketed_contract_facts(self, zero):
        # the properties the committed contract pins: one
        # reduce-scatter per bucket (fixture has 3), residual
        # all-reduce tiny, full donation aliasing
        s = hlolint.fact_summary(zero, axis_order=["data"],
                                 axis_sizes={"data": 8})
        per_op = s["collectives"]["per_op"]
        assert per_op["reduce-scatter"]["count"] == 3
        assert per_op["all-reduce"]["bytes"] <= 64
        assert per_op["all-gather"]["count"] <= 6
        assert s["donation"]["aliased"] == 9

    def test_int8_decode_facts(self, int8, int8_stablehlo):
        s = hlolint.fact_summary(int8, stablehlo=int8_stablehlo,
                                 weight_shapes=INT8_WEIGHT_SHAPES)
        assert "s8" in s["dtypes"]["dtypes"]
        assert s["weights"]["float_materializations"] == []
        assert s["sub_f32_accumulators"] == []
        assert s["stats"]["while"] == 3
        # act_quant="none" StableHLO carries the dequant converts (f32
        # weight-shaped tensors) — they fuse away in the optimized HLO,
        # which is exactly why the bf16-materialization gate runs there
        assert s["stablehlo"]["dtypes"]["s8"] > 0

    def test_schedule_stats_via_shared_parser(self):
        # overlap.py's analyzer now rides the same IR: one collective
        # per bucket on the zero fixture
        from incubator_mxnet_tpu.parallel import overlap

        st = overlap.schedule_overlap_stats(
            _read("zero_bucketed_step.hlo.txt"))
        assert st["n_collectives"] == 3
        assert 0.0 <= st["overlap_fraction"] <= 1.0


# --------------------------------------------------------------------- #
# contracts
# --------------------------------------------------------------------- #
class TestContracts:
    def _facts(self, mono):
        return {"prog": hlolint.fact_summary(
            mono, axis_order=["data"], axis_sizes={"data": 8})}

    def test_pass_and_fail(self, mono):
        facts = self._facts(mono)
        contracts = {"version": 1, "programs": {"prog": {"checks": [
            {"rule": "HLO003",
             "expr": "collective_count('all-reduce') > 0"}]}}}
        v, unc = hcontracts.evaluate(contracts, facts)
        assert v == [] and unc == []
        contracts["programs"]["prog"]["checks"][0]["expr"] = \
            "collective_count('all-reduce') == 0"
        v, _ = hcontracts.evaluate(contracts, facts)
        assert len(v) == 1
        r = v[0].render()
        assert "prog" in r and "HLO003" in r and "per_op" in r

    def test_uncontracted_vs_accepted(self, mono):
        facts = self._facts(mono)
        v, unc = hcontracts.evaluate({"programs": {}}, facts)
        assert unc == ["prog"]
        v, unc = hcontracts.evaluate(
            {"programs": {}, "accepted": ["prog"]}, facts)
        assert unc == []

    def test_default_checks_apply_everywhere(self):
        mod = hlolint.parse_hlo(_SYNTH)  # has f64 + an outfeed
        facts = {"prog": hlolint.fact_summary(mod)}
        v, _ = hcontracts.evaluate(
            {"programs": {}, "accepted": ["prog"]}, facts)
        assert {x.rule for x in v} == {"HLO001", "HLO005"}

    def test_bad_expr_is_a_violation_not_a_pass(self, mono):
        facts = self._facts(mono)
        contracts = {"programs": {"prog": {"checks": [
            {"rule": "HLO003", "expr": "no_such_name > 0"}]}}}
        v, _ = hcontracts.evaluate(contracts, facts)
        assert len(v) == 1 and "NameError" in v[0].observed

    def test_ctx_and_cross_program(self, mono, zero):
        facts = {
            "mono": hlolint.fact_summary(mono),
            "zero": hlolint.fact_summary(zero),
        }
        contracts = {"programs": {
            "mono": {"checks": [
                {"rule": "HLO003",
                 "expr": "collective_count('all-reduce') == ctx['n_ar']"}]},
            "zero": {"checks": [
                {"rule": "HLO003",
                 "expr": "param_bytes < programs['mono']['entry']"
                         "['param_bytes']"}]},
        }}
        n_ar = facts["mono"]["collectives"]["per_op"]["all-reduce"]["count"]
        v, unc = hcontracts.evaluate(contracts, facts,
                                     ctx={"n_ar": n_ar})
        assert v == [] and unc == []

    def test_bootstrap_roundtrip(self, zero):
        facts = {"zero": hlolint.fact_summary(
            zero, axis_order=["data"], axis_sizes={"data": 8})}
        doc = hcontracts.bootstrap_contracts(facts)
        v, unc = hcontracts.evaluate(doc, facts)
        assert v == [] and unc == []

    def test_committed_contract_file_is_wellformed(self):
        path = os.path.join(os.path.dirname(FIXTURES), "..", "..",
                            ".hlolint_contracts.json")
        doc = hcontracts.load_contracts(path)
        assert doc["version"] == 1
        names = set(doc["programs"])
        assert {"trainer_full_step", "trainer_full_step_zero_bucketed",
                "decode_float", "decode_int8"} <= names
        for prog in doc["programs"].values():
            for chk in prog["checks"]:
                assert chk["rule"] in hcontracts.RULES
                compile(chk["expr"], "<contract>", "eval")

    def test_load_rejects_garbage(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"not": "contracts"}))
        with pytest.raises(ValueError):
            hcontracts.load_contracts(str(p))
