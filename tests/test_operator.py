"""Per-op numeric matrix vs NumPy + finite-difference gradient checks.

The translation of the reference's `tests/python/unittest/test_operator.py`
culture (SURVEY.md §4): NumPy is the numeric oracle, gradients are
checked by central differences (`test_utils.check_numeric_gradient`),
and a bf16-vs-f32 consistency sweep replaces cpu-vs-gpu
`check_consistency`.
"""
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import test_utils
from incubator_mxnet_tpu.ndarray import contrib, linalg, nn_ops, ops
from incubator_mxnet_tpu.ndarray.ndarray import NDArray

nd = mx.nd


def _nd(a):
    return NDArray(jnp.asarray(a))


def _rand(shape, lo=-1.0, hi=1.0, seed=0):
    return onp.random.RandomState(seed).uniform(lo, hi, shape).astype("float32")


# --------------------------------------------------------------------- #
# unary matrix
# --------------------------------------------------------------------- #
_UNARY = [
    # (name, numpy_fn, (lo, hi))
    ("exp", onp.exp, (-2, 2)), ("log", onp.log, (0.1, 5)),
    ("log2", onp.log2, (0.1, 5)), ("log10", onp.log10, (0.1, 5)),
    ("log1p", onp.log1p, (-0.5, 5)), ("expm1", onp.expm1, (-2, 2)),
    ("sqrt", onp.sqrt, (0.01, 9)), ("rsqrt", lambda x: 1 / onp.sqrt(x), (0.1, 9)),
    ("cbrt", onp.cbrt, (-8, 8)), ("square", onp.square, (-3, 3)),
    ("reciprocal", onp.reciprocal, (0.2, 4)), ("abs", onp.abs, (-3, 3)),
    ("sign", onp.sign, (-3, 3)), ("floor", onp.floor, (-3, 3)),
    ("ceil", onp.ceil, (-3, 3)), ("round", onp.round, (-3, 3)),
    ("trunc", onp.trunc, (-3, 3)), ("negative", onp.negative, (-3, 3)),
    ("sigmoid", lambda x: 1 / (1 + onp.exp(-x)), (-4, 4)),
    ("relu", lambda x: onp.maximum(x, 0), (-3, 3)),
    ("softsign", lambda x: x / (1 + onp.abs(x)), (-3, 3)),
    ("sin", onp.sin, (-3, 3)), ("cos", onp.cos, (-3, 3)),
    ("tan", onp.tan, (-1, 1)), ("arcsin", onp.arcsin, (-0.9, 0.9)),
    ("arccos", onp.arccos, (-0.9, 0.9)), ("arctan", onp.arctan, (-3, 3)),
    ("sinh", onp.sinh, (-2, 2)), ("cosh", onp.cosh, (-2, 2)),
    ("tanh", onp.tanh, (-2, 2)), ("arcsinh", onp.arcsinh, (-3, 3)),
    ("arccosh", onp.arccosh, (1.1, 4)), ("arctanh", onp.arctanh, (-0.9, 0.9)),
    ("degrees", onp.degrees, (-3, 3)), ("radians", onp.radians, (-90, 90)),
    ("erf", None, (-2, 2)), ("gammaln", None, (0.5, 4)),
]


@pytest.mark.parametrize("name,npf,dom", _UNARY, ids=[u[0] for u in _UNARY])
def test_unary_vs_numpy(name, npf, dom):
    if npf is None:
        import scipy.special as sp  # available via jax deps? fall back
        npf = {"erf": sp.erf, "gammaln": sp.gammaln}[name]
    x = _rand((3, 4), *dom)
    got = getattr(ops, name)(_nd(x)).asnumpy()
    test_utils.assert_almost_equal(got, npf(x).astype("float32"),
                                   rtol=1e-5, atol=1e-5)


_BINARY = [
    ("add", onp.add), ("subtract", onp.subtract), ("multiply", onp.multiply),
    ("divide", onp.divide), ("power", lambda a, b: onp.power(onp.abs(a) + 0.5, b)),
    ("maximum", onp.maximum), ("minimum", onp.minimum), ("hypot", onp.hypot),
    ("equal", lambda a, b: (a == b).astype("float32")),
    ("not_equal", lambda a, b: (a != b).astype("float32")),
    ("greater", lambda a, b: (a > b).astype("float32")),
    ("lesser", lambda a, b: (a < b).astype("float32")),
]


@pytest.mark.parametrize("name,npf", _BINARY, ids=[b[0] for b in _BINARY])
def test_binary_vs_numpy(name, npf):
    a, b = _rand((3, 4), seed=1), _rand((3, 4), 0.5, 2.0, seed=2)
    aa, bb = (onp.abs(a) + 0.5, b) if name == "power" else (a, b)
    got = getattr(ops, name)(_nd(aa), _nd(bb)).asnumpy()
    want = npf(a, b) if name == "power" else npf(aa, bb)
    test_utils.assert_almost_equal(got, want.astype("float32"), rtol=1e-5, atol=1e-5)


def test_binary_broadcasting():
    a, b = _rand((3, 1, 4)), _rand((1, 5, 4), seed=3)
    test_utils.assert_almost_equal(
        ops.broadcast_add(_nd(a), _nd(b)).asnumpy(), a + b, rtol=1e-6, atol=1e-6)
    test_utils.assert_almost_equal(
        ops.broadcast_mul(_nd(a), _nd(b)).asnumpy(), a * b, rtol=1e-6, atol=1e-6)


# --------------------------------------------------------------------- #
# reductions / ordering
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("name,npf", [
    ("sum", onp.sum), ("mean", onp.mean), ("max", onp.max),
    ("min", onp.min), ("prod", onp.prod), ("nansum", onp.nansum),
], ids=["sum", "mean", "max", "min", "prod", "nansum"])
@pytest.mark.parametrize("axis", [None, 0, 1, (0, 1)])
def test_reduce_vs_numpy(name, npf, axis):
    x = _rand((4, 5), 0.1, 2.0)
    got = getattr(ops, name)(_nd(x), axis=axis).asnumpy()
    test_utils.assert_almost_equal(onp.asarray(got), npf(x, axis=axis),
                                   rtol=1e-5, atol=1e-5)


def test_argmax_argmin_norm():
    x = _rand((4, 5))
    assert onp.array_equal(ops.argmax(_nd(x), axis=1).asnumpy(), x.argmax(1))
    assert onp.array_equal(ops.argmin(_nd(x), axis=0).asnumpy(), x.argmin(0))
    test_utils.assert_almost_equal(
        onp.asarray(ops.norm(_nd(x)).asnumpy()), onp.linalg.norm(x), rtol=1e-5, atol=1e-5)


def test_sort_argsort_topk():
    x = _rand((3, 6))
    assert onp.allclose(ops.sort(_nd(x), axis=1).asnumpy(), onp.sort(x, 1))
    assert onp.array_equal(ops.argsort(_nd(x), axis=1).asnumpy().astype(int),
                           onp.argsort(x, 1, kind="stable"))
    topv = ops.topk(_nd(x), k=2, ret_typ="value").asnumpy()
    want = onp.sort(x, 1)[:, ::-1][:, :2]
    assert onp.allclose(topv, want)


# --------------------------------------------------------------------- #
# shape / indexing ops
# --------------------------------------------------------------------- #
def test_matrix_ops():
    x = _rand((2, 3, 4))
    assert ops.reshape(_nd(x), (4, 6)).shape == (4, 6)
    assert ops.transpose(_nd(x), (2, 0, 1)).shape == (4, 2, 3)
    assert ops.expand_dims(_nd(x), 1).shape == (2, 1, 3, 4)
    assert ops.flatten(_nd(x)).shape == (2, 12)
    c = ops.concat(_nd(x), _nd(x), dim=2)
    assert c.shape == (2, 3, 8)
    s = ops.stack(_nd(x), _nd(x), axis=0)
    assert s.shape == (2, 2, 3, 4)
    parts = ops.split(_nd(x), 2, axis=2)
    assert parts[0].shape == (2, 3, 2)
    assert ops.tile(_nd(x), (2, 1, 1)).shape == (4, 3, 4)
    assert ops.repeat(_nd(x), 2, axis=0).shape == (4, 3, 4)
    assert ops.reverse(_nd(x), axis=0).asnumpy()[0].sum() == pytest.approx(x[1].sum(), rel=1e-5)


def test_slice_family():
    x = _rand((5, 6))
    assert onp.allclose(ops.slice(_nd(x), (1, 2), (4, 5)).asnumpy(), x[1:4, 2:5])
    assert onp.allclose(ops.slice_axis(_nd(x), 1, 1, 4).asnumpy(), x[:, 1:4])
    like = _nd(onp.zeros((3, 2), "float32"))
    assert onp.allclose(ops.slice_like(_nd(x), like).asnumpy(), x[:3, :2])


def test_pad_depth_space():
    x = _rand((1, 4, 2, 2))
    p = ops.pad(_nd(x), mode="constant",
                pad_width=(0, 0, 0, 0, 1, 1, 1, 1), constant_value=0.0)
    assert p.shape == (1, 4, 4, 4)
    d2s = ops.depth_to_space(_nd(x), 2)
    assert d2s.shape == (1, 1, 4, 4)
    s2d = ops.space_to_depth(d2s, 2)
    assert onp.allclose(s2d.asnumpy(), x)


def test_take_pick_gather_scatter():
    x = _rand((4, 5))
    idx = onp.array([0, 2, 3])
    assert onp.allclose(ops.take(_nd(x), _nd(idx)).asnumpy(), x[idx])
    pk = ops.pick(_nd(x), _nd(onp.array([0, 1, 2, 3])), axis=1).asnumpy()
    assert onp.allclose(pk, x[onp.arange(4), [0, 1, 2, 3]])
    gi = onp.array([[0, 1], [2, 3]])  # gather_nd indices (2, N)
    g = ops.gather_nd(_nd(x), _nd(gi)).asnumpy()
    assert onp.allclose(g, x[[0, 1], [2, 3]])
    sc = ops.scatter_nd(_nd(onp.array([1.0, 2.0], "float32")), _nd(gi), (4, 5)).asnumpy()
    want = onp.zeros((4, 5), "float32")
    want[0, 2], want[1, 3] = 1.0, 2.0
    assert onp.allclose(sc, want)


def test_one_hot_embedding():
    oh = ops.one_hot(_nd(onp.array([0, 2])), 3).asnumpy()
    assert onp.allclose(oh, onp.eye(3, dtype="float32")[[0, 2]])
    w = _rand((10, 4))
    e = ops.embedding(_nd(onp.array([1, 5])), _nd(w)).asnumpy()
    assert onp.allclose(e, w[[1, 5]])


def test_sequence_ops():
    x = _rand((4, 2, 3))  # (T, B, C)
    sl = onp.array([2.0, 4.0], "float32")
    m = ops.sequence_mask(_nd(x), _nd(sl), use_sequence_length=True, value=-1.0).asnumpy()
    assert onp.all(m[2:, 0] == -1.0) and onp.allclose(m[:, 1], x[:, 1])
    last = ops.sequence_last(_nd(x), _nd(sl), use_sequence_length=True).asnumpy()
    assert onp.allclose(last[0], x[1, 0]) and onp.allclose(last[1], x[3, 1])
    rev = ops.sequence_reverse(_nd(x), _nd(sl), use_sequence_length=True).asnumpy()
    assert onp.allclose(rev[0, 0], x[1, 0]) and onp.allclose(rev[0, 1], x[3, 1])


def test_where_clip_cast():
    x = _rand((3, 4))
    c = (x > 0).astype("float32")
    assert onp.allclose(ops.where(_nd(c), _nd(x), _nd(-x)).asnumpy(), onp.abs(x))
    assert onp.allclose(ops.clip(_nd(x), -0.5, 0.5).asnumpy(), onp.clip(x, -0.5, 0.5))
    assert ops.cast(_nd(x), "int32").dtype == onp.dtype("int32")


# --------------------------------------------------------------------- #
# gradient checks (finite differences — the reference oracle)
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("fn,dom", [
    (lambda x: ops.tanh(x), (-1, 1)),
    (lambda x: ops.sigmoid(x), (-2, 2)),
    (lambda x: ops.exp(x), (-1, 1)),
    (lambda x: ops.log(x), (0.5, 2)),
    (lambda x: nn_ops.softmax(x), (-1, 1)),
    (lambda x: nn_ops.log_softmax(x), (-1, 1)),
    (lambda x: ops.square(x) * ops.sin(x), (-1, 1)),
    (lambda x: nn_ops.smooth_l1(x), (-2, 2)),
], ids=["tanh", "sigmoid", "exp", "log", "softmax", "log_softmax",
        "square_sin", "smooth_l1"])
def test_numeric_gradient_unary(fn, dom):
    x = _rand((2, 3), *dom, seed=11)
    test_utils.check_numeric_gradient(fn, [_nd(x)])


def test_numeric_gradient_dot_fc():
    a, b = _rand((2, 3), seed=5), _rand((3, 2), seed=6)
    test_utils.check_numeric_gradient(lambda x, y: ops.dot(x, y), [_nd(a), _nd(b)])
    x, w = _rand((2, 4), seed=7), _rand((3, 4), seed=8)
    test_utils.check_numeric_gradient(
        lambda d, ww: nn_ops.FullyConnected(d, ww, num_hidden=3, no_bias=True),
        [_nd(x), _nd(w)])


def test_numeric_gradient_layernorm():
    x = _rand((2, 4), seed=9)
    g, b = onp.ones(4, "float32"), onp.zeros(4, "float32")
    test_utils.check_numeric_gradient(
        lambda d: nn_ops.LayerNorm(d, _nd(g), _nd(b)), [_nd(x)],
        rtol=2e-2, atol=2e-3)


def test_numeric_gradient_take():
    x = _rand((4, 3), seed=10)
    idx = _nd(onp.array([0, 2]))
    test_utils.check_numeric_gradient(lambda d: ops.take(d, idx), [_nd(x)])


# --------------------------------------------------------------------- #
# dense NN ops vs explicit NumPy implementations
# --------------------------------------------------------------------- #
def test_fullyconnected_vs_numpy():
    x, w, b = _rand((2, 8)), _rand((5, 8), seed=2), _rand((5,), seed=3)
    got = nn_ops.FullyConnected(_nd(x), _nd(w), _nd(b), num_hidden=5).asnumpy()
    assert onp.allclose(got, x @ w.T + b, atol=1e-5)


def _np_conv2d(x, w, stride, pad):
    B, C, H, W = x.shape
    O, _, KH, KW = w.shape
    xp = onp.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    OH = (H + 2 * pad - KH) // stride + 1
    OW = (W + 2 * pad - KW) // stride + 1
    out = onp.zeros((B, O, OH, OW), "float32")
    for i in range(OH):
        for j in range(OW):
            patch = xp[:, :, i * stride:i * stride + KH, j * stride:j * stride + KW]
            out[:, :, i, j] = onp.einsum("bchw,ochw->bo", patch, w)
    return out


@pytest.mark.parametrize("stride,pad", [(1, 0), (1, 1), (2, 1)])
def test_convolution_vs_numpy(stride, pad):
    x = _rand((2, 3, 6, 6), seed=4)
    w = _rand((4, 3, 3, 3), seed=5)
    got = nn_ops.Convolution(_nd(x), _nd(w), kernel=(3, 3),
                             stride=(stride, stride), pad=(pad, pad),
                             num_filter=4, no_bias=True).asnumpy()
    assert onp.allclose(got, _np_conv2d(x, w, stride, pad), atol=1e-4)


def test_pooling_vs_numpy():
    x = _rand((1, 2, 4, 4), seed=6)
    mp = nn_ops.Pooling(_nd(x), kernel=(2, 2), stride=(2, 2), pool_type="max").asnumpy()
    want = x.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
    assert onp.allclose(mp, want)
    ap = nn_ops.Pooling(_nd(x), kernel=(2, 2), stride=(2, 2), pool_type="avg").asnumpy()
    wanta = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
    assert onp.allclose(ap, wanta, atol=1e-6)
    gp = nn_ops.Pooling(_nd(x), pool_type="max", global_pool=True).asnumpy()
    assert onp.allclose(gp.ravel(), x.max(axis=(2, 3)).ravel())


def test_norm_layers_vs_numpy():
    x = _rand((2, 3, 4), seed=7)
    g, b = onp.ones(4, "float32") * 1.5, onp.ones(4, "float32") * 0.2
    ln = nn_ops.LayerNorm(_nd(x), _nd(g), _nd(b)).asnumpy()
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    assert onp.allclose(ln, (x - mu) / onp.sqrt(var + 1e-5) * g + b, atol=1e-5)

    xc = _rand((2, 4, 3, 3), seed=8)
    gi, bi = onp.ones(4, "float32"), onp.zeros(4, "float32")
    inorm = nn_ops.InstanceNorm(_nd(xc), _nd(gi), _nd(bi)).asnumpy()
    mu = xc.mean(axis=(2, 3), keepdims=True)
    var = xc.var(axis=(2, 3), keepdims=True)
    assert onp.allclose(inorm, (xc - mu) / onp.sqrt(var + 1e-5), atol=1e-4)


def test_batchnorm_train_and_inference():
    x = _rand((4, 3, 2, 2), seed=9)
    g = onp.ones(3, "float32")
    b = onp.zeros(3, "float32")
    mm = onp.zeros(3, "float32")
    mv = onp.ones(3, "float32")
    out, new_mean, new_var = nn_ops.BatchNorm(
        _nd(x), _nd(g), _nd(b), _nd(mm), _nd(mv), training=True)
    mu = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    want = (x - mu.reshape(1, 3, 1, 1)) / onp.sqrt(var.reshape(1, 3, 1, 1) + 1e-5)
    assert onp.allclose(out.asnumpy(), want, atol=1e-4)
    # inference path uses moving stats
    out2 = nn_ops.BatchNorm(_nd(x), _nd(g), _nd(b), _nd(mm), _nd(mv),
                            training=False)
    out2 = out2[0] if isinstance(out2, tuple) else out2
    assert onp.allclose(out2.asnumpy(), x, atol=1e-4)  # mean 0 var 1 -> identity


def test_softmax_family_vs_numpy():
    x = _rand((3, 5), seed=10)
    sm = onp.exp(x) / onp.exp(x).sum(-1, keepdims=True)
    assert onp.allclose(nn_ops.softmax(_nd(x)).asnumpy(), sm, atol=1e-6)
    assert onp.allclose(nn_ops.log_softmax(_nd(x)).asnumpy(), onp.log(sm), atol=1e-5)
    assert onp.allclose(nn_ops.softmin(_nd(x)).asnumpy(),
                        onp.exp(-x) / onp.exp(-x).sum(-1, keepdims=True), atol=1e-6)
    mask = onp.ones_like(x)
    mask[:, -1] = 0
    msm = nn_ops.masked_softmax(_nd(x), _nd(mask)).asnumpy()
    assert onp.allclose(msm[:, -1], 0, atol=1e-6)
    assert onp.allclose(msm[:, :-1].sum(-1), 1, atol=1e-5)


def test_dropout_modes():
    x = _nd(onp.ones((100, 100), "float32"))
    out = nn_ops.Dropout(x, p=0.5, training=False)
    assert onp.allclose(out.asnumpy(), 1.0)  # identity at inference
    out_t = nn_ops.Dropout(x, p=0.5, training=True).asnumpy()
    kept = (out_t != 0).mean()
    assert 0.4 < kept < 0.6
    assert onp.allclose(out_t[out_t != 0], 2.0, atol=1e-5)  # inverted scaling


def test_activation_variants():
    x = _rand((3, 4), -2, 2)
    assert onp.allclose(nn_ops.Activation(_nd(x), "relu").asnumpy(), onp.maximum(x, 0))
    assert onp.allclose(nn_ops.Activation(_nd(x), "tanh").asnumpy(), onp.tanh(x), atol=1e-6)
    lk = nn_ops.LeakyReLU(_nd(x), act_type="leaky", slope=0.1).asnumpy()
    assert onp.allclose(lk, onp.where(x > 0, x, 0.1 * x), atol=1e-6)


def test_upsampling_nearest():
    x = _rand((1, 2, 2, 2))
    up = nn_ops.UpSampling(_nd(x), scale=2, sample_type="nearest").asnumpy()
    assert up.shape == (1, 2, 4, 4)
    assert onp.allclose(up[0, 0, :2, :2], x[0, 0, 0, 0])


def test_l2_normalization():
    x = _rand((2, 6))
    out = nn_ops.L2Normalization(_nd(x)).asnumpy()
    assert onp.allclose(onp.linalg.norm(out, axis=1), 1.0, atol=1e-5)


# --------------------------------------------------------------------- #
# linalg family vs numpy.linalg
# --------------------------------------------------------------------- #
def test_linalg_gemm_potrf_trsm():
    a, b = _rand((3, 4), seed=1), _rand((4, 2), seed=2)
    c = _rand((3, 2), seed=3)
    got = linalg.gemm(_nd(a), _nd(b), _nd(c), alpha=2.0, beta=0.5).asnumpy()
    assert onp.allclose(got, 2.0 * a @ b + 0.5 * c, atol=1e-5)
    assert onp.allclose(linalg.gemm2(_nd(a), _nd(b)).asnumpy(), a @ b, atol=1e-5)

    m = _rand((3, 3), seed=4)
    spd = m @ m.T + 3 * onp.eye(3, dtype="float32")
    L = linalg.potrf(_nd(spd)).asnumpy()
    assert onp.allclose(L @ L.T, spd, atol=1e-4)
    x = linalg.trsm(_nd(L), _nd(onp.eye(3, dtype="float32"))).asnumpy()
    assert onp.allclose(L @ x, onp.eye(3), atol=1e-4)


def test_linalg_decompositions():
    m = _rand((4, 4), seed=5)
    assert onp.allclose(linalg.det(_nd(m)).asnumpy(), onp.linalg.det(m), atol=1e-4)
    inv = linalg.inverse(_nd(m)).asnumpy()
    assert onp.allclose(m @ inv, onp.eye(4), atol=1e-3)
    q, r = linalg.qr(_nd(m))
    assert onp.allclose(q.asnumpy() @ r.asnumpy(), m, atol=1e-4)
    u, s, vt = linalg.svd(_nd(m))
    assert onp.allclose(u.asnumpy() @ onp.diag(s.asnumpy()) @ vt.asnumpy(), m, atol=1e-4)
    spd = m @ m.T + 4 * onp.eye(4, dtype="float32")
    w, v = linalg.eigh(_nd(spd))
    assert onp.allclose(v.asnumpy() @ onp.diag(w.asnumpy()) @ v.asnumpy().T, spd, atol=1e-3)
    bb = _rand((4, 2), seed=6)
    assert onp.allclose(linalg.solve(_nd(m), _nd(bb)).asnumpy(),
                        onp.linalg.solve(m, bb), atol=1e-3)


def test_linalg_diag_trian():
    m = _rand((3, 3))
    assert onp.allclose(linalg.extractdiag(_nd(m)).asnumpy(), onp.diag(m))
    d = onp.array([1.0, 2.0, 3.0], "float32")
    assert onp.allclose(linalg.makediag(_nd(d)).asnumpy(), onp.diag(d))
    assert onp.allclose(linalg.syrk(_nd(m)).asnumpy(), m @ m.T, atol=1e-5)


# --------------------------------------------------------------------- #
# control flow + contrib
# --------------------------------------------------------------------- #
def test_foreach_cumsum():
    data = _nd(onp.arange(5, dtype="float32"))

    def body(x, state):
        new = state + x
        return new, new

    outs, final = contrib.foreach(body, data, _nd(onp.zeros((), "float32")))
    assert onp.allclose(outs.asnumpy(), onp.cumsum(onp.arange(5)))
    assert float(final.asnumpy()) == 10.0


def test_while_loop_and_cond():
    # reference contract: func -> (step_outputs, new_loop_vars)
    def cond_fn(i, s):
        return i < 5

    def func(i, s):
        return None, (i + 1, s + i)

    _, (i, s) = contrib.while_loop(cond_fn, func,
                                   (_nd(onp.zeros((), "int32")),
                                    _nd(onp.zeros((), "int32"))),
                                   max_iterations=10)
    assert int(s.asnumpy()) == 10
    out = contrib.cond(_nd(onp.ones((), "float32")),
                       lambda x: x * 2, lambda x: x * 3,
                       (_nd(onp.full((), 5.0, "float32")),))
    assert float(out.asnumpy() if hasattr(out, "asnumpy") else out[0].asnumpy()) == 10.0


def test_boolean_mask_static_shape_deviation():
    """boolean_mask keeps static shape: selected rows are compacted to the
    front and the selected count returned (documented TPU deviation)."""
    x = _rand((4, 3))
    mask = onp.array([1, 0, 1, 0], "float32")
    out = contrib.boolean_mask(_nd(x), _nd(mask))
    n = int(mask.sum())
    assert onp.allclose(out.asnumpy()[:n], x[[0, 2]])


def test_index_copy():
    old = onp.zeros((4, 3), "float32")
    new = _rand((2, 3), seed=3)
    got = contrib.index_copy(_nd(old), _nd(onp.array([1, 3])), _nd(new)).asnumpy()
    assert onp.allclose(got[[1, 3]], new) and onp.allclose(got[[0, 2]], 0)


def test_quantize_dequantize_roundtrip():
    x = _rand((3, 4), -1, 1)
    mn = _nd(onp.float32(-1.0))
    mx_ = _nd(onp.float32(1.0))
    q = contrib.quantize(_nd(x), mn, mx_)
    deq = contrib.dequantize(q[0] if isinstance(q, tuple) else q, mn, mx_)
    assert onp.allclose(deq.asnumpy(), x, atol=2.0 / 255 + 1e-3)


# --------------------------------------------------------------------- #
# bf16 consistency (replaces cpu-vs-gpu check_consistency)
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("fn", [
    lambda x: ops.tanh(x), lambda x: nn_ops.softmax(x),
    lambda x: ops.square(x).sum(),
], ids=["tanh", "softmax", "square_sum"])
def test_bf16_consistency(fn):
    x = _rand((4, 8), seed=12)
    test_utils.check_consistency(fn, [x])


@pytest.mark.parametrize("cin,cout,g,k,s,p,d,a", [
    (5, 3, 1, 4, 2, 1, 1, 0),   # DCGAN upsample shape (Cin != Cout)
    (4, 6, 2, 3, 2, 1, 1, 1),   # grouped + output_padding
    (6, 4, 2, 3, 1, 0, 2, 0),   # dilated
])
def test_deconvolution_vs_conv_vjp(cin, cout, g, k, s, p, d, a):
    """Deconvolution == gradient of the forward conv w.r.t. its input
    (the defining property, ref deconvolution-inl.h), incl. the MXNet
    output-size rule out = s*(i-1) + d*(k-1) + 1 - 2p + a."""
    import jax
    from jax import lax

    rs = onp.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, cin, 8, 8), jnp.float32)
    w = jnp.asarray(rs.randn(cin, cout // g, k, k), jnp.float32)
    y = nn_ops.Deconvolution(_nd(onp.asarray(x)), _nd(onp.asarray(w)),
                             kernel=(k, k), stride=(s, s), dilate=(d, d),
                             pad=(p, p), adj=(a, a), num_filter=cout,
                             num_group=g, no_bias=True)
    expect = s * (8 - 1) + d * (k - 1) + 1 - 2 * p + a
    assert y.shape == (2, cout, expect, expect)

    def fwd(z):
        # adj extends the deconv output at the high edge, which in the
        # forward-conv view is asymmetric padding (p, p - a)
        return lax.conv_general_dilated(
            z, w, window_strides=(s, s), padding=[(p, p - a)] * 2,
            rhs_dilation=(d, d), dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=g)

    z0 = jnp.zeros((2, cout, expect, expect), jnp.float32)
    out, vjp = jax.vjp(fwd, z0)
    assert out.shape == x.shape, (out.shape, x.shape)
    (gz,) = vjp(x)
    assert onp.allclose(onp.asarray(gz), y.asnumpy(), atol=1e-4)


def test_deconvolution_bias_and_grad():
    x = _rand((2, 3, 5, 5), seed=11)
    w = _rand((3, 4, 3, 3), seed=12)
    b = _rand((4,), seed=13)
    got = nn_ops.Deconvolution(_nd(x), _nd(w), _nd(b), kernel=(3, 3),
                               stride=(2, 2), pad=(1, 1), num_filter=4,
                               no_bias=False)
    assert got.shape == (2, 4, 9, 9)
    nobias = nn_ops.Deconvolution(_nd(x), _nd(w), kernel=(3, 3),
                                  stride=(2, 2), pad=(1, 1), num_filter=4,
                                  no_bias=True).asnumpy()
    assert onp.allclose(got.asnumpy(), nobias + b.reshape(1, 4, 1, 1), atol=1e-5)


def test_stem_s2d_rewrite_exact():
    """The TPU stem rewrite (7x7 s2 p3 -> s2d + 4x4 s1) is EXACT math —
    value and gradient parity vs the canonical conv (r4,
    nn_ops._stem_conv_s2d; active on TPU backends only)."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax import lax

    from incubator_mxnet_tpu.ndarray.nn_ops import _stem_conv_s2d

    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (2, 3, 32, 32), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(k, 1), (8, 3, 7, 7),
                          jnp.float32) * 0.1

    def direct(x, w):
        return lax.conv_general_dilated(
            x, w, (2, 2), [(3, 3), (3, 3)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    y1 = direct(x, w)
    y2 = _stem_conv_s2d(x, w)
    onp.testing.assert_allclose(onp.asarray(y2), onp.asarray(y1),
                                rtol=2e-5, atol=2e-5)

    g1 = jax.grad(lambda x, w: jnp.sum(direct(x, w) ** 2), (0, 1))(x, w)
    g2 = jax.grad(lambda x, w: jnp.sum(_stem_conv_s2d(x, w) ** 2),
                  (0, 1))(x, w)
    for a, b in zip(g2, g1):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=2e-4, atol=2e-4)


def test_stem_s2d_dispatch_predicate_and_integration(monkeypatch):
    """Pin the dispatch gate AND the integrated Convolution branch
    (bias included) — on CPU the predicate is forced via the backend
    check so the TPU product path is executed under test."""
    import jax
    import jax.numpy as jnp
    import numpy as onp

    from incubator_mxnet_tpu.ndarray import nn_ops
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    x = jnp.ones((2, 3, 16, 16), jnp.float32)
    w = jnp.ones((4, 3, 7, 7), jnp.float32)

    def ok(**kw):
        args = dict(x=x, w=w, nd=2, stride=(2, 2), dilate=(1, 1),
                    pad=(3, 3), groups=1)
        args.update(kw)
        return nn_ops._stem_s2d_applicable(**args)

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert ok()
    assert not ok(stride=(1, 1))
    assert not ok(pad=(2, 2))
    assert not ok(groups=2)
    assert not ok(w=jnp.ones((4, 3, 5, 5), jnp.float32))
    assert not ok(w=jnp.ones((4, 8, 7, 7), jnp.float32))  # thick input
    assert not ok(x=jnp.ones((2, 3, 15, 16), jnp.float32))  # odd H
    monkeypatch.setenv("MXTPU_NO_S2D_STEM", "1")
    assert not ok()
    monkeypatch.delenv("MXTPU_NO_S2D_STEM")
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert not ok()

    # integrated parity through nd.Convolution with bias, branch forced
    k = jax.random.PRNGKey(0)
    xr = jax.random.normal(k, (2, 3, 16, 16), jnp.float32)
    wr = jax.random.normal(jax.random.fold_in(k, 1), (4, 3, 7, 7),
                           jnp.float32) * 0.1
    br = jax.random.normal(jax.random.fold_in(k, 2), (4,), jnp.float32)
    want = nn_ops.Convolution(NDArray(xr), NDArray(wr), NDArray(br),
                              kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                              num_filter=4).asnumpy()
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    got = nn_ops.Convolution(NDArray(xr), NDArray(wr), NDArray(br),
                             kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                             num_filter=4).asnumpy()
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=2e-5, atol=2e-5)
