"""Backward-overlapped bucketed gradient sync (ISSUE 5): the
parallel/overlap.py partitioner + pack/unpack kernels, the HLO schedule
analyzer, the Trainer's bucketed explicit-tier path (parity vs the
monolithic exchange), the sticky fallback, and the runtime XLA-flag
hook.  Runs on the 8-virtual-CPU mesh from conftest."""
import os

import jax
import jax.numpy as jnp
import numpy as onp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, runtime
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.parallel import overlap as ov
from incubator_mxnet_tpu.parallel.compat import shard_map

D = 8


# ---------------------------------------------------------------------------
# bucket partitioner
# ---------------------------------------------------------------------------

def test_partition_reverse_order_and_cap():
    # grads arrive last-layer-first in the backward pass: buckets are
    # built in REVERSE param order so the first bucket's reduce-scatter
    # can issue while earlier layers are still differentiating
    bks = ov.partition_buckets([80, 160, 80, 240], [4, 4, 4, 4],
                               ["a"] * 4, D, cap_bytes=1000)
    assert [b.idxs for b in bks] == [(3,), (2, 1), (0,)]
    assert bks[0].nbytes == 240 * 4
    assert bks[0].chunks == (240 // D,)
    assert bks[1].chunks == (80 // D, 160 // D)


def test_partition_group_key_split():
    # mixed dtypes/mp flags must not share a bucket (packing would
    # promote one side); a key change flushes even under the cap
    bks = ov.partition_buckets([80, 80, 80], [4, 4, 4], ["a", "b", "b"],
                               D, cap_bytes=10**9)
    assert [b.idxs for b in bks] == [(2, 1), (0,)]


def test_partition_oversize_param_gets_own_bucket():
    bks = ov.partition_buckets([8000, 80, 80], [4, 4, 4], ["a"] * 3,
                               D, cap_bytes=1000)
    assert [b.idxs for b in bks] == [(2, 1), (0,)]
    assert bks[1].nbytes == 8000 * 4  # over cap, alone by construction


def test_partition_rejects_unaligned_npad():
    with pytest.raises(ValueError):
        ov.partition_buckets([81], [4], ["a"], D, cap_bytes=1000)


def test_knob_resolution(monkeypatch):
    assert ov.resolve_bucket_bytes(2.0) == 2 << 20
    monkeypatch.setenv("MXTPU_ZERO_BUCKET_MB", "1.5")
    assert ov.resolve_bucket_bytes(None) == int(1.5 * (1 << 20))
    monkeypatch.delenv("MXTPU_ZERO_BUCKET_MB")
    assert ov.resolve_bucket_bytes(None) == int(
        ov.DEFAULT_BUCKET_MB * (1 << 20))
    assert ov.overlap_enabled(True) and not ov.overlap_enabled(False)
    monkeypatch.setenv("MXTPU_ZERO_OVERLAP", "off")
    assert not ov.overlap_enabled(None)
    assert ov.overlap_enabled(True)  # explicit arg beats env
    monkeypatch.setenv("MXTPU_ZERO_OVERLAP", "1")
    assert ov.overlap_enabled(None)


# ---------------------------------------------------------------------------
# interleaved pack layout: bucketed exchange == per-param exchange
# ---------------------------------------------------------------------------

def test_pack_unpack_parity_bit_exact(mesh8):
    key = jax.random.PRNGKey(0)
    sizes = [80, 160, 240]
    gs = [jax.random.normal(jax.random.fold_in(key, i), (s,), jnp.float32)
          for i, s in enumerate(sizes)]

    def per_param(gs):
        return [lax.psum_scatter(g, "data", tiled=True) for g in gs]

    def bucketed(gs):
        b = ov.GradBucket(idxs=(0, 1, 2), chunks=(10, 20, 30), nbytes=0)
        packed = ov.pack_bucket([gs[j] for j in b.idxs], D)
        sh = lax.psum_scatter(packed, "data", tiled=True)
        segs = ov.unpack_shards(sh, b.chunks)
        # return trip: bucketed all_gather must reassemble per-param flats
        flat = lax.all_gather(ov.pack_shards(segs), "data",
                              tiled=True, axis=0)
        return segs, ov.unpack_gathered(flat, b.chunks, D)

    f1 = jax.jit(shard_map(per_param, mesh=mesh8, in_specs=(P(),),
                           out_specs=P("data"), check_rep=False))
    f2 = jax.jit(shard_map(bucketed, mesh=mesh8, in_specs=(P(),),
                           out_specs=(P("data"), P()), check_rep=False))
    want = f1(gs)
    segs, backs = f2(gs)
    for a, b in zip(want, segs):
        # BIT-equal: the interleaved layout reduces the exact same
        # addends in the same shard positions as the per-param exchange
        assert onp.array_equal(onp.asarray(a), onp.asarray(b))
    psum = jax.jit(shard_map(lambda g: lax.psum(g, "data"), mesh=mesh8,
                             in_specs=(P(),), out_specs=P(),
                             check_rep=False))
    for j in range(3):
        onp.testing.assert_allclose(onp.asarray(backs[j]),
                                    onp.asarray(psum(gs[j])))


def test_pack_single_element_short_circuit():
    g = jnp.arange(16, dtype=jnp.float32)
    assert ov.pack_bucket([g], D) is g


# ---------------------------------------------------------------------------
# HLO schedule analyzer
# ---------------------------------------------------------------------------

_SYNTH_HLO = """\
HloModule jit_step, is_scheduled=true

ENTRY %main (p0: f32[64], p1: f32[64]) -> (f32[8], f32[8]) {
  %p0 = f32[64]{0} parameter(0)
  %p1 = f32[64]{0} parameter(1)
  %rs.1 = f32[8]{0} reduce-scatter(%p0), replica_groups={}, dimensions={0}
  %fusion.1 = f32[64]{0} fusion(%p1), kind=kLoop
  %rs.2 = f32[8]{0} reduce-scatter(%fusion.1), replica_groups={}, dimensions={0}
  %fusion.2 = f32[8]{0} fusion(%rs.2), kind=kLoop
  ROOT %t = (f32[8]{0}, f32[8]{0}) tuple(%rs.1, %fusion.2)
}
"""


def test_schedule_analyzer_synthetic():
    st = ov.schedule_overlap_stats(_SYNTH_HLO)
    assert st["n_collectives"] == 2
    first, second = st["per_collective"]
    # rs.1 has fusion.1 (independent compute) after it -> hidden;
    # rs.2's only successor compute is its own descendant -> exposed
    assert first["independent_compute_after"] > 0
    assert second["independent_compute_after"] == 0
    assert 0.0 < st["overlap_fraction"] < 1.0


def test_schedule_analyzer_async_forms():
    hlo = _SYNTH_HLO.replace(
        "%rs.1 = f32[8]{0} reduce-scatter(%p0), replica_groups={}, "
        "dimensions={0}",
        "%rs.1s = f32[8]{0} reduce-scatter-start(%p0), replica_groups={}\n"
        "  %rs.1 = f32[8]{0} reduce-scatter-done(%rs.1s)")
    st = ov.schedule_overlap_stats(hlo)
    assert st["n_collectives"] == 2


# ---------------------------------------------------------------------------
# trace-measured exposure (tools/xprof_summary.py pair attribution)
# ---------------------------------------------------------------------------

def _ev(name, t0, dur):
    from incubator_mxnet_tpu.utils.xplane import XEvent

    return XEvent(name=name, offset_ps=t0, duration_ps=dur)


def test_trace_attribution_async_pair_and_sync():
    from tools.xprof_summary import collective_overlap_from_events

    evs = [
        _ev("all-reduce-start.1", 0, 10),   # wire = [0, 100] via done
        _ev("fusion.1", 0, 120),            # covers the whole transfer
        _ev("all-reduce-done.1", 90, 10),
        _ev("reduce-scatter.2", 200, 100),  # [200,300]; fusion covers half
        _ev("fusion.2", 250, 100),
    ]
    st = collective_overlap_from_events(evs)
    assert st["n_collectives"] == 2
    assert st["comm_seconds"] == pytest.approx(200e-12)
    assert st["hidden_seconds"] == pytest.approx(150e-12)
    assert st["overlap_fraction"] == pytest.approx(0.75)


def test_trace_attribution_suffix_fallback():
    from tools.xprof_summary import collective_overlap_from_events

    # mismatched suffixes (XLA renumbers dones): time-ordered pairing
    st = collective_overlap_from_events(
        [_ev("all-gather-start.5", 0, 5), _ev("all-gather-done.9", 40, 10)])
    assert st["n_collectives"] == 1
    assert st["comm_seconds"] == pytest.approx(50e-12)
    assert st["overlap_fraction"] == 0.0


def test_trace_attribution_no_collectives():
    from tools.xprof_summary import collective_overlap_from_events

    st = collective_overlap_from_events([_ev("fusion.1", 0, 100)])
    assert st["n_collectives"] == 0 and st["overlap_fraction"] == 0.0


# ---------------------------------------------------------------------------
# Trainer integration: bucketed explicit tier
# ---------------------------------------------------------------------------

class _MLPWithLoss(gluon.nn.HybridBlock):
    def __init__(self):
        super().__init__()
        self.d1 = nn.Dense(64, activation="relu", in_units=32)
        self.d2 = nn.Dense(64, activation="relu", in_units=64)
        self.d3 = nn.Dense(8, in_units=64)
        self.loss = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(self, x, y):
        return self.loss(self.d3(self.d2(self.d1(x))), y).mean()


def _train(mesh, steps=3, **trainer_kw):
    onp.random.seed(0)
    mx.random.seed(0)
    net = _MLPWithLoss()
    net.initialize(force_reinit=True)
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-2}, mesh=mesh, zero_stage=1,
                       **trainer_kw)
    tr._capture_hlo = True
    losses = []
    with mesh:
        for s in range(steps):
            rs = onp.random.RandomState(s)
            x = rs.randn(16, 32).astype(onp.float32)
            y = rs.randint(0, 8, (16,)).astype(onp.int32)
            with autograd.record():
                loss = net(mx.nd.array(x), mx.nd.array(y))
            loss.backward()
            tr.step(16)
            losses.append(float(loss.asnumpy()))
    params = {k: v.data().asnumpy() for k, v in net.collect_params().items()}
    return losses, params, tr


def _assert_param_parity(p_a, p_b, exact=True):
    # gluon name counters differ between instantiations (and sorting
    # misaligns once the counter crosses a digit boundary: dense10 <
    # dense9) — pair by insertion order, which is creation order
    for (ka, va), (kb, vb) in zip(p_a.items(), p_b.items()):
        if exact:
            assert onp.array_equal(va, vb), f"not bit-equal: {ka} vs {kb}"
        else:
            onp.testing.assert_allclose(va, vb, rtol=2e-3, atol=1e-4,
                                        err_msg=f"{ka} vs {kb}")


def test_trainer_bucketed_parity_and_hlo(mesh8):
    l_off, p_off, _ = _train(mesh8, zero_overlap=False)
    # tiny cap: the MLP's ~20 KB of grads must split into >= 2 buckets
    l_on, p_on, tr = _train(mesh8, zero_overlap=True, zero_bucket_mb=0.01)
    assert tr._zero_sig() == ("explicit", "data", D)
    assert not tr._zero_overlap_broken
    bks = tr._fullstep_ctx["zero_buckets"]
    assert bks is not None and len(bks) >= 2
    onp.testing.assert_allclose(l_on, l_off, rtol=2e-4, atol=2e-5)
    # the interleaved pack feeds the identical per-param update: exact
    _assert_param_parity(p_off, p_on, exact=True)
    hlo = tr.last_step_hlo
    nrs = (hlo.count(" reduce-scatter(")
           + hlo.count(" reduce-scatter-start("))
    assert nrs == len(bks), "expected one reduce-scatter per bucket"
    st = ov.schedule_overlap_stats(hlo)
    assert st["n_collectives"] == len(bks)
    assert st["overlap_fraction"] > 0.5


def test_trainer_one_bucket_default_cap(mesh8):
    # default 25 MB cap swallows the whole MLP: single bucket, still
    # the bucketed code path, still exact parity
    l_off, p_off, _ = _train(mesh8, zero_overlap=False)
    l_on, p_on, tr = _train(mesh8, zero_overlap=True)
    bks = tr._fullstep_ctx["zero_buckets"]
    assert bks is not None and len(bks) == 1
    onp.testing.assert_allclose(l_on, l_off, rtol=2e-4, atol=2e-5)
    _assert_param_parity(p_off, p_on, exact=True)


def test_trainer_sticky_fallback(mesh8, monkeypatch):
    # a failing bucketed build must fall back to the monolithic
    # exchange (NOT to gspmd), warn once, and stay fallen back
    def boom(*a, **k):
        raise RuntimeError("synthetic pack failure")

    monkeypatch.setattr(ov, "pack_bucket", boom)
    with pytest.warns(UserWarning, match="monolithic"):
        l_on, p_on, tr = _train(mesh8, zero_overlap=True,
                                zero_bucket_mb=0.01)
    assert tr._zero_overlap_broken
    assert tr._overlap_sig() is None  # sticky: no rebuild attempts
    assert tr._zero_sig() == ("explicit", "data", D)  # tier survived
    assert tr._fullstep_ctx["zero_buckets"] is None
    monkeypatch.undo()
    l_off, p_off, _ = _train(mesh8, zero_overlap=False)
    onp.testing.assert_allclose(l_on, l_off, rtol=2e-4, atol=2e-5)
    _assert_param_parity(p_off, p_on, exact=True)


def test_trainer_env_knob_disables(mesh8, monkeypatch):
    monkeypatch.setenv("MXTPU_ZERO_OVERLAP", "0")
    _, _, tr = _train(mesh8)  # zero_overlap unset -> env decides
    assert tr._fullstep_ctx["zero_buckets"] is None
    assert not tr._zero_overlap_broken  # disabled, not broken


# ---------------------------------------------------------------------------
# runtime XLA-flag hook
# ---------------------------------------------------------------------------

def test_overlap_flags_per_platform():
    assert runtime.collective_overlap_flags("tpu")
    assert all(f.startswith("--xla_") for f in
               runtime.collective_overlap_flags("tpu"))
    # CPU's list scheduler already interleaves; and unknown flags are
    # fatal to XLA, so the CPU set must stay empty
    assert runtime.collective_overlap_flags("cpu") == ()


def test_enable_collective_overlap_guards(monkeypatch):
    # live backend (these tests hold one): must refuse to touch env
    before = os.environ.get("XLA_FLAGS")
    assert runtime.enable_collective_overlap("tpu") == []
    assert os.environ.get("XLA_FLAGS") == before
    # pre-init path: flags land in XLA_FLAGS exactly once
    monkeypatch.setattr(runtime, "_backend_initialized", lambda: False)
    monkeypatch.setenv("XLA_FLAGS", "--existing=1")
    added = runtime.enable_collective_overlap("tpu")
    assert added == list(runtime.collective_overlap_flags("tpu"))
    for f in added:
        assert f in os.environ["XLA_FLAGS"]
    assert runtime.enable_collective_overlap("tpu") == []  # deduped
    # kill switch
    monkeypatch.setenv("MXTPU_OVERLAP_FLAGS", "0")
    monkeypatch.setenv("XLA_FLAGS", "")
    assert runtime.enable_collective_overlap("tpu") == []
    assert os.environ["XLA_FLAGS"] == ""
