"""KVStore facade semantics (SURVEY.md §4 "Distributed" invariants,
single-process slice; multi-process invariants live in
tests/test_dist_kvstore.py)."""
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import kvstore as kvs_mod
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def _nd(a):
    return NDArray(jnp.asarray(onp.asarray(a, "float32")))


def test_create_types():
    for t in ("local", "device", "nccl", "dist_sync"):
        kv = kvs_mod.create(t)
        assert kv.type == t
    with pytest.raises(Exception):
        kvs_mod.create("dist_async")  # documented drop
    with pytest.raises(Exception):
        kvs_mod.create("bogus")


def test_push_pull_sum_semantics():
    kv = kvs_mod.create("local")
    kv.init(3, _nd(onp.zeros((2, 2))))
    # push a LIST of device values -> pull returns their SUM
    vals = [_nd(onp.full((2, 2), float(i))) for i in range(1, 4)]
    kv.push(3, vals)
    out = _nd(onp.zeros((2, 2)))
    kv.pull(3, out)
    onp.testing.assert_allclose(out.asnumpy(), 6.0 * onp.ones((2, 2)))


def test_push_pull_list_keys_and_pushpull():
    kv = kvs_mod.create("device")
    keys = [5, 7]
    kv.init(keys, [_nd(onp.zeros(3)), _nd(onp.zeros(3))])
    kv.push(keys, [[_nd(onp.ones(3))], [_nd(2 * onp.ones(3))]])
    outs = [_nd(onp.zeros(3)), _nd(onp.zeros(3))]
    kv.pull(keys, outs)
    onp.testing.assert_allclose(outs[0].asnumpy(), 1.0)
    onp.testing.assert_allclose(outs[1].asnumpy(), 2.0)
    out = _nd(onp.zeros(3))
    kv.pushpull(5, _nd(3 * onp.ones(3)), out)
    onp.testing.assert_allclose(out.asnumpy(), 3.0)


def test_uninitialized_key_raises():
    kv = kvs_mod.create("local")
    with pytest.raises(Exception):
        kv.pull(99, _nd(onp.zeros(2)))
    with pytest.raises(Exception):
        kv.set_optimizer(mx.optimizer.create("sgd"))
        kv.push(99, _nd(onp.ones(2)))


def test_server_side_updater():
    """set_optimizer -> push applies the update, pull returns weights."""
    kv = kvs_mod.create("local")
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.5))
    w0 = onp.ones(4, "float32")
    kv.init(0, _nd(w0))
    kv.push(0, _nd(onp.ones(4)))
    out = _nd(onp.zeros(4))
    kv.pull(0, out)
    onp.testing.assert_allclose(out.asnumpy(), w0 - 0.5, rtol=1e-6)


def test_row_sparse_pull():
    kv = kvs_mod.create("local")
    w = onp.arange(12, dtype="float32").reshape(4, 3)
    kv.init(1, _nd(w))
    out = _nd(onp.zeros((4, 3)))
    kv.row_sparse_pull(1, out, row_ids=_nd(onp.array([1, 3])))
    got = out.asnumpy()
    onp.testing.assert_allclose(got[[1, 3]], w[[1, 3]])
    onp.testing.assert_allclose(got[[0, 2]], 0.0)


def test_optimizer_states_io(tmp_path):
    kv = kvs_mod.create("local")
    kv.set_optimizer(mx.optimizer.create("adam"))
    kv.init(0, _nd(onp.ones(3)))
    kv.push(0, _nd(onp.ones(3)))
    f = str(tmp_path / "states.bin")
    kv.save_optimizer_states(f)
    kv2 = kvs_mod.create("local")
    kv2.set_optimizer(mx.optimizer.create("adam"))
    kv2.load_optimizer_states(f)
    assert 0 in kv2._updater.states


def test_rank_and_num_workers_single_process():
    kv = kvs_mod.create("dist_sync")
    assert kv.rank == 0 and kv.num_workers == 1
    kv.barrier()  # no-op single process


def test_gradient_compression_error_feedback():
    from incubator_mxnet_tpu.kvstore.gradient_compression import GradientCompression

    gc = GradientCompression(type="2bit", threshold=0.5)
    g = onp.array([0.7, -0.6, 0.1, 0.0], "float32")
    c1 = onp.asarray(gc.compress(0, jnp.asarray(g)))
    # quantized to {-t, 0, +t}
    assert set(onp.unique(onp.abs(c1)).tolist()) <= {0.0, 0.5}
    # error feedback: residual 0.2 from the first push accumulates with
    # the second push's 0.4 and crosses the threshold
    c2 = onp.asarray(gc.compress(0, jnp.asarray(
        onp.array([0.4, 0.0, 0.0, 0.0], "float32"))))
    assert c2[0] == 0.5


def test_gradient_compression_bit_packing_roundtrip():
    """Values REALLY pack 16-per-int32 (r1 VERDICT: zero bytes were
    saved) and unpack exactly."""
    from incubator_mxnet_tpu.kvstore.gradient_compression import GradientCompression

    rng = onp.random.RandomState(0)
    g = rng.uniform(-1, 1, (5, 7)).astype("float32")  # 35 values
    gc = GradientCompression(type="2bit", threshold=0.3)
    packed = gc.compress_packed(3, jnp.asarray(g))
    assert packed.dtype == jnp.int32
    assert packed.shape == (3,)  # ceil(35/16) words: 16x bandwidth saving
    deq = onp.asarray(gc.decompress(packed, g.shape))
    # matches the unpacked quantization of the same grad+residual
    gc2 = GradientCompression(type="2bit", threshold=0.3)
    q = onp.asarray(gc2.compress(3, jnp.asarray(g)))
    onp.testing.assert_allclose(deq, q, rtol=1e-6)
    # residual states agree between the packed and unpacked paths
    onp.testing.assert_allclose(onp.asarray(gc._residuals[3]).ravel(),
                                onp.asarray(gc2._residuals[3]).ravel(),
                                rtol=1e-6)


def test_runtime_features_honest():
    from incubator_mxnet_tpu import runtime

    feats = runtime.Features()
    assert feats.is_enabled("DIST_KVSTORE")
    assert feats.is_enabled("GRAD_COMPRESSION")
    # INT8 must reflect reality (True only if contrib.quantization exists)
    try:
        from incubator_mxnet_tpu.contrib import quantization  # noqa: F401

        has = True
    except Exception:
        has = False
    assert feats.is_enabled("INT8") == has
